"""Serving metrics: per-request latency, batch size, queue depth, plan
cache hits and compile counts — the gauges a serving process exports.

Pure host-side bookkeeping (a lock, bounded buckets/reservoirs, a handful
of counters); nothing here touches the device, so observing a request
costs nanoseconds next to the dispatch it measures.

Unified telemetry (docs/OBSERVABILITY.md): every observation ALSO mirrors
into the process-wide registry (``lightgbm_tpu.telemetry.registry()``)
under ``serve.*`` names, so one scrape of the registry sees training,
resilience and serving together; :meth:`ServeMetrics.render_prometheus`
answers a Prometheus scrape from one call.

Request-path observability (ISSUE-14):

- **Per-tenant dimensions** — a :class:`ServeMetrics` built with
  ``model="name"`` additionally publishes LABELED registry series
  (``serve.requests{model="name"}``, per-tenant latency histogram, shed /
  deadline counters), so a multi-Booster process's scrape distinguishes
  tenants instead of aliasing them into one counter set.
- **Full-run percentiles** — p50/p99/p999 come from fixed log-spaced
  bucket counts over EVERY request this process served (the registry
  ``Histogram``), not the trailing 4096-observation deque the original
  scheme measured; the mean stays exact (sum/count).
- **Per-request tracing** (:class:`RequestTracer`) — host-side phase
  breakdown (queue-wait / bin+assemble / device dispatch / post-process)
  recorded at dispatch boundaries only, deterministic-paced sampled
  ``serve.request`` JSONL events (slow requests always sample), and a
  bounded top-K slow-request exemplar ring surfaced in
  :meth:`ServeMetrics.snapshot`.  Off by default and bitwise-inert.
- **SLO accounting** — ``tpu_serve_slo_p99_ms`` arms rolling-window
  SLO-attainment and error-budget-burn gauges with violation attribution
  (latency / shed / deadline / fault).
"""

from __future__ import annotations

import math
import threading
import time
from collections import deque
from typing import Dict, Optional

import numpy as np

from ..telemetry import registry, render_prometheus
from ..telemetry.events import emit as _emit_event
from ..telemetry.registry import Histogram

# phases a request trace decomposes into, in wall order
TRACE_PHASES = ("queue_wait", "assemble", "dispatch", "post")

# slow-request exemplar ring capacity (top-K by total latency)
SLOW_RING_SIZE = 16

# rolling SLO window (seconds): attainment/burn gauges cover requests
# inside this trailing window, so a recovered incident stops burning
_SLO_WINDOW_S = 300.0

_SLO_CAUSES = ("latency", "shed", "deadline", "fault")


class PhaseTrace:
    """Host-side per-request phase marks.  ``mark(name)`` attributes the
    wall time since the previous mark (or construction) to ``name`` —
    pure ``perf_counter`` arithmetic at dispatch boundaries, never inside
    a traced program."""

    __slots__ = ("_t", "phases")

    def __init__(self):
        self._t = time.perf_counter()
        self.phases: Dict[str, float] = {}

    def mark(self, name: str) -> None:
        now = time.perf_counter()
        self.phases[name] = self.phases.get(name, 0.0) + (now - self._t)
        self._t = now


class RequestTracer:
    """Sampling ``serve.request`` emitter + slow-request exemplar ring +
    per-phase latency histograms for ONE predictor.

    Armed by ``tpu_serve_request_log=on``; when off (default) every hot
    path bails on one attribute read and the predict path is
    bitwise-inert (pinned).  Sampling is DETERMINISTIC over the request
    sequence — request ``n`` samples iff ``floor((n+1)*rate)`` crosses an
    integer boundary — so a fixed request stream emits the same event set
    every run; requests at/above ``slow_ms`` bypass the rate and also
    enter the bounded top-K exemplar ring (latency-sorted, with phase
    breakdown and batch context)."""

    def __init__(self, *, armed: bool = False, sample: float = 0.01,
                 slow_ms: float = 100.0, model: Optional[str] = None,
                 ring_size: int = SLOW_RING_SIZE):
        self.armed = bool(armed)
        self.sample = float(sample)
        self.slow_ms = float(slow_ms)
        self.model = model
        self.ring_size = int(ring_size)
        self._lock = threading.Lock()
        self._n = 0                      # requests traced (the id source)
        self._ring: list = []            # slow exemplars, desc by total_ms
        # Private full-run phase histograms (deliberately NOT registry
        # instruments: phases are per-predictor — two tenants' queue
        # waits must not blend — and tests/loadgen read them per handle).
        self._phase_hist = {p: Histogram(f"phase.{p}", threading.Lock())
                            for p in TRACE_PHASES}
        self._h_total = Histogram("phase.total", threading.Lock())

    # ------------------------------------------------------------- record
    def record(self, phases: Dict[str, float], *, rows: int,
               total_s: float, queue_wait_s: float = 0.0,
               coalesced: int = 1,
               batch_rows: Optional[int] = None) -> None:
        """Record one completed request's phase breakdown.  ``phases``
        carries the assemble/dispatch/post seconds the dispatch-boundary
        marks measured (shared by every request of a coalesced batch);
        ``queue_wait_s`` is this request's own queue time."""
        if not self.armed:
            return
        ph = {"queue_wait": float(queue_wait_s)}
        for name in ("assemble", "dispatch", "post"):
            ph[name] = float(phases.get(name, 0.0))
        for name, v in ph.items():
            self._phase_hist[name].observe(v)
        self._h_total.observe(total_s)
        total_ms = total_s * 1e3
        slow = self.slow_ms > 0 and total_ms >= self.slow_ms
        with self._lock:
            rid = self._n
            self._n += 1
            sampled = slow or (
                math.floor((rid + 1) * self.sample)
                > math.floor(rid * self.sample))
            if slow:
                self._ring_insert_locked({
                    "req_id": rid, "model": self.model,
                    "total_ms": round(total_ms, 4),
                    "rows": int(rows),
                    "batch_rows": int(batch_rows if batch_rows is not None
                                      else rows),
                    "coalesced": int(coalesced),
                    **{f"{n}_ms": round(v * 1e3, 4)
                       for n, v in ph.items()},
                })
        if sampled:
            _emit_event(
                "serve.request", req_id=rid, model=self.model,
                rows=int(rows),
                batch_rows=int(batch_rows if batch_rows is not None
                               else rows),
                coalesced=int(coalesced), slow=bool(slow),
                total_s=round(total_s, 6),
                **{f"{n}_s": round(v, 6) for n, v in ph.items()})

    def _ring_insert_locked(self, entry: Dict) -> None:
        ring = self._ring
        ring.append(entry)
        ring.sort(key=lambda e: -e["total_ms"])
        del ring[self.ring_size:]

    # ---------------------------------------------------------- reporting
    def slow_requests(self) -> list:
        """Top-K slowest traced requests (desc), each with its phase
        breakdown and batch context — the exemplars a latency incident
        triages from."""
        with self._lock:
            return [dict(e) for e in self._ring]

    def phase_quantiles(self) -> Dict[str, Dict[str, Optional[float]]]:
        """Full-run per-phase latency distribution (ms): count, mean and
        bucket-estimated p50/p99 per phase plus the traced total."""
        out = {}
        for name, hist in list(self._phase_hist.items()) \
                + [("total", self._h_total)]:
            p50, p99 = hist.quantiles((0.5, 0.99))
            out[name] = {
                "count": hist.count,
                "mean_ms": (hist.sum / hist.count * 1e3
                            if hist.count else None),
                "p50_ms": None if p50 is None else p50 * 1e3,
                "p99_ms": None if p99 is None else p99 * 1e3,
            }
        return out


class ServeMetrics:
    """Thread-safe request/latency/queue accounting for one Predictor."""

    def __init__(self, reservoir: int = 4096, *,
                 model: Optional[str] = None,
                 slo_p99_ms: float = 0.0,
                 slo_window_s: float = _SLO_WINDOW_S,
                 request_log: bool = False,
                 request_sample: float = 0.01,
                 slow_ms: float = 100.0):
        self._lock = threading.Lock()
        self.model = model
        # Full-run latency buckets (ISSUE-14): the quantile source for
        # p50/p99/p999 over EVERY request, not a trailing window; the
        # deque stays as a bounded raw-value reservoir for exemplars.
        self._lat_full = Histogram("latency_s", threading.Lock(),
                                   reservoir=reservoir)
        self._latencies = deque(maxlen=reservoir)   # seconds (reservoir)
        self._batch_sizes = deque(maxlen=reservoir)
        self.requests = 0
        self.rows = 0
        self.batches = 0
        self.queue_depth = 0
        self.max_queue_depth = 0
        self.padded_rows = 0
        # Graceful-degradation counters (docs/ROBUSTNESS.md): requests shed
        # by admission control, requests failed past their deadline, device
        # dispatch faults seen, and dispatches answered by the one-shot
        # host-predict fallback.
        self.shed = 0
        self.deadline_misses = 0
        self.device_faults = 0
        self.host_fallbacks = 0
        # Health guard (docs/ROBUSTNESS.md): device dispatches whose
        # scores came back non-finite — answered from the host mirror
        # instead of shipping NaN to a caller.
        self.nan_scores = 0
        # Hot-swap accounting (docs/STREAMING.md serve handoff):
        # plan_swaps = stale plans refreshed by the per-request freshness
        # check (the model mutated under this predictor); model_swaps =
        # explicit Predictor.swap_model calls (continual retrain/refit
        # landing without a restart).
        self.plan_swaps = 0
        self.model_swaps = 0
        # Per-request tracing (RequestTracer): armed by
        # tpu_serve_request_log=on, one-attribute-read inert otherwise.
        self.tracer = RequestTracer(armed=request_log,
                                    sample=request_sample,
                                    slow_ms=slow_ms, model=model)
        # SLO accounting (tpu_serve_slo_p99_ms > 0): a rolling window of
        # (monotonic_t, ok) verdicts drives the attainment / error-budget
        # burn gauges; violations attribute to latency/shed/deadline/fault.
        self.slo_p99_ms = float(slo_p99_ms)
        self.slo_window_s = float(slo_window_s)
        self._slo_window: deque = deque()   # (t, ok)
        self._slo_ok_in_window = 0
        self._slo_causes = {c: 0 for c in _SLO_CAUSES}
        # Registry mirrors resolved ONCE (get-or-create instruments are
        # stable objects with their own locks): the serve hot path pays no
        # table lookup under the registry lock per observation.  Caveat:
        # MetricsRegistry.reset() (tests only) detaches these mirrors for
        # the life of this ServeMetrics — see the reset() docstring.
        reg = registry()
        self._c_requests = reg.counter("serve.requests")
        self._c_rows = reg.counter("serve.rows")
        self._h_latency = reg.histogram("serve.latency_s")
        self._c_batches = reg.counter("serve.batches")
        self._g_queue = reg.gauge("serve.queue_depth")
        self._c_shed = reg.counter("serve.shed")
        self._c_deadline = reg.counter("serve.deadline_misses")
        self._c_faults = reg.counter("serve.device_faults")
        self._c_fallbacks = reg.counter("serve.host_fallbacks")
        self._c_nan = reg.counter("serve.nan_scores")
        self._c_plan_swaps = reg.counter("serve.plan_swaps")
        self._c_model_swaps = reg.counter("serve.model_swaps")
        # Labeled per-tenant mirrors (ISSUE-14): model-keyed series so a
        # multi-Booster scrape separates tenants.  None model = process
        # totals only (the original single-tenant schema, unchanged).
        self._t_requests = self._t_rows = None
        self._t_latency = self._t_shed = self._t_deadline = None
        if model is not None:
            lab = {"model": model}
            self._t_requests = reg.counter("serve.requests", labels=lab)
            self._t_rows = reg.counter("serve.rows", labels=lab)
            self._t_latency = reg.histogram("serve.latency_s", labels=lab)
            self._t_shed = reg.counter("serve.shed", labels=lab)
            self._t_deadline = reg.counter("serve.deadline_misses",
                                           labels=lab)
        self._g_slo_att = self._g_slo_burn = None
        if self.slo_p99_ms > 0:
            lab = None if model is None else {"model": model}
            self._g_slo_att = reg.gauge("serve.slo_attainment", labels=lab)
            self._g_slo_burn = reg.gauge("serve.slo_budget_burn",
                                         labels=lab)

    # ------------------------------------------------------------- recording
    def observe_request(self, rows: int, seconds: float) -> None:
        seconds = float(seconds)
        with self._lock:
            self.requests += 1
            self.rows += int(rows)
            self._latencies.append(seconds)
        self._lat_full.observe(seconds)
        self._c_requests.inc()
        self._c_rows.inc(int(rows))
        self._h_latency.observe(seconds)
        if self._t_requests is not None:
            self._t_requests.inc()
            self._t_rows.inc(int(rows))
            self._t_latency.observe(seconds)
        if self.slo_p99_ms > 0:
            ok = seconds * 1e3 <= self.slo_p99_ms
            self._slo_record(ok, cause=None if ok else "latency")

    def observe_batch(self, rows: int, padded_to: int) -> None:
        with self._lock:
            self.batches += 1
            self._batch_sizes.append(int(rows))
            self.padded_rows += max(int(padded_to) - int(rows), 0)
        self._c_batches.inc()

    def observe_queue_depth(self, depth: int) -> None:
        with self._lock:
            self.queue_depth = int(depth)
            self.max_queue_depth = max(self.max_queue_depth, int(depth))
        self._g_queue.set(int(depth))

    def observe_shed(self, requests: int = 1) -> None:
        with self._lock:
            self.shed += int(requests)
        self._c_shed.inc(int(requests))
        if self._t_shed is not None:
            self._t_shed.inc(int(requests))
        if self.slo_p99_ms > 0:
            for _ in range(int(requests)):
                self._slo_record(False, cause="shed")

    def observe_deadline_miss(self, requests: int = 1) -> None:
        with self._lock:
            self.deadline_misses += int(requests)
        self._c_deadline.inc(int(requests))
        if self._t_deadline is not None:
            self._t_deadline.inc(int(requests))
        if self.slo_p99_ms > 0:
            for _ in range(int(requests)):
                self._slo_record(False, cause="deadline")

    def observe_device_fault(self) -> None:
        with self._lock:
            self.device_faults += 1
        self._c_faults.inc()
        if self.slo_p99_ms > 0:
            self._slo_record(False, cause="fault")

    def observe_host_fallback(self) -> None:
        with self._lock:
            self.host_fallbacks += 1
        self._c_fallbacks.inc()

    def observe_nan_scores(self) -> None:
        with self._lock:
            self.nan_scores += 1
        self._c_nan.inc()

    def observe_plan_swap(self) -> None:
        with self._lock:
            self.plan_swaps += 1
        self._c_plan_swaps.inc()

    def observe_model_swap(self) -> None:
        with self._lock:
            self.model_swaps += 1
        self._c_model_swaps.inc()

    # ----------------------------------------------------------------- SLO
    def _slo_record(self, ok: bool, cause: Optional[str] = None) -> None:
        """One request verdict into the rolling SLO window; recomputes and
        publishes the attainment/burn gauges (cheap: deque ops + two
        divisions under the lock)."""
        now = time.monotonic()
        with self._lock:
            self._slo_window.append((now, ok))
            if ok:
                self._slo_ok_in_window += 1
            elif cause is not None:
                self._slo_causes[cause] += 1
            horizon = now - self.slo_window_s
            win = self._slo_window
            while win and win[0][0] < horizon:
                _, was_ok = win.popleft()
                if was_ok:
                    self._slo_ok_in_window -= 1
            total = len(win)
            att = self._slo_ok_in_window / total if total else None
        if self._g_slo_att is not None:
            self._g_slo_att.set(att)
            # Error budget for a p99 target: 1% of requests may violate.
            # burn = violation_fraction / 0.01 — burn > 1 means the
            # window is eating budget faster than the SLO allows.
            self._g_slo_burn.set(None if att is None
                                 else (1.0 - att) / 0.01)

    def _slo_block(self) -> Optional[Dict]:
        if self.slo_p99_ms <= 0:
            return None
        with self._lock:
            total = len(self._slo_window)
            ok = self._slo_ok_in_window
            causes = dict(self._slo_causes)
        return {
            "target_p99_ms": self.slo_p99_ms,
            "window_s": self.slo_window_s,
            "window_requests": total,
            "attainment": (ok / total) if total else None,
            "budget_burn": ((1.0 - ok / total) / 0.01) if total else None,
            "violations": causes,
        }

    # ------------------------------------------------------------ reporting
    def latency_quantiles_ms(self) -> Dict[str, Optional[float]]:
        """Full-run latency quantiles (ms) from the log-spaced buckets —
        the whole process history, not the reservoir window — plus the
        exact mean (sum/count)."""
        hist = self._lat_full
        if hist.count == 0:
            return {"p50_ms": None, "p99_ms": None, "p999_ms": None,
                    "mean_ms": None}
        p50, p99, p999 = hist.quantiles((0.5, 0.99, 0.999))
        return {
            "p50_ms": p50 * 1e3,
            "p99_ms": p99 * 1e3,
            "p999_ms": p999 * 1e3,
            "mean_ms": hist.sum / hist.count * 1e3,
        }

    def snapshot(self, plan=None) -> Dict:
        """One flat dict of every gauge; ``plan`` adds its cache/compile
        counters (the fields docs/SERVING.md documents).

        STABLE SCHEMA: the plan-derived keys (``compiles``,
        ``plan_bytes``, ``plan_cache``, ``quantize``, ``traverse``,
        ``aot``) are always present — ``None`` when no plan was passed
        (and ``aot`` is None without a persistent compile cache) — so
        scrapers and the Prometheus renderer see the same metric set
        every call.  Likewise ``model``/``slo``/``slow_requests``/
        ``phases`` are always present: ``None`` for an unlabeled /
        SLO-less / tracing-off instance.  ``plan_bytes`` is THIS
        plan's resident device bytes (tree pack + bin tables);
        ``plan_cache`` carries the process-global hit/miss counters plus
        ``size`` (entries) and ``bytes`` (resident bytes across every
        cached plan, with labeled per-tenant ``bytes{model="..."}``
        entries — the byte totals are the admission-control input ROADMAP
        item 1 consumes, docs/SERVING.md).  Note ``plan_cache`` is
        PROCESS-GLOBAL: the plan cache is shared by every Predictor and
        routed ``Booster.predict`` in this process, never per-predictor."""
        with self._lock:
            bs = np.asarray(self._batch_sizes, np.float64)
            out = {
                "model": self.model,
                "requests": self.requests,
                "rows": self.rows,
                "batches": self.batches,
                "queue_depth": self.queue_depth,
                "max_queue_depth": self.max_queue_depth,
                "padded_rows": self.padded_rows,
                "mean_batch_rows": float(bs.mean()) if bs.size else None,
                "shed": self.shed,
                "deadline_misses": self.deadline_misses,
                "device_faults": self.device_faults,
                "host_fallbacks": self.host_fallbacks,
                "nan_scores": self.nan_scores,
                "plan_swaps": self.plan_swaps,
                "model_swaps": self.model_swaps,
            }
        out.update(self.latency_quantiles_ms())
        out["slo"] = self._slo_block()
        # tracing surfaces (None when the tracer is disarmed — the
        # tracing-off schema carries the keys either way)
        if self.tracer.armed:
            out["slow_requests"] = self.tracer.slow_requests()
            out["phases"] = self.tracer.phase_quantiles()
        else:
            out["slow_requests"] = None
            out["phases"] = None
        out["compiles"] = None if plan is None else plan.compile_count()
        out["plan_bytes"] = (None if plan is None
                             else int(getattr(plan, "plan_bytes", 0)))
        out["plan_cache"] = (None if plan is None
                             else dict(plan_cache_stats()))
        # Quantized-pack / traversal-kernel / AOT-cache state (ISSUE-12):
        # which pack format and traversal the plan serves with, and the
        # zero-cold-start counters (``aot`` is None when no persistent
        # compile cache is configured — a stable key either way).
        out["quantize"] = (None if plan is None
                           else getattr(plan, "quantize_mode", "off"))
        out["traverse"] = (None if plan is None
                           else getattr(plan, "traverse_mode", "unfused"))
        out["aot"] = (None if plan is None
                      else getattr(plan, "aot_stats", lambda: None)())
        return out

    def render_prometheus(self, plan=None,
                          prefix: str = "lgbm_tpu_serve") -> str:
        """Prometheus text exposition of :meth:`snapshot` — a serving
        process answers a scrape from this one call
        (docs/OBSERVABILITY.md scrape example).  A ``model``-labeled
        instance renders every series with ``{model="..."}`` — two
        tenants' expositions are disjoint series sets
        (``lgbm_tpu_serve_requests{model="a"}`` vs ``{model="b"}``)."""
        snap = self.snapshot(plan=plan)
        if snap["plan_cache"] is None:
            # stable exposition even plan-less: the cache counters render
            # as NaN instead of vanishing between scrapes
            snap["plan_cache"] = {k: None for k in
                                  ("hits", "misses", "builds", "evictions",
                                   "size", "bytes")}
        # Schema stability both ways: the quantize/traverse/model strings
        # and the slow-request/phase structures never render (non-numeric
        # — they'd flap the series set with arming state), and the
        # slo/aot blocks always carry their FULL numeric shape so the
        # series exist on every scrape whether or not the feature is on.
        del snap["quantize"], snap["traverse"], snap["model"]
        del snap["slow_requests"], snap["phases"]
        slo = snap["slo"] or {}
        snap["slo"] = {
            "target_p99_ms": slo.get("target_p99_ms"),
            "window_requests": slo.get("window_requests"),
            "attainment": slo.get("attainment"),
            "budget_burn": slo.get("budget_burn"),
            "violations": {c: (slo.get("violations") or {}).get(c)
                           for c in _SLO_CAUSES},
        }
        aot = snap["aot"] or {}
        cache = aot.get("cache") or {}
        snap["aot"] = {
            "hits": aot.get("hits"), "compiles": aot.get("compiles"),
            "cache": {k: cache.get(k) for k in
                      ("hits", "misses", "stores", "errors")},
        }
        labels = None if self.model is None else {"model": self.model}
        # plan_cache is PROCESS-GLOBAL (shared by every predictor): its
        # flat counters must NOT carry this tenant's label, or N scraped
        # tenants render the same global value as N distinct series and
        # sum() double-counts.  The per-tenant bytes{model=...} entries
        # inside it carry their OWN correct label.  Everything else in
        # the snapshot is per-predictor and labels cleanly.
        plan_cache = snap.pop("plan_cache")
        return render_prometheus(snap, prefix=prefix, labels=labels) \
            + render_prometheus({"plan_cache": plan_cache}, prefix=prefix)


def plan_cache_stats() -> Dict[str, int]:
    from .plan import cache_stats
    return cache_stats()
