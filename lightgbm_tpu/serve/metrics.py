"""Serving metrics: per-request latency, batch size, queue depth, plan
cache hits and compile counts — the gauges a serving process exports.

Pure host-side bookkeeping (a lock, two bounded reservoirs, a handful of
counters); nothing here touches the device, so observing a request costs
nanoseconds next to the dispatch it measures.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Dict, Optional

import numpy as np


class ServeMetrics:
    """Thread-safe request/latency/queue accounting for one Predictor."""

    def __init__(self, reservoir: int = 4096):
        self._lock = threading.Lock()
        self._latencies = deque(maxlen=reservoir)   # seconds
        self._batch_sizes = deque(maxlen=reservoir)
        self.requests = 0
        self.rows = 0
        self.batches = 0
        self.queue_depth = 0
        self.max_queue_depth = 0
        self.padded_rows = 0
        # Graceful-degradation counters (docs/ROBUSTNESS.md): requests shed
        # by admission control, requests failed past their deadline, device
        # dispatch faults seen, and dispatches answered by the one-shot
        # host-predict fallback.
        self.shed = 0
        self.deadline_misses = 0
        self.device_faults = 0
        self.host_fallbacks = 0
        # Health guard (docs/ROBUSTNESS.md): device dispatches whose
        # scores came back non-finite — answered from the host mirror
        # instead of shipping NaN to a caller.
        self.nan_scores = 0

    # ------------------------------------------------------------- recording
    def observe_request(self, rows: int, seconds: float) -> None:
        with self._lock:
            self.requests += 1
            self.rows += int(rows)
            self._latencies.append(float(seconds))

    def observe_batch(self, rows: int, padded_to: int) -> None:
        with self._lock:
            self.batches += 1
            self._batch_sizes.append(int(rows))
            self.padded_rows += max(int(padded_to) - int(rows), 0)

    def observe_queue_depth(self, depth: int) -> None:
        with self._lock:
            self.queue_depth = int(depth)
            self.max_queue_depth = max(self.max_queue_depth, int(depth))

    def observe_shed(self, requests: int = 1) -> None:
        with self._lock:
            self.shed += int(requests)

    def observe_deadline_miss(self, requests: int = 1) -> None:
        with self._lock:
            self.deadline_misses += int(requests)

    def observe_device_fault(self) -> None:
        with self._lock:
            self.device_faults += 1

    def observe_host_fallback(self) -> None:
        with self._lock:
            self.host_fallbacks += 1

    def observe_nan_scores(self) -> None:
        with self._lock:
            self.nan_scores += 1

    # ------------------------------------------------------------ reporting
    def latency_quantiles_ms(self) -> Dict[str, Optional[float]]:
        with self._lock:
            lat = np.asarray(self._latencies, np.float64)
        if lat.size == 0:
            return {"p50_ms": None, "p99_ms": None, "mean_ms": None}
        return {
            "p50_ms": float(np.percentile(lat, 50) * 1e3),
            "p99_ms": float(np.percentile(lat, 99) * 1e3),
            "mean_ms": float(lat.mean() * 1e3),
        }

    def snapshot(self, plan=None) -> Dict:
        """One flat dict of every gauge; ``plan`` adds its cache/compile
        counters (the fields docs/SERVING.md documents)."""
        with self._lock:
            bs = np.asarray(self._batch_sizes, np.float64)
            out = {
                "requests": self.requests,
                "rows": self.rows,
                "batches": self.batches,
                "queue_depth": self.queue_depth,
                "max_queue_depth": self.max_queue_depth,
                "padded_rows": self.padded_rows,
                "mean_batch_rows": float(bs.mean()) if bs.size else None,
                "shed": self.shed,
                "deadline_misses": self.deadline_misses,
                "device_faults": self.device_faults,
                "host_fallbacks": self.host_fallbacks,
                "nan_scores": self.nan_scores,
            }
        out.update(self.latency_quantiles_ms())
        if plan is not None:
            out["compiles"] = plan.compile_count()
            # PROCESS-GLOBAL cache counters (docs/SERVING.md): the plan
            # cache is shared by every Predictor and routed Booster.predict
            # in this process, so hits/misses here are not per-predictor.
            out["plan_cache"] = dict(plan_cache_stats())
        return out


def plan_cache_stats() -> Dict[str, int]:
    from .plan import cache_stats
    return cache_stats()
