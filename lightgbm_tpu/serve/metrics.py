"""Serving metrics: per-request latency, batch size, queue depth, plan
cache hits and compile counts — the gauges a serving process exports.

Pure host-side bookkeeping (a lock, two bounded reservoirs, a handful of
counters); nothing here touches the device, so observing a request costs
nanoseconds next to the dispatch it measures.

Unified telemetry (docs/OBSERVABILITY.md): every observation ALSO mirrors
into the process-wide registry (``lightgbm_tpu.telemetry.registry()``)
under ``serve.*`` names, so one scrape of the registry sees training,
resilience and serving together; :meth:`ServeMetrics.render_prometheus`
answers a Prometheus scrape from one call.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Dict, Optional

import numpy as np

from ..telemetry import registry, render_prometheus


class ServeMetrics:
    """Thread-safe request/latency/queue accounting for one Predictor."""

    def __init__(self, reservoir: int = 4096):
        self._lock = threading.Lock()
        self._latencies = deque(maxlen=reservoir)   # seconds
        self._batch_sizes = deque(maxlen=reservoir)
        self.requests = 0
        self.rows = 0
        self.batches = 0
        self.queue_depth = 0
        self.max_queue_depth = 0
        self.padded_rows = 0
        # Graceful-degradation counters (docs/ROBUSTNESS.md): requests shed
        # by admission control, requests failed past their deadline, device
        # dispatch faults seen, and dispatches answered by the one-shot
        # host-predict fallback.
        self.shed = 0
        self.deadline_misses = 0
        self.device_faults = 0
        self.host_fallbacks = 0
        # Health guard (docs/ROBUSTNESS.md): device dispatches whose
        # scores came back non-finite — answered from the host mirror
        # instead of shipping NaN to a caller.
        self.nan_scores = 0
        # Hot-swap accounting (docs/STREAMING.md serve handoff):
        # plan_swaps = stale plans refreshed by the per-request freshness
        # check (the model mutated under this predictor); model_swaps =
        # explicit Predictor.swap_model calls (continual retrain/refit
        # landing without a restart).
        self.plan_swaps = 0
        self.model_swaps = 0
        # Registry mirrors resolved ONCE (get-or-create instruments are
        # stable objects with their own locks): the serve hot path pays no
        # table lookup under the registry lock per observation.  Caveat:
        # MetricsRegistry.reset() (tests only) detaches these mirrors for
        # the life of this ServeMetrics — see the reset() docstring.
        reg = registry()
        self._c_requests = reg.counter("serve.requests")
        self._c_rows = reg.counter("serve.rows")
        self._h_latency = reg.histogram("serve.latency_s")
        self._c_batches = reg.counter("serve.batches")
        self._g_queue = reg.gauge("serve.queue_depth")
        self._c_shed = reg.counter("serve.shed")
        self._c_deadline = reg.counter("serve.deadline_misses")
        self._c_faults = reg.counter("serve.device_faults")
        self._c_fallbacks = reg.counter("serve.host_fallbacks")
        self._c_nan = reg.counter("serve.nan_scores")
        self._c_plan_swaps = reg.counter("serve.plan_swaps")
        self._c_model_swaps = reg.counter("serve.model_swaps")

    # ------------------------------------------------------------- recording
    def observe_request(self, rows: int, seconds: float) -> None:
        with self._lock:
            self.requests += 1
            self.rows += int(rows)
            self._latencies.append(float(seconds))
        self._c_requests.inc()
        self._c_rows.inc(int(rows))
        self._h_latency.observe(float(seconds))

    def observe_batch(self, rows: int, padded_to: int) -> None:
        with self._lock:
            self.batches += 1
            self._batch_sizes.append(int(rows))
            self.padded_rows += max(int(padded_to) - int(rows), 0)
        self._c_batches.inc()

    def observe_queue_depth(self, depth: int) -> None:
        with self._lock:
            self.queue_depth = int(depth)
            self.max_queue_depth = max(self.max_queue_depth, int(depth))
        self._g_queue.set(int(depth))

    def observe_shed(self, requests: int = 1) -> None:
        with self._lock:
            self.shed += int(requests)
        self._c_shed.inc(int(requests))

    def observe_deadline_miss(self, requests: int = 1) -> None:
        with self._lock:
            self.deadline_misses += int(requests)
        self._c_deadline.inc(int(requests))

    def observe_device_fault(self) -> None:
        with self._lock:
            self.device_faults += 1
        self._c_faults.inc()

    def observe_host_fallback(self) -> None:
        with self._lock:
            self.host_fallbacks += 1
        self._c_fallbacks.inc()

    def observe_nan_scores(self) -> None:
        with self._lock:
            self.nan_scores += 1
        self._c_nan.inc()

    def observe_plan_swap(self) -> None:
        with self._lock:
            self.plan_swaps += 1
        self._c_plan_swaps.inc()

    def observe_model_swap(self) -> None:
        with self._lock:
            self.model_swaps += 1
        self._c_model_swaps.inc()

    # ------------------------------------------------------------ reporting
    def latency_quantiles_ms(self) -> Dict[str, Optional[float]]:
        with self._lock:
            lat = np.asarray(self._latencies, np.float64)
        if lat.size == 0:
            return {"p50_ms": None, "p99_ms": None, "mean_ms": None}
        return {
            "p50_ms": float(np.percentile(lat, 50) * 1e3),
            "p99_ms": float(np.percentile(lat, 99) * 1e3),
            "mean_ms": float(lat.mean() * 1e3),
        }

    def snapshot(self, plan=None) -> Dict:
        """One flat dict of every gauge; ``plan`` adds its cache/compile
        counters (the fields docs/SERVING.md documents).

        STABLE SCHEMA: the plan-derived keys (``compiles``,
        ``plan_bytes``, ``plan_cache``, ``quantize``, ``traverse``,
        ``aot``) are always present — ``None`` when no plan was passed
        (and ``aot`` is None without a persistent compile cache) — so
        scrapers and the Prometheus renderer see the same metric set
        every call.  ``plan_bytes`` is THIS
        plan's resident device bytes (tree pack + bin tables);
        ``plan_cache`` carries the process-global hit/miss counters plus
        ``size`` (entries) and ``bytes`` (resident bytes across every
        cached plan — the byte totals, not just entry counts, are the
        admission-control input ROADMAP item 1 consumes,
        docs/SERVING.md).  Note ``plan_cache`` is PROCESS-GLOBAL: the
        plan cache is shared by every Predictor and routed
        ``Booster.predict`` in this process, never per-predictor."""
        with self._lock:
            bs = np.asarray(self._batch_sizes, np.float64)
            out = {
                "requests": self.requests,
                "rows": self.rows,
                "batches": self.batches,
                "queue_depth": self.queue_depth,
                "max_queue_depth": self.max_queue_depth,
                "padded_rows": self.padded_rows,
                "mean_batch_rows": float(bs.mean()) if bs.size else None,
                "shed": self.shed,
                "deadline_misses": self.deadline_misses,
                "device_faults": self.device_faults,
                "host_fallbacks": self.host_fallbacks,
                "nan_scores": self.nan_scores,
                "plan_swaps": self.plan_swaps,
                "model_swaps": self.model_swaps,
            }
        out.update(self.latency_quantiles_ms())
        out["compiles"] = None if plan is None else plan.compile_count()
        out["plan_bytes"] = (None if plan is None
                             else int(getattr(plan, "plan_bytes", 0)))
        out["plan_cache"] = (None if plan is None
                             else dict(plan_cache_stats()))
        # Quantized-pack / traversal-kernel / AOT-cache state (ISSUE-12):
        # which pack format and traversal the plan serves with, and the
        # zero-cold-start counters (``aot`` is None when no persistent
        # compile cache is configured — a stable key either way).
        out["quantize"] = (None if plan is None
                           else getattr(plan, "quantize_mode", "off"))
        out["traverse"] = (None if plan is None
                           else getattr(plan, "traverse_mode", "unfused"))
        out["aot"] = (None if plan is None
                      else getattr(plan, "aot_stats", lambda: None)())
        return out

    def render_prometheus(self, plan=None,
                          prefix: str = "lgbm_tpu_serve") -> str:
        """Prometheus text exposition of :meth:`snapshot` — a serving
        process answers a scrape from this one call
        (docs/OBSERVABILITY.md scrape example)."""
        snap = self.snapshot(plan=plan)
        if snap["plan_cache"] is None:
            # stable exposition even plan-less: the cache counters render
            # as NaN instead of vanishing between scrapes
            snap["plan_cache"] = {k: None for k in
                                  ("hits", "misses", "builds", "evictions",
                                   "size", "bytes")}
        # Schema stability both ways: the quantize/traverse strings never
        # render (the renderer skips non-numerics — they'd appear as NaN
        # only when plan-less, flapping the series), and the aot block
        # always carries the FULL counter shape so aot_* series exist on
        # every scrape whether or not a compile cache is configured.
        del snap["quantize"], snap["traverse"]
        aot = snap["aot"] or {}
        cache = aot.get("cache") or {}
        snap["aot"] = {
            "hits": aot.get("hits"), "compiles": aot.get("compiles"),
            "cache": {k: cache.get(k) for k in
                      ("hits", "misses", "stores", "errors")},
        }
        return render_prometheus(snap, prefix=prefix)


def plan_cache_stats() -> Dict[str, int]:
    from .plan import cache_stats
    return cache_stats()
