"""Device-side binning: raw f64 rows -> bin indices, bitwise-equal to the
host :meth:`BinnedData.apply` path.

The serving accelerator runs without 64-bit mode (the training stack is
f32/int32 end-to-end), but bin boundaries are f64 midpoints of training
values — a value and a boundary can be distinguishable ONLY in f64, so an
f32 ``searchsorted`` would misbin rows near boundaries.  Instead of
widening the device dtypes, binning is done in **bit space**: an IEEE-754
double's total order equals the unsigned order of its bit pattern after a
monotone transform (negative -> all bits flipped, positive -> sign bit
set), so each f64 value travels to the device as two uint32 words and
every ``bound < value`` decision is an exact 32-bit lexicographic compare.
The whole pipeline — key transform, per-feature lower-bound search,
NaN / zero-as-missing routing, categorical vocabulary lookup — is integer
ALU work that fuses into the caller's single XLA program.

Categorical columns replicate the host LUT semantics (truncate toward
zero, unseen/negative/non-finite -> last bin) by extracting the integer
part straight from the exponent/mantissa bits; vocabularies with category
values >= 2^31 fall back to host binning (``build_bin_tables`` returns
None), exactly mirroring the host LUT's practical range.
"""

from __future__ import annotations

import sys
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..binning import _KZERO_HI, _KZERO_LO, MISSING_ZERO, BinMapper

_SIGN64 = np.uint64(1) << np.uint64(63)
_U32 = np.uint64(0xFFFFFFFF)


def f64_sort_keys(values: np.ndarray):
    """Host side: f64 array -> (hi, lo) uint32 monotone sort keys.

    For non-NaN a, b:  a < b  <=>  key(a) < key(b) lexicographically.
    (The only widening is -0.0 < +0.0, which total-order treats as strict;
    bin boundaries are midpoints of distinct values and can never be -0.0,
    so the binning decisions are unaffected.)
    """
    b = np.ascontiguousarray(np.asarray(values, np.float64)).view(np.uint64)
    key = np.where((b >> np.uint64(63)) == 1, ~b, b | _SIGN64)
    return ((key >> np.uint64(32)).astype(np.uint32),
            (key & _U32).astype(np.uint32))


def float_bits(X: np.ndarray):
    """Raw IEEE bit halves of a dense f64 matrix: ``(hi, lo)`` uint32 arrays
    of X's shape.  This is the ONLY per-request host compute on the serve
    hot path (a reinterpreting view + split); the monotone key transform
    runs on device inside the compiled program."""
    X = np.ascontiguousarray(np.asarray(X, np.float64))
    w = X.view(np.uint32).reshape(X.shape + (2,))
    if sys.byteorder == "little":
        return np.ascontiguousarray(w[..., 1]), np.ascontiguousarray(w[..., 0])
    return np.ascontiguousarray(w[..., 0]), np.ascontiguousarray(w[..., 1])


def _scalar_key(v: float):
    hi, lo = f64_sort_keys(np.asarray([v]))
    return int(hi[0]), int(lo[0])


def _steps_for(count: int) -> int:
    """Fixed trip count for a lower-bound binary search over ``count``."""
    return max(int(np.ceil(np.log2(count + 1))), 1)


def build_bin_tables(mappers: List[BinMapper]) -> Optional[dict]:
    """Flatten per-feature mappers into the device arrays ``bin_rows_device``
    consumes.  Returns None when device binning cannot reproduce the host
    path exactly (categorical values >= 2^31, outside the host LUT's
    practical range) — callers fall back to host binning."""
    f = len(mappers)
    if f == 0:
        return None
    bv = 1   # padded bound axis (numeric searched-bound count)
    cmax = 1  # padded categorical vocabulary axis
    for m in mappers:
        if m.is_categorical:
            if m.categories is not None and len(m.categories):
                if int(m.categories.max()) >= 2 ** 31:
                    return None
                cmax = max(cmax, len(m.categories))
        elif m.upper_bounds is not None:
            n_value_bins = m.num_bins - (1 if m.has_nan_bin else 0)
            bv = max(bv, n_value_bins - 1)
    ub = np.full((f, bv), np.inf, np.float64)
    nvb = np.zeros(f, np.int32)        # searched bounds per feature
    nan_target = np.zeros(f, np.int32)  # bin of NaN rows (nan_bin or 0)
    last_bin = np.zeros(f, np.int32)
    zam = np.zeros(f, bool)
    is_cat = np.zeros(f, bool)
    cat_vals = np.full((f, cmax), np.int32(2 ** 31 - 1), np.int32)
    cat_bins = np.zeros((f, cmax), np.int32)
    cat_n = np.zeros(f, np.int32)
    for j, m in enumerate(mappers):
        last_bin[j] = m.num_bins - 1
        if m.has_nan_bin:
            nan_target[j] = m.nan_bin
        if m.is_categorical:
            is_cat[j] = True
            cats = (np.asarray(m.categories, np.int64)
                    if m.categories is not None else np.zeros(0, np.int64))
            order = np.argsort(cats, kind="stable")
            cat_n[j] = len(cats)
            cat_vals[j, : len(cats)] = cats[order].astype(np.int32)
            cat_bins[j, : len(cats)] = order.astype(np.int32)
            continue
        zam[j] = m.missing_type == MISSING_ZERO
        if m.upper_bounds is None:
            continue
        n_value_bins = m.num_bins - (1 if m.has_nan_bin else 0)
        k = max(n_value_bins - 1, 0)
        nvb[j] = k
        ub[j, :k] = np.asarray(m.upper_bounds[:k], np.float64)
    ub_hi, ub_lo = f64_sort_keys(ub)
    return {
        "ub_hi": jnp.asarray(ub_hi), "ub_lo": jnp.asarray(ub_lo),
        "nvb": jnp.asarray(nvb),
        "nan_target": jnp.asarray(nan_target),
        "last_bin": jnp.asarray(last_bin),
        "zam": jnp.asarray(zam), "is_cat": jnp.asarray(is_cat),
        "cat_vals": jnp.asarray(cat_vals), "cat_bins": jnp.asarray(cat_bins),
        "cat_n": jnp.asarray(cat_n),
        # static (trace-time) scalars
        "_steps_num": _steps_for(bv),
        "_steps_cat": _steps_for(cmax),
        "_kz_lo": _scalar_key(_KZERO_LO),
        "_kz_hi": _scalar_key(_KZERO_HI),
    }


def _lex_lt(ahi, alo, bhi, blo):
    return (ahi < bhi) | ((ahi == bhi) & (alo < blo))


def _lower_bound(gather_lt, n_rows, num_feat, right0, steps):
    """#{j < right0[f] : bound[f, j] < value} via a fixed-trip binary
    search; ``gather_lt(f_idx, mid)`` answers bound[f, mid] < value."""
    f_idx = jnp.broadcast_to(jnp.arange(num_feat, dtype=jnp.int32),
                             (n_rows, num_feat))
    lo_i = jnp.zeros((n_rows, num_feat), jnp.int32)
    hi_i = jnp.broadcast_to(right0.astype(jnp.int32), (n_rows, num_feat))

    def body(_, st):
        lo_i, hi_i = st
        act = lo_i < hi_i
        mid = (lo_i + hi_i) >> 1
        less = gather_lt(f_idx, mid)
        lo_i = jnp.where(act & less, mid + 1, lo_i)
        hi_i = jnp.where(act & ~less, mid, hi_i)
        return lo_i, hi_i

    lo_i, _ = jax.lax.fori_loop(0, steps, body, (lo_i, hi_i))
    return lo_i, f_idx


def _trunc_toward_zero(hi, lo):
    """Integer part of the f64 encoded by bit halves (hi, lo), exactly,
    for |v| < 2^31.  Returns (vi int32 >= 0, unseen bool) where ``unseen``
    marks values the host LUT maps to the last bin (negative integer part,
    |v| >= 2^31, inf, NaN)."""
    e = ((hi >> jnp.uint32(20)) & jnp.uint32(0x7FF)).astype(jnp.int32)
    exp = e - 1023
    mhi = (hi & jnp.uint32(0xFFFFF)) | jnp.uint32(0x100000)
    neg = (hi >> jnp.uint32(31)) == 1
    shift = 52 - exp
    in_small = (exp >= 0) & (exp <= 20)    # shift in [32, 52]: lo shifts out
    in_big = (exp >= 21) & (exp <= 30)     # shift in [22, 31]
    sh_s = jnp.clip(shift - 32, 0, 31).astype(jnp.uint32)
    sh_b = jnp.clip(shift, 0, 31).astype(jnp.uint32)
    sh_bl = jnp.clip(32 - shift, 0, 31).astype(jnp.uint32)
    v_small = (mhi >> sh_s).astype(jnp.int32)
    v_big = ((mhi << sh_bl) | (lo >> sh_b)).astype(jnp.int32)
    vi = jnp.where(in_small, v_small, jnp.where(in_big, v_big, 0))
    vi = jnp.where(exp < 0, 0, vi)         # |v| < 1 truncates to 0
    non_finite = e == 0x7FF
    too_big = (~non_finite) & (exp >= 31)
    unseen = non_finite | too_big | (neg & (vi != 0))
    return vi, unseen


def bin_rows_device(tables: dict, hi: jnp.ndarray, lo: jnp.ndarray):
    """(N, F) int32 bins from the f64 bit halves — trace-time function, no
    host sync; meant to be inlined into one jitted predict program."""
    n, f = hi.shape
    neg = (hi >> jnp.uint32(31)) == 1
    khi = jnp.where(neg, ~hi, hi ^ jnp.uint32(0x80000000))
    klo = jnp.where(neg, ~lo, lo)
    isnan = (((hi & jnp.uint32(0x7FF00000)) == jnp.uint32(0x7FF00000))
             & (((hi & jnp.uint32(0xFFFFF)) | lo) != 0))

    # ---- numeric: lower-bound over the feature's finite bound keys
    ub_hi, ub_lo = tables["ub_hi"], tables["ub_lo"]

    def num_lt(f_idx, mid):
        return _lex_lt(ub_hi[f_idx, mid], ub_lo[f_idx, mid], khi, klo)

    nbin, _ = _lower_bound(num_lt, n, f, tables["nvb"], tables["_steps_num"])
    kz_lo, kz_hi = tables["_kz_lo"], tables["_kz_hi"]
    in_zero = (_lex_lt(jnp.uint32(kz_lo[0]), jnp.uint32(kz_lo[1]), khi, klo)
               & _lex_lt(khi, klo, jnp.uint32(kz_hi[0]), jnp.uint32(kz_hi[1])))
    nbin = jnp.where(tables["zam"][None, :] & in_zero & ~isnan,
                     tables["nan_target"][None, :], nbin)
    nbin = jnp.where(isnan, tables["nan_target"][None, :], nbin)

    # ---- categorical: truncate toward zero, sorted-vocabulary lookup
    vi, unseen = _trunc_toward_zero(hi, lo)
    cat_vals, cat_bins = tables["cat_vals"], tables["cat_bins"]

    def cat_lt(f_idx, mid):
        return cat_vals[f_idx, mid] < vi

    pos, f_idx = _lower_bound(cat_lt, n, f, tables["cat_n"],
                              tables["_steps_cat"])
    at = jnp.minimum(pos, cat_vals.shape[1] - 1)
    match = ((pos < tables["cat_n"][None, :])
             & (cat_vals[f_idx, at] == vi) & ~unseen)
    cbin = jnp.where(match, cat_bins[f_idx, at], tables["last_bin"][None, :])

    return jnp.where(tables["is_cat"][None, :], cbin, nbin)
