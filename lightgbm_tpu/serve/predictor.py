"""Serving front end: :class:`Predictor` (compiled predicts over a frozen
plan, with metrics) and :class:`MicroBatcher` (a queue that coalesces
small requests into one device dispatch up to a max wait).

``Predictor.predict`` matches ``Booster.predict`` semantics for the slice
it was frozen with (raw scores summed per class + init scores, then the
objective's output transform) — the differential tests pin the two
bitwise-equal on the device path.

Graceful degradation (docs/ROBUSTNESS.md): a device dispatch that faults
is answered ONCE from a host-side raw-threshold mirror (the serialized
model, no device touch) instead of failing the request; the MicroBatcher
adds admission control (``serve_max_queue`` -> :class:`ServeOverloadError`)
and per-request deadlines (``serve_deadline_ms`` ->
:class:`ServeDeadlineError`), all counted in :class:`ServeMetrics`.

Health guards (docs/ROBUSTNESS.md health section): every device dispatch's
scores are checked finite — non-finite output is answered from the same
host mirror (f64 raw-threshold traversal, which heals device-side numeric
faults) and counted in ``ServeMetrics.nan_scores``; raw DENSE inputs
carrying ``inf`` are rejected up front with a ``ValueError`` (the binning
contract reserves non-finite for NaN-as-missing — an Inf row would bin
into the last value bin on the host path but has no defined device
bit-key ordering).
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import Future, InvalidStateError
from queue import Empty, Queue
from typing import Optional

import numpy as np

from ..binning import _is_sparse
from ..resilience import faults
from ..telemetry import span
from ..utils.log import Log
from .bucketing import BucketLadder
from .metrics import PhaseTrace, ServeMetrics
from .plan import plan_for_model


class ServeOverloadError(RuntimeError):
    """Request shed by admission control: the queue is at ``serve_max_queue``.
    Callers should back off — queueing deeper only grows tail latency."""


class ServeDeadlineError(RuntimeError):
    """Request expired in the queue past its ``serve_deadline_ms`` — failed
    instead of dispatched late (the caller has already given up)."""


def _host_convert_output(cfg, raw: np.ndarray) -> np.ndarray:
    """Numpy re-implementation of the objective output transform for the
    host fallback path — the jax ``convert_output`` would dispatch to the
    very device that just faulted.  Covers the closed-form transforms
    (matching objectives.py); unknown objectives degrade to raw margins
    with a warning rather than failing the request."""
    obj = cfg.objective
    if obj in ("binary", "multiclassova"):
        return 1.0 / (1.0 + np.exp(-cfg.sigmoid * raw))
    if obj == "cross_entropy":
        return 1.0 / (1.0 + np.exp(-raw))
    if obj == "cross_entropy_lambda":
        return np.log1p(np.exp(raw))
    if obj == "multiclass":
        z = raw - raw.max(axis=-1, keepdims=True)
        e = np.exp(z)
        return e / e.sum(axis=-1, keepdims=True)
    if obj in ("poisson", "gamma", "tweedie"):
        return np.exp(raw)
    if obj == "regression" and cfg.reg_sqrt:
        return np.sign(raw) * raw * raw
    if obj in ("regression", "regression_l1", "huber", "fair", "quantile",
               "mape", "lambdarank", "rank_xendcg", "custom"):
        return raw
    Log.warning(f"serve host fallback: no host transform for objective="
                f"{obj}; returning raw scores")
    return raw


def _reject_inf_rows(X: np.ndarray) -> None:
    """Raw-input sanitization (binning contract): NaN means missing and is
    welcome; ``inf`` is not a value the bin mappers define an ordering
    for, so Inf-laden rows are the CALLER's bug — rejected with a clear
    error instead of silently binning into the last value bin."""
    if np.isinf(X).any():
        rows = np.unique(np.nonzero(np.isinf(X))[0])[:8]
        raise ValueError(
            f"input rows {rows.tolist()} contain inf values; the binning "
            "contract accepts NaN (missing) but not inf — clean or clip "
            "the feature pipeline upstream")


class Predictor:
    """Long-lived compiled inference handle for one Booster slice
    (reference ``Predictor``, ``src/application/predictor.cpp``: extract
    traversal state once, then only traverse)."""

    def __init__(self, booster, *, raw_score: bool = False,
                 num_iteration: Optional[int] = None,
                 start_iteration: int = 0,
                 ladder: Optional[BucketLadder] = None,
                 max_compiles: int = 16,
                 host_fallback: bool = True,
                 quantize: Optional[str] = None,
                 traverse: Optional[str] = None,
                 compile_cache: Optional[str] = None,
                 name: Optional[str] = None):
        """``quantize``/``traverse``/``compile_cache`` override the
        booster's ``tpu_serve_quantize`` / ``tpu_traverse_kernel`` /
        ``tpu_serve_compile_cache`` knobs for THIS predictor (per-tenant
        pack formats and cache dirs; docs/SERVING.md).  ``name`` labels
        the served model for per-tenant metrics (ISSUE-14): the
        predictor's registry mirrors and Prometheus exposition gain
        ``{model="<name>"}`` series, and plan-cache bytes attribute to
        it — a multi-Booster process should name every tenant."""
        model = self._validate_model(booster)
        if name is not None:
            # stamped on the MODEL so cached plans (built by any
            # predictor/route) attribute their bytes to this tenant
            model._serve_label = str(name)
        if num_iteration is None and getattr(booster, "best_iteration", -1) > 0:
            num_iteration = booster.best_iteration
        self._model = model
        self._raw_score = bool(raw_score)
        # per-plan option set, kept for freshness-driven rebuilds and
        # swap_model (the hot-swap paths must resolve the SAME plan the
        # constructor would)
        self._ladder = ladder
        self._quantize = quantize
        self._traverse = traverse
        self._compile_cache = compile_cache
        self.plan = plan_for_model(model, num_iteration, start_iteration,
                                   ladder=ladder, quantize=quantize,
                                   traverse=traverse,
                                   compile_cache=compile_cache)
        if self.plan is None:
            raise ValueError(
                "device binning cannot reproduce this dataset's bin "
                "mappers exactly (categorical values >= 2^31); use "
                "Booster.predict")
        # Request-path observability knobs (ISSUE-14): per-tenant labeled
        # metrics, SLO accounting and the sampled request tracer all live
        # on the ServeMetrics; tracing off (default) is bitwise-inert.
        cfg = model.cfg
        request_log = str(getattr(cfg, "tpu_serve_request_log",
                                  "off")).lower()
        if request_log not in ("on", "off"):
            raise ValueError(
                f"tpu_serve_request_log={request_log!r}: expected on or "
                "off")
        self.metrics = ServeMetrics(
            model=getattr(model, "_serve_label", None),
            slo_p99_ms=float(getattr(cfg, "tpu_serve_slo_p99_ms", 0.0)),
            request_log=request_log == "on",
            request_sample=float(getattr(cfg, "tpu_serve_request_sample",
                                         0.01)),
            slow_ms=float(getattr(cfg, "tpu_serve_slow_ms", 100.0)))
        self.max_compiles = int(max_compiles)
        self._compile_warned = False
        # One-shot host fallback (docs/ROBUSTNESS.md): the request that
        # sees a device fault is answered from a host raw-threshold mirror
        # built lazily on first fault; subsequent requests try the device
        # again (a transient fault heals, a dead device faults per request
        # and every fault is counted).
        self._host_fallback = bool(host_fallback)
        self._num_iteration = num_iteration
        self._start_iteration = max(int(start_iteration), 0)
        self._host_mirror_cache = None
        # Per-thread in-flight PhaseTrace: threaded to the plan calls
        # WITHOUT widening the _predict_device seam (tests and the fault
        # machinery monkeypatch it with the historical (X, sparse)
        # signature).
        self._trace_tl = threading.local()

    @staticmethod
    def _validate_model(booster):
        model = getattr(booster, "_gbdt", booster)
        if not hasattr(model, "train_data"):
            raise ValueError(
                "serve.Predictor needs a dataset-backed booster (training "
                "Booster or GBDT); a text-loaded model carries no bin "
                "mappers — retrain or keep its Booster.predict path")
        if getattr(model, "base_model", None) is not None:
            raise ValueError(
                "serve.Predictor does not support continuation boosters "
                "(base_model); save_model() and retrain, or use "
                "Booster.predict")
        if model.cfg.linear_tree:
            raise ValueError(
                "serve.Predictor does not support linear trees (leaf "
                "models need raw-value host math); use Booster.predict")
        return model

    # ------------------------------------------------------------------ API
    @property
    def num_features(self) -> int:
        return self.plan.num_features

    def _maybe_refresh_plan(self) -> None:
        """Plan freshness (the hot-swap contract, docs/STREAMING.md /
        docs/SERVING.md): a model mutated since this predictor's plan was
        built — continued training, rollback, an in-place refit's
        ``_pred_version`` bump, DART renorm — must never serve the stale
        pack.  The check is three int compares on the hot path; on
        mismatch the plan re-resolves through the cache (same option
        set), counted as ``plan_swaps``."""
        m = self._model
        state = (int(m.iter_), int(m.num_trees),
                 int(getattr(m, "_pred_version", 0)))
        if state == self.plan.built_state:
            return
        plan = plan_for_model(m, self._num_iteration,
                              self._start_iteration, ladder=self._ladder,
                              quantize=self._quantize,
                              traverse=self._traverse,
                              compile_cache=self._compile_cache)
        if plan is None:
            # dataset-level verdicts cannot change mid-flight; defensive
            raise ValueError("device binning unavailable for this model")
        if plan is not self.plan:
            self.plan = plan
            self.metrics.observe_plan_swap()

    def swap_model(self, booster) -> None:
        """Land a NEW booster (a continual retrain, a streamed refit) in
        this RUNNING predictor — no process restart: the plan re-resolves
        for the new model and, because executables are keyed
        structurally (same architecture => same AOT entries), the new
        version pays zero cold-start compiles.  Counted in
        ``ServeMetrics.model_swaps``; the host fallback mirror resets."""
        model = self._validate_model(booster)
        if self._num_iteration is None \
                and getattr(booster, "best_iteration", -1) > 0:
            num_iteration = booster.best_iteration
        else:
            num_iteration = self._num_iteration
        plan = plan_for_model(model, num_iteration, self._start_iteration,
                              ladder=self._ladder, quantize=self._quantize,
                              traverse=self._traverse,
                              compile_cache=self._compile_cache)
        if plan is None:
            raise ValueError(
                "device binning cannot reproduce the new model's bin "
                "mappers exactly; keeping the current model")
        self._model = model
        self._num_iteration = num_iteration
        self.plan = plan
        self._host_mirror_cache = None
        self.metrics.observe_model_swap()

    def predict(self, X, _record: bool = True,
                _validated: bool = False,
                _phases_out: Optional[dict] = None) -> np.ndarray:
        """Scores for a batch of rows — one compiled dispatch, recorded in
        the serving metrics.  Accepts dense arrays (device binning) or
        scipy sparse (host binning from CSC, device traversal).  A faulted
        device dispatch is answered once from the host mirror
        (``host_fallback``) instead of failing the request.
        ``_validated`` skips the Inf-input scan for callers (the
        MicroBatcher) that already door-step-checked every row;
        ``_phases_out`` (MicroBatcher, tracing armed) receives the phase
        breakdown of this dispatch so the batcher can attribute it to
        every coalesced caller."""
        t0 = time.perf_counter()
        tracer = self.metrics.tracer
        tr = PhaseTrace() if tracer.armed else None
        self._maybe_refresh_plan()
        sparse = _is_sparse(X)
        if sparse:
            if X.shape[1] != self.plan.num_features:
                # same clear error the dense path raises, instead of an
                # IndexError deep inside column-wise sparse binning
                raise ValueError(
                    f"plan expects (N, {self.plan.num_features}) rows, "
                    f"got {X.shape}")
            n = X.shape[0]
        else:
            X = np.asarray(X, np.float64)
            if X.ndim == 1:
                X = X.reshape(1, -1)
            if X.shape[1] != self.plan.num_features:
                raise ValueError(
                    f"plan expects (N, {self.plan.num_features}) rows, "
                    f"got {X.shape}")
            if not _validated:
                _reject_inf_rows(X)
            n = X.shape[0]
        self._trace_tl.current = tr
        try:
            with span("serve/predict"):
                out = self._predict_device(X, sparse)
            if not np.isfinite(out).all():
                # Health guard: never ship NaN/Inf scores.  The host
                # mirror recomputes in f64 from the serialized model — a
                # device-side numeric fault heals; a genuinely poisoned
                # model still answers (counted either way, so the gauge
                # pages before a customer does).
                self.metrics.observe_nan_scores()
                if self._host_fallback:
                    out = self._predict_host(
                        X, sparse,
                        RuntimeError("non-finite scores from the device "
                                     "dispatch"))
        except (ValueError, TypeError):
            # caller input errors are the caller's to see — only
            # infrastructure faults route to the host mirror
            raise
        except Exception as e:  # noqa: BLE001 — device fault -> host answer
            if not self._host_fallback:
                raise
            out = self._predict_host(X, sparse, e)
        finally:
            self._trace_tl.current = None
        if tr is not None:
            # post-process: output transform, finite check, slicing —
            # everything after the blocking fetch (or the whole host-
            # fallback answer when the device path never marked)
            tr.mark("post")
            if _phases_out is not None:
                _phases_out.update(tr.phases)
        if _record:   # the microbatcher records per-CALLER requests itself
            dt = time.perf_counter() - t0
            self.metrics.observe_request(n, dt)
            if tr is not None:
                tracer.record(tr.phases, rows=n, total_s=dt,
                              queue_wait_s=0.0, coalesced=1, batch_rows=n)
        self._check_compile_guard()
        return out

    def _predict_device(self, X, sparse: bool) -> np.ndarray:
        trace = getattr(self._trace_tl, "current", None)
        # fault seam (resilience/faults.py): a wedged or erroring device
        # dispatch enters serving exactly here
        faults.maybe_wedge("serve")
        if faults.serve_error_due():
            raise RuntimeError(
                "injected serve device fault "
                "(LIGHTGBM_TPU_FAULTS=serve_device_error)")
        if sparse:
            bins = self._model.train_data.binned.apply(X)
            raw = self.plan.raw_scores_binned(bins, metrics=self.metrics,
                                              trace=trace)
        else:
            raw = self.plan.raw_scores(X, metrics=self.metrics,
                                       trace=trace)
        out = raw[:, 0] if self.plan.num_class == 1 else raw
        obj = getattr(self._model, "objective", None)
        if not self._raw_score and obj is not None:
            # The output transform runs EXACTLY as Booster.predict runs it
            # (host f64 -> f32 upload -> eager convert_output): fusing it
            # into the plan's jitted program would change the rounding
            # sequence and break the pinned bitwise parity.  It is one
            # extra small dispatch; latency-critical raw-margin serving
            # should pass raw_score=True (docs/SERVING.md).
            import jax
            import jax.numpy as jnp
            out = np.asarray(jax.device_get(
                obj.convert_output(jnp.asarray(out))))
        return out

    def _predict_host(self, X, sparse: bool, cause: Exception) -> np.ndarray:
        """One-shot host fallback: raw-threshold traversal of the
        serialized model mirror — no device touch anywhere, including the
        output transform (numpy re-implementation)."""
        self.metrics.observe_device_fault()
        Log.warning(
            f"serve: device dispatch faulted ({str(cause)[:160]}); "
            "answering this request from the host mirror")
        mirror = self._host_mirror()
        if sparse:
            # densify in bounded chunks: one full todense() of the huge
            # sparse batches that route here would turn a degraded request
            # into a host OOM
            step = 65536
            out = np.concatenate([
                mirror.predict_raw(
                    np.asarray(X[lo:lo + step].todense(), np.float64),
                    num_iteration=self._num_iteration,
                    start_iteration=self._start_iteration)
                for lo in range(0, X.shape[0], step)], axis=0)
        else:
            out = mirror.predict_raw(
                np.asarray(X, np.float64),
                num_iteration=self._num_iteration,
                start_iteration=self._start_iteration)
        if not self._raw_score \
                and getattr(self._model, "objective", None) is not None:
            out = _host_convert_output(self._model.cfg, out)
        self.metrics.observe_host_fallback()
        return out

    def _host_mirror(self):
        """Serialized raw-threshold mirror of the frozen model, rebuilt
        only when trees were added/removed or rewritten in place
        (the same (num_trees, _pred_version) key the pred-early-stop
        mirror uses)."""
        from ..serialization import load_model_string, model_to_string
        key = (self._model.num_trees,
               getattr(self._model, "_pred_version", 0))
        cache = self._host_mirror_cache
        if cache is None or cache[0] != key:
            cache = (key, load_model_string(
                model_to_string(self._model, fold_bias=False)))
            self._host_mirror_cache = cache
        return cache[1]

    def warmup(self, max_rows: int = 1024) -> int:
        """Compile every ladder rung up to ``max_rows`` ahead of traffic."""
        return self.plan.warmup(max_rows)

    def batcher(self, max_batch: int = 1024, max_wait_ms: float = 2.0,
                max_queue: Optional[int] = None,
                deadline_ms: Optional[float] = None) -> "MicroBatcher":
        """``max_queue``/``deadline_ms`` default to the model's
        ``serve_max_queue``/``serve_deadline_ms`` config knobs (0 =
        unbounded / no deadline)."""
        cfg = self._model.cfg
        if max_queue is None:
            max_queue = int(getattr(cfg, "serve_max_queue", 0))
        if deadline_ms is None:
            deadline_ms = float(getattr(cfg, "serve_deadline_ms", 0.0))
        return MicroBatcher(self, max_batch=max_batch,
                            max_wait_ms=max_wait_ms, max_queue=max_queue,
                            deadline_ms=deadline_ms)

    def metrics_snapshot(self) -> dict:
        return self.metrics.snapshot(plan=self.plan)

    # ------------------------------------------------------------- internals
    def _check_compile_guard(self) -> None:
        """Compile-count guard: the ladder should hold compiles at
        O(log max_batch); blowing past ``max_compiles`` means bucketing is
        mis-sized (ratio too fine, pathological size mix) — warn once."""
        if self._compile_warned:
            return
        n = self.plan.compile_count()
        if n > self.max_compiles:
            self._compile_warned = True
            Log.warning(
                f"serve: {n} compiled predict programs exceed the guard "
                f"({self.max_compiles}); widen the BucketLadder ratio or "
                "warmup() the expected sizes")


class MicroBatcher:
    """Coalesces small predict requests into one device dispatch.

    ``submit`` returns a Future; a worker thread drains the queue, waits at
    most ``max_wait_ms`` from the first queued request (or until
    ``max_batch`` rows accumulate), predicts ONCE, and slices results back
    per request.  Queue depth / batch sizes / per-request latency land in
    the predictor's metrics.

    Degradation semantics (docs/ROBUSTNESS.md): ``max_queue`` > 0 sheds
    submits past that many queued REQUESTS with :class:`ServeOverloadError`
    (admission control — failing fast beats queueing into a latency cliff);
    ``deadline_ms`` > 0 fails requests still queued past their deadline
    with :class:`ServeDeadlineError` right before the batch dispatches (a
    dispatch already in flight is not interrupted — the deadline governs
    queue wait, the dominant tail-latency term).
    """

    def __init__(self, predictor: Predictor, *, max_batch: int = 1024,
                 max_wait_ms: float = 2.0, max_queue: int = 0,
                 deadline_ms: float = 0.0):
        self.predictor = predictor
        self.max_batch = int(max_batch)
        self.max_wait_s = float(max_wait_ms) / 1e3
        self.max_queue = int(max_queue)
        self.deadline_s = float(deadline_ms) / 1e3
        self._queue: Queue = Queue()
        self._closed = False
        # Serializes submits against close(): the None sentinel must be the
        # LAST item ever enqueued, or a racing submit's Future would sit
        # behind it on a dead queue and never resolve.
        self._submit_lock = threading.Lock()
        self._worker = threading.Thread(target=self._run, daemon=True)
        self._worker.start()

    def submit(self, X) -> Future:
        """Enqueue rows (1-D row or small 2-D batch); resolves to the same
        scores ``predictor.predict`` would return for them."""
        X = np.asarray(X, np.float64)
        if X.ndim == 1:
            X = X.reshape(1, -1)
        if X.ndim != 2 or X.shape[1] != self.predictor.num_features:
            # reject HERE: a malformed request inside a coalesced batch
            # would otherwise fail every innocent co-batched caller
            raise ValueError(
                f"expected rows with {self.predictor.num_features} "
                f"features, got {X.shape}")
        _reject_inf_rows(X)   # same door-step rule: one Inf-laden request
        # must not poison (or fail) every co-batched caller
        fut: Future = Future()
        with self._submit_lock:
            if self._closed:
                raise RuntimeError("MicroBatcher is closed")
            if self.max_queue > 0 and self._queue.qsize() >= self.max_queue:
                # Admission control: shed-with-error at the door.  Counted,
                # and raised OUTSIDE the future so the caller's submit path
                # sees backpressure immediately.
                self.predictor.metrics.observe_shed()
                raise ServeOverloadError(
                    f"serve queue full ({self._queue.qsize()} requests >= "
                    f"serve_max_queue={self.max_queue}); request shed")
            self._queue.put((X, fut, time.perf_counter()))
        self.predictor.metrics.observe_queue_depth(self._queue.qsize())
        return fut

    def close(self) -> None:
        with self._submit_lock:
            if self._closed:
                return
            self._closed = True
            self._queue.put(None)
        self._worker.join(timeout=60)

    # ------------------------------------------------------------- internals
    def _run(self) -> None:
        while True:
            item = self._queue.get()
            if item is None:
                return
            batch = [item]
            rows = item[0].shape[0]
            deadline = time.perf_counter() + self.max_wait_s
            while rows < self.max_batch:
                timeout = deadline - time.perf_counter()
                if timeout <= 0:
                    break
                try:
                    nxt = self._queue.get(timeout=timeout)
                except Empty:
                    break
                if nxt is None:
                    self._flush(batch)
                    return
                batch.append(nxt)
                rows += nxt[0].shape[0]
            self.predictor.metrics.observe_queue_depth(self._queue.qsize())
            self._flush(batch)

    @staticmethod
    def _settle(fut: Future, value=None, exc=None) -> bool:
        """Resolve a Future, tolerating callers that cancelled it while it
        was queued — an InvalidStateError here must not kill the worker
        loop (every later submit would then hang on a dead queue)."""
        try:
            if exc is not None:
                fut.set_exception(exc)
            else:
                fut.set_result(value)
            return True
        except InvalidStateError:
            return False

    def _flush(self, batch) -> None:
        if self.deadline_s > 0:
            # Requests that expired while QUEUED are failed here, not
            # dispatched: their caller has already timed out, and padding
            # the batch with them only slows the live ones.
            now = time.perf_counter()
            live, expired = [], []
            for entry in batch:
                (expired if now - entry[2] > self.deadline_s
                 else live).append(entry)
            batch = live
            for _x, fut, t_in in expired:
                if self._settle(fut, exc=ServeDeadlineError(
                        f"request waited {(now - t_in) * 1e3:.1f}ms > "
                        f"serve_deadline_ms="
                        f"{self.deadline_s * 1e3:g}")):
                    self.predictor.metrics.observe_deadline_miss()
            if not batch:
                return
        xs = [x for x, _f, _t in batch]
        tracer = self.predictor.metrics.tracer
        ph: Optional[dict] = {} if tracer.armed else None
        t_service = time.perf_counter()
        try:
            # _validated: every request was Inf-scanned at submit(), so
            # the coalesced batch skips the redundant second pass
            out = self.predictor.predict(np.concatenate(xs, axis=0),
                                         _record=False, _validated=True,
                                         _phases_out=ph)
        except Exception as e:  # noqa: BLE001 — fail every caller, not the loop
            for _x, fut, _t in batch:
                self._settle(fut, exc=e)
            return
        done = time.perf_counter()
        batch_rows = sum(x.shape[0] for x, _f, _t in batch)
        lo = 0
        for x, fut, t_in in batch:
            hi = lo + x.shape[0]
            if self._settle(fut, out[lo:hi]):
                # queue wait + coalesced dispatch, from the caller's view
                self.predictor.metrics.observe_request(x.shape[0],
                                                       done - t_in)
                if ph is not None:
                    # per-request trace: THIS caller's queue wait plus
                    # the coalesced dispatch's shared phase breakdown
                    tracer.record(ph, rows=x.shape[0],
                                  total_s=done - t_in,
                                  queue_wait_s=t_service - t_in,
                                  coalesced=len(batch),
                                  batch_rows=batch_rows)
            lo = hi
