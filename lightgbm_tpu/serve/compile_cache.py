"""Persistent AOT compile cache: zero cold-start serving (ISSUE-12).

PR 10's ``compile.end`` telemetry itemized what a serving process pays on
every restart or hot model swap: one XLA compile per (program, ladder
rung) — tens of seconds of p99 cliff before the first warm request.  This
module makes that cost once-per-fleet instead of once-per-process: each
compiled predict executable is serialized (``jax.experimental
.serialize_executable``) into a checksummed frame on disk
(``serialization.write_atomic_frame`` — the PR-6 atomic-write/checksum
helpers), keyed by

    sha256(plan identity | program kind | padded batch rows
           | jax + jaxlib version | backend)

where *plan identity* digests the pack/table array bytes plus the
quantize/traverse modes — the same model served at the same rung hits; a
retrained model, a different slice, a different quantize mode, or a
jaxlib upgrade misses by construction (stale entries can never load).

Hygiene: a corrupt frame (torn write, bitrot) fails the checksum, is
warned about, unlinked and rebuilt from a fresh compile; entries whose
embedded version tag no longer matches the running jax/jaxlib are swept
by :func:`CompileCache.sweep_stale` (and skipped on load either way).

**Trust boundary**: entries hold serialized executables (machine code)
plus pickled pytree metadata — loading one EXECUTES what the cache dir
contains, exactly like jax's own ``JAX_COMPILATION_CACHE_DIR``.  The
checksum detects corruption, not tampering.  Point the cache only at
directories with the same write-trust as the model files and code
(never world-writable paths); the serving process's filesystem
permissions ARE the security boundary.
Every hit/miss/store/error counts into the telemetry registry under
``compile.aot_cache_*`` and into the owning plan's counters (surfaced by
``ServeMetrics.snapshot`` and the ``BENCH_serve`` blob's restart fields).
"""

from __future__ import annotations

import hashlib
import os
import pickle
from typing import Optional

from ..serialization import (FrameCorruptError, read_frame,
                             write_atomic_frame)
from ..utils.log import Log

ENTRY_SUFFIX = ".aot"
_ENV_DIR = "LIGHTGBM_TPU_SERVE_CACHE_DIR"


def _versions() -> dict:
    import jax
    import jaxlib
    return {
        "jax": jax.__version__,
        "jaxlib": jaxlib.__version__,
        "backend": jax.default_backend(),
    }


def cache_dir_for(cfg) -> Optional[str]:
    """Resolve the cache directory: the ``LIGHTGBM_TPU_SERVE_CACHE_DIR``
    env var wins (deploy-time relocation without touching model params),
    else the ``tpu_serve_compile_cache`` config knob; ''/unset disables."""
    path = os.environ.get(_ENV_DIR)
    if path is None:
        path = str(getattr(cfg, "tpu_serve_compile_cache", "") or "")
    return path or None


def entry_key(plan_identity: str, kind: str, padded_rows: int) -> str:
    """Stable entry key; the version tag rides the key so an upgraded
    jax/jaxlib simply misses instead of deserializing garbage."""
    v = _versions()
    raw = (f"{plan_identity}|{kind}|{padded_rows}"
           f"|{v['jax']}|{v['jaxlib']}|{v['backend']}")
    return hashlib.sha256(raw.encode()).hexdigest()


class CompileCache:
    """One on-disk executable cache directory (shared by any number of
    plans/processes — entries are content-keyed and atomically published,
    so concurrent writers only ever race to the same bytes)."""

    def __init__(self, root: str):
        self.root = root
        self.hits = 0
        self.misses = 0
        self.stores = 0
        self.errors = 0
        from ..telemetry import registry
        reg = registry()
        self._c_hits = reg.counter("compile.aot_cache_hits")
        self._c_misses = reg.counter("compile.aot_cache_misses")
        self._c_errors = reg.counter("compile.aot_cache_errors")

    def _path(self, key: str) -> str:
        return os.path.join(self.root, key + ENTRY_SUFFIX)

    # ------------------------------------------------------------ load/store
    def load(self, key: str):
        """Deserialized compiled executable for ``key``, or None (miss /
        corrupt / version-stale — the latter two unlinked so the caller's
        fresh compile rebuilds the entry)."""
        path = self._path(key)
        if not os.path.exists(path):
            self.misses += 1
            self._c_misses.inc()
            return None
        try:
            meta, payload, in_tree, out_tree = pickle.loads(read_frame(path))
            if meta.get("versions") != _versions():
                raise FrameCorruptError(
                    f"version-stale entry (built under "
                    f"{meta.get('versions')}, running {_versions()})")
            from jax.experimental.serialize_executable import \
                deserialize_and_load
            compiled = deserialize_and_load(payload, in_tree, out_tree)
        except Exception as e:  # noqa: BLE001 — any bad entry: warn+rebuild
            self.errors += 1
            self.misses += 1
            self._c_errors.inc()
            self._c_misses.inc()
            Log.warning(
                f"serve compile cache: entry {os.path.basename(path)} "
                f"failed to load ({str(e)[:160]}); removing and "
                "recompiling")
            try:
                os.unlink(path)
            except OSError:
                pass
            return None
        self.hits += 1
        self._c_hits.inc()
        return compiled

    def store(self, key: str, compiled) -> bool:
        """Serialize and atomically publish one executable; False (with a
        warning) when the backend cannot serialize it — the cache degrades
        to per-process compiles, it never fails a request."""
        try:
            from jax.experimental.serialize_executable import serialize
            payload, in_tree, out_tree = serialize(compiled)
            blob = pickle.dumps(
                ({"versions": _versions()}, payload, in_tree, out_tree),
                protocol=4)
            os.makedirs(self.root, exist_ok=True)
            write_atomic_frame(self._path(key), blob)
        except Exception as e:  # noqa: BLE001 — cache is an accelerant only
            self.errors += 1
            self._c_errors.inc()
            Log.warning(f"serve compile cache: could not persist entry "
                        f"({str(e)[:160]}); serving continues uncached")
            return False
        self.stores += 1
        return True

    # --------------------------------------------------------------- hygiene
    def sweep_stale(self) -> dict:
        """Walk the cache dir and drop entries that can never load again:
        corrupt frames (checksum failure) and version-stale executables.
        Returns ``{"kept": n, "removed": n}`` — run it from deploy tooling
        after a jaxlib upgrade so dead bytes don't accumulate."""
        kept = removed = 0
        if not os.path.isdir(self.root):
            return {"kept": 0, "removed": 0}
        for name in os.listdir(self.root):
            if not name.endswith(ENTRY_SUFFIX):
                continue
            path = os.path.join(self.root, name)
            try:
                meta = pickle.loads(read_frame(path))[0]
                if meta.get("versions") != _versions():
                    raise FrameCorruptError("version-stale")
                kept += 1
            except Exception as e:  # noqa: BLE001 — corrupt or stale: drop
                removed += 1
                Log.warning(f"serve compile cache: sweeping {name} "
                            f"({str(e)[:120]})")
                try:
                    os.unlink(path)
                except OSError:
                    pass
        return {"kept": kept, "removed": removed}

    def stats(self) -> dict:
        return {"hits": self.hits, "misses": self.misses,
                "stores": self.stores, "errors": self.errors}
