"""PredictPlan: a Booster slice frozen into a cached, device-resident
inference program.

Training-side prediction (``GBDT._predict_raw_own``) re-runs host binning
and re-builds the SoA tree pack on EVERY call; the reference instead keeps
a long-lived ``Predictor`` with pre-extracted traversal state
(``src/application/predictor.cpp``), and the GPU-boosting literature
(arXiv:1706.08359, arXiv:1806.11248) is blunt that batched device
traversal only pays off once the model stays resident and dispatch
overhead is amortized.  A PredictPlan is that resident state for the TPU
build:

- the ``(T, ...)`` stacked tree arrays per class (built ONCE from the host
  mirrors, uploaded once),
- the binning tables (bound sort keys, categorical vocabularies,
  NaN / zero-as-missing routing — serve/device_binning.py),
- two jitted programs: raw f64 bits -> bins -> per-class scores, and
  pre-binned rows -> scores (the sparse-input path),
- shape bucketing + compile accounting.

Plans are cached per ``(model identity, iteration slice, model version)``
so repeated predicts never re-stack or re-upload; the cache keeps hit /
miss / build / eviction counters (assertable from tests and exported by
the serving metrics).
"""

from __future__ import annotations

import hashlib
import threading
import time
import weakref
from collections import OrderedDict
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..models.tree import (forest_scores, forest_scores_quantized,
                           quantize_error_bound, quantize_stack_trees,
                           stack_trees)
from ..utils.log import Log
from .bucketing import BucketLadder
from .device_binning import bin_rows_device, build_bin_tables, float_bits


class PredictPlan:
    """Frozen, device-resident predict state for one Booster slice."""

    def __init__(self, model, start_iteration: int, end_iteration: int,
                 ladder: Optional[BucketLadder] = None,
                 quantize: Optional[str] = None,
                 traverse: Optional[str] = None,
                 compile_cache: Optional[str] = None):
        binned = model.train_data.binned
        self._model_ref = weakref.ref(model)
        self.start_iteration = int(start_iteration)
        self.end_iteration = int(end_iteration)
        self.num_class = int(model.num_class)
        self.num_features = int(binned.num_features)
        self.init_scores = np.asarray(model.init_scores, np.float64).copy()
        self.ladder = ladder or BucketLadder()
        tables = build_bin_tables(binned.mappers)
        if tables is None:
            raise ValueError("device binning unavailable for this dataset")
        self._tables = tables
        # ONE batched host transfer for ONLY the sliced iterations
        # (host_trees materializes lazily per range), then one stack+upload
        # per class — the only time this plan touches the host mirrors.
        trees_by_class = model.host_trees(self.start_iteration,
                                          self.end_iteration)
        self.num_trees = sum(len(t) for t in trees_by_class)
        self._nan_bins = jnp.asarray(binned.nan_bins, jnp.int32)
        # Quantized serving packs (ISSUE-12, docs/SERVING.md): int16/int8
        # leaf quanta + narrow node arrays + bit-packed cat masks — ~4x
        # smaller resident footprint with exact routing; leaf values
        # round within quantize_error_bound().  Shapes the narrow
        # encodings can't hold degrade to the fp32 pack with a warning.
        self._stacked = None
        self._packs = None
        self.quantize_mode = "off"
        quantize = _resolve_quantize(model, quantize, warn=True)
        if quantize != "off":
            packs = [quantize_stack_trees(trees, model.cfg.num_leaves,
                                          binned.max_num_bins, quantize)
                     if trees else None for trees in trees_by_class]
            if any(p is None and trees
                   for p, trees in zip(packs, trees_by_class)):
                Log.warning(
                    f"serve: tpu_serve_quantize={quantize} needs "
                    "num_leaves/bins/features <= 32767; falling back to "
                    "the fp32 pack")
            else:
                # the dequant scale is VALUE-derived (max|leaf|): carry it
                # as a 0-d device operand so a refit/retrain swap (same
                # structure, new values) keeps the structural identity —
                # and the zero-cold-start executables — intact
                self._packs = [None if p is None
                               else dict(p, scale=jnp.float32(p["scale"]))
                               for p in packs]
                self.quantize_mode = quantize
        if self._packs is None:
            self._stacked = [
                stack_trees(trees, model.cfg.num_leaves,
                            binned.max_num_bins)
                if trees else None
                for trees in trees_by_class]
        self.traverse_mode, self.traverse_degrade = _resolve_traverse(
            model, traverse, self.quantize_mode, self._packs,
            self.num_features)
        self._interpret = jax.default_backend() != "tpu"
        self.stack_count = 1          # re-stacks would increment (never do)
        # Resident bytes for this plan (tree pack — quantized or fp32 —
        # + bin tables + NaN routing) — the per-plan half of the serve
        # byte accounting (docs/SERVING.md): plan-cache admission/eviction
        # by bytes (ROADMAP item 1) consumes exactly this number.
        # ``pack_bytes`` is the tree pack alone: the part quantization
        # shrinks (the bin tables are f64-exactness-bound and shared by
        # every mode), so shrink ratios stay meaningful on small models
        # where the tables dominate.
        self.pack_bytes = _pytree_bytes(
            self._packs if self._packs is not None else self._stacked)
        self.plan_bytes = self.pack_bytes + _pytree_bytes(
            (self._tables, self._nan_bins))

        # The pack/table arrays ride as jit ARGUMENTS (one device-resident
        # pytree), not closure constants: the compiled executables then
        # depend only on SHAPES/dtypes/modes, so a hot-swapped model
        # version (same architecture, new values) reuses the previous
        # version's executables — in-process jit cache AND the persistent
        # AOT cache (structural ``identity``) — paying ZERO cold-start
        # compiles on swap (docs/STREAMING.md serve handoff).
        self._arrays, self._static = _partition_arrays(
            ((self._packs if self._packs is not None else self._stacked),
             self._tables, self._nan_bins))
        quantized = self._packs is not None
        fused = self.traverse_mode == "fused"
        interp = self._interpret
        static = self._static

        def _scores(arrs, bins):
            packs, _tables, nan_bins = _merge_arrays(arrs, static)
            if quantized:
                return forest_scores_quantized(
                    packs, bins, nan_bins, fused=fused, interpret=interp)
            return forest_scores(packs, bins, nan_bins)

        def _from_bits(arrs, hi, lo):
            _packs, tables, _nb = _merge_arrays(arrs, static)
            return _scores(arrs, bin_rows_device(tables, hi, lo))

        # watch_compiles (telemetry/spans.py): each new ladder rung's XLA
        # compile lands as a compile.end event; launches already run
        # under the predictor's serve/predict span.
        from ..telemetry import watch_compiles
        self._jit_bits = jax.jit(_from_bits)
        self._jit_binned = jax.jit(_scores)
        self._predict_bits = watch_compiles(self._jit_bits,
                                            "serve/predict_bits")
        self._predict_binned = watch_compiles(self._jit_binned,
                                              "serve/predict_binned")
        self._shapes = set()          # padded (kind, rows) this plan compiled
        self._lock = threading.Lock()
        # Persistent AOT compile cache (serve/compile_cache.py): compiled
        # executables for this plan's ladder rungs round-trip through disk
        # so a restart/hot-swap pays ZERO XLA compiles on warm entries.
        self._aot: Dict[tuple, object] = {}
        self.aot_hits = 0
        self.aot_compiles = 0
        self._ccache = None
        self._identity = None
        if compile_cache is None:
            from .compile_cache import cache_dir_for
            compile_cache = cache_dir_for(model.cfg)
        if compile_cache:
            from .compile_cache import CompileCache
            self._ccache = CompileCache(compile_cache)
        # model mutation state at build time (iter_, num_trees,
        # _pred_version): the Predictor's per-request freshness check
        # compares the live model against this to hot-swap stale plans
        self.built_state = (int(model.iter_), int(model.num_trees),
                            int(getattr(model, "_pred_version", 0)))

    # ------------------------------------------------------------- identity
    @property
    def identity(self) -> str:
        """STRUCTURAL digest of everything the compiled predict programs
        bake in — shapes/dtypes of every pack/table leaf plus the modes
        and static metadata; array VALUES are runtime arguments and
        deliberately not hashed.  That makes the AOT cache key shared
        across model VERSIONS of the same architecture: a retrain/refit
        hot-swap loads the previous version's executables from disk
        (zero cold-start), while a re-slice, shape change, mode change or
        jax upgrade still forks the key.  Safe because the executables
        carry no model values — every call passes the plan's own
        resident arrays."""
        if self._identity is None:
            h = hashlib.sha256()
            h.update(f"{self.num_class}|{self.num_features}|"
                     f"{self.quantize_mode}|{self.traverse_mode}|"
                     f"{self._interpret}".encode())
            for leaf in jax.tree_util.tree_leaves(self._arrays):
                h.update(f"{tuple(leaf.shape)}|{leaf.dtype}".encode())
            # static metadata (quantized scale excluded by partition? no:
            # non-array leaves — scale/bits/depth — ARE baked into the
            # trace, so they stay in the digest)
            h.update(repr(jax.tree_util.tree_leaves(
                self._static, is_leaf=lambda x: not isinstance(
                    x, (dict, list, tuple)))).encode())
            self._identity = h.hexdigest()
        return self._identity

    def quantize_error_bound(self) -> float:
        """Worst-case |quantized - fp32| raw-score gap (max across
        classes; 0.0 for fp32 packs) — the fp32-parity harness's pinned
        tolerance (tests/test_serve_quantize.py)."""
        if self._packs is None:
            return 0.0
        # scale rides as a 0-d device operand (structural identity);
        # the bound is host-facing — pin it back to a float
        return max((float(quantize_error_bound(p)) for p in self._packs
                    if p is not None), default=0.0)

    # ---------------------------------------------------------- AOT dispatch
    def _call(self, kind: str, *args):
        """Launch one predict program: straight through the jitted entry
        when no compile cache is configured (today's path), else through
        the per-rung AOT executable — loaded from disk when a prior
        process compiled it (zero cold-start), compiled-and-persisted
        otherwise."""
        if self._ccache is None:
            fn = (self._predict_bits if kind == "bits"
                  else self._predict_binned)
            return fn(self._arrays, *args)
        key = (kind, int(args[0].shape[0]))
        with self._lock:
            compiled = self._aot.get(key)
        if compiled is None:
            compiled = self._aot_compile(kind, key, args)
        return compiled(self._arrays, *args)

    def _aot_compile(self, kind: str, key: tuple, args):
        from .compile_cache import entry_key
        ck = entry_key(self.identity, kind, key[1])
        compiled = self._ccache.load(ck)
        fresh = compiled is None
        if fresh:
            jit_fn = self._jit_bits if kind == "bits" else self._jit_binned
            t0 = time.perf_counter()
            compiled = jit_fn.lower(self._arrays, *args).compile()
            # compile telemetry (the jit seam can't see AOT compiles):
            # every fresh rung compile lands as a compile.end event with
            # its memory_analysis byte summary, mirroring profile_iter.
            from ..telemetry.memory import note_compile
            note_compile(f"serve/aot_{kind}", time.perf_counter() - t0,
                         compiled=compiled)
            self._ccache.store(ck, compiled)
        with self._lock:
            self._aot[key] = compiled
            if fresh:
                self.aot_compiles += 1
            else:
                self.aot_hits += 1
        return compiled

    def aot_stats(self) -> Optional[Dict[str, int]]:
        """Zero-cold-start counters: this plan's disk hits vs fresh
        compiles, plus the cache-level frame counters (None when no cache
        is configured) — ``BENCH_serve``'s post-restart compile count
        reads exactly this."""
        if self._ccache is None:
            return None
        with self._lock:
            out = {"hits": self.aot_hits, "compiles": self.aot_compiles}
        out["cache"] = self._ccache.stats()
        return out

    # ------------------------------------------------------------ accounting
    def compile_count(self) -> int:
        """Distinct FRESH XLA compiles behind this plan: the jit
        executable-cache sizes plus AOT compiles this process actually
        paid (disk-loaded executables are deliberately NOT counted — they
        are the compiles a restart skipped, reported via aot_stats()).
        Falls back to the padded-shape census on a jax without
        ``_cache_size``."""
        n = self.aot_compiles
        for fn in (self._jit_bits, self._jit_binned):
            try:
                n += int(fn._cache_size())
            except Exception:  # noqa: BLE001 — older jax: census fallback
                with self._lock:
                    return len(self._shapes)
        return n

    def _note_shape(self, kind: str, padded: int) -> None:
        with self._lock:
            self._shapes.add((kind, padded))

    def is_for(self, model) -> bool:
        return self._model_ref() is model

    @property
    def tenant(self) -> Optional[str]:
        """Model label for per-tenant attribution (ISSUE-14): the serve
        label a named ``Predictor`` stamped on the model, read LIVE so a
        cached plan follows a late naming.  ``None`` for unnamed models
        (their bytes attribute to the ``_unnamed`` bucket)."""
        model = self._model_ref()
        return None if model is None else getattr(model, "_serve_label",
                                                  None)

    # ------------------------------------------------------------ prediction
    def _pad(self, arrs, n: int):
        padded = self.ladder.bucket(n)
        if padded == n:
            return arrs, padded
        return [np.pad(a, ((0, padded - n), (0, 0))) for a in arrs], padded

    def raw_scores(self, X, metrics=None, trace=None) -> np.ndarray:
        """(N, K) f64 raw scores (init scores included) for dense rows —
        host work is one bit-split view + ladder pad; binning, traversal
        and per-class accumulation run as ONE jitted dispatch.  ``trace``
        (a ``serve.metrics.PhaseTrace``) marks the assemble/dispatch
        boundary split — host ``perf_counter`` arithmetic only, the
        compiled program is identical with or without it (ISSUE-14
        inertness pin)."""
        X = np.asarray(X)
        n = X.shape[0]
        if X.ndim != 2 or X.shape[1] != self.num_features:
            raise ValueError(
                f"plan expects (N, {self.num_features}) rows, got {X.shape}")
        if n == 0:
            return np.zeros((0, self.num_class), np.float64) \
                + self.init_scores[None, :]
        hi, lo = float_bits(X)
        (hi, lo), padded = self._pad([hi, lo], n)
        self._note_shape("bits", padded)
        if trace is not None:
            trace.mark("assemble")
        scores = self._call("bits", jnp.asarray(hi), jnp.asarray(lo))
        if metrics is not None:
            metrics.observe_batch(n, padded)
        out = np.asarray(jax.device_get(scores), np.float64)[:n]
        if trace is not None:       # upload + launch + blocking fetch
            trace.mark("dispatch")
        out += self.init_scores[None, :]
        return out

    def raw_scores_binned(self, bins: np.ndarray, metrics=None,
                          trace=None) -> np.ndarray:
        """(N, K) f64 raw scores from PRE-BINNED rows (the sparse-input
        path: host binning straight from CSC, device traversal from the
        resident pack — still no re-stacking)."""
        bins = np.asarray(bins)
        n = bins.shape[0]
        if n == 0:
            return np.zeros((0, self.num_class), np.float64) \
                + self.init_scores[None, :]
        (bins,), padded = self._pad([bins], n)
        self._note_shape("binned", padded)
        if trace is not None:
            trace.mark("assemble")
        scores = self._call("binned", jnp.asarray(bins))
        if metrics is not None:
            metrics.observe_batch(n, padded)
        out = np.asarray(jax.device_get(scores), np.float64)[:n]
        if trace is not None:
            trace.mark("dispatch")
        out += self.init_scores[None, :]
        return out

    def warmup(self, max_rows: int) -> int:
        """Pre-compile the dense-path program for every ladder rung up to
        ``bucket(max_rows)``; returns the number of rungs warmed."""
        rungs = self.ladder.rungs_upto(max_rows)
        for m in rungs:
            self.raw_scores(np.zeros((m, self.num_features)))
        return len(rungs)


class _ArraySlot:
    """Sentinel marking 'this leaf lives in the arrays pytree'."""

    __slots__ = ()

    def __repr__(self):
        return "<array>"


_ARRAY = _ArraySlot()


def _partition_arrays(obj):
    """Split a nested pack/table structure into (device arrays pytree,
    static skeleton).  Arrays become jit ARGUMENTS (uploaded once here);
    ints/floats/strings stay trace-time constants.  ``_merge_arrays``
    reassembles the original structure inside the trace."""
    if isinstance(obj, dict):
        arrs, stat = {}, {}
        for k, v in obj.items():
            arrs[k], stat[k] = _partition_arrays(v)
        return arrs, stat
    if isinstance(obj, (list, tuple)):
        pairs = [_partition_arrays(v) for v in obj]
        return (type(obj)(p[0] for p in pairs),
                type(obj)(p[1] for p in pairs))
    if hasattr(obj, "shape") and hasattr(obj, "dtype"):
        return jnp.asarray(obj), _ARRAY
    return None, obj


def _merge_arrays(arrs, stat):
    if stat is _ARRAY:
        return arrs
    if isinstance(stat, dict):
        return {k: _merge_arrays(arrs[k], stat[k]) for k in stat}
    if isinstance(stat, (list, tuple)):
        return type(stat)(_merge_arrays(a, s) for a, s in zip(arrs, stat))
    return stat


def _pytree_bytes(tree) -> int:
    """Total array bytes across a pytree (stacked packs, table dicts)."""
    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        total += int(getattr(leaf, "nbytes", 0) or 0)
    return total


def _resolve_quantize(model, quantize: Optional[str],
                      warn: bool = False) -> str:
    """Effective pack quantize mode: the explicit kwarg wins, else the
    booster's ``tpu_serve_quantize`` knob; unknown spellings mean off
    (warned only from the plan BUILD — this also runs in the hot-path
    cache-key computation, which must not spam the log)."""
    if quantize is None:
        quantize = getattr(model.cfg, "tpu_serve_quantize", "off")
    quantize = str(quantize).lower()
    if quantize not in ("off", "int16", "int8"):
        if warn:
            Log.warning(f"serve: unknown tpu_serve_quantize={quantize!r} "
                        "(expected off|int16|int8); using off")
        return "off"
    return quantize


def _resolve_traverse(model, traverse: Optional[str], quantize_mode: str,
                      packs, num_features: int):
    """(mode, degrade_reason) for the traversal kernel.  fused needs a
    quantized pack (integer identity is the kernel's contract) and the
    VMEM fit gate; auto additionally requires a live TPU backend (on CPU
    the kernel only runs in interpret mode — a test vehicle, engaged by
    forcing fused, never by auto)."""
    if traverse is None:
        traverse = getattr(model.cfg, "tpu_traverse_kernel", "auto")
    traverse = str(traverse).lower()
    if traverse not in ("auto", "fused", "unfused"):
        Log.warning(f"serve: unknown tpu_traverse_kernel={traverse!r} "
                    "(expected auto|fused|unfused); using unfused")
        return "unfused", f"unknown mode {traverse!r}"
    if traverse == "unfused":
        return "unfused", None
    if quantize_mode == "off" or packs is None:
        reason = "fused traversal needs a quantized pack " \
                 "(tpu_serve_quantize=int16|int8)"
        if traverse == "fused":
            Log.warning(f"serve: tpu_traverse_kernel=fused ignored — "
                        f"{reason}")
            return "unfused", reason
        return "unfused", None          # auto simply doesn't engage
    from ..ops.pallas_traverse import traverse_layout_fits
    fits = all(
        traverse_layout_fits(int(p["leaf_q"].shape[0]),
                             int(p["leaf_q"].shape[1]), num_features,
                             int(p["num_bins"]))
        for p in packs if p is not None)
    if not fits:
        reason = "tree pack exceeds the traversal kernel's VMEM budget"
        if traverse == "fused":
            Log.warning(f"serve: tpu_traverse_kernel=fused ignored — "
                        f"{reason}")
        return "unfused", reason
    if traverse == "auto" and jax.default_backend() != "tpu":
        return "unfused", None
    return "fused", None


# ---------------------------------------------------------------- plan cache
_CACHE: "OrderedDict[tuple, PredictPlan]" = OrderedDict()
_CACHE_LOCK = threading.Lock()
_CACHE_CAP = 8
_STATS = {"hits": 0, "misses": 0, "builds": 0, "evictions": 0}
# Per-key in-flight build markers: N threads missing the same key must run
# ONE stack+upload, not N (the losers wait on the winner's Event).
_INFLIGHT: Dict[tuple, threading.Event] = {}


def _stale_locked(key, plan) -> bool:
    """A cache entry is stale when its model was garbage-collected or has
    trained/rolled past the keyed (iter_, num_trees) state — the key can
    never hit again, but the entry would pin a device-resident tree pack
    until cap pressure evicted it."""
    model = plan._model_ref()
    if model is None:
        return True
    return (int(model.iter_), int(model.num_trees),
            int(getattr(model, "_pred_version", 0))) != key[3:6]


def _sweep_dead_locked() -> int:
    """Drop stale entries (caller holds _CACHE_LOCK); returns how many
    were removed, so hit-path callers republish the byte gauges only
    when something actually changed."""
    stale = [k for k, p in _CACHE.items() if _stale_locked(k, p)]
    for k in stale:
        del _CACHE[k]
        _STATS["evictions"] += 1
    return len(stale)


def _resolve_slice(model, num_iteration: Optional[int],
                   start_iteration: int):
    # dev_models (not the .models property): a cache HIT must not touch —
    # let alone materialize — the host tree mirrors.
    n = len(model.dev_models[0]) if model.dev_models else 0
    start = max(int(start_iteration), 0)
    end = n if num_iteration is None else min(n, start + int(num_iteration))
    return start, max(end, start)


def plan_for_model(model, num_iteration: Optional[int] = None,
                   start_iteration: int = 0,
                   ladder: Optional[BucketLadder] = None,
                   quantize: Optional[str] = None,
                   traverse: Optional[str] = None,
                   compile_cache: Optional[str] = None
                   ) -> Optional[PredictPlan]:
    """Fetch (or build) the cached PredictPlan for a GBDT slice.

    The key carries the model's identity AND its mutation state (``iter_``,
    ``num_trees``, ``_pred_version`` — the latter bumped by in-place leaf
    mutations like the C-API's SetLeafValue/Refit): training another
    round, rolling one back, or rewriting leaves changes the key, so a
    stale pack can never serve.  ``quantize``/``traverse``/
    ``compile_cache`` override the booster's knobs per plan (per-tenant
    pack formats, ROADMAP item 1) and ride the key — a quantized plan and
    the fp32 plan of the same model coexist in the cache.  Returns None
    when the dataset cannot be device-binned exactly (callers fall back
    to the legacy host path); that verdict is dataset-level and
    permanent, so it is memoized on the model — the hot predict path must
    not re-derive the bin tables just to fail again."""
    if getattr(model, "_serve_unsupported", False):
        return None
    ladder = ladder or BucketLadder()
    start, end = _resolve_slice(model, num_iteration, start_iteration)
    # Key on NORMALIZED mode requests (kwarg-or-knob, lowercased; cache
    # dir through the env/knob resolution): Predictor(bst) and
    # Predictor(bst, traverse="auto") describe the same plan and must
    # share one device-resident build, not double the cache bytes.
    if traverse is None:
        traverse = getattr(model.cfg, "tpu_traverse_kernel", "auto")
    traverse = str(traverse).lower()
    if compile_cache is None:
        from .compile_cache import cache_dir_for
        compile_cache = cache_dir_for(model.cfg)
    key = (id(model), start, end, int(model.iter_), int(model.num_trees),
           int(getattr(model, "_pred_version", 0)), ladder,
           _resolve_quantize(model, quantize), traverse, compile_cache)
    while True:
        with _CACHE_LOCK:
            plan = _CACHE.get(key)
            # id() can be recycled after GC — the weakref check makes a
            # hit structural, not just numeric.
            if plan is not None and plan.is_for(model):
                _STATS["hits"] += 1
                _CACHE.move_to_end(key)
                # sweep on hits too: a steady stream of cache hits must
                # not pin dead models' tree packs until the next build —
                # and the byte gauges must follow an actual eviction, or
                # a scraper sees evicted packs' bytes forever.  A clean
                # hit (the common case) publishes nothing: the serve hot
                # path pays no registry work and no O(cache) byte sum.
                if _sweep_dead_locked():
                    _publish_bytes_locked()
                return plan
            ev = _INFLIGHT.get(key)
            if ev is None:
                _INFLIGHT[key] = threading.Event()
                _STATS["misses"] += 1
                break
        # Another thread is stacking/uploading this exact plan — wait for
        # it, then re-check (if it failed, the loop makes us the builder).
        ev.wait()
    plan = None
    try:
        plan = PredictPlan(model, start, end, ladder=ladder,
                           quantize=quantize, traverse=traverse,
                           compile_cache=compile_cache)
    except ValueError:
        model._serve_unsupported = True
        return None
    finally:
        with _CACHE_LOCK:
            if plan is not None:
                _STATS["builds"] += 1
                _CACHE[key] = plan
                _CACHE.move_to_end(key)
                _sweep_dead_locked()
                while len(_CACHE) > _CACHE_CAP:
                    _CACHE.popitem(last=False)
                    _STATS["evictions"] += 1
            _publish_bytes_locked()
            _INFLIGHT.pop(key).set()
    return plan


def _cache_bytes_locked() -> int:
    return sum(p.plan_bytes for p in _CACHE.values())


def _cache_bytes_by_tenant_locked() -> Dict[str, int]:
    """Resident plan-cache bytes grouped by model label (``_unnamed``
    for label-less models) — ROADMAP item 1's per-tenant admission input
    (a byte budget can only evict per tenant if the bytes attribute per
    tenant)."""
    out: Dict[str, int] = {}
    for p in _CACHE.values():
        name = p.tenant or "_unnamed"
        out[name] = out.get(name, 0) + p.plan_bytes
    return out


# tenant labels whose plan_cache_bytes gauge was ever published: an
# evicted tenant's gauge drops to 0 instead of lingering at its last value
_PUBLISHED_TENANTS: set = set()


def _publish_bytes_locked() -> None:
    """Byte gauges (docs/OBSERVABILITY.md serve section): the
    most-recently-used cached plan's resident bytes
    (``serve.plan_bytes``, 0 when the cache is empty — an evicted pack's
    bytes never linger in the gauge), the cache-wide total
    (``serve.plan_cache_bytes``) and the per-tenant labeled split
    (``serve.plan_cache_bytes{model="..."}``) — the admission-control
    input ROADMAP item 1's eviction-by-bytes will consume."""
    from ..telemetry import registry
    reg = registry()
    mru = next(reversed(_CACHE)) if _CACHE else None
    reg.gauge("serve.plan_bytes").set(
        _CACHE[mru].plan_bytes if mru is not None else 0)
    reg.gauge("serve.plan_cache_bytes").set(_cache_bytes_locked())
    by_tenant = _cache_bytes_by_tenant_locked()
    for name in _PUBLISHED_TENANTS - set(by_tenant):
        reg.gauge("serve.plan_cache_bytes",
                  labels={"model": name}).set(0)
    for name, nbytes in by_tenant.items():
        _PUBLISHED_TENANTS.add(name)
        reg.gauge("serve.plan_cache_bytes",
                  labels={"model": name}).set(nbytes)


def cache_stats() -> Dict[str, int]:
    """Hit/miss/build/eviction counters plus the live cache footprint:
    ``size`` (entries) AND ``bytes`` (resident device bytes across every
    cached plan — entry counts alone cannot drive byte-budget admission
    control, docs/SERVING.md), with labeled per-tenant
    ``bytes{model="..."}`` entries that render as labeled Prometheus
    series."""
    from ..telemetry.registry import labeled_name
    with _CACHE_LOCK:
        out = dict(_STATS, size=len(_CACHE), bytes=_cache_bytes_locked())
        for name, nbytes in _cache_bytes_by_tenant_locked().items():
            out[labeled_name("bytes", {"model": name})] = nbytes
    return out


def clear_plan_cache() -> None:
    with _CACHE_LOCK:
        _CACHE.clear()
        for k in ("hits", "misses", "builds", "evictions"):
            _STATS[k] = 0
        _publish_bytes_locked()
