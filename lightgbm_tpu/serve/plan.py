"""PredictPlan: a Booster slice frozen into a cached, device-resident
inference program.

Training-side prediction (``GBDT._predict_raw_own``) re-runs host binning
and re-builds the SoA tree pack on EVERY call; the reference instead keeps
a long-lived ``Predictor`` with pre-extracted traversal state
(``src/application/predictor.cpp``), and the GPU-boosting literature
(arXiv:1706.08359, arXiv:1806.11248) is blunt that batched device
traversal only pays off once the model stays resident and dispatch
overhead is amortized.  A PredictPlan is that resident state for the TPU
build:

- the ``(T, ...)`` stacked tree arrays per class (built ONCE from the host
  mirrors, uploaded once),
- the binning tables (bound sort keys, categorical vocabularies,
  NaN / zero-as-missing routing — serve/device_binning.py),
- two jitted programs: raw f64 bits -> bins -> per-class scores, and
  pre-binned rows -> scores (the sparse-input path),
- shape bucketing + compile accounting.

Plans are cached per ``(model identity, iteration slice, model version)``
so repeated predicts never re-stack or re-upload; the cache keeps hit /
miss / build / eviction counters (assertable from tests and exported by
the serving metrics).
"""

from __future__ import annotations

import threading
import weakref
from collections import OrderedDict
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..models.tree import forest_scores, stack_trees
from .bucketing import BucketLadder
from .device_binning import bin_rows_device, build_bin_tables, float_bits


class PredictPlan:
    """Frozen, device-resident predict state for one Booster slice."""

    def __init__(self, model, start_iteration: int, end_iteration: int,
                 ladder: Optional[BucketLadder] = None):
        binned = model.train_data.binned
        self._model_ref = weakref.ref(model)
        self.start_iteration = int(start_iteration)
        self.end_iteration = int(end_iteration)
        self.num_class = int(model.num_class)
        self.num_features = int(binned.num_features)
        self.init_scores = np.asarray(model.init_scores, np.float64).copy()
        self.ladder = ladder or BucketLadder()
        tables = build_bin_tables(binned.mappers)
        if tables is None:
            raise ValueError("device binning unavailable for this dataset")
        self._tables = tables
        # ONE batched host transfer for ONLY the sliced iterations
        # (host_trees materializes lazily per range), then one stack+upload
        # per class — the only time this plan touches the host mirrors.
        trees_by_class = model.host_trees(self.start_iteration,
                                          self.end_iteration)
        self.num_trees = sum(len(t) for t in trees_by_class)
        self._stacked = [
            stack_trees(trees, model.cfg.num_leaves, binned.max_num_bins)
            if trees else None
            for trees in trees_by_class]
        self._nan_bins = jnp.asarray(binned.nan_bins, jnp.int32)
        self.stack_count = 1          # re-stacks would increment (never do)
        # Resident bytes for this plan (stacked tree pack + bin tables +
        # NaN routing) — the per-plan half of the serve byte accounting
        # (docs/SERVING.md): plan-cache admission/eviction by bytes
        # (ROADMAP item 1) consumes exactly this number.
        self.plan_bytes = _pytree_bytes(
            (self._stacked, self._tables, self._nan_bins))

        def _from_bits(hi, lo):
            bins = bin_rows_device(self._tables, hi, lo)
            return forest_scores(self._stacked, bins, self._nan_bins)

        def _from_bins(bins):
            return forest_scores(self._stacked, bins, self._nan_bins)

        # watch_compiles (telemetry/spans.py): each new ladder rung's XLA
        # compile lands as a compile.end event; launches already run
        # under the predictor's serve/predict span.
        from ..telemetry import watch_compiles
        self._predict_bits = watch_compiles(jax.jit(_from_bits),
                                            "serve/predict_bits")
        self._predict_binned = watch_compiles(jax.jit(_from_bins),
                                              "serve/predict_binned")
        self._shapes = set()          # padded (kind, rows) this plan compiled
        self._lock = threading.Lock()

    # ------------------------------------------------------------ accounting
    def compile_count(self) -> int:
        """Distinct compiled programs behind this plan.  Prefers the jit
        executable-cache sizes (actual XLA compiles); falls back to the
        padded-shape census when running on a jax without ``_cache_size``."""
        n = 0
        for fn in (self._predict_bits, self._predict_binned):
            try:
                n += int(fn._cache_size())
            except Exception:  # noqa: BLE001 — older jax: census fallback
                with self._lock:
                    return len(self._shapes)
        return n

    def _note_shape(self, kind: str, padded: int) -> None:
        with self._lock:
            self._shapes.add((kind, padded))

    def is_for(self, model) -> bool:
        return self._model_ref() is model

    # ------------------------------------------------------------ prediction
    def _pad(self, arrs, n: int):
        padded = self.ladder.bucket(n)
        if padded == n:
            return arrs, padded
        return [np.pad(a, ((0, padded - n), (0, 0))) for a in arrs], padded

    def raw_scores(self, X, metrics=None) -> np.ndarray:
        """(N, K) f64 raw scores (init scores included) for dense rows —
        host work is one bit-split view + ladder pad; binning, traversal
        and per-class accumulation run as ONE jitted dispatch."""
        X = np.asarray(X)
        n = X.shape[0]
        if X.ndim != 2 or X.shape[1] != self.num_features:
            raise ValueError(
                f"plan expects (N, {self.num_features}) rows, got {X.shape}")
        if n == 0:
            return np.zeros((0, self.num_class), np.float64) \
                + self.init_scores[None, :]
        hi, lo = float_bits(X)
        (hi, lo), padded = self._pad([hi, lo], n)
        self._note_shape("bits", padded)
        scores = self._predict_bits(jnp.asarray(hi), jnp.asarray(lo))
        if metrics is not None:
            metrics.observe_batch(n, padded)
        out = np.asarray(jax.device_get(scores), np.float64)[:n]
        out += self.init_scores[None, :]
        return out

    def raw_scores_binned(self, bins: np.ndarray, metrics=None) -> np.ndarray:
        """(N, K) f64 raw scores from PRE-BINNED rows (the sparse-input
        path: host binning straight from CSC, device traversal from the
        resident pack — still no re-stacking)."""
        bins = np.asarray(bins)
        n = bins.shape[0]
        if n == 0:
            return np.zeros((0, self.num_class), np.float64) \
                + self.init_scores[None, :]
        (bins,), padded = self._pad([bins], n)
        self._note_shape("binned", padded)
        scores = self._predict_binned(jnp.asarray(bins))
        if metrics is not None:
            metrics.observe_batch(n, padded)
        out = np.asarray(jax.device_get(scores), np.float64)[:n]
        out += self.init_scores[None, :]
        return out

    def warmup(self, max_rows: int) -> int:
        """Pre-compile the dense-path program for every ladder rung up to
        ``bucket(max_rows)``; returns the number of rungs warmed."""
        rungs = self.ladder.rungs_upto(max_rows)
        for m in rungs:
            self.raw_scores(np.zeros((m, self.num_features)))
        return len(rungs)


def _pytree_bytes(tree) -> int:
    """Total array bytes across a pytree (stacked packs, table dicts)."""
    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        total += int(getattr(leaf, "nbytes", 0) or 0)
    return total


# ---------------------------------------------------------------- plan cache
_CACHE: "OrderedDict[tuple, PredictPlan]" = OrderedDict()
_CACHE_LOCK = threading.Lock()
_CACHE_CAP = 8
_STATS = {"hits": 0, "misses": 0, "builds": 0, "evictions": 0}
# Per-key in-flight build markers: N threads missing the same key must run
# ONE stack+upload, not N (the losers wait on the winner's Event).
_INFLIGHT: Dict[tuple, threading.Event] = {}


def _stale_locked(key, plan) -> bool:
    """A cache entry is stale when its model was garbage-collected or has
    trained/rolled past the keyed (iter_, num_trees) state — the key can
    never hit again, but the entry would pin a device-resident tree pack
    until cap pressure evicted it."""
    model = plan._model_ref()
    if model is None:
        return True
    return (int(model.iter_), int(model.num_trees),
            int(getattr(model, "_pred_version", 0))) != key[3:6]


def _sweep_dead_locked() -> int:
    """Drop stale entries (caller holds _CACHE_LOCK); returns how many
    were removed, so hit-path callers republish the byte gauges only
    when something actually changed."""
    stale = [k for k, p in _CACHE.items() if _stale_locked(k, p)]
    for k in stale:
        del _CACHE[k]
        _STATS["evictions"] += 1
    return len(stale)


def _resolve_slice(model, num_iteration: Optional[int],
                   start_iteration: int):
    # dev_models (not the .models property): a cache HIT must not touch —
    # let alone materialize — the host tree mirrors.
    n = len(model.dev_models[0]) if model.dev_models else 0
    start = max(int(start_iteration), 0)
    end = n if num_iteration is None else min(n, start + int(num_iteration))
    return start, max(end, start)


def plan_for_model(model, num_iteration: Optional[int] = None,
                   start_iteration: int = 0,
                   ladder: Optional[BucketLadder] = None
                   ) -> Optional[PredictPlan]:
    """Fetch (or build) the cached PredictPlan for a GBDT slice.

    The key carries the model's identity AND its mutation state (``iter_``,
    ``num_trees``, ``_pred_version`` — the latter bumped by in-place leaf
    mutations like the C-API's SetLeafValue/Refit): training another
    round, rolling one back, or rewriting leaves changes the key, so a
    stale pack can never serve.  Returns None when the dataset cannot be
    device-binned exactly (callers fall back to the legacy host path);
    that verdict is dataset-level and permanent, so it is memoized on the
    model — the hot predict path must not re-derive the bin tables just
    to fail again."""
    if getattr(model, "_serve_unsupported", False):
        return None
    ladder = ladder or BucketLadder()
    start, end = _resolve_slice(model, num_iteration, start_iteration)
    key = (id(model), start, end, int(model.iter_), int(model.num_trees),
           int(getattr(model, "_pred_version", 0)), ladder)
    while True:
        with _CACHE_LOCK:
            plan = _CACHE.get(key)
            # id() can be recycled after GC — the weakref check makes a
            # hit structural, not just numeric.
            if plan is not None and plan.is_for(model):
                _STATS["hits"] += 1
                _CACHE.move_to_end(key)
                # sweep on hits too: a steady stream of cache hits must
                # not pin dead models' tree packs until the next build —
                # and the byte gauges must follow an actual eviction, or
                # a scraper sees evicted packs' bytes forever.  A clean
                # hit (the common case) publishes nothing: the serve hot
                # path pays no registry work and no O(cache) byte sum.
                if _sweep_dead_locked():
                    _publish_bytes_locked()
                return plan
            ev = _INFLIGHT.get(key)
            if ev is None:
                _INFLIGHT[key] = threading.Event()
                _STATS["misses"] += 1
                break
        # Another thread is stacking/uploading this exact plan — wait for
        # it, then re-check (if it failed, the loop makes us the builder).
        ev.wait()
    plan = None
    try:
        plan = PredictPlan(model, start, end, ladder=ladder)
    except ValueError:
        model._serve_unsupported = True
        return None
    finally:
        with _CACHE_LOCK:
            if plan is not None:
                _STATS["builds"] += 1
                _CACHE[key] = plan
                _CACHE.move_to_end(key)
                _sweep_dead_locked()
                while len(_CACHE) > _CACHE_CAP:
                    _CACHE.popitem(last=False)
                    _STATS["evictions"] += 1
            _publish_bytes_locked()
            _INFLIGHT.pop(key).set()
    return plan


def _cache_bytes_locked() -> int:
    return sum(p.plan_bytes for p in _CACHE.values())


def _publish_bytes_locked() -> None:
    """Byte gauges (docs/OBSERVABILITY.md serve section): the
    most-recently-used cached plan's resident bytes
    (``serve.plan_bytes``, 0 when the cache is empty — an evicted pack's
    bytes never linger in the gauge) and the cache-wide total
    (``serve.plan_cache_bytes``) — the admission-control input ROADMAP
    item 1's eviction-by-bytes will consume."""
    from ..telemetry import registry
    reg = registry()
    mru = next(reversed(_CACHE)) if _CACHE else None
    reg.gauge("serve.plan_bytes").set(
        _CACHE[mru].plan_bytes if mru is not None else 0)
    reg.gauge("serve.plan_cache_bytes").set(_cache_bytes_locked())


def cache_stats() -> Dict[str, int]:
    """Hit/miss/build/eviction counters plus the live cache footprint:
    ``size`` (entries) AND ``bytes`` (resident device bytes across every
    cached plan — entry counts alone cannot drive byte-budget admission
    control, docs/SERVING.md)."""
    with _CACHE_LOCK:
        return dict(_STATS, size=len(_CACHE), bytes=_cache_bytes_locked())


def clear_plan_cache() -> None:
    with _CACHE_LOCK:
        _CACHE.clear()
        for k in ("hits", "misses", "builds", "evictions"):
            _STATS[k] = 0
        _publish_bytes_locked()
