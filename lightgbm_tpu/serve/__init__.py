"""lightgbm_tpu.serve — compiled inference serving.

A trained Booster is frozen into a :class:`PredictPlan` (device-resident
SoA tree pack + exact device binning tables + jitted raw-floats->scores
program, cached per model slice), fronted by a :class:`Predictor` with
shape-bucketed batching, an optional request-coalescing
:class:`MicroBatcher`, and serving metrics.  See docs/SERVING.md.

Quickstart::

    import lightgbm_tpu as lgb
    from lightgbm_tpu import serve

    bst = lgb.train(params, train_set, 100)
    pred = serve.Predictor(bst)
    pred.warmup(1024)                  # pre-compile the bucket ladder
    scores = pred.predict(rows)        # == bst.predict(rows)
    print(pred.metrics_snapshot())     # p50/p99, compiles, cache hits
"""

from .bucketing import BucketLadder
from .compile_cache import CompileCache
from .metrics import PhaseTrace, RequestTracer, ServeMetrics
from .plan import (PredictPlan, cache_stats, clear_plan_cache,
                   plan_for_model)
from .predictor import (MicroBatcher, Predictor, ServeDeadlineError,
                        ServeOverloadError)

__all__ = [
    "BucketLadder", "CompileCache", "MicroBatcher", "PhaseTrace",
    "PredictPlan", "Predictor", "RequestTracer", "ServeDeadlineError",
    "ServeMetrics", "ServeOverloadError", "cache_stats",
    "clear_plan_cache", "plan_for_model",
]
