"""Training callbacks.

Reference: ``python-package/lightgbm/callback.py`` (498 LoC) — same public
surface: ``early_stopping``, ``log_evaluation``, ``record_evaluation``,
``reset_parameter``, with the ``CallbackEnv`` protocol and ``EarlyStopException``.
"""

from __future__ import annotations

import collections
from typing import Any, Callable, Dict, List

CallbackEnv = collections.namedtuple(
    "CallbackEnv",
    ["model", "params", "iteration", "begin_iteration", "end_iteration",
     "evaluation_result_list"],
)


class EarlyStopException(Exception):
    def __init__(self, best_iteration: int, best_score):
        super().__init__()
        self.best_iteration = best_iteration
        self.best_score = best_score


def log_evaluation(period: int = 1, show_stdv: bool = True) -> Callable:
    def _callback(env: CallbackEnv) -> None:
        if period > 0 and env.evaluation_result_list \
                and (env.iteration + 1) % period == 0:
            result = "\t".join(
                f"{name}'s {metric}: {value:g}"
                for name, metric, value, _ in env.evaluation_result_list)
            print(f"[{env.iteration + 1}]\t{result}")
    _callback.order = 10
    # Eval-cadence contract (docs/ITER_PACK.md): this callback only consumes
    # metrics on iterations where (it + 1) % eval_period == 0; the engine
    # may skip metric computation (and the host sync it costs) on the other
    # iterations, and the iteration-packed path aligns its auto pack size
    # to this period.  Callbacks without the attribute default to period 1;
    # period <= 0 (logging disabled) never consumes any metric.
    _callback.eval_period = period if period > 0 else 0
    return _callback


def record_evaluation(eval_result: Dict[str, Dict[str, List[float]]]) -> Callable:
    if not isinstance(eval_result, dict):
        raise TypeError("eval_result should be a dictionary")

    def _callback(env: CallbackEnv) -> None:
        for name, metric, value, _ in env.evaluation_result_list:
            eval_result.setdefault(name, collections.OrderedDict())
            eval_result[name].setdefault(metric, [])
            eval_result[name][metric].append(value)
    _callback.order = 20
    _callback.eval_period = 1   # records every round (cadence contract)
    return _callback


def reset_parameter(**kwargs: Any) -> Callable:
    def _callback(env: CallbackEnv) -> None:
        new_params = {}
        for key, value in kwargs.items():
            if isinstance(value, list):
                if len(value) != env.end_iteration - env.begin_iteration:
                    raise ValueError(
                        f"Length of list {key!r} has to be {env.end_iteration - env.begin_iteration}")
                new_params[key] = value[env.iteration - env.begin_iteration]
            elif callable(value):
                new_params[key] = value(env.iteration - env.begin_iteration)
            else:
                raise ValueError("Only list and callable values supported")
        if new_params:
            env.model.reset_parameter(new_params)
    _callback.before_iteration = True
    _callback.order = 10
    return _callback


def early_stopping(stopping_rounds: int, first_metric_only: bool = False,
                   verbose: bool = True, min_delta: float = 0.0) -> Callable:
    """reference ``_EarlyStoppingCallback`` (``callback.py:278``)."""
    best_score: List[float] = []
    best_iter: List[int] = []
    best_score_list: List[Any] = []
    cmp_op: List[Callable] = []
    enabled = [True]
    first_metric = [""]
    inited = [False]

    def _init(env: CallbackEnv) -> None:
        enabled[0] = bool(env.evaluation_result_list)
        if not enabled[0]:
            return
        best_score.clear(); best_iter.clear(); best_score_list.clear()
        cmp_op.clear()
        first_metric[0] = env.evaluation_result_list[0][1].split("@")[0]
        for _, metric, _, higher_better in env.evaluation_result_list:
            best_iter.append(0)
            best_score_list.append(None)
            if higher_better:
                best_score.append(float("-inf"))
                cmp_op.append(lambda new, best: new > best + min_delta)
            else:
                best_score.append(float("inf"))
                cmp_op.append(lambda new, best: new < best - min_delta)

    def _callback(env: CallbackEnv) -> None:
        # init at the run's first round, OR on this callback's first firing
        # — a resumed run (engine.train resume_from=) starts mid-stream
        # with begin_iteration still 0, so the first-firing arm covers it
        if env.iteration == env.begin_iteration or not inited[0]:
            inited[0] = True
            _init(env)
        if not enabled[0]:
            return
        for i, (name, metric, value, _) in enumerate(env.evaluation_result_list):
            if best_score_list[i] is None or cmp_op[i](value, best_score[i]):
                best_score[i] = value
                best_iter[i] = env.iteration
                best_score_list[i] = env.evaluation_result_list
            if first_metric_only and metric.split("@")[0] != first_metric[0]:
                continue
            if name == "training":
                continue
            if env.iteration - best_iter[i] >= stopping_rounds:
                if verbose:
                    print(f"Early stopping, best iteration is:\n"
                          f"[{best_iter[i] + 1}]")
                raise EarlyStopException(best_iter[i], best_score_list[i])
            if env.iteration == env.end_iteration - 1:
                if verbose:
                    print(f"Did not meet early stopping. Best iteration is:\n"
                          f"[{best_iter[i] + 1}]")
                raise EarlyStopException(best_iter[i], best_score_list[i])
    _callback.order = 30
    _callback.eval_period = 1   # the no-improvement counter ticks per round
    return _callback
