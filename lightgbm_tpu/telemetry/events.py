"""Structured JSONL event log (``tpu_telemetry_log=<path>``).

One line per event, append-only, schema-versioned::

    {"schema": 1, "kind": "train.iter", "ts": <monotonic_s>,
     "wall": <unix_s>, "pid": <pid>, ...event fields...}

``ts`` is ``time.monotonic()`` — the ordering/duration clock (immune to
wall-clock steps); ``wall`` is ``time.time()`` for humans correlating with
external logs.  Event kinds and their fields are the taxonomy table in
docs/OBSERVABILITY.md; ``tools/telemetry_report.py`` replays a log into a
per-iteration/per-phase triage table, and the same file feeds
``tools/health_report.py`` and ``tools/profile_iter.py --from-log``.

The sink is process-global (one training run configures it at start and
closes it at end — ``engine.train`` does both).  ``emit`` with no sink
still counts the event in the registry (``event.<kind>`` counters), so
``detail.telemetry`` blocks carry event counts even when nothing is being
written to disk.  Writes are lock-serialized; a full disk or revoked path
warns once and drops subsequent lines rather than failing training.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Optional

from ..utils.log import Log
from . import spans
from .registry import registry

SCHEMA_VERSION = 1


class JsonlSink:
    """Append-only JSONL writer for one telemetry log path."""

    def __init__(self, path: str):
        self.path = path
        self._lock = threading.Lock()
        parent = os.path.dirname(os.path.abspath(path))
        os.makedirs(parent, exist_ok=True)
        self._fh = open(path, "a", encoding="utf-8")
        self._write_failed = False

    def write(self, event: dict) -> None:
        line = json.dumps(event, default=str)
        with self._lock:
            if self._fh is None:
                return
            try:
                self._fh.write(line + "\n")
                self._fh.flush()
            except OSError as e:
                if not self._write_failed:
                    self._write_failed = True
                    Log.warning(f"telemetry: dropping events — write to "
                                f"{self.path} failed ({e})")

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                try:
                    self._fh.close()
                except OSError:
                    pass
                self._fh = None


_sink_lock = threading.Lock()
_sink: Optional[JsonlSink] = None


def configure_log(path: Optional[str]) -> Optional[JsonlSink]:
    """Open (or switch) the process sink; ``None``/"" closes it.  Returns
    the active sink — or ``None`` with a warning when the path cannot be
    opened (a pure observability knob must never abort training)."""
    global _sink
    with _sink_lock:
        if _sink is not None and (not path or _sink.path != path):
            _sink.close()
            _sink = None
        if path and _sink is None:
            try:
                _sink = JsonlSink(path)
            except OSError as e:
                Log.warning(f"telemetry: cannot open event log {path!r} "
                            f"({e}); events will not be recorded")
        return _sink


def active_sink() -> Optional[JsonlSink]:
    with _sink_lock:
        return _sink


def close_log() -> None:
    configure_log(None)


def emit(kind: str, **fields) -> None:
    """Emit one event: counted in the registry always (when telemetry is
    enabled), written to the JSONL sink when one is configured."""
    if not spans.enabled():
        return
    registry().counter(f"event.{kind}").inc()
    sink = active_sink()
    if sink is None:
        return
    event = {"schema": SCHEMA_VERSION, "kind": kind,
             "ts": round(time.monotonic(), 6),
             "wall": round(time.time(), 6), "pid": os.getpid()}
    event.update(fields)
    sink.write(event)
