"""Process-wide metrics registry: counters, gauges and histograms with
bounded reservoirs (docs/OBSERVABILITY.md).

One :class:`MetricsRegistry` instance per process (:func:`registry`), the
single sink every subsystem publishes into — training spans
(telemetry/spans.py), the resilience layer (health sentinel trips,
checkpoint save/restore durations, watchdog verdicts) and serving
(serve/metrics.py mirrors its per-predictor gauges here).  All host-side:
observing a metric is a lock + a dict write, never a device touch.

Thread-safety: the registry lock guards only the instrument tables
(two racing ``counter(name)`` calls share one instrument); each
instrument carries its OWN lock, so high-QPS serve observations never
serialize against training spans or health counters.
"""

from __future__ import annotations

import math
import re
import threading
from collections import deque
from typing import Dict, List, Optional, Sequence

import numpy as np

# ---------------------------------------------------------------- buckets
# Fixed log-spaced bucket ladder shared by every Histogram (ISSUE-14):
# 24 buckets per decade across [1e-7, 1e5) — sub-microsecond latencies up
# to ~28-hour durations land in a 288-int array, so percentiles cover the
# FULL observation history (not a trailing reservoir window) at a bounded
# resolution of one bucket ratio 10^(1/24) ~ 1.10 (estimates within ~5%
# of the true quantile).  Values <= 0 or below the floor clamp into the
# first bucket; values past the ceiling clamp into the last.
BUCKETS_PER_DECADE = 24
_BUCKET_LO_EXP = -7
_BUCKET_HI_EXP = 5
NUM_BUCKETS = (_BUCKET_HI_EXP - _BUCKET_LO_EXP) * BUCKETS_PER_DECADE


def bucket_index(v: float) -> int:
    """Bucket slot for one observation (clamped into the fixed ladder)."""
    if not v > 0.0:
        return 0
    i = int(math.floor((math.log10(v) - _BUCKET_LO_EXP)
                       * BUCKETS_PER_DECADE))
    return min(max(i, 0), NUM_BUCKETS - 1)


def bucket_value(i: int) -> float:
    """Representative (geometric-midpoint) value of bucket ``i``."""
    return 10.0 ** (_BUCKET_LO_EXP + (i + 0.5) / BUCKETS_PER_DECADE)


_LABEL_BAD = re.compile(r"[\"\\\n]")


def labeled_name(name: str, labels: Optional[Dict[str, str]]) -> str:
    """Canonical ``name{key="value",...}`` instrument key (Prometheus
    label syntax, keys sorted so one label set always maps to ONE
    instrument).  ``None``/empty labels return the bare name."""
    if not labels:
        return name
    inner = ",".join(
        f'{k}="{_LABEL_BAD.sub("_", str(labels[k]))}"'
        for k in sorted(labels))
    return f"{name}{{{inner}}}"


class Counter:
    """Monotonic count (requests served, events emitted, sentinel trips)."""

    def __init__(self, name: str, lock: threading.Lock):
        self.name = name
        self._lock = lock
        self._value = 0

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> int:
        with self._lock:
            return self._value


class Gauge:
    """Last-write-wins scalar (queue depth, watchdog latency, pack size)."""

    def __init__(self, name: str, lock: threading.Lock):
        self.name = name
        self._lock = lock
        self._value: Optional[float] = None

    def set(self, v) -> None:
        with self._lock:
            self._value = None if v is None else float(v)

    @property
    def value(self) -> Optional[float]:
        with self._lock:
            return self._value


class Histogram:
    """Duration/size distribution: exact count/sum/min/max, fixed
    log-spaced bucket counts covering the FULL observation history (the
    quantile source — a long-lived serving process's p99 is over every
    request it ever served, not the trailing window the old
    deque-reservoir scheme measured), plus a bounded reservoir (newest
    ``reservoir`` observations) kept for exemplars, so the telemetry
    footprint stays O(1) regardless of lifetime."""

    def __init__(self, name: str, lock: threading.Lock,
                 reservoir: int = 1024):
        self.name = name
        self._lock = lock
        self.count = 0
        self.sum = 0.0
        self._min: Optional[float] = None
        self._max: Optional[float] = None
        self._buckets = [0] * NUM_BUCKETS
        self._values = deque(maxlen=reservoir)

    def observe(self, v: float) -> None:
        v = float(v)
        with self._lock:
            self.count += 1
            self.sum += v
            self._buckets[bucket_index(v)] += 1
            if self._min is None or v < self._min:
                self._min = v
            if self._max is None or v > self._max:
                self._max = v
            self._values.append(v)

    def quantiles(self, qs: Sequence[float]) -> List[Optional[float]]:
        """Full-history quantile estimates from the bucket counts, each
        within one bucket ratio (~10%) of the exact value; estimates are
        clamped into the observed [min, max] so small samples never report
        a quantile outside the data.  ``None`` per entry when empty."""
        with self._lock:
            total = self.count
            buckets = list(self._buckets)
            vmin, vmax = self._min, self._max
        if total == 0:
            return [None for _ in qs]
        out: List[Optional[float]] = []
        for q in qs:
            rank = max(min(float(q), 1.0), 0.0) * total
            cum = 0
            est = bucket_value(NUM_BUCKETS - 1)
            for i, n in enumerate(buckets):
                cum += n
                if cum >= rank and n:
                    est = bucket_value(i)
                    break
            out.append(min(max(est, vmin), vmax))
        return out

    def reservoir_values(self) -> np.ndarray:
        """Newest raw observations (exemplar window, NOT the quantile
        source — quantiles come from the full-history buckets)."""
        with self._lock:
            return np.asarray(self._values, np.float64)

    def summary(self) -> Dict[str, Optional[float]]:
        with self._lock:
            count, total, vmax = self.count, self.sum, self._max
        out = {"count": count, "sum": total, "p50": None, "p99": None,
               "p999": None, "max": None}
        if count:
            out["p50"], out["p99"], out["p999"] = self.quantiles(
                (0.5, 0.99, 0.999))
            out["max"] = vmax
        return out


class MetricsRegistry:
    """Named instrument table.  ``counter``/``gauge``/``histogram`` are
    get-or-create (idempotent, shared instance per name).

    Labels (ISSUE-14): pass ``labels={"model": "tenant_a"}`` to get a
    DISTINCT instrument keyed ``name{model="tenant_a"}`` — the serve
    layer publishes per-tenant series this way so multi-Booster processes
    stop aliasing into one counter set; the Prometheus renderer emits the
    key verbatim as a labeled series."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: Dict[str, Counter] = {}
        self._gauges: Dict[str, Gauge] = {}
        self._histograms: Dict[str, Histogram] = {}

    def counter(self, name: str,
                labels: Optional[Dict[str, str]] = None) -> Counter:
        name = labeled_name(name, labels)
        with self._lock:
            c = self._counters.get(name)
            if c is None:
                c = self._counters[name] = Counter(name, threading.Lock())
            return c

    def gauge(self, name: str,
              labels: Optional[Dict[str, str]] = None) -> Gauge:
        name = labeled_name(name, labels)
        with self._lock:
            g = self._gauges.get(name)
            if g is None:
                g = self._gauges[name] = Gauge(name, threading.Lock())
            return g

    def histogram(self, name: str, reservoir: int = 1024,
                  labels: Optional[Dict[str, str]] = None) -> Histogram:
        name = labeled_name(name, labels)
        with self._lock:
            h = self._histograms.get(name)
            if h is None:
                h = self._histograms[name] = Histogram(
                    name, threading.Lock(), reservoir)
            return h

    def snapshot(self) -> Dict[str, Dict]:
        """One nested dict of every instrument's current value — the
        ``registry`` section of ``detail.telemetry`` in BENCH blobs."""
        with self._lock:
            counters = list(self._counters.items())
            gauges = list(self._gauges.items())
            hists = list(self._histograms.items())
        # instrument reads take each instrument's own lock, outside the
        # registry lock (no lock-order coupling)
        return {
            "counters": {n: c.value for n, c in counters},
            "gauges": {n: g.value for n, g in gauges},
            "histograms": {n: h.summary() for n, h in hists},
        }

    def reset(self) -> None:
        """Drop every instrument — TESTS ONLY.  A long-lived process keeps
        its counters for the life of the process (like any Prometheus
        target): holders of cached instrument objects (ServeMetrics
        mirrors) would keep publishing into detached instruments after a
        reset, invisible to later snapshots."""
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._histograms.clear()


_REGISTRY = MetricsRegistry()


def registry() -> MetricsRegistry:
    """THE process-wide registry (training, resilience and serving all
    publish here; scrapes and bench blobs read it)."""
    return _REGISTRY
