"""Process-wide metrics registry: counters, gauges and histograms with
bounded reservoirs (docs/OBSERVABILITY.md).

One :class:`MetricsRegistry` instance per process (:func:`registry`), the
single sink every subsystem publishes into — training spans
(telemetry/spans.py), the resilience layer (health sentinel trips,
checkpoint save/restore durations, watchdog verdicts) and serving
(serve/metrics.py mirrors its per-predictor gauges here).  All host-side:
observing a metric is a lock + a dict write, never a device touch.

Thread-safety: the registry lock guards only the instrument tables
(two racing ``counter(name)`` calls share one instrument); each
instrument carries its OWN lock, so high-QPS serve observations never
serialize against training spans or health counters.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Dict, Optional

import numpy as np


class Counter:
    """Monotonic count (requests served, events emitted, sentinel trips)."""

    def __init__(self, name: str, lock: threading.Lock):
        self.name = name
        self._lock = lock
        self._value = 0

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> int:
        with self._lock:
            return self._value


class Gauge:
    """Last-write-wins scalar (queue depth, watchdog latency, pack size)."""

    def __init__(self, name: str, lock: threading.Lock):
        self.name = name
        self._lock = lock
        self._value: Optional[float] = None

    def set(self, v) -> None:
        with self._lock:
            self._value = None if v is None else float(v)

    @property
    def value(self) -> Optional[float]:
        with self._lock:
            return self._value


class Histogram:
    """Duration/size distribution: exact count and sum plus a bounded
    reservoir (newest ``reservoir`` observations) for the quantiles — the
    same deque scheme ServeMetrics uses, so a long-lived process never
    grows its telemetry footprint."""

    def __init__(self, name: str, lock: threading.Lock,
                 reservoir: int = 1024):
        self.name = name
        self._lock = lock
        self.count = 0
        self.sum = 0.0
        self._values = deque(maxlen=reservoir)

    def observe(self, v: float) -> None:
        v = float(v)
        with self._lock:
            self.count += 1
            self.sum += v
            self._values.append(v)

    def summary(self) -> Dict[str, Optional[float]]:
        with self._lock:
            vals = np.asarray(self._values, np.float64)
            count, total = self.count, self.sum
        out = {"count": count, "sum": total, "p50": None, "p99": None,
               "max": None}
        if vals.size:
            out["p50"] = float(np.percentile(vals, 50))
            out["p99"] = float(np.percentile(vals, 99))
            out["max"] = float(vals.max())
        return out


class MetricsRegistry:
    """Named instrument table.  ``counter``/``gauge``/``histogram`` are
    get-or-create (idempotent, shared instance per name)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: Dict[str, Counter] = {}
        self._gauges: Dict[str, Gauge] = {}
        self._histograms: Dict[str, Histogram] = {}

    def counter(self, name: str) -> Counter:
        with self._lock:
            c = self._counters.get(name)
            if c is None:
                c = self._counters[name] = Counter(name, threading.Lock())
            return c

    def gauge(self, name: str) -> Gauge:
        with self._lock:
            g = self._gauges.get(name)
            if g is None:
                g = self._gauges[name] = Gauge(name, threading.Lock())
            return g

    def histogram(self, name: str, reservoir: int = 1024) -> Histogram:
        with self._lock:
            h = self._histograms.get(name)
            if h is None:
                h = self._histograms[name] = Histogram(
                    name, threading.Lock(), reservoir)
            return h

    def snapshot(self) -> Dict[str, Dict]:
        """One nested dict of every instrument's current value — the
        ``registry`` section of ``detail.telemetry`` in BENCH blobs."""
        with self._lock:
            counters = list(self._counters.items())
            gauges = list(self._gauges.items())
            hists = list(self._histograms.items())
        # instrument reads take each instrument's own lock, outside the
        # registry lock (no lock-order coupling)
        return {
            "counters": {n: c.value for n, c in counters},
            "gauges": {n: g.value for n, g in gauges},
            "histograms": {n: h.summary() for n, h in hists},
        }

    def reset(self) -> None:
        """Drop every instrument — TESTS ONLY.  A long-lived process keeps
        its counters for the life of the process (like any Prometheus
        target): holders of cached instrument objects (ServeMetrics
        mirrors) would keep publishing into detached instruments after a
        reset, invisible to later snapshots."""
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._histograms.clear()


_REGISTRY = MetricsRegistry()


def registry() -> MetricsRegistry:
    """THE process-wide registry (training, resilience and serving all
    publish here; scrapes and bench blobs read it)."""
    return _REGISTRY
