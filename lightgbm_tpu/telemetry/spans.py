"""Trace spans: ONE context manager that opens a ``jax.profiler.
TraceAnnotation`` region (so the span shows up in device profiler traces)
AND aggregates host wall time into the hierarchical timer + the process
registry (docs/OBSERVABILITY.md).

Span names are ``area/phase`` (``train/iter_dispatch``, ``grower/grow``,
``serve/predict``); nested spans join with ``/`` through a thread-local
stack, so a ``grow`` span opened inside ``train/iter_dispatch`` aggregates
as ``train/iter_dispatch/grow``.

HOST-SIDE ONLY, at dispatch boundaries: a span wraps the *launch* of a
compiled program (and any blocking fetch), never code inside a trace —
``tpu_telemetry=off`` therefore compiles bitwise-identical programs and
the dispatch census stays pinned (tests/test_telemetry.py).  Disabled
spans cost one flag read.
"""

from __future__ import annotations

import threading
import time
from typing import Dict

from ..utils.timer import Timer
from .registry import registry

# Process-wide arm switch (tpu_telemetry).  Set per-run by the engine /
# GBDT constructor from the config; raw Booster.update loops (bench rungs)
# keep whatever the last constructed booster asked for (default: on).
_enabled = True

# Dedicated span timer (not utils.timer.global_timer: the LGBM_TPU_TIMETAG
# summary stays the legacy FunctionTimer surface; span totals are read
# programmatically via span_totals / the bench telemetry block).
_span_timer = Timer()

_local = threading.local()


def set_enabled(on: bool) -> None:
    global _enabled
    _enabled = bool(on)


def enabled() -> bool:
    return _enabled


def _stack():
    st = getattr(_local, "stack", None)
    if st is None:
        st = _local.stack = []
    return st


class span:
    """``with span("train/grow"): ...`` — host timer + profiler
    annotation + registry histogram, one context manager.  Re-entrant and
    thread-safe (per-thread name stacks; the timer is lock-guarded)."""

    __slots__ = ("name", "_path", "_t0", "_trace")

    def __init__(self, name: str):
        self.name = name
        self._path = None
        self._t0 = 0.0
        self._trace = None

    def __enter__(self):
        if not _enabled:
            return self
        stack = _stack()
        self._path = (f"{stack[-1]}/{self.name}" if stack else self.name)
        stack.append(self._path)
        try:
            import jax.profiler
            self._trace = jax.profiler.TraceAnnotation(self._path)
            self._trace.__enter__()
        except Exception:  # noqa: BLE001 — profiler is garnish on the timer
            self._trace = None
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        if self._path is None:   # entered disabled
            return False
        dt = time.perf_counter() - self._t0
        if self._trace is not None:
            try:
                self._trace.__exit__(*exc)
            except Exception:  # noqa: BLE001 — a torn-down profiler must
                pass           # not break training or strand the stack
        stack = _stack()
        if stack and stack[-1] == self._path:
            stack.pop()
        _span_timer.add(self._path, dt)
        registry().histogram(f"span.{self._path}").observe(dt)
        self._path = None
        return False


def instrument(fn, name: str):
    """Wrap a compiled callable so every launch runs under ``span(name)``,
    delegating attribute access (``.lower``, ``.raw``, the grower's static
    capability facts) to the wrapped function — callers and the dispatch
    census see the same surface."""
    return _Instrumented(fn, name)


class _Instrumented:
    def __init__(self, fn, name: str):
        self._fn = fn
        self._span_name = name

    def __call__(self, *args, **kwargs):
        with span(self._span_name):
            return self._fn(*args, **kwargs)

    def __getattr__(self, item):
        return getattr(self._fn, item)


def span_totals() -> Dict[str, Dict[str, float]]:
    """``{span_path: {"seconds": s, "count": n}}`` aggregated since process
    start (or the last :func:`reset_spans`)."""
    return {name: {"seconds": secs, "count": cnt}
            for name, secs, cnt in _span_timer.snapshot()}


def reset_spans() -> None:
    _span_timer.reset()
