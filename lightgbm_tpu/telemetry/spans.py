"""Trace spans: ONE context manager that opens a ``jax.profiler.
TraceAnnotation`` region (so the span shows up in device profiler traces)
AND aggregates host wall time into the hierarchical timer + the process
registry (docs/OBSERVABILITY.md).

Span names are ``area/phase`` (``train/iter_dispatch``, ``grower/grow``,
``serve/predict``); nested spans join with ``/`` through a thread-local
stack, so a ``grow`` span opened inside ``train/iter_dispatch`` aggregates
as ``train/iter_dispatch/grow``.

HOST-SIDE ONLY, at dispatch boundaries: a span wraps the *launch* of a
compiled program (and any blocking fetch), never code inside a trace —
``tpu_telemetry=off`` therefore compiles bitwise-identical programs and
the dispatch census stays pinned (tests/test_telemetry.py).  Disabled
spans cost one flag read.
"""

from __future__ import annotations

import threading
import time
from typing import Dict

from ..utils.timer import Timer
from .registry import registry

# Process-wide arm switch (tpu_telemetry).  Set per-run by the engine /
# GBDT constructor from the config; raw Booster.update loops (bench rungs)
# keep whatever the last constructed booster asked for (default: on).
_enabled = True

# Dedicated span timer (not utils.timer.global_timer: the LGBM_TPU_TIMETAG
# summary stays the legacy FunctionTimer surface; span totals are read
# programmatically via span_totals / the bench telemetry block).
_span_timer = Timer()

_local = threading.local()


def set_enabled(on: bool) -> None:
    global _enabled
    _enabled = bool(on)


def enabled() -> bool:
    return _enabled


def _stack():
    st = getattr(_local, "stack", None)
    if st is None:
        st = _local.stack = []
    return st


class span:
    """``with span("train/grow"): ...`` — host timer + profiler
    annotation + registry histogram, one context manager.  Re-entrant and
    thread-safe (per-thread name stacks; the timer is lock-guarded).

    ``track_memory=True`` additionally records the span's device-memory
    delta + watermark (telemetry/memory.py) when
    ``tpu_telemetry_memory`` is armed — a no-op (one mode check) when it
    is ``off``, host-side observation either way."""

    __slots__ = ("name", "_path", "_t0", "_trace", "_track_memory",
                 "_mem_token")

    def __init__(self, name: str, track_memory: bool = False):
        self.name = name
        self._path = None
        self._t0 = 0.0
        self._trace = None
        self._track_memory = track_memory
        self._mem_token = None

    def __enter__(self):
        if not _enabled:
            return self
        stack = _stack()
        self._path = (f"{stack[-1]}/{self.name}" if stack else self.name)
        stack.append(self._path)
        try:
            import jax.profiler
            self._trace = jax.profiler.TraceAnnotation(self._path)
            self._trace.__enter__()
        except Exception:  # noqa: BLE001 — profiler is garnish on the timer
            self._trace = None
        if self._track_memory:
            from . import memory
            self._mem_token = memory.span_begin()
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        if self._path is None:   # entered disabled
            return False
        dt = time.perf_counter() - self._t0
        if self._trace is not None:
            try:
                self._trace.__exit__(*exc)
            except Exception:  # noqa: BLE001 — a torn-down profiler must
                pass           # not break training or strand the stack
        stack = _stack()
        if stack and stack[-1] == self._path:
            stack.pop()
        if self._mem_token is not None:
            from . import memory
            try:
                memory.span_end(self._path, self._mem_token)
            except Exception:  # noqa: BLE001 — accounting must never
                pass           # break training or mask the real exception
            self._mem_token = None
        _span_timer.add(self._path, dt)
        registry().histogram(f"span.{self._path}").observe(dt)
        self._path = None
        return False


def instrument(fn, name: str, track_memory: bool = False):
    """Wrap a compiled callable so every launch runs under ``span(name)``,
    delegating attribute access (``.lower``, ``.raw``, the grower's static
    capability facts) to the wrapped function — callers and the dispatch
    census see the same surface.  The wrapper is ALSO the compile seam:
    a call that grows the jit executable cache emits a ``compile.end``
    event (telemetry/memory.py note_compile) with the call's wall seconds
    — a first call to a new shape is dominated by the XLA compile."""
    return _Instrumented(fn, name, track_memory=track_memory)


def watch_compiles(fn, name: str):
    """Compile telemetry WITHOUT a span: for jitted programs whose
    launches already run under a caller-side span (the fused iteration
    under ``train/fused_iter``, the pack program under
    ``train/pack_dispatch``) — wrapping them in ``instrument`` would
    double-count the span."""
    return _Instrumented(fn, name, use_span=False)


def _compile_cache_size(fn):
    """jit executable-cache size, or None where jax doesn't expose it."""
    try:
        return int(fn._cache_size())
    except Exception:  # noqa: BLE001 — older jax / non-jit callables
        return None


class _Instrumented:
    def __init__(self, fn, name: str, track_memory: bool = False,
                 use_span: bool = True):
        self._fn = fn
        self._span_name = name
        self._track_memory = track_memory
        self._use_span = use_span

    def __call__(self, *args, **kwargs):
        if not _enabled:
            return self._fn(*args, **kwargs)
        n0 = _compile_cache_size(self._fn)
        t0 = time.perf_counter()
        if self._use_span:
            with span(self._span_name, track_memory=self._track_memory):
                out = self._fn(*args, **kwargs)
        else:
            out = self._fn(*args, **kwargs)
        if n0 is not None and _compile_cache_size(self._fn) > n0:
            from . import memory
            memory.note_compile(self._span_name,
                                time.perf_counter() - t0)
        return out

    def __getattr__(self, item):
        return getattr(self._fn, item)


def span_totals() -> Dict[str, Dict[str, float]]:
    """``{span_path: {"seconds": s, "count": n}}`` aggregated since process
    start (or the last :func:`reset_spans`)."""
    return {name: {"seconds": secs, "count": cnt}
            for name, secs, cnt in _span_timer.snapshot()}


def reset_spans() -> None:
    _span_timer.reset()
