"""lightgbm_tpu.telemetry — unified observability layer.

One process-wide home for the three signal families every subsystem
publishes (docs/OBSERVABILITY.md):

- **Metrics registry** (:mod:`.registry`): counters / gauges / histograms
  with bounded reservoirs.  Training, resilience (health sentinel,
  watchdog, checkpoints) and serving all publish here;
  :func:`render_prometheus` turns any snapshot into a scrape answer.
- **Spans** (:mod:`.spans`): ``with span("train/grow")`` wraps
  ``jax.profiler.TraceAnnotation`` + the lock-guarded hierarchical host
  timer behind one context manager.  Host-side, at dispatch boundaries
  only — ``tpu_telemetry=off`` compiles bitwise-identical programs.
- **JSONL events** (:mod:`.events`): ``tpu_telemetry_log=<path>`` streams
  schema-versioned, monotonic-clocked events (``train.iter`` per committed
  round with dispatch-wait vs host-bookkeeping wall split, checkpoint
  durations, health verdicts, serve snapshots) that
  ``tools/telemetry_report.py`` replays into a triage table.

Knobs: ``tpu_telemetry=on|off`` (off is bitwise-inert),
``tpu_telemetry_log=<path>``, ``tpu_profile_iters=N`` (+
``tpu_profile_dir``) for a first-N-iterations ``jax.profiler`` trace.
"""

from __future__ import annotations

from typing import Dict

from .events import (SCHEMA_VERSION, JsonlSink, active_sink, close_log,
                     configure_log, emit)
from .memory import (MEMORY_MODES, MemoryTracker, arm_memory_from_config,
                     device_memory_stats, host_peak_rss_mb,
                     live_buffer_census, memory_analysis_summary,
                     memory_block, memory_mode, note_compile,
                     set_memory_mode)
from .prometheus import render_prometheus
from .registry import (Counter, Gauge, Histogram, MetricsRegistry, registry)
from .spans import (enabled, instrument, reset_spans, set_enabled, span,
                    span_totals, watch_compiles)

__all__ = [
    "MEMORY_MODES", "SCHEMA_VERSION", "Counter", "Gauge", "Histogram",
    "JsonlSink", "MemoryTracker", "MetricsRegistry", "TrainTelemetry",
    "active_sink", "arm_from_config", "arm_memory_from_config",
    "close_log", "configure_log", "device_memory_stats", "emit", "enabled",
    "host_peak_rss_mb", "instrument", "live_buffer_census",
    "memory_analysis_summary", "memory_block", "memory_mode",
    "note_compile", "registry", "render_prometheus", "reset_spans",
    "set_enabled", "set_memory_mode", "span", "span_totals",
    "telemetry_block", "train_session", "watch_compiles",
]


def arm_from_config(cfg) -> bool:
    """Set the process-wide enable flag from a resolved Config
    (``tpu_telemetry``).  Called by every GBDT construction so raw
    ``Booster.update`` loops honor the knob too; returns the armed state."""
    on = getattr(cfg, "tpu_telemetry", "on") != "off"
    set_enabled(on)
    return on


def telemetry_block() -> Dict:
    """The ``detail.telemetry`` block every BENCH blob (primary + rungs)
    carries: schema version, armed state, per-kind event counts, span
    totals and the registry snapshot — the whole observability state of
    the process in one JSON-safe dict."""
    snap = registry().snapshot()
    events = {name[len("event."):]: count
              for name, count in snap["counters"].items()
              if name.startswith("event.")}
    return {
        "schema": SCHEMA_VERSION,
        "enabled": enabled(),
        "events": events,
        "spans": span_totals(),
        "registry": snap,
    }


class TrainTelemetry:
    """Per-``engine.train`` telemetry session: arms the enable flag and the
    JSONL sink from the config, tracks span deltas, and closes the sink it
    opened on :meth:`close` (the leak the conftest guard warns about)."""

    def __init__(self, cfg):
        self.enabled = arm_from_config(cfg)
        # Device-memory accounting mode (telemetry/memory.py): armed per
        # run from tpu_telemetry_memory, exactly like the master switch.
        self.memory_mode = arm_memory_from_config(cfg)
        self.log_path = getattr(cfg, "tpu_telemetry_log", "") or None
        self.profile_iters = int(getattr(cfg, "tpu_profile_iters", 0) or 0)
        self.profile_dir = getattr(cfg, "tpu_profile_dir", "") or (
            f"{self.log_path}.trace" if self.log_path
            else "/tmp/lightgbm_tpu_profile")
        self._opened_sink = False
        if self.enabled and self.log_path:
            configure_log(self.log_path)
            self._opened_sink = True
        self._span_base = {n: d["seconds"]
                          for n, d in span_totals().items()}
        self._profiling = False

    # ------------------------------------------------------------ events
    def emit(self, kind: str, **fields) -> None:
        if self.enabled:
            emit(kind, **fields)

    def span_delta(self) -> Dict[str, float]:
        """Per-span seconds accumulated since this session started."""
        out = {}
        for name, d in span_totals().items():
            dt = d["seconds"] - self._span_base.get(name, 0.0)
            if dt > 0:
                out[name] = round(dt, 6)
        return out

    # --------------------------------------------------------- profiling
    def maybe_start_profile(self) -> None:
        """Arm the ``jax.profiler`` trace for the first
        ``tpu_profile_iters`` committed rounds (ROADMAP 3: a live-TPU
        round lands with Mosaic kernel traces in hand)."""
        if not self.enabled or self.profile_iters <= 0 or self._profiling:
            return
        try:
            import jax
            jax.profiler.start_trace(self.profile_dir)
            self._profiling = True
            self.emit("profile.start", trace_dir=self.profile_dir,
                      iters=self.profile_iters)
        except Exception as e:  # noqa: BLE001 — profiling is best-effort
            from ..utils.log import Log
            Log.warning(f"telemetry: jax.profiler trace failed to start "
                        f"({e}); training continues unprofiled")
            self.profile_iters = 0

    def maybe_stop_profile(self, committed_rounds: int) -> None:
        if not self._profiling or committed_rounds < self.profile_iters:
            return
        self._stop_profile()

    def _stop_profile(self) -> None:
        if not self._profiling:
            return
        self._profiling = False
        try:
            import jax
            jax.profiler.stop_trace()
            self.emit("profile.stop", trace_dir=self.profile_dir)
            from ..utils.log import Log
            Log.info(f"telemetry: profiler trace written to "
                     f"{self.profile_dir} (tensorboard --logdir "
                     f"{self.profile_dir})")
        except Exception:  # noqa: BLE001 — stop must never fail training
            pass

    # ------------------------------------------------------------- close
    def close(self) -> None:
        self._stop_profile()
        if self._opened_sink:
            close_log()
            self._opened_sink = False


def train_session(cfg) -> TrainTelemetry:
    return TrainTelemetry(cfg)
