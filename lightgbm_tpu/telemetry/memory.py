"""Device-memory and compile telemetry (``tpu_telemetry_memory``,
docs/OBSERVABILITY.md memory section).

Three signal families, all publishing through the PR-9 registry/event
sink so one scrape (or one JSONL artifact) answers "where did the bytes
and compiles go":

- **Device-memory accounting** — :func:`device_memory_stats` snapshots
  ``device.memory_stats()`` (``bytes_in_use`` / ``peak_bytes_in_use``;
  gracefully ``None`` on backends that do not account, e.g. CPU) and
  :func:`live_buffer_census` groups ``jax.live_arrays()`` by
  (shape, dtype) with byte totals.  Any telemetry span opened with
  ``track_memory=True`` records its HBM delta + watermark into
  ``memory.*`` registry gauges and a ``memory.watermark`` JSONL event.
- **Host-side RSS** — :class:`MemoryTracker` owns the peak-RSS watermark
  (``VmHWM`` with a ``clear_refs`` reset where /proc allows, else
  ``ru_maxrss``); the engine publishes it as the
  ``memory.host_peak_rss_mb`` gauge.
- **Compile telemetry** — :func:`note_compile` (driven by the
  ``instrument()``/``watch_compiles()`` seam in spans.py) emits one
  ``compile.end`` event per XLA compile (program label, compile wall
  seconds, plus the ``compiled.memory_analysis()`` byte summary where the
  caller has the AOT object) and bumps the ``compile.count`` counter /
  ``compile.seconds`` histogram.

Arming: ``tpu_telemetry_memory=off|watermark|census``; ``off`` (the
default) is bitwise-inert — memory accounting is host-side observation at
span boundaries, never traced into a device program, so the lowered-HLO
equality pin from PR 9 extends to this knob
(tests/test_memory_telemetry.py).  ``watermark`` snapshots device memory
stats per tracked span; ``census`` additionally walks ``jax.live_arrays``
per tracked span — O(live buffers) host work, cheap next to a dispatch
but not free (the cost caveat in docs/OBSERVABILITY.md).  Compile
telemetry rides the master ``tpu_telemetry`` switch, not this knob: a
compile is a rare, expensive event worth counting whenever telemetry is
on at all.
"""

from __future__ import annotations

import sys
from typing import Any, Dict, Optional

try:
    import resource
except ImportError:          # Windows: no resource module — the VmHWM
    resource = None          # path is absent there too; report 0.0

from .registry import registry

MEMORY_MODES = ("off", "watermark", "census")

# How many (shape, dtype) groups a census keeps, and how many of those a
# memory.watermark EVENT carries (events are per-span — the log must not
# grow by a full census table per dispatch).
CENSUS_TOP_GROUPS = 12
EVENT_TOP_GROUPS = 4

_mode = "off"


def set_memory_mode(mode: str) -> str:
    """Set the process-wide accounting mode; returns the armed mode."""
    global _mode
    if mode not in MEMORY_MODES:
        raise ValueError(
            f"tpu_telemetry_memory={mode!r}: expected one of "
            f"{', '.join(MEMORY_MODES)}")
    _mode = mode
    return _mode


def memory_mode() -> str:
    return _mode


def arm_memory_from_config(cfg) -> str:
    """Arm the accounting mode from a resolved Config
    (``tpu_telemetry_memory``); engine.train calls this for every run."""
    return set_memory_mode(
        getattr(cfg, "tpu_telemetry_memory", "off") or "off")


def tracking_enabled() -> bool:
    """Memory accounting is live: mode is not ``off`` AND the master
    telemetry switch (``tpu_telemetry``) is on."""
    if _mode == "off":
        return False
    from . import spans
    return spans.enabled()


# ------------------------------------------------------------ device side
def device_memory_stats(device=None) -> Optional[Dict[str, int]]:
    """``device.memory_stats()`` snapshot (``bytes_in_use`` always,
    ``peak_bytes_in_use``/``bytes_limit`` where the allocator reports
    them) — or ``None``, gracefully, on backends without memory
    accounting (CPU jax returns None) or before jax is importable."""
    try:
        if device is None:
            import jax
            device = jax.devices()[0]
        stats = device.memory_stats()
    except Exception:  # noqa: BLE001 — accounting must never raise
        return None
    if not stats or "bytes_in_use" not in stats:
        return None
    out = {"bytes_in_use": int(stats["bytes_in_use"])}
    for key in ("peak_bytes_in_use", "bytes_limit", "largest_alloc_size"):
        if key in stats:
            out[key] = int(stats[key])
    return out


def live_buffer_census(arrays=None, top: int = CENSUS_TOP_GROUPS) -> Dict:
    """Group live device arrays by (shape, dtype) with byte totals.

    ``arrays`` defaults to ``jax.live_arrays()`` — the process-wide live
    set (pass an explicit list to census a known working set, as the
    tests do).  Returns ``{"total_bytes", "total_arrays",
    "distinct_shapes", "groups": [{shape, dtype, count, bytes}, ...
    largest first, top N], "truncated"}``."""
    if arrays is None:
        try:
            import jax
            arrays = jax.live_arrays()
        except Exception:  # noqa: BLE001 — census is observation only
            arrays = []
    groups: Dict[tuple, Dict[str, Any]] = {}
    total = 0
    count = 0
    for a in arrays:
        try:
            shape = tuple(int(d) for d in a.shape)
            dtype = str(a.dtype)
            nbytes = int(a.nbytes)
        except Exception:  # noqa: BLE001 — deleted/donated buffers raise
            continue
        count += 1
        total += nbytes
        g = groups.get((shape, dtype))
        if g is None:
            g = groups[(shape, dtype)] = {
                "shape": list(shape), "dtype": dtype, "count": 0, "bytes": 0}
        g["count"] += 1
        g["bytes"] += nbytes
    ordered = sorted(groups.values(), key=lambda g: (-g["bytes"],
                                                    g["dtype"],
                                                    g["shape"]))
    return {
        "total_bytes": total,
        "total_arrays": count,
        "distinct_shapes": len(ordered),
        "groups": ordered[:top] if top else [],
        "truncated": max(len(ordered) - top, 0) if top else len(ordered),
    }


# -------------------------------------------------------------- host side
class MemoryTracker:
    """Host + device memory snapshotter.

    The host half owns the peak-RSS watermark the sparse-ingestion bound
    test asserts on (tests/test_inputs.py): :meth:`reset_host_peak`
    resets the kernel's VmHWM watermark (``/proc/self/clear_refs`` "5")
    so a subsequent :meth:`host_peak_rss_mb` reads only what happened
    AFTER the reset point; where /proc is unavailable the fallback is
    ``ru_maxrss`` (a lifetime peak — deltas across it still catch any
    allocation pushing past the prior high-water mark)."""

    @staticmethod
    def reset_host_peak() -> bool:
        """Reset the kernel peak-RSS watermark; returns True when VmHWM
        tracking is live (clear_refs written), False on the ru_maxrss
        fallback."""
        try:
            with open("/proc/self/clear_refs", "w") as fh:
                fh.write("5")
            return True
        except OSError:
            return False

    @staticmethod
    def host_peak_rss_mb(use_hwm: bool = True) -> float:
        """Peak resident-set MB: ``VmHWM`` (honors :meth:`reset_host_peak`)
        when readable and ``use_hwm``, else ``ru_maxrss``."""
        if use_hwm:
            try:
                with open("/proc/self/status") as fh:
                    for line in fh:
                        if line.startswith("VmHWM:"):
                            return int(line.split()[1]) / 1024.0
            except OSError:
                pass
        if resource is None:
            return 0.0
        peak = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
        # ru_maxrss unit is kilobytes on Linux but BYTES on Darwin
        return peak / (2**20 if sys.platform == "darwin" else 1024.0)

    def __init__(self, device=None):
        self._device = device

    def device_stats(self) -> Optional[Dict[str, int]]:
        return device_memory_stats(self._device)

    def census(self, top: int = CENSUS_TOP_GROUPS) -> Dict:
        return live_buffer_census(top=top)

    def publish(self) -> Dict:
        """One combined snapshot, pushed into the ``memory.*`` gauges."""
        reg = registry()
        stats = self.device_stats()
        if stats is not None:
            reg.gauge("memory.bytes_in_use").set(stats["bytes_in_use"])
            if "peak_bytes_in_use" in stats:
                reg.gauge("memory.peak_bytes").set(
                    stats["peak_bytes_in_use"])
        rss = self.host_peak_rss_mb()
        reg.gauge("memory.host_peak_rss_mb").set(rss)
        return {"device": stats, "host_peak_rss_mb": rss}


def host_peak_rss_mb() -> float:
    """Module-level convenience: read the host watermark AND publish the
    ``memory.host_peak_rss_mb`` gauge (the engine's train.end hook)."""
    v = MemoryTracker.host_peak_rss_mb()
    registry().gauge("memory.host_peak_rss_mb").set(v)
    return v


# ------------------------------------------------------------- span hooks
def _live_total_bytes() -> int:
    """Just the live-array byte total — the span-entry baseline needs no
    shape/dtype grouping, so this costs one walk, not a census build."""
    try:
        import jax
        arrays = jax.live_arrays()
    except Exception:  # noqa: BLE001 — observation only
        return 0
    total = 0
    for a in arrays:
        try:
            total += int(a.nbytes)
        except Exception:  # noqa: BLE001 — deleted/donated buffers raise
            pass
    return total


def span_begin():
    """Token for a ``track_memory=True`` span; ``None`` when accounting is
    disarmed (the common case — one mode check)."""
    if not tracking_enabled():
        return None
    stats = device_memory_stats()
    live = _live_total_bytes() if _mode == "census" else None
    return (None if stats is None else stats["bytes_in_use"], live)


def span_end(path: str, token) -> None:
    """Close a tracked span: HBM delta + watermark into ``memory.*``
    gauges and one ``memory.watermark`` JSONL event.  Host-side
    observation only — never touches a compiled program."""
    if token is None or not tracking_enabled():
        return
    base_dev, base_live = token
    reg = registry()
    fields: Dict[str, Any] = {"span": path}
    stats = device_memory_stats()
    if stats is not None:
        fields["bytes_in_use"] = stats["bytes_in_use"]
        fields["peak_bytes"] = stats.get("peak_bytes_in_use")
        if base_dev is not None:
            fields["delta_bytes"] = stats["bytes_in_use"] - base_dev
        reg.gauge("memory.bytes_in_use").set(stats["bytes_in_use"])
        if stats.get("peak_bytes_in_use") is not None:
            reg.gauge("memory.peak_bytes").set(stats["peak_bytes_in_use"])
    else:
        # graceful-None contract: the event still lands (a CPU run's log
        # shows WHICH spans were tracked), just with no device numbers
        fields["bytes_in_use"] = None
        fields["peak_bytes"] = None
    if _mode == "census":
        census = live_buffer_census()
        fields["live_bytes"] = census["total_bytes"]
        fields["live_arrays"] = census["total_arrays"]
        if base_live is not None:
            fields["live_delta_bytes"] = census["total_bytes"] - base_live
        fields["census"] = census["groups"][:EVENT_TOP_GROUPS]
        reg.gauge("memory.live_bytes").set(census["total_bytes"])
    rss = MemoryTracker.host_peak_rss_mb()
    fields["host_peak_rss_mb"] = round(rss, 1)
    reg.gauge("memory.host_peak_rss_mb").set(rss)
    from . import events
    events.emit("memory.watermark", **fields)


# -------------------------------------------------------- compile telemetry
def memory_analysis_summary(compiled) -> Optional[Dict[str, int]]:
    """Byte summary from ``compiled.memory_analysis()`` (XLA
    CompiledMemoryStats): temp / generated-code / argument / output /
    donated-alias sizes.  ``None`` where the backend has no analysis."""
    try:
        ma = compiled.memory_analysis()
    except Exception:  # noqa: BLE001 — optional on some backends
        return None
    if ma is None:
        return None
    if isinstance(ma, list):
        if not ma:
            return None
        ma = ma[0]
    out = {}
    for key in ("temp_size_in_bytes", "argument_size_in_bytes",
                "output_size_in_bytes", "alias_size_in_bytes",
                "generated_code_size_in_bytes"):
        v = getattr(ma, key, None)
        if v is not None:
            out[key] = int(v)
    return out or None


def note_compile(label: str, seconds: float, compiled=None) -> None:
    """Record one XLA compile: bump ``compile.count``, observe
    ``compile.seconds``, emit a ``compile.end`` event (with the
    memory-analysis byte summary when the caller holds the AOT compiled
    object — the jit seam only knows the wall time)."""
    reg = registry()
    reg.counter("compile.count").inc()
    reg.histogram("compile.seconds").observe(seconds)
    fields: Dict[str, Any] = {"label": label, "seconds": round(seconds, 6)}
    if compiled is not None:
        summary = memory_analysis_summary(compiled)
        if summary:
            fields["memory_analysis"] = summary
    from . import events
    events.emit("compile.end", **fields)


# ----------------------------------------------------------- bench block
def memory_block() -> Dict:
    """The ``detail.memory`` block every BENCH blob (primary + rungs)
    carries: device watermark (None on CPU), the live-buffer census,
    compile count/seconds so far, and the host peak RSS.  bench.py adds
    the per-program ``memory_analysis`` byte summary beside it."""
    reg = registry()
    compile_hist = reg.histogram("compile.seconds")
    return {
        "mode": _mode,
        "device": device_memory_stats(),
        "live_buffers": live_buffer_census(),
        "compile": {
            "count": reg.counter("compile.count").value,
            "seconds": round(compile_hist.sum, 6),
        },
        "host_peak_rss_mb": round(MemoryTracker.host_peak_rss_mb(), 1),
    }
