"""Prometheus text-exposition rendering (docs/OBSERVABILITY.md scrape
section): flatten a metrics dict (``ServeMetrics.snapshot()`` or the
process registry snapshot) into ``text/plain; version=0.0.4`` lines a
scraper ingests directly — a serving process answers a scrape from ONE
call (``ServeMetrics.render_prometheus``).

Schema stability: every key renders every scrape.  ``None`` values (no
observations yet, or ``snapshot(plan=None)``'s plan-less counters) render
as ``NaN`` — a gauge that vanishes between scrapes breaks rate() queries,
a NaN one does not.  Nested dicts flatten with ``_`` (``plan_cache.hits``
-> ``<prefix>_plan_cache_hits``).

Labels (ISSUE-14): an instrument key carrying a ``{k="v"}`` suffix
(``telemetry.registry.labeled_name``) renders as a labeled series —
``serve.requests{model="a"}`` becomes
``lgbm_tpu_serve_requests{model="a"}`` — with ONE ``# TYPE`` line per
metric family; the ``labels=`` argument stamps a label set onto every
series of a document (how a per-tenant ``ServeMetrics`` renders its whole
snapshot as that tenant's series).
"""

from __future__ import annotations

import re
from typing import Dict, Optional

# snapshot keys that are monotonic counts (everything else is a gauge)
_COUNTER_KEYS = frozenset({
    "requests", "rows", "batches", "padded_rows", "shed", "deadline_misses",
    "device_faults", "host_fallbacks", "nan_scores", "compiles", "hits",
    "misses", "builds", "evictions", "plan_swaps", "model_swaps",
    # SLO violation-attribution leaves (snapshot["slo"]["violations"])
    "latency", "deadline", "fault",
})

_NAME_OK = re.compile(r"[^a-zA-Z0-9_]")


def _metric_name(prefix: str, *parts: str) -> str:
    name = "_".join([prefix] + [p for p in parts if p])
    return _NAME_OK.sub("_", name)


def _flatten(d: Dict, path=()) -> list:
    out = []
    for key, val in d.items():
        if isinstance(val, dict):
            out.extend(_flatten(val, path + (str(key),)))
        else:
            out.append((path + (str(key),), val))
    return out


def _merge_labels(inner: str, extra: Optional[Dict[str, str]]) -> str:
    """Combine a series' own ``k="v"`` label body with document-level
    labels; a series' own labels win on key clash (no duplicate keys —
    Prometheus rejects them)."""
    if not extra:
        return inner
    inner_keys = {part.partition("=")[0].strip()
                  for part in inner.split(",") if part}
    parts = [f'{k}="{str(v)}"' for k, v in sorted(extra.items())
             if k not in inner_keys]
    if inner:
        parts.append(inner)
    return ",".join(parts)


def render_prometheus(snapshot: Dict, prefix: str = "lgbm_tpu_serve",
                      labels: Optional[Dict[str, str]] = None) -> str:
    """One exposition document from a flat-or-nested snapshot dict.
    Non-numeric values (strings, lists) are skipped; ``None`` renders as
    ``NaN`` so the metric set is identical every scrape.  ``labels``
    stamps every series with the given label set."""
    lines = []
    typed = set()
    for path, val in _flatten(snapshot):
        if isinstance(val, bool):
            val = int(val)
        if val is not None and not isinstance(val, (int, float)):
            continue
        # a labeled instrument key ("bytes{model=\"a\"}") splits into the
        # metric-family name and the label body; only the name sanitizes
        leaf, _, label_part = path[-1].partition("{")
        name = _metric_name(prefix, *path[:-1], leaf)
        label_body = _merge_labels(label_part.rstrip("}"), labels)
        series = f"{name}{{{label_body}}}" if label_body else name
        # A registry snapshot declares its sections ("counters" holds only
        # monotonic counts); flat snapshots (ServeMetrics) type by leaf key.
        if path[0] == "counters":
            mtype = "counter"
        elif path[0] in ("gauges", "histograms"):
            mtype = "gauge"
        else:
            mtype = "counter" if leaf in _COUNTER_KEYS else "gauge"
        if name not in typed:
            typed.add(name)
            lines.append(f"# TYPE {name} {mtype}")
        lines.append(f"{series} {'NaN' if val is None else repr(float(val))}")
    return "\n".join(lines) + "\n"
