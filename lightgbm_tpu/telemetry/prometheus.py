"""Prometheus text-exposition rendering (docs/OBSERVABILITY.md scrape
section): flatten a metrics dict (``ServeMetrics.snapshot()`` or the
process registry snapshot) into ``text/plain; version=0.0.4`` lines a
scraper ingests directly — a serving process answers a scrape from ONE
call (``ServeMetrics.render_prometheus``).

Schema stability: every key renders every scrape.  ``None`` values (no
observations yet, or ``snapshot(plan=None)``'s plan-less counters) render
as ``NaN`` — a gauge that vanishes between scrapes breaks rate() queries,
a NaN one does not.  Nested dicts flatten with ``_`` (``plan_cache.hits``
-> ``<prefix>_plan_cache_hits``).
"""

from __future__ import annotations

import re
from typing import Dict

# snapshot keys that are monotonic counts (everything else is a gauge)
_COUNTER_KEYS = frozenset({
    "requests", "rows", "batches", "padded_rows", "shed", "deadline_misses",
    "device_faults", "host_fallbacks", "nan_scores", "compiles", "hits",
    "misses", "builds", "evictions",
})

_NAME_OK = re.compile(r"[^a-zA-Z0-9_]")


def _metric_name(prefix: str, *parts: str) -> str:
    name = "_".join([prefix] + [p for p in parts if p])
    return _NAME_OK.sub("_", name)


def _flatten(d: Dict, path=()) -> list:
    out = []
    for key, val in d.items():
        if isinstance(val, dict):
            out.extend(_flatten(val, path + (str(key),)))
        else:
            out.append((path + (str(key),), val))
    return out


def render_prometheus(snapshot: Dict, prefix: str = "lgbm_tpu_serve") -> str:
    """One exposition document from a flat-or-nested snapshot dict.
    Non-numeric values (strings, lists) are skipped; ``None`` renders as
    ``NaN`` so the metric set is identical every scrape."""
    lines = []
    for path, val in _flatten(snapshot):
        if isinstance(val, bool):
            val = int(val)
        if val is not None and not isinstance(val, (int, float)):
            continue
        name = _metric_name(prefix, *path)
        # A registry snapshot declares its sections ("counters" holds only
        # monotonic counts); flat snapshots (ServeMetrics) type by leaf key.
        if path[0] == "counters":
            mtype = "counter"
        elif path[0] in ("gauges", "histograms"):
            mtype = "gauge"
        else:
            mtype = "counter" if path[-1] in _COUNTER_KEYS else "gauge"
        lines.append(f"# TYPE {name} {mtype}")
        lines.append(f"{name} {'NaN' if val is None else repr(float(val))}")
    return "\n".join(lines) + "\n"
