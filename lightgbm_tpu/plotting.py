"""Plotting utilities.

Reference: ``python-package/lightgbm/plotting.py`` (840 LoC) —
``plot_importance``, ``plot_split_value_histogram``, ``plot_metric``,
``plot_tree``, ``create_tree_digraph``.  The public signatures (argument
names and defaults) match the reference — they are the API contract — but
the bodies are structured around two local helpers: ``_new_axes`` builds
the figure, ``_decorate`` applies the shared limit/title/label/grid
treatment that every chart needs.  matplotlib is imported lazily, graphviz
is optional (gated, like the reference).
"""

from __future__ import annotations

from copy import deepcopy
from typing import Any, Dict, List, Optional, Tuple, Union

import numpy as np

from .basic import Booster
from .sklearn import LGBMModel


def _require_pair(obj, obj_name="obj"):
    if not isinstance(obj, tuple) or len(obj) != 2:
        raise TypeError(f"{obj_name} must be a pair of 2 elements")


def _to_booster(booster) -> Booster:
    if isinstance(booster, LGBMModel):
        return booster.booster_
    if isinstance(booster, Booster):
        return booster
    raise TypeError("booster must be a Booster or LGBMModel instance")


def _new_axes(figsize, dpi):
    import matplotlib.pyplot as plt

    if figsize is not None:
        _require_pair(figsize, "figsize")
    _, ax = plt.subplots(1, 1, figsize=figsize, dpi=dpi)
    return ax


def _decorate(ax, *, xlim=None, ylim=None, auto_xlim=None, auto_ylim=None,
              title=None, xlabel=None, ylabel=None, grid=True):
    """Shared axis treatment: explicit limits win (validated as pairs),
    otherwise the chart's computed defaults apply; None labels stay off."""
    if xlim is not None:
        _require_pair(xlim, "xlim")
    elif auto_xlim is not None:
        xlim = auto_xlim
    if xlim is not None:
        ax.set_xlim(xlim)
    if ylim is not None:
        _require_pair(ylim, "ylim")
    elif auto_ylim is not None:
        ylim = auto_ylim
    if ylim is not None:
        ax.set_ylim(ylim)
    if title is not None:
        ax.set_title(title)
    if xlabel is not None:
        ax.set_xlabel(xlabel)
    if ylabel is not None:
        ax.set_ylabel(ylabel)
    ax.grid(grid)
    return ax


def plot_importance(
    booster,
    ax=None,
    height: float = 0.2,
    xlim: Optional[Tuple[float, float]] = None,
    ylim: Optional[Tuple[float, float]] = None,
    title: Optional[str] = "Feature importance",
    xlabel: Optional[str] = "Feature importance",
    ylabel: Optional[str] = "Features",
    importance_type: str = "auto",
    max_num_features: Optional[int] = None,
    ignore_zero: bool = True,
    figsize: Optional[Tuple[float, float]] = None,
    dpi: Optional[int] = None,
    grid: bool = True,
    precision: Optional[int] = 3,
    **kwargs: Any,
):
    """Horizontal bar chart of feature importances (reference
    ``plotting.py plot_importance``)."""
    bst = _to_booster(booster)
    imp_kind = "split" if importance_type == "auto" else importance_type
    values = np.asarray(
        bst.feature_importance(importance_type=imp_kind), np.float64)
    if values.size == 0:
        raise ValueError("Booster's feature_importance is empty.")
    names = np.asarray(bst.feature_name(), dtype=object)

    # ascending by importance so the top feature lands on the top row
    order = np.argsort(values, kind="stable")
    if ignore_zero:
        order = order[values[order] > 0]
    if max_num_features is not None and max_num_features > 0:
        order = order[-max_num_features:]
    values = values[order]
    names = names[order]
    if values.size == 0:
        raise ValueError(
            "No feature has nonzero importance to plot; train the model "
            "first or pass ignore_zero=False.")

    if ax is None:
        ax = _new_axes(figsize, dpi)
    rows = np.arange(values.size)
    ax.barh(rows, values, height=height, align="center", **kwargs)
    show_decimals = precision is not None and imp_kind == "gain"
    for row, v in zip(rows, values):
        text = f"{v:.{precision}f}" if show_decimals else f"{int(v)}"
        ax.text(v + 1, row, text, va="center")
    ax.set_yticks(rows)
    ax.set_yticklabels(list(names))
    return _decorate(ax, xlim=xlim, ylim=ylim,
                     auto_xlim=(0, float(values.max()) * 1.1),
                     auto_ylim=(-1, values.size),
                     title=title, xlabel=xlabel, ylabel=ylabel, grid=grid)


def plot_split_value_histogram(
    booster,
    feature: Union[int, str],
    bins: Union[int, str, None] = None,
    ax=None,
    width_coef: float = 0.8,
    xlim: Optional[Tuple[float, float]] = None,
    ylim: Optional[Tuple[float, float]] = None,
    title: Optional[str] = "Split value histogram for feature with @index/name@ @feature@",
    xlabel: Optional[str] = "Feature split value",
    ylabel: Optional[str] = "Count",
    figsize: Optional[Tuple[float, float]] = None,
    dpi: Optional[int] = None,
    grid: bool = True,
    **kwargs: Any,
):
    """Histogram of a feature's split thresholds across the model (reference
    ``plotting.py plot_split_value_histogram``)."""
    bst = _to_booster(booster)
    dump = bst.dump_model()
    if isinstance(feature, str):
        fidx = dump["feature_names"].index(feature)
    else:
        fidx = int(feature)

    # iterative walk over every tree collecting this feature's thresholds
    thresholds: List[float] = []
    stack = [info["tree_structure"] for info in dump["tree_info"]]
    while stack:
        node = stack.pop()
        if "leaf_index" in node:
            continue
        if node["split_feature"] == fidx and node["decision_type"] == "<=":
            thresholds.append(float(node["threshold"]))
        stack.append(node["left_child"])
        stack.append(node["right_child"])
    if not thresholds:
        raise ValueError(
            f"Cannot plot split value histogram, "
            f"because feature {feature} was not used in splitting")

    counts, edges = np.histogram(thresholds, bins=bins or "auto")
    if ax is None:
        ax = _new_axes(figsize, dpi)
    ax.bar((edges[:-1] + edges[1:]) / 2, counts,
           width=width_coef * (edges[1] - edges[0]), align="center",
           **kwargs)
    if title is not None:
        kind = "name" if isinstance(feature, str) else "index"
        title = title.replace("@feature@", str(feature)) \
                     .replace("@index/name@", kind)
    return _decorate(ax, xlim=xlim, ylim=ylim,
                     auto_ylim=(0, float(counts.max()) * 1.1),
                     title=title, xlabel=xlabel, ylabel=ylabel, grid=grid)


def plot_metric(
    booster: Union[Dict, "LGBMModel"],
    metric: Optional[str] = None,
    dataset_names: Optional[List[str]] = None,
    ax=None,
    xlim: Optional[Tuple[float, float]] = None,
    ylim: Optional[Tuple[float, float]] = None,
    title: Optional[str] = "Metric during training",
    xlabel: Optional[str] = "Iterations",
    ylabel: Optional[str] = "@metric@",
    figsize: Optional[Tuple[float, float]] = None,
    dpi: Optional[int] = None,
    grid: bool = True,
):
    """Plot metric curves recorded by ``record_evaluation`` (reference
    ``plotting.py plot_metric``)."""
    if isinstance(booster, Booster):
        raise TypeError("booster must be a dict from record_evaluation() "
                        "or an LGBMModel (reference behavior)")
    if isinstance(booster, LGBMModel):
        source = booster.evals_result_
    elif isinstance(booster, dict):
        source = booster
    else:
        raise TypeError("booster must be dict or LGBMModel.")
    if not source:
        raise ValueError("eval results cannot be empty.")
    eval_results = deepcopy(source)

    # resolve the metric name from the first dataset, then pull one curve
    # per requested dataset
    datasets = (list(eval_results.keys()) if dataset_names is None
                else list(dataset_names))
    first = eval_results[datasets[0]]
    if metric is None:
        if len(first) > 1:
            raise ValueError("more than one metric available, pick one with "
                             "the metric parameter")
        metric = next(iter(first))
    elif metric not in first:
        raise KeyError("No given metric in eval results.")
    curves = [(name, eval_results[name][metric]) for name in datasets]

    if ax is None:
        ax = _new_axes(figsize, dpi)
    for name, series in curves:
        ax.plot(range(len(series)), series, label=name)
    ax.legend(loc="best")

    n_iters = max(len(series) for _, series in curves)
    lo = min(min(series) for _, series in curves)
    hi = max(max(series) for _, series in curves)
    margin = (hi - lo) * 0.2
    return _decorate(ax, xlim=xlim, ylim=ylim,
                     auto_xlim=(0, n_iters),
                     auto_ylim=(lo - margin, hi + margin),
                     title=title, xlabel=xlabel,
                     ylabel=(None if ylabel is None
                             else ylabel.replace("@metric@", metric)),
                     grid=grid)


def _float2str(value, precision: Optional[int] = 3) -> str:
    return (f"{value:.{precision}f}" if precision is not None
            and not isinstance(value, str) else str(value))


def create_tree_digraph(
    booster,
    tree_index: int = 0,
    show_info: Optional[List[str]] = None,
    precision: Optional[int] = 3,
    orientation: str = "horizontal",
    **kwargs: Any,
):
    """Graphviz digraph of one tree (reference ``plotting.py
    create_tree_digraph``); requires the optional graphviz package."""
    try:
        from graphviz import Digraph
    except ImportError as exc:
        raise ImportError(
            "You must install graphviz and restart your session "
            "to plot tree.") from exc

    bst = _to_booster(booster)
    dump = bst.dump_model()
    if tree_index >= len(dump["tree_info"]):
        raise IndexError("tree_index is out of range.")
    tree_info = dump["tree_info"][tree_index]
    names = dump["feature_names"]
    show_info = show_info or []

    graph = Digraph(**kwargs)
    rankdir = "LR" if orientation == "horizontal" else "TB"
    graph.attr("graph", nodesep="0.05", ranksep="0.3", rankdir=rankdir)

    def add(node, parent=None, decision=None):
        if "split_index" in node:
            name = f"split{node['split_index']}"
            label = (f"{names[node['split_feature']]} "
                     f"{node['decision_type']} "
                     f"{_float2str(node['threshold'], precision)}")
            for info in ("split_gain", "internal_value", "internal_count"):
                if info in show_info:
                    label += f"\n{info}: {_float2str(node[info], precision)}"
            graph.node(name, label=label, shape="rectangle")
            add(node["left_child"], name, "yes")
            add(node["right_child"], name, "no")
        else:
            name = f"leaf{node['leaf_index']}"
            label = f"leaf {node['leaf_index']}: " \
                    f"{_float2str(node['leaf_value'], precision)}"
            if "leaf_count" in show_info and "leaf_count" in node:
                label += f"\ncount: {node['leaf_count']}"
            graph.node(name, label=label)
        if parent is not None:
            graph.edge(parent, name, decision)

    add(tree_info["tree_structure"])
    return graph


def plot_tree(
    booster,
    ax=None,
    tree_index: int = 0,
    figsize: Optional[Tuple[float, float]] = None,
    dpi: Optional[int] = None,
    show_info: Optional[List[str]] = None,
    precision: Optional[int] = 3,
    orientation: str = "horizontal",
    **kwargs: Any,
):
    """Render one tree with matplotlib.  Uses graphviz when available
    (reference behavior); otherwise falls back to a pure-matplotlib
    layout so the function works in this hermetic environment."""
    if ax is None:
        ax = _new_axes(figsize, dpi)
    try:
        from graphviz import Digraph  # noqa: F401
        graph = create_tree_digraph(booster, tree_index, show_info, precision,
                                    orientation)
        import io
        try:
            from PIL import Image
            s = io.BytesIO(graph.pipe(format="png"))
            ax.imshow(Image.open(s))
            ax.axis("off")
            return ax
        except Exception:
            pass
    except ImportError:
        pass

    # matplotlib-only fallback: recursive box layout
    bst = _to_booster(booster)
    dump = bst.dump_model()
    if tree_index >= len(dump["tree_info"]):
        raise IndexError("tree_index is out of range.")
    names = dump["feature_names"]
    root = dump["tree_info"][tree_index]["tree_structure"]

    def depth_of(node):
        if "leaf_index" in node:
            return 1
        return 1 + max(depth_of(node["left_child"]),
                       depth_of(node["right_child"]))

    total_depth = depth_of(root)
    next_y = [0.0]

    def layout(node, depth):
        x = depth / max(total_depth - 1, 1)
        if "leaf_index" in node:
            y = next_y[0]
            next_y[0] += 1.0
            label = f"leaf {node['leaf_index']}\n" \
                    f"{_float2str(node['leaf_value'], precision)}"
            ax.annotate(label, (x, y), ha="center", va="center",
                        bbox=dict(boxstyle="round", fc="lightyellow"))
            return y
        yl = layout(node["left_child"], depth + 1)
        yr = layout(node["right_child"], depth + 1)
        y = (yl + yr) / 2
        label = (f"{names[node['split_feature']]} {node['decision_type']} "
                 f"{_float2str(node['threshold'], precision)}")
        ax.annotate(label, (x, y), ha="center", va="center",
                    bbox=dict(boxstyle="round", fc="lightblue"))
        xl = (depth + 1) / max(total_depth - 1, 1)
        ax.plot([x, xl], [y, yl], "k-", lw=0.8, zorder=0)
        ax.plot([x, xl], [y, yr], "k-", lw=0.8, zorder=0)
        return y

    layout(root, 0)
    ax.set_xlim(-0.1, 1.1)
    ax.set_ylim(-1, next_y[0])
    ax.axis("off")
    return ax
