"""Plotting utilities.

Reference: ``python-package/lightgbm/plotting.py`` (840 LoC) —
``plot_importance``, ``plot_split_value_histogram``, ``plot_metric``,
``plot_tree``, ``create_tree_digraph``.  Same call signatures for the common
arguments; matplotlib is imported lazily, graphviz is optional (gated, like the
reference).
"""

from __future__ import annotations

from copy import deepcopy
from typing import Any, Dict, List, Optional, Tuple, Union

import numpy as np

from .basic import Booster
from .sklearn import LGBMModel


def _require_pair(obj, obj_name="obj"):
    if not isinstance(obj, tuple) or len(obj) != 2:
        raise TypeError(f"{obj_name} must be a pair of 2 elements")


def _to_booster(booster) -> Booster:
    if isinstance(booster, LGBMModel):
        return booster.booster_
    if isinstance(booster, Booster):
        return booster
    raise TypeError("booster must be a Booster or LGBMModel instance")


def plot_importance(
    booster,
    ax=None,
    height: float = 0.2,
    xlim: Optional[Tuple[float, float]] = None,
    ylim: Optional[Tuple[float, float]] = None,
    title: Optional[str] = "Feature importance",
    xlabel: Optional[str] = "Feature importance",
    ylabel: Optional[str] = "Features",
    importance_type: str = "auto",
    max_num_features: Optional[int] = None,
    ignore_zero: bool = True,
    figsize: Optional[Tuple[float, float]] = None,
    dpi: Optional[int] = None,
    grid: bool = True,
    precision: Optional[int] = 3,
    **kwargs: Any,
):
    """Horizontal bar chart of feature importances (reference
    ``plotting.py plot_importance``)."""
    import matplotlib.pyplot as plt

    bst = _to_booster(booster)
    if importance_type == "auto":
        importance_type = "split"
    importance = bst.feature_importance(importance_type=importance_type)
    feature_name = bst.feature_name()

    if not len(importance):
        raise ValueError("Booster's feature_importance is empty.")
    tuples = sorted(zip(feature_name, importance), key=lambda x: x[1])
    if ignore_zero:
        tuples = [x for x in tuples if x[1] > 0]
    if max_num_features is not None and max_num_features > 0:
        tuples = tuples[-max_num_features:]
    labels, values = zip(*tuples)

    if ax is None:
        if figsize is not None:
            _require_pair(figsize, "figsize")
        _, ax = plt.subplots(1, 1, figsize=figsize, dpi=dpi)
    ylocs = np.arange(len(values))
    ax.barh(ylocs, values, align="center", height=height, **kwargs)
    for x, y in zip(values, ylocs):
        fmt = f"%.{precision}f" if (precision is not None
                                    and importance_type == "gain") else "%d"
        ax.text(x + 1, y, fmt % x, va="center")
    ax.set_yticks(ylocs)
    ax.set_yticklabels(labels)
    if xlim is not None:
        _require_pair(xlim, "xlim")
    else:
        xlim = (0, max(values) * 1.1)
    ax.set_xlim(xlim)
    if ylim is not None:
        _require_pair(ylim, "ylim")
    else:
        ylim = (-1, len(values))
    ax.set_ylim(ylim)
    if title is not None:
        ax.set_title(title)
    if xlabel is not None:
        ax.set_xlabel(xlabel)
    if ylabel is not None:
        ax.set_ylabel(ylabel)
    ax.grid(grid)
    return ax


def plot_split_value_histogram(
    booster,
    feature: Union[int, str],
    bins: Union[int, str, None] = None,
    ax=None,
    width_coef: float = 0.8,
    xlim: Optional[Tuple[float, float]] = None,
    ylim: Optional[Tuple[float, float]] = None,
    title: Optional[str] = "Split value histogram for feature with @index/name@ @feature@",
    xlabel: Optional[str] = "Feature split value",
    ylabel: Optional[str] = "Count",
    figsize: Optional[Tuple[float, float]] = None,
    dpi: Optional[int] = None,
    grid: bool = True,
    **kwargs: Any,
):
    """Histogram of a feature's split thresholds across the model (reference
    ``plotting.py plot_split_value_histogram``)."""
    import matplotlib.pyplot as plt

    bst = _to_booster(booster)
    dump = bst.dump_model()
    names = dump["feature_names"]
    if isinstance(feature, str):
        fidx = names.index(feature)
    else:
        fidx = int(feature)

    values: List[float] = []

    def walk(node):
        if "leaf_index" in node:
            return
        if node["split_feature"] == fidx and node["decision_type"] == "<=":
            values.append(float(node["threshold"]))
        walk(node["left_child"])
        walk(node["right_child"])

    for info in dump["tree_info"]:
        walk(info["tree_structure"])
    if not values:
        raise ValueError(
            f"Cannot plot split value histogram, "
            f"because feature {feature} was not used in splitting")
    hist, bin_edges = np.histogram(values, bins=bins or "auto")
    centers = (bin_edges[:-1] + bin_edges[1:]) / 2

    if ax is None:
        if figsize is not None:
            _require_pair(figsize, "figsize")
        _, ax = plt.subplots(1, 1, figsize=figsize, dpi=dpi)
    width = width_coef * (bin_edges[1] - bin_edges[0])
    ax.bar(centers, hist, width=width, align="center", **kwargs)
    if xlim is not None:
        _require_pair(xlim, "xlim")
        ax.set_xlim(xlim)
    if ylim is not None:
        _require_pair(ylim, "ylim")
    else:
        ylim = (0, max(hist) * 1.1)
    ax.set_ylim(ylim)
    if title is not None:
        title = title.replace("@feature@", str(feature)).replace(
            "@index/name@", "name" if isinstance(feature, str) else "index")
        ax.set_title(title)
    if xlabel is not None:
        ax.set_xlabel(xlabel)
    if ylabel is not None:
        ax.set_ylabel(ylabel)
    ax.grid(grid)
    return ax


def plot_metric(
    booster: Union[Dict, "LGBMModel"],
    metric: Optional[str] = None,
    dataset_names: Optional[List[str]] = None,
    ax=None,
    xlim: Optional[Tuple[float, float]] = None,
    ylim: Optional[Tuple[float, float]] = None,
    title: Optional[str] = "Metric during training",
    xlabel: Optional[str] = "Iterations",
    ylabel: Optional[str] = "@metric@",
    figsize: Optional[Tuple[float, float]] = None,
    dpi: Optional[int] = None,
    grid: bool = True,
):
    """Plot metric curves recorded by ``record_evaluation`` (reference
    ``plotting.py plot_metric``)."""
    import matplotlib.pyplot as plt

    if isinstance(booster, LGBMModel):
        eval_results = deepcopy(booster.evals_result_)
    elif isinstance(booster, dict):
        eval_results = deepcopy(booster)
    elif isinstance(booster, Booster):
        raise TypeError("booster must be a dict from record_evaluation() "
                        "or an LGBMModel (reference behavior)")
    else:
        raise TypeError("booster must be dict or LGBMModel.")
    if not eval_results:
        raise ValueError("eval results cannot be empty.")

    if ax is None:
        if figsize is not None:
            _require_pair(figsize, "figsize")
        _, ax = plt.subplots(1, 1, figsize=figsize, dpi=dpi)

    if dataset_names is None:
        dataset_names_iter = iter(eval_results.keys())
    else:
        dataset_names_iter = iter(dataset_names)
    name = next(dataset_names_iter)
    metrics_for_one = eval_results[name]
    num_metric = len(metrics_for_one)
    if metric is None:
        if num_metric > 1:
            raise ValueError("more than one metric available, pick one with "
                             "the metric parameter")
        metric, results = list(metrics_for_one.items())[0]
    else:
        if metric not in metrics_for_one:
            raise KeyError("No given metric in eval results.")
        results = metrics_for_one[metric]
    num_iteration = len(results)
    max_result = max(results)
    min_result = min(results)
    x_ = range(num_iteration)
    ax.plot(x_, results, label=name)
    for name in dataset_names_iter:
        metrics_for_one = eval_results[name]
        results = metrics_for_one[metric]
        max_result = max(*results, max_result)
        min_result = min(*results, min_result)
        ax.plot(x_, results, label=name)
    ax.legend(loc="best")
    if xlim is not None:
        _require_pair(xlim, "xlim")
    else:
        xlim = (0, num_iteration)
    ax.set_xlim(xlim)
    if ylim is not None:
        _require_pair(ylim, "ylim")
    else:
        range_result = max_result - min_result
        ylim = (min_result - range_result * 0.2,
                max_result + range_result * 0.2)
    ax.set_ylim(ylim)
    if title is not None:
        ax.set_title(title)
    if xlabel is not None:
        ax.set_xlabel(xlabel)
    if ylabel is not None:
        ax.set_ylabel(ylabel.replace("@metric@", metric))
    ax.grid(grid)
    return ax


def _float2str(value, precision: Optional[int] = 3) -> str:
    return (f"{value:.{precision}f}" if precision is not None
            and not isinstance(value, str) else str(value))


def create_tree_digraph(
    booster,
    tree_index: int = 0,
    show_info: Optional[List[str]] = None,
    precision: Optional[int] = 3,
    orientation: str = "horizontal",
    **kwargs: Any,
):
    """Graphviz digraph of one tree (reference ``plotting.py
    create_tree_digraph``); requires the optional graphviz package."""
    try:
        from graphviz import Digraph
    except ImportError as exc:
        raise ImportError(
            "You must install graphviz and restart your session "
            "to plot tree.") from exc

    bst = _to_booster(booster)
    dump = bst.dump_model()
    if tree_index >= len(dump["tree_info"]):
        raise IndexError("tree_index is out of range.")
    tree_info = dump["tree_info"][tree_index]
    names = dump["feature_names"]
    show_info = show_info or []

    graph = Digraph(**kwargs)
    rankdir = "LR" if orientation == "horizontal" else "TB"
    graph.attr("graph", nodesep="0.05", ranksep="0.3", rankdir=rankdir)

    def add(node, parent=None, decision=None):
        if "split_index" in node:
            name = f"split{node['split_index']}"
            label = (f"{names[node['split_feature']]} "
                     f"{node['decision_type']} "
                     f"{_float2str(node['threshold'], precision)}")
            for info in ("split_gain", "internal_value", "internal_count"):
                if info in show_info:
                    label += f"\n{info}: {_float2str(node[info], precision)}"
            graph.node(name, label=label, shape="rectangle")
            add(node["left_child"], name, "yes")
            add(node["right_child"], name, "no")
        else:
            name = f"leaf{node['leaf_index']}"
            label = f"leaf {node['leaf_index']}: " \
                    f"{_float2str(node['leaf_value'], precision)}"
            if "leaf_count" in show_info and "leaf_count" in node:
                label += f"\ncount: {node['leaf_count']}"
            graph.node(name, label=label)
        if parent is not None:
            graph.edge(parent, name, decision)

    add(tree_info["tree_structure"])
    return graph


def plot_tree(
    booster,
    ax=None,
    tree_index: int = 0,
    figsize: Optional[Tuple[float, float]] = None,
    dpi: Optional[int] = None,
    show_info: Optional[List[str]] = None,
    precision: Optional[int] = 3,
    orientation: str = "horizontal",
    **kwargs: Any,
):
    """Render one tree with matplotlib.  Uses graphviz when available
    (reference behavior); otherwise falls back to a pure-matplotlib
    layout so the function works in this hermetic environment."""
    import matplotlib.pyplot as plt

    if ax is None:
        if figsize is not None:
            _require_pair(figsize, "figsize")
        _, ax = plt.subplots(1, 1, figsize=figsize, dpi=dpi)
    try:
        from graphviz import Digraph  # noqa: F401
        graph = create_tree_digraph(booster, tree_index, show_info, precision,
                                    orientation)
        import io
        try:
            from PIL import Image
            s = io.BytesIO(graph.pipe(format="png"))
            ax.imshow(Image.open(s))
            ax.axis("off")
            return ax
        except Exception:
            pass
    except ImportError:
        pass

    # matplotlib-only fallback: recursive box layout
    bst = _to_booster(booster)
    dump = bst.dump_model()
    if tree_index >= len(dump["tree_info"]):
        raise IndexError("tree_index is out of range.")
    names = dump["feature_names"]
    root = dump["tree_info"][tree_index]["tree_structure"]

    def depth_of(node):
        if "leaf_index" in node:
            return 1
        return 1 + max(depth_of(node["left_child"]),
                       depth_of(node["right_child"]))

    total_depth = depth_of(root)
    next_y = [0.0]

    def layout(node, depth):
        x = depth / max(total_depth - 1, 1)
        if "leaf_index" in node:
            y = next_y[0]
            next_y[0] += 1.0
            label = f"leaf {node['leaf_index']}\n" \
                    f"{_float2str(node['leaf_value'], precision)}"
            ax.annotate(label, (x, y), ha="center", va="center",
                        bbox=dict(boxstyle="round", fc="lightyellow"))
            return y
        yl = layout(node["left_child"], depth + 1)
        yr = layout(node["right_child"], depth + 1)
        y = (yl + yr) / 2
        label = (f"{names[node['split_feature']]} {node['decision_type']} "
                 f"{_float2str(node['threshold'], precision)}")
        ax.annotate(label, (x, y), ha="center", va="center",
                    bbox=dict(boxstyle="round", fc="lightblue"))
        xl = (depth + 1) / max(total_depth - 1, 1)
        ax.plot([x, xl], [y, yl], "k-", lw=0.8, zorder=0)
        ax.plot([x, xl], [y, yr], "k-", lw=0.8, zorder=0)
        return y

    layout(root, 0)
    ax.set_xlim(-0.1, 1.1)
    ax.set_ylim(-1, next_y[0])
    ax.axis("off")
    return ax
