"""Training entry points: ``train`` and ``cv``.

Reference: ``python-package/lightgbm/engine.py`` (``train:109`` — the iteration
loop at ``engine.py:309-322``; ``cv:611`` with stratified/group folds).
"""

from __future__ import annotations

import copy
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Union

import numpy as np

from . import callback as callback_mod
from . import telemetry as telemetry_mod
from .basic import Booster, Dataset
from .callback import CallbackEnv, EarlyStopException
from .resilience import faults


def train(
    params: Dict[str, Any],
    train_set: Dataset,
    num_boost_round: int = 100,
    valid_sets: Optional[Sequence[Dataset]] = None,
    valid_names: Optional[Sequence[str]] = None,
    feval: Optional[Callable] = None,
    init_model: Optional[Union[str, Booster]] = None,
    keep_training_booster: bool = False,
    callbacks: Optional[List[Callable]] = None,
    resume_from: Optional[str] = None,
) -> Booster:
    """Train a booster (reference ``engine.train``).

    ``resume_from`` continues training from a resilience checkpoint (a
    snapshot file or a checkpoint directory — the newest valid generation
    wins); the resumed run's trees are bitwise-identical to the
    uninterrupted run's (docs/ROBUSTNESS.md).  ``checkpoint_interval`` in
    ``params`` emits such snapshots every N committed rounds, at iter-pack
    commit boundaries."""
    # Backend watchdog preflight (opt-in LIGHTGBM_TPU_WATCHDOG=1): classify
    # a wedged accelerator in a budgeted subprocess BEFORE this process
    # touches the device — a clear error instead of an indefinite hang.
    from .resilience.watchdog import preflight
    preflight(params)
    # Callable objective (reference: params["objective"] may be a function
    # (grad, hess) = fobj(preds, train_data) since lightgbm 4.x).
    fobj = None
    if callable(params.get("objective")):
        fobj = params["objective"]
        params = {**params, "objective": "custom"}
    params = copy.deepcopy(params)
    if "num_iterations" in params or "num_boost_round" in params:
        num_boost_round = int(params.pop("num_boost_round",
                              params.pop("num_iterations", num_boost_round)))
    # early stopping via params (reference: _ConfigAliases handling).
    early_stopping_rounds = None
    for alias in ("early_stopping_round", "early_stopping_rounds",
                  "early_stopping", "n_iter_no_change"):
        if params.get(alias):
            early_stopping_rounds = int(params[alias])
    first_metric_only = bool(params.get("first_metric_only", False))
    es_min_delta = float(params.get("early_stopping_min_delta", 0.0))

    valid_sets = list(valid_sets or [])
    names = list(valid_names or [])
    valid_pairs = []
    for i, vs in enumerate(valid_sets):
        if vs is train_set:
            continue
        nm = names[i] if i < len(names) else f"valid_{i}"
        valid_pairs.append((nm, vs))

    # Training continuation (reference boosting.cpp:34-59 + engine.py init_model
    # handling): load the base model, replay its raw predictions into every
    # dataset's init_score, and keep its trees for saving/prediction.
    base = None
    if init_model is not None:
        from .serialization import LoadedModel, load_model_string
        if isinstance(init_model, Booster):
            base = load_model_string(init_model.model_to_string())
        elif isinstance(init_model, LoadedModel):
            base = init_model
        else:
            with open(init_model) as fh:
                base = load_model_string(fh.read())

        def _fold_init(ds: Dataset) -> Dataset:
            # Work on a shallow copy: the caller's Dataset must keep its own
            # init_score (re-running train() on it would otherwise compound).
            if getattr(ds, "_text_path", None) is not None:
                ds.construct(params)   # load raw rows before predicting
            if not getattr(ds, "data", np.zeros(0)).size:
                raise ValueError(
                    "init_model continuation needs raw feature data to "
                    "fold base predictions; binary dataset caches hold "
                    "only binned columns — pass arrays or a text file")
            out = copy.copy(ds)
            from .binning import _is_sparse, predict_dense_chunks
            if _is_sparse(ds.data):
                pred = predict_dense_chunks(base.predict_raw, ds.data)
            else:
                pred = np.asarray(base.predict_raw(ds.data), np.float64)
            if ds.init_score is not None:
                pred = pred + np.asarray(ds.init_score,
                                         np.float64).reshape(pred.shape)
            out.init_score = pred
            out._train_data = None  # re-construct with the new init_score
            return out
        orig_train = train_set
        train_set = _fold_init(train_set)
        new_pairs = []
        for nm, vs in valid_pairs:
            vc = _fold_init(vs)
            if vc.reference is orig_train:
                vc.reference = train_set
            new_pairs.append((nm, vc))
        valid_pairs = new_pairs

    booster = Booster(params=params, train_set=train_set,
                      valid_sets=valid_pairs, base_model=base)

    cbs = list(callbacks or [])
    if early_stopping_rounds is not None and valid_pairs:
        cbs.append(callback_mod.early_stopping(
            early_stopping_rounds, first_metric_only=first_metric_only,
            verbose=params.get("verbosity", 1) > 0,
            min_delta=es_min_delta))
    cbs_before = [cb for cb in cbs if getattr(cb, "before_iteration", False)]
    cbs_after = [cb for cb in cbs if not getattr(cb, "before_iteration", False)]
    cbs_before.sort(key=lambda cb: getattr(cb, "order", 0))
    cbs_after.sort(key=lambda cb: getattr(cb, "order", 0))

    # Periodic model snapshots (reference gbdt.cpp:250-254 snapshot_freq:
    # saves "<output_model>.snapshot_iter_<n>" during training).  Resolved
    # through Config so aliases (save_period, model_out, ...) apply.
    snapshot_freq = booster.cfg.snapshot_freq
    snapshot_base = booster.cfg.output_model or "LightGBM_model.txt"

    # Eval cadence (callback.py contract): a callback may declare the period
    # at which it consumes metrics via ``cb.eval_period`` (default 1); the
    # engine skips metric computation — and its host transfer — on rounds
    # nothing consumes, and the pack plan below aligns to the cadence.
    # eval_period <= 0 marks a callback that never consumes metrics (e.g.
    # log_evaluation(period=0), the documented way to silence logging).
    cb_periods = [p for p in (int(getattr(cb, "eval_period", 1))
                              for cb in cbs_after) if p > 0]
    if feval is not None:
        cb_periods.append(1)
    eval_period = min(cb_periods) if cb_periods else None

    def _round_needs_eval(it: int) -> bool:
        return any((it + 1) % p == 0 for p in cb_periods)

    # Iteration packing (docs/ITER_PACK.md): scan K boosting rounds into ONE
    # device dispatch when nothing demands per-round host access.  Per-round
    # param resets (before-callbacks), snapshots, custom objectives and
    # training-score consumers (feval / training metric — mid-pack train
    # scores do not exist on the host) pin the per-round path; everything
    # else is the booster's pack plan (auto-degrade list lives there).
    needs_train_scores = feval is not None or (
        bool(cbs_after) and booster.cfg.is_provide_training_metric)
    pack_k, use_pack = 1, False
    if (fobj is None and not cbs_before and snapshot_freq <= 0
            and not needs_train_scores):
        pack_k, use_pack = booster._gbdt.iter_pack_plan(
            num_boost_round, eval_period)
    if use_pack and num_boost_round % pack_k:
        # A trailing remainder pack would compile a SECOND scan program
        # (the pack cache keys on K).  Pack size is scheduling-only (models
        # are bitwise identical across K), so snap to a divisor of the
        # round count when one exists nearby; keep the remainder scheme
        # when the only divisors are tiny (a prime round count must not
        # degrade to per-round dispatching).
        div = max((d for d in range(1, pack_k + 1)
                   if num_boost_round % d == 0), default=1)
        if div >= max(pack_k // 2, 2):
            pack_k = div

    # best_iteration counts over the COMBINED model (base trees first) so
    # Booster.predict's num_iteration slicing keeps the full base ensemble.
    n_base = base.iter_ if base is not None else 0

    # Telemetry session (telemetry/, docs/OBSERVABILITY.md): arms the
    # process-wide span switch and the JSONL event sink from the config,
    # owns the optional first-N-iterations jax.profiler capture, and
    # closes what it opened when training ends.  Host-side only — with
    # tpu_telemetry=off every emit below is a no-op and the compiled
    # training programs are bitwise-identical either way.
    tel = telemetry_mod.train_session(booster.cfg)

    # Checkpoint/resume (docs/ROBUSTNESS.md).  Snapshots are emitted only
    # at iter-pack commit boundaries — mid-pack, scores already include
    # uncommitted rounds — so with packing the interval is a floor: the
    # snapshot lands at the first boundary at/after each interval multiple.
    start_it = 0
    # Per-round eval history, recorded while checkpointing (and carried in
    # every snapshot): after-callback closure state — early_stopping's
    # best/wait counters, record_evaluation's dict — is DERIVED from these
    # values, so a resumed run replays them below instead of trying to
    # pickle user callback closures.
    booster._ckpt_eval_history = []
    # Training-health sentinel (docs/ROBUSTNESS.md, resilience/health.py):
    # tpu_health_policy != off arms in-dispatch NaN/Inf/overflow guards, a
    # loss-divergence detector over the per-round eval history and —
    # under "rollback" — checkpoint-backed auto-recovery.
    from .resilience import health as health_mod
    sentinel = None
    if booster.cfg.tpu_health_policy != "off":
        sentinel = health_mod.TrainingHealthSentinel(booster.cfg)
    booster._health_report = (health_mod.off_report() if sentinel is None
                              else sentinel.report())
    if resume_from is not None:
        from .resilience import checkpoint as checkpoint_mod
        try:
            start_it = checkpoint_mod.restore(booster, resume_from)
            # Recovery generation (tpu_health_recovery_salt > 0): the SAME
            # lr-backoff + key-refold transformation the in-process
            # rollback applies — which is what makes a fresh resume with
            # the same salt reproduce the recovered run's trees bitwise.
            health_mod.apply_recovery(booster,
                                      booster.cfg.tpu_health_recovery_salt)
            try:
                for it_h, evals_h in booster._ckpt_eval_history:
                    if it_h >= start_it:
                        continue
                    for cb in cbs_after:
                        cb(CallbackEnv(booster, params, it_h, 0,
                                       num_boost_round, evals_h))
            except EarlyStopException as e:
                # cannot fire for rounds the original run trained past (a
                # stop breaks the loop before the next snapshot), but
                # handle it exactly as _fire_after would, defensively
                booster.best_iteration = e.best_iteration + 1 + n_base
                booster.best_score = e.best_score
                tel.close()   # this session's sink must not outlive it
                return booster
        except BaseException:
            # a failed restore/recovery/replay must not strand the sink
            tel.close()
            raise
    ckpt_interval = booster.cfg.checkpoint_interval
    if ckpt_interval > 0 and not booster._gbdt._supports_checkpoint:
        from .utils.log import Log
        Log.warning(
            f"checkpoint_interval is ignored for boosting="
            f"{booster.cfg.boosting}: per-round host state is not captured")
        ckpt_interval = 0
    ckpt_dir = booster.cfg.checkpoint_dir or f"{snapshot_base}.ckpt"
    last_ckpt = [start_it]
    if (sentinel is not None and sentinel.policy == "rollback"
            and ckpt_interval <= 0):
        from .utils.log import Log
        Log.warning(
            "tpu_health_policy=rollback without checkpoint_interval>0: "
            "there will be no checkpoint to roll back to, so a tripped "
            "sentinel escalates straight to HealthHaltError")

    def _maybe_checkpoint(done_it: int) -> Optional[float]:
        """Snapshot when the cadence is due; returns the write duration in
        seconds (None when no snapshot was due) — the ``checkpoint_s``
        field of the round's ``train.iter`` event."""
        if ckpt_interval <= 0 \
                or done_it // ckpt_interval <= last_ckpt[0] // ckpt_interval:
            return None
        from .resilience import checkpoint as checkpoint_mod
        t0 = time.perf_counter()
        checkpoint_mod.save_snapshot(booster, ckpt_dir,
                                     keep=booster.cfg.checkpoint_keep)
        dt = time.perf_counter() - t0
        last_ckpt[0] = done_it
        tel.emit("train.checkpoint", iteration=done_it, dir=ckpt_dir,
                 seconds=round(dt, 6))
        return dt

    # evals the sentinel already computed for a round (keyed by 0-based
    # iteration), reused by _fire_after so arming the sentinel never
    # doubles the per-round eval cost.  Only populated when feval is None
    # (the sentinel's _evals() carries no feval rows).
    sentinel_evals: Dict[int, list] = {}

    def _fire_after(it: int) -> bool:
        """Eval + after-callbacks for round ``it``; True = early stop."""
        if not _round_needs_eval(it):
            return False
        evals = sentinel_evals.pop(it, None)
        if evals is None:
            evals = booster._evals(feval)
        # no after-callbacks -> nothing to replay on resume: skip the
        # history (each snapshot re-pickles the whole list, so for long
        # runs this is the difference between O(1) and O(rounds) extra
        # bytes per generation)
        if ckpt_interval > 0 and cbs_after:
            booster._ckpt_eval_history.append((it, evals))
        try:
            for cb in cbs_after:
                # begin_iteration stays 0 on resume: callbacks see the same
                # absolute (iteration, begin, end) stream as the
                # uninterrupted run, so reset_parameter schedules index the
                # same values and the bitwise-resume contract holds
                # (early_stopping self-initializes on its first firing).
                cb(CallbackEnv(booster, params, it, 0,
                               num_boost_round, evals))
        except EarlyStopException as e:
            booster.best_iteration = e.best_iteration + 1 + n_base
            booster.best_score = e.best_score
            return True
        return False

    # ---- health sentinel hooks (docs/ROBUSTNESS.md health section) ----
    rollbacks_done = [0]

    def _health_check(done_it: int) -> bool:
        """Observe the just-committed round ``done_it`` (1-based count of
        committed rounds).  Returns True when the engine must roll back;
        warn logs and continues; halt (and an exhausted rollback budget)
        raises :class:`~.resilience.health.HealthHaltError`.  Runs BEFORE
        the round's after-callbacks so halt/rollback policies never feed a
        diverged metric into early-stopping state."""
        if sentinel is None:
            return False
        hv = booster._gbdt.consume_health()
        evals = None
        if valid_pairs or booster.cfg.is_provide_training_metric:
            evals = booster._evals()
            if feval is None:
                sentinel_evals.clear()
                sentinel_evals[done_it - 1] = evals
            if use_pack and evals:
                # Mid-pack, train scores already include the WHOLE pack
                # (train_pack committed scores2 up front), so the training
                # metric is the same end-of-pack value at every commit —
                # feeding it to the detector would trip loss_stagnation
                # on healthy runs.  Valid scores DO advance per commit
                # (_store_tree), so only training rows are dropped.
                evals = [e for e in evals if e[0] != "training"]
        trip = sentinel.observe_round(done_it, hv, evals)
        if trip is None:
            return False
        from .utils.log import Log
        if sentinel.policy == "warn":
            Log.warning(f"health sentinel tripped: {trip} (policy=warn, "
                        "training continues)")
            return False
        if sentinel.policy == "halt":
            sentinel.note_halt()
            booster._health_report = sentinel.report()
            raise health_mod.HealthHaltError(
                f"training halted by the health sentinel: {trip} "
                "(tpu_health_policy=halt)", booster)
        return True   # rollback

    def _do_rollback() -> int:
        """Restore the newest valid checkpoint in-process and apply the
        next recovery generation (lr backoff + key refold).  Returns the
        iteration training resumes at."""
        trip = sentinel.trips[-1]
        rollbacks_done[0] += 1
        cap = booster.cfg.tpu_health_max_rollbacks
        if rollbacks_done[0] > cap:
            sentinel.note_halt()
            booster._health_report = sentinel.report()
            raise health_mod.HealthHaltError(
                f"health sentinel: {trip} — tpu_health_max_rollbacks="
                f"{cap} recovery attempts exhausted", booster)
        from .resilience import checkpoint as checkpoint_mod
        from .serialization import FrameCorruptError
        try:
            start = checkpoint_mod.restore(booster, ckpt_dir)
        except (FileNotFoundError, FrameCorruptError) as e:
            sentinel.note_halt()
            booster._health_report = sentinel.report()
            raise health_mod.HealthHaltError(
                f"health sentinel: {trip} — rollback impossible "
                f"({e})", booster) from e
        salt = booster.cfg.tpu_health_recovery_salt + rollbacks_done[0]
        health_mod.apply_recovery(booster, salt)
        sentinel.note_rollback(start, salt)
        tel.emit("train.rollback", restored_iteration=start, salt=salt,
                 trip=str(trip),
                 rollbacks=f"{rollbacks_done[0]}/{cap}")
        sentinel_evals.clear()   # cached evals refer to discarded rounds
        # checkpoint cadence and eval-history replay state rewind with the
        # restore; after-callbacks are NOT replayed here (they already saw
        # rounds <= start in this process — docs/ROBUSTNESS.md).
        last_ckpt[0] = start
        return start

    it = start_it
    t_train0 = time.perf_counter()
    tel.emit(
        "train.start", num_boost_round=num_boost_round, start_iteration=it,
        objective=booster.cfg.objective, boosting=booster.cfg.boosting,
        num_class=booster._gbdt.num_class,
        rows=booster._gbdt.train_data.num_data,
        features=booster._gbdt.train_data.num_features,
        packed=use_pack, pack_size=pack_k if use_pack else 1,
        pack_degrade_reason=booster._gbdt.iter_pack_degrade_reason(),
        health_policy=booster.cfg.tpu_health_policy,
        checkpoint_interval=ckpt_interval,
        valid_sets=[nm for nm, _ in valid_pairs])
    tel.maybe_start_profile()

    def _emit_iter(done_it: int, dispatch_s: float, host_s: float,
                   pack_size: int, ckpt_s: Optional[float]) -> None:
        """One ``train.iter`` event per COMMITTED round: wall time split
        into dispatch wait (time inside the device-facing call — amortized
        per round on the pack path) vs host bookkeeping (commit, eval,
        callbacks, checkpoint), plus the health verdict so far."""
        host_s = max(host_s, 0.0)
        tel.emit("train.iter", iteration=done_it,
                 wall_s=round(dispatch_s + host_s, 6),
                 dispatch_wait_s=round(dispatch_s, 6),
                 host_s=round(host_s, 6), pack_size=pack_size,
                 checkpoint_s=(None if ckpt_s is None
                               else round(ckpt_s, 6)),
                 health=(None if sentinel is None else sentinel.verdict()))
        tel.maybe_stop_profile(done_it - start_it)

    try:
        while it < num_boost_round:
            if use_pack:
                t_pack0 = time.perf_counter()
                rounds, finished = booster._gbdt.train_pack(
                    min(pack_k, num_boost_round - it))
                # amortized device share of each committed round's event
                # (the pack is ONE dispatch — per-round attribution below
                # it is not observable from the host)
                disp_share = ((time.perf_counter() - t_pack0)
                              / max(len(rounds), 1))
                committed = 0
                stopped = False
                rollback_due = False
                try:
                    for j, rnd in enumerate(rounds):
                        t_round0 = time.perf_counter()
                        # Commit one round, then replay its callbacks/eval:
                        # valid scores update per committed tree, so
                        # callbacks observe the SAME per-iteration metric
                        # sequence as the per-round loop (early stopping
                        # fires at the identical iteration).
                        booster._gbdt.commit_round(rnd)
                        committed += 1
                        # fault seam: a mid-training SIGKILL lands right
                        # after a commit, the worst legal place for a crash
                        faults.maybe_kill(it + j + 1)
                        rollback_due = _health_check(it + j + 1)
                        stopped = (not rollback_due) and _fire_after(it + j)
                        _emit_iter(it + j + 1, disp_share,
                                   time.perf_counter() - t_round0,
                                   len(rounds), None)
                        if rollback_due or stopped:
                            break
                finally:
                    # Uncommitted rounds were trained inside the same
                    # dispatch but never observed (mid-pack early stop, a
                    # tripped sentinel, or a callback raising) — drop their
                    # score contributions so a caller who keeps training
                    # from this booster sees consistent state.
                    if committed < len(rounds):
                        booster._gbdt.discard_rounds(rounds[committed:])
                it += committed
                if rollback_due:
                    it = _do_rollback()
                    continue
                if (finished and not stopped and _health_check(it + 1)):
                    # a degenerate stop can BE the failure: a NaN-poisoned
                    # round grows no tree, so the trimmed stopping round's
                    # health vector (surfaced by train_pack) is checked
                    # before the stop is accepted as convergence
                    it = _do_rollback()
                    continue
                if stopped or finished:
                    break
                _maybe_checkpoint(it)
            else:
                t_round0 = time.perf_counter()
                for cb in cbs_before:
                    cb(CallbackEnv(booster, params, it, 0,
                                   num_boost_round, None))
                t_disp0 = time.perf_counter()
                finished = booster.update(fobj=fobj)
                disp_s = time.perf_counter() - t_disp0
                faults.maybe_kill(it + 1)
                if snapshot_freq > 0 and (it + 1) % snapshot_freq == 0:
                    booster.save_model(
                        f"{snapshot_base}.snapshot_iter_{it + 1}")
                if _health_check(it + 1):
                    _emit_iter(it + 1, disp_s,
                               time.perf_counter() - t_round0 - disp_s,
                               1, None)
                    it = _do_rollback()
                    continue
                stopped = _fire_after(it)
                it += 1
                ckpt_s = None
                if not (stopped or finished):
                    ckpt_s = _maybe_checkpoint(it)
                _emit_iter(it, disp_s,
                           time.perf_counter() - t_round0 - disp_s,
                           1, ckpt_s)
                if stopped or finished:
                    break
    finally:
        if sentinel is not None:
            booster._health_report = sentinel.report()
        tel.emit("train.end", iterations=int(booster._gbdt.iter_),
                 elapsed_s=round(time.perf_counter() - t_train0, 6),
                 best_iteration=int(booster.best_iteration),
                 health=(None if sentinel is None
                         else sentinel.verdict()),
                 # host-side peak RSS (telemetry/memory.py) — also
                 # published as the memory.host_peak_rss_mb gauge, the
                 # host half of the run's memory accounting
                 host_peak_rss_mb=round(telemetry_mod.host_peak_rss_mb(), 1),
                 spans=tel.span_delta())
        tel.close()
    return booster


def cv(
    params: Dict[str, Any],
    train_set: Dataset,
    num_boost_round: int = 100,
    folds=None,
    nfold: int = 5,
    stratified: bool = True,
    shuffle: bool = True,
    metrics=None,
    seed: int = 0,
    callbacks: Optional[List[Callable]] = None,
    eval_train_metric: bool = False,
    return_cv_booster: bool = False,
) -> Dict[str, List[float]]:
    """K-fold cross-validation (reference ``engine.cv:611``)."""
    params = copy.deepcopy(params)
    if metrics is not None:
        params["metric"] = metrics
    train_set.construct(params)
    X, y = train_set.data, train_set.label
    n = len(y)
    rng = np.random.RandomState(seed)

    group = train_set.group
    if folds is None and group is not None:
        # Query-aware folds: split whole queries (reference _make_n_folds
        # group handling) so ranking objectives keep their query structure.
        nq = len(group)
        bounds = np.concatenate([[0], np.cumsum(group)])
        q_idx = np.arange(nq)
        if shuffle:
            rng.shuffle(q_idx)
        q_parts = np.array_split(q_idx, nfold)
        folds = []
        for i in range(nfold):
            va_q = np.sort(q_parts[i])
            tr_q = np.sort(np.concatenate(
                [p for j, p in enumerate(q_parts) if j != i]))
            va_rows = np.concatenate([np.arange(bounds[q], bounds[q + 1])
                                      for q in va_q])
            tr_rows = np.concatenate([np.arange(bounds[q], bounds[q + 1])
                                      for q in tr_q])
            folds.append((tr_rows, va_rows, group[tr_q], group[va_q]))
        results: Dict[str, List[float]] = {}
        boosters, fold_histories = [], []
        w = train_set.weight
        for tr_idx, va_idx, tr_g, va_g in folds:
            dtr = Dataset(X[tr_idx], label=np.asarray(y)[tr_idx], group=tr_g,
                          weight=None if w is None else w[tr_idx],
                          params=params)
            dva = Dataset(X[va_idx], label=np.asarray(y)[va_idx], group=va_g,
                          weight=None if w is None else w[va_idx],
                          reference=dtr, params=params)
            history: Dict[str, Dict[str, List[float]]] = {}
            cbs = list(callbacks or []) + [callback_mod.record_evaluation(history)]
            bst = train(params, dtr, num_boost_round, valid_sets=[dva],
                        valid_names=["valid"], callbacks=cbs)
            boosters.append(bst)
            fold_histories.append(history.get("valid", {}))
        return _collect_cv(results, fold_histories, boosters,
                           return_cv_booster)

    if folds is None:
        idx = np.arange(n)
        if stratified and params.get("objective") in ("binary", "multiclass",
                                                      "multiclassova"):
            folds_idx = [[] for _ in range(nfold)]
            for cls in np.unique(y):
                cls_idx = idx[y == cls]
                if shuffle:
                    rng.shuffle(cls_idx)
                for i, part in enumerate(np.array_split(cls_idx, nfold)):
                    folds_idx[i].extend(part)
            folds = [(np.setdiff1d(idx, np.array(f)), np.array(sorted(f)))
                     for f in folds_idx]
        else:
            if shuffle:
                rng.shuffle(idx)
            parts = np.array_split(idx, nfold)
            folds = [(np.concatenate([p for j, p in enumerate(parts) if j != i]),
                      parts[i]) for i in range(nfold)]

    results: Dict[str, List[float]] = {}
    boosters = []
    fold_histories = []
    for tr_idx, va_idx in folds:
        dtr = Dataset(X[tr_idx], label=np.asarray(y)[tr_idx],
                      weight=None if train_set.weight is None
                      else train_set.weight[tr_idx],
                      params=params)
        dva = Dataset(X[va_idx], label=np.asarray(y)[va_idx],
                      weight=None if train_set.weight is None
                      else train_set.weight[va_idx],
                      reference=dtr, params=params)
        history: Dict[str, Dict[str, List[float]]] = {}
        cbs = list(callbacks or []) + [callback_mod.record_evaluation(history)]
        bst = train(params, dtr, num_boost_round, valid_sets=[dva],
                    valid_names=["valid"], callbacks=cbs)
        boosters.append(bst)
        fold_histories.append(history.get("valid", {}))

    return _collect_cv(results, fold_histories, boosters, return_cv_booster)


def _collect_cv(results, fold_histories, boosters, return_cv_booster):
    metric_names = sorted({m for h in fold_histories for m in h})
    for m in metric_names:
        rounds = min(len(h[m]) for h in fold_histories if m in h)
        vals = np.array([h[m][:rounds] for h in fold_histories if m in h])
        results[f"valid {m}-mean"] = list(vals.mean(axis=0))
        results[f"valid {m}-stdv"] = list(vals.std(axis=0))
    if return_cv_booster:
        results["cvbooster"] = boosters
    return results
