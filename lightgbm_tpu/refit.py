"""Model refit: keep every tree's structure, refit leaf values on new data.

Reference: ``GBDT::RefitTree`` (``src/boosting/gbdt.cpp:258``) +
``SerialTreeLearner::FitByExistingTree`` (``serial_tree_learner.cpp:247``):
per iteration, gradients are computed at the progressively-updated scores,
each leaf's output becomes ``decay * old + (1 - decay) * shrinkage *
CalculateSplittedLeafOutput(sum_grad, sum_hess)``.

Host-side by design: the per-tree leaf routing is a handful of vectorized
numpy traversals over the new data — refit is a one-shot model surgery, not
a training hot loop.
"""

from __future__ import annotations

import copy
from typing import TYPE_CHECKING, Callable, Tuple

import numpy as np

from .config import Config

if TYPE_CHECKING:
    from .basic import Booster


def _leaf_output_np(g: np.ndarray, h: np.ndarray, cfg: Config) -> np.ndarray:
    l1 = cfg.lambda_l1
    t = np.sign(g) * np.maximum(np.abs(g) - l1, 0.0) if l1 > 0 else g
    out = -t / (h + cfg.lambda_l2 + 1e-15)
    if cfg.max_delta_step > 0:
        out = np.clip(out, -cfg.max_delta_step, cfg.max_delta_step)
    return out


def _init_objective(objective, label, weight, group, cfg):
    if objective is None:
        raise ValueError("refit requires a built-in objective")
    objective.init(
        np.asarray(label),
        None if weight is None else np.asarray(weight, np.float32),
        None if group is None else np.asarray(group, np.int64),
        cfg)
    return objective


def _refit_pass(
    n: int, k_cls: int, n_iters: int, init_scores: np.ndarray,
    objective, cfg: Config, decay_rate: float,
    route: Callable[[int, int], Tuple[np.ndarray, int, float, np.ndarray]],
    store: Callable[[int, int, np.ndarray, np.ndarray], None],
) -> None:
    """Shared refit loop.  ``route(it, k) -> (leaf_idx, num_leaves,
    shrinkage, old_leaf_values)``; ``store(it, k, new_leaf_values,
    leaf_counts)`` writes them back.  Scores progress exactly as the
    reference's ``Boosting(); FitByExistingTree`` sequence."""
    import jax
    import jax.numpy as jnp

    scores = np.tile(np.asarray(init_scores, np.float64)[None, :k_cls],
                     (n, 1)).astype(np.float32)
    for it in range(n_iters):
        sc = scores[:, 0] if k_cls == 1 else scores
        g_dev, h_dev = objective.get_gradients(jnp.asarray(sc))
        g = np.asarray(jax.device_get(g_dev)).reshape(n, -1)
        h = np.asarray(jax.device_get(h_dev)).reshape(n, -1)
        for k in range(k_cls):
            leaf, nl, shrinkage, old = route(it, k)
            sum_g = np.bincount(leaf, weights=g[:, k], minlength=nl)
            sum_h = np.bincount(leaf, weights=h[:, k], minlength=nl) + 1e-15
            refit_val = _leaf_output_np(sum_g, sum_h, cfg) * shrinkage
            new_leaf = (decay_rate * np.asarray(old[:nl], np.float64)
                        + (1.0 - decay_rate) * refit_val)
            store(it, k, new_leaf,
                  np.bincount(leaf, minlength=nl).astype(np.float32))
            scores[:, k] += new_leaf[leaf].astype(np.float32)


def refit_loaded(model, X: np.ndarray, label: np.ndarray,
                 decay_rate: float, weight=None, group=None):
    """Refit a LoadedModel (raw-threshold trees) in place-free fashion and
    return the new LoadedModel.  Reference flow: ``Application`` task=refit —
    predict leaf indices with the loaded model, then ``GBDT::RefitTree``."""
    cfg = Config({k: v for k, v in model.params.items()})
    if model.cfg.num_class > 1:
        cfg.update({"objective": model.cfg.objective,
                    "num_class": model.cfg.num_class})
    from .objectives import create_objective
    objective = _init_objective(create_objective(cfg), label, weight, group,
                                cfg)

    if any(t.is_linear for t in model.trees):
        raise ValueError("refit of linear-tree models is not supported "
                         "(leaf linear coefficients are not refit)")
    X = np.asarray(X, np.float64)
    k_cls = model.num_class
    new_model = copy.copy(model)
    new_model.trees = [copy.copy(t) for t in model.trees]

    def route(it, k):
        tree = new_model.trees[it * k_cls + k]
        return (tree.predict_leaf(X), tree.num_leaves, tree.shrinkage,
                np.asarray(tree.leaf_value, np.float64))

    def store(it, k, new_leaf, _counts):
        tree = new_model.trees[it * k_cls + k]
        tree.leaf_value = np.asarray(tree.leaf_value, np.float64).copy()
        tree.leaf_value[: len(new_leaf)] = new_leaf

    _refit_pass(X.shape[0], k_cls, len(model.trees) // k_cls,
                model.init_scores, objective, cfg, decay_rate, route, store)
    return new_model


def refit_booster(booster: "Booster", X: np.ndarray, label: np.ndarray,
                  decay_rate: float, params: dict,
                  weight=None, group=None) -> "Booster":
    import jax.numpy as jnp

    gbdt = booster._gbdt
    if getattr(gbdt, "base_model", None) is not None:
        raise ValueError("refit of a continuation booster is not supported; "
                         "save and reload the combined model first")
    if gbdt.cfg.linear_tree:
        raise ValueError("refit of linear-tree models is not supported "
                         "(leaf linear coefficients are not refit)")
    cfg = gbdt.cfg
    binned = gbdt.train_data.binned
    bins = binned.apply(np.asarray(X))
    nan_bins = np.asarray(binned.nan_bins)
    k_cls = gbdt.num_class

    new_b = copy.copy(booster)
    new_gbdt = copy.copy(gbdt)
    new_b._gbdt = new_gbdt
    new_gbdt.dev_models = [list(m) for m in gbdt.dev_models]
    new_gbdt._host_cache = [list(m) for m in gbdt._host_cache]
    objective = _init_objective(copy.copy(gbdt.objective), label, weight,
                                group, cfg)

    def route(it, k):
        tree = copy.copy(gbdt.models[k][it])
        new_gbdt._host_cache[k][it] = tree
        return (tree.predict_leaf_bins(bins, nan_bins), tree.num_leaves,
                tree.shrinkage, np.asarray(tree.leaf_value, np.float64))

    def store(it, k, new_leaf, counts):
        tree = new_gbdt._host_cache[k][it]
        nl = len(new_leaf)
        tree.leaf_value = tree.leaf_value.copy()
        tree.leaf_value[:nl] = new_leaf
        tree.leaf_count = counts[: len(tree.leaf_count)]
        arrays = new_gbdt.dev_models[k][it]
        lv = np.zeros(arrays.leaf_value.shape[0], np.float32)
        lv[:nl] = new_leaf
        new_gbdt.dev_models[k][it] = arrays._replace(
            leaf_value=jnp.asarray(lv))

    n_iters = min(len(m) for m in gbdt.models) if gbdt.models else 0
    _refit_pass(np.asarray(X).shape[0], k_cls, n_iters, gbdt.init_scores,
                objective, cfg, decay_rate, route, store)
    return new_b
