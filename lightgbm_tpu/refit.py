"""Model refit: keep every tree's structure, refit leaf values on new data.

Reference: ``GBDT::RefitTree`` (``src/boosting/gbdt.cpp:258``) +
``SerialTreeLearner::FitByExistingTree`` (``serial_tree_learner.cpp:247``):
per iteration, gradients are computed at the progressively-updated scores,
each leaf's output becomes ``decay * old + (1 - decay) * shrinkage *
CalculateSplittedLeafOutput(sum_grad, sum_hess)``.

Host-side by design: the per-tree leaf routing is a handful of vectorized
numpy traversals over the new data — refit is a one-shot model surgery, not
a training hot loop.
"""

from __future__ import annotations

import copy
from typing import TYPE_CHECKING, Callable, Tuple

import numpy as np

from .config import Config

if TYPE_CHECKING:
    from .basic import Booster


def _leaf_output_np(g: np.ndarray, h: np.ndarray, cfg: Config) -> np.ndarray:
    l1 = cfg.lambda_l1
    t = np.sign(g) * np.maximum(np.abs(g) - l1, 0.0) if l1 > 0 else g
    out = -t / (h + cfg.lambda_l2 + 1e-15)
    if cfg.max_delta_step > 0:
        out = np.clip(out, -cfg.max_delta_step, cfg.max_delta_step)
    return out


def _init_objective(objective, label, weight, group, cfg):
    if objective is None:
        raise ValueError("refit requires a built-in objective")
    objective.init(
        np.asarray(label),
        None if weight is None else np.asarray(weight, np.float32),
        None if group is None else np.asarray(group, np.int64),
        cfg)
    return objective


def _refit_pass(
    n: int, k_cls: int, n_iters: int, init_scores: np.ndarray,
    objective, cfg: Config, decay_rate: float,
    route: Callable[[int, int], Tuple[np.ndarray, int, float, np.ndarray]],
    store: Callable[..., "np.ndarray | None"],
) -> None:
    """Shared refit loop.  ``route(it, k) -> (leaf_idx, num_leaves,
    shrinkage, old_leaf_values)``; ``store(it, k, new_leaf_values,
    leaf_counts, leaf_idx, grad_k, hess_k)`` writes them back and may
    return a per-row score contribution overriding ``new_leaf[leaf]``
    (linear trees).  Scores progress exactly as the reference's
    ``Boosting(); FitByExistingTree`` sequence."""
    import jax
    import jax.numpy as jnp

    scores = np.tile(np.asarray(init_scores, np.float64)[None, :k_cls],
                     (n, 1)).astype(np.float32)
    for it in range(n_iters):
        sc = scores[:, 0] if k_cls == 1 else scores
        g_dev, h_dev = objective.get_gradients(jnp.asarray(sc))
        g = np.asarray(jax.device_get(g_dev)).reshape(n, -1)
        h = np.asarray(jax.device_get(h_dev)).reshape(n, -1)
        for k in range(k_cls):
            leaf, nl, shrinkage, old = route(it, k)
            sum_g = np.bincount(leaf, weights=g[:, k], minlength=nl)
            sum_h = np.bincount(leaf, weights=h[:, k], minlength=nl) + 1e-15
            refit_val = _leaf_output_np(sum_g, sum_h, cfg) * shrinkage
            new_leaf = (decay_rate * np.asarray(old[:nl], np.float64)
                        + (1.0 - decay_rate) * refit_val)
            contrib = store(
                it, k, new_leaf,
                np.bincount(leaf, minlength=nl).astype(np.float32),
                leaf, g[:, k], h[:, k])
            scores[:, k] += (new_leaf[leaf] if contrib is None
                             else contrib).astype(np.float32)


def refit_loaded(model, X: np.ndarray, label: np.ndarray,
                 decay_rate: float, weight=None, group=None):
    """Refit a LoadedModel (raw-threshold trees) in place-free fashion and
    return the new LoadedModel.  Reference flow: ``Application`` task=refit —
    predict leaf indices with the loaded model, then ``GBDT::RefitTree``."""
    cfg = Config({k: v for k, v in model.params.items()})
    if model.cfg.num_class > 1:
        cfg.update({"objective": model.cfg.objective,
                    "num_class": model.cfg.num_class})
    from .objectives import create_objective
    objective = _init_objective(create_objective(cfg), label, weight, group,
                                cfg)

    X = np.asarray(X, np.float64)
    k_cls = model.num_class
    new_model = copy.copy(model)
    new_model.trees = [copy.copy(t) for t in model.trees]

    def route(it, k):
        tree = new_model.trees[it * k_cls + k]
        return (tree.predict_leaf(X), tree.num_leaves, tree.shrinkage,
                np.asarray(tree.leaf_value, np.float64))

    def store(it, k, new_leaf, _counts, leaf, gk, hk):
        tree = new_model.trees[it * k_cls + k]
        tree.leaf_value = np.asarray(tree.leaf_value, np.float64).copy()
        tree.leaf_value[: len(new_leaf)] = new_leaf
        if getattr(tree, "is_linear", False):
            from .models.linear import (predict_linear,
                                        refit_leaf_linear_models)
            refit_leaf_linear_models(tree, X, leaf, gk, hk,
                                     cfg.linear_lambda, decay_rate,
                                     tree.shrinkage)
            return predict_linear(tree, leaf, X)

    _refit_pass(X.shape[0], k_cls, len(model.trees) // k_cls,
                model.init_scores, objective, cfg, decay_rate, route, store)
    return new_model


def refit_booster(booster: "Booster", X: np.ndarray, label: np.ndarray,
                  decay_rate: float, params: dict,
                  weight=None, group=None) -> "Booster":
    import jax.numpy as jnp

    gbdt = booster._gbdt
    cfg = gbdt.cfg
    binned = gbdt.train_data.binned
    bins = binned.apply(np.asarray(X))
    nan_bins = np.asarray(binned.nan_bins)
    k_cls = gbdt.num_class

    new_b = copy.copy(booster)
    new_gbdt = copy.copy(gbdt)
    new_b._gbdt = new_gbdt
    new_gbdt.dev_models = [list(m) for m in gbdt.dev_models]
    new_gbdt._host_cache = [list(m) for m in gbdt._host_cache]
    objective = _init_objective(copy.copy(gbdt.objective), label, weight,
                                group, cfg)

    # A continuation booster refits the COMBINED ensemble — the base model's
    # trees come first, exactly as RefitTree walks every loaded model
    # (gbdt.cpp:258 iterates models_ which includes input_model trees).
    base = getattr(gbdt, "base_model", None)
    nb = base.iter_ if base is not None else 0
    init_scores = np.asarray(gbdt.init_scores, np.float64).copy()
    Xf = np.asarray(X, np.float64)
    if base is not None:
        new_base = copy.copy(base)
        new_base.trees = [copy.copy(t) for t in base.trees]
        new_gbdt.base_model = new_base
        init_scores[:k_cls] += np.asarray(base.init_scores,
                                          np.float64)[:k_cls]

    def _refit_linear(tree, leaf, gk, hk):
        from .models.linear import predict_linear, refit_leaf_linear_models
        refit_leaf_linear_models(tree, Xf, leaf, gk, hk, cfg.linear_lambda,
                                 decay_rate, tree.shrinkage)
        return predict_linear(tree, leaf, Xf)

    def route(it, k):
        if it < nb:
            tree = new_gbdt.base_model.trees[it * k_cls + k]
            return (tree.predict_leaf(Xf), tree.num_leaves, tree.shrinkage,
                    np.asarray(tree.leaf_value, np.float64))
        tree = copy.copy(gbdt.models[k][it - nb])
        new_gbdt._host_cache[k][it - nb] = tree
        return (tree.predict_leaf_bins(bins, nan_bins), tree.num_leaves,
                tree.shrinkage, np.asarray(tree.leaf_value, np.float64))

    def store(it, k, new_leaf, counts, leaf, gk, hk):
        if it < nb:
            tree = new_gbdt.base_model.trees[it * k_cls + k]
            tree.leaf_value = np.asarray(tree.leaf_value, np.float64).copy()
            tree.leaf_value[: len(new_leaf)] = new_leaf
            if getattr(tree, "is_linear", False):
                return _refit_linear(tree, leaf, gk, hk)
            return None
        tree = new_gbdt._host_cache[k][it - nb]
        nl = len(new_leaf)
        tree.leaf_value = tree.leaf_value.copy()
        tree.leaf_value[:nl] = new_leaf
        tree.leaf_count = counts[: len(tree.leaf_count)]
        arrays = new_gbdt.dev_models[k][it - nb]
        lv = np.zeros(arrays.leaf_value.shape[0], np.float32)
        lv[:nl] = new_leaf
        new_gbdt.dev_models[k][it - nb] = arrays._replace(
            leaf_value=jnp.asarray(lv))
        if tree.is_linear:
            return _refit_linear(tree, leaf, gk, hk)
        return None

    n_iters = min(len(m) for m in gbdt.models) if gbdt.models else 0
    _refit_pass(np.asarray(X).shape[0], k_cls, nb + n_iters, init_scores,
                objective, cfg, decay_rate, route, store)
    return new_b
