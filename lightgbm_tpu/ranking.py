"""Learning-to-rank objectives: LambdaRank NDCG and RankXENDCG.

Reference: ``src/objective/rank_objective.hpp:459`` — per-query pairwise lambda
gradients with delta-NDCG weighting, truncation at ``lambdarank_truncation_level``,
optional normalization; CUDA analog ``cuda_rank_objective.cu`` (per-query kernels).

TPU re-design: queries are padded to a common ``(Q, S)`` doc matrix once at init
(host side), and the per-iteration gradient is ONE fused XLA program: an in-query
argsort ranks documents, the truncated pair set is materialized as a dense
``(Q, T, S)`` tensor with masking, and lambdas scatter back to flat doc order via
a segment-sum.  No per-query loops, no dynamic shapes.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from .config import Config
from .objectives import ObjectiveFunction, register_objective


def default_label_gain(max_label: int = 31) -> np.ndarray:
    """2^i - 1 (reference config.cpp default label_gain)."""
    return (np.power(2.0, np.arange(max_label + 1)) - 1.0).astype(np.float64)


def _pad_queries(group: np.ndarray):
    """Group sizes -> (doc_idx (Q,S) int32 padded -1, boundaries)."""
    sizes = np.asarray(group, np.int64)
    q = len(sizes)
    s = int(sizes.max()) if q else 0
    bounds = np.concatenate([[0], np.cumsum(sizes)])
    doc_idx = np.full((q, s), -1, np.int64)
    for i in range(q):
        doc_idx[i, : sizes[i]] = np.arange(bounds[i], bounds[i + 1])
    return doc_idx, bounds


class LambdaRankNDCG(ObjectiveFunction):
    """Pairwise LambdaRank with delta-NDCG weights (reference
    ``LambdarankNDCG::GetGradientsForOneQuery``)."""

    def __init__(self):
        super().__init__(name="lambdarank")

    def init(self, label, weight, group, cfg: Config, position=None):
        super().init(label, weight, group, cfg, position)
        if group is None:
            raise ValueError("lambdarank requires query/group information")
        # Unbiased LTR (reference RankingObjective positions,
        # rank_objective.hpp:43-86,296-333): scores are adjusted by learned
        # per-position bias factors, updated each iteration with a
        # Newton-Raphson step on the accumulated lambdas/hessians.
        self.pos_ids = None
        if position is not None:
            _, pos_ids = np.unique(np.asarray(position), return_inverse=True)
            self.num_positions = int(pos_ids.max()) + 1
            self.pos_ids = jnp.asarray(pos_ids.astype(np.int32))
            self.pos_bias = jnp.zeros(self.num_positions, jnp.float32)
            self.bias_lr = cfg.learning_rate
            self.bias_reg = cfg.lambdarank_position_bias_regularization
            # bias update mutates host-side state each call: keep out of the
            # fused once-traced path (same routing as RankXENDCG's PRNG).
            self.stochastic_gradients = True
        label_np = np.asarray(label, np.float64)
        gains = (np.asarray(cfg.label_gain, np.float64)
                 if cfg.label_gain else default_label_gain())
        doc_idx, bounds = _pad_queries(group)
        q, s = doc_idx.shape
        self.trunc = min(cfg.lambdarank_truncation_level, s)
        valid = doc_idx >= 0
        lab = np.zeros((q, s), np.float64)
        lab[valid] = label_np[doc_idx[valid]]
        gain = np.where(valid, gains[np.minimum(lab.astype(np.int64),
                                                len(gains) - 1)], 0.0)
        # Ideal DCG per query at the truncation level (reference
        # DCGCalculator::CalMaxDCGAtK with lambdarank_truncation_level).
        top = np.sort(gain, axis=1)[:, ::-1]
        disc = 1.0 / np.log2(np.arange(s) + 2.0)
        max_dcg = (top[:, : self.trunc] * disc[None, : self.trunc]).sum(axis=1)
        self.inv_max_dcg = jnp.asarray(
            np.where(max_dcg > 0, 1.0 / np.maximum(max_dcg, 1e-20), 0.0),
            jnp.float32)
        self.doc_idx = jnp.asarray(doc_idx, jnp.int32)
        self.valid = jnp.asarray(valid)
        self.qgain = jnp.asarray(gain, jnp.float32)
        self.num_docs = len(label_np)
        self.sigmoid = cfg.sigmoid
        self.norm = cfg.lambdarank_norm
        self._grad_fn = self._build()

    def _build(self):
        trunc = self.trunc
        sigmoid = self.sigmoid
        norm = self.norm

        @jax.jit
        def grads(score, doc_idx, valid, qgain, inv_max_dcg):
            q, s = doc_idx.shape
            sc = jnp.where(valid, score[jnp.clip(doc_idx, 0)], -jnp.inf)
            order = jnp.argsort(-sc, axis=1)               # (Q,S) sorted slots
            rank_of = jnp.argsort(order, axis=1)           # doc slot -> rank
            disc = 1.0 / jnp.log2(jnp.arange(s, dtype=jnp.float32) + 2.0)
            doc_disc = disc[rank_of]                       # per slot discount
            # Pair tensor: i = top-`trunc` ranked docs, j = all docs.
            top_slots = order[:, :trunc]                   # (Q,T)
            gather = lambda a: jnp.take_along_axis(a, top_slots, axis=1)
            sc_i = gather(sc)                              # (Q,T)
            gain_i = gather(qgain)
            disc_i = gather(doc_disc)
            valid_i = gather(valid)
            # high/low determined by label gain comparison per pair.
            d_gain = gain_i[:, :, None] - qgain[:, None, :]       # (Q,T,S)
            d_score = sc_i[:, :, None] - sc[:, None, :]
            d_disc = jnp.abs(disc_i[:, :, None] - doc_disc[:, None, :])
            # Count each pair once (reference loops i in [0,trunc), j in
            # (i, count)): require j's rank strictly below i's, which keeps
            # cross-boundary pairs and de-duplicates in-window pairs.
            i_rank = jnp.arange(trunc, dtype=jnp.int32)[None, :, None]
            j_rank = rank_of[:, None, :]
            pair_ok = (valid_i[:, :, None] & valid[:, None, :]
                       & (jnp.abs(d_gain) > 0) & (j_rank > i_rank))
            # Orient every pair so "i" is the better-labelled doc.
            s_hl = jnp.where(d_gain > 0, d_score, -d_score)
            delta_ndcg = (jnp.abs(d_gain) * d_disc
                          * inv_max_dcg[:, None, None])
            p = 1.0 / (1.0 + jnp.exp(sigmoid * s_hl))      # P(low beats high)
            lam = -sigmoid * p * delta_ndcg                # d loss / d s_high
            hes = sigmoid * sigmoid * p * (1.0 - p) * delta_ndcg
            lam = jnp.where(pair_ok, lam, 0.0)
            hes = jnp.where(pair_ok, hes, 0.0)
            sign = jnp.where(d_gain > 0, 1.0, -1.0)
            # Accumulate on both endpoints (high gets +lam, low gets -lam).
            lam_i = jnp.sum(jnp.where(d_gain > 0, lam, -lam), axis=2)   # (Q,T)
            hes_i = jnp.sum(hes, axis=2)
            lam_j = -jnp.sum(sign * lam, axis=1)                        # (Q,S)
            hes_j = jnp.sum(hes, axis=1)
            if norm:
                # Reference normalizes per query by the accumulated
                # |lambda| over BOTH pair endpoints (``sum_lambdas -=
                # 2 * p_lambda``, rank_objective.hpp:178) — the factor is
                # log2(1 + 2S)/(2S), not log2(1 + S)/S; the halved
                # denominator over-scaled every query's lambdas and let
                # position-bias over-correction swamp the debias gain
                # (test_unbiased_lambdarank_positions).
                sum_abs = 2.0 * jnp.sum(jnp.abs(lam), axis=(1, 2)) + 1e-20
                scale = jnp.where(
                    sum_abs > 0,
                    jnp.log2(1.0 + sum_abs) / sum_abs, 1.0)[:, None]
            else:
                scale = jnp.ones((q, 1), jnp.float32)
            grad = jnp.zeros_like(score)
            hess = jnp.zeros_like(score)
            idx_top = jnp.clip(jnp.take_along_axis(doc_idx, top_slots, axis=1), 0)
            grad = grad.at[idx_top.ravel()].add((lam_i * scale).ravel())
            hess = hess.at[idx_top.ravel()].add((hes_i * scale).ravel())
            grad = grad.at[jnp.clip(doc_idx, 0).ravel()].add((lam_j * scale).ravel())
            hess = hess.at[jnp.clip(doc_idx, 0).ravel()].add((hes_j * scale).ravel())
            return grad, hess

        return grads

    def get_gradients(self, score):
        if self.pos_ids is not None:
            score = score + self.pos_bias[self.pos_ids]
        grad, hess = self._grad_fn(score, self.doc_idx, self.valid, self.qgain,
                                   self.inv_max_dcg)
        grad, hess = self._apply_weight(grad, hess)
        if self.pos_ids is not None:
            # Newton step on per-position utility derivatives
            # (rank_objective.hpp:296-331): fd_p = -sum(lambda), sd_p =
            # -sum(hessian), both L2-regularized by instance count.
            fd = -jax.ops.segment_sum(grad, self.pos_ids,
                                      num_segments=self.num_positions)
            sd = -jax.ops.segment_sum(hess, self.pos_ids,
                                      num_segments=self.num_positions)
            cnt = jax.ops.segment_sum(jnp.ones_like(grad), self.pos_ids,
                                      num_segments=self.num_positions)
            fd = fd - self.pos_bias * self.bias_reg * cnt
            sd = sd - self.bias_reg * cnt
            self.pos_bias = self.pos_bias + self.bias_lr * fd / (
                jnp.abs(sd) + 0.001)
        return grad, hess

    def mutable_state(self) -> dict:
        # the position-bias vector advances every iteration; a resume that
        # reset it would re-learn the bias and diverge from the
        # uninterrupted run's trees
        if self.pos_ids is None:
            return {}
        return {"pos_bias": np.asarray(jax.device_get(self.pos_bias))}

    def set_mutable_state(self, state: dict) -> None:
        if self.pos_ids is not None and "pos_bias" in state:
            self.pos_bias = jnp.asarray(state["pos_bias"])


class RankXENDCG(ObjectiveFunction):
    """Listwise XE-NDCG (reference ``RankXENDCG``): per-query softmax cross
    entropy against gain-derived targets perturbed by fresh uniform gammas each
    iteration."""

    # The host-side PRNG key advance in get_gradients must run eagerly every
    # iteration — jit-wrapping would freeze the gammas at trace time.
    stochastic_gradients = True

    def __init__(self):
        super().__init__(name="rank_xendcg")

    def init(self, label, weight, group, cfg: Config, position=None):
        super().init(label, weight, group, cfg, position)
        if group is None:
            raise ValueError("rank_xendcg requires query/group information")
        doc_idx, _ = _pad_queries(group)
        self.doc_idx = jnp.asarray(doc_idx, jnp.int32)
        self.valid = jnp.asarray(doc_idx >= 0)
        label_np = np.asarray(label, np.float64)
        q, s = doc_idx.shape
        lab = np.zeros((q, s), np.float64)
        lab[doc_idx >= 0] = label_np[doc_idx[doc_idx >= 0]]
        self.phi_base = jnp.asarray(np.power(2.0, lab) - 1.0, jnp.float32)
        self.key = jax.random.PRNGKey(cfg.objective_seed)

    def get_gradients(self, score):
        self.key, sub = jax.random.split(self.key)
        gammas = jax.random.uniform(sub, self.phi_base.shape)
        grad, hess = _xendcg_grads(score, gammas, self.doc_idx, self.valid,
                                   self.phi_base)
        return grad, hess

    def mutable_state(self) -> dict:
        # the gamma stream splits off this key each iteration; resume must
        # continue the SAME stream, not restart it at objective_seed
        return {"key": np.asarray(jax.device_get(self.key))}

    def set_mutable_state(self, state: dict) -> None:
        if "key" in state:
            self.key = jnp.asarray(state["key"])


@jax.jit
def _xendcg_grads(score, gammas, doc_idx, valid, phi_base):
    sc = jnp.where(valid, score[jnp.clip(doc_idx, 0)], -jnp.inf)
    rho = jax.nn.softmax(sc, axis=1)
    rho = jnp.where(valid, rho, 0.0)
    phi = jnp.where(valid, phi_base - gammas, 0.0)
    phi_sum = jnp.sum(phi, axis=1, keepdims=True)
    p = jnp.where(phi_sum > 0, phi / jnp.maximum(phi_sum, 1e-20), 0.0)
    lam = rho - p
    hes = jnp.maximum(rho * (1.0 - rho), 1e-16)
    grad = jnp.zeros_like(score)
    hess = jnp.zeros_like(score)
    flat_idx = jnp.clip(doc_idx, 0).ravel()
    grad = grad.at[flat_idx].add(jnp.where(valid, lam, 0.0).ravel())
    hess = hess.at[flat_idx].add(jnp.where(valid, hes, 0.0).ravel())
    return grad, hess


register_objective("lambdarank", LambdaRankNDCG)
register_objective("rank_xendcg", RankXENDCG)
