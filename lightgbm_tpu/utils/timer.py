"""Hierarchical wall-clock timers + device-trace integration.

Reference: ``Common::Timer``/``FunctionTimer`` RAII spans aggregated per name and
printed at exit under ``USE_TIMETAG`` (``utils/common.h:973-1057``; global
instance ``src/boosting/gbdt.cpp:22``).

TPU addition: named spans also open ``jax.profiler.TraceAnnotation`` regions so
the same span set shows up in TPU profiler traces (the reference's hand
instrumentation of hot paths, e.g. ``serial_tree_learner.cpp:180``)."""

from __future__ import annotations

import atexit
import collections
import os
import time
from typing import Dict, Optional


class Timer:
    def __init__(self):
        self.durations: Dict[str, float] = collections.defaultdict(float)
        self.counts: Dict[str, int] = collections.defaultdict(int)
        self._starts: Dict[str, float] = {}

    def start(self, name: str) -> None:
        self._starts[name] = time.perf_counter()

    def stop(self, name: str) -> None:
        if name in self._starts:
            self.durations[name] += time.perf_counter() - self._starts.pop(name)
            self.counts[name] += 1

    def summary(self) -> str:
        lines = ["LightGBM-TPU timer summary:"]
        for name in sorted(self.durations, key=lambda n: -self.durations[n]):
            lines.append(f"  {name}: {self.durations[name]:.3f}s "
                         f"(x{self.counts[name]})")
        return "\n".join(lines)

    def print_at_exit(self) -> None:
        atexit.register(lambda: print(self.summary()))


global_timer = Timer()
if os.environ.get("LGBM_TPU_TIMETAG"):
    global_timer.print_at_exit()


class FunctionTimer:
    """Context-manager span: host timer + device trace annotation."""

    def __init__(self, name: str, timer: Optional[Timer] = None):
        self.name = name
        self.timer = timer or global_timer
        self._trace = None

    def __enter__(self):
        self.timer.start(self.name)
        try:
            import jax.profiler
            self._trace = jax.profiler.TraceAnnotation(self.name)
            self._trace.__enter__()
        except Exception:
            self._trace = None
        return self

    def __exit__(self, *exc):
        if self._trace is not None:
            self._trace.__exit__(*exc)
        self.timer.stop(self.name)
        return False
