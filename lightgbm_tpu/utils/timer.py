"""Hierarchical wall-clock timers + device-trace integration.

Reference: ``Common::Timer``/``FunctionTimer`` RAII spans aggregated per name and
printed at exit under ``USE_TIMETAG`` (``utils/common.h:973-1057``; global
instance ``src/boosting/gbdt.cpp:22``).

TPU addition: named spans also open ``jax.profiler.TraceAnnotation`` regions so
the same span set shows up in TPU profiler traces (the reference's hand
instrumentation of hot paths, e.g. ``serial_tree_learner.cpp:180``).

Thread-safety: concurrent serve threads (MicroBatcher worker + caller
threads) time spans on the SAME instance, so every mutation is
lock-guarded and in-flight starts are tracked per ``(thread, name)`` as a
STACK — nested same-name spans on one thread are re-entrancy-safe (each
``stop`` closes the innermost matching ``start``)."""

from __future__ import annotations

import atexit
import collections
import os
import threading
import time
from typing import Dict, List, Optional, Tuple


class Timer:
    def __init__(self):
        self._lock = threading.Lock()
        self.durations: Dict[str, float] = collections.defaultdict(float)
        self.counts: Dict[str, int] = collections.defaultdict(int)
        # (thread ident, name) -> stack of perf_counter starts
        self._starts: Dict[Tuple[int, str], List[float]] = {}

    def start(self, name: str) -> None:
        t = time.perf_counter()
        key = (threading.get_ident(), name)
        with self._lock:
            self._starts.setdefault(key, []).append(t)

    def stop(self, name: str) -> None:
        t = time.perf_counter()
        key = (threading.get_ident(), name)
        with self._lock:
            stack = self._starts.get(key)
            if not stack:
                return   # unmatched stop (or a different thread's start)
            t0 = stack.pop()
            if not stack:
                del self._starts[key]
            self.durations[name] += t - t0
            self.counts[name] += 1

    def add(self, name: str, seconds: float) -> None:
        """Aggregate an externally-measured duration (telemetry spans)."""
        with self._lock:
            self.durations[name] += float(seconds)
            self.counts[name] += 1

    def snapshot(self) -> List[Tuple[str, float, int]]:
        """``(name, total_seconds, count)`` rows, longest first."""
        with self._lock:
            return sorted(((n, self.durations[n], self.counts[n])
                           for n in self.durations),
                          key=lambda row: -row[1])

    def reset(self) -> None:
        with self._lock:
            self.durations.clear()
            self.counts.clear()
            self._starts.clear()

    def summary(self) -> str:
        lines = ["LightGBM-TPU timer summary:"]
        for name, secs, cnt in self.snapshot():
            lines.append(f"  {name}: {secs:.3f}s (x{cnt})")
        return "\n".join(lines)

    def print_at_exit(self) -> None:
        # Through Log (stderr / the registered callback), never raw
        # stdout: the atexit summary must not corrupt parseable CLI or
        # bench JSON output.
        def _emit():
            from .log import Log
            Log.info(self.summary())
        atexit.register(_emit)


global_timer = Timer()
if os.environ.get("LGBM_TPU_TIMETAG"):
    global_timer.print_at_exit()


class FunctionTimer:
    """Context-manager span: host timer + device trace annotation."""

    def __init__(self, name: str, timer: Optional[Timer] = None):
        self.name = name
        self.timer = timer or global_timer
        self._trace = None

    def __enter__(self):
        self.timer.start(self.name)
        try:
            import jax.profiler
            self._trace = jax.profiler.TraceAnnotation(self.name)
            self._trace.__enter__()
        except Exception:
            self._trace = None
        return self

    def __exit__(self, *exc):
        if self._trace is not None:
            self._trace.__exit__(*exc)
        self.timer.stop(self.name)
        return False
