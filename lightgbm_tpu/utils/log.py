"""Leveled logging with a redirectable callback.

Reference: ``include/LightGBM/utils/log.h:88`` — Fatal/Warning/Info/Debug levels,
``Log::ResetCallBack`` used by the Python/R bindings to reroute output
(``LGBM_RegisterLogCallback``, ``c_api.h:73``).
"""

from __future__ import annotations

import sys
from typing import Callable, Optional

FATAL, WARNING, INFO, DEBUG = -1, 0, 1, 2


class Log:
    level: int = INFO
    _callback: Optional[Callable[[str], None]] = None

    @classmethod
    def reset_callback(cls, callback: Optional[Callable[[str], None]]) -> None:
        cls._callback = callback

    @classmethod
    def set_level(cls, level: int) -> None:
        cls.level = level

    @classmethod
    def _write(cls, level_str: str, msg: str) -> None:
        text = f"[LightGBM-TPU] [{level_str}] {msg}\n"
        if cls._callback is not None:
            cls._callback(text)
        else:
            sys.stderr.write(text)

    @classmethod
    def debug(cls, msg: str) -> None:
        if cls.level >= DEBUG:
            cls._write("Debug", msg)

    @classmethod
    def info(cls, msg: str) -> None:
        if cls.level >= INFO:
            cls._write("Info", msg)

    @classmethod
    def warning(cls, msg: str) -> None:
        if cls.level >= WARNING:
            cls._write("Warning", msg)

    @classmethod
    def fatal(cls, msg: str) -> None:
        cls._write("Fatal", msg)
        raise RuntimeError(msg)


def register_log_callback(callback: Optional[Callable[[str], None]]) -> None:
    """reference ``LGBM_RegisterLogCallback`` (``c_api.h:73``)."""
    Log.reset_callback(callback)
