from .log import Log, register_log_callback  # noqa: F401
from .timer import FunctionTimer, Timer, global_timer  # noqa: F401
