"""Force JAX onto the hermetic CPU platform with N virtual devices.

Package home of the helper (the repo-root ``_hermetic`` shim re-exports
it for tests/bench): sharding code is exercised on virtual CPU devices,
no accelerator required — the reference's localhost mock-cluster pattern
(``tests/distributed/_test_distributed.py:168-196``).

Two layers of override are needed because an environment PJRT boot hook
(sitecustomize) may force-set ``jax_platforms`` to an accelerator: env
vars (read by XLA at backend init) AND a ``jax.config.update`` after
import (beats the hook's config write).
"""

import os
import re

_COUNT_RE = re.compile(r"--xla_force_host_platform_device_count=\d+")


def cpu_env(n_devices, env=None):
    """Env-var dict forcing ``n_devices`` virtual CPU devices.

    Pure (never imports jax) so a watchdog parent process can build a
    child environment without touching the accelerator stack.  Replaces
    any existing device-count flag instead of skipping, so an inherited
    XLA_FLAGS value cannot pin the count to a stale number.
    """
    env = dict(os.environ if env is None else env)
    env["JAX_PLATFORMS"] = "cpu"
    flag = f"--xla_force_host_platform_device_count={n_devices}"
    flags = env.get("XLA_FLAGS", "")
    flags = _COUNT_RE.sub(flag, flags) if _COUNT_RE.search(flags) \
        else (flags + " " + flag).strip()
    env["XLA_FLAGS"] = flags
    return env


def force_cpu(n_devices):
    """Force THIS process onto the hermetic CPU platform; returns jax.

    Must run before jax's backend initializes (XLA_FLAGS is read exactly
    once at backend init); importing jax beforehand is fine.
    """
    for key, val in cpu_env(n_devices).items():
        os.environ[key] = val

    import jax

    jax.config.update("jax_platforms", "cpu")
    return jax
