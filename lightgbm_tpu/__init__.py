"""lightgbm_tpu — a TPU-native gradient boosting framework.

A from-scratch re-design of LightGBM's capabilities (reference: h2oai/LightGBM)
for TPU hardware: histogram GBDT/DART/RF with a fully device-resident training
loop expressed as XLA programs (one-hot MXU histogram contractions, vectorized
split scans, static-shape leaf-wise growth), data/feature-parallel scaling via
``jax.sharding`` meshes, and a lightgbm-compatible Python API.
"""

import os as _os

if _os.environ.get("LIGHTGBM_TPU_PLATFORM"):
    # Honor an explicit platform override (e.g. cpu for hermetic CI) even when
    # a PJRT plugin boot hook has force-set jax_platforms.
    import jax as _jax

    _jax.config.update("jax_platforms", _os.environ["LIGHTGBM_TPU_PLATFORM"])

from .basic import Booster, Dataset, Sequence
from .callback import EarlyStopException, early_stopping, log_evaluation, \
    record_evaluation, reset_parameter
from .config import Config
from .engine import cv, train

__version__ = "0.1.0"

__all__ = [
    "Booster", "Dataset", "Config", "train", "cv",
    "early_stopping", "log_evaluation", "record_evaluation",
    "reset_parameter", "EarlyStopException",
    "LGBMModel", "LGBMClassifier", "LGBMRegressor", "LGBMRanker",
    "plot_importance", "plot_metric", "plot_split_value_histogram",
    "plot_tree", "create_tree_digraph",
    "Sequence",
]

_PLOT_FNS = ("plot_importance", "plot_metric", "plot_split_value_histogram",
             "plot_tree", "create_tree_digraph")


def __getattr__(name):
    # sklearn wrappers / plotting / serving are imported lazily to keep the
    # base import light.
    if name in ("serve", "stream"):
        # importlib (not ``from . import``): the fromlist machinery would
        # re-enter this __getattr__ and recurse.
        import importlib
        return importlib.import_module(f".{name}", __name__)
    if name in ("LGBMModel", "LGBMClassifier", "LGBMRegressor", "LGBMRanker"):
        from . import sklearn as _sk
        return getattr(_sk, name)
    if name in _PLOT_FNS:
        from . import plotting as _pl
        return getattr(_pl, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
