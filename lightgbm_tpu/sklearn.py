"""scikit-learn compatible estimator wrappers.

Reference: ``python-package/lightgbm/sklearn.py`` (``LGBMModel:486`` +
Classifier/Regressor/Ranker subclasses) — same constructor surface and
fit/predict semantics over the :mod:`engine` layer.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Union

import numpy as np

from . import callback as callback_mod
from .basic import Booster, Dataset
from .engine import train


class LGBMModel:
    def __init__(
        self,
        boosting_type: str = "gbdt",
        num_leaves: int = 31,
        max_depth: int = -1,
        learning_rate: float = 0.1,
        n_estimators: int = 100,
        subsample_for_bin: int = 200000,
        objective: Optional[str] = None,
        class_weight=None,
        min_split_gain: float = 0.0,
        min_child_weight: float = 1e-3,
        min_child_samples: int = 20,
        subsample: float = 1.0,
        subsample_freq: int = 0,
        colsample_bytree: float = 1.0,
        reg_alpha: float = 0.0,
        reg_lambda: float = 0.0,
        random_state: Optional[int] = None,
        n_jobs: Optional[int] = None,
        importance_type: str = "split",
        **kwargs: Any,
    ):
        self.boosting_type = boosting_type
        self.num_leaves = num_leaves
        self.max_depth = max_depth
        self.learning_rate = learning_rate
        self.n_estimators = n_estimators
        self.subsample_for_bin = subsample_for_bin
        self.objective = objective
        self.class_weight = class_weight
        self.min_split_gain = min_split_gain
        self.min_child_weight = min_child_weight
        self.min_child_samples = min_child_samples
        self.subsample = subsample
        self.subsample_freq = subsample_freq
        self.colsample_bytree = colsample_bytree
        self.reg_alpha = reg_alpha
        self.reg_lambda = reg_lambda
        self.random_state = random_state
        self.n_jobs = n_jobs
        self.importance_type = importance_type
        self._other_params = dict(kwargs)
        self._Booster: Optional[Booster] = None
        self._n_classes: Optional[int] = None
        self._classes: Optional[np.ndarray] = None

    # -------------------------------------------------------- sklearn protocol
    def get_params(self, deep: bool = True) -> Dict[str, Any]:
        params = {
            "boosting_type": self.boosting_type,
            "num_leaves": self.num_leaves,
            "max_depth": self.max_depth,
            "learning_rate": self.learning_rate,
            "n_estimators": self.n_estimators,
            "subsample_for_bin": self.subsample_for_bin,
            "objective": self.objective,
            "class_weight": self.class_weight,
            "min_split_gain": self.min_split_gain,
            "min_child_weight": self.min_child_weight,
            "min_child_samples": self.min_child_samples,
            "subsample": self.subsample,
            "subsample_freq": self.subsample_freq,
            "colsample_bytree": self.colsample_bytree,
            "reg_alpha": self.reg_alpha,
            "reg_lambda": self.reg_lambda,
            "random_state": self.random_state,
            "n_jobs": self.n_jobs,
            "importance_type": self.importance_type,
        }
        params.update(self._other_params)
        return params

    def set_params(self, **params: Any) -> "LGBMModel":
        for key, value in params.items():
            if hasattr(self, key):
                setattr(self, key, value)
            else:
                self._other_params[key] = value
        return self

    def _default_objective(self) -> str:
        return "regression"

    def _lgb_params(self) -> Dict[str, Any]:
        extra = getattr(self, "_lgb_extra", {})
        # When the user supplied the objective through an alias kwarg
        # (application=...), the class-default "objective" key must not be
        # emitted: config alias resolution is first-write-wins with the
        # canonical key beating aliases (reference KeyAliasTransform), so
        # the filler default would silently override the user's choice.
        from .config import aliases_of
        if self.objective is None and any(
                self._other_params.get(k) is not None
                for k in aliases_of("objective")):
            objective = None
        else:
            objective = self.objective or self._default_objective()
        p = {
            "boosting": self.boosting_type,
            "objective": objective,
            "num_leaves": self.num_leaves,
            "max_depth": self.max_depth,
            "learning_rate": self.learning_rate,
            "bin_construct_sample_cnt": self.subsample_for_bin,
            "min_gain_to_split": self.min_split_gain,
            "min_sum_hessian_in_leaf": self.min_child_weight,
            "min_data_in_leaf": self.min_child_samples,
            "bagging_fraction": self.subsample,
            "bagging_freq": self.subsample_freq,
            "feature_fraction": self.colsample_bytree,
            "lambda_l1": self.reg_alpha,
            "lambda_l2": self.reg_lambda,
            "verbosity": -1,
        }
        if objective is None:
            del p["objective"]
        if self.random_state is not None:
            p["seed"] = int(self.random_state)
        p.update(self._other_params)
        p.update(extra)
        return p

    def _class_sample_weight(self, y, sample_weight):
        if self.class_weight is None:
            return sample_weight
        from sklearn.utils.class_weight import compute_sample_weight
        cw = compute_sample_weight(self.class_weight, y)
        if sample_weight is not None:
            cw = cw * np.asarray(sample_weight)
        return cw

    def fit(self, X, y, sample_weight=None, init_score=None, group=None,
            eval_set=None, eval_names=None, eval_sample_weight=None,
            eval_group=None, eval_metric=None, feature_name="auto",
            categorical_feature="auto", callbacks=None,
            init_model=None) -> "LGBMModel":
        params = self._lgb_params()
        if eval_metric is not None:
            params["metric"] = eval_metric
        sample_weight = self._class_sample_weight(y, sample_weight)
        ds = Dataset(X, label=y, weight=sample_weight, group=group,
                     init_score=init_score, feature_name=feature_name,
                     categorical_feature=categorical_feature, params=params)
        valid_sets: List[Dataset] = []
        valid_names: List[str] = []
        if eval_set is not None:
            for i, (ex, ey) in enumerate(eval_set):
                vw = (eval_sample_weight[i]
                      if eval_sample_weight is not None else None)
                vg = eval_group[i] if eval_group is not None else None
                valid_sets.append(Dataset(ex, label=ey, weight=vw, group=vg,
                                          reference=ds, params=params))
                valid_names.append(
                    eval_names[i] if eval_names else f"valid_{i}")
        from .callback import record_evaluation
        self._evals_result: Dict[str, Dict[str, List[float]]] = {}
        callbacks = list(callbacks) if callbacks else []
        callbacks.append(record_evaluation(self._evals_result))
        self._Booster = train(params, ds,
                              num_boost_round=self.n_estimators,
                              valid_sets=valid_sets, valid_names=valid_names,
                              callbacks=callbacks, init_model=init_model)
        self.fitted_ = True
        return self

    @property
    def evals_result_(self) -> Dict[str, Dict[str, List[float]]]:
        """Per-dataset metric curves recorded during fit (reference
        ``LGBMModel.evals_result_``)."""
        if self._Booster is None:
            raise ValueError("Model not fitted")
        return self._evals_result

    def predict(self, X, raw_score=False, start_iteration=0,
                num_iteration=None, **kwargs):
        if self._Booster is None:
            raise ValueError("Model not fitted")
        return self._Booster.predict(X, raw_score=raw_score,
                                     start_iteration=start_iteration,
                                     num_iteration=num_iteration, **kwargs)

    @property
    def booster_(self) -> Booster:
        if self._Booster is None:
            raise ValueError("Model not fitted")
        return self._Booster

    @property
    def feature_importances_(self) -> np.ndarray:
        return self.booster_.feature_importance(self.importance_type)

    @property
    def best_iteration_(self) -> int:
        return self.booster_.best_iteration

    @property
    def best_score_(self) -> Dict[str, Dict[str, float]]:
        """Best validation scores (reference ``LGBMModel.best_score_``):
        {dataset: {metric: value}} at the best (or final) iteration.  The
        recorded curves cover only THIS fit's rounds, while best_iteration
        counts any init_model base trees too — index curve-relative."""
        it = self.n_estimators_
        total = self.booster_.current_iteration

        def pick(curve):
            idx = min(it, total) - (total - len(curve))
            return curve[min(max(idx, 1), len(curve)) - 1]

        return {name: {metric: pick(curve)
                       for metric, curve in metrics.items() if curve}
                for name, metrics in self._evals_result.items()}

    @property
    def objective_(self) -> Union[str, Callable]:
        if self._Booster is None:
            raise ValueError("Model not fitted")
        if callable(self.objective):
            return self.objective
        from .config import Config
        return Config(self._lgb_params()).objective  # resolves aliases

    @property
    def n_estimators_(self) -> int:
        """Trained tree count per class (reference ``n_estimators_`` —
        reflects early stopping, unlike the ``n_estimators`` param)."""
        bst = self.booster_
        return bst.best_iteration if bst.best_iteration > 0 \
            else bst.current_iteration

    @property
    def n_iter_(self) -> int:
        return self.n_estimators_

    @property
    def n_features_(self) -> int:
        return self.booster_.num_feature()

    @property
    def n_features_in_(self) -> int:
        return self.n_features_

    @property
    def feature_name_(self) -> List[str]:
        return self.booster_.feature_name()

    @property
    def feature_names_in_(self) -> np.ndarray:
        return np.asarray(self.feature_name_)


class LGBMRegressor(LGBMModel):
    def _default_objective(self) -> str:
        return "regression"


class LGBMClassifier(LGBMModel):
    def _default_objective(self) -> str:
        return "binary" if (self._n_classes or 2) <= 2 else "multiclass"

    def fit(self, X, y, **kwargs):
        y = np.asarray(y)
        self._classes = np.unique(y)
        self._n_classes = len(self._classes)
        y_enc = np.searchsorted(self._classes, y)
        if self._n_classes > 2:
            self._other_params.setdefault("num_class", self._n_classes)
            # objective stays None: _default_objective() resolves to
            # multiclass via _n_classes, and _lgb_params' alias-suppression
            # then also honors e.g. application='multiclassova'
        if "eval_set" in kwargs and kwargs["eval_set"] is not None:
            kwargs["eval_set"] = [
                (ex, np.searchsorted(self._classes, np.asarray(ey)))
                for ex, ey in kwargs["eval_set"]]
        return super().fit(X, y_enc, **kwargs)

    @property
    def classes_(self):
        return self._classes

    @property
    def n_classes_(self):
        return self._n_classes

    def predict_proba(self, X, raw_score=False, start_iteration=0,
                      num_iteration=None, **kwargs):
        result = super().predict(X, raw_score=raw_score,
                                 start_iteration=start_iteration,
                                 num_iteration=num_iteration, **kwargs)
        if raw_score or result.ndim == 2:
            return result
        return np.column_stack([1.0 - result, result])

    def predict(self, X, raw_score=False, start_iteration=0,
                num_iteration=None, **kwargs):
        if raw_score or kwargs.get("pred_leaf") or kwargs.get("pred_contrib"):
            return super().predict(X, raw_score=raw_score,
                                   start_iteration=start_iteration,
                                   num_iteration=num_iteration, **kwargs)
        proba = self.predict_proba(X, start_iteration=start_iteration,
                                   num_iteration=num_iteration)
        return self._classes[np.argmax(proba, axis=1)]


class LGBMRanker(LGBMModel):
    def _default_objective(self) -> str:
        return "lambdarank"

    def fit(self, X, y, group=None, eval_at=(1, 2, 3, 4, 5), **kwargs):
        if group is None:
            raise ValueError("LGBMRanker.fit requires group")
        self._lgb_extra = {"eval_at": list(eval_at)}
        return super().fit(X, y, group=group, **kwargs)
