"""Row sampling strategies: bagging and GOSS.

Reference: ``SampleStrategy`` factory (``include/LightGBM/sample_strategy.h:23``,
``src/boosting/sample_strategy.cpp:14``) with ``BaggingSampleStrategy``
(``bagging.hpp``) and ``GOSSStrategy`` (``goss.hpp``).

TPU re-design: the reference materializes index subsets and copies rows
(``Dataset::CopySubrow``); here sampling is a **multiplicative row mask** so every
shape stays static under jit — out-of-bag rows contribute zero gradient/hessian
and zero count to histograms, which is numerically identical.  GOSS's amplification
``(1-top_rate)/other_rate`` becomes a per-row weight in the same mask.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from .config import Config


class SampleStrategy:
    """Produces the per-iteration row mask (1.0 in-bag, 0.0 out, >1.0 GOSS boost)."""

    def __init__(self, cfg: Config, num_data: int,
                 label: Optional[np.ndarray] = None,
                 query_boundaries: Optional[np.ndarray] = None):
        self.cfg = cfg
        self.num_data = num_data
        self.label = label
        self.query_boundaries = query_boundaries
        self.rng = np.random.RandomState(cfg.bagging_seed)
        self.is_goss = cfg.data_sample_strategy == "goss"
        balanced = (cfg.pos_bagging_fraction < 1.0
                    or cfg.neg_bagging_fraction < 1.0)
        self.is_bagging = (not self.is_goss) and (
            (cfg.bagging_fraction < 1.0 and cfg.bagging_freq > 0) or balanced)
        self.is_balanced = balanced and not self.is_goss
        self._cached: Optional[np.ndarray] = None

    def needs_resample(self, iteration: int) -> bool:
        if self.is_goss:
            return True
        if not self.is_bagging:
            return False
        freq = max(self.cfg.bagging_freq, 1)
        return iteration % freq == 0 or self._cached is None

    def mask(self, iteration: int, grad: Optional[np.ndarray] = None,
             hess: Optional[np.ndarray] = None) -> Optional[np.ndarray]:
        """Return the (N,) f32 mask for this iteration, or None (all rows)."""
        if self.is_goss:
            return self._goss_mask(grad, hess)
        if not self.is_bagging:
            return None
        if self.needs_resample(iteration):
            self._cached = self._bagging_mask()
        return self._cached

    def _bagging_mask(self) -> np.ndarray:
        cfg = self.cfg
        n = self.num_data
        mask = np.zeros(n, np.float32)
        if cfg.bagging_by_query and self.query_boundaries is not None:
            nq = len(self.query_boundaries) - 1
            take = self.rng.rand(nq) < cfg.bagging_fraction
            for qi in np.nonzero(take)[0]:
                mask[self.query_boundaries[qi]: self.query_boundaries[qi + 1]] = 1.0
            return mask
        if self.is_balanced and self.label is not None:
            pos = self.label > 0
            r = self.rng.rand(n)
            mask[(pos) & (r < cfg.pos_bagging_fraction)] = 1.0
            mask[(~pos) & (r < cfg.neg_bagging_fraction)] = 1.0
            return mask
        k = int(n * cfg.bagging_fraction)
        idx = self.rng.choice(n, size=k, replace=False)
        mask[idx] = 1.0
        return mask

    def goss_constants(self):
        """(top_k, other_k, amplification) — shared by the host and device
        GOSS paths (reference goss.hpp:30-60)."""
        cfg = self.cfg
        n = self.num_data
        top_k = max(int(n * cfg.top_rate), 1)
        other_k = int(n * cfg.other_rate)
        amp = ((1.0 - cfg.top_rate) / cfg.other_rate
               if cfg.other_rate > 0 else 0.0)
        return top_k, other_k, amp

    def _goss_mask(self, grad: np.ndarray, hess: np.ndarray) -> np.ndarray:
        """GOSS (reference ``goss.hpp:30-60``): keep the top ``top_rate`` fraction
        by |grad*hess|, sample ``other_rate`` of the rest and up-weight them."""
        cfg = self.cfg
        n = self.num_data
        score = np.abs(grad * hess)
        top_k, other_k, _amp = self.goss_constants()
        order = np.argsort(-score, kind="stable")
        mask = np.zeros(n, np.float32)
        mask[order[:top_k]] = 1.0
        rest = order[top_k:]
        if len(rest) > 0 and other_k > 0 and cfg.other_rate > 0:
            pick = self.rng.choice(len(rest), size=min(other_k, len(rest)),
                                   replace=False)
            mask[rest[pick]] = (1.0 - cfg.top_rate) / cfg.other_rate
        return mask


def goss_mask_device(grad_sum, hess_sum, key, top_k: int, other_k: int,
                     amplify: float):
    """Device-resident GOSS (reference ``goss.hpp:30-60``) — no host
    round-trip: exact top-k by |grad*hess|, gumbel-style uniform top-k for
    the random remainder, amplification folded into the mask."""
    import jax
    import jax.numpy as jnp

    n = grad_sum.shape[0]
    score = jnp.abs(grad_sum * hess_sum)
    _, top_idx = jax.lax.top_k(score, top_k)
    mask = jnp.zeros(n, jnp.float32).at[top_idx].set(1.0)
    if other_k > 0:
        u = jax.random.uniform(key, (n,))
        u = jnp.where(mask > 0.0, -1.0, u)       # exclude the top set
        sel_vals, sel_idx = jax.lax.top_k(u, other_k)
        # drop slots that fell back onto excluded rows (rest smaller than
        # other_k)
        tgt = jnp.where(sel_vals >= 0.0, sel_idx, n)
        mask = mask.at[tgt].set(jnp.float32(amplify), mode="drop")
    return mask


def _rank_select_device(u, valid, k):
    """Boolean mask keeping the k smallest draws among ``valid`` entries —
    the device analog of ``rng.choice(valid, k, replace=False)`` (exact
    subset size, matching the reference's index-subset bagging rather than
    per-row Bernoulli)."""
    import jax.numpy as jnp

    n = u.shape[0]
    u = jnp.where(valid, u, 2.0)              # invalid entries sort last
    order = jnp.argsort(u)
    rank = jnp.zeros(n, jnp.int32).at[order].set(
        jnp.arange(n, dtype=jnp.int32))
    return valid & (rank < k)


def bagging_mask_device(key, epoch, num_data: int, bag_k: int):
    """In-scan bagging row mask for the iteration-packed path: key-folded by
    the resample epoch (``iteration // bagging_freq``), so every iteration
    inside a pack derives the SAME mask its epoch demands — the device
    analog of ``SampleStrategy.mask``'s host cache, with
    ``jax.random.fold_in`` replacing the host RNG stream."""
    import jax
    import jax.numpy as jnp

    k2 = jax.random.fold_in(key, epoch)
    u = jax.random.uniform(k2, (num_data,))
    sel = _rank_select_device(u, jnp.ones(num_data, bool), bag_k)
    return sel.astype(jnp.float32)


def feature_mask_device(key, iteration, base_mask, keep_k: int):
    """In-scan per-tree ``feature_fraction`` mask (device analog of
    ``FeatureSampler.tree_mask``): keep exactly ``keep_k`` of the base-mask
    features, drawn from a key folded with the iteration number."""
    import jax

    k2 = jax.random.fold_in(key, iteration)
    u = jax.random.uniform(k2, base_mask.shape)
    return _rank_select_device(u, base_mask, keep_k)


class FeatureSampler:
    """``feature_fraction`` per tree + interaction constraints
    (reference ``ColSampler``, ``col_sampler.hpp``)."""

    def __init__(self, cfg: Config, num_features: int):
        self.cfg = cfg
        self.num_features = num_features
        self.rng = np.random.RandomState(cfg.feature_fraction_seed)
        self.used = np.ones(num_features, bool)
        # Interaction constraint groups (reference ColSampler ctor,
        # col_sampler.hpp:27-30).  The per-BRANCH narrowing (a node may only
        # split on its branch features plus groups containing the whole
        # branch) lives in the grower; here the tree-level mask is the union
        # of all groups, which equals the root's allowed set.
        self.interaction_groups = None
        if cfg.interaction_constraints:
            groups = []
            for grp in cfg.interaction_constraints:
                ids = tuple(int(tok) for tok in str(grp).strip("[] ").split(",")
                            if tok.strip())
                if ids:
                    groups.append(ids)
            if groups:
                self.interaction_groups = tuple(groups)
                allowed = sorted({i for g in groups for i in g})
                self.used = np.zeros(num_features, bool)
                self.used[allowed] = True

    def tree_mask(self, iteration: int) -> np.ndarray:
        frac = self.cfg.feature_fraction
        base = self.used.copy()
        if frac >= 1.0:
            return base
        valid = np.nonzero(base)[0]
        k = max(int(np.ceil(len(valid) * frac)), 1)
        pick = self.rng.choice(valid, size=k, replace=False)
        mask = np.zeros(self.num_features, bool)
        mask[pick] = True
        return mask
