"""Internal training dataset: binned features + metadata, device-resident.

Reference: ``Dataset``/``Metadata`` (``include/LightGBM/dataset.h:487,~80``).  The
reference stores per-group ``Bin`` columns with EFB bundling for CPU cache
behavior; on TPU the natural layout is one dense (N, F) uint8/uint16 HBM array
(rows × features), which feeds both the histogram contraction and the partition
predicate directly.  Metadata (label/weight/group/init_score) mirrors
``src/io/metadata.cpp``.
"""

from __future__ import annotations

import dataclasses
import os
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from .binning import BinnedData, bin_dataset
from .config import Config


def query_boundaries(group) -> Optional[np.ndarray]:
    """Per-query sizes -> cumulative boundaries (len num_queries+1), the
    reference's ``Metadata::query_boundaries_`` layout."""
    if group is None:
        return None
    return np.concatenate([[0], np.cumsum(group)])


@dataclasses.dataclass
class TrainData:
    """Device-ready dataset (reference ``Dataset`` + ``CUDARowData``)."""

    binned: BinnedData
    label: np.ndarray
    weight: Optional[np.ndarray] = None
    group: Optional[np.ndarray] = None          # query sizes (ranking)
    position: Optional[np.ndarray] = None       # per-row position ids
                                                # (unbiased LTR)
    init_score: Optional[np.ndarray] = None
    feature_names: Optional[List[str]] = None
    monotone_constraints: Optional[np.ndarray] = None
    raw: Optional[np.ndarray] = None     # raw values (kept for linear trees)
    # EFB (reference FeatureGroup/FindGroups): bundled column matrix used by
    # the grower's histogram/partition hot path; built lazily on demand.
    bundles: Optional[object] = None
    _bundles_key: Optional[tuple] = None
    # device arrays (lazily uploaded)
    _bins_dev: Optional[jnp.ndarray] = None
    _bundled_bins_dev: Optional[jnp.ndarray] = None
    _meta_dev: Optional[dict] = None

    @classmethod
    def build(
        cls,
        X: np.ndarray,
        label: np.ndarray,
        cfg: Config,
        *,
        weight: Optional[np.ndarray] = None,
        group: Optional[np.ndarray] = None,
        position: Optional[np.ndarray] = None,
        init_score: Optional[np.ndarray] = None,
        categorical_features: Sequence[int] = (),
        feature_names: Optional[List[str]] = None,
        reference: Optional["TrainData"] = None,
    ) -> "TrainData":
        from .binning import _is_sparse
        if not _is_sparse(X):
            X = np.asarray(X)
        # Ingestion validation (docs/ROBUSTNESS.md; reference
        # Metadata::CheckOrPartition + per-objective CheckLabel): a single
        # NaN label poisons every gradient and only shows up as a garbage
        # model hours later — reject at the door with a clear error.
        label_arr = np.asarray(label, np.float64).ravel()
        if label_arr.size and not np.isfinite(label_arr).all():
            bad = np.nonzero(~np.isfinite(label_arr))[0]
            raise ValueError(
                f"{bad.size} non-finite label(s) (first at rows "
                f"{bad[:8].tolist()}); labels must be finite")
        if weight is not None:
            w_arr = np.asarray(weight, np.float64).ravel()
            if w_arr.size and not np.isfinite(w_arr).all():
                bad = np.nonzero(~np.isfinite(w_arr))[0]
                raise ValueError(
                    f"{bad.size} non-finite sample weight(s) (first at "
                    f"rows {bad[:8].tolist()}); weights must be finite")
        if reference is not None:
            binned = dataclasses.replace(
                reference.binned, bins=reference.binned.apply(X))
        else:
            from .binning import load_forced_bins
            binned = bin_dataset(
                X,
                max_bin=cfg.max_bin,
                min_data_in_bin=cfg.min_data_in_bin,
                categorical_features=categorical_features,
                use_missing=cfg.use_missing,
                zero_as_missing=cfg.zero_as_missing,
                sample_cnt=cfg.bin_construct_sample_cnt,
                random_state=cfg.data_random_seed,
                max_bin_by_feature=cfg.max_bin_by_feature,
                forced_bins=load_forced_bins(cfg.forcedbins_filename,
                                             X.shape[1],
                                             categorical_features),
            )
        mono = None
        if cfg.monotone_constraints:
            mono = np.zeros(binned.num_features, np.int32)
            mc = np.asarray(cfg.monotone_constraints, np.int32)
            mono[: len(mc)] = mc
        return cls(
            binned=binned,
            label=np.asarray(label),
            weight=None if weight is None else np.asarray(weight, np.float32),
            group=None if group is None else np.asarray(group, np.int64),
            position=None if position is None else np.asarray(position),
            init_score=None if init_score is None else np.asarray(init_score),
            feature_names=feature_names,
            monotone_constraints=mono,
            # Reference keeps raw data when linear_tree=true (Dataset
            # raw_data_); sparse raw must densify for the per-leaf solves.
            raw=(None if not cfg.linear_tree
                 else np.asarray(X.todense() if _is_sparse(X) else X,
                                 np.float64)),
        )

    @property
    def num_data(self) -> int:
        return self.binned.num_data

    @property
    def num_features(self) -> int:
        return self.binned.num_features

    def bins_device(self, sharding=None) -> jnp.ndarray:
        if self._bins_dev is None:
            arr = jnp.asarray(self.binned.bins)
            if sharding is not None:
                arr = jax.device_put(arr, sharding)
            self._bins_dev = arr
        return self._bins_dev

    def build_bundles(self, cfg: Config):
        """EFB bundling (reference FindGroups); None when data is dense or
        bundling is disabled.  Cached per TrainData."""
        key = (bool(cfg.enable_bundle), float(cfg.max_conflict_rate))
        if self._bundles_key != key:
            self._bundles_key = key
            self.bundles = None
            self._bundled_bins_dev = None
            if cfg.enable_bundle:
                from .binning import build_bundles
                self.bundles = build_bundles(
                    self.binned, max_conflict_rate=cfg.max_conflict_rate)
        return self.bundles

    def bundled_bins_device(self) -> jnp.ndarray:
        if self._bundled_bins_dev is None:
            self._bundled_bins_dev = jnp.asarray(self.bundles.bins)
        return self._bundled_bins_dev

    def feature_meta_device(self) -> dict:
        if self._meta_dev is None:
            mono = (self.monotone_constraints
                    if self.monotone_constraints is not None
                    else np.zeros(self.num_features, np.int32))
            self._meta_dev = {
                "num_bins_per_feature": jnp.asarray(
                    self.binned.num_bins_per_feature, jnp.int32),
                "nan_bins": jnp.asarray(self.binned.nan_bins, jnp.int32),
                "is_categorical": jnp.asarray(self.binned.is_categorical),
                "monotone": jnp.asarray(mono, jnp.int32),
            }
        return self._meta_dev

    def query_boundaries(self) -> Optional[np.ndarray]:
        return query_boundaries(self.group)

    # ------------------------------------------------------------ binary cache
    def save_binary(self, path: str) -> None:
        """Save the binned dataset + metadata (reference ``save_binary`` /
        ``Dataset::SaveBinaryFile`` — the fast-reload path that skips text
        parsing and bin construction)."""
        from .binning import mappers_to_arrays
        b = self.binned
        arrs = dict(
            magic=np.asarray([0x4C47424D]),  # 'LGBM'
            bins=b.bins, label=self.label,
            upper_bounds_padded=b.upper_bounds_padded,
            nan_bins=b.nan_bins,
            num_bins_per_feature=b.num_bins_per_feature,
            is_categorical=b.is_categorical,
            max_num_bins=np.asarray([b.max_num_bins]),
            **mappers_to_arrays(b.mappers),
        )
        if self.weight is not None:
            arrs["weight"] = self.weight
        if self.group is not None:
            arrs["group"] = self.group
        if self.init_score is not None:
            arrs["init_score"] = self.init_score
        if self.monotone_constraints is not None:
            arrs["monotone"] = self.monotone_constraints
        if self.feature_names:
            arrs["feature_names"] = np.asarray(self.feature_names)
        # write through a handle so numpy keeps the exact filename (no
        # forced .npz suffix)
        with open(path, "wb") as fh:
            np.savez_compressed(fh, **arrs)

    @classmethod
    def load_binary(cls, path: str) -> "TrainData":
        """Load a dataset saved by :meth:`save_binary`."""
        from .binning import BinnedData, mappers_from_arrays
        with np.load(path, allow_pickle=False) as d:
            mappers = mappers_from_arrays(d)
            binned = BinnedData(
                bins=d["bins"], mappers=mappers,
                max_num_bins=int(d["max_num_bins"][0]),
                upper_bounds_padded=d["upper_bounds_padded"],
                nan_bins=d["nan_bins"],
                num_bins_per_feature=d["num_bins_per_feature"],
                is_categorical=d["is_categorical"],
            )
            names = (list(map(str, d["feature_names"]))
                     if "feature_names" in d else None)
            return cls(
                binned=binned,
                label=d["label"],
                weight=d["weight"] if "weight" in d else None,
                group=d["group"] if "group" in d else None,
                init_score=d["init_score"] if "init_score" in d else None,
                feature_names=names,
                monotone_constraints=d["monotone"] if "monotone" in d else None,
            )


def is_binary_dataset_file(path) -> bool:
    """reference ``DatasetLoader::CheckCanLoadFromBin``."""
    if not isinstance(path, str) or not os.path.exists(path):
        return False
    try:
        with np.load(path, allow_pickle=False) as d:
            return "magic" in d and int(d["magic"][0]) == 0x4C47424D
    except Exception:  # noqa: BLE001
        return False


def load_train_data_two_round(path: str, cfg: Config, *,
                              block_lines: int = 65536) -> TrainData:
    """Two-round streaming text load (reference ``two_round=true``,
    ``DatasetLoader::LoadFromFile`` -> ``SampleTextDataFromFile``,
    ``dataset_loader.cpp:203,1022``): pass 1 reservoir-samples rows for
    the bin mappers (uniform over the WHOLE file, like the reference's
    stream sampler) and collects labels; pass 2 re-reads the file in
    chunks and bins each chunk straight into the (N, F) bin matrix.  Peak
    memory is the bin matrix + the sample + one f64 chunk — the raw
    matrix never materializes.
    """
    from .binning import BinnedData, find_bin
    from .io.parser import (_resolve_header, _side_files, iter_file_blocks,
                            position_side_file)

    sample_cnt = cfg.bin_construct_sample_cnt
    rng = np.random.RandomState(cfg.data_random_seed)
    header_names = None
    if cfg.header:
        cols, li, _ = _resolve_header(path, cfg.label_column)
        header_names = [c for i, c in enumerate(cols) if i != li]

    # ---- pass 1: count rows, collect labels + a uniform reservoir sample
    n_total = 0
    labels = []
    reservoir: Optional[np.ndarray] = None
    n_in_res = 0
    max_f = 0
    for Xb, yb in iter_file_blocks(path, cfg.label_column, cfg.header,
                                   block_lines=block_lines):
        labels.append(yb)
        nb, fb = Xb.shape
        if fb > max_f:                       # libsvm blocks can widen
            if reservoir is not None:
                reservoir = np.pad(reservoir,
                                   ((0, 0), (0, fb - reservoir.shape[1])))
            max_f = fb
        if reservoir is None:
            reservoir = np.zeros((sample_cnt, max_f))
        Xp = (np.pad(Xb, ((0, 0), (0, max_f - fb))) if fb < max_f else Xb)
        # vectorized Algorithm R: row with global index i replaces a
        # random reservoir slot with probability sample_cnt / (i + 1)
        fill = min(max(sample_cnt - n_in_res, 0), nb)
        if fill:
            reservoir[n_in_res: n_in_res + fill] = Xp[:fill]
            n_in_res += fill
        if fill < nb:
            gidx = n_total + np.arange(fill, nb)
            slots = (rng.rand(nb - fill) * (gidx + 1)).astype(np.int64)
            keep = slots < sample_cnt
            reservoir[slots[keep]] = Xp[fill:][keep]
        n_total += nb
    if n_total == 0:
        raise ValueError(f"{path!r} contains no data rows")
    sample = reservoir[:n_in_res]

    cats = []
    if cfg.categorical_feature:
        cats = [int(c) for c in str(cfg.categorical_feature).split(",")
                if str(c).strip().lstrip("-").isdigit()]
    mbf = cfg.max_bin_by_feature
    if mbf is not None and len(mbf) != max_f:
        raise ValueError(
            f"max_bin_by_feature has {len(mbf)} entries for {max_f} "
            "features (reference requires an exact match)")
    from .binning import load_forced_bins
    fbins = load_forced_bins(cfg.forcedbins_filename, max_f, cats) or {}
    mappers = [find_bin(sample[:, j],
                        int(mbf[j]) if mbf is not None else cfg.max_bin,
                        cfg.min_data_in_bin,
                        is_categorical=(j in set(cats)),
                        use_missing=cfg.use_missing,
                        zero_as_missing=cfg.zero_as_missing,
                        forced_upper_bounds=fbins.get(j))
               for j in range(max_f)]
    del sample, reservoir
    max_b = max(max(m.num_bins for m in mappers), 2)
    dtype = np.uint8 if max_b <= 256 else np.uint16

    # ---- pass 2: bin chunk-by-chunk into the final matrix
    from .binning import _bin_full_matrix
    bins = np.empty((n_total, max_f), dtype=dtype)
    r0 = 0
    for Xb, _yb in iter_file_blocks(path, cfg.label_column, cfg.header,
                                    num_features=max_f,
                                    block_lines=block_lines):
        if Xb.shape[1] < max_f:
            Xb = np.pad(Xb, ((0, 0), (0, max_f - Xb.shape[1])))
        bins[r0: r0 + Xb.shape[0]] = _bin_full_matrix(Xb, mappers, dtype)
        r0 += Xb.shape[0]

    weight, group = _side_files(path)
    mono = None
    if cfg.monotone_constraints:
        mono = np.zeros(max_f, np.int32)
        mc = np.asarray(cfg.monotone_constraints, np.int32)
        mono[: len(mc)] = mc
    td = TrainData(
        binned=BinnedData.from_prebinned(bins, mappers),
        label=np.concatenate(labels),
        weight=None if weight is None else np.asarray(weight, np.float32),
        group=None if group is None else np.asarray(group, np.int64),
        monotone_constraints=mono,
        position=position_side_file(path, expected_rows=n_total),
        feature_names=(header_names
                       if header_names and len(header_names) == max_f
                       else None),
    )
    td._two_round_loaded = True
    return td
