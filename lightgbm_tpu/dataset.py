"""Internal training dataset: binned features + metadata, device-resident.

Reference: ``Dataset``/``Metadata`` (``include/LightGBM/dataset.h:487,~80``).  The
reference stores per-group ``Bin`` columns with EFB bundling for CPU cache
behavior; on TPU the natural layout is one dense (N, F) uint8/uint16 HBM array
(rows × features), which feeds both the histogram contraction and the partition
predicate directly.  Metadata (label/weight/group/init_score) mirrors
``src/io/metadata.cpp``.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from .binning import BinnedData, bin_dataset
from .config import Config


@dataclasses.dataclass
class TrainData:
    """Device-ready dataset (reference ``Dataset`` + ``CUDARowData``)."""

    binned: BinnedData
    label: np.ndarray
    weight: Optional[np.ndarray] = None
    group: Optional[np.ndarray] = None          # query sizes (ranking)
    init_score: Optional[np.ndarray] = None
    feature_names: Optional[List[str]] = None
    monotone_constraints: Optional[np.ndarray] = None
    raw: Optional[np.ndarray] = None     # raw values (kept for linear trees)
    # device arrays (lazily uploaded)
    _bins_dev: Optional[jnp.ndarray] = None
    _meta_dev: Optional[dict] = None

    @classmethod
    def build(
        cls,
        X: np.ndarray,
        label: np.ndarray,
        cfg: Config,
        *,
        weight: Optional[np.ndarray] = None,
        group: Optional[np.ndarray] = None,
        init_score: Optional[np.ndarray] = None,
        categorical_features: Sequence[int] = (),
        feature_names: Optional[List[str]] = None,
        reference: Optional["TrainData"] = None,
    ) -> "TrainData":
        X = np.asarray(X)
        if reference is not None:
            binned = dataclasses.replace(
                reference.binned, bins=reference.binned.apply(X))
        else:
            binned = bin_dataset(
                X,
                max_bin=cfg.max_bin,
                min_data_in_bin=cfg.min_data_in_bin,
                categorical_features=categorical_features,
                use_missing=cfg.use_missing,
                zero_as_missing=cfg.zero_as_missing,
                sample_cnt=cfg.bin_construct_sample_cnt,
                random_state=cfg.data_random_seed,
            )
        mono = None
        if cfg.monotone_constraints:
            mono = np.zeros(binned.num_features, np.int32)
            mc = np.asarray(cfg.monotone_constraints, np.int32)
            mono[: len(mc)] = mc
        return cls(
            binned=binned,
            label=np.asarray(label),
            weight=None if weight is None else np.asarray(weight, np.float32),
            group=None if group is None else np.asarray(group, np.int64),
            init_score=None if init_score is None else np.asarray(init_score),
            feature_names=feature_names,
            monotone_constraints=mono,
            # Reference keeps raw data when linear_tree=true (Dataset raw_data_)
            raw=np.asarray(X, np.float64) if cfg.linear_tree else None,
        )

    @property
    def num_data(self) -> int:
        return self.binned.num_data

    @property
    def num_features(self) -> int:
        return self.binned.num_features

    def bins_device(self, sharding=None) -> jnp.ndarray:
        if self._bins_dev is None:
            arr = jnp.asarray(self.binned.bins)
            if sharding is not None:
                arr = jax.device_put(arr, sharding)
            self._bins_dev = arr
        return self._bins_dev

    def feature_meta_device(self) -> dict:
        if self._meta_dev is None:
            mono = (self.monotone_constraints
                    if self.monotone_constraints is not None
                    else np.zeros(self.num_features, np.int32))
            self._meta_dev = {
                "num_bins_per_feature": jnp.asarray(
                    self.binned.num_bins_per_feature, jnp.int32),
                "nan_bins": jnp.asarray(self.binned.nan_bins, jnp.int32),
                "is_categorical": jnp.asarray(self.binned.is_categorical),
                "monotone": jnp.asarray(mono, jnp.int32),
            }
        return self._meta_dev

    def query_boundaries(self) -> Optional[np.ndarray]:
        if self.group is None:
            return None
        return np.concatenate([[0], np.cumsum(self.group)])
