"""Best-split search over histograms.

Reference counterpart: ``FeatureHistogram::FindBestThreshold`` /
``FindBestThresholdSequentially`` (``src/treelearner/feature_histogram.hpp:165,832``)
— per-feature forward/backward scans with L1/L2 regularization, ``min_data_in_leaf``,
``min_sum_hessian_in_leaf``, ``min_gain_to_split`` and missing-value
default-direction handling; categorical one-hot splits; CUDA analog
``cuda_best_split_finder.cu``.

TPU re-design: instead of sequential per-feature scans, ALL features and ALL
thresholds are evaluated at once as cumulative sums over the padded (F, B)
histogram, with the two missing directions evaluated as two vectorized variants
(the reference's forward + backward scans).  Invalid candidates are masked to
``-inf`` and a single argmax picks the winner — this is the shape XLA/TPU wants:
no data-dependent control flow, one reduction.
"""

from __future__ import annotations

import dataclasses
from typing import NamedTuple

import jax.numpy as jnp

_EPS = 1e-15


@dataclasses.dataclass(frozen=True)
class SplitConfig:
    """Static (compile-time) split hyper-parameters."""

    lambda_l1: float = 0.0
    lambda_l2: float = 0.0
    min_data_in_leaf: int = 20
    min_sum_hessian_in_leaf: float = 1e-3
    min_gain_to_split: float = 0.0
    max_delta_step: float = 0.0
    cat_l2: float = 10.0
    cat_smooth: float = 10.0
    max_cat_threshold: int = 32
    max_cat_to_onehot: int = 4
    min_data_per_group: int = 100
    path_smooth: float = 0.0
    # Static dataset facts (set from the bin mappers) that let the compiled
    # scan skip whole candidate families.  True = "may be present" (safe).
    has_nan: bool = True
    has_categorical: bool = True
    has_monotone: bool = True
    # Cost-effective gradient boosting (reference
    # ``cost_effective_gradient_boosting.hpp:79`` DeltaGain).
    use_cegb: bool = False
    cegb_tradeoff: float = 1.0
    cegb_penalty_split: float = 0.0


class BestSplit(NamedTuple):
    """Scalar split decision (reference ``SplitInfo``, ``split_info.hpp``)."""

    gain: jnp.ndarray          # f32; -inf when no valid split
    feature: jnp.ndarray       # i32
    bin: jnp.ndarray           # i32 threshold bin (numerical: go left if bin<=t)
    default_left: jnp.ndarray  # bool: NaN direction
    is_cat: jnp.ndarray        # bool
    cat_mask: jnp.ndarray      # (B,) bool: bins going LEFT (categorical only)
    sum_grad_left: jnp.ndarray
    sum_hess_left: jnp.ndarray
    count_left: jnp.ndarray
    sum_grad_right: jnp.ndarray
    sum_hess_right: jnp.ndarray
    count_right: jnp.ndarray


def threshold_l1(s: jnp.ndarray, l1: float) -> jnp.ndarray:
    """ThresholdL1 (reference ``feature_histogram.hpp`` GetLeafGain helpers)."""
    if l1 <= 0.0:
        return s
    return jnp.sign(s) * jnp.maximum(jnp.abs(s) - l1, 0.0)


def leaf_output(g, h, cfg: SplitConfig, l2_extra: float = 0.0):
    """Optimal leaf value −ThresholdL1(G, l1)/(H + l2), with ``max_delta_step``
    clamping (reference ``CalculateSplittedLeafOutput``)."""
    out = -threshold_l1(g, cfg.lambda_l1) / (h + cfg.lambda_l2 + l2_extra + _EPS)
    if cfg.max_delta_step > 0.0:
        out = jnp.clip(out, -cfg.max_delta_step, cfg.max_delta_step)
    return out


def leaf_gain(g, h, cfg: SplitConfig, l2_extra: float = 0.0):
    t = threshold_l1(g, cfg.lambda_l1)
    return (t * t) / (h + cfg.lambda_l2 + l2_extra + _EPS)


def best_split(
    hist: jnp.ndarray,            # (F, B, 3) leaf histogram
    parent_grad: jnp.ndarray,     # scalar ΣG over the leaf (includes NaN bin)
    parent_hess: jnp.ndarray,     # scalar ΣH
    parent_count: jnp.ndarray,    # scalar rows
    *,
    num_bins_per_feature: jnp.ndarray,  # (F,) i32 (includes NaN bin if present)
    nan_bins: jnp.ndarray,              # (F,) i32; == B when feature has no NaN bin
    is_categorical: jnp.ndarray,        # (F,) bool
    monotone: jnp.ndarray | None,       # (F,) i32 in {-1,0,1} or None
    feature_mask: jnp.ndarray,          # (F,) bool (feature_fraction / interaction)
    cfg: SplitConfig,
    gain_penalty: jnp.ndarray | None = None,  # (F,) subtracted from every gain
                                              # (CEGB DeltaGain)
) -> BestSplit:
    """Evaluate every (feature, threshold, missing-direction) candidate and argmax."""
    f, b, _ = hist.shape
    G, H, C = hist[..., 0], hist[..., 1], hist[..., 2]
    biota = jnp.arange(b, dtype=jnp.int32)[None, :]
    in_feature = biota < num_bins_per_feature[:, None]
    nan_pos = biota == nan_bins[:, None]
    value_mask = in_feature & ~nan_pos

    Gv = jnp.where(value_mask, G, 0.0)
    Hv = jnp.where(value_mask, H, 0.0)
    Cv = jnp.where(value_mask, C, 0.0)
    Gn = jnp.sum(jnp.where(nan_pos, G, 0.0), axis=1)  # (F,)
    Hn = jnp.sum(jnp.where(nan_pos, H, 0.0), axis=1)
    Cn = jnp.sum(jnp.where(nan_pos, C, 0.0), axis=1)

    cumG = jnp.cumsum(Gv, axis=1)
    cumH = jnp.cumsum(Hv, axis=1)
    cumC = jnp.cumsum(Cv, axis=1)

    parent_gain = leaf_gain(parent_grad, parent_hess, cfg)
    min_count = float(max(cfg.min_data_in_leaf, 1))

    def eval_dir(GL, HL, CL):
        GR = parent_grad - GL
        HR = parent_hess - HL
        CR = parent_count - CL
        valid = (
            (CL >= min_count)
            & (CR >= min_count)
            & (HL >= cfg.min_sum_hessian_in_leaf)
            & (HR >= cfg.min_sum_hessian_in_leaf)
        )
        gain = leaf_gain(GL, HL, cfg) + leaf_gain(GR, HR, cfg) - parent_gain
        gain = jnp.where(valid & (gain > cfg.min_gain_to_split + _EPS), gain, -jnp.inf)
        return gain, (GL, HL, CL, GR, HR, CR)

    # Numerical: threshold t means "value-bin <= t goes left".
    gain_mr, stats_mr = eval_dir(cumG, cumH, cumC)                    # NaN -> right
    if cfg.has_nan:
        gain_ml, stats_ml = eval_dir(cumG + Gn[:, None], cumH + Hn[:, None],
                                     cumC + Cn[:, None])              # NaN -> left
        # Without a NaN bin both directions coincide; keep missing-right.
        has_nan = (nan_bins < b)[:, None]
        gain_ml = jnp.where(has_nan, gain_ml, -jnp.inf)
        num_gain = jnp.maximum(gain_mr, gain_ml)
        num_default_left = gain_ml > gain_mr
    else:
        stats_ml = stats_mr
        num_gain = gain_mr
        num_default_left = jnp.zeros_like(gain_mr, bool)
    num_gain = jnp.where(value_mask, num_gain, -jnp.inf)

    # Categorical one-hot: "bin == k goes left" (reference one-hot branch of
    # FindBestThreshold; uses cat_l2 in place of plain l2).
    def eval_cat(GL, HL, CL):
        GR = parent_grad - GL
        HR = parent_hess - HL
        CR = parent_count - CL
        valid = (
            (CL >= min_count) & (CR >= min_count)
            & (HL >= cfg.min_sum_hessian_in_leaf)
            & (HR >= cfg.min_sum_hessian_in_leaf)
        )
        pg = leaf_gain(parent_grad, parent_hess, cfg, l2_extra=cfg.cat_l2)
        gain = (leaf_gain(GL, HL, cfg, l2_extra=cfg.cat_l2)
                + leaf_gain(GR, HR, cfg, l2_extra=cfg.cat_l2) - pg)
        gain = jnp.where(valid & (gain > cfg.min_gain_to_split + _EPS), gain, -jnp.inf)
        return gain, (GL, HL, CL, GR, HR, CR)

    if cfg.has_categorical:
        cat_gain, cat_stats = eval_cat(G, H, C)
        cat_gain = jnp.where(in_feature, cat_gain, -jnp.inf)
        is_cat_col = is_categorical[:, None]
        gain_fb = jnp.where(is_cat_col, cat_gain, num_gain)
    else:
        cat_stats = stats_mr
        is_cat_col = jnp.zeros_like(is_categorical, bool)[:, None]
        gain_fb = num_gain

    if monotone is not None and cfg.has_monotone:
        # Basic monotone mode: reject splits whose child outputs violate the
        # direction (reference monotone_constraints.hpp BasicLeafConstraints).
        GLm = jnp.where(is_cat_col, cat_stats[0], jnp.where(num_default_left,
                        stats_ml[0], stats_mr[0]))
        HLm = jnp.where(is_cat_col, cat_stats[1], jnp.where(num_default_left,
                        stats_ml[1], stats_mr[1]))
        GRm = parent_grad - GLm
        HRm = parent_hess - HLm
        out_l = leaf_output(GLm, HLm, cfg)
        out_r = leaf_output(GRm, HRm, cfg)
        mono = monotone[:, None]
        viol = ((mono > 0) & (out_l > out_r)) | ((mono < 0) & (out_l < out_r))
        gain_fb = jnp.where(viol, -jnp.inf, gain_fb)

    if gain_penalty is not None and cfg.use_cegb:
        gain_fb = gain_fb - gain_penalty[:, None]
        # Penalized gains that drop to <= 0 are no longer worth splitting
        # (reference stops on "gain <= 0").
        gain_fb = jnp.where(gain_fb > _EPS, gain_fb, -jnp.inf)

    gain_fb = jnp.where(feature_mask[:, None], gain_fb, -jnp.inf)

    flat = jnp.argmax(gain_fb)
    bf = (flat // b).astype(jnp.int32)
    bb = (flat % b).astype(jnp.int32)
    bgain = gain_fb[bf, bb]
    bis_cat = (is_categorical[bf] if cfg.has_categorical
               else jnp.asarray(False))
    bdefault_left = jnp.where(bis_cat, False, num_default_left[bf, bb])

    def pick(stats_cat, stats_numl, stats_numr, i):
        return jnp.where(
            bis_cat, stats_cat[i][bf, bb],
            jnp.where(bdefault_left, stats_numl[i][bf, bb], stats_numr[i][bf, bb]),
        )

    GL, HL, CL, GR, HR, CR = (pick(cat_stats, stats_ml, stats_mr, i) for i in range(6))
    cat_mask = (jnp.arange(b, dtype=jnp.int32) == bb) & bis_cat

    return BestSplit(
        gain=bgain, feature=bf, bin=bb,
        default_left=bdefault_left, is_cat=bis_cat, cat_mask=cat_mask,
        sum_grad_left=GL, sum_hess_left=HL, count_left=CL,
        sum_grad_right=GR, sum_hess_right=HR, count_right=CR,
    )
