"""Best-split search over histograms.

Reference counterpart: ``FeatureHistogram::FindBestThreshold`` /
``FindBestThresholdSequentially`` (``src/treelearner/feature_histogram.hpp:165,832``)
— per-feature forward/backward scans with L1/L2 regularization, ``min_data_in_leaf``,
``min_sum_hessian_in_leaf``, ``min_gain_to_split`` and missing-value
default-direction handling; categorical one-hot splits; CUDA analog
``cuda_best_split_finder.cu``.

TPU re-design: instead of sequential per-feature scans, ALL features and ALL
thresholds are evaluated at once as cumulative sums over the padded (F, B)
histogram, with the two missing directions evaluated as two vectorized variants
(the reference's forward + backward scans).  Invalid candidates are masked to
``-inf`` and a single argmax picks the winner — this is the shape XLA/TPU wants:
no data-dependent control flow, one reduction.
"""

from __future__ import annotations

import dataclasses
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

_EPS = 1e-15


@dataclasses.dataclass(frozen=True)
class SplitConfig:
    """Static (compile-time) split hyper-parameters."""

    lambda_l1: float = 0.0
    lambda_l2: float = 0.0
    min_data_in_leaf: int = 20
    min_sum_hessian_in_leaf: float = 1e-3
    min_gain_to_split: float = 0.0
    max_delta_step: float = 0.0
    cat_l2: float = 10.0
    cat_smooth: float = 10.0
    max_cat_threshold: int = 32
    max_cat_to_onehot: int = 4
    min_data_per_group: int = 100
    path_smooth: float = 0.0
    # Monotone split-gain penalty near the root (reference
    # ComputeMonotoneSplitGainPenalty, monotone_constraints.hpp:357).
    monotone_penalty: float = 0.0
    # Per-feature split-gain multipliers (reference feature_contri /
    # config->feature_contri applied in FindBestThreshold* gain).
    feature_contri: 'Optional[Tuple[float, ...]]' = None
    # Extremely-randomized trees (reference col_sampler + USE_RAND scans):
    # when set, each (node, feature) evaluates ONE random threshold.
    extra_trees: bool = False
    # Static dataset facts (set from the bin mappers) that let the compiled
    # scan skip whole candidate families.  True = "may be present" (safe).
    has_nan: bool = True
    has_categorical: bool = True
    # Any categorical feature with num_bins > max_cat_to_onehot (enables the
    # sorted many-vs-many scan; one-hot-only datasets skip it entirely).
    use_sorted_categorical: bool = True
    has_monotone: bool = True
    # Cost-effective gradient boosting (reference
    # ``cost_effective_gradient_boosting.hpp:79`` DeltaGain).
    use_cegb: bool = False
    cegb_tradeoff: float = 1.0
    cegb_penalty_split: float = 0.0
    # Feature-block width for the scan's (F, B) cumsum/gain buffers: the
    # candidate evaluation runs per G-block through a sequential lax.map so
    # peak scan scratch stops scaling with full F (wide-feature shapes,
    # F=700/F=2000).  0 = auto (128-wide blocks once the scan width exceeds
    # 256 columns), 1 = untiled, >= 2 = explicit block width.  The winner is
    # selected with the exact tie-break order of the untiled argmax (lowest
    # flat index; sorted-categorical wins only strictly), so tiling never
    # changes the chosen split.
    scan_tile: int = 0


class BestSplit(NamedTuple):
    """Scalar split decision (reference ``SplitInfo``, ``split_info.hpp``)."""

    gain: jnp.ndarray          # f32; -inf when no valid split
    feature: jnp.ndarray       # i32
    bin: jnp.ndarray           # i32 threshold bin (numerical: go left if bin<=t)
    default_left: jnp.ndarray  # bool: NaN direction
    is_cat: jnp.ndarray        # bool
    cat_mask: jnp.ndarray      # (B,) bool: bins going LEFT (categorical only)
    sum_grad_left: jnp.ndarray
    sum_hess_left: jnp.ndarray
    count_left: jnp.ndarray
    sum_grad_right: jnp.ndarray
    sum_hess_right: jnp.ndarray
    count_right: jnp.ndarray


def sync_best_split(bs: "BestSplit", feature_offset, axis: str,
                    n_shards: int) -> "BestSplit":
    """Globalize per-shard slice-local winners (reference
    ``SyncUpGlobalBestSplit``, ``parallel_tree_learner.h`` /
    ``feature_parallel_tree_learner.cpp:59-77``).

    Each shard ran :func:`best_split` over only the feature slice it owns —
    the feature-parallel layout's sharded columns, or the data-parallel
    reduce-scatter path's owned block of the reduced histograms
    (``data_parallel_tree_learner.cpp:284``).  The winner's SplitInfo
    (scalars + categorical mask) is broadcast by a one-hot psum; LOCAL
    feature indices become GLOBAL by adding this shard's
    ``feature_offset``.  Ties break to the lowest shard, like the
    reference's rank order — for contiguous ascending feature slices that
    is exactly the replicated scan's lowest-flat-index argmax.

    Precision note: the f32 payload transports counts/sums losslessly —
    the psum has exactly one non-zero contributor per element, so the
    received value bit-equals the sender's.  Counts are f32 BEFORE the
    payload in every path (f32 histogram count channel, f32 cumsum in
    the split scan, f32 GrowthState.leaf_count; the quantized path
    converts int32→f32 before scanning), so serial and sharded share the
    same >2^24 representation limit and cannot drift apart at this sync.
    The feature index rides exactly up to 2^24 features.  Works on scalar
    or batched (vmapped) BestSplits."""
    neg_inf = -jnp.inf

    def one(gain, feature, sbin, dl, ic, cmask, gl, hl, cl, gr, hr, cr):
        win = jax.lax.pmax(gain, axis)
        sidx = jax.lax.axis_index(axis)
        is_w = (gain >= win) & (win > neg_inf)
        first = jax.lax.pmin(jnp.where(is_w, sidx, n_shards), axis)
        mine = sidx == first
        scal = jnp.stack([
            (feature + feature_offset).astype(jnp.float32),
            sbin.astype(jnp.float32), dl.astype(jnp.float32),
            ic.astype(jnp.float32), gl, hl, cl, gr, hr, cr])
        payload = jnp.concatenate([scal, cmask.astype(jnp.float32)])
        payload = jax.lax.psum(
            jnp.where(mine, payload, jnp.zeros_like(payload)), axis)
        return BestSplit(
            gain=win,
            feature=jnp.round(payload[0]).astype(jnp.int32),
            bin=jnp.round(payload[1]).astype(jnp.int32),
            default_left=payload[2] > 0.5,
            is_cat=payload[3] > 0.5,
            cat_mask=payload[10:] > 0.5,
            sum_grad_left=payload[4], sum_hess_left=payload[5],
            count_left=payload[6],
            sum_grad_right=payload[7], sum_hess_right=payload[8],
            count_right=payload[9])

    args = (bs.gain, bs.feature, bs.bin, bs.default_left, bs.is_cat,
            bs.cat_mask, bs.sum_grad_left, bs.sum_hess_left,
            bs.count_left, bs.sum_grad_right, bs.sum_hess_right,
            bs.count_right)
    if bs.gain.ndim == 0:
        return one(*args)
    return jax.vmap(one)(*args)


def threshold_l1(s: jnp.ndarray, l1: float) -> jnp.ndarray:
    """ThresholdL1 (reference ``feature_histogram.hpp`` GetLeafGain helpers)."""
    if l1 <= 0.0:
        return s
    return jnp.sign(s) * jnp.maximum(jnp.abs(s) - l1, 0.0)


def leaf_output(g, h, cfg: SplitConfig, l2_extra: float = 0.0):
    """Optimal leaf value −ThresholdL1(G, l1)/(H + l2), with ``max_delta_step``
    clamping (reference ``CalculateSplittedLeafOutput``)."""
    out = -threshold_l1(g, cfg.lambda_l1) / (h + cfg.lambda_l2 + l2_extra + _EPS)
    if cfg.max_delta_step > 0.0:
        out = jnp.clip(out, -cfg.max_delta_step, cfg.max_delta_step)
    return out


def leaf_gain(g, h, cfg: SplitConfig, l2_extra: float = 0.0):
    t = threshold_l1(g, cfg.lambda_l1)
    return (t * t) / (h + cfg.lambda_l2 + l2_extra + _EPS)


def smoothed_output(g, h, count, parent_output, cfg: SplitConfig,
                    l2_extra: float = 0.0):
    """``CalculateSplittedLeafOutput`` with path smoothing (reference
    ``feature_histogram.hpp``): ``w*(n/s)/(n/s+1) + parent/(n/s+1)``."""
    w = leaf_output(g, h, cfg, l2_extra)
    if cfg.path_smooth <= 0.0:
        return w
    ratio = count / cfg.path_smooth
    return w * ratio / (ratio + 1.0) + parent_output / (ratio + 1.0)


def gain_given_output(g, h, out, cfg: SplitConfig, l2_extra: float = 0.0):
    """``GetLeafGainGivenOutput``: ``-(2*TL1(g)*w + (h+l2)*w^2)``."""
    t = threshold_l1(g, cfg.lambda_l1)
    return -(2.0 * t * out + (h + cfg.lambda_l2 + l2_extra) * out * out)


def child_gain(g, h, count, parent_output, cfg: SplitConfig,
               l2_extra: float = 0.0, out_lo=None, out_hi=None):
    """Per-child gain; closed form without smoothing/constraints,
    output-based otherwise (reference GetSplitGains USE_SMOOTHING/USE_MC
    dispatch: outputs clipped to the leaf's monotone bounds)."""
    if cfg.path_smooth <= 0.0 and out_lo is None:
        return leaf_gain(g, h, cfg, l2_extra)
    w = smoothed_output(g, h, count, parent_output, cfg, l2_extra)
    if out_lo is not None:
        w = jnp.clip(w, out_lo, out_hi)
    return gain_given_output(g, h, w, cfg, l2_extra)


def _sorted_categorical(G, H, C, parent_grad, parent_hess, parent_count,
                        parent_output, in_feature, cfg: SplitConfig,
                        min_count: float, rand_bins=None):
    """Sorted many-vs-many categorical scan (reference
    ``FindBestThresholdCategoricalInner`` sorted branch,
    ``feature_histogram.cpp:241-340``): bins with enough data are sorted by
    ``grad/(hess+cat_smooth)``; prefixes of length <= ``max_cat_threshold``
    are scanned from both ends with ``min_data_per_group`` grouping; child
    gains use ``l2 + cat_l2``.

    Returns per-feature ``(gain, cat_mask, gl, hl, cl)``; gain is the child
    sum (the caller subtracts the parent gain shift).
    """
    f, b = G.shape
    K = min(b, max(int(cfg.max_cat_threshold), 1))
    mdpg = float(cfg.min_data_per_group)
    valid = in_feature & (C >= cfg.cat_smooth)
    ctr = G / (H + cfg.cat_smooth)
    key = jnp.where(valid, ctr, jnp.inf)
    order = jnp.argsort(key, axis=1, stable=True)              # (F, B)
    rank = jnp.argsort(order, axis=1)                          # inverse perm
    used = jnp.sum(valid, axis=1).astype(jnp.int32)            # (F,)
    vs = jnp.take_along_axis(valid, order, axis=1)
    Gs = jnp.where(vs, jnp.take_along_axis(G, order, axis=1), 0.0)
    Hs = jnp.where(vs, jnp.take_along_axis(H, order, axis=1), 0.0)
    Cs = jnp.where(vs, jnp.take_along_axis(C, order, axis=1), 0.0)
    max_num_cat = jnp.minimum(cfg.max_cat_threshold, (used + 1) // 2)
    iidx = jnp.arange(K, dtype=jnp.int32)[None, :]             # (1, K)
    rand_pos = None
    if rand_bins is not None:
        max_thr = jnp.maximum(jnp.minimum(max_num_cat, used) - 1, 0) + 1
        rand_pos = (rand_bins % max_thr)[:, None]

    def direction(Gd, Hd, Cd):
        cg = jnp.cumsum(Gd, axis=1)
        ch = jnp.cumsum(Hd, axis=1) + _EPS
        cc = jnp.cumsum(Cd, axis=1)
        pos_ok = (iidx < used[:, None]) & (iidx < max_num_cat[:, None])
        left_ok = (cc >= min_count) & (ch >= cfg.min_sum_hessian_in_leaf)
        rc = parent_count - cc
        rh = parent_hess - ch
        right_ok = ((rc >= min_count) & (rc >= mdpg)
                    & (rh >= cfg.min_sum_hessian_in_leaf))
        ok = pos_ok & left_ok & right_ok

        def step(carry, x):
            cnt_i, ok_i = x
            acc = carry + cnt_i
            cand = ok_i & (acc >= mdpg)
            return jnp.where(cand, 0.0, acc), cand

        _, emit = jax.lax.scan(step, jnp.zeros(f, cg.dtype),
                               (Cd.T, ok.T))
        emit = emit.T                                          # (F, K)
        if rand_pos is not None:
            emit = emit & (iidx == rand_pos)
        gl, hl, cl = cg, ch, cc
        gr, hr, cr = (parent_grad - gl, parent_hess - hl, parent_count - cl)
        gain = (child_gain(gl, hl, cl, parent_output, cfg, cfg.cat_l2)
                + child_gain(gr, hr, cr, parent_output, cfg, cfg.cat_l2))
        return jnp.where(emit, gain, -jnp.inf), gl, hl, cl

    gain_f, glf, hlf, clf = direction(Gs[:, :K], Hs[:, :K], Cs[:, :K])
    # Backward direction starts at the last USED position per feature.
    bidx = jnp.clip(used[:, None] - 1 - iidx, 0, b - 1)        # (F, K)
    in_back = iidx < used[:, None]
    Gb = jnp.where(in_back, jnp.take_along_axis(Gs, bidx, axis=1), 0.0)
    Hb = jnp.where(in_back, jnp.take_along_axis(Hs, bidx, axis=1), 0.0)
    Cb = jnp.where(in_back, jnp.take_along_axis(Cs, bidx, axis=1), 0.0)
    gain_b, glb, hlb, clb = direction(Gb, Hb, Cb)

    gain2 = jnp.stack([gain_f, gain_b], axis=1)                # (F, 2, K)
    flat = jnp.argmax(gain2.reshape(f, 2 * K), axis=1)
    best_dir = (flat // K).astype(jnp.int32)                   # 0 fwd, 1 bwd
    best_i = (flat % K).astype(jnp.int32)
    take = lambda a2: jnp.take_along_axis(
        a2.reshape(f, 2 * K), flat[:, None], axis=1)[:, 0]
    gain = take(gain2)
    gl = take(jnp.stack([glf, glb], axis=1))
    hl = take(jnp.stack([hlf, hlb], axis=1))
    cl = take(jnp.stack([clf, clb], axis=1))
    # cat_mask: the chosen prefix of the sorted order routes LEFT.
    fwd_mask = rank <= best_i[:, None]
    bwd_mask = rank >= (used - 1 - best_i)[:, None]
    cat_mask = valid & jnp.where((best_dir == 0)[:, None], fwd_mask, bwd_mask)
    return gain, cat_mask, gl, hl, cl


def _resolve_tile(scan_tile: int, f: int) -> int:
    """Effective G-block width for a scan over ``f`` columns (0 = untiled).
    Auto (0) engages 128-wide blocks only once the width exceeds 256 —
    narrow shapes keep the single fused scan they always had."""
    if scan_tile >= 2:
        return 0 if scan_tile >= f else scan_tile
    if scan_tile == 1:
        return 0
    return 128 if f > 256 else 0


class _ScanTables(NamedTuple):
    """Candidate tables of one (F, B) scan block — everything the argmax
    selection and the sorted-categorical merge consume.  Produced by
    :func:`scan_tables`, the half of the split scan that is callable from
    INSIDE a Pallas kernel (ops/pallas_wave.py): pure elementwise/cumsum
    arithmetic over (F, B) blocks — no argsort, no dynamic indexing."""

    gain_fb: jnp.ndarray           # (F, B) masked candidate gains
    num_default_left: jnp.ndarray  # (F, B) bool NaN direction of num. wins
    stats_mr: tuple                # 6x (F, B) child stats, NaN -> right
    stats_ml: tuple                # 6x (F, B) child stats, NaN -> left
    cat_stats: tuple               # 6x (F, B) child stats, one-hot cat.
    parent_gain: jnp.ndarray       # scalar parent gain shift
    parent_output: jnp.ndarray     # scalar resolved parent output
    in_feature: jnp.ndarray        # (F, B) bool valid-bin mask
    sorted_eligible: Optional[jnp.ndarray]  # (F, 1) sorted-cat eligibility
    penalty_col: Optional[jnp.ndarray]      # (F, 1) CEGB penalty column
    min_count: float


def _col(a):
    """Per-feature vector as an (F, 1) column.  The host paths pass (F,)
    vectors; the Pallas kernel passes (F, 1) columns (Mosaic dislikes 1D
    operands and lane-dim transposes), and broadcasting against (F, B)
    blocks is identical either way."""
    return a if a.ndim == 2 else a[:, None]


def scan_tables(
    G: jnp.ndarray,               # (F, B) grad sums (f32, scaled)
    H: jnp.ndarray,               # (F, B) hess sums
    C: jnp.ndarray,               # (F, B) counts
    parent_grad: jnp.ndarray,     # scalar ΣG over the leaf (incl. NaN bin)
    parent_hess: jnp.ndarray,     # scalar ΣH
    parent_count: jnp.ndarray,    # scalar rows
    *,
    num_bins_per_feature: jnp.ndarray,  # (F,)/(F,1) i32 (incl. NaN bin)
    nan_bins: jnp.ndarray,              # (F,)/(F,1) i32; == B when no NaN bin
    is_categorical: jnp.ndarray,        # (F,)/(F,1) bool
    feature_mask: jnp.ndarray,          # (F,)/(F,1) bool
    cfg: SplitConfig,
    monotone: jnp.ndarray | None = None,       # (F,) i32 in {-1,0,1}
    gain_penalty: jnp.ndarray | None = None,   # (F,) CEGB DeltaGain
    parent_output: jnp.ndarray | None = None,  # scalar (path_smooth anchor)
    rand_bins: jnp.ndarray | None = None,      # (F,) i32 (extra_trees)
    out_lo: jnp.ndarray | None = None,         # scalar monotone lower bound
    out_hi: jnp.ndarray | None = None,         # scalar monotone upper bound
    adv_bounds: tuple | None = None,           # advanced monotone (F, B) x4
    leaf_depth: jnp.ndarray | None = None,     # scalar (monotone_penalty)
    feature_contri: jnp.ndarray | None = None,  # (F,) f32 gain multipliers
) -> _ScanTables:
    """Evaluate every (feature, threshold, missing-direction) candidate of
    one (F, B) histogram block into masked gain/stat tables.  Phantom bins
    (``bin >= num_bins_per_feature[f]``, e.g. the fused kernel's
    lane-padded columns) are masked to ``-inf`` so a wider B never changes
    the candidate set."""
    f, b = G.shape
    nbpf_c = _col(num_bins_per_feature)
    nanb_c = _col(nan_bins)
    fmask_c = _col(feature_mask)
    biota = jax.lax.broadcasted_iota(jnp.int32, (f, b), 1)
    in_feature = biota < nbpf_c
    nan_pos = biota == nanb_c
    value_mask = in_feature & ~nan_pos
    if parent_output is None:
        parent_output = leaf_output(parent_grad, parent_hess, cfg)

    Gv = jnp.where(value_mask, G, 0.0)
    Hv = jnp.where(value_mask, H, 0.0)
    Cv = jnp.where(value_mask, C, 0.0)
    Gn = jnp.sum(jnp.where(nan_pos, G, 0.0), axis=1, keepdims=True)  # (F,1)
    Hn = jnp.sum(jnp.where(nan_pos, H, 0.0), axis=1, keepdims=True)
    Cn = jnp.sum(jnp.where(nan_pos, C, 0.0), axis=1, keepdims=True)

    cumG = jnp.cumsum(Gv, axis=1)
    cumH = jnp.cumsum(Hv, axis=1)
    cumC = jnp.cumsum(Cv, axis=1)

    # Parent gain shift: closed form without smoothing, output-based with
    # (reference BeforeNumerical / FindBestThresholdCategoricalInner).
    if cfg.path_smooth > 0.0:
        parent_gain = gain_given_output(parent_grad, parent_hess,
                                        parent_output, cfg)
    else:
        parent_gain = leaf_gain(parent_grad, parent_hess, cfg)
    min_count = float(max(cfg.min_data_in_leaf, 1))

    mono_bounds = (out_lo is not None and out_hi is not None
                   and cfg.has_monotone)
    blo = out_lo if mono_bounds else None
    bhi = out_hi if mono_bounds else None
    # Advanced monotone mode (reference AdvancedLeafConstraints,
    # monotone_constraints.hpp:583): numerical candidates clip each child to
    # its PER-THRESHOLD bound slice instead of the whole-leaf scalar;
    # categorical columns (not covered by the reference's slice machinery
    # either) fall back to the scalar leaf bounds.
    use_adv = adv_bounds is not None and cfg.has_monotone
    if use_adv:
        icc0 = _col(is_categorical)
        s_lo = blo if mono_bounds else -jnp.inf
        s_hi = bhi if mono_bounds else jnp.inf
        a_llo = jnp.where(icc0, s_lo, adv_bounds[0])
        a_lhi = jnp.where(icc0, s_hi, adv_bounds[1])
        a_rlo = jnp.where(icc0, s_lo, adv_bounds[2])
        a_rhi = jnp.where(icc0, s_hi, adv_bounds[3])
        num_lb, num_rb = (a_llo, a_lhi), (a_rlo, a_rhi)
    else:
        num_lb = num_rb = None

    def eval_dir(GL, HL, CL, l2_extra=0.0, lb=None, rb=None):
        GR = parent_grad - GL
        HR = parent_hess - HL
        CR = parent_count - CL
        valid = (
            (CL >= min_count)
            & (CR >= min_count)
            & (HL >= cfg.min_sum_hessian_in_leaf)
            & (HR >= cfg.min_sum_hessian_in_leaf)
        )
        llo, lhi = lb if lb is not None else (blo, bhi)
        rlo, rhi = rb if rb is not None else (blo, bhi)
        gain = (child_gain(GL, HL, CL, parent_output, cfg, l2_extra, llo, lhi)
                + child_gain(GR, HR, CR, parent_output, cfg, l2_extra,
                             rlo, rhi)
                - parent_gain)
        gain = jnp.where(valid & (gain > cfg.min_gain_to_split + _EPS), gain, -jnp.inf)
        return gain, (GL, HL, CL, GR, HR, CR)

    # Numerical: threshold t means "value-bin <= t goes left".
    gain_mr, stats_mr = eval_dir(cumG, cumH, cumC,
                                 lb=num_lb, rb=num_rb)                # NaN -> right
    if cfg.has_nan:
        gain_ml, stats_ml = eval_dir(cumG + Gn, cumH + Hn, cumC + Cn,
                                     lb=num_lb, rb=num_rb)            # NaN -> left
        # Without a NaN bin both directions coincide; keep missing-right.
        has_nan = nanb_c < b
        gain_ml = jnp.where(has_nan, gain_ml, -jnp.inf)
        num_gain = jnp.maximum(gain_mr, gain_ml)
        num_default_left = gain_ml > gain_mr
    else:
        stats_ml = stats_mr
        num_gain = gain_mr
        num_default_left = jnp.zeros_like(gain_mr, bool)
    num_gain = jnp.where(value_mask, num_gain, -jnp.inf)

    if cfg.has_categorical:
        # One-hot categorical: "bin == k goes left" (reference one-hot branch
        # of FindBestThresholdCategoricalInner — plain lambda_l2, not cat_l2,
        # which only applies in the sorted branch).
        cat_gain, cat_stats = eval_dir(G, H, C)
        cat_gain = jnp.where(in_feature, cat_gain, -jnp.inf)
        # Sorted features are excluded from the one-hot table; they compete
        # through the per-feature sorted scan merged by the caller.
        sorted_eligible = (_col(is_categorical)
                           & (nbpf_c > cfg.max_cat_to_onehot))
        is_cat_col = _col(is_categorical)
        gain_fb = jnp.where(is_cat_col, cat_gain, num_gain)
        gain_fb = jnp.where(sorted_eligible, -jnp.inf, gain_fb)
    else:
        cat_stats = stats_mr
        sorted_eligible = None
        is_cat_col = jnp.zeros((f, 1), bool)
        gain_fb = num_gain

    if rand_bins is not None and cfg.extra_trees:
        # extra_trees (reference USE_RAND scans): one random threshold per
        # (node, feature); all other candidates are masked out.
        gain_fb = jnp.where(biota == _col(rand_bins), gain_fb, -jnp.inf)

    if monotone is not None and cfg.has_monotone:
        # Basic monotone mode: reject splits whose child outputs violate the
        # direction (reference monotone_constraints.hpp BasicLeafConstraints).
        GLm = jnp.where(is_cat_col, cat_stats[0], jnp.where(num_default_left,
                        stats_ml[0], stats_mr[0]))
        HLm = jnp.where(is_cat_col, cat_stats[1], jnp.where(num_default_left,
                        stats_ml[1], stats_mr[1]))
        GRm = parent_grad - GLm
        HRm = parent_hess - HLm
        out_l = leaf_output(GLm, HLm, cfg)
        out_r = leaf_output(GRm, HRm, cfg)
        if use_adv:
            out_l = jnp.clip(out_l, a_llo, a_lhi)
            out_r = jnp.clip(out_r, a_rlo, a_rhi)
        elif mono_bounds:
            out_l = jnp.clip(out_l, blo, bhi)
            out_r = jnp.clip(out_r, blo, bhi)
        mono = _col(monotone)
        viol = ((mono > 0) & (out_l > out_r)) | ((mono < 0) & (out_l < out_r))
        gain_fb = jnp.where(viol, -jnp.inf, gain_fb)
        if cfg.monotone_penalty > 0.0 and leaf_depth is not None:
            # reference ComputeMonotoneSplitGainPenalty
            # (monotone_constraints.hpp:357): multiplies the gain of splits
            # on monotone features, fading with depth.
            p = cfg.monotone_penalty
            d = leaf_depth.astype(jnp.float32)
            pen = jnp.where(
                p >= d + 1.0, _EPS,
                jnp.where(p <= 1.0, 1.0 - p / (2.0 ** d) + _EPS,
                          1.0 - 2.0 ** (p - 1.0 - d) + _EPS))
            gain_fb = jnp.where(mono != 0, gain_fb * pen, gain_fb)

    penalty_col = None
    if gain_penalty is not None and cfg.use_cegb:
        penalty_col = _col(gain_penalty)
        gain_fb = gain_fb - penalty_col
        # Penalized gains that drop to <= 0 are no longer worth splitting
        # (reference stops on "gain <= 0").
        gain_fb = jnp.where(gain_fb > _EPS, gain_fb, -jnp.inf)

    if feature_contri is not None:
        scaled = gain_fb * _col(feature_contri)
        # reference stops on best gain <= 0: a zeroed-out feature must not
        # win over "no split"
        gain_fb = jnp.where(jnp.isfinite(gain_fb) & (scaled > _EPS),
                            scaled, -jnp.inf)
    gain_fb = jnp.where(fmask_c, gain_fb, -jnp.inf)

    return _ScanTables(
        gain_fb=gain_fb, num_default_left=num_default_left,
        stats_mr=stats_mr, stats_ml=stats_ml, cat_stats=cat_stats,
        parent_gain=parent_gain, parent_output=parent_output,
        in_feature=in_feature, sorted_eligible=sorted_eligible,
        penalty_col=penalty_col, min_count=min_count)


def _select_from_tables(t: _ScanTables, is_categorical, cfg: SplitConfig
                        ) -> BestSplit:
    """Argmax + winner-stat gather over the scan tables (the host half):
    lowest flat (feature, bin) index wins ties — the tie-break every other
    reducer in the framework replays.  Must stay selection-identical to
    :func:`select_payload` (the Pallas-safe one-hot variant; pinned in
    tests/test_wave_fused.py)."""
    gain_fb = t.gain_fb
    f, b = gain_fb.shape
    flat = jnp.argmax(gain_fb)
    bf = (flat // b).astype(jnp.int32)
    bb = (flat % b).astype(jnp.int32)
    bgain = gain_fb[bf, bb]
    bis_cat = (is_categorical[bf] if cfg.has_categorical
               else jnp.asarray(False))
    bdefault_left = jnp.where(bis_cat, False, t.num_default_left[bf, bb])

    def pick(stats_cat, stats_numl, stats_numr, i):
        return jnp.where(
            bis_cat, stats_cat[i][bf, bb],
            jnp.where(bdefault_left, stats_numl[i][bf, bb], stats_numr[i][bf, bb]),
        )

    GL, HL, CL, GR, HR, CR = (pick(t.cat_stats, t.stats_ml, t.stats_mr, i)
                              for i in range(6))
    cat_mask = (jnp.arange(b, dtype=jnp.int32) == bb) & bis_cat

    return BestSplit(
        gain=bgain, feature=bf, bin=bb,
        default_left=bdefault_left, is_cat=bis_cat, cat_mask=cat_mask,
        sum_grad_left=GL, sum_hess_left=HL, count_left=CL,
        sum_grad_right=GR, sum_hess_right=HR, count_right=CR,
    )


def select_payload(t: _ScanTables, is_categorical, cfg: SplitConfig, *,
                   flat_keys=None, key_bins: int = 0):
    """Mosaic-safe winner selection: the same max-gain / lowest-flat-key
    tie-break as :func:`_select_from_tables`'s ``argmax``, expressed as a
    full-block max + one-hot masked gathers (no dynamic indexing, which
    Pallas TPU kernels cannot lower).  The extracted values are exact —
    each gather sums exactly one selected element.

    ``flat_keys`` (int32, same shape as the gain table) assigns every
    candidate its tie-break priority; lower wins.  The default row-major
    ``feat * B + bin`` reproduces ``argmax`` exactly; the fused kernel's
    packed4 path passes ORIGINAL-feature-order keys so the nibble-plane
    layout cannot perturb the tie-break.  Candidates keyed ``INT32_MAX``
    (phantom lane-padding) can win only if every real candidate is also
    ``-inf`` — and every real key < INT32_MAX, so they never do.

    Returns the scalar tuple ``(gain, feature, bin, default_left, is_cat,
    GL, HL, CL, GR, HR, CR)`` with feature/bin decoded through
    ``key_bins`` (defaults to the table width)."""
    gain_fb = t.gain_fb
    f, b = gain_fb.shape
    key_bins = key_bins or b
    if flat_keys is None:
        flat_keys = (jax.lax.broadcasted_iota(jnp.int32, (f, b), 0) * b
                     + jax.lax.broadcasted_iota(jnp.int32, (f, b), 1))
    imax = jnp.iinfo(jnp.int32).max
    mx = jnp.max(gain_fb)
    tie = gain_fb == mx
    kwin = jnp.min(jnp.where(tie, flat_keys, imax))
    sel = tie & (flat_keys == kwin)
    bf = (kwin // key_bins).astype(jnp.int32)
    bb = (kwin % key_bins).astype(jnp.int32)
    bgain = jnp.max(jnp.where(sel, gain_fb, -jnp.inf))
    if cfg.has_categorical:
        bis_cat = jnp.any(sel & _col(is_categorical))
    else:
        bis_cat = jnp.asarray(False)
    bdefault_left = jnp.where(bis_cat, False,
                              jnp.any(sel & t.num_default_left))

    def take(a):
        return jnp.sum(jnp.where(sel, a, 0.0))

    def pick(i):
        return jnp.where(
            bis_cat, take(t.cat_stats[i]),
            jnp.where(bdefault_left, take(t.stats_ml[i]),
                      take(t.stats_mr[i])))

    GL, HL, CL, GR, HR, CR = (pick(i) for i in range(6))
    return bgain, bf, bb, bdefault_left, bis_cat, GL, HL, CL, GR, HR, CR


def _best_split_impl(
    hist: jnp.ndarray,            # (F, B, 3) leaf histogram
    parent_grad: jnp.ndarray,     # scalar ΣG over the leaf (includes NaN bin)
    parent_hess: jnp.ndarray,     # scalar ΣH
    parent_count: jnp.ndarray,    # scalar rows
    *,
    num_bins_per_feature: jnp.ndarray,  # (F,) i32 (includes NaN bin if present)
    nan_bins: jnp.ndarray,              # (F,) i32; == B when feature has no NaN bin
    is_categorical: jnp.ndarray,        # (F,) bool
    monotone: jnp.ndarray | None,       # (F,) i32 in {-1,0,1} or None
    feature_mask: jnp.ndarray,          # (F,) bool (feature_fraction / interaction)
    cfg: SplitConfig,
    gain_penalty: jnp.ndarray | None = None,  # (F,) subtracted from every gain
                                              # (CEGB DeltaGain)
    parent_output: jnp.ndarray | None = None,  # scalar leaf output
                                               # (path_smooth anchor)
    rand_bins: jnp.ndarray | None = None,      # (F,) i32 random threshold per
                                               # feature (extra_trees)
    out_lo: jnp.ndarray | None = None,         # scalar monotone lower bound
    out_hi: jnp.ndarray | None = None,         # scalar monotone upper bound
    adv_bounds: tuple | None = None,           # advanced monotone mode:
                                               # (LLO, LHI, RLO, RHI) each
                                               # (F, B) — per-threshold child
                                               # output bounds (reference
                                               # AdvancedLeafConstraints
                                               # cumulative slices)
    leaf_depth: jnp.ndarray | None = None,     # scalar (monotone_penalty)
    feature_contri: jnp.ndarray | None = None,  # (F,) f32 gain multipliers,
                                                # pre-resolved by best_split
    with_feature_gains: bool = False,          # also return (F,) best gain per
                                               # feature (voting-parallel)
):
    """One scan over an (F, B, 3) histogram block (the whole feature space
    untiled, or one G-block of it).  Returns ``(best, from_sorted, fg)``
    where ``from_sorted`` flags a sorted-categorical winner — the cross-tile
    reducer needs it to reproduce the untiled "sorted wins only strictly"
    rule — and ``fg`` is the per-feature gain vector (None unless
    ``with_feature_gains``)."""
    G, H, C = hist[..., 0], hist[..., 1], hist[..., 2]
    t = scan_tables(
        G, H, C, parent_grad, parent_hess, parent_count,
        num_bins_per_feature=num_bins_per_feature, nan_bins=nan_bins,
        is_categorical=is_categorical, feature_mask=feature_mask, cfg=cfg,
        monotone=monotone, gain_penalty=gain_penalty,
        parent_output=parent_output, rand_bins=rand_bins,
        out_lo=out_lo, out_hi=out_hi, adv_bounds=adv_bounds,
        leaf_depth=leaf_depth, feature_contri=feature_contri)
    best = _select_from_tables(t, is_categorical, cfg)

    from_sorted = jnp.asarray(False)
    if cfg.has_categorical and cfg.use_sorted_categorical:
        best, from_sorted = _merge_sorted_categorical(
            best, G, H, C, parent_grad, parent_hess, parent_count,
            t.parent_output, t.parent_gain, t.in_feature,
            t.sorted_eligible[:, 0], feature_mask, t.penalty_col, cfg,
            t.min_count, rand_bins if cfg.extra_trees else None,
            feature_contri)
    fg = None
    if with_feature_gains:
        fg = jnp.max(t.gain_fb, axis=1)
        # NOTE: sorted-categorical gains are not folded into the vote — the
        # vote only ranks features, and one-hot gains rank the same columns.
    return best, from_sorted, fg


def best_split(
    hist: jnp.ndarray,            # (F, B, 3) leaf histogram
    parent_grad: jnp.ndarray,
    parent_hess: jnp.ndarray,
    parent_count: jnp.ndarray,
    *,
    num_bins_per_feature: jnp.ndarray,
    nan_bins: jnp.ndarray,
    is_categorical: jnp.ndarray,
    monotone: jnp.ndarray | None,
    feature_mask: jnp.ndarray,
    cfg: SplitConfig,
    gain_penalty: jnp.ndarray | None = None,
    parent_output: jnp.ndarray | None = None,
    rand_bins: jnp.ndarray | None = None,
    out_lo: jnp.ndarray | None = None,
    out_hi: jnp.ndarray | None = None,
    adv_bounds: tuple | None = None,
    leaf_depth: jnp.ndarray | None = None,
    with_feature_gains: bool = False,
) -> BestSplit:
    """Evaluate every (feature, threshold, missing-direction) candidate and
    argmax (argument semantics documented on :func:`_best_split_impl`).

    With ``with_feature_gains`` returns ``(best, per_feature_gain)`` — the
    local vote input of the voting-parallel learner (reference
    ``VotingParallelTreeLearner``, ``voting_parallel_tree_learner.cpp``).

    Wide feature spaces (``cfg.scan_tile``) evaluate in G-blocks through a
    sequential ``lax.map`` so the (F, B) cumsum/gain scratch peaks at one
    block instead of full F; the cross-block reduction replays the untiled
    tie-break order exactly (lowest flat index within a block, lowest block
    across blocks, sorted-categorical winners only on strictly greater
    gain), so the chosen split is identical to the untiled scan."""
    f, b, _ = hist.shape
    fc = None
    if cfg.feature_contri is not None:
        fc = jnp.asarray(cfg.feature_contri, jnp.float32)[:f]
        if fc.shape[0] < f:
            fc = jnp.concatenate(
                [fc, jnp.ones(f - fc.shape[0], jnp.float32)])
    t = _resolve_tile(cfg.scan_tile, f)
    if t == 0:
        best, _src, fg = _best_split_impl(
            hist, parent_grad, parent_hess, parent_count,
            num_bins_per_feature=num_bins_per_feature, nan_bins=nan_bins,
            is_categorical=is_categorical, monotone=monotone,
            feature_mask=feature_mask, cfg=cfg, gain_penalty=gain_penalty,
            parent_output=parent_output, rand_bins=rand_bins,
            out_lo=out_lo, out_hi=out_hi, adv_bounds=adv_bounds,
            leaf_depth=leaf_depth, feature_contri=fc,
            with_feature_gains=with_feature_gains)
        return (best, fg) if with_feature_gains else best

    nt = -(-f // t)
    pad = nt * t - f

    def blk(a, fill):
        """(F, ...) per-feature array -> (nt, t, ...) padded G-blocks.
        Pad columns are inert: nbpf=0 masks them out of every candidate."""
        if a is None:
            return None
        if pad:
            a = jnp.concatenate(
                [a, jnp.full((pad,) + a.shape[1:], fill, a.dtype)])
        return a.reshape((nt, t) + a.shape[1:])

    ops = {"hist": blk(hist, 0), "nbpf": blk(num_bins_per_feature, 0),
           "nanb": blk(nan_bins, b), "iscat": blk(is_categorical, False),
           "fmask": blk(feature_mask, False)}
    if monotone is not None:
        ops["mono"] = blk(monotone, 0)
    if gain_penalty is not None:
        ops["pen"] = blk(gain_penalty, 0.0)
    if rand_bins is not None:
        ops["rand"] = blk(rand_bins, 0)
    if fc is not None:
        ops["fc"] = blk(fc, 1.0)
    if adv_bounds is not None:
        for i, a in enumerate(adv_bounds):
            ops[f"adv{i}"] = blk(a, 0.0)

    def tile_fn(x):
        adv = (tuple(x[f"adv{i}"] for i in range(4))
               if adv_bounds is not None else None)
        best, src, fg = _best_split_impl(
            x["hist"], parent_grad, parent_hess, parent_count,
            num_bins_per_feature=x["nbpf"], nan_bins=x["nanb"],
            is_categorical=x["iscat"],
            monotone=x.get("mono"),
            feature_mask=x["fmask"], cfg=cfg,
            gain_penalty=x.get("pen"),
            parent_output=parent_output,
            rand_bins=x.get("rand"),
            out_lo=out_lo, out_hi=out_hi, adv_bounds=adv,
            leaf_depth=leaf_depth,
            feature_contri=x.get("fc"),
            with_feature_gains=with_feature_gains)
        if with_feature_gains:
            return best, src, fg
        return best, src

    mapped = jax.lax.map(tile_fn, ops)
    bests, srcs = mapped[0], mapped[1]
    # Cross-block winner with the untiled argmax's exact tie-break: max
    # gain; on ties a numeric/one-hot winner beats a sorted-categorical one
    # (the untiled merge takes sorted only on STRICTLY greater gain); then
    # the lowest block (= lowest feature id, blocks are contiguous).
    gains = bests.gain
    iota = jnp.arange(nt)
    is_max = gains == jnp.max(gains)
    numeric_max = is_max & ~srcs
    first_numeric = jnp.argmin(jnp.where(numeric_max, iota, nt))
    first_any = jnp.argmin(jnp.where(is_max, iota, nt))
    ti = jnp.where(jnp.any(numeric_max), first_numeric,
                   first_any).astype(jnp.int32)
    best = jax.tree.map(lambda a: a[ti], bests)
    best = best._replace(feature=best.feature + ti * t)
    if with_feature_gains:
        return best, mapped[2].reshape(nt * t)[:f]
    return best


def _merge_sorted_categorical(best, G, H, C, parent_grad, parent_hess,
                              parent_count, parent_output, parent_gain,
                              in_feature, sorted_eligible, feature_mask,
                              penalty_col, cfg, min_count, rand_bins,
                              feature_contri=None):
    """Run the sorted many-vs-many scan and take it when it beats ``best``.
    Returns ``(best, from_sorted)``."""
    s_gain, s_mask, s_gl, s_hl, s_cl = _sorted_categorical(
        G, H, C, parent_grad, parent_hess, parent_count, parent_output,
        in_feature, cfg, min_count, rand_bins)
    # NOTE: the parent gain shift deliberately uses PLAIN lambda_l2 even
    # though the sorted children use l2+cat_l2 — the reference computes
    # gain_shift (feature_histogram.cpp:161-173) before `l2 += cat_l2`
    # (:250), and comments that this asymmetry is intentional.
    s_gain = s_gain - parent_gain
    s_gain = jnp.where(s_gain > cfg.min_gain_to_split + _EPS, s_gain, -jnp.inf)
    if penalty_col is not None:
        s_gain = s_gain - penalty_col[:, 0]
        s_gain = jnp.where(s_gain > _EPS, s_gain, -jnp.inf)
    if feature_contri is not None:
        s_scaled = s_gain * feature_contri
        s_gain = jnp.where(jnp.isfinite(s_gain) & (s_scaled > _EPS),
                           s_scaled, -jnp.inf)
    s_gain = jnp.where(sorted_eligible & feature_mask, s_gain, -jnp.inf)
    sf = jnp.argmax(s_gain).astype(jnp.int32)
    sg = s_gain[sf]
    better = sg > best.gain
    pickf = lambda a_new, a_old: jnp.where(better, a_new, a_old)
    return BestSplit(
        gain=pickf(sg, best.gain),
        feature=pickf(sf, best.feature),
        bin=pickf(jnp.asarray(0, jnp.int32), best.bin),
        default_left=pickf(jnp.asarray(False), best.default_left),
        is_cat=pickf(jnp.asarray(True), best.is_cat),
        cat_mask=jnp.where(better, s_mask[sf], best.cat_mask),
        sum_grad_left=pickf(s_gl[sf], best.sum_grad_left),
        sum_hess_left=pickf(s_hl[sf], best.sum_hess_left),
        count_left=pickf(s_cl[sf], best.count_left),
        sum_grad_right=pickf(parent_grad - s_gl[sf], best.sum_grad_right),
        sum_hess_right=pickf(parent_hess - s_hl[sf], best.sum_hess_right),
        count_right=pickf(parent_count - s_cl[sf], best.count_right),
    ), better
