"""Gradient/hessian histogram construction — the hottest op in GBDT training.

Reference counterparts: ``DenseBin::ConstructHistogram`` (``src/io/dense_bin.hpp:143``,
sequential CPU scan) and the CUDA shared-memory scatter-add kernels
(``src/treelearner/cuda/cuda_histogram_constructor.cu:31-66``).

TPU re-design: the TPU has no atomics and scatters serialize, so the histogram is
expressed as a **one-hot contraction** that XLA maps onto the MXU:

    hist[f, b, c] = sum_r  (bins[r, f] == b) * vals[r, c]      c in {grad, hess, count}

computed blockwise under ``lax.scan`` so the one-hot never materializes in HBM at
full size.  Leaf membership / bagging are folded into ``vals`` as multiplicative
masks, which keeps every shape static under ``jit``.  A ``segment_sum`` (scatter)
variant is kept for comparison/benchmarking on CPU backends.

Sharding: when ``bins``/``vals`` are sharded along rows, the contraction's reduce
axis spans the mesh and XLA inserts a ``psum`` of the partial histograms — this IS
the reference's histogram ReduceScatter (``data_parallel_tree_learner.cpp:284``),
derived automatically from shardings instead of hand-written collectives.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp


def pack_bins4(bins: jnp.ndarray) -> jnp.ndarray:
    """Pack a (N, F) bin matrix whose bins all fit 4 bits (max_num_bins <=
    16, NaN bin included) into (N, ceil(F/2)) uint8 — feature 2j in the low
    nibble, 2j+1 in the high nibble.  Reference ``DenseBin`` IS_4BIT arm
    (``src/io/dense_bin.hpp``) packs ROW pairs; packing FEATURE pairs here
    keeps row gathers contiguous, which is what the perm layout streams."""
    n, f = bins.shape
    if n == 0:
        # zero-row placeholder (streamed training): reshape(-1) cannot
        # infer a dimension from an empty array
        return jnp.zeros((0, (f + 1) // 2), jnp.uint8)
    b = bins.astype(jnp.uint8)
    if f % 2:
        b = jnp.pad(b, ((0, 0), (0, 1)))
    b = b.reshape(n, -1, 2)
    return b[:, :, 0] | (b[:, :, 1] << 4)


def unpack_bins4(packed: jnp.ndarray, num_features: int) -> jnp.ndarray:
    """Inverse of :func:`pack_bins4` (drops the phantom odd-F column)."""
    low = packed & jnp.uint8(15)
    high = (packed >> 4) & jnp.uint8(15)
    full = jnp.stack([low, high], axis=-1).reshape(packed.shape[0], -1)
    return full[:, :num_features]


def pack_values(
    grad: jnp.ndarray, hess: jnp.ndarray, mask: Optional[jnp.ndarray]
) -> jnp.ndarray:
    """Stack (grad, hess, ones) into the (N, 3) channel matrix, pre-masked."""
    ones = jnp.ones_like(grad)
    vals = jnp.stack([grad, hess, ones], axis=-1)
    if mask is not None:
        vals = vals * mask.astype(vals.dtype)[:, None]
    return vals


@functools.partial(jax.jit, static_argnames=("num_bins", "rows_block",
                                             "packed4", "features"))
def histogram_onehot(
    bins: jnp.ndarray,       # (N, F) integer bins — or (N, ceil(F/2)) packed
    vals: jnp.ndarray,       # (N, 3) f32 (grad, hess, 1) or int8 quantized
    *,
    num_bins: int,
    rows_block: int = 16384,
    packed4: bool = False,   # bins carry two 4-bit features per byte
    features: int = 0,       # real F when packed4
    init: Optional[jnp.ndarray] = None,  # seed accumulator (streaming:
                             # chunk k continues chunk k-1's scan carry, so
                             # the cross-chunk fold replays the one-call
                             # block order exactly — docs/STREAMING.md)
) -> jnp.ndarray:            # (F, num_bins, 3) f32 — or i32 for int8 vals
    n, cols = bins.shape
    f = features if packed4 else cols
    integer = jnp.issubdtype(vals.dtype, jnp.integer)
    pad = (-n) % rows_block
    if pad:
        bins = jnp.pad(bins, ((0, pad), (0, 0)))
        vals = jnp.pad(vals, ((0, pad), (0, 0)))
    nblocks = (n + pad) // rows_block
    bins_blk = bins.reshape(nblocks, rows_block, cols)
    vals_blk = vals.reshape(nblocks, rows_block, 3)
    iota = jnp.arange(num_bins, dtype=jnp.int32)
    acc_dtype = jnp.int32 if integer else vals.dtype

    def body(acc, blk):
        b, v = blk
        if packed4:
            # per-block nibble unpack fuses into the contraction's input
            # pipeline; the full-size (N, F) matrix never lands in HBM
            b = unpack_bins4(b, f)
        onehot = (b.astype(jnp.int32)[:, :, None] == iota[None, None, :])
        if integer:
            # Quantized path: s8 x s8 -> s32 (the MXU's integer contraction;
            # reference Int32HistogramSumReducer accumulation, bin.h:48-81).
            part = jnp.einsum("nfb,nc->fbc", onehot.astype(jnp.int8), v,
                              preferred_element_type=jnp.int32)
        else:
            part = jnp.einsum("nfb,nc->fbc", onehot.astype(v.dtype), v,
                              precision=jax.lax.Precision.HIGHEST)
        return acc + part, None

    acc0 = (jnp.zeros((f, num_bins, 3), dtype=acc_dtype)
            if init is None else init.astype(acc_dtype))
    hist, _ = jax.lax.scan(body, acc0, (bins_blk, vals_blk))
    return hist


@functools.partial(jax.jit, static_argnames=("num_bins", "packed4",
                                             "features"))
def histogram_segment(
    bins: jnp.ndarray, vals: jnp.ndarray, *, num_bins: int,
    packed4: bool = False, features: int = 0,
    init: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    """Scatter-add variant (useful on CPU; TPU scatters serialize)."""
    if packed4:
        bins = unpack_bins4(bins, features)
    n, f = bins.shape
    integer = jnp.issubdtype(vals.dtype, jnp.integer)
    acc_dtype = jnp.int32 if integer else vals.dtype
    flat_ids = bins.astype(jnp.int32) + jnp.arange(f, dtype=jnp.int32)[None, :] * num_bins
    hist = (jnp.zeros((f * num_bins, 3), dtype=acc_dtype)
            if init is None else init.astype(acc_dtype).reshape(-1, 3))
    hist = hist.at[flat_ids].add(vals.astype(acc_dtype)[:, None, :])
    return hist.reshape(f, num_bins, 3)


def resolve_impl(impl: str, platform: Optional[str] = None) -> str:
    """Resolve the ``auto`` histogram impl for a backend platform (the
    single source of truth — bench reporting uses it too)."""
    if impl != "auto":
        return impl
    platform = jax.default_backend() if platform is None else platform
    return "pallas" if platform == "tpu" else "segment"


def histogram_from_vals(
    bins: jnp.ndarray,
    vals: jnp.ndarray,
    *,
    num_bins: int,
    impl: str = "auto",
    rows_block: int = 16384,
    packed4: bool = False,
    features: int = 0,
    init: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    """Histogram from pre-packed (N, 3) channel values.

    ``init`` seeds the accumulator (streaming chunk accumulation,
    docs/STREAMING.md): for the scatter and blockwise-scan impls the
    seeded per-chunk calls replay the EXACT add sequence of the one-call
    full-N histogram (chunk k's first add continues chunk k-1's carry),
    which is what makes streamed fp32 histograms bitwise-equal to in-core
    ones; the pallas kernel reduces per-chunk then adds the seed (integer
    quantized histograms stay exact either way)."""
    impl = resolve_impl(impl)
    if impl in ("pallas", "flat", "flat_bf16"):
        from .pallas_histogram import histogram_flat
        if jnp.issubdtype(vals.dtype, jnp.integer):
            # Quantized histograms: s8 x s8 -> s32 on the MXU's double-rate
            # int8 path (reference Int32HistogramSumReducer, bin.h:48-81).
            out = histogram_flat(bins, vals, num_bins=num_bins,
                                 rows_block=rows_block, dtype="int8",
                                 packed4=packed4, features=features)
        else:
            out = histogram_flat(bins, vals, num_bins=num_bins,
                                 rows_block=rows_block,
                                 dtype="bf16" if impl == "flat_bf16"
                                 else "f32",
                                 packed4=packed4, features=features)
        return out if init is None else init + out
    if impl == "onehot":
        return histogram_onehot(bins, vals, num_bins=num_bins,
                                rows_block=rows_block, packed4=packed4,
                                features=features, init=init)
    if impl == "segment":
        return histogram_segment(bins, vals, num_bins=num_bins,
                                 packed4=packed4, features=features,
                                 init=init)
    raise ValueError(f"unknown histogram impl: {impl}")


def build_histogram(
    bins: jnp.ndarray,
    grad: jnp.ndarray,
    hess: jnp.ndarray,
    mask: Optional[jnp.ndarray],
    *,
    num_bins: int,
    impl: str = "auto",
    rows_block: int = 16384,
) -> jnp.ndarray:
    """Histogram for the rows selected by ``mask`` (all rows when ``mask=None``)."""
    vals = pack_values(grad, hess, mask)
    return histogram_from_vals(bins, vals, num_bins=num_bins, impl=impl,
                               rows_block=rows_block)


def subtract_histogram(parent: jnp.ndarray, child: jnp.ndarray) -> jnp.ndarray:
    """Sibling histogram via subtraction (reference ``serial_tree_learner.cpp:369``,
    ``FeatureHistogram::Subtract``)."""
    return parent - child
