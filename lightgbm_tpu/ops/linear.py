"""Device-resident linear-leaf solves (``linear_tree``).

Reference: ``LinearTreeLearner::CalculateLinear`` (``src/treelearner/
linear_tree_learner.cpp``) solves one small weighted normal-equation
system per leaf on the host (Eigen), looping leaves in Python here —
six host syncs per iteration pulling gradients, hessians, the mask and
the row->leaf vector off the device (the ISSUE-5 census numbers).

TPU re-design: ONE dispatch builds every leaf's normal equations by
segment-summing weighted feature outer products over the row->leaf
assignment — each leaf's path-feature set is padded to a common width
``Dp`` (next power of two, so the trace re-specializes O(log depth)
times at most) — and a single batched ``jnp.linalg.solve`` solves all
leaves at once.  NaN-row masking and the too-few-rows fallback replicate
the host semantics exactly; padded dimensions carry an identity diagonal
and a zero RHS, so their coefficients come out exactly zero.  The solve
runs in the device's native f32 (the reference's f64 Eigen solve stays
available behind the host facade, ``models/linear.py``, for callers that
need it — LIGHTGBM_TPU_HOST_LINEAR=1).

The op also emits the per-row training predictions (linear output with
per-row NaN fallback to the constant leaf value), so no per-leaf value
ever round-trips the host inside the training loop.
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

# Above this many scratch elements (rows x padded-dim^2) the outer-product
# accumulation runs as a lax.scan over row blocks instead of one
# materialized (N, D1, D1) tensor.
_BLOCK_ELEMS = 1 << 24
_BLOCK_ROWS = 1 << 16


def pad_leaf_features(feats: Sequence[np.ndarray], num_leaves_max: int
                      ) -> Tuple[np.ndarray, np.ndarray]:
    """Pack per-leaf path-feature lists into ``(leaf_feat, feat_ok)`` —
    ``(L, Dp)`` int32 indices (real features first, zero-padded) and the
    matching validity mask.  ``Dp`` is the max feature count rounded up to
    a power of two (min 2) so the jitted solve re-specializes at most
    O(log depth) times across a training run."""
    dmax = max([len(f) for f in feats] + [1])
    dp = 2
    while dp < dmax:
        dp *= 2
    leaf_feat = np.zeros((num_leaves_max, dp), np.int32)
    feat_ok = np.zeros((num_leaves_max, dp), bool)
    for l, fl in enumerate(feats):
        d = len(fl)
        if d:
            leaf_feat[l, :d] = np.asarray(fl, np.int32)
            feat_ok[l, :d] = True
    return leaf_feat, feat_ok


def _accumulate(Xa, gz, hz, okf, rl, L):
    """(A, b, cnt) segment sums over rows; blocked when the outer-product
    scratch would not fit comfortably."""
    n, d1 = Xa.shape

    def seg(block):
        xa, g, h, ok, r = block
        a = jax.ops.segment_sum(
            h[:, None, None] * xa[:, :, None] * xa[:, None, :], r,
            num_segments=L + 1)
        b = jax.ops.segment_sum(g[:, None] * xa, r, num_segments=L + 1)
        c = jax.ops.segment_sum(ok.astype(jnp.float32), r,
                                num_segments=L + 1)
        return a, b, c

    if n * d1 * d1 <= _BLOCK_ELEMS:
        a, b, c = seg((Xa, gz, hz, okf, rl))
        return a[:L], b[:L], c[:L]
    blk = _BLOCK_ROWS
    pad = (-n) % blk
    Xa = jnp.pad(Xa, ((0, pad), (0, 0)))
    gz = jnp.pad(gz, (0, pad))
    hz = jnp.pad(hz, (0, pad))
    okf = jnp.pad(okf, (0, pad))
    rl = jnp.pad(rl, (0, pad), constant_values=L)   # pad rows -> dropped
    nb = (n + pad) // blk

    def body(carry, block):
        a0, b0, c0 = carry
        a, b, c = seg(block)
        return (a0 + a, b0 + b, c0 + c), None

    shape = lambda *s: jnp.zeros(s, jnp.float32)
    (a, b, c), _ = jax.lax.scan(
        body,
        (shape(L + 1, d1, d1), shape(L + 1, d1), shape(L + 1)),
        (Xa.reshape(nb, blk, d1), gz.reshape(nb, blk),
         hz.reshape(nb, blk), okf.reshape(nb, blk), rl.reshape(nb, blk)))
    return a[:L], b[:L], c[:L]


def fit_linear_leaves(X, row_leaf, grad, hess, mask, leaf_feat, feat_ok,
                      leaf_value, linear_lambda, shrink):
    """Solve every leaf's weighted normal equations in one batched device
    program (trace body — see :func:`fit_linear_leaves_device`).

    Replicates ``fit_leaf_linear_models``: rows whose leaf features
    contain NaN are excluded from the solve and fall back to the plain
    leaf value at prediction; a leaf with fewer usable rows than
    coefficients (or an empty feature set, or a singular system) keeps
    its constant output.  (Refit's decay blend stays on the host —
    ``models/linear.refit_leaf_linear_models`` — its keep-old /
    intercept-only-leaf semantics operate on the post-trim feature sets,
    not the fit-time padded ones.)

    Returns ``(coeffs (L, Dp), const (L,), good (L,) bool,
    pred (N,))`` — ``pred`` is the SHRUNK per-row training contribution.
    """
    n = X.shape[0]
    L, dp = leaf_feat.shape
    lf = leaf_feat[row_leaf]                      # (N, Dp)
    fok = feat_ok[row_leaf]                       # (N, Dp)
    xr = jnp.take_along_axis(X, lf, axis=1)
    nan_row = jnp.any(jnp.isnan(xr) & fok, axis=1)
    ok = ~nan_row
    xr0 = jnp.where(fok & ~jnp.isnan(xr), xr, 0.0)
    Xa = jnp.concatenate([xr0, jnp.ones((n, 1), xr0.dtype)], axis=1)
    Xa = jnp.where(ok[:, None], Xa, 0.0)
    gz = jnp.where(ok, grad * mask, 0.0)
    hz = jnp.where(ok, hess * mask, 0.0)
    A, b, cnt = _accumulate(Xa, gz, hz, ok, row_leaf, L)
    # Diagonal: ridge lambda on real feature dims, identity on padded
    # dims (zero rows/cols otherwise — keeps the batched solve
    # nonsingular with an exactly-zero padded coefficient), nothing on
    # the intercept (reference adds lambda to the d feature dims only).
    dleaf = feat_ok.sum(axis=1)                   # (L,)
    j = jnp.arange(dp + 1)
    diag_add = jnp.where(j[None, :] < dleaf[:, None],
                         jnp.float32(linear_lambda),
                         jnp.where(j[None, :] == dp, 0.0, 1.0))
    A = A + diag_add[:, :, None] * jnp.eye(dp + 1, dtype=A.dtype)[None]
    coeffs_all = -jnp.linalg.solve(A, b[:, :, None])[:, :, 0]   # (L, Dp+1)
    good = ((dleaf > 0) & (cnt >= dleaf + 1)
            & jnp.all(jnp.isfinite(coeffs_all), axis=1))
    coeffs = jnp.where(good[:, None], coeffs_all[:, :dp], 0.0)
    const = jnp.where(good, coeffs_all[:, dp], leaf_value)
    lin = jnp.sum(xr0 * coeffs[row_leaf], axis=1) + const[row_leaf]
    pred = jnp.where(good[row_leaf] & ok, lin, leaf_value[row_leaf])
    return coeffs, const, good, pred * shrink


fit_linear_leaves_device = jax.jit(fit_linear_leaves)


def attach_leaf_models(tree, feats: List[np.ndarray], coeffs: np.ndarray,
                       const: np.ndarray, good: np.ndarray,
                       zero_threshold: float = 1e-35) -> None:
    """Attach the batched device solve's results to a host Tree (mutates
    ``tree``) with the reference's |coef| > kZeroThreshold feature trim —
    the ONE host pass replacing the per-leaf solve loop."""
    nl = tree.num_leaves
    leaf_const = np.asarray(tree.leaf_value[:nl], np.float64).copy()
    leaf_features: List[np.ndarray] = []
    leaf_coeffs: List[np.ndarray] = []
    for l in range(nl):
        fl = np.asarray(feats[l], np.int64) if l < len(feats) \
            else np.zeros(0, np.int64)
        d = len(fl)
        if d == 0 or not bool(good[l]):
            leaf_features.append(np.zeros(0, np.int64))
            leaf_coeffs.append(np.zeros(0, np.float64))
            continue
        c = np.asarray(coeffs[l][:d], np.float64)
        keep = np.abs(c) > zero_threshold
        leaf_features.append(fl[keep])
        leaf_coeffs.append(c[keep])
        leaf_const[l] = float(const[l])
    tree.is_linear = True
    tree.leaf_const = leaf_const
    tree.leaf_features = leaf_features
    tree.leaf_coeff = leaf_coeffs
