"""Gradient discretization for quantized training (``use_quantized_grad``).

Reference counterpart: ``GradientDiscretizer`` (``src/treelearner/
gradient_discretizer.hpp:128``, ``.cpp:218``; CUDA analog
``cuda_gradient_discretizer.cu``) — gradients/hessians are discretized to a
few integer levels with stochastic rounding, histograms accumulate in
integers, and gains are computed after rescaling.  This is the reference's
own answer to histogram bandwidth; on TPU it additionally unlocks the MXU's
int8 contraction path (s8 x s8 -> s32) and shrinks gradient HBM traffic 4x.

TPU re-design: discretization is a single fused elementwise program on
device (no host round-trip, PRNG = counter-based ``jax.random`` keyed per
iteration, so results are reproducible and independent of execution order —
unlike the reference's per-thread PRNG streams).
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

_EPS = 1e-30


def gradient_scales(
    grad: jnp.ndarray, hess: jnp.ndarray, num_bins: int
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Per-iteration scale factors mapping grad/hess onto integer levels.

    Mirrors the reference's scale computation (``gradient_discretizer.cpp``
    / arXiv:2207.09682 §3.1): gradients use the signed half-range
    ``num_bins / 2`` levels per sign (delta_g = max|g| / (B/2)), hessians
    (non-negative) the full ``num_bins`` range (delta_h = max h / B).

    The previous ``num_bins/2 - 1`` / ``num_bins - 1`` divisors halved the
    gradient resolution at the default B=4 (levels {-1, 0, 1} instead of
    {-2..2}) and cost a measured ~2.6e-3 holdout AUC at the bench config
    (docs/PERF.md round 8) — the whole quantized-parity drift.
    """
    # int8 storage bounds the level range at +/-127: at the maximum
    # num_grad_quant_bins=128 the full-range hessian level would be 128
    # and silently clip low for every max-hessian row, so the scale must
    # target the largest level that actually fits.
    g_levels = min(max(num_bins // 2, 1), 127)
    h_levels = min(max(num_bins, 1), 127)
    g_scale = jnp.maximum(jnp.max(jnp.abs(grad)) / g_levels, _EPS)
    h_scale = jnp.maximum(jnp.max(jnp.abs(hess)) / h_levels, _EPS)
    return g_scale.astype(jnp.float32), h_scale.astype(jnp.float32)


def discretize_gradients(
    grad: jnp.ndarray,
    hess: jnp.ndarray,
    g_scale: jnp.ndarray,
    h_scale: jnp.ndarray,
    key: jnp.ndarray,
    stochastic: bool = True,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """int8 (grad, hess) levels; stochastic rounding keeps E[q * scale] = x.

    Exactly-zero inputs (e.g. masked-out rows) stay exactly zero under
    stochastic rounding: floor(0 + U[0,1)) == 0.
    """
    gs = grad / g_scale
    hs = hess / h_scale
    if stochastic:
        kg, kh = jax.random.split(key)
        gq = jnp.floor(gs + jax.random.uniform(kg, gs.shape, gs.dtype))
        hq = jnp.floor(hs + jax.random.uniform(kh, hs.shape, hs.dtype))
    else:
        gq = jnp.round(gs)
        hq = jnp.round(hs)
    gq = jnp.clip(gq, -127, 127).astype(jnp.int8)
    hq = jnp.clip(hq, -127, 127).astype(jnp.int8)
    return gq, hq
