"""Shared scaffolding for the Pallas TPU kernels (histogram + fused wave).

One copy of the jax-version shims and layout constants both kernels need,
so the fused wave kernel (``ops/pallas_wave.py``) reuses the histogram
kernel's exact compile parameters and dtype table instead of duplicating
the rename shim (the ISSUE-7 cleanup satellite).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental.pallas import tpu as pltpu

# Channels (grad, hess, count) padded; BlockSpec dim == array dim so
# sublane alignment is not required, and 4 halves the streamed valsT bytes
# vs a full 8-sublane tile.
C_PAD = 4

# Mosaic scoped-vmem ceiling (v5e has 128MB).
VMEM_LIMIT = 64 * 1024 * 1024

# one-hot/compute dtype -> (operand dtype, accumulator dtype, itemsize)
DTYPES = {
    "f32": (jnp.float32, jnp.float32, 4),
    "bf16": (jnp.bfloat16, jnp.float32, 2),
    "int8": (jnp.int8, jnp.int32, 1),
}


def compiler_params_cls():
    """pltpu compiler-params class across the jax rename
    (TPUCompilerParams -> CompilerParams); fails with the attribute names
    rather than an opaque NoneType call on a third rename."""
    cls = getattr(pltpu, "CompilerParams",
                  getattr(pltpu, "TPUCompilerParams", None))
    if cls is None:
        raise AttributeError(
            "jax.experimental.pallas.tpu exposes neither CompilerParams "
            "nor TPUCompilerParams; unsupported jax version")
    return cls


def onehot_contract(bins_blk, valsT, *, num_bins, oh_dtype, acc_dtype,
                    precision):
    """One row-block's histogram contribution as a matmul against the
    in-VMEM one-hot: ``(C_PAD, blk) x (blk, ft*num_bins)``.  ``num_bins``
    is the LANE-PADDED bin count (multiple of 128) — Mosaic only supports
    the (blk, ft, B) -> (blk, ft*B) flatten when the merged minor dim
    stays 128-aligned.  The ONE implementation shared by the flat
    histogram kernel and the fused wave kernel, so their accumulation is
    op-for-op identical."""
    blk, ft = bins_blk.shape
    iota_b = jax.lax.broadcasted_iota(jnp.int32, (blk, ft, num_bins), 2)
    oh = (bins_blk[:, :, None] == iota_b).astype(oh_dtype)
    oh = oh.reshape(blk, ft * num_bins)             # lane-aligned merge
    return jax.lax.dot_general(
        valsT, oh, dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=acc_dtype, precision=precision)
