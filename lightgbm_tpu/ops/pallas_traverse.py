"""Fused serving traversal: the whole quantized tree pack VMEM-resident,
row blocks pipelined through the Pallas grid (ISSUE-12, ROADMAP item 3).

The unfused predict walk (``models/tree._tree_walk_q``) advances every row
one level per ``while_loop`` step with XLA gathers — each step re-reads
the (T, M) node arrays from HBM and the gather lowers poorly on TPU.  The
dataflow-pipelined traversal in "Booster: An Accelerator for Gradient
Boosting Decision Trees" (arxiv 2011.02022) keeps the tree structure
resident next to the compute units and streams rows past it; this kernel
is that shape for the TPU build:

- grid ``(row_blocks,)`` — ONE ``pallas_call`` per class scores the whole
  batch, tree pack and bin tables' nan routing staying in VMEM across
  every row block (vs O(depth) gather dispatches worth of HBM re-reads);
- per-node lookups are Mosaic-safe masked sums / one-hot matmuls (the
  ``onehot_contract`` discipline of the histogram kernels) — no device
  gathers anywhere in the body;
- the categorical masks arrive BIT-PACKED (the quantized pack encoding)
  and the kernel tests membership with ``(byte >> (col & 7)) & 1``,
  exactly the unfused walk's arithmetic;
- leaf quanta accumulate in int32 — associative, so the kernel is
  bitwise-identical to the unfused walk UNCONDITIONALLY (the serving twin
  of the PR-7 wave kernel's int32 histogram identity), pinned across the
  shape-bucket ladder in tests/test_serve_quantize.py.

The kernel REQUIRES a quantized pack (``tpu_serve_quantize != off``): an
fp32 leaf sum would tie bitwise identity to summation order, and the whole
point of the integer pack is that it cannot.  On CPU the kernel body runs
in interpret mode (tier-1 coverage), selected the same way the wave kernel
does it.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .pallas_common import VMEM_LIMIT, compiler_params_cls

#: VMEM working-set budget for one traversal call: resident pack (widened
#: to i32 operands) + one streamed row block + the per-step one-hot
#: temporaries, with 2x headroom (the wave kernel's budget discipline).
TRAVERSE_VMEM_BUDGET = 32 * 1024 * 1024

#: default rows per grid step (overridable via layout rows_block)
_ROWS_BLOCK = 1024

_LANE = 128


def _pad_to(n: int, mult: int = _LANE) -> int:
    return max(-(-n // mult) * mult, mult)


def traverse_layout(num_trees: int, max_leaves: int, features: int,
                    num_bins: int, rows_block: int = 0) -> dict:
    """Static VMEM plan for one fused traversal call — the fit gate in one
    testable place (the ``wave_layout`` discipline).  All lane dims pad to
    128; the pack operands are WIDENED to i32 for the kernel (the narrow
    resident arrays stay the plan's footprint — widening is a trace-time
    relayout XLA fuses into the operand copy)."""
    blk = int(rows_block) if rows_block else _ROWS_BLOCK
    m_pad = _pad_to(max(max_leaves - 1, 1))
    l_pad = _pad_to(max_leaves)
    f_pad = _pad_to(features)
    bb_pad = _pad_to(-(-num_bins // 8))
    pack_bytes = num_trees * (6 * m_pad + m_pad * bb_pad + l_pad) * 4
    stream_bytes = blk * f_pad * 4
    # per-step temporaries: the (blk, m/f/bb/l) one-hots and their masked
    # products, ~6 live at once
    scratch_bytes = 6 * blk * max(m_pad, f_pad, bb_pad, l_pad) * 4
    total = 2 * (pack_bytes + stream_bytes) + scratch_bytes
    return {
        "rows_block": blk, "m_pad": m_pad, "l_pad": l_pad, "f_pad": f_pad,
        "bb_pad": bb_pad, "pack_bytes": pack_bytes,
        "stream_bytes": stream_bytes, "scratch_bytes": scratch_bytes,
        "total_bytes": total, "fits": total <= TRAVERSE_VMEM_BUDGET,
    }


def traverse_layout_fits(num_trees: int, max_leaves: int, features: int,
                         num_bins: int, rows_block: int = 0) -> bool:
    return traverse_layout(num_trees, max_leaves, features, num_bins,
                           rows_block)["fits"]


def _traverse_kernel(bins_ref, nanb_ref, sf_ref, sb_ref, dl_ref, ic_ref,
                     catb_ref, lc_ref, rc_ref, leaf_ref, out_ref, *,
                     num_trees, depth, m_pad, bb_pad):
    """Kernel body at grid point (rb): walk row block ``rb`` through every
    tree of the resident pack, accumulating int32 leaf quanta.

    Decision arithmetic mirrors ``models/tree._tree_walk_q`` op for op;
    node/feature/leaf lookups are masked sums over one-hots (exact for
    integers), the cat-byte row comes from a (blk, M) x (M, BB) one-hot
    matmul (f32 is exact for byte values <= 255)."""
    bins = bins_ref[...].astype(jnp.int32)               # (blk, f_pad)
    blk, f_pad = bins.shape
    nanb = nanb_ref[...].astype(jnp.int32)               # (1, f_pad)
    sf_all = sf_ref[...]                                 # (T, m_pad) i32
    sb_all = sb_ref[...]
    dl_all = dl_ref[...]
    ic_all = ic_ref[...]
    lc_all = lc_ref[...]
    rc_all = rc_ref[...]
    catb_all = catb_ref[...]                             # (T, m_pad*bb_pad)
    leaf_all = leaf_ref[...]                             # (T, l_pad) i32
    l_pad = leaf_all.shape[1]
    iota_m = jax.lax.broadcasted_iota(jnp.int32, (blk, m_pad), 1)
    iota_f = jax.lax.broadcasted_iota(jnp.int32, (blk, f_pad), 1)
    iota_bb = jax.lax.broadcasted_iota(jnp.int32, (blk, bb_pad), 1)
    iota_l = jax.lax.broadcasted_iota(jnp.int32, (blk, l_pad), 1)

    def row_of(arr2d, t):
        return jax.lax.dynamic_index_in_dim(arr2d, t, 0, keepdims=True)

    def tree_body(t, acc):
        sf_t = row_of(sf_all, t)
        sb_t = row_of(sb_all, t)
        dl_t = row_of(dl_all, t)
        ic_t = row_of(ic_all, t)
        lc_t = row_of(lc_all, t)
        rc_t = row_of(rc_all, t)
        catb_t = row_of(catb_all, t).reshape(m_pad, bb_pad) \
            .astype(jnp.float32)
        leaf_t = row_of(leaf_all, t)

        def step(_, st):
            node, done = st
            ohn = (node == iota_m).astype(jnp.int32)     # (blk, m_pad)

            def sel(row):                                # row (1, m_pad)
                return jnp.sum(ohn * row, axis=1, keepdims=True)

            f = sel(sf_t)
            sb = sel(sb_t)
            dl = sel(dl_t)
            ic = sel(ic_t)
            lc = sel(lc_t)
            rc = sel(rc_t)
            ohf = (f == iota_f).astype(jnp.int32)        # (blk, f_pad)
            col = jnp.sum(ohf * bins, axis=1, keepdims=True)
            nb = jnp.sum(ohf * nanb, axis=1, keepdims=True)
            isnan = col == nb
            rowb = jax.lax.dot_general(                  # (blk, bb_pad)
                (node == iota_m).astype(jnp.float32), catb_t,
                dimension_numbers=(((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            ohb = (jnp.minimum(col >> 3, bb_pad - 1) == iota_bb) \
                .astype(jnp.float32)
            byte = jnp.sum(ohb * rowb, axis=1,
                           keepdims=True).astype(jnp.int32)
            catbit = ((byte >> (col & 7)) & 1) > 0
            gl = jnp.where(ic > 0, catbit, col <= sb)
            gl = jnp.where(isnan & (ic == 0), dl > 0, gl)
            nxt = jnp.where(gl, lc, rc)
            is_leaf = nxt < 0
            node = jnp.where(is_leaf | done, node, nxt)
            node = jnp.where(is_leaf & ~done, nxt, node)
            return node, done | is_leaf

        node0 = jnp.zeros((blk, 1), jnp.int32)
        done0 = jnp.zeros((blk, 1), jnp.bool_)
        node, _ = jax.lax.fori_loop(0, depth, step, (node0, done0))
        leaf_idx = jnp.where(node < 0, ~node, 0)
        ohl = (leaf_idx == iota_l).astype(jnp.int32)
        return acc + jnp.sum(ohl * leaf_t, axis=1, keepdims=True)

    out_ref[...] = jax.lax.fori_loop(
        0, num_trees, tree_body, jnp.zeros((blk, 1), jnp.int32))


@functools.partial(
    jax.jit, static_argnames=("depth", "rows_block", "interpret"))
def fused_traverse_call(
    bins: jnp.ndarray,      # (N_pad, f_pad) i32 lane-padded binned rows
    nan_bins: jnp.ndarray,  # (1, f_pad) i32
    sf: jnp.ndarray,        # (T, m_pad) i32 — pack arrays widened + padded
    sb: jnp.ndarray,
    dl: jnp.ndarray,
    ic: jnp.ndarray,
    catb: jnp.ndarray,      # (T, m_pad*bb_pad) i32 bit-packed cat bytes
    lc: jnp.ndarray,
    rc: jnp.ndarray,
    leaf: jnp.ndarray,      # (T, l_pad) i32 leaf quanta
    *,
    depth: int,
    rows_block: int,
    interpret: bool = False,
):
    """One fused traversal pass: (N_pad, 1) int32 leaf-quanta sums for one
    class's resident pack, rows pipelined through the grid."""
    n, f_pad = bins.shape
    t, m_pad = sf.shape
    bb_pad = catb.shape[1] // m_pad
    blk = min(rows_block, n)
    pad = (-n) % blk
    if pad:
        bins = jnp.pad(bins, ((0, pad), (0, 0)))
    nblocks = (n + pad) // blk
    kern = functools.partial(
        _traverse_kernel, num_trees=t, depth=depth, m_pad=m_pad,
        bb_pad=bb_pad)
    whole = lambda r: (0, 0)    # noqa: E731 — pack resident across blocks
    out = pl.pallas_call(
        kern,
        grid=(nblocks,),
        in_specs=[
            pl.BlockSpec((blk, f_pad), lambda r: (r, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(nan_bins.shape, whole, memory_space=pltpu.VMEM),
            pl.BlockSpec(sf.shape, whole, memory_space=pltpu.VMEM),
            pl.BlockSpec(sb.shape, whole, memory_space=pltpu.VMEM),
            pl.BlockSpec(dl.shape, whole, memory_space=pltpu.VMEM),
            pl.BlockSpec(ic.shape, whole, memory_space=pltpu.VMEM),
            pl.BlockSpec(catb.shape, whole, memory_space=pltpu.VMEM),
            pl.BlockSpec(lc.shape, whole, memory_space=pltpu.VMEM),
            pl.BlockSpec(rc.shape, whole, memory_space=pltpu.VMEM),
            pl.BlockSpec(leaf.shape, whole, memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((blk, 1), lambda r: (r, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((n + pad, 1), jnp.int32),
        compiler_params=compiler_params_cls()(
            dimension_semantics=("arbitrary",),
            vmem_limit_bytes=VMEM_LIMIT),
        interpret=interpret,
    )(bins, nan_bins, sf, sb, dl, ic, catb, lc, rc, leaf)
    return out[:n, 0]


def fused_class_sums(pack: dict, bins: jnp.ndarray, nan_bins: jnp.ndarray,
                     *, interpret: bool = False) -> jnp.ndarray:
    """(N,) int32 quanta sums for one quantized pack via the fused kernel.
    Trace-time prep (lane padding + i32 widening) only relayouts — the
    values the kernel walks are exactly the pack's, so the result equals
    ``models/tree._ensemble_sum_q`` bit for bit."""
    t, m = pack["split_feature"].shape
    bb = pack["cat_bits"].shape[2]
    n, f = bins.shape
    lay = traverse_layout(t, int(pack["leaf_q"].shape[1]), f,
                          int(pack["num_bins"]))
    m_pad, f_pad = lay["m_pad"], lay["f_pad"]
    bb_pad, l_pad = lay["bb_pad"], lay["l_pad"]

    def widen(a, cols):
        a = a.astype(jnp.int32)
        return jnp.pad(a, ((0, 0), (0, cols - a.shape[1])))

    catb = jnp.pad(pack["cat_bits"].astype(jnp.int32),
                   ((0, 0), (0, m_pad - m), (0, bb_pad - bb)))
    return fused_traverse_call(
        jnp.pad(bins.astype(jnp.int32), ((0, 0), (0, f_pad - f))),
        jnp.pad(nan_bins.astype(jnp.int32), (0, f_pad - f)).reshape(1, -1),
        widen(pack["split_feature"], m_pad),
        widen(pack["split_bin"], m_pad),
        widen(pack["default_left"], m_pad),
        widen(pack["is_cat"], m_pad),
        catb.reshape(t, m_pad * bb_pad),
        widen(pack["left_child"], m_pad),
        widen(pack["right_child"], m_pad),
        widen(pack["leaf_q"], l_pad),
        depth=int(pack["depth"]), rows_block=lay["rows_block"],
        interpret=interpret)
