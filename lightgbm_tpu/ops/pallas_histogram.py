"""Pallas TPU histogram kernel — the framework's hottest op.

Reference counterpart: the CUDA shared-memory histogram kernels
(``src/treelearner/cuda/cuda_histogram_constructor.cu:31-66`` — per-block
shared-mem scatter-add + atomics).  TPUs have no atomics and scatters
serialize, so the kernel uses a different decomposition:

    hist[c, f*B+b] = sum_n vals[n, c] * (bins[n, f] == b)

i.e. a matmul ``valsᵀ (C × n) @ onehot (n × B)`` per feature, accumulated in
VMEM across row blocks.  Two properties make this the right TPU shape:

- The channel axis C (grad, hess, count) sits on the MXU's **sublane** side
  where the padding floor is 8, not on the lane side where it would be 128 —
  a 16x reduction in wasted MACs vs the naive ``onehotᵀ @ vals`` layout.
- The one-hot matrix is generated **inside VMEM** from the (blk, F) uint8 bin
  tile, so HBM traffic is just bins + vals (the XLA einsum fallback
  materializes the (blk, F, B) one-hot through HBM, ~B× more traffic).

Output layout is (F, C_pad, B); the public wrapper transposes to the (F, B, 3)
histogram the split scan consumes.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

C_PAD = 8  # f32 sublane tile


def _hist_kernel(bins_ref, vals_ref, out_ref, *, num_bins: int,
                 num_features: int):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        out_ref[:] = jnp.zeros_like(out_ref)

    bins_blk = bins_ref[:].astype(jnp.int32)        # (blk, F)
    vals_blk = vals_ref[:]                          # (blk, C_PAD) f32
    blk = bins_blk.shape[0]
    iota_b = jax.lax.broadcasted_iota(jnp.int32, (blk, num_bins), 1)
    for f in range(num_features):
        onehot = (bins_blk[:, f][:, None] == iota_b).astype(jnp.float32)
        # (C_PAD, blk) @ (blk, B) on the MXU, f32 accumulation.
        partial = jax.lax.dot_general(
            vals_blk, onehot,
            dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )                                           # (C_PAD, B)
        out_ref[f, :, :] += partial


@functools.partial(jax.jit,
                   static_argnames=("num_bins", "rows_block", "interpret"))
def histogram_pallas(
    bins: jnp.ndarray,   # (N, F) uint8/uint16
    vals: jnp.ndarray,   # (N, 3) f32 masked (grad, hess, count)
    *,
    num_bins: int,
    rows_block: int = 2048,
    interpret: bool = False,
) -> jnp.ndarray:        # (F, num_bins, 3) f32
    n, f = bins.shape
    pad = (-n) % rows_block
    if pad:
        bins = jnp.pad(bins, ((0, pad), (0, 0)))
        vals = jnp.pad(vals, ((0, pad), (0, 0)))
    ntot = n + pad
    vals8 = jnp.pad(vals.astype(jnp.float32), ((0, 0), (0, C_PAD - 3)))
    nblocks = ntot // rows_block

    out = pl.pallas_call(
        functools.partial(_hist_kernel, num_bins=num_bins, num_features=f),
        grid=(nblocks,),
        in_specs=[
            pl.BlockSpec((rows_block, f), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((rows_block, C_PAD), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((f, C_PAD, num_bins), lambda i: (0, 0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((f, C_PAD, num_bins), jnp.float32),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary",),
        ),
        interpret=interpret,
    )(bins, vals8)
    return jnp.transpose(out[:, :3, :], (0, 2, 1))  # (F, B, 3)
