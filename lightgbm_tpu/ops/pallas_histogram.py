"""Pallas TPU histogram kernels — the framework's hottest op.

Reference counterpart: the CUDA shared-memory histogram kernels
(``src/treelearner/cuda/cuda_histogram_constructor.cu:31-66`` — per-block
shared-mem scatter-add + atomics).  TPUs have no atomics and scatters
serialize, so the kernel computes the histogram as a **matmul against a
flattened one-hot**, generated inside VMEM:

    out[c, f*B + b] = sum_n  vals[n, c] * (bins[n, f] == b)

Why this shape wins on the MXU:

- The one-hot (the big streamed operand) never touches HBM: it is built in
  VMEM from the (blk, ft) uint8 bin tile, so HBM traffic is just bins + vals.
- A whole feature CHUNK shares ONE dot per row-block (N = ft*B lanes),
  instead of per-feature M=8 matmuls — fewer, larger matmuls with identical
  streamed volume.  The grid iterates row-blocks only; very wide datasets
  are chunked at trace time into separate same-shaped calls so the VMEM
  one-hot stays bounded (and every BlockSpec dim is Mosaic-legal: the
  feature dim always equals the array dim, row blocks are 128-multiples).
- The kernel is HBM-bandwidth-bound (bins + vals streams), so the wave
  grower issues one bandwidth-optimal call per smaller sibling instead of
  packing siblings into the matmul M dimension (measured ~100x faster on
  v5e than an M-packed multi-sibling kernel); the streamed volume stays
  proportional to the rows actually histogrammed — the reference's
  smaller-sibling trick (``serial_tree_learner.cpp:369``).
- int8 variant: s8 vals x s8 one-hot -> s32 accumulation — the reference's
  quantized-training histograms (``Int32HistogramSumReducer``, ``bin.h:48``)
  on the MXU's double-rate int8 path.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Shared kernel scaffolding (ops/pallas_common.py): the fused wave kernel
# reuses the SAME compile-params shim / dtype table / one-hot contraction,
# so the two kernels cannot drift apart.  The old private names stay as
# aliases for back-compat with external callers/tests.
from .pallas_common import (C_PAD, DTYPES as _DTYPES,
                            VMEM_LIMIT as _VMEM_LIMIT,
                            compiler_params_cls as _compiler_params_cls,
                            onehot_contract)


def _pick_tiles(f: int, num_bins: int, itemsize: int, rows_block: int,
                acc_size: int = 4):
    """(rows_block, features_per_chunk) bounding the kernel's VMEM working
    set (the in-VMEM one-hot PLUS the (C_PAD, ft*B) accumulator block).
    ``num_bins`` here is the LANE-PADDED bin count (multiple of 128).

    Mosaic requires each BlockSpec's last dim to be a multiple of 128 or
    equal to the full array dim, so the kernel never tiles features inside
    one ``pallas_call``: the bins block spans the WHOLE (chunk) feature
    width, and wide datasets are chunked at trace time into separate
    same-shaped calls.  Row blocks stay multiples of 128 (the sublane-
    aligned choice for every dtype used here).

    The 2x on the one-hot bytes models Mosaic's observed scoped-stack peak
    (the (blk, ft, B) compare plus its (blk, ft*B) reshape copy coexist)."""
    budget = 16 * 1024 * 1024

    def bytes_for(blk, ft):
        return ft * num_bins * (blk * 2 * itemsize + C_PAD * acc_size)

    # rows_block > 4096 means "tuned for the XLA einsum path" — auto-pick.
    # Powers of two >= 128 keep every halving on the 128-multiple lattice
    # Mosaic requires for the valsT block's last dim.
    if rows_block <= 0 or rows_block > 4096:
        blk = 1024
    else:
        blk = max(128, 1 << (int(rows_block).bit_length() - 1))
    while blk > 128 and bytes_for(blk, f) > budget:
        blk //= 2
    if bytes_for(blk, f) <= budget:
        return blk, f
    # Very wide data: fix the minimum row block and chunk the features.
    ft = max(1, budget // (num_bins * (blk * 2 * itemsize
                                       + C_PAD * acc_size)))
    return blk, ft


def kernel_layout(f: int, num_bins: int, dtype: str, rows_block: int = 0,
                  packed4: bool = False):
    """(rows_block, ftile, cols_tile, b_pad) for one ``histogram_flat``
    config.  Every Mosaic legality constraint lives here so it is testable
    without hardware: the bin axis is padded to a 128-multiple (bin ids are
    < num_bins, so phantom bins stay exactly zero), which keeps the
    kernel's one-hot flatten — and, under packed4, each nibble plane's
    contiguous output half — lane-aligned."""
    isz = _DTYPES[dtype][2]
    b_pad = -(-num_bins // 128) * 128
    rows_block, ftile = _pick_tiles(f, b_pad, isz, rows_block)
    if packed4 and ftile % 2:
        ftile += 1           # chunk boundaries must not split nibble pairs
    cols_tile = ftile // 2 if packed4 else ftile
    return rows_block, ftile, cols_tile, b_pad


def _prep(bins, vals, rows_block, ftile):
    """Pad rows to the block size, features to a multiple of the chunk
    width, channels to C_PAD; returns (bins, valsT, nblocks, nchunks).

    Phantom feature columns are filled with bin 0; their histogram blocks
    are sliced off by the caller, so the garbage never escapes.
    """
    n, f = bins.shape
    pad = (-n) % rows_block
    fpad = (-f) % ftile
    if pad or fpad:
        bins = jnp.pad(bins, ((0, pad), (0, fpad)))
    if pad:
        vals = jnp.pad(vals, ((0, pad), (0, 0)))
    c = vals.shape[1]
    valsT = jnp.pad(vals, ((0, 0), (0, C_PAD - c))).T  # (C_PAD, ntot)
    ntot = n + pad
    return bins, valsT, ntot // rows_block, (f + fpad) // ftile


def _flat_kernel(bins_ref, valsT_ref, out_ref, *, num_bins, ftile,
                 oh_dtype, acc_dtype, precision, packed4=False):
    """``num_bins`` is the lane-padded bin count (multiple of 128): Mosaic
    only supports the (blk, ft, B) -> (blk, ft*B) one-hot flatten when the
    merged minor dim stays 128-aligned.  Real bin ids never reach the
    phantom bins, so their histogram lanes are exact zeros and the caller
    slices them off."""
    rb = pl.program_id(0)  # row-block index

    @pl.when(rb == 0)
    def _init():
        out_ref[:] = jnp.zeros_like(out_ref)

    bins_blk = bins_ref[:].astype(jnp.int32)            # (blk, ct)
    valsT = valsT_ref[:]                                # (C_PAD, blk)
    if oh_dtype != valsT.dtype:
        valsT = valsT.astype(oh_dtype)

    def contract(b2d):
        return onehot_contract(b2d, valsT, num_bins=num_bins,
                               oh_dtype=oh_dtype, acc_dtype=acc_dtype,
                               precision=precision)

    if packed4:
        # 4-bit mode: the streamed tile carries two features per byte
        # (reference DenseBin IS_4BIT, dense_bin.hpp); the nibble unpack
        # happens HERE in VMEM so HBM streams half the bin bytes.  The two
        # nibble planes are contracted separately into contiguous output
        # halves (a vector interleave of the planes is not a Mosaic-legal
        # shape cast); the caller un-permutes the feature order.
        half = (ftile // 2) * num_bins
        out_ref[:, :half] += contract(bins_blk & 15)
        out_ref[:, half:] += contract((bins_blk >> 4) & 15)
    else:
        out_ref[:, :] += contract(bins_blk)


@functools.partial(
    jax.jit, static_argnames=("num_bins", "rows_block", "dtype", "interpret",
                              "packed4", "features"))
def histogram_flat(
    bins: jnp.ndarray,   # (N, F) uint8/uint16 — or (N, ceil(F/2)) packed
    vals: jnp.ndarray,   # (N, 3) f32 masked (grad, hess, count) — or int8
    *,
    num_bins: int,
    rows_block: int = 0,
    dtype: str = "f32",  # one-hot/compute dtype: f32 | bf16 | int8
    interpret: bool = False,
    packed4: bool = False,   # two 4-bit features per streamed byte
    features: int = 0,       # real F when packed4
) -> jnp.ndarray:        # (F, num_bins, 3) f32 (int32 for int8)
    """Single-leaf flat-matmul histogram."""
    n, fcols = bins.shape
    f = features if packed4 else fcols
    oh_dtype, acc_dtype, isz = _DTYPES[dtype]
    # f32 must accumulate exactly (reference hists are exact f32 sums);
    # DEFAULT would run the MXU at bf16 and perturb every histogram entry.
    precision = (jax.lax.Precision.HIGHEST if dtype == "f32"
                 else jax.lax.Precision.DEFAULT)
    rows_block, ftile, cols_tile, b_pad = kernel_layout(
        f, num_bins, dtype, rows_block, packed4)
    bins, valsT, nblocks, nchunks = _prep(bins, vals, rows_block, cols_tile)
    call = pl.pallas_call(
        functools.partial(_flat_kernel, num_bins=b_pad, ftile=ftile,
                          oh_dtype=oh_dtype, acc_dtype=acc_dtype,
                          precision=precision, packed4=packed4),
        grid=(nblocks,),
        in_specs=[
            pl.BlockSpec((rows_block, cols_tile), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((C_PAD, rows_block), lambda i: (0, i),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((C_PAD, ftile * b_pad),
                               lambda i: (0, 0), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((C_PAD, ftile * b_pad), acc_dtype),
        # jax renamed TPUCompilerParams -> CompilerParams across 0.4.x/0.5
        compiler_params=_compiler_params_cls()(
            dimension_semantics=("arbitrary",),
            vmem_limit_bytes=_VMEM_LIMIT),
        interpret=interpret,
    )
    chunks = [call(jax.lax.slice_in_dim(bins, c * cols_tile,
                                        (c + 1) * cols_tile, axis=1), valsT)
              for c in range(nchunks)]
    out = chunks[0] if nchunks == 1 else jnp.concatenate(chunks, axis=1)
    out = out.reshape(C_PAD, nchunks * ftile, b_pad)[:3, :, :num_bins]
    if packed4:
        # Each chunk emits its low-nibble features then its high-nibble
        # features; un-permute back to the interleaved pack_bins4 order
        # (feature 2j in packed column j's low nibble, 2j+1 high).
        order = np.concatenate(
            [np.concatenate([2 * cols, 2 * cols + 1])
             for cols in np.split(np.arange(nchunks * cols_tile), nchunks)])
        out = jnp.take(out, jnp.asarray(np.argsort(order)[:f]), axis=1)
    else:
        out = out[:, :f]     # drop phantom feature blocks
    return jnp.transpose(out, (1, 2, 0))


def histogram_pallas(
    bins: jnp.ndarray,
    vals: jnp.ndarray,
    *,
    num_bins: int,
    rows_block: int = 0,
    interpret: bool = False,
) -> jnp.ndarray:
    """Backwards-compatible name for the f32 flat-matmul kernel.  A plain
    alias (no decorator): ``histogram_flat`` is already jitted, and the old
    ``jax.jit``-of-``jax.jit`` wrapper only added a second trace level."""
    return histogram_flat(bins, vals, num_bins=num_bins,
                          rows_block=rows_block, dtype="f32",
                          interpret=interpret)
