"""Pallas TPU histogram kernels — the framework's hottest op.

Reference counterpart: the CUDA shared-memory histogram kernels
(``src/treelearner/cuda/cuda_histogram_constructor.cu:31-66`` — per-block
shared-mem scatter-add + atomics).  TPUs have no atomics and scatters
serialize, so the kernel computes the histogram as a **matmul against a
flattened one-hot**, generated inside VMEM:

    out[(l, c), f*B + b] = sum_n  vals[n, c] * (sib[n] == l) * (bins[n, f] == b)

Why this shape wins on the MXU:

- The one-hot (the big streamed operand) never touches HBM: it is built in
  VMEM from the (blk, ft) uint8 bin tile, so HBM traffic is just bins + vals.
- A whole feature TILE shares ONE dot per row-block (N = ft*B lanes),
  instead of per-feature M=8 matmuls — fewer, larger matmuls with identical
  streamed volume.  The grid tiles (row-blocks x feature-tiles) so the VMEM
  one-hot stays bounded for arbitrarily wide datasets.
- The M dimension carries (sibling x channel).  Growing multiple leaves per
  wave packs M up to 128 (16 siblings x 8 channels), so the systolic array's
  row dimension is fully used while the streamed K x N volume stays
  proportional to the rows actually histogrammed (the reference's
  smaller-sibling trick, ``serial_tree_learner.cpp:369``).
- int8 variant: s8 vals x s8 one-hot -> s32 accumulation — the reference's
  quantized-training histograms (``Int32HistogramSumReducer``, ``bin.h:48``)
  on the MXU's double-rate int8 path.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

C_PAD = 8  # channels (grad, hess, count) padded to one f32 sublane tile

_DTYPES = {
    "f32": (jnp.float32, jnp.float32, 4),
    "bf16": (jnp.bfloat16, jnp.float32, 2),
    "int8": (jnp.int8, jnp.int32, 1),
}


def _pick_tiles(f: int, num_bins: int, itemsize: int, rows_block: int,
                num_sibs: int = 1, acc_size: int = 4):
    """(rows_block, features_per_tile) bounding the kernel's VMEM working
    set (the in-VMEM one-hot PLUS the (num_sibs*C_PAD, ft*B) accumulator
    block) to ~12MB.

    The row block is fixed first (1024 unless the caller asks for less) and
    the feature tile is sized from the remaining budget — wide matmul N
    (ft*B lanes) beats a deep K, and arbitrarily wide datasets tile along
    the feature grid dimension instead of blowing VMEM."""
    budget = 12 * 1024 * 1024
    # rows_block > 4096 means "tuned for the XLA einsum path" — auto-pick.
    blk = 1024 if (rows_block <= 0 or rows_block > 4096) else rows_block
    per_ft = num_bins * (blk * itemsize + num_sibs * C_PAD * acc_size)
    ft = max(1, min(f, budget // per_ft))
    while blk > 256 and ft * num_bins * (blk * itemsize
                                         + num_sibs * C_PAD * acc_size) \
            > budget:
        blk //= 2
    return blk, ft


def _prep(bins, vals, rows_block, ftile, sib=None):
    """Pad rows to the block size, features to the tile size, channels to
    C_PAD; returns (bins, valsT, sib2, nblocks, nftiles).

    Phantom feature columns are filled with bin 0; their histogram blocks
    are sliced off by the caller, so the garbage never escapes.
    """
    n, f = bins.shape
    pad = (-n) % rows_block
    fpad = (-f) % ftile
    if pad or fpad:
        bins = jnp.pad(bins, ((0, pad), (0, fpad)))
    if pad:
        vals = jnp.pad(vals, ((0, pad), (0, 0)))
        if sib is not None:
            sib = jnp.pad(sib, (0, pad), constant_values=-1)
    c = vals.shape[1]
    valsT = jnp.pad(vals, ((0, 0), (0, C_PAD - c))).T  # (C_PAD, ntot)
    ntot = n + pad
    sib2 = None if sib is None else sib.reshape(1, ntot)
    return bins, valsT, sib2, ntot // rows_block, (f + fpad) // ftile


def _flat_kernel(bins_ref, valsT_ref, out_ref, *, num_bins, ftile,
                 oh_dtype, acc_dtype):
    rb = pl.program_id(1)  # row-block index (grid dim 1, iterates fastest)

    @pl.when(rb == 0)
    def _init():
        out_ref[:] = jnp.zeros_like(out_ref)

    bins_blk = bins_ref[:].astype(jnp.int32)            # (blk, ft)
    valsT = valsT_ref[:]                                # (C_PAD, blk)
    blk = bins_blk.shape[0]
    iota_b = jax.lax.broadcasted_iota(jnp.int32, (blk, ftile, num_bins), 2)
    oh = (bins_blk[:, :, None] == iota_b).astype(oh_dtype)
    oh = oh.reshape(blk, ftile * num_bins)              # (blk, ft*B)
    out_ref[:, :] += jax.lax.dot_general(
        valsT.astype(oh_dtype) if oh_dtype != valsT.dtype else valsT,
        oh, dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=acc_dtype)


def _flat_sib_kernel(bins_ref, valsT_ref, sib_ref, out_ref, *, num_bins,
                     ftile, num_sibs, oh_dtype, acc_dtype):
    rb = pl.program_id(1)  # row-block index (grid dim 1, iterates fastest)

    @pl.when(rb == 0)
    def _init():
        out_ref[:] = jnp.zeros_like(out_ref)

    bins_blk = bins_ref[:].astype(jnp.int32)            # (blk, ft)
    valsT = valsT_ref[:]                                # (C_PAD, blk)
    sib = sib_ref[:].astype(jnp.int32)                  # (1, blk)
    blk = bins_blk.shape[0]
    iota_b = jax.lax.broadcasted_iota(jnp.int32, (blk, ftile, num_bins), 2)
    oh = (bins_blk[:, :, None] == iota_b).astype(oh_dtype)
    oh = oh.reshape(blk, ftile * num_bins)
    iota_s = jax.lax.broadcasted_iota(jnp.int32, (num_sibs, blk), 0)
    sib_oh = (iota_s == sib).astype(valsT.dtype)        # (W, blk)
    # A[(l, c), r] = vals[c, r] * (sib[r] == l)  -> (W*C_PAD, blk)
    A = (sib_oh[:, None, :] * valsT[None, :, :]).reshape(
        num_sibs * C_PAD, blk)
    out_ref[:, :] += jax.lax.dot_general(
        A.astype(oh_dtype) if oh_dtype != A.dtype else A,
        oh, dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=acc_dtype)


@functools.partial(
    jax.jit, static_argnames=("num_bins", "rows_block", "dtype", "interpret"))
def histogram_flat(
    bins: jnp.ndarray,   # (N, F) uint8/uint16
    vals: jnp.ndarray,   # (N, 3) f32 masked (grad, hess, count) — or int8
    *,
    num_bins: int,
    rows_block: int = 0,
    dtype: str = "f32",  # one-hot/compute dtype: f32 | bf16 | int8
    interpret: bool = False,
) -> jnp.ndarray:        # (F, num_bins, 3) f32 (int32 for int8)
    """Single-leaf flat-matmul histogram."""
    n, f = bins.shape
    oh_dtype, acc_dtype, isz = _DTYPES[dtype]
    rows_block, ftile = _pick_tiles(f, num_bins, isz, rows_block)
    bins, valsT, _, nblocks, nftiles = _prep(bins, vals, rows_block, ftile)
    out = pl.pallas_call(
        functools.partial(_flat_kernel, num_bins=num_bins, ftile=ftile,
                          oh_dtype=oh_dtype, acc_dtype=acc_dtype),
        grid=(nftiles, nblocks),
        in_specs=[
            pl.BlockSpec((rows_block, ftile), lambda j, i: (i, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((C_PAD, rows_block), lambda j, i: (0, i),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((C_PAD, ftile * num_bins),
                               lambda j, i: (0, j), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct(
            (C_PAD, nftiles * ftile * num_bins), acc_dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary", "arbitrary")),
        interpret=interpret,
    )(bins, valsT)
    # (C_PAD, Fpad*B) -> (F, B, 3), dropping phantom feature blocks
    out = out.reshape(C_PAD, nftiles * ftile, num_bins)[:3, :f]
    return jnp.transpose(out, (1, 2, 0))


@functools.partial(
    jax.jit,
    static_argnames=("num_bins", "num_sibs", "rows_block", "dtype",
                     "interpret"))
def histogram_flat_sib(
    bins: jnp.ndarray,   # (S, F) gathered rows (padded; pad rows sib=-1)
    vals: jnp.ndarray,   # (S, 3)
    sib: jnp.ndarray,    # (S,) i32 sibling slot in [0, num_sibs); -1 = pad
    *,
    num_bins: int,
    num_sibs: int,
    rows_block: int = 0,
    dtype: str = "f32",
    interpret: bool = False,
) -> jnp.ndarray:        # (num_sibs, F, num_bins, 3)
    """Multi-leaf wave histogram: all siblings in ONE kernel, M = sibs x
    channels (up to 128)."""
    n, f = bins.shape
    oh_dtype, acc_dtype, isz = _DTYPES[dtype]
    rows_block, ftile = _pick_tiles(f, num_bins, isz, rows_block,
                                    num_sibs=num_sibs)
    bins, valsT, sib2, nblocks, nftiles = _prep(bins, vals, rows_block,
                                                ftile, sib)
    out = pl.pallas_call(
        functools.partial(_flat_sib_kernel, num_bins=num_bins, ftile=ftile,
                          num_sibs=num_sibs, oh_dtype=oh_dtype,
                          acc_dtype=acc_dtype),
        grid=(nftiles, nblocks),
        in_specs=[
            pl.BlockSpec((rows_block, ftile), lambda j, i: (i, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((C_PAD, rows_block), lambda j, i: (0, i),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, rows_block), lambda j, i: (0, i),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((num_sibs * C_PAD, ftile * num_bins),
                               lambda j, i: (0, j), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct(
            (num_sibs * C_PAD, nftiles * ftile * num_bins), acc_dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary", "arbitrary")),
        interpret=interpret,
    )(bins, valsT, sib2)
    # (W*C_PAD, Fpad*B) -> (W, F, B, 3), dropping phantom feature blocks
    out = out.reshape(num_sibs, C_PAD, nftiles * ftile, num_bins)[:, :3, :f]
    return jnp.transpose(out, (0, 2, 3, 1))


# Backwards-compatible name: the per-feature-loop kernel is superseded by the
# flat formulation; histogram_pallas now routes to it.
@functools.partial(jax.jit,
                   static_argnames=("num_bins", "rows_block", "interpret"))
def histogram_pallas(
    bins: jnp.ndarray,
    vals: jnp.ndarray,
    *,
    num_bins: int,
    rows_block: int = 0,
    interpret: bool = False,
) -> jnp.ndarray:
    return histogram_flat(bins, vals, num_bins=num_bins,
                          rows_block=rows_block, dtype="f32",
                          interpret=interpret)
