"""Fused wave kernel: histogram -> sibling-subtract -> split-scan in ONE
VMEM-resident Pallas pass per leaf-batch wave.

The split-finding wave is the framework's hot loop, and unfused it
round-trips the (W, G, B, 3) histogram tensors through HBM three times per
wave: ``ops/pallas_histogram.py`` builds each smaller sibling's histogram
(write), the grower subtracts the larger sibling from the parent in plain
XLA (read + write), and ``ops/split.py`` re-streams both children for the
split scan (read).  Both "Booster: An Accelerator for Gradient Boosting
Decision Trees" (arxiv 2011.02022) and "XGBoost: Scalable GPU Accelerated
Learning" (arxiv 1806.11248) locate the remaining headroom in exactly this
fusion once the histogram itself is matmul-shaped.

This kernel runs the whole sequence while the (C_PAD, F*B) accumulators
are VMEM-resident:

- grid ``(W, row_blocks)`` — one ``pallas_call`` per WAVE, leaf batches
  pipelined through the leading grid dimension (vs one histogram dispatch
  per leaf unfused);
- (a) the smaller sibling accumulates via the SAME in-VMEM one-hot matmul
  as ``histogram_flat`` (``ops/pallas_common.onehot_contract`` — shared
  code, op-for-op identical accumulation, including the packed4 nibble
  unpack and the int8 x int8 -> int32 quantized path);
- (b) at the last row block the larger sibling derives by subtraction from
  the parent's histogram WITHOUT leaving VMEM (reference
  ``FeatureHistogram::Subtract``, ``serial_tree_learner.cpp:369``);
- (c) the cumulative-sum split scan (``ops/split.scan_tables`` — the exact
  gain arithmetic of the unfused scan, refactored to be kernel-callable)
  plus the Mosaic-safe winner selection (``ops/split.select_payload``,
  tie-break-identical to the unfused argmax) run over BOTH siblings while
  the accumulators are still resident.

HBM traffic per wave drops to one bins+vals stream plus the O(W * G * B)
child-histogram writeback the pool retains and a tiny (W, 2, 16+B)
SplitInfo payload — the full (L, G, B, 3) tensor never round-trips between
build and scan (pinned structurally in tests/test_hlo_cost.py).

Quantized training rides the int8/int32 accumulation path (``DTYPES``),
subtraction stays exact integer arithmetic, and the per-iteration scales
apply in-register right before the scan — mirroring ``grower._scale_hist``
bit for bit.  packed4 composes: the nibble planes contract into contiguous
output halves and the scan runs in PLANE order with ORIGINAL-feature-order
tie-break keys, so the layout cannot perturb the chosen split.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .pallas_common import (C_PAD, DTYPES, VMEM_LIMIT, compiler_params_cls,
                            onehot_contract)
from .pallas_histogram import kernel_layout
from .split import BestSplit, SplitConfig, scan_tables, select_payload

# Scalar lanes ahead of the cat_mask in the per-child SplitInfo payload:
# [gain, feature, bin, default_left, is_cat, GL, HL, CL, GR, HR, CR] + pad.
PAYLOAD_SCALARS = 16

# Per-child scalar-input lanes: [pg, ph, pc, parent_out, small_left, active].
STAT_LANES = 8

# The fused working set holds the one-hot block PLUS three (C_PAD, F*B)
# histograms (small accumulator, its sibling slot, the parent) PLUS the
# scan's (F, B) gain/stat tables — budgeted below VMEM_LIMIT with the same
# 2x one-hot headroom model as ``_pick_tiles``.  v5e carries 128 MB VMEM;
# the histogram kernel's own 16 MB budget stays untouched so fused and
# unfused share identical row blocking (bitwise-identical accumulation).
WAVE_VMEM_BUDGET = 48 * 1024 * 1024

# (F, B)-shaped f32 buffers the scan materializes at peak (cum sums, three
# stats directions x 6, gain/mask tables) — a deliberate over-count.
_SCAN_BUFS = 32


def plane_order(features: int, packed4: bool):
    """(order, inverse) for the kernel's feature layout.  packed4 nibble
    planes contract into contiguous halves (low-nibble features first), so
    plane position p holds ORIGINAL feature ``order[p]``; ``inverse``
    restores original order (phantom odd-F column sorts last and is
    sliced off).  None/None when the layouts coincide."""
    if not packed4:
        return None, None
    ct = -(-features // 2)
    order = np.concatenate([2 * np.arange(ct), 2 * np.arange(ct) + 1])
    return order.astype(np.int32), np.argsort(order).astype(np.int32)


def wave_layout(features: int, num_bins: int, dtype: str,
                rows_block: int = 0, packed4: bool = False) -> dict:
    """Static VMEM plan for one fused-wave call — every Mosaic legality
    constraint and the working-set budget in one testable place (the
    ``kernel_layout`` discipline, extended with the fused extras):

    - row blocking comes from ``kernel_layout`` UNCHANGED, resolved at the
      wave's SHARED bucket (the largest smaller-sibling bucket of the
      wave) — the unfused path resolves it per leaf, so a leaf whose own
      bucket is smaller can see different f32 partial-sum grouping; the
      accumulated VALUES are identical whenever histogram sums are
      exactly representable (and always under int32 quantized), which is
      the scope of the bitwise-identity pins;
    - ``single_chunk``: the kernel scans the whole feature space in one
      block — trace-time feature chunking (very wide F) cannot fuse, those
      shapes keep the unfused path (plus the pool + tiled scan that
      already serve them);
    - ``fits``: single-chunk AND the modeled working set (2x one-hot +
      3 resident histograms + scan scratch + streamed blocks) stays under
      ``WAVE_VMEM_BUDGET``."""
    blk, ftile, cols_tile, b_pad = kernel_layout(
        features, num_bins, dtype, rows_block, packed4)
    isz = DTYPES[dtype][2]
    fb = ftile * b_pad
    needed_cols = -(-features // 2) if packed4 else features
    single_chunk = cols_tile >= needed_cols
    onehot_bytes = 2 * blk * fb * isz
    hist_block_bytes = 3 * C_PAD * fb * 4
    scan_scratch_bytes = _SCAN_BUFS * fb * 4
    stream_bytes = blk * cols_tile + C_PAD * blk * isz
    total = (onehot_bytes + hist_block_bytes + scan_scratch_bytes
             + stream_bytes)
    return {
        "rows_block": blk, "ftile": ftile, "cols_tile": cols_tile,
        "b_pad": b_pad, "payload_width": PAYLOAD_SCALARS + num_bins,
        "onehot_bytes": onehot_bytes,
        "hist_block_bytes": hist_block_bytes,
        "scan_scratch_bytes": scan_scratch_bytes,
        "stream_bytes": stream_bytes, "total_bytes": total,
        "single_chunk": single_chunk,
        "fits": single_chunk and total <= WAVE_VMEM_BUDGET,
    }


def wave_layout_fits(features: int, num_bins: int, dtype: str,
                     rows_block: int = 0, packed4: bool = False) -> bool:
    return wave_layout(features, num_bins, dtype, rows_block, packed4)["fits"]


def wave_dtype_for(cfg) -> str:
    """The fused kernel's one-hot dtype for a GrowerConfig-like ``cfg`` —
    the ONE resolution shared by the grower's trace-time gate and GBDT's
    ``wave_fused_active`` reporting, so the two cannot drift apart."""
    if cfg.quantized:
        return "int8"
    return "bf16" if cfg.histogram_impl == "flat_bf16" else "f32"


def wave_fits_for(cfg, features: int) -> bool:
    """Shape gate for a GrowerConfig-like ``cfg`` at ``features`` columns
    (duck-typed: quantized / histogram_impl / hist_bins / num_bins /
    rows_block / packed4) — exactly what ``_grow_wave`` evaluates at trace
    time."""
    return wave_layout_fits(features, cfg.hist_bins or cfg.num_bins,
                            wave_dtype_for(cfg), cfg.rows_block,
                            cfg.packed4)


def wave_meta(num_bins_per_feature, nan_bins, is_categorical, feature_mask,
              *, features: int, num_bins: int, packed4: bool) -> jnp.ndarray:
    """The kernel's (ftile, 8) i32 meta block in PLANE order:
    ``[nbpf, nan_bin, is_cat, feature_mask, orig_feature_id, 0, 0, 0]``.
    Phantom rows (packed4 odd-F padding) get ``nbpf = 0`` so no candidate
    of theirs is ever valid; column 4 feeds the ORIGINAL-feature-order
    tie-break keys."""
    order, _ = plane_order(features, packed4)
    ftile = features if order is None else int(order.shape[0])

    def prep(a, fill):
        a = jnp.asarray(a).astype(jnp.int32)
        if ftile > features:
            a = jnp.concatenate(
                [a, jnp.full(ftile - features, fill, jnp.int32)])
        return a if order is None else a[order]

    orig = jnp.asarray(order if order is not None
                       else np.arange(features), jnp.int32)
    zero = jnp.zeros(ftile, jnp.int32)
    return jnp.stack(
        [prep(num_bins_per_feature, 0), prep(nan_bins, num_bins),
         prep(is_categorical, 0), prep(feature_mask, 0), orig,
         zero, zero, zero], axis=1)


def hist_to_flat(h: jnp.ndarray, ftile: int, b_pad: int,
                 order) -> jnp.ndarray:
    """(W, F, HB, 3) stored parent histograms -> the kernel's
    (W, C_PAD, ftile*b_pad) flat layout (channel-major, lane-padded bins,
    plane-permuted features under packed4).  Pure relayout — XLA fuses it
    into the operand copy; no arithmetic, so the values stay bitwise."""
    w, f, hb, c = h.shape
    h = jnp.pad(h, ((0, 0), (0, ftile - f), (0, b_pad - hb),
                    (0, C_PAD - c)))
    if order is not None:
        h = h[:, order]
    return jnp.transpose(h, (0, 3, 1, 2)).reshape(w, C_PAD, ftile * b_pad)


def hist_from_flat(o: jnp.ndarray, features: int, hb: int, b_pad: int,
                   inverse) -> jnp.ndarray:
    """(W, 2, C_PAD, ftile*b_pad) kernel output -> (W, 2, F, HB, 3) stored
    child histograms (inverse of :func:`hist_to_flat`)."""
    w, two, cp, fb = o.shape
    ftile = fb // b_pad
    o = o.reshape(w, two, cp, ftile, b_pad)[:, :, :3, :, :hb]
    o = jnp.transpose(o, (0, 1, 3, 4, 2))
    if inverse is not None:
        o = o[:, :, inverse]
    return o[:, :, :features]


def payload_to_best(pay: jnp.ndarray) -> BestSplit:
    """(K, PAYLOAD_SCALARS + B) kernel payload -> batched BestSplit.  The
    f32 lanes transport counts/sums losslessly (exactly one writer per
    lane, same discipline as ``sync_best_split``'s one-hot psum)."""
    col = lambda i: pay[:, i]
    return BestSplit(
        gain=col(0),
        feature=jnp.round(col(1)).astype(jnp.int32),
        bin=jnp.round(col(2)).astype(jnp.int32),
        default_left=col(3) > 0.5,
        is_cat=col(4) > 0.5,
        cat_mask=pay[:, PAYLOAD_SCALARS:] > 0.5,
        sum_grad_left=col(5), sum_hess_left=col(6), count_left=col(7),
        sum_grad_right=col(8), sum_hess_right=col(9), count_right=col(10))


def _wave_kernel(*refs, nblocks, ftile, b_pad, key_bins, oh_dtype,
                 acc_dtype, precision, packed4, scfg, has_scale):
    """Kernel body at grid point (w, rb): accumulate row block ``rb`` of
    leaf ``w``'s smaller sibling, and at the last block subtract the
    parent, reorder into (left, right) and scan both children."""
    if has_scale:
        (bins_ref, valsT_ref, parent_ref, stats_ref, meta_ref, scale_ref,
         hist_ref, pay_ref) = refs
    else:
        (bins_ref, valsT_ref, parent_ref, stats_ref, meta_ref,
         hist_ref, pay_ref) = refs
        scale_ref = None
    rb = pl.program_id(1)

    @pl.when(rb == 0)
    def _init():
        hist_ref[:] = jnp.zeros_like(hist_ref)

    bins_blk = bins_ref[0].astype(jnp.int32)             # (blk, ct)
    valsT = valsT_ref[0]                                 # (C_PAD, blk)
    if oh_dtype != valsT.dtype:
        valsT = valsT.astype(oh_dtype)

    def contract(b2d):
        return onehot_contract(b2d, valsT, num_bins=b_pad,
                               oh_dtype=oh_dtype, acc_dtype=acc_dtype,
                               precision=precision)

    if packed4:
        # Two 4-bit features per streamed byte (reference DenseBin IS_4BIT,
        # dense_bin.hpp): unpack in VMEM, contract the nibble planes into
        # contiguous output halves — identical to _flat_kernel.
        half = (ftile // 2) * b_pad
        hist_ref[0, 0, :, :half] += contract(bins_blk & 15)
        hist_ref[0, 0, :, half:] += contract((bins_blk >> 4) & 15)
    else:
        hist_ref[0, 0] += contract(bins_blk)

    @pl.when(rb == nblocks - 1)
    def _subtract_and_scan():
        small = hist_ref[0, 0, :, :]                     # (C_PAD, fb)
        parent = parent_ref[0]
        big = parent - small         # exact (int32 quantized / f32 sums)
        stats = stats_ref[0]                             # (2, STAT_LANES)
        small_left = stats[0, 4] > 0.5
        left = jnp.where(small_left, small, big)
        right = jnp.where(small_left, big, small)
        hist_ref[0, 0] = left
        hist_ref[0, 1] = right

        nbpf = meta_ref[:, 0:1]                          # (ftile, 1) i32
        nanb = meta_ref[:, 1:2]
        iscat = meta_ref[:, 2:3] > 0
        fmask = meta_ref[:, 3:4] > 0
        biota = jax.lax.broadcasted_iota(jnp.int32, (ftile, b_pad), 1)
        # ORIGINAL-feature-order tie-break keys (lane padding keyed out;
        # meta column 4 carries each plane row's original feature id, so
        # the packed4 plane layout cannot perturb the tie-break).
        okey = meta_ref[:, 4:5]
        keys = jnp.where(biota < key_bins, okey * key_bins + biota,
                         jnp.iinfo(jnp.int32).max)

        def child_payload(hflat, ci):
            h3 = hflat.reshape(C_PAD, ftile, b_pad)
            if has_scale:
                # grower._scale_hist: raw int32 -> f32 * per-channel scale
                G = h3[0].astype(jnp.float32) * scale_ref[0, 0]
                H = h3[1].astype(jnp.float32) * scale_ref[0, 1]
                C = h3[2].astype(jnp.float32) * scale_ref[0, 2]
            else:
                G, H, C = h3[0], h3[1], h3[2]
            tables = scan_tables(
                G, H, C, stats[ci, 0], stats[ci, 1], stats[ci, 2],
                num_bins_per_feature=nbpf, nan_bins=nanb,
                is_categorical=iscat, feature_mask=fmask, cfg=scfg,
                parent_output=stats[ci, 3])
            (gain, bf, bb, dl, ic, GL, HL, CL, GR, HR,
             CR) = select_payload(tables, iscat, scfg, flat_keys=keys,
                                  key_bins=key_bins)
            # Inactive wave slots (lane 5) scanned garbage parents: emit a
            # clean no-split payload — the grower drops these lanes via
            # OOB scatters either way, this just keeps the payload sane.
            gain = jnp.where(stats[ci, 5] > 0.5, gain, -jnp.inf)
            scalars = [gain, bf, bb, dl, ic, GL, HL, CL, GR, HR, CR]
            cat_mask = ((jax.lax.broadcasted_iota(
                jnp.int32, (1, key_bins), 1) == bb)
                & ic).astype(jnp.float32)
            return jnp.concatenate(
                [jnp.asarray(v).astype(jnp.float32).reshape(1, 1)
                 for v in scalars]
                + [jnp.zeros((1, PAYLOAD_SCALARS - len(scalars)),
                             jnp.float32), cat_mask], axis=1)

        pay_ref[0, 0:1, :] = child_payload(left, 0)
        pay_ref[0, 1:2, :] = child_payload(right, 1)


@functools.partial(
    jax.jit, static_argnames=("num_bins", "features", "rows_block", "dtype",
                              "packed4", "scfg", "interpret"))
def fused_wave_call(
    gbins: jnp.ndarray,        # (W, S, ct) gathered smaller-sibling rows
    gvalsT: jnp.ndarray,       # (W, C_PAD, S) gathered channel values
    parent_flat: jnp.ndarray,  # (W, C_PAD, ftile*b_pad) parent histograms
    stats: jnp.ndarray,        # (W, 2, STAT_LANES) per-child scalars
    meta: jnp.ndarray,         # (ftile, 8) i32 [nbpf|nan|is_cat|fmask|...]
    scale3: jnp.ndarray | None = None,   # (1, 4) f32 quantized scales
    *,
    num_bins: int,             # REAL scan bin count (HB)
    features: int,             # real F
    rows_block: int,
    dtype: str,                # f32 | bf16 | int8
    packed4: bool = False,
    scfg: SplitConfig = None,
    interpret: bool = False,
):
    """One fused wave: returns ``(child_hists, payload)`` where
    ``child_hists`` is (W, 2, C_PAD, ftile*b_pad) RAW (left, right)
    histograms in the flat layout and ``payload`` is the (W, 2,
    PAYLOAD_SCALARS + num_bins) per-child SplitInfo block."""
    w, s, ct = gbins.shape
    oh_dtype, acc_dtype, _ = DTYPES[dtype]
    blk, ftile, cols_tile, b_pad = kernel_layout(
        features, num_bins, dtype, rows_block, packed4)
    if ct != cols_tile or parent_flat.shape[-1] != ftile * b_pad:
        raise ValueError(
            f"fused wave needs the single-chunk layout: got {ct} bin "
            f"columns / parent width {parent_flat.shape[-1]} vs layout "
            f"({cols_tile}, {ftile * b_pad}); check wave_layout_fits")
    precision = (jax.lax.Precision.HIGHEST if dtype == "f32"
                 else jax.lax.Precision.DEFAULT)
    pad = (-s) % blk
    if pad:
        gbins = jnp.pad(gbins, ((0, 0), (0, pad), (0, 0)))
        gvalsT = jnp.pad(gvalsT, ((0, 0), (0, 0), (0, pad)))
    nblocks = (s + pad) // blk
    fb = ftile * b_pad
    pay_w = PAYLOAD_SCALARS + num_bins
    has_scale = scale3 is not None
    kern = functools.partial(
        _wave_kernel, nblocks=nblocks, ftile=ftile, b_pad=b_pad,
        key_bins=num_bins, oh_dtype=oh_dtype, acc_dtype=acc_dtype,
        precision=precision, packed4=packed4, scfg=scfg,
        has_scale=has_scale)
    in_specs = [
        pl.BlockSpec((1, blk, ct), lambda i, r: (i, r, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, C_PAD, blk), lambda i, r: (i, 0, r),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, C_PAD, fb), lambda i, r: (i, 0, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, 2, STAT_LANES), lambda i, r: (i, 0, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((ftile, 8), lambda i, r: (0, 0),
                     memory_space=pltpu.VMEM),
    ]
    inputs = [gbins, gvalsT, parent_flat, stats, meta]
    if has_scale:
        in_specs.append(pl.BlockSpec((1, 4), lambda i, r: (0, 0),
                                     memory_space=pltpu.VMEM))
        inputs.append(scale3)
    return pl.pallas_call(
        kern,
        grid=(w, nblocks),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, 2, C_PAD, fb), lambda i, r: (i, 0, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 2, pay_w), lambda i, r: (i, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((w, 2, C_PAD, fb), acc_dtype),
            jax.ShapeDtypeStruct((w, 2, pay_w), jnp.float32),
        ],
        compiler_params=compiler_params_cls()(
            dimension_semantics=("arbitrary", "arbitrary"),
            vmem_limit_bytes=VMEM_LIMIT),
        interpret=interpret,
    )(*inputs)
