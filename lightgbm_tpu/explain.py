"""Prediction explanations: leaf indices and SHAP feature contributions.

Reference: ``Tree::PredictLeafIndex`` and ``Tree::PredictContrib`` (TreeSHAP,
``src/io/tree.cpp``; surfaced via ``GBDT::PredictContrib``, ``gbdt.cpp:640``).
Branchy recursion — kept host-side exactly as the reference keeps it on CPU
even in CUDA mode.  Fast paths run in the native C++ module
(``native/csrc/native.cpp`` ``ltpu_predict_leaf_index`` / ``ltpu_tree_shap``);
the Python implementations below are the portable fallback and the oracle the
native code is tested against.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np


def _tree_children(tree):
    return tree.left_child, tree.right_child


def _decide_left(tree, node: int, bins_row: np.ndarray,
                 nan_bins: np.ndarray) -> bool:
    f = tree.split_feature[node]
    col = int(bins_row[f])
    if col == nan_bins[f] and not tree.is_cat[node]:
        return bool(tree.default_left[node])
    if tree.is_cat[node]:
        b = min(col, tree.cat_mask.shape[1] - 1)
        return bool(tree.cat_mask[node, b])
    return col <= tree.split_bin[node]


def predict_leaf_index(gbdt, X: np.ndarray, start_iteration: int = 0,
                       num_iteration: Optional[int] = None) -> np.ndarray:
    """(N, num_trees) leaf index matrix (reference ``predict_leaf_index``).

    Native C++ traversal when available; vectorized numpy frontier walk
    (``Tree.predict_leaf_bins``) otherwise."""
    from . import native

    bins = gbdt.train_data.binned.apply(X)
    nan_bins = gbdt.train_data.binned.nan_bins
    all_trees = []
    for k in range(gbdt.num_class):
        trees = gbdt.models[k]
        end = len(trees) if num_iteration is None else min(
            len(trees), start_iteration + num_iteration)
        all_trees.append(trees[start_iteration:end])
    n = bins.shape[0]
    t_per_class = max(len(t) for t in all_trees) if all_trees else 0
    out = np.zeros((n, t_per_class * gbdt.num_class), np.int32)
    use_native = native.available()
    if use_native:
        # one widen+copy for the whole ensemble, not one per tree
        bins = np.ascontiguousarray(bins, np.uint16)
    for ti in range(t_per_class):
        for k in range(gbdt.num_class):
            tree = all_trees[k][ti]
            col = ti * gbdt.num_class + k
            if tree.num_leaves <= 1:
                continue
            li = (native.predict_leaf_index(bins, nan_bins, tree)
                  if use_native else None)
            out[:, col] = (li if li is not None
                           else tree.predict_leaf_bins(bins, nan_bins))
    return out


# --------------------------------------------------------------------- TreeSHAP
class _PathElement:
    __slots__ = ("feature_index", "zero_fraction", "one_fraction", "pweight")

    def __init__(self, feature_index, zero_fraction, one_fraction, pweight):
        self.feature_index = feature_index
        self.zero_fraction = zero_fraction
        self.one_fraction = one_fraction
        self.pweight = pweight

    def copy(self):
        return _PathElement(self.feature_index, self.zero_fraction,
                            self.one_fraction, self.pweight)


def _extend(path: List[_PathElement], zero_fraction, one_fraction,
            feature_index):
    path.append(_PathElement(feature_index, zero_fraction, one_fraction,
                             1.0 if len(path) == 0 else 0.0))
    m = len(path) - 1
    for i in range(m - 1, -1, -1):
        path[i + 1].pweight += one_fraction * path[i].pweight * (i + 1) / (m + 1)
        path[i].pweight = zero_fraction * path[i].pweight * (m - i) / (m + 1)


def _unwind(path: List[_PathElement], i: int):
    m = len(path) - 1
    one_fraction = path[i].one_fraction
    zero_fraction = path[i].zero_fraction
    n = path[m].pweight
    for j in range(m - 1, -1, -1):
        if one_fraction != 0.0:
            t = path[j].pweight
            path[j].pweight = n * (m + 1) / ((j + 1) * one_fraction)
            n = t - path[j].pweight * zero_fraction * (m - j) / (m + 1)
        else:
            path[j].pweight = path[j].pweight * (m + 1) / (zero_fraction * (m - j))
    for j in range(i, m):
        path[j].feature_index = path[j + 1].feature_index
        path[j].zero_fraction = path[j + 1].zero_fraction
        path[j].one_fraction = path[j + 1].one_fraction
    path.pop()


def _unwound_sum(path: List[_PathElement], i: int) -> float:
    m = len(path) - 1
    one_fraction = path[i].one_fraction
    zero_fraction = path[i].zero_fraction
    total = 0.0
    n = path[m].pweight
    for j in range(m - 1, -1, -1):
        if one_fraction != 0.0:
            t = n * (m + 1) / ((j + 1) * one_fraction)
            total += t
            n = path[j].pweight - t * zero_fraction * (m - j) / (m + 1)
        else:
            total += path[j].pweight / (zero_fraction * (m - j) / (m + 1))
    return total


def _tree_shap_recurse(tree, bins_row, nan_bins, phi, node, path,
                       parent_zero, parent_one, parent_feature, cover):
    path = [p.copy() for p in path]
    _extend(path, parent_zero, parent_one, parent_feature)
    if node < 0:  # leaf
        leaf = ~node
        for i in range(1, len(path)):
            w = _unwound_sum(path, i)
            el = path[i]
            phi[el.feature_index] += w * (el.one_fraction - el.zero_fraction) \
                * tree.leaf_value[leaf]
        return
    f = tree.split_feature[node]
    go_left = _decide_left(tree, node, bins_row, nan_bins)
    lc, rc = tree.left_child[node], tree.right_child[node]
    hot, cold = (lc, rc) if go_left else (rc, lc)

    def _cover(child):
        if child < 0:
            return float(tree.leaf_count[~child])
        return float(tree.internal_count[child])

    hot_cover, cold_cover = _cover(hot), _cover(cold)
    node_cover = cover if cover > 0 else hot_cover + cold_cover
    incoming_zero, incoming_one = 1.0, 1.0
    path_idx = next((i for i in range(1, len(path))
                     if path[i].feature_index == f), -1)
    if path_idx >= 0:
        incoming_zero = path[path_idx].zero_fraction
        incoming_one = path[path_idx].one_fraction
        _unwind(path, path_idx)
    _tree_shap_recurse(tree, bins_row, nan_bins, phi, hot, path,
                       incoming_zero * hot_cover / max(node_cover, 1e-30),
                       incoming_one, f, hot_cover)
    _tree_shap_recurse(tree, bins_row, nan_bins, phi, cold, path,
                       incoming_zero * cold_cover / max(node_cover, 1e-30),
                       0.0, f, cold_cover)


def _tree_expected_value(tree) -> float:
    nl = tree.num_leaves
    counts = np.maximum(tree.leaf_count[:nl], 0)
    total = counts.sum()
    if total <= 0:
        return 0.0
    return float((tree.leaf_value[:nl] * counts).sum() / total)


def predict_contrib(gbdt, X: np.ndarray, start_iteration: int = 0,
                    num_iteration: Optional[int] = None) -> np.ndarray:
    """(N, (F+1)*K) SHAP values; last column per class is the expected value
    (reference ``PredictContrib``)."""
    bins = gbdt.train_data.binned.apply(X)
    nan_bins = gbdt.train_data.binned.nan_bins
    n = bins.shape[0]
    nf = gbdt.train_data.num_features
    k = gbdt.num_class
    from . import native

    out = np.zeros((n, (nf + 1) * k))
    use_native = native.available()
    for kk in range(k):
        trees = gbdt.models[kk]
        end = len(trees) if num_iteration is None else min(
            len(trees), start_iteration + num_iteration)
        window = trees[start_iteration:end]
        base = gbdt.init_scores[kk]
        col0 = kk * (nf + 1)
        contrib = native.tree_shap(bins, nan_bins, window) \
            if use_native else None
        if contrib is not None:
            out[:, col0: col0 + nf] += contrib[:, :nf]
            for tree in window:
                base += _tree_expected_value(tree)
        else:
            for tree in window:
                base += _tree_expected_value(tree)
                if tree.num_leaves <= 1:
                    continue
                for i in range(n):
                    phi = np.zeros(nf + 1)
                    _tree_shap_recurse(tree, bins[i], nan_bins, phi,
                                       # root is node 0 (internal), >= 0
                                       0, [], 1.0, 1.0, -1, 0.0)
                    out[i, col0: col0 + nf] += phi[:nf]
        out[:, col0 + nf] = base
    return out
