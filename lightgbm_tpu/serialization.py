"""Model text serialization, following the reference's model-file layout.

Reference: ``src/boosting/gbdt_model_text.cpp`` (``SaveModelToString:334``,
``LoadModelFromString:439``) and ``Tree::ToString`` (``src/io/tree.cpp``).
The format mirrors the reference's section structure (header key=value lines,
``Tree=i`` blocks with array lines, ``end of trees``, feature importances,
parameters) and its ``decision_type`` bit layout (bit0 categorical, bit1
default-left, bits 2-3 missing type), so tooling written against the reference's
format has a familiar shape.  One extension: an ``init_scores=`` header line
(the reference folds boost-from-average into tree outputs; we keep it explicit).

Loaded models carry real-valued thresholds and categorical *value* sets, so
prediction runs on raw features without bin mappers (reference ``Tree::Predict``).
"""

from __future__ import annotations

import dataclasses
import hashlib
import os
from typing import Dict, List, Optional

import numpy as np

from .config import Config

_CAT_MASK = 1
_DEFAULT_LEFT_MASK = 2


# ------------------------------------------------- checksummed atomic frames
# Durable single-file publication for checkpoints (resilience/checkpoint.py):
# a fixed header carries a magic, the payload length and a sha256 digest, so
# a torn write (truncation) or bitrot is DETECTED at read time instead of
# deserializing garbage; the write path is write-temp -> flush -> fsync ->
# rename -> fsync(dir), so a crash leaves either the old generation or the
# complete new one, never a partial file under the published name.

FRAME_MAGIC = b"LGTPUCK1"
_FRAME_HEADER_LEN = len(FRAME_MAGIC) + 8 + 32


class FrameCorruptError(ValueError):
    """The frame failed validation (bad magic, truncation, checksum)."""


def write_atomic_frame(path: str, payload: bytes) -> None:
    """Atomically publish ``payload`` at ``path`` inside a checksummed frame."""
    header = (FRAME_MAGIC + len(payload).to_bytes(8, "little")
              + hashlib.sha256(payload).digest())
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "wb") as fh:
            fh.write(header)
            fh.write(payload)
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, path)
    except OSError:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    # fsync the directory so the rename itself survives a crash
    try:
        dfd = os.open(os.path.dirname(os.path.abspath(path)), os.O_RDONLY)
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)
    except OSError:
        pass


def read_frame(path: str) -> bytes:
    """Read and validate a frame; :class:`FrameCorruptError` on any damage."""
    with open(path, "rb") as fh:
        header = fh.read(_FRAME_HEADER_LEN)
        if len(header) < _FRAME_HEADER_LEN \
                or header[: len(FRAME_MAGIC)] != FRAME_MAGIC:
            raise FrameCorruptError(f"{path}: bad or truncated frame header")
        n = int.from_bytes(header[len(FRAME_MAGIC): len(FRAME_MAGIC) + 8],
                           "little")
        digest = header[len(FRAME_MAGIC) + 8:]
        payload = fh.read(n + 1)
    if len(payload) != n:
        raise FrameCorruptError(
            f"{path}: payload length {len(payload)} != declared {n} "
            "(torn write)")
    if hashlib.sha256(payload).digest() != digest:
        raise FrameCorruptError(f"{path}: sha256 mismatch (corrupt payload)")
    return payload


def _fmt_arr(arr, fmt="%.17g") -> str:
    return " ".join(fmt % v for v in np.asarray(arr).ravel())


def _tree_to_string(tree, index: int, mappers, bias: float = 0.0) -> str:
    """Serialize one tree (reference ``Tree::ToString``).  ``bias`` folds
    the boost-from-average constant into the first iteration's leaf values
    (the reference stores no separate init score in the model file)."""
    m = tree.num_splits()
    lines = [f"Tree={index}", f"num_leaves={tree.num_leaves}"]
    cat_nodes = np.nonzero(tree.is_cat[:m])[0]
    lines.append(f"num_cat={len(cat_nodes)}")
    decision_type = np.zeros(m, np.int64)
    decision_type[tree.is_cat[:m]] |= _CAT_MASK
    decision_type[tree.default_left[:m]] |= _DEFAULT_LEFT_MASK
    for i in range(m):
        mt = mappers[tree.split_feature[i]].missing_type if mappers else 2
        decision_type[i] |= (mt & 3) << 2
    # Categorical thresholds: bitsets over raw category values, concatenated
    # with per-node boundaries (reference cat_boundaries_/cat_threshold_).
    cat_boundaries = [0]
    cat_threshold: List[int] = []
    threshold = tree.threshold.astype(np.float64).copy()
    for ci, node in enumerate(cat_nodes):
        f = int(tree.split_feature[node])
        bins_left = np.nonzero(tree.cat_mask[node])[0]
        if mappers is not None and mappers[f].categories is not None:
            cats = mappers[f].categories
            vals = [int(cats[b]) for b in bins_left if b < len(cats)]
        else:
            vals = [int(b) for b in bins_left]
        nwords = (max(vals) // 32 + 1) if vals else 1
        words = [0] * nwords
        for v in vals:
            words[v // 32] |= 1 << (v % 32)
        cat_threshold.extend(words)
        cat_boundaries.append(len(cat_threshold))
        threshold[node] = ci  # categorical nodes store the cat-set index
    lines.append("split_feature=" + _fmt_arr(tree.split_feature[:m], "%d"))
    lines.append("split_gain=" + _fmt_arr(tree.split_gain[:m], "%g"))
    lines.append("threshold=" + _fmt_arr(threshold[:m]))
    lines.append("decision_type=" + _fmt_arr(decision_type, "%d"))
    lines.append("left_child=" + _fmt_arr(tree.left_child[:m], "%d"))
    lines.append("right_child=" + _fmt_arr(tree.right_child[:m], "%d"))
    lines.append("leaf_value=" + _fmt_arr(
        np.asarray(tree.leaf_value[: tree.num_leaves], np.float64) + bias))
    lines.append("leaf_weight="
                 + _fmt_arr(tree.leaf_weight[: tree.num_leaves], "%g"))
    lines.append("leaf_count=" + _fmt_arr(
        tree.leaf_count[: tree.num_leaves].astype(np.int64), "%d"))
    lines.append("internal_value=" + _fmt_arr(
        np.asarray(tree.internal_value[:m], np.float64) + bias, "%g"))
    lines.append("internal_count=" + _fmt_arr(
        tree.internal_count[:m].astype(np.int64), "%d"))
    if len(cat_nodes):
        lines.append("cat_boundaries=" + _fmt_arr(cat_boundaries, "%d"))
        lines.append("cat_threshold=" + _fmt_arr(cat_threshold, "%d"))
    if getattr(tree, "is_linear", False):
        # Linear-leaf fields (reference Tree::ToString is_linear branch).
        nl = tree.num_leaves
        lines.append("is_linear=1")
        # linear leaves predict const + coef.x; the bias folds there too
        lines.append("leaf_const=" + _fmt_arr(
            np.asarray(tree.leaf_const[:nl], np.float64) + bias))
        lines.append("num_features=" + _fmt_arr(
            [len(f) for f in tree.leaf_features[:nl]], "%d"))
        flat_feats = [int(v) for f in tree.leaf_features[:nl] for v in f]
        flat_coefs = [float(v) for c in tree.leaf_coeff[:nl] for v in c]
        lines.append("leaf_features=" + _fmt_arr(flat_feats, "%d"))
        lines.append("leaf_coeff=" + _fmt_arr(flat_coefs))
    lines.append(f"shrinkage={tree.shrinkage:g}")
    lines.append("")
    return "\n".join(lines)


def _loaded_tree_to_string(t: "LoadedTree", index: int,
                           bias: float = 0.0) -> str:
    """Re-serialize a loaded (raw-threshold) tree verbatim — used when saving a
    continuation booster so the base model's trees survive unchanged
    (reference: continuation re-saves the full ensemble)."""
    m = max(t.num_leaves - 1, 0)
    lines = [f"Tree={index}", f"num_leaves={t.num_leaves}"]
    n_cat = int(np.count_nonzero(t.decision_type[:m] & _CAT_MASK)) if m else 0
    lines.append(f"num_cat={n_cat}")
    lines.append("split_feature=" + _fmt_arr(t.split_feature[:m], "%d"))
    lines.append("split_gain=" + _fmt_arr(t.split_gain[:m], "%g"))
    lines.append("threshold=" + _fmt_arr(t.threshold[:m]))
    lines.append("decision_type=" + _fmt_arr(t.decision_type[:m], "%d"))
    lines.append("left_child=" + _fmt_arr(t.left_child[:m], "%d"))
    lines.append("right_child=" + _fmt_arr(t.right_child[:m], "%d"))
    lines.append("leaf_value=" + _fmt_arr(
        np.asarray(t.leaf_value[: t.num_leaves], np.float64) + bias))
    if t.internal_value is not None:
        lines.append("internal_value=" + _fmt_arr(
            np.asarray(t.internal_value[:m], np.float64) + bias, "%g"))
    if t.internal_count is not None:
        lines.append("internal_count=" + _fmt_arr(t.internal_count[:m], "%d"))
    if t.cat_boundaries is not None:
        lines.append("cat_boundaries=" + _fmt_arr(t.cat_boundaries, "%d"))
        lines.append("cat_threshold=" + _fmt_arr(t.cat_threshold, "%d"))
    if t.is_linear:
        nl = t.num_leaves
        lines.append("is_linear=1")
        lines.append("leaf_const=" + _fmt_arr(
            np.asarray(t.leaf_const[:nl], np.float64) + bias))
        lines.append("num_features=" + _fmt_arr(
            [len(f) for f in t.leaf_features[:nl]], "%d"))
        lines.append("leaf_features=" + _fmt_arr(
            [int(v) for f in t.leaf_features[:nl] for v in f], "%d"))
        lines.append("leaf_coeff=" + _fmt_arr(
            [float(v) for c in t.leaf_coeff[:nl] for v in c]))
    lines.append(f"shrinkage={t.shrinkage:g}")
    lines.append("")
    return "\n".join(lines)


def _objective_to_string(cfg, num_class: int) -> str:
    """Reference ``ObjectiveFunction::ToString`` parameter suffixes —
    required for the reference binary to reload our models."""
    name = cfg.objective
    if name == "binary":
        return f"binary sigmoid:{cfg.sigmoid:g}"
    if name == "multiclass":
        return f"multiclass num_class:{num_class}"
    if name == "multiclassova":
        return (f"multiclassova num_class:{num_class} "
                f"sigmoid:{cfg.sigmoid:g}")
    if name == "regression" and cfg.reg_sqrt:
        return "regression sqrt"
    if name == "quantile":
        return f"quantile alpha:{cfg.alpha:g}"
    return name


def model_to_string(gbdt, num_iteration: Optional[int] = None,
                    start_iteration: int = 0,
                    fold_bias: bool = True) -> str:
    """``fold_bias``: write reference-compatible files (boost-from-average
    folded into the first iteration's values, init_scores line zeroed); the
    in-memory prediction mirror passes False to keep init scores explicit
    so ``start_iteration`` slicing stays exact."""
    cfg = gbdt.cfg
    td = gbdt.train_data
    mappers = td.binned.mappers
    base = getattr(gbdt, "base_model", None)
    init_scores = np.asarray(gbdt.init_scores, np.float64).copy()
    if base is not None:
        init_scores[: len(base.init_scores)] += base.init_scores
    out = ["tree", "version=v4",
           f"num_class={gbdt.num_class}",
           f"num_tree_per_iteration={gbdt.num_class}",
           "label_index=0",
           f"max_feature_idx={td.num_features - 1}",
           # reference ObjectiveFunction::ToString suffixes: the loader
           # (ours AND the reference binary) parses these back into config
           # (e.g. binary_objective.hpp:181 "sigmoid:", multiclass
           # "num_class:", regression " sqrt").
           f"objective={_objective_to_string(cfg, gbdt.num_class)}",
           "feature_names=" + " ".join(
               td.feature_names or
               [f"Column_{i}" for i in range(td.num_features)]),
           "feature_infos=" + " ".join(_feature_info(m) for m in mappers),
           # The reference has no init-score line: boost-from-average is
           # folded into the first iteration's leaf values below so the
           # reference binary reloads our models bit-compatibly.  The line
           # stays (zeroed) for our own loader's benefit, and keeps the
           # constant when a partial save drops the first iteration.
           "init_scores=" + _fmt_arr(
               np.zeros_like(init_scores)
               if (fold_bias and start_iteration == 0) else init_scores),
           ""]
    end = None if num_iteration is None else start_iteration + num_iteration
    idx = 0
    # Trees are interleaved per iteration (iter0/class0, iter0/class1, ...)
    # matching the reference's model layout and LoadedModel.predict_raw.
    # Combined indexing: a continuation base model's iterations come first.
    n_base = base.iter_ if base is not None else 0
    n_own = min(len(m) for m in gbdt.models) if gbdt.models else 0
    n_total = n_base + n_own
    iters = range(start_iteration, n_total if end is None else min(end, n_total))
    for t in iters:
        for k in range(gbdt.num_class):
            bias = float(init_scores[k]) \
                if (fold_bias and t == 0 and start_iteration == 0) else 0.0
            if t < n_base:
                out.append(_loaded_tree_to_string(
                    base.trees[t * gbdt.num_class + k], idx, bias))
            else:
                out.append(_tree_to_string(gbdt.models[k][t - n_base], idx,
                                           mappers, bias))
            idx += 1
    out.append("end of trees")
    out.append("")
    # saved_feature_importance_type=1 writes gain importances (reference
    # GBDT::FeatureImportance via saved_feature_importance_type)
    by_gain = getattr(cfg, "saved_feature_importance_type", 0) == 1
    imp = gbdt.feature_importance("gain" if by_gain else "split")
    names = td.feature_names or [f"Column_{i}" for i in range(td.num_features)]
    pairs = sorted(zip(imp, names), reverse=True)
    out.append("feature_importances:")
    out.extend((f"{n}={v:g}" if by_gain else f"{n}={int(v)}")
               for v, n in pairs if v > 0)
    out.append("")
    out.append("parameters:")
    for key, val in sorted(cfg.raw_params.items()):
        out.append(f"[{key}: {val}]")
    out.append("end of parameters")
    return "\n".join(out)


def _feature_info(m) -> str:
    if m.is_categorical:
        return ":".join(str(int(c)) for c in (m.categories if m.categories is not
                                              None else [])) or "none"
    if m.is_trivial or m.upper_bounds is None or len(m.upper_bounds) <= 1:
        return "none"
    return f"[{m.upper_bounds[0]:g}:{m.upper_bounds[-2]:g}]"


# -------------------------------------------------------------------- JSON dump
def _loaded_tree_structure_dict(t: "LoadedTree") -> dict:
    """Nested node dict for a loaded (raw-threshold) tree."""
    import sys
    sys.setrecursionlimit(max(sys.getrecursionlimit(),
                              4 * t.num_leaves + 1000))
    m = max(t.num_leaves - 1, 0)

    def node(idx: int):
        if m == 0 or idx < 0:
            leaf = ~idx if idx < 0 else 0
            return {"leaf_index": int(leaf),
                    "leaf_value": float(t.leaf_value[leaf])
                    if leaf < len(t.leaf_value) else 0.0}
        dt = int(t.decision_type[idx])
        is_cat = bool(dt & _CAT_MASK)
        thr = float(t.threshold[idx])
        if is_cat and t.cat_boundaries is not None:
            ci = int(thr)
            lo, hi = int(t.cat_boundaries[ci]), int(t.cat_boundaries[ci + 1])
            vals = [w * 32 + b for w in range(hi - lo) for b in range(32)
                    if (int(t.cat_threshold[lo + w]) >> b) & 1]
            thr_repr = "||".join(str(v) for v in vals)
        else:
            thr_repr = thr
        return {
            "split_index": int(idx),
            "split_feature": int(t.split_feature[idx]),
            "split_gain": float(t.split_gain[idx]),
            "threshold": thr_repr,
            "decision_type": "==" if is_cat else "<=",
            "default_left": bool(dt & _DEFAULT_LEFT_MASK),
            "missing_type": ["None", "Zero", "NaN"][min((dt >> 2) & 3, 2)],
            "internal_value": (float(t.internal_value[idx])
                               if t.internal_value is not None else 0.0),
            "internal_count": (int(t.internal_count[idx])
                               if t.internal_count is not None else 0),
            "left_child": node(int(t.left_child[idx])),
            "right_child": node(int(t.right_child[idx])),
        }

    return node(0) if m else node(-1)


def _tree_structure_dict(tree, mappers) -> dict:
    """Nested node dict for one tree (reference ``Tree::ToJSON``,
    ``src/io/tree.cpp``)."""
    m = tree.num_splits()

    def node(idx: int):
        if m == 0 or idx < 0:
            leaf = ~idx if idx < 0 else 0
            d = {
                "leaf_index": int(leaf),
                "leaf_value": float(tree.leaf_value[leaf])
                if leaf < len(tree.leaf_value) else 0.0,
            }
            if leaf < len(tree.leaf_count):
                d["leaf_count"] = int(tree.leaf_count[leaf])
                d["leaf_weight"] = float(tree.leaf_weight[leaf])
            return d
        f = int(tree.split_feature[idx])
        is_cat = bool(tree.is_cat[idx])
        d = {
            "split_index": int(idx),
            "split_feature": f,
            "split_gain": float(tree.split_gain[idx]),
            "threshold": (float(tree.threshold[idx]) if not is_cat else
                          "||".join(str(int(b)) for b in
                                    np.nonzero(tree.cat_mask[idx])[0])),
            "decision_type": "==" if is_cat else "<=",
            "default_left": bool(tree.default_left[idx]),
            "missing_type": ["None", "Zero", "NaN"][
                (mappers[f].missing_type & 3) if mappers else 2],
            "internal_value": float(tree.internal_value[idx]),
            "internal_count": int(tree.internal_count[idx]),
            "left_child": node(int(tree.left_child[idx])),
            "right_child": node(int(tree.right_child[idx])),
        }
        return d

    return node(0) if m else node(-1)


def model_to_dict(gbdt, num_iteration: Optional[int] = None,
                  start_iteration: int = 0) -> dict:
    """JSON-dump structure (reference ``GBDT::DumpModel``,
    ``gbdt_model_text.cpp:38``; Python ``Booster.dump_model``)."""
    cfg = gbdt.cfg
    td = gbdt.train_data
    mappers = td.binned.mappers
    names = td.feature_names or [f"Column_{i}"
                                 for i in range(td.num_features)]
    end = None if num_iteration is None else start_iteration + num_iteration
    base = getattr(gbdt, "base_model", None)
    n_base = base.iter_ if base is not None else 0
    n_own = min(len(m) for m in gbdt.models) if gbdt.models else 0
    n_total = n_base + n_own
    iters = range(start_iteration,
                  n_total if end is None else min(end, n_total))
    tree_info = []
    idx = 0
    for t in iters:
        for k in range(gbdt.num_class):
            if t < n_base:
                lt = base.trees[t * gbdt.num_class + k]
                tree_info.append({
                    "tree_index": idx,
                    "num_leaves": int(lt.num_leaves),
                    "num_cat": int(np.count_nonzero(
                        lt.decision_type[: max(lt.num_leaves - 1, 0)]
                        & _CAT_MASK)),
                    "shrinkage": float(lt.shrinkage),
                    "tree_structure": _loaded_tree_structure_dict(lt),
                })
                idx += 1
                continue
            tree = gbdt.models[k][t - n_base]
            tree_info.append({
                "tree_index": idx,
                "num_leaves": int(tree.num_leaves),
                "num_cat": int(np.count_nonzero(
                    tree.is_cat[: tree.num_splits()])),
                "shrinkage": float(tree.shrinkage),
                "tree_structure": _tree_structure_dict(tree, mappers),
            })
            idx += 1
    return {
        "name": "tree",
        "version": "v4",
        "num_class": gbdt.num_class,
        "num_tree_per_iteration": gbdt.num_class,
        "label_index": 0,
        "max_feature_idx": td.num_features - 1,
        "objective": cfg.objective,
        "average_output": cfg.boosting == "rf",
        "feature_names": names,
        "monotone_constraints": list(map(int, td.monotone_constraints))
        if td.monotone_constraints is not None else [],
        "feature_infos": {
            n: {"min_value": (float(m.upper_bounds[0])
                              if m.upper_bounds is not None
                              and len(m.upper_bounds) > 1 else 0.0),
                "max_value": (float(m.upper_bounds[-2])
                              if m.upper_bounds is not None
                              and len(m.upper_bounds) > 1 else 0.0),
                "values": ([int(c) for c in m.categories]
                           if m.categories is not None else [])}
            for n, m in zip(names, mappers)
        },
        "tree_info": tree_info,
    }


# ------------------------------------------------------------------------- load
@dataclasses.dataclass
class LoadedTree:
    """Raw-threshold tree reconstructed from a model string."""

    num_leaves: int
    split_feature: np.ndarray
    threshold: np.ndarray
    decision_type: np.ndarray
    left_child: np.ndarray
    right_child: np.ndarray
    leaf_value: np.ndarray
    split_gain: np.ndarray
    cat_boundaries: Optional[np.ndarray] = None
    cat_threshold: Optional[np.ndarray] = None
    internal_value: Optional[np.ndarray] = None
    internal_count: Optional[np.ndarray] = None
    shrinkage: float = 1.0
    is_linear: bool = False
    leaf_const: Optional[np.ndarray] = None
    leaf_features: Optional[list] = None
    leaf_coeff: Optional[list] = None

    def predict_leaf(self, X: np.ndarray) -> np.ndarray:
        """Leaf index per row (raw-value traversal)."""
        _, leaf = self._walk(X)
        return leaf

    def predict(self, X: np.ndarray) -> np.ndarray:
        """Vectorized raw-value traversal (reference ``Tree::Predict``)."""
        out, _ = self._walk(X)
        return out

    def _walk(self, X: np.ndarray):
        n = X.shape[0]
        out = np.empty(n, np.float64)
        if self.num_leaves <= 1:
            out[:] = self.leaf_value[0] if len(self.leaf_value) else 0.0
            return out, np.zeros(n, np.int64)
        node = np.zeros(n, np.int32)
        leaf_idx = np.zeros(n, np.int64)
        active = np.ones(n, bool)
        is_cat = (self.decision_type & _CAT_MASK) > 0
        dleft = (self.decision_type & _DEFAULT_LEFT_MASK) > 0
        missing_type = (self.decision_type >> 2) & 3
        while active.any():
            idx = np.nonzero(active)[0]
            nd = node[idx]
            f = self.split_feature[nd]
            v = X[idx, f]
            mt = missing_type[nd]
            nan = np.isnan(v)
            # Missing semantics must match the bin-space path
            # (binning.value_to_bin): MissingType None -> NaN maps to the
            # left-most bin (always left); Zero -> |v|<=kZeroThreshold and NaN
            # follow the default direction; NaN -> NaN follows default.
            missing = np.where(mt == 1, nan | (np.abs(v) <= 1e-35), nan)
            gl = np.zeros(len(idx), bool)
            num = ~is_cat[nd]
            gl[num] = v[num] <= self.threshold[nd[num]]
            catm = is_cat[nd]
            if catm.any():
                gl[catm] = self._cat_left(nd[catm], v[catm])
            default_dir = np.where(mt == 0, True, dleft[nd])
            gl = np.where(missing & ~is_cat[nd], default_dir, gl)
            nxt = np.where(gl, self.left_child[nd], self.right_child[nd])
            leaf = nxt < 0
            out[idx[leaf]] = self.leaf_value[~nxt[leaf]]
            leaf_idx[idx[leaf]] = ~nxt[leaf]
            node[idx[~leaf]] = nxt[~leaf]
            active[idx[leaf]] = False
        if self.is_linear:
            for l in range(self.num_leaves):
                sel = np.nonzero(leaf_idx == l)[0]
                if not len(sel):
                    continue
                fl = self.leaf_features[l]
                vals = np.full(len(sel), self.leaf_const[l])
                if len(fl):
                    Xl = X[sel][:, fl]
                    nan = np.isnan(Xl).any(axis=1)
                    vals = vals + Xl @ self.leaf_coeff[l]
                    vals[nan] = self.leaf_value[l]
                out[sel] = vals
        return out, leaf_idx

    def _cat_left(self, nodes: np.ndarray, values: np.ndarray) -> np.ndarray:
        res = np.zeros(len(nodes), bool)
        for i, (nd, v) in enumerate(zip(nodes, values)):
            if not np.isfinite(v) or v < 0:
                continue
            ci = int(self.threshold[nd])
            lo = self.cat_boundaries[ci]
            hi = self.cat_boundaries[ci + 1]
            iv = int(v)
            word = iv // 32
            if lo + word < hi:
                res[i] = bool((self.cat_threshold[lo + word] >> (iv % 32)) & 1)
        return res


class LoadedModel:
    """Prediction-only booster from a model string (reference ``GBDT::
    LoadModelFromString`` + ``Predictor``)."""

    def __init__(self, num_class: int, objective: str, trees: List[LoadedTree],
                 init_scores: np.ndarray, feature_names: List[str],
                 params: Dict[str, str],
                 header: Optional[Dict[str, str]] = None):
        self.num_class = num_class
        self.objective_name = objective
        self.trees = trees
        self.init_scores = init_scores
        self.feature_names = feature_names
        self.num_features = int(
            (header or {}).get("max_feature_idx", len(feature_names) - 1)
        ) + 1 if (header or feature_names) else len(feature_names)
        self.params = params
        self.header = dict(header or {})
        obj_extra = {}
        for tok in objective.split(" ")[1:]:
            # reference ToString suffixes: "sigmoid:1", "num_class:3", "sqrt"
            if ":" in tok:
                key, val = tok.split(":", 1)
                if key in ("sigmoid", "alpha"):
                    obj_extra[key] = val
                elif key == "num_class":
                    obj_extra["num_class"] = val
        cfg_dict = {"objective": objective.split(" ")[0], **obj_extra}
        if num_class > 1:
            cfg_dict["num_class"] = num_class
        self.cfg = Config(cfg_dict)
        from .objectives import create_objective
        self.objective = create_objective(self.cfg) \
            if self.cfg.objective != "custom" else None
        # Objective string extras (reference objective ToString suffixes):
        # "regression sqrt" restores the reg_sqrt back-transform on load.
        if (self.objective is not None and "sqrt" in objective.split()
                and self.cfg.objective == "regression"):
            self.objective.sqrt = True

    @property
    def iter_(self) -> int:
        return len(self.trees) // self.num_class

    @property
    def num_trees(self) -> int:
        return len(self.trees)

    def predict_raw(self, X: np.ndarray, num_iteration: Optional[int] = None,
                    start_iteration: int = 0, pred_early_stop: bool = False,
                    pred_early_stop_freq: int = 10,
                    pred_early_stop_margin: float = 10.0) -> np.ndarray:
        X = np.asarray(X, np.float64)
        n = X.shape[0]
        k = self.num_class
        # normalized like GBDT.predict_raw: no Python wraparound indexing
        start_iteration = max(int(start_iteration), 0)
        out = np.tile(self.init_scores[None, :], (n, 1))
        per_class = [self.trees[i::k] if k > 1 else self.trees
                     for i in range(k)]
        end = (len(per_class[0]) if num_iteration is None else
               min(len(per_class[0]), start_iteration + num_iteration))
        iters = range(start_iteration, end)
        if not pred_early_stop:
            for kk in range(k):
                for it in iters:
                    out[:, kk] += per_class[kk][it].predict(X)
            return out[:, 0] if k == 1 else out
        # Margin-based prediction early stop (reference
        # prediction_early_stop.cpp): every `freq` iterations, rows whose
        # margin (binary: |score|; multiclass: top1-top2) exceeds the
        # threshold stop accumulating further trees.
        active = np.arange(n)
        for step, it in enumerate(iters):
            if len(active) == 0:
                break
            Xa = X[active]
            for kk in range(k):
                out[active, kk] += per_class[kk][it].predict(Xa)
            if (step + 1) % max(pred_early_stop_freq, 1) == 0:
                sub = out[active]
                if k == 1:
                    margin = np.abs(sub[:, 0])
                else:
                    part = np.partition(sub, k - 2, axis=1)
                    margin = part[:, k - 1] - part[:, k - 2]
                active = active[margin <= pred_early_stop_margin]
        return out[:, 0] if k == 1 else out

    def predict(self, X, raw_score: bool = False, num_iteration=None,
                start_iteration: int = 0, **kwargs):
        raw = self.predict_raw(
            X, num_iteration, start_iteration,
            pred_early_stop=bool(kwargs.get("pred_early_stop", False)),
            pred_early_stop_freq=int(kwargs.get("pred_early_stop_freq", 10)),
            pred_early_stop_margin=float(
                kwargs.get("pred_early_stop_margin", 10.0)))
        if raw_score or self.objective is None:
            return raw
        import jax
        import jax.numpy as jnp
        self.objective.cfg = self.cfg
        return np.asarray(jax.device_get(
            self.objective.convert_output(jnp.asarray(raw))))

    def feature_importance(self, importance_type: str = "split") -> np.ndarray:
        nf = len(self.feature_names)
        imp = np.zeros(nf, np.float64)
        for t in self.trees:
            if importance_type == "split":
                np.add.at(imp, t.split_feature, 1.0)
            else:
                np.add.at(imp, t.split_feature, t.split_gain)
        return imp

    def to_string(self, num_iteration: Optional[int] = None,
                  start_iteration: int = 0) -> str:
        """Re-serialize (used by task=refit and continuation saves)."""
        hdr = dict(self.header)
        hdr.setdefault("num_class", str(self.num_class))
        hdr.setdefault("num_tree_per_iteration", str(self.num_class))
        hdr.setdefault("objective", self.objective_name)
        hdr.setdefault("feature_names", " ".join(self.feature_names))
        hdr["init_scores"] = _fmt_arr(self.init_scores)
        out = ["tree"]
        for key in ("version", "num_class", "num_tree_per_iteration",
                    "label_index", "max_feature_idx", "objective",
                    "feature_names", "feature_infos", "init_scores"):
            if key in hdr:
                out.append(f"{key}={hdr[key]}")
        out.append("")
        end_it = (self.iter_ if num_iteration is None
                  else min(self.iter_, start_iteration + num_iteration))
        lo = start_iteration * self.num_class
        hi = end_it * self.num_class
        for i, t in enumerate(self.trees[lo:hi]):
            out.append(_loaded_tree_to_string(t, i))
        out.append("end of trees")
        out.append("")
        out.append("parameters:")
        for key, val in sorted(self.params.items()):
            out.append(f"[{key}: {val}]")
        out.append("end of parameters")
        return "\n".join(out)


def load_model_string(s: str) -> LoadedModel:
    lines = s.splitlines()
    header: Dict[str, str] = {}
    i = 0
    while i < len(lines) and not lines[i].startswith("Tree="):
        line = lines[i].strip()
        if "=" in line:
            key, _, val = line.partition("=")
            header[key] = val
        i += 1
    num_class = int(header.get("num_class", 1))
    init_scores = np.array(
        [float(v) for v in header.get("init_scores", "0").split()])
    if len(init_scores) < num_class:
        init_scores = np.zeros(num_class)
    trees: List[LoadedTree] = []
    while i < len(lines):
        if not lines[i].startswith("Tree="):
            if lines[i].startswith("end of trees"):
                break
            i += 1
            continue
        block: Dict[str, str] = {}
        i += 1
        while i < len(lines) and lines[i].strip() and \
                not lines[i].startswith("Tree=") and \
                not lines[i].startswith("end of trees"):
            key, _, val = lines[i].partition("=")
            block[key] = val
            i += 1
        nl = int(block["num_leaves"])
        geti = lambda k, d=None: (np.array([int(float(x)) for x in
                                  block[k].split()], np.int32)
                                  if k in block else d)
        getf = lambda k, d=None: (np.array([float(x) for x in block[k].split()])
                                  if k in block else d)
        m = max(nl - 1, 0)
        is_linear = block.get("is_linear", "0").strip() == "1"
        leaf_const = leaf_features = leaf_coeff = None
        if is_linear:
            leaf_const = getf("leaf_const", np.zeros(max(nl, 1)))
            counts = geti("num_features", np.zeros(max(nl, 1), np.int32))
            flat_f = geti("leaf_features", np.zeros(0, np.int32))
            flat_c = getf("leaf_coeff", np.zeros(0))
            leaf_features, leaf_coeff, pos = [], [], 0
            for c in counts:
                leaf_features.append(np.asarray(flat_f[pos: pos + c]))
                leaf_coeff.append(np.asarray(flat_c[pos: pos + c]))
                pos += int(c)
        trees.append(LoadedTree(
            num_leaves=nl,
            split_feature=geti("split_feature", np.zeros(m, np.int32)),
            threshold=getf("threshold", np.zeros(m)),
            decision_type=geti("decision_type", np.zeros(m, np.int32)),
            left_child=geti("left_child", np.zeros(m, np.int32)),
            right_child=geti("right_child", np.zeros(m, np.int32)),
            leaf_value=getf("leaf_value", np.zeros(max(nl, 1))),
            split_gain=getf("split_gain", np.zeros(m)),
            cat_boundaries=geti("cat_boundaries"),
            cat_threshold=geti("cat_threshold"),
            internal_value=getf("internal_value"),
            internal_count=geti("internal_count"),
            shrinkage=float(block.get("shrinkage", 1.0)),
            is_linear=is_linear,
            leaf_const=leaf_const,
            leaf_features=leaf_features,
            leaf_coeff=leaf_coeff,
        ))
    params: Dict[str, str] = {}
    for line in lines[i:]:
        line = line.strip()
        if line.startswith("[") and ":" in line:
            key, _, val = line[1:-1].partition(": ")
            params[key] = val
    return LoadedModel(
        num_class=num_class,
        objective=header.get("objective", "regression"),
        trees=trees,
        init_scores=init_scores,
        feature_names=header.get("feature_names", "").split(),
        params=params,
        header=header,
    )
