"""Feature discretization: value -> bin mapping.

TPU-native re-design of the reference ``BinMapper`` (``include/LightGBM/bin.h:85``,
``src/io/bin.cpp:1072`` — greedy equal-count bin finding with ``min_data_in_bin``,
categorical vocabularies, ``MissingType`` None/Zero/NaN).  Differences from the
reference, chosen for the TPU storage model:

- Bins are stored **dense** per feature as ``uint8``/``uint16`` device arrays; there is
  no most-frequent-bin elision (``GetMostFreqBin``/``FixHistogram``) because dense HBM
  histograms do not need it.
- The NaN bin, when present, is always the **last** bin of a feature, so the split
  scan can peel it off with a static slice instead of per-feature bin bookkeeping.
- Categorical bins are ordered by descending category frequency (rare categories
  beyond ``max_bin`` collapse into the last bin).

On the reference's ``SparseBin`` (``src/io/sparse_bin.hpp:73``, delta-encoded
sparse column storage): that structure exists to serve the CPU's pointer-chasing
scan; on TPU the histogram is a dense MXU contraction over gathered row blocks,
so a sparse post-binning layout would force serialized scatters.  The roles
SparseBin plays are covered TPU-natively instead: sparse INGESTION bins straight
from CSC without densifying (``_bin_sparse_matrix``, O(nnz) peak), EFB bundles
mutually-exclusive sparse columns into shared histogram columns (the compaction
win), and 4-bit nibble packing (``ops/histogram.pack_bins4``) halves the dense
matrix whenever every feature fits 16 bins — the reference's own ``IS_4BIT``
dense arm, which is what LightGBM itself uses once sparse columns are bundled.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence

import numpy as np

from . import native

MISSING_NONE = 0
MISSING_ZERO = 1
MISSING_NAN = 2

_KZERO_LO, _KZERO_HI = -1e-35, 1e-35  # reference uses kZeroThreshold = 1e-35


@dataclasses.dataclass
class BinMapper:
    """Per-feature value->bin discretizer (reference ``bin.h:85``)."""

    num_bins: int
    missing_type: int
    is_categorical: bool
    # Numerical: inclusive upper bound of each *value* bin (excludes the NaN bin).
    upper_bounds: Optional[np.ndarray] = None
    # Categorical: category integer value per bin index.
    categories: Optional[np.ndarray] = None
    is_trivial: bool = False  # single-bin feature; carries no signal
    default_bin: int = 0      # bin of value 0.0 (used by sparse paths later)

    @property
    def has_nan_bin(self) -> bool:
        return self.missing_type != MISSING_NONE

    @property
    def nan_bin(self) -> int:
        return self.num_bins - 1 if self.has_nan_bin else -1

    def value_to_bin(self, values: np.ndarray) -> np.ndarray:
        """Vectorized ValueToBin (reference ``bin.h:173``)."""
        v = np.asarray(values, dtype=np.float64)
        if self.is_categorical:
            cats = self.categories
            # Map category value -> bin by table lookup; unseen/negative -> last bin.
            out = np.full(v.shape, self.num_bins - 1, dtype=np.int32)
            vi = np.where(np.isfinite(v), v, -1).astype(np.int64)
            lut_size = int(cats.max()) + 1 if cats.size else 1
            lut = np.full(lut_size, self.num_bins - 1, dtype=np.int32)
            lut[cats] = np.arange(len(cats), dtype=np.int32)
            in_range = (vi >= 0) & (vi < lut_size)
            out[in_range] = lut[vi[in_range]]
            return out
        n_value_bins = self.num_bins - (1 if self.has_nan_bin else 0)
        nb = native.value_to_bin(
            v.ravel(), self.upper_bounds, n_value_bins,
            self.nan_bin, self.missing_type == MISSING_ZERO)
        if nb is not None:
            return nb.reshape(v.shape)
        if self.missing_type == MISSING_ZERO:
            v = np.where((v > _KZERO_LO) & (v < _KZERO_HI), np.nan, v)
        # bin b holds values <= upper_bounds[b]; clip overflow into last value bin.
        bins = np.searchsorted(self.upper_bounds[: n_value_bins - 1], v, side="left")
        bins = bins.astype(np.int32)
        if self.has_nan_bin:
            bins = np.where(np.isnan(v), self.nan_bin, bins)
        else:
            bins = np.where(np.isnan(v), 0, bins)
        return bins

    def bin_to_threshold(self, bin_idx: int) -> float:
        """Real-valued split threshold for ``bin <= bin_idx`` (go-left) decisions."""
        if self.is_categorical:
            return float(bin_idx)
        n_value_bins = self.num_bins - (1 if self.has_nan_bin else 0)
        b = min(int(bin_idx), n_value_bins - 1)
        return float(self.upper_bounds[b])


def _greedy_find_boundaries(
    distinct: np.ndarray,
    counts: np.ndarray,
    max_bins: int,
    total_cnt: int,
    min_data_in_bin: int,
) -> List[float]:
    """Greedy equal-count boundary search (reference ``bin.cpp`` GreedyFindBin).

    Walks distinct values accumulating counts; closes a bin once it holds at least
    ``max(mean_size, min_data_in_bin)`` samples, re-estimating the mean from the
    remainder.  Heavy hitters (count >= mean) always get their own bin.
    """
    n = len(distinct)
    if n == 0:
        return [np.inf]
    if n <= max_bins:
        # Every distinct value gets a bin; boundary = midpoint to next value.
        bounds = [(distinct[i] + distinct[i + 1]) / 2.0 for i in range(n - 1)]
        bounds.append(np.inf)
        return bounds
    bounds: List[float] = []
    rest_cnt = total_cnt
    rest_bins = max_bins
    cur = 0
    i = 0
    while i < n:
        mean_size = rest_cnt / max(rest_bins, 1)
        target = max(mean_size, float(min_data_in_bin))
        cur += counts[i]
        rest_cnt -= counts[i]
        # Close the bin if full, or if the remaining values just fit remaining bins.
        if cur >= target or (n - i - 1) <= (rest_bins - 1 - len(bounds) - 1):
            if i + 1 < n:
                bounds.append((distinct[i] + distinct[i + 1]) / 2.0)
            cur = 0
            rest_bins -= 1
            if len(bounds) >= max_bins - 1:
                break
        i += 1
    bounds.append(np.inf)
    return bounds


def load_forced_bins(path: str, num_features: int,
                     categorical: Sequence[int] = ()) -> Optional[dict]:
    """Parse a forcedbins_filename JSON file into {feature: [bounds]}
    (reference ``DatasetLoader::GetForcedBins``, dataset_loader.cpp:1493:
    array of {"feature": i, "bin_upper_bound": [...]}; categorical
    features are warned and skipped; missing file warns and is ignored)."""
    if not path:
        return None
    import json
    from .utils.log import Log
    try:
        with open(path) as fh:
            spec = json.load(fh)
    except OSError:
        Log.warning(f"Could not open {path}. Will ignore.")
        return None
    cats = set(int(c) for c in categorical)
    out: dict = {}
    for entry in spec:
        fi = int(entry["feature"])
        if fi >= num_features:
            raise ValueError(
                f"forced bins feature {fi} out of range ({num_features})")
        if fi in cats:
            Log.warning(f"Feature {fi} is categorical. Will ignore forced "
                        "bins for this feature.")
            continue
        out[fi] = [float(b) for b in entry["bin_upper_bound"]]
    return out or None


def _bounds_with_forced(distinct, counts, max_bins, total_cnt,
                        min_data_in_bin, forced) -> List[float]:
    """Bin boundaries honoring user-forced upper bounds (reference
    ``FindBinWithPredefinedBin``, bin.cpp:157): the forced bounds become
    boundaries first, then each segment between them gets a greedy-
    equal-count refill proportional to its sample mass, the last segment
    absorbing the remaining budget.

    Forced bounds within ``kZeroThreshold`` (1e-35) of zero are dropped,
    as the reference skips any ``|bound| <= kZeroThreshold`` — it reserves
    that band for its own ±kZeroThreshold boundaries so value 0.0 always
    gets a dedicated bin.  Deviation note: this repo omits those implicit
    zero boundaries REPO-WIDE (``_greedy_find_boundaries`` too, not just
    here) — dense HBM histograms have no most-frequent-bin elision, so
    zero earns a bin only when the data's own mass puts one there; what
    must not differ is the forced-bound filter, else a user bound at/near
    0.0 would create a sliver bin the reference refuses."""
    forced = sorted({float(b) for b in forced
                     if np.isfinite(b) and not (_KZERO_LO <= b <= _KZERO_HI)})
    bounds = forced[: max(max_bins - 1, 0)] + [np.inf]
    free_bins = max_bins - len(bounds)
    to_add: List[float] = []
    vi = 0
    for i, ub in enumerate(bounds):
        seg_start = vi
        cnt_in_bin = 0
        while vi < len(distinct) and distinct[vi] < ub:
            cnt_in_bin += int(counts[vi])
            vi += 1
        remaining = free_bins - len(to_add)
        if i == len(bounds) - 1:
            num_sub = remaining + 1
        else:
            num_sub = min(int(round(cnt_in_bin * free_bins
                                    / max(total_cnt, 1))), remaining) + 1
        if num_sub > 1 and vi > seg_start:
            sub = _greedy_find_boundaries(
                distinct[seg_start:vi], counts[seg_start:vi], num_sub,
                cnt_in_bin, min_data_in_bin)
            to_add.extend(sub[:-1])   # last sub-bound is +inf
    return sorted(bounds[:-1] + to_add) + [np.inf]


def find_bin(
    sample_values: np.ndarray,
    max_bin: int,
    min_data_in_bin: int = 3,
    *,
    is_categorical: bool = False,
    use_missing: bool = True,
    zero_as_missing: bool = False,
    min_data_per_category: int = 1,
    forced_upper_bounds: Optional[Sequence[float]] = None,
) -> BinMapper:
    """Construct a :class:`BinMapper` from sampled values (reference ``FindBin``,
    ``bin.cpp:~150``)."""
    v = np.asarray(sample_values, dtype=np.float64).ravel()
    na_mask = np.isnan(v)
    if zero_as_missing:
        zmask = (v > _KZERO_LO) & (v < _KZERO_HI)
        na_mask = na_mask | zmask
    num_na = int(na_mask.sum())
    vv = v[~na_mask]

    if is_categorical:
        cats_f = vv[vv >= 0]
        cats, counts = np.unique(cats_f.astype(np.int64), return_counts=True)
        order = np.argsort(-counts, kind="stable")
        cats, counts = cats[order], counts[order]
        keep = counts >= min_data_per_category
        if keep.any():
            cats, counts = cats[keep], counts[keep]
        cats = cats[: max_bin - 1] if len(cats) >= max_bin else cats
        num_bins = len(cats) + 1  # final bin: rare/unseen/missing
        if num_bins < 2:
            return BinMapper(num_bins=1, missing_type=MISSING_NONE,
                             is_categorical=True, categories=cats.astype(np.int64),
                             is_trivial=True)
        return BinMapper(
            num_bins=num_bins,
            missing_type=MISSING_NAN if (use_missing and num_na > 0) else MISSING_NONE,
            is_categorical=True,
            categories=cats.astype(np.int64),
        )

    missing_type = MISSING_NONE
    if use_missing and zero_as_missing and num_na > 0:
        missing_type = MISSING_ZERO
    elif use_missing and num_na > 0:
        missing_type = MISSING_NAN

    has_nan_bin = missing_type != MISSING_NONE
    max_value_bins = max_bin - (1 if has_nan_bin else 0)
    uc = native.unique_counts(vv)
    if uc is not None:
        distinct, counts = uc
    else:
        distinct, counts = np.unique(vv, return_counts=True)
    if forced_upper_bounds:
        bounds = _bounds_with_forced(distinct, counts, max_value_bins,
                                     len(vv), min_data_in_bin,
                                     forced_upper_bounds)
    else:
        nb = native.find_boundaries(distinct, counts, max_value_bins,
                                    len(vv), min_data_in_bin)
        if nb is not None:
            bounds = list(nb)
        else:
            bounds = _greedy_find_boundaries(
                distinct, counts, max_value_bins, len(vv), min_data_in_bin
            )
    num_bins = len(bounds) + (1 if has_nan_bin else 0)
    trivial = num_bins <= 1 or (len(distinct) <= 1 and not has_nan_bin)
    ub = np.asarray(bounds, dtype=np.float64)
    default_bin = int(np.searchsorted(ub[:-1], 0.0, side="left")) if len(ub) else 0
    return BinMapper(
        num_bins=max(num_bins, 1),
        missing_type=missing_type,
        is_categorical=False,
        upper_bounds=ub,
        is_trivial=trivial,
        default_bin=default_bin,
    )


def _is_sparse(X) -> bool:
    return hasattr(X, "tocsc") and hasattr(X, "tocsr")


def bin_dataset(
    X: np.ndarray,
    max_bin: int = 255,
    min_data_in_bin: int = 3,
    categorical_features: Sequence[int] = (),
    *,
    use_missing: bool = True,
    zero_as_missing: bool = False,
    sample_cnt: int = 200000,
    random_state: int = 1,
    max_bin_by_feature: Optional[Sequence[int]] = None,
    forced_bins: Optional[dict] = None,
) -> "BinnedData":
    """Bin a full feature matrix. Sampling mirrors the reference's
    ``DatasetLoader::SampleTextDataFromFile`` (``dataset_loader.cpp:1022``): bin
    boundaries come from a row subsample, then the full matrix is discretized.

    scipy sparse inputs are binned column-wise straight from CSC — peak
    memory stays O(nnz) + the (N, F) uint8/16 bin matrix, never a dense f64
    copy (the reference's sparse answer is ``SparseBin``,
    ``src/io/sparse_bin.hpp:73``; here post-binning storage is dense-narrow
    + EFB, so only INGESTION needs the sparse-aware path)."""
    sparse = _is_sparse(X)
    if not sparse:
        X = np.asarray(X)
    n, f = X.shape
    if n > sample_cnt:
        rng = np.random.RandomState(random_state)
        idx = rng.choice(n, size=sample_cnt, replace=False)
        sample = X[idx] if not sparse else X.tocsr()[np.sort(idx)]
    else:
        sample = X
    if sparse:
        sample = sample.tocsc()
    cat_set = set(int(c) for c in categorical_features)
    if max_bin_by_feature is not None:
        # reference CHECKs length == num features and every value > 1
        if len(max_bin_by_feature) != f:
            raise ValueError(
                f"max_bin_by_feature has {len(max_bin_by_feature)} entries "
                f"for {f} features (reference requires an exact match)")
        if any(int(v) <= 1 for v in max_bin_by_feature):
            raise ValueError("max_bin_by_feature values must be > 1")
    mappers: List[BinMapper] = []
    s = sample.shape[0]
    all_nan_cols: List[int] = []
    for j in range(f):
        mb = max_bin
        if max_bin_by_feature is not None:
            mb = int(max_bin_by_feature[j])
        if sparse:
            nz = np.asarray(sample.data[sample.indptr[j]:
                                        sample.indptr[j + 1]], np.float64)
            col = np.zeros(s, np.float64)
            col[: len(nz)] = nz       # find_bin is order-invariant
        else:
            col = sample[:, j]
        if (j not in cat_set and s
                and bool(np.isnan(np.asarray(col, np.float64)).all())):
            all_nan_cols.append(j)
        mappers.append(
            find_bin(
                col, mb, min_data_in_bin,
                is_categorical=(j in cat_set),
                use_missing=use_missing, zero_as_missing=zero_as_missing,
                forced_upper_bounds=(forced_bins or {}).get(j),
            )
        )
    # Ingestion health (docs/ROBUSTNESS.md; reference DatasetLoader
    # feature_pre_filter warnings): a column that is entirely NaN in the
    # binning sample, or binned trivially (constant), can never split —
    # usually an upstream join/pipeline bug worth one loud line.
    const_cols = [j for j, m in enumerate(mappers)
                  if m.is_trivial and j not in all_nan_cols]
    if all_nan_cols or const_cols:
        from .utils.log import Log
        if all_nan_cols:
            Log.warning(
                f"{len(all_nan_cols)} feature column(s) are entirely NaN "
                f"in the binning sample (e.g. {all_nan_cols[:8]}); they "
                "can never split")
        if const_cols:
            Log.warning(
                f"{len(const_cols)} feature column(s) are constant "
                f"(e.g. {const_cols[:8]}); they can never split")
    return BinnedData.from_mappers(X, mappers)


def _bin_sparse_matrix(X, mappers: List["BinMapper"], dtype) -> np.ndarray:
    """Bin a scipy sparse matrix column-wise without densifying: every
    column starts at its zero-value bin, then only the nonzeros are
    discretized and scattered.  Peak extra memory is O(nnz)."""
    csc = X.tocsc()
    n, f = csc.shape
    out = np.empty((n, f), dtype=dtype)
    zero = np.zeros(1, np.float64)
    for j, m in enumerate(mappers):
        out[:, j] = m.value_to_bin(zero)[0]
        lo, hi = csc.indptr[j], csc.indptr[j + 1]
        if hi > lo:
            out[csc.indices[lo:hi], j] = m.value_to_bin(
                np.asarray(csc.data[lo:hi], np.float64)).astype(dtype)
    return out


def predict_dense_chunks(predict_fn, X, chunk: int = 65536) -> np.ndarray:
    """Run a dense-only predict over a sparse matrix in row chunks: peak
    extra memory stays O(chunk * F) instead of the full dense copy (used
    where raw-value tree traversal genuinely needs dense rows — loaded
    models, linear trees)."""
    outs = [np.asarray(predict_fn(
                np.asarray(X[lo:lo + chunk].todense(), np.float64)),
                np.float64)
            for lo in range(0, X.shape[0], chunk)]
    return np.concatenate(outs, axis=0)


def bake_bin_luts(mappers: List["BinMapper"]):
    """Flatten the numerical mappers into the (ubm, nvb, nnb, zam) arrays
    ``native.bin_matrix`` consumes.  Single source of the bin-encoding
    convention — shared by batch binning here and the C API's single-row
    fast path (capi/bridge.py FastConfig)."""
    f = len(mappers)
    max_b = max((len(m.upper_bounds) for m in mappers
                 if m.upper_bounds is not None), default=1)
    ubm = np.full((f, max_b), np.inf, np.float64)
    nvb = np.ones(f, np.int32)
    nnb = np.full(f, -1, np.int32)
    zam = np.zeros(f, np.uint8)
    for j, m in enumerate(mappers):
        if m.is_categorical or m.upper_bounds is None:
            continue
        k = len(m.upper_bounds)
        ubm[j, :k] = m.upper_bounds
        nvb[j] = m.num_bins - (1 if m.has_nan_bin else 0) + 1
        nnb[j] = m.nan_bin if m.has_nan_bin else -1
        zam[j] = 1 if m.missing_type == MISSING_ZERO else 0
    return ubm, nvb, nnb, zam


def _bin_full_matrix(X, mappers: List["BinMapper"], dtype) -> np.ndarray:
    """Bin every column in one threaded native pass (numerical features);
    categorical columns fall back to the per-feature LUT path."""
    if _is_sparse(X):
        return _bin_sparse_matrix(X, mappers, dtype)
    X = np.asarray(X)
    n, f = X.shape
    any_num = any(not m.is_categorical for m in mappers)
    out = None
    if any_num:
        nb = native.bin_matrix(X, *bake_bin_luts(mappers))
        if nb is not None:
            out = nb.astype(dtype, copy=False)
    if out is None:
        out = np.empty((n, f), dtype=dtype)
        for j, m in enumerate(mappers):
            out[:, j] = m.value_to_bin(X[:, j]).astype(dtype)
        return out
    for j, m in enumerate(mappers):
        if m.is_categorical:
            out[:, j] = m.value_to_bin(X[:, j]).astype(dtype)
    return out


@dataclasses.dataclass
class BinnedData:
    """Dense binned matrix + per-feature metadata, ready for device upload."""

    bins: np.ndarray                 # (N, F) uint8/uint16
    mappers: List[BinMapper]
    max_num_bins: int                # B: padded bin axis for device histograms
    upper_bounds_padded: np.ndarray  # (F, B) f32: threshold per (feature, bin)
    nan_bins: np.ndarray             # (F,) int32: NaN bin index or B (none)
    num_bins_per_feature: np.ndarray  # (F,) int32
    is_categorical: np.ndarray       # (F,) bool

    @classmethod
    def from_mappers(cls, X: np.ndarray, mappers: List[BinMapper]) -> "BinnedData":
        max_b = max(max(m.num_bins for m in mappers), 2)
        dtype = np.uint8 if max_b <= 256 else np.uint16
        return cls.from_prebinned(_bin_full_matrix(X, mappers, dtype),
                                  mappers)

    @classmethod
    def from_prebinned(cls, bins: np.ndarray,
                       mappers: List[BinMapper]) -> "BinnedData":
        """Wrap an ALREADY-binned matrix (two-round streaming load bins
        chunk-by-chunk; binary-cache reload stores bins directly)."""
        f = len(mappers)
        max_b = max(max(m.num_bins for m in mappers), 2)
        ub = np.full((f, max_b), np.inf, dtype=np.float32)
        nan_bins = np.full(f, max_b, dtype=np.int32)
        nbpf = np.empty(f, dtype=np.int32)
        is_cat = np.zeros(f, dtype=bool)
        for j, m in enumerate(mappers):
            nbpf[j] = m.num_bins
            is_cat[j] = m.is_categorical
            if m.is_categorical:
                ub[j, : m.num_bins] = np.arange(m.num_bins, dtype=np.float32)
            elif m.upper_bounds is not None:
                k = len(m.upper_bounds)
                ub[j, :k] = m.upper_bounds.astype(np.float32)
            if m.has_nan_bin:
                nan_bins[j] = m.nan_bin
        return cls(
            bins=bins, mappers=mappers, max_num_bins=max_b,
            upper_bounds_padded=ub, nan_bins=nan_bins,
            num_bins_per_feature=nbpf, is_categorical=is_cat,
        )

    @property
    def num_data(self) -> int:
        return self.bins.shape[0]

    @property
    def num_features(self) -> int:
        return self.bins.shape[1]

    def apply(self, X) -> np.ndarray:
        """Bin new data (e.g. a validation set) with the training mappers —
        reference ``LoadFromFileAlignWithOtherDataset`` (``dataset_loader.cpp:299``).
        Accepts dense arrays or scipy sparse (binned straight from CSC)."""
        if _is_sparse(X):
            return _bin_sparse_matrix(X, self.mappers, self.bins.dtype)
        return _bin_full_matrix(np.asarray(X), self.mappers,
                                self.bins.dtype)


# ---------------------------------------------------------------- binary cache
def mappers_to_arrays(mappers: List[BinMapper]) -> dict:
    """Flatten per-feature mappers into fixed arrays for the binary dataset
    cache (reference ``Dataset::SaveBinaryFile``, ``dataset_loader.cpp:417``
    reload path)."""
    f = len(mappers)
    num_bins = np.array([m.num_bins for m in mappers], np.int32)
    missing = np.array([m.missing_type for m in mappers], np.int32)
    is_cat = np.array([m.is_categorical for m in mappers], bool)
    trivial = np.array([m.is_trivial for m in mappers], bool)
    default_bin = np.array([m.default_bin for m in mappers], np.int32)
    ub_flat, ub_off = [], [0]
    cat_flat, cat_off = [], [0]
    for m in mappers:
        ub = m.upper_bounds if m.upper_bounds is not None else np.zeros(0)
        ub_flat.append(np.asarray(ub, np.float64))
        ub_off.append(ub_off[-1] + len(ub))
        cats = m.categories if m.categories is not None else np.zeros(0, np.int64)
        cat_flat.append(np.asarray(cats, np.int64))
        cat_off.append(cat_off[-1] + len(cats))
    return {
        "mapper_num_bins": num_bins, "mapper_missing": missing,
        "mapper_is_cat": is_cat, "mapper_trivial": trivial,
        "mapper_default_bin": default_bin,
        "mapper_ub": np.concatenate(ub_flat) if f else np.zeros(0),
        "mapper_ub_off": np.array(ub_off, np.int64),
        "mapper_cats": np.concatenate(cat_flat) if f else np.zeros(0, np.int64),
        "mapper_cat_off": np.array(cat_off, np.int64),
    }


def mappers_from_arrays(d: dict) -> List[BinMapper]:
    # Materialize members once: NpzFile.__getitem__ decompresses the whole
    # array on every access, which would make this loop O(F^2).
    d = {k: np.asarray(d[k]) for k in (
        "mapper_num_bins", "mapper_missing", "mapper_is_cat",
        "mapper_trivial", "mapper_default_bin", "mapper_ub",
        "mapper_ub_off", "mapper_cats", "mapper_cat_off")}
    f = len(d["mapper_num_bins"])
    out: List[BinMapper] = []
    for j in range(f):
        is_cat = bool(d["mapper_is_cat"][j])
        lo, hi = int(d["mapper_ub_off"][j]), int(d["mapper_ub_off"][j + 1])
        clo, chi = int(d["mapper_cat_off"][j]), int(d["mapper_cat_off"][j + 1])
        out.append(BinMapper(
            num_bins=int(d["mapper_num_bins"][j]),
            missing_type=int(d["mapper_missing"][j]),
            is_categorical=is_cat,
            upper_bounds=None if is_cat else d["mapper_ub"][lo:hi],
            categories=d["mapper_cats"][clo:chi] if is_cat else None,
            is_trivial=bool(d["mapper_trivial"][j]),
            default_bin=int(d["mapper_default_bin"][j]),
        ))
    return out


# ------------------------------------------------------------------------ EFB
@dataclasses.dataclass
class FeatureBundles:
    """Exclusive feature bundling (reference EFB: ``DatasetLoader::FindGroups``
    / ``FeatureGroup``, ``src/io/dataset_loader.cpp`` + ``feature_group.h:26``).

    Mutually (near-)exclusive sparse features share ONE histogram column:
    bundle bin 0 means "every member at its default"; member ``f``'s
    non-default bins ``1..nb_f-1`` occupy ``[offset_f, offset_f + nb_f - 2]``.
    Dense/categorical/non-zero-default features ride along as singleton
    groups with identity bin mapping (``feat_offset == -1``).

    The grower consumes the bundled (N, G) matrix for histograms and row
    partitions, then reconstructs per-ORIGINAL-feature histogram views at
    split-scan time — trees, serialization, and prediction stay entirely in
    original feature space.
    """

    feat_group: np.ndarray    # (F,) int32 — bundle column of each feature
    feat_offset: np.ndarray   # (F,) int32 — non-default-bin offset; -1 = identity
    group_bins: np.ndarray    # (G,) int32 — bins per bundle column
    bins: np.ndarray          # (N, G) bundled matrix

    @property
    def num_groups(self) -> int:
        return len(self.group_bins)

    @property
    def max_group_bins(self) -> int:
        return int(self.group_bins.max()) if len(self.group_bins) else 1

    def bundle_row_matrix(self, bins: np.ndarray) -> np.ndarray:
        """Re-bundle an (N, F) original-bin matrix (e.g. after binary-cache
        reload)."""
        n = bins.shape[0]
        out = np.zeros((n, self.num_groups), dtype=self.bins.dtype)
        for f in range(len(self.feat_group)):
            g, off = int(self.feat_group[f]), int(self.feat_offset[f])
            col = bins[:, f]
            if off < 0:
                out[:, g] = col
            else:
                nz = col > 0
                out[nz, g] = (off + col[nz].astype(np.int32) - 1).astype(
                    out.dtype)
        return out


def build_bundles(binned: "BinnedData", *, max_conflict_rate: float = 0.0,
                  sample_cnt: int = 20000, max_bundle_bins: int = 4096,
                  min_gain_cols: float = 0.75,
                  random_state: int = 3) -> Optional[FeatureBundles]:
    """Greedy conflict-bounded bundling (the EFB paper's Greedy Bundling,
    reference ``FindGroups``).  Returns None when bundling would not shrink
    the column count below ``min_gain_cols * F`` (dense data)."""
    bins = binned.bins
    n, f = bins.shape
    if f < 8:
        return None
    mappers = binned.mappers
    eligible = np.array(
        [(not m.is_categorical) and m.default_bin == 0 and m.num_bins >= 2
         and m.num_bins - 1 <= max_bundle_bins - 1
         for m in mappers])
    if n > sample_cnt:
        rng = np.random.RandomState(random_state)
        sample = bins[rng.choice(n, size=sample_cnt, replace=False)]
    else:
        sample = bins
    s = sample.shape[0]
    nz = sample != 0                                   # (S, F)
    nz_cnt = nz.sum(axis=0)
    budget = int(max_conflict_rate * s)
    nbpf = binned.num_bins_per_feature

    # Greedy: sparsest-first so dense features don't eat bundle capacity.
    order = [int(j) for j in np.argsort(nz_cnt) if eligible[j]]
    bundles: List[List[int]] = []
    bundle_nz: List[np.ndarray] = []
    bundle_bins: List[int] = []
    for j in order:
        extra = int(nbpf[j]) - 1
        placed = False
        for bi in range(len(bundles)):
            if bundle_bins[bi] + extra > max_bundle_bins:
                continue
            conflict = int(np.count_nonzero(bundle_nz[bi] & nz[:, j]))
            if conflict <= budget:
                bundles[bi].append(j)
                bundle_nz[bi] |= nz[:, j]
                bundle_bins[bi] += extra
                placed = True
                break
        if not placed:
            bundles.append([j])
            bundle_nz.append(nz[:, j].copy())
            bundle_bins.append(1 + extra)

    # The greedy pass enforced the budget on a sample only; re-check each
    # multi-member bundle on the FULL matrix with the SAME accumulated
    # criterion the greedy pass used (each of the m-1 additions was allowed
    # <= budget conflicts, so a bundle may hold up to (m-1)*budget total)
    # and evict the worst offender into a singleton until it fits —
    # otherwise out-of-sample conflicts silently lose values last-writer-
    # wins in bundle_row_matrix.  When the sample was the full matrix the
    # greedy pass already enforced this exactly.
    full_budget = int(max_conflict_rate * n)
    n_evicted = 0
    if n > s:
        for bi in range(len(bundles)):
            members = bundles[bi]
            while len(members) > 1:
                nz_cols = bins[:, members] != 0          # (N, m)
                row_nnz = nz_cols.sum(axis=1)
                conflicts = int(np.maximum(row_nnz - 1, 0).sum())
                if conflicts <= (len(members) - 1) * full_budget:
                    break
                overlap = ((row_nnz > 1)[:, None] & nz_cols).sum(axis=0)
                bundles.append([members.pop(int(np.argmax(overlap)))])
                n_evicted += 1
    if n_evicted:
        from .utils.log import Log
        Log.debug(f"EFB: evicted {n_evicted} feature(s) whose full-data "
                  f"conflict count exceeded the sampled budget")

    n_single = f - sum(len(b) for b in bundles)
    n_groups = len(bundles) + n_single
    if n_groups > min_gain_cols * f:
        return None

    feat_group = np.empty(f, np.int32)
    feat_offset = np.full(f, -1, np.int32)
    group_bins = []
    for bi, members in enumerate(bundles):
        off = 1
        for j in members:
            feat_group[j] = bi
            feat_offset[j] = off
            off += int(nbpf[j]) - 1
        group_bins.append(off)
    g = len(bundles)
    for j in range(f):
        if eligible[j]:
            continue
        feat_group[j] = g
        group_bins.append(int(nbpf[j]))
        g += 1

    dtype = np.uint8 if max(group_bins) <= 256 else np.uint16
    assert max(group_bins) <= 65535
    fb = FeatureBundles(
        feat_group=feat_group, feat_offset=feat_offset,
        group_bins=np.asarray(group_bins, np.int32),
        bins=np.zeros((0, len(group_bins)), dtype))
    # conflicts outside the sample resolve last-writer-wins (the reference
    # likewise tolerates bounded conflicts)
    fb.bins = fb.bundle_row_matrix(bins)
    return fb
