"""Model text round-trip tests (reference: gbdt_model_text.cpp save/load)."""

import numpy as np
from sklearn.datasets import make_classification, make_regression

import lightgbm_tpu as lgb


def test_roundtrip_regression(tmp_path):
    X, y = make_regression(n_samples=800, n_features=6, noise=0.1,
                           random_state=0)
    bst = lgb.train({"objective": "regression", "min_data_in_leaf": 5,
                     "verbosity": -1}, lgb.Dataset(X, label=y), 20)
    path = tmp_path / "model.txt"
    bst.save_model(str(path))
    bst2 = lgb.Booster(model_file=str(path))
    np.testing.assert_allclose(bst.predict(X), bst2.predict(X),
                               rtol=1e-5, atol=1e-5)


def test_roundtrip_binary_probabilities():
    X, y = make_classification(n_samples=800, n_features=10, random_state=1)
    bst = lgb.train({"objective": "binary", "verbosity": -1},
                    lgb.Dataset(X, label=y), 15)
    s = bst.model_to_string()
    bst2 = lgb.Booster(model_str=s)
    np.testing.assert_allclose(bst.predict(X), bst2.predict(X),
                               rtol=1e-4, atol=1e-5)
    assert bst2.num_trees() == bst.num_trees()


def test_roundtrip_multiclass():
    X, y = make_classification(n_samples=900, n_features=10, n_informative=8,
                               n_classes=3, random_state=2)
    bst = lgb.train({"objective": "multiclass", "num_class": 3,
                     "verbosity": -1}, lgb.Dataset(X, label=y), 10)
    bst2 = lgb.Booster(model_str=bst.model_to_string())
    np.testing.assert_allclose(bst.predict(X), bst2.predict(X),
                               rtol=1e-4, atol=1e-5)


def test_roundtrip_with_nan_and_categorical():
    rng = np.random.RandomState(3)
    n = 1000
    cat = rng.randint(0, 6, n).astype(float)
    num = rng.randn(n)
    num[::11] = np.nan
    X = np.column_stack([cat, num])
    y = (np.isin(cat, [1, 4]) | np.isnan(num)).astype(int)
    bst = lgb.train({"objective": "binary", "min_data_in_leaf": 5,
                     "verbosity": -1},
                    lgb.Dataset(X, label=y, categorical_feature=[0]), 15)
    bst2 = lgb.Booster(model_str=bst.model_to_string())
    np.testing.assert_allclose(bst.predict(X), bst2.predict(X),
                               rtol=1e-4, atol=1e-5)


def test_model_string_sections():
    X, y = make_regression(n_samples=300, n_features=4, random_state=4)
    bst = lgb.train({"objective": "regression", "min_data_in_leaf": 5,
                     "verbosity": -1}, lgb.Dataset(X, label=y), 3)
    s = bst.model_to_string()
    assert s.startswith("tree\n")
    for section in ("num_class=1", "max_feature_idx=3", "Tree=0",
                    "end of trees", "feature_importances:", "parameters:",
                    "end of parameters"):
        assert section in s


def test_num_iteration_predict():
    X, y = make_regression(n_samples=500, n_features=5, random_state=5)
    bst = lgb.train({"objective": "regression", "min_data_in_leaf": 5,
                     "verbosity": -1}, lgb.Dataset(X, label=y), 20)
    p5 = bst.predict(X, num_iteration=5)
    p20 = bst.predict(X)
    assert np.abs(p20 - y).mean() < np.abs(p5 - y).mean()


def test_saved_feature_importance_type_gain():
    """saved_feature_importance_type=1 writes gain importances (floats)
    into the model file; default 0 writes split counts (reference
    GBDT::SaveModelToFile FeatureImportance selection)."""
    import numpy as np
    import lightgbm_tpu as lgb
    X = np.random.RandomState(0).randn(500, 4)
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(float)
    p = {"objective": "binary", "verbosity": -1, "num_leaves": 7}
    m0 = lgb.train(p, lgb.Dataset(X, label=y), 5).model_to_string()
    m1 = lgb.train({**p, "saved_feature_importance_type": 1},
                   lgb.Dataset(X, label=y), 5).model_to_string()

    def importances(txt):
        lines = txt.split("feature_importances:")[1].split("\n\n")[0]
        return [ln.split("=")[1] for ln in lines.strip().splitlines()]

    assert all(float(v) == int(float(v)) for v in importances(m0))
    gains = importances(m1)
    assert any("." in v or "e" in v for v in gains)   # float gains
    assert all(float(v) > 0 for v in gains)
