"""BinMapper / BinnedData unit tests (reference behavior: bin.cpp FindBin)."""

import numpy as np
import pytest

from lightgbm_tpu.binning import (MISSING_NAN, MISSING_NONE, MISSING_ZERO,
                                  BinnedData, bin_dataset, find_bin)


def test_few_distinct_values_get_own_bins():
    v = np.array([1.0, 1.0, 2.0, 2.0, 3.0] * 10)
    m = find_bin(v, max_bin=255, min_data_in_bin=1)
    assert m.num_bins == 3
    assert m.missing_type == MISSING_NONE
    # upper bounds are inclusive midpoints: 1.5 -> bin 0, 2.5 -> bin 1
    bins = m.value_to_bin(np.array([0.5, 1.0, 1.6, 2.0, 2.6, 3.0, 99.0]))
    assert list(bins) == [0, 0, 1, 1, 2, 2, 2]


def test_greedy_equal_count_binning(rng):
    v = rng.randn(10000)
    m = find_bin(v, max_bin=64, min_data_in_bin=3)
    assert 2 <= m.num_bins <= 64
    bins = m.value_to_bin(v)
    counts = np.bincount(bins, minlength=m.num_bins)
    # Roughly equal-count: no bin more than 5x the mean.
    assert counts.max() < 5 * counts.mean()


def test_nan_goes_to_last_bin(rng):
    v = rng.randn(1000)
    v[::7] = np.nan
    m = find_bin(v, max_bin=32)
    assert m.missing_type == MISSING_NAN
    assert m.nan_bin == m.num_bins - 1
    bins = m.value_to_bin(np.array([np.nan, 0.0]))
    assert bins[0] == m.nan_bin
    assert bins[1] != m.nan_bin


def test_zero_as_missing(rng):
    v = rng.randn(1000)
    v[::5] = 0.0
    m = find_bin(v, max_bin=32, zero_as_missing=True)
    assert m.missing_type == MISSING_ZERO
    bins = m.value_to_bin(np.array([0.0, 1e-40, np.nan]))
    assert (bins == m.nan_bin).all()


def test_monotone_bin_boundaries(rng):
    v = rng.exponential(size=5000)
    m = find_bin(v, max_bin=100)
    ub = m.upper_bounds
    assert (np.diff(ub[:-1]) > 0).all()
    assert ub[-1] == np.inf
    # value_to_bin is monotone in value
    q = np.sort(rng.exponential(size=100))
    assert (np.diff(m.value_to_bin(q)) >= 0).all()


def test_categorical_mapping():
    v = np.array([3.0] * 50 + [7.0] * 30 + [1.0] * 20 + [9.0] * 2)
    m = find_bin(v, max_bin=255, is_categorical=True)
    assert m.is_categorical
    # ordered by frequency: 3 -> bin0, 7 -> bin1, 1 -> bin2, 9 -> bin3
    bins = m.value_to_bin(np.array([3, 7, 1, 9, 12345]))
    assert bins[0] == 0 and bins[1] == 1 and bins[2] == 2
    assert bins[4] == m.num_bins - 1  # unseen -> last ("other") bin


def test_binned_data_apply_matches_train(rng):
    X = rng.randn(500, 5)
    bd = bin_dataset(X, max_bin=32)
    reb = bd.apply(X)
    assert (reb == bd.bins).all()


def test_bin_dataset_respects_max_bin(rng):
    X = rng.randn(2000, 3)
    bd = bin_dataset(X, max_bin=16)
    assert bd.max_num_bins <= 16
    assert (bd.num_bins_per_feature <= 16).all()


def test_interaction_constraints_bracket_string_parses_as_groups():
    """The reference CLI form '[0,1],[2,3]' must parse as TWO groups, not
    be shredded into singleton fragments on every comma (config
    Str2FeatureVec semantics; caught by the differential harness)."""
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.sampling import FeatureSampler
    cfg = Config({"interaction_constraints": "[0,1],[2,3,4]"})
    fs = FeatureSampler(cfg, 6)
    assert fs.interaction_groups == ((0, 1), (2, 3, 4))
    # list-of-lists (python API) parses identically
    cfg2 = Config({"interaction_constraints": [[0, 1], [2, 3, 4]]})
    assert FeatureSampler(cfg2, 6).interaction_groups == ((0, 1), (2, 3, 4))


def test_forced_bins(tmp_path):
    """forcedbins_filename pins user bounds as bin boundaries (reference
    FindBinWithPredefinedBin + GetForcedBins): forced bounds appear
    exactly; remaining budget refills by equal count; categorical features
    warn and ignore; trees then split exactly at forced bounds."""
    import json
    import numpy as np
    import lightgbm_tpu as lgb
    from lightgbm_tpu.binning import find_bin

    rng = np.random.RandomState(0)
    v = rng.randn(5000)
    m = find_bin(v, 16, 1, forced_upper_bounds=[-0.5, 0.5])
    ub = np.asarray(m.upper_bounds)
    assert np.isclose(ub, -0.5).any() and np.isclose(ub, 0.5).any()
    assert len(ub) <= 16

    # end to end: a forced boundary becomes an exact split threshold
    spec = [{"feature": 0, "bin_upper_bound": [0.123]}]
    path = tmp_path / "fb.json"
    path.write_text(json.dumps(spec))
    X = rng.randn(3000, 3)
    y = (X[:, 0] > 0.123).astype(float)
    bst = lgb.train({"objective": "binary", "num_leaves": 3,
                     "verbosity": -1, "forcedbins_filename": str(path)},
                    lgb.Dataset(X, label=y), 5)
    model = bst.model_to_string()
    assert "0.123" in model   # the forced bound is a real threshold
    pred = bst.predict(X)
    assert ((pred > 0.5) == (y > 0.5)).mean() > 0.99
