"""examples/ quickstart corpus smoke test (ISSUE-4 satellite / VERDICT
missing #5): every example's train.conf + predict.conf must run end to end
through the CLI — the reference exercises its examples the same way
(test_consistency.py) so the corpus doubles as living documentation."""

import os
import shutil

import numpy as np
import pytest

from lightgbm_tpu.cli import run

EXAMPLES = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "examples")


@pytest.mark.parametrize("name,model,result,n_pred", [
    ("binary_classification", "LightGBM_model.txt",
     "LightGBM_predict_result.txt", 100),
    ("lambdarank", "LightGBM_rank_model.txt",
     "LightGBM_rank_predict_result.txt", 160),
])
def test_example_trains_and_predicts_via_cli(tmp_path, monkeypatch, name,
                                             model, result, n_pred):
    src = os.path.join(EXAMPLES, name)
    work = tmp_path / name
    shutil.copytree(src, work)
    monkeypatch.chdir(work)
    assert run(["config=train.conf"]) == 0
    assert (work / model).exists()
    assert run(["config=predict.conf"]) == 0
    pred = np.loadtxt(work / result)
    assert pred.shape == (n_pred,)
    assert np.all(np.isfinite(pred))
    if name == "binary_classification":
        # predictions are probabilities and carry real signal on the
        # committed holdout (labels in column 0 of binary.test)
        data = np.loadtxt(work / "binary.test")
        y = data[:, 0]
        assert np.all((pred >= 0) & (pred <= 1))
        acc = np.mean((pred > 0.5) == (y > 0.5))
        assert acc > 0.75, acc


def test_examples_readme_lists_every_example():
    with open(os.path.join(EXAMPLES, "README.md")) as fh:
        txt = fh.read()
    for d in sorted(os.listdir(EXAMPLES)):
        if os.path.isdir(os.path.join(EXAMPLES, d)):
            assert d in txt, f"examples/README.md misses {d}/"
