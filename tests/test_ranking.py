"""Learning-to-rank objective tests (reference: rank_objective.hpp)."""

import numpy as np

import lightgbm_tpu as lgb
from lightgbm_tpu.config import Config
from lightgbm_tpu.metrics import _ndcg_multi


def _make_ranking_data(rng, n_queries=60, docs_per_query=20, n_features=10):
    n = n_queries * docs_per_query
    X = rng.randn(n, n_features)
    relevance_score = X[:, 0] * 2 + X[:, 1] + 0.3 * rng.randn(n)
    # labels 0..4 by within-query quantile of the relevance score
    y = np.zeros(n, np.int64)
    group = np.full(n_queries, docs_per_query)
    for q in range(n_queries):
        sl = slice(q * docs_per_query, (q + 1) * docs_per_query)
        ranks = np.argsort(np.argsort(relevance_score[sl]))
        y[sl] = np.minimum(4, ranks * 5 // docs_per_query)
    return X, y, group


def _ndcg_at5(y, score, group):
    gains = np.power(2.0, np.arange(32)) - 1
    return _ndcg_multi(y, score, group, [5], gains)[0]


def test_lambdarank_improves_ndcg(rng):
    X, y, group = _make_ranking_data(rng)
    ds = lgb.Dataset(X, label=y, group=group)
    bst = lgb.train({"objective": "lambdarank", "min_data_in_leaf": 5,
                     "verbosity": -1, "metric": "none"}, ds, 30)
    pred = bst.predict(X, raw_score=True)
    random_ndcg = _ndcg_at5(y, rng.randn(len(y)), group)
    model_ndcg = _ndcg_at5(y, pred, group)
    assert model_ndcg > random_ndcg + 0.15
    assert model_ndcg > 0.75


def test_rank_xendcg_improves_ndcg(rng):
    X, y, group = _make_ranking_data(rng)
    ds = lgb.Dataset(X, label=y, group=group)
    bst = lgb.train({"objective": "rank_xendcg", "min_data_in_leaf": 5,
                     "verbosity": -1, "metric": "none"}, ds, 30)
    pred = bst.predict(X, raw_score=True)
    model_ndcg = _ndcg_at5(y, pred, group)
    assert model_ndcg > 0.72


def test_ndcg_metric_reported_during_training(rng):
    X, y, group = _make_ranking_data(rng, n_queries=40)
    ds = lgb.Dataset(X, label=y, group=group)
    va = lgb.Dataset(X, label=y, group=group, reference=ds)
    ev = {}
    lgb.train({"objective": "lambdarank", "metric": "ndcg",
               "eval_at": [1, 5], "min_data_in_leaf": 5, "verbosity": -1},
              ds, 10, valid_sets=[va], callbacks=[lgb.record_evaluation(ev)])
    assert "ndcg@1" in ev["valid_0"] and "ndcg@5" in ev["valid_0"]
    assert ev["valid_0"]["ndcg@5"][-1] > ev["valid_0"]["ndcg@5"][0]


def test_unbiased_lambdarank_positions():
    """Position-debiased lambdarank (reference RankingObjective positions +
    UpdatePositionBiasFactors, rank_objective.hpp:43-86,296-333): training
    on position-biased clicks with positions should learn nonzero bias
    factors, monotone-ish in position, and beat the position-blind model on
    the TRUE labels."""
    import lightgbm_tpu as lgb
    from lightgbm_tpu.metrics import _ndcg_multi
    from lightgbm_tpu.ranking import default_label_gain

    gains = default_label_gain()

    rng = np.random.RandomState(5)
    n_q, per_q = 150, 8
    n = n_q * per_q
    X = rng.randn(n, 5)
    true_rel = (X[:, 0] + 0.5 * X[:, 1] > 0.6).astype(np.float64)
    group = np.full(n_q, per_q)
    # presentation position within each query; heavy click bias by position
    position = np.tile(np.arange(per_q), n_q)
    p_click = true_rel * np.clip(1.0 / (1 + position), 0.05, 1.0)
    clicks = (rng.rand(n) < p_click).astype(np.float64)

    params = {"objective": "lambdarank", "num_leaves": 15,
              "min_data_in_leaf": 5, "verbosity": -1, "metric": "none"}
    ds = lgb.Dataset(X, label=clicks, group=group, position=position)
    bst = lgb.train(params, ds, 30)
    obj = bst._gbdt.objective
    bias = np.asarray(obj.pos_bias)
    assert bias.shape == (per_q,)
    assert np.abs(bias).max() > 0.1          # factors actually learned
    # top positions attract positive bias (clicks over-represent them)
    assert bias[0] > bias[-1]

    blind = lgb.train(params, lgb.Dataset(X, label=clicks, group=group), 30)
    nd_unbiased = _ndcg_multi(true_rel, bst.predict(X, raw_score=True),
                              group, [5], gains)[0]
    nd_blind = _ndcg_multi(true_rel, blind.predict(X, raw_score=True),
                           group, [5], gains)[0]
    assert nd_unbiased >= nd_blind - 1e-3


def test_position_side_file_autoload(tmp_path):
    """<data>.position loads automatically (reference Advanced-Topics:108)
    and drives unbiased LambdaRank; constructor positions win over it."""
    import numpy as np
    import lightgbm_tpu as lgb
    from lightgbm_tpu.io.parser import position_side_file

    rng = np.random.RandomState(0)
    n_q, per_q = 120, 10
    n = n_q * per_q
    X = rng.randn(n, 5)
    y = np.clip((X[:, 0] * 2 + rng.randn(n) * 0.3).astype(int) % 5, 0, 4)
    path = tmp_path / "tr.csv"
    np.savetxt(path, np.column_stack([y, X]), delimiter=",", fmt="%.8g")
    np.savetxt(str(path) + ".query", np.full(n_q, per_q), fmt="%d")
    pos = np.tile(np.arange(per_q), n_q)
    np.savetxt(str(path) + ".position", pos, fmt="%d")

    loaded = position_side_file(str(path))
    np.testing.assert_array_equal(loaded, pos)

    ds = lgb.Dataset(str(path))
    bst = lgb.train({"objective": "lambdarank", "verbosity": -1,
                     "num_leaves": 7, "lambdarank_position_bias_regularization": 0.1},
                    ds, 5)
    assert bst.num_trees() == 5
    assert ds.position is not None
