"""Explanation-path tests: golden TreeSHAP values, native/Python parity,
sum-to-prediction, and leaf-index correctness.

Reference: ``Tree::PredictContrib`` (``src/io/tree.cpp``) and the
``predict_contrib`` behaviour tests in
``tests/python_package_test/test_engine.py``.
"""

import itertools

import numpy as np
import pytest
from sklearn.datasets import make_classification

import lightgbm_tpu as lgb
from lightgbm_tpu import native


def _train(n=600, f=6, num_leaves=8, rounds=5, seed=0, **extra):
    X, y = make_classification(n_samples=n, n_features=f, n_informative=4,
                               random_state=seed)
    params = {"objective": "binary", "num_leaves": num_leaves,
              "min_data_in_leaf": 10, "verbosity": -1}
    params.update(extra)
    bst = lgb.train(params, lgb.Dataset(X, label=y), rounds)
    return bst, X, y


def _brute_force_shap(tree_fn, cover_fn, x, nf):
    """Exact Shapley values of a tree via subset enumeration.

    ``tree_fn(S)``: expected tree output when only the features in S take
    x's values and the rest are marginalized by the tree's cover weights
    (the conditional-expectation semantics TreeSHAP implements)."""
    phi = np.zeros(nf)
    feats = list(range(nf))
    import math
    for i in feats:
        others = [f for f in feats if f != i]
        for r in range(len(others) + 1):
            for S in itertools.combinations(others, r):
                w = (math.factorial(len(S))
                     * math.factorial(nf - len(S) - 1) / math.factorial(nf))
                phi[i] += w * (tree_fn(set(S) | {i}) - tree_fn(set(S)))
    return phi


def test_golden_shap_hand_tree():
    """Exact SHAP values on a hand-built 3-leaf tree, verified against
    brute-force Shapley enumeration of the tree's conditional expectation."""
    # Build via training on deterministic data that forces the shape:
    #   root: split f0; left child: split f1.
    rng = np.random.RandomState(0)
    n = 800
    f0 = (rng.rand(n) < 0.5).astype(float)
    f1 = (rng.rand(n) < 0.5).astype(float)
    y = np.where(f0 < 0.5, np.where(f1 < 0.5, 0.0, 1.0), 0.5) \
        + 0.01 * rng.randn(n)
    X = np.stack([f0, f1], axis=1)
    bst = lgb.train({"objective": "regression", "num_leaves": 3,
                     "min_data_in_leaf": 1, "min_sum_hessian_in_leaf": 1e-3,
                     "learning_rate": 1.0, "verbosity": -1},
                    lgb.Dataset(X, label=y), 1)
    tree = bst._gbdt.models[0][0]
    assert tree.num_leaves == 3

    contrib = bst.predict(X[:4], pred_contrib=True)
    pred = bst.predict(X[:4], raw_score=True)

    # The tree's conditional expectation for a feature subset S: walk the
    # tree; at a split on a known feature follow x, otherwise average the
    # children weighted by cover.
    def tree_expect(x_row, S):
        def rec(node):
            if node < 0:
                return float(tree.leaf_value[~node])
            f = int(tree.split_feature[node])
            lc, rc = int(tree.left_child[node]), int(tree.right_child[node])

            def cover(c):
                return float(tree.leaf_count[~c] if c < 0
                             else tree.internal_count[c])
            if f in S:
                bins = bst._gbdt.train_data.binned.apply(x_row[None, :])[0]
                go_left = bins[f] <= tree.split_bin[node]
                return rec(lc if go_left else rc)
            tot = cover(lc) + cover(rc)
            return (cover(lc) * rec(lc) + cover(rc) * rec(rc)) / tot
        return rec(0)

    base = contrib[:, -1]
    for i in range(4):
        golden = _brute_force_shap(
            lambda S: tree_expect(X[i], S), None, X[i], 2)
        np.testing.assert_allclose(contrib[i, :2], golden, rtol=1e-5,
                                   atol=1e-7)
        # sum-to-prediction (local accuracy)
        np.testing.assert_allclose(contrib[i, :2].sum() + base[i], pred[i],
                                   rtol=1e-5, atol=1e-7)


def test_contrib_sums_to_prediction_ensemble():
    bst, X, y = _train(rounds=8)
    contrib = bst.predict(X[:50], pred_contrib=True)
    pred = bst.predict(X[:50], raw_score=True)
    np.testing.assert_allclose(contrib.sum(axis=1), pred, rtol=1e-5,
                               atol=1e-6)


@pytest.mark.skipif(not native.available(), reason="native module unavailable")
def test_native_shap_matches_python_oracle():
    """The C++ TreeSHAP must match the recursive Python implementation
    exactly (same algorithm, same arithmetic)."""
    from lightgbm_tpu.explain import _tree_shap_recurse

    bst, X, y = _train(rounds=4, num_leaves=12)
    g = bst._gbdt
    bins = g.train_data.binned.apply(X[:30])
    nan_bins = g.train_data.binned.nan_bins
    trees = g.models[0][:4]
    got = native.tree_shap(bins, nan_bins, trees)
    assert got is not None
    nf = g.train_data.num_features
    want = np.zeros((30, nf + 1))
    for tree in trees:
        if tree.num_leaves <= 1:
            continue
        for i in range(30):
            phi = np.zeros(nf + 1)
            _tree_shap_recurse(tree, bins[i], nan_bins, phi, 0, [],
                               1.0, 1.0, -1, 0.0)
            want[i] += phi
    np.testing.assert_allclose(got[:, :nf], want[:, :nf], rtol=1e-9,
                               atol=1e-12)


@pytest.mark.skipif(not native.available(), reason="native module unavailable")
def test_native_leaf_index_matches_vectorized_walk():
    bst, X, y = _train(rounds=3, num_leaves=10)
    g = bst._gbdt
    bins = g.train_data.binned.apply(X)
    nan_bins = g.train_data.binned.nan_bins
    for tree in g.models[0]:
        got = native.predict_leaf_index(bins, nan_bins, tree)
        want = tree.predict_leaf_bins(bins, nan_bins)
        np.testing.assert_array_equal(got, want)


def test_leaf_index_routes_to_predicted_leaf():
    bst, X, y = _train(rounds=4)
    li = bst.predict(X[:100], pred_leaf=True)
    g = bst._gbdt
    pred = bst.predict(X[:100], raw_score=True)
    # reconstruct predictions from leaf indices
    acc = np.full(100, g.init_scores[0])
    for t, tree in enumerate(g.models[0]):
        acc += tree.leaf_value[li[:, t]]
    np.testing.assert_allclose(acc, pred, rtol=1e-5, atol=1e-6)
