"""REAL multi-process distributed smoke test.

Reference pattern: tests/distributed/_test_distributed.py:168-196 — spawn
worker processes on localhost, bootstrap ranks from a machine list, train
distributed, assert parity with the single-process result.

Here: 2 OS processes x 4 virtual CPU devices each bootstrap through
``parallel/distributed.py`` (machine-list parse -> rank derivation ->
``jax.distributed.initialize``), build ONE global 8-device mesh spanning
both processes, run the sharded grower over it, and the parent asserts the
resulting tree is IDENTICAL to the single-process serial tree.  This is the
only test where the collectives actually cross a process boundary (gRPC
loopback instead of intra-process threads).
"""

import os
import socket
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

N, F, LEAVES = 8 * 2304, 12, 31


def _make_data():
    rng = np.random.RandomState(7)
    X = rng.randn(N, F)
    y = (X[:, 0] + 0.7 * X[:, 1] * X[:, 2] + 0.3 * rng.randn(N) > 0)
    return X, y.astype(np.float64)


WORKER = r"""
import os, sys
sys.path.insert(0, os.environ["LGB_REPO"])
import _hermetic
jax = _hermetic.force_cpu(4)
import numpy as np
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from lightgbm_tpu.config import Config
from lightgbm_tpu.parallel.distributed import (global_mesh, init_distributed,
                                               is_multi_process, shutdown)
from lightgbm_tpu.parallel import collectives
from lightgbm_tpu.parallel.mesh import DATA_AXIS

rank_expect = int(os.environ["LIGHTGBM_TPU_RANK"])
boot = Config({"machines": os.environ["LGB_MACHINES"], "num_machines": 2,
               "verbosity": -1})
rank, world = init_distributed(boot)
assert (rank, world) == (rank_expect, 2), (rank, world)
assert is_multi_process()
assert len(jax.devices()) == 8, len(jax.devices())
mesh = global_mesh()

# L1 facade over a REAL process boundary: psum of per-device values.
vals = jax.device_put(np.arange(8, dtype=np.float32),
                      NamedSharding(mesh, P(DATA_AXIS)))
got = float(np.asarray(collectives.global_sum(vals, mesh))[0])
assert got == 28.0, got

# sharded grower over the global mesh
sys.path.insert(0, os.path.join(os.environ["LGB_REPO"], "tests"))
from test_distributed_mp import _make_data
import lightgbm_tpu.models.grower as G
from lightgbm_tpu.dataset import TrainData
from lightgbm_tpu.models.gbdt import _split_config

X, y = _make_data()
tcfg = Config({"objective": "binary", "num_leaves": 31,
               "min_data_in_leaf": 20, "verbosity": -1})
td = TrainData.build(X, y, tcfg)
meta = td.feature_meta_device()
gcfg = G.GrowerConfig(num_leaves=31, num_bins=td.binned.max_num_bins,
                      split=_split_config(tcfg))
grow = G.make_grower(gcfg, mesh=mesh, data_axis=DATA_AXIS)
row = NamedSharding(mesh, P(DATA_AXIS))
rep = NamedSharding(mesh, P())
n = X.shape[0]
grad = jax.device_put((0.5 - y).astype(np.float32), row)
hess = jax.device_put(np.full(n, 0.25, np.float32), row)
mask = jax.device_put(np.ones(n, np.float32), row)
bins = jax.device_put(np.asarray(td.binned.bins), NamedSharding(mesh, P(DATA_AXIS, None)))
fmask = jax.device_put(np.ones(X.shape[1], bool), rep)
metas = [jax.device_put(np.asarray(meta[k]), rep)
         for k in ("num_bins_per_feature", "nan_bins", "is_categorical",
                   "monotone")]
tree, _row_leaf = grow(bins, grad, hess, mask, fmask, *metas)
if rank == 0:
    np.savez(os.environ["LGB_OUT"],
             split_feature=np.asarray(tree.split_feature),
             split_bin=np.asarray(tree.split_bin),
             left_child=np.asarray(tree.left_child),
             leaf_value=np.asarray(tree.leaf_value),
             num_leaves=int(tree.num_leaves))
shutdown()
print("WORKER_OK", rank)
"""


def test_two_process_data_parallel_matches_serial(tmp_path):
    # pick two free loopback ports: one for the jax coordinator (entry 0 of
    # the machine list = coordinator, like the reference's rank-0 socket)
    with socket.socket() as s1, socket.socket() as s2:
        s1.bind(("127.0.0.1", 0))
        s2.bind(("127.0.0.1", 0))
        p1, p2 = s1.getsockname()[1], s2.getsockname()[1]
    machines = f"127.0.0.1:{p1},127.0.0.1:{p2}"
    script = tmp_path / "worker.py"
    script.write_text(WORKER)
    out_npz = str(tmp_path / "tree.npz")

    procs = []
    for rank in range(2):
        env = dict(os.environ)
        env.update({"LGB_REPO": REPO, "LGB_MACHINES": machines,
                    "LIGHTGBM_TPU_RANK": str(rank), "LGB_OUT": out_npz,
                    "JAX_PLATFORMS": "cpu"})
        procs.append(subprocess.Popen(
            [sys.executable, "-u", str(script)], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True))
    outs = []
    for pr in procs:
        try:
            out, _ = pr.communicate(timeout=600)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outs.append(out)
    for rank, (pr, out) in enumerate(zip(procs, outs)):
        assert pr.returncode == 0, f"rank {rank} failed:\n{out[-4000:]}"
        assert f"WORKER_OK {rank}" in out

    # single-process serial reference tree on the same data
    import jax.numpy as jnp

    import lightgbm_tpu.models.grower as G
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.dataset import TrainData
    from lightgbm_tpu.models.gbdt import _split_config

    X, y = _make_data()
    tcfg = Config({"objective": "binary", "num_leaves": 31,
                   "min_data_in_leaf": 20, "verbosity": -1})
    td = TrainData.build(X, y, tcfg)
    meta = td.feature_meta_device()
    gcfg = G.GrowerConfig(num_leaves=31, num_bins=td.binned.max_num_bins,
                          split=_split_config(tcfg))
    tree, _ = G.make_grower(gcfg)(
        jnp.asarray(td.binned.bins),
        jnp.asarray((0.5 - y).astype(np.float32)),
        jnp.full(N, 0.25, jnp.float32), jnp.ones(N, jnp.float32),
        jnp.ones(F, bool), meta["num_bins_per_feature"], meta["nan_bins"],
        meta["is_categorical"], meta["monotone"])

    got = np.load(out_npz)
    assert got["num_leaves"] == int(tree.num_leaves)
    np.testing.assert_array_equal(got["split_feature"],
                                  np.asarray(tree.split_feature))
    np.testing.assert_array_equal(got["split_bin"],
                                  np.asarray(tree.split_bin))
    np.testing.assert_array_equal(got["left_child"],
                                  np.asarray(tree.left_child))
    np.testing.assert_allclose(got["leaf_value"],
                               np.asarray(tree.leaf_value),
                               rtol=1e-4, atol=1e-6)


def _launcher_worker(rank, world, n, f):
    """Train one data-parallel tree over the global mesh and return the
    replicated split features (module-level: must pickle under spawn)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    import lightgbm_tpu.models.grower as G
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.dataset import TrainData
    from lightgbm_tpu.models.gbdt import _split_config
    from lightgbm_tpu.parallel.distributed import global_mesh
    from lightgbm_tpu.parallel.mesh import DATA_AXIS

    mesh = global_mesh()
    rng = np.random.RandomState(7)
    X = rng.randn(n, f)
    y = (X[:, 0] + 0.7 * X[:, 1] * X[:, 2] > 0).astype(np.float64)
    cfg = Config({"objective": "binary", "num_leaves": 15,
                  "min_data_in_leaf": 20, "verbosity": -1})
    td = TrainData.build(X, y, cfg)
    meta = td.feature_meta_device()
    grow = G.make_grower(
        G.GrowerConfig(num_leaves=15, num_bins=td.binned.max_num_bins,
                       split=_split_config(cfg)),
        mesh=mesh, data_axis=DATA_AXIS)
    row = NamedSharding(mesh, P(DATA_AXIS))
    rep = NamedSharding(mesh, P())
    tree, _rl = grow(
        jax.device_put(np.asarray(td.binned.bins),
                       NamedSharding(mesh, P(DATA_AXIS, None))),
        jax.device_put((0.5 - y).astype(np.float32), row),
        jax.device_put(np.full(n, 0.25, np.float32), row),
        jax.device_put(np.ones(n, np.float32), row),
        jax.device_put(np.ones(f, bool), rep),
        *[jax.device_put(np.asarray(meta[k]), rep)
          for k in ("num_bins_per_feature", "nan_bins", "is_categorical",
                    "monotone")])
    return (int(tree.num_leaves),
            np.asarray(tree.split_feature).tolist())


def test_launcher_two_workers_match_serial():
    """The dask-style launcher (reference dask.py _train: machine list +
    per-worker jobs) runs the whole bootstrap + train + collect cycle."""
    from lightgbm_tpu.parallel.launcher import launch

    n, f = 8 * 2304, 10
    results = launch(_launcher_worker, 2, args=(n, f),
                     devices_per_worker=4, timeout=600)
    assert len(results) == 2
    assert results[0] == results[1]          # replicated tree state
    nl, feats = results[0]
    assert nl == 15

    # single-process serial tree on the same data
    import jax.numpy as jnp

    import lightgbm_tpu.models.grower as G
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.dataset import TrainData
    from lightgbm_tpu.models.gbdt import _split_config

    rng = np.random.RandomState(7)
    X = rng.randn(n, f)
    y = (X[:, 0] + 0.7 * X[:, 1] * X[:, 2] > 0).astype(np.float64)
    cfg = Config({"objective": "binary", "num_leaves": 15,
                  "min_data_in_leaf": 20, "verbosity": -1})
    td = TrainData.build(X, y, cfg)
    meta = td.feature_meta_device()
    tree, _ = G.make_grower(
        G.GrowerConfig(num_leaves=15, num_bins=td.binned.max_num_bins,
                       split=_split_config(cfg)))(
        jnp.asarray(td.binned.bins),
        jnp.asarray((0.5 - y).astype(np.float32)),
        jnp.full(n, 0.25, jnp.float32), jnp.ones(n, jnp.float32),
        jnp.ones(f, bool), meta["num_bins_per_feature"], meta["nan_bins"],
        meta["is_categorical"], meta["monotone"])
    assert feats == np.asarray(tree.split_feature).tolist()


CLI_WORKER = """
import os, sys
sys.path.insert(0, os.environ["LGB_REPO"])
import _hermetic
jax = _hermetic.force_cpu(4)
from lightgbm_tpu.cli import run
rc = run([f"config={os.environ['LGB_CONF']}"])
assert rc == 0
print("CLI_WORKER_OK", os.environ["LIGHTGBM_TPU_RANK"])
"""


def test_cli_two_process_training(tmp_path):
    """The CLI trains distributed from the reference-style config
    (machines + num_machines + tree_learner=data): 2 OS processes
    bootstrap through jax.distributed, shard rows over the global mesh,
    and rank 0 writes the model (Application::Train parity)."""
    X, y = _make_data()
    train_csv = tmp_path / "train.csv"
    np.savetxt(train_csv, np.column_stack([y, X]), delimiter=",",
               fmt="%.6g")
    with socket.socket() as s1, socket.socket() as s2:
        s1.bind(("127.0.0.1", 0))
        s2.bind(("127.0.0.1", 0))
        p1, p2 = s1.getsockname()[1], s2.getsockname()[1]
    model_out = tmp_path / "model.txt"
    conf = tmp_path / "train.conf"
    conf.write_text(
        "task = train\n"
        "objective = binary\n"
        "num_leaves = 15\n"
        "num_iterations = 5\n"
        "tree_learner = data\n"
        f"machines = 127.0.0.1:{p1},127.0.0.1:{p2}\n"
        "num_machines = 2\n"
        f"data = {train_csv}\n"
        f"output_model = {model_out}\n"
        "verbosity = -1\n")
    script = tmp_path / "cli_worker.py"
    script.write_text(CLI_WORKER)

    procs = []
    for rank in range(2):
        env = dict(os.environ)
        env.update({"LGB_REPO": REPO, "LGB_CONF": str(conf),
                    "LIGHTGBM_TPU_RANK": str(rank),
                    "JAX_PLATFORMS": "cpu"})
        procs.append(subprocess.Popen(
            [sys.executable, "-u", str(script)], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True))
    outs = []
    for pr in procs:
        try:
            out, _ = pr.communicate(timeout=600)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outs.append(out)
    for rank, (pr, out) in enumerate(zip(procs, outs)):
        assert pr.returncode == 0, f"rank {rank} failed:\n{out[-4000:]}"
        assert f"CLI_WORKER_OK {rank}" in out

    assert model_out.exists()
    import lightgbm_tpu as lgb
    bst = lgb.Booster(model_file=str(model_out))
    assert bst.num_trees() == 5
    acc = ((bst.predict(X) > 0.5) == (y > 0.5)).mean()
    assert acc > 0.85, acc
