"""REAL multi-process distributed smoke test.

Reference pattern: tests/distributed/_test_distributed.py:168-196 — spawn
worker processes on localhost, bootstrap ranks from a machine list, train
distributed, assert parity with the single-process result.

Here: 2 OS processes x 4 virtual CPU devices each bootstrap through
``parallel/distributed.py`` (machine-list parse -> rank derivation ->
``jax.distributed.initialize``), build ONE global 8-device mesh spanning
both processes, run the sharded grower over it, and the parent asserts the
resulting tree is IDENTICAL to the single-process serial tree.  This is the
only test where the collectives actually cross a process boundary (gRPC
loopback instead of intra-process threads).
"""

import os
import socket
import subprocess
import sys

import numpy as np
import pytest

from lightgbm_tpu.resilience.watchdog import probe_multiprocess

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# Capability gate (ISSUE-6 satellite): CPU jaxlib raises "Multiprocess
# computations aren't implemented on the CPU backend" — a known platform
# gap, not a regression.  Probe it ONCE (two subprocess workers bootstrap
# jax.distributed over loopback; verdict cached per test process) and skip
# the whole module when real cross-process collectives can't run, so a
# FAILURE here always means a regression.
_MP = probe_multiprocess(num_processes=2, timeout=120.0)
pytestmark = pytest.mark.skipif(
    not _MP.ok,
    reason="jaxlib cannot run multiprocess collectives on this backend: "
           f"{_MP.reason}")

N, F, LEAVES = 8 * 2304, 12, 31


def _make_data():
    rng = np.random.RandomState(7)
    X = rng.randn(N, F)
    y = (X[:, 0] + 0.7 * X[:, 1] * X[:, 2] + 0.3 * rng.randn(N) > 0)
    return X, y.astype(np.float64)


WORKER = r"""
import os, sys
sys.path.insert(0, os.environ["LGB_REPO"])
import _hermetic
jax = _hermetic.force_cpu(4)
import numpy as np
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from lightgbm_tpu.config import Config
from lightgbm_tpu.parallel.distributed import (global_mesh, init_distributed,
                                               is_multi_process, shutdown)
from lightgbm_tpu.parallel import collectives
from lightgbm_tpu.parallel.mesh import DATA_AXIS

rank_expect = int(os.environ["LIGHTGBM_TPU_RANK"])
boot = Config({"machines": os.environ["LGB_MACHINES"], "num_machines": 2,
               "verbosity": -1})
rank, world = init_distributed(boot)
assert (rank, world) == (rank_expect, 2), (rank, world)
assert is_multi_process()
assert len(jax.devices()) == 8, len(jax.devices())
mesh = global_mesh()

# L1 facade over a REAL process boundary: psum of per-device values.
vals = jax.device_put(np.arange(8, dtype=np.float32),
                      NamedSharding(mesh, P(DATA_AXIS)))
got = float(np.asarray(collectives.global_sum(vals, mesh))[0])
assert got == 28.0, got

# sharded grower over the global mesh
sys.path.insert(0, os.path.join(os.environ["LGB_REPO"], "tests"))
from test_distributed_mp import _make_data
import lightgbm_tpu.models.grower as G
from lightgbm_tpu.dataset import TrainData
from lightgbm_tpu.models.gbdt import _split_config

X, y = _make_data()
tcfg = Config({"objective": "binary", "num_leaves": 31,
               "min_data_in_leaf": 20, "verbosity": -1})
td = TrainData.build(X, y, tcfg)
meta = td.feature_meta_device()
gcfg = G.GrowerConfig(num_leaves=31, num_bins=td.binned.max_num_bins,
                      split=_split_config(tcfg))
grow = G.make_grower(gcfg, mesh=mesh, data_axis=DATA_AXIS)
row = NamedSharding(mesh, P(DATA_AXIS))
rep = NamedSharding(mesh, P())
n = X.shape[0]
grad = jax.device_put((0.5 - y).astype(np.float32), row)
hess = jax.device_put(np.full(n, 0.25, np.float32), row)
mask = jax.device_put(np.ones(n, np.float32), row)
bins = jax.device_put(np.asarray(td.binned.bins), NamedSharding(mesh, P(DATA_AXIS, None)))
fmask = jax.device_put(np.ones(X.shape[1], bool), rep)
metas = [jax.device_put(np.asarray(meta[k]), rep)
         for k in ("num_bins_per_feature", "nan_bins", "is_categorical",
                   "monotone")]
tree, _row_leaf = grow(bins, grad, hess, mask, fmask, *metas)
if rank == 0:
    np.savez(os.environ["LGB_OUT"],
             split_feature=np.asarray(tree.split_feature),
             split_bin=np.asarray(tree.split_bin),
             left_child=np.asarray(tree.left_child),
             leaf_value=np.asarray(tree.leaf_value),
             num_leaves=int(tree.num_leaves))
shutdown()
print("WORKER_OK", rank)
"""



def _spawn_two_workers(script_path, extra_env, timeout=600):
    """Shared 2-process scaffolding: pick coordinator ports, spawn both
    rank processes, collect output, assert both succeeded."""
    with socket.socket() as s1, socket.socket() as s2:
        s1.bind(("127.0.0.1", 0))
        s2.bind(("127.0.0.1", 0))
        p1, p2 = s1.getsockname()[1], s2.getsockname()[1]
    machines = f"127.0.0.1:{p1},127.0.0.1:{p2}"
    procs = []
    for rank in range(2):
        env = dict(os.environ)
        env.update({"LGB_REPO": REPO, "LGB_MACHINES": machines,
                    "LIGHTGBM_TPU_RANK": str(rank), "JAX_PLATFORMS": "cpu"})
        env.update(extra_env)
        procs.append(subprocess.Popen(
            [sys.executable, "-u", str(script_path)], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True))
    outs = []
    for pr in procs:
        try:
            out, _ = pr.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outs.append(out)
    for rank, (pr, out) in enumerate(zip(procs, outs)):
        assert pr.returncode == 0, f"rank {rank} failed:\n{out[-4000:]}"
    return machines, outs


def test_two_process_data_parallel_matches_serial(tmp_path):
    script = tmp_path / "worker.py"
    script.write_text(WORKER)
    out_npz = str(tmp_path / "tree.npz")
    _machines, outs = _spawn_two_workers(script, {"LGB_OUT": out_npz})
    for rank, out in enumerate(outs):
        assert f"WORKER_OK {rank}" in out

    # single-process serial reference tree on the same data
    import jax.numpy as jnp

    import lightgbm_tpu.models.grower as G
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.dataset import TrainData
    from lightgbm_tpu.models.gbdt import _split_config

    X, y = _make_data()
    tcfg = Config({"objective": "binary", "num_leaves": 31,
                   "min_data_in_leaf": 20, "verbosity": -1})
    td = TrainData.build(X, y, tcfg)
    meta = td.feature_meta_device()
    gcfg = G.GrowerConfig(num_leaves=31, num_bins=td.binned.max_num_bins,
                          split=_split_config(tcfg))
    tree, _ = G.make_grower(gcfg)(
        jnp.asarray(td.binned.bins),
        jnp.asarray((0.5 - y).astype(np.float32)),
        jnp.full(N, 0.25, jnp.float32), jnp.ones(N, jnp.float32),
        jnp.ones(F, bool), meta["num_bins_per_feature"], meta["nan_bins"],
        meta["is_categorical"], meta["monotone"])

    got = np.load(out_npz)
    assert got["num_leaves"] == int(tree.num_leaves)
    np.testing.assert_array_equal(got["split_feature"],
                                  np.asarray(tree.split_feature))
    np.testing.assert_array_equal(got["split_bin"],
                                  np.asarray(tree.split_bin))
    np.testing.assert_array_equal(got["left_child"],
                                  np.asarray(tree.left_child))
    np.testing.assert_allclose(got["leaf_value"],
                               np.asarray(tree.leaf_value),
                               rtol=1e-4, atol=1e-6)


def _launcher_worker(rank, world, n, f):
    """Train one data-parallel tree over the global mesh and return the
    replicated split features (module-level: must pickle under spawn)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    import lightgbm_tpu.models.grower as G
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.dataset import TrainData
    from lightgbm_tpu.models.gbdt import _split_config
    from lightgbm_tpu.parallel.distributed import global_mesh
    from lightgbm_tpu.parallel.mesh import DATA_AXIS

    mesh = global_mesh()
    rng = np.random.RandomState(7)
    X = rng.randn(n, f)
    y = (X[:, 0] + 0.7 * X[:, 1] * X[:, 2] > 0).astype(np.float64)
    cfg = Config({"objective": "binary", "num_leaves": 15,
                  "min_data_in_leaf": 20, "verbosity": -1})
    td = TrainData.build(X, y, cfg)
    meta = td.feature_meta_device()
    grow = G.make_grower(
        G.GrowerConfig(num_leaves=15, num_bins=td.binned.max_num_bins,
                       split=_split_config(cfg)),
        mesh=mesh, data_axis=DATA_AXIS)
    row = NamedSharding(mesh, P(DATA_AXIS))
    rep = NamedSharding(mesh, P())
    tree, _rl = grow(
        jax.device_put(np.asarray(td.binned.bins),
                       NamedSharding(mesh, P(DATA_AXIS, None))),
        jax.device_put((0.5 - y).astype(np.float32), row),
        jax.device_put(np.full(n, 0.25, np.float32), row),
        jax.device_put(np.ones(n, np.float32), row),
        jax.device_put(np.ones(f, bool), rep),
        *[jax.device_put(np.asarray(meta[k]), rep)
          for k in ("num_bins_per_feature", "nan_bins", "is_categorical",
                    "monotone")])
    return (int(tree.num_leaves),
            np.asarray(tree.split_feature).tolist())


def test_launcher_two_workers_match_serial():
    """The dask-style launcher (reference dask.py _train: machine list +
    per-worker jobs) runs the whole bootstrap + train + collect cycle."""
    from lightgbm_tpu.parallel.launcher import launch

    n, f = 8 * 2304, 10
    results = launch(_launcher_worker, 2, args=(n, f),
                     devices_per_worker=4, timeout=600)
    assert len(results) == 2
    assert results[0] == results[1]          # replicated tree state
    nl, feats = results[0]
    assert nl == 15

    # single-process serial tree on the same data
    import jax.numpy as jnp

    import lightgbm_tpu.models.grower as G
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.dataset import TrainData
    from lightgbm_tpu.models.gbdt import _split_config

    rng = np.random.RandomState(7)
    X = rng.randn(n, f)
    y = (X[:, 0] + 0.7 * X[:, 1] * X[:, 2] > 0).astype(np.float64)
    cfg = Config({"objective": "binary", "num_leaves": 15,
                  "min_data_in_leaf": 20, "verbosity": -1})
    td = TrainData.build(X, y, cfg)
    meta = td.feature_meta_device()
    tree, _ = G.make_grower(
        G.GrowerConfig(num_leaves=15, num_bins=td.binned.max_num_bins,
                       split=_split_config(cfg)))(
        jnp.asarray(td.binned.bins),
        jnp.asarray((0.5 - y).astype(np.float32)),
        jnp.full(n, 0.25, jnp.float32), jnp.ones(n, jnp.float32),
        jnp.ones(f, bool), meta["num_bins_per_feature"], meta["nan_bins"],
        meta["is_categorical"], meta["monotone"])
    assert feats == np.asarray(tree.split_feature).tolist()


CLI_WORKER = """
import os, sys
sys.path.insert(0, os.environ["LGB_REPO"])
import _hermetic
jax = _hermetic.force_cpu(4)
from lightgbm_tpu.cli import run
rc = run([f"config={os.environ['LGB_CONF']}"])
assert rc == 0
print("CLI_WORKER_OK", os.environ["LIGHTGBM_TPU_RANK"])
"""


def test_cli_two_process_training(tmp_path):
    """The CLI trains distributed from the reference-style config
    (machines + num_machines + tree_learner=data): 2 OS processes
    bootstrap through jax.distributed, shard rows over the global mesh,
    and rank 0 writes the model (Application::Train parity)."""
    X, y = _make_data()
    train_csv = tmp_path / "train.csv"
    np.savetxt(train_csv, np.column_stack([y, X]), delimiter=",",
               fmt="%.6g")
    model_out = tmp_path / "model.txt"
    conf = tmp_path / "train.conf"
    script = tmp_path / "cli_worker.py"
    script.write_text(CLI_WORKER)

    # the CLI worker reads machines from the config file, which needs the
    # ports before spawn; reuse the helper's machine list via a placeholder
    # rewritten per spawn is overkill — pick ports once here instead.
    with socket.socket() as s1, socket.socket() as s2:
        s1.bind(("127.0.0.1", 0))
        s2.bind(("127.0.0.1", 0))
        p1, p2 = s1.getsockname()[1], s2.getsockname()[1]
    conf.write_text(
        "task = train\n"
        "objective = binary\n"
        "num_leaves = 15\n"
        "num_iterations = 5\n"
        "tree_learner = data\n"
        f"machines = 127.0.0.1:{p1},127.0.0.1:{p2}\n"
        "num_machines = 2\n"
        f"data = {train_csv}\n"
        f"output_model = {model_out}\n"
        "verbosity = -1\n")

    procs = []
    for rank in range(2):
        env = dict(os.environ)
        env.update({"LGB_REPO": REPO, "LGB_CONF": str(conf),
                    "LIGHTGBM_TPU_RANK": str(rank),
                    "JAX_PLATFORMS": "cpu"})
        procs.append(subprocess.Popen(
            [sys.executable, "-u", str(script)], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True))
    outs = []
    for pr in procs:
        try:
            out, _ = pr.communicate(timeout=600)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outs.append(out)
    for rank, (pr, out) in enumerate(zip(procs, outs)):
        assert pr.returncode == 0, f"rank {rank} failed:\n{out[-4000:]}"
        assert f"CLI_WORKER_OK {rank}" in out

    assert model_out.exists()
    import lightgbm_tpu as lgb
    bst = lgb.Booster(model_file=str(model_out))
    assert bst.num_trees() == 5
    acc = ((bst.predict(X) > 0.5) == (y > 0.5)).mean()
    assert acc > 0.85, acc


PP_WORKER = """
import os, sys
sys.path.insert(0, os.environ["LGB_REPO"])
import _hermetic
jax = _hermetic.force_cpu(4)
import numpy as np
import jax.numpy as jnp

from lightgbm_tpu.binning import BinnedData
from lightgbm_tpu.config import Config
from lightgbm_tpu.parallel.distributed import (global_mesh, init_distributed,
                                               shutdown)
from lightgbm_tpu.parallel.mesh import DATA_AXIS
from lightgbm_tpu.parallel.pre_partition import (global_row_sharded,
                                                 pad_local_rows,
                                                 sync_bin_mappers)
import lightgbm_tpu.models.grower as G
from lightgbm_tpu.models.gbdt import _split_config

rank = int(os.environ["LIGHTGBM_TPU_RANK"])
boot = Config({"machines": os.environ["LGB_MACHINES"], "num_machines": 2,
               "verbosity": -1})
r, world = init_distributed(boot)
assert (r, world) == (rank, 2)
mesh = global_mesh()

# each rank holds a DIFFERENT slice of the data (pre_partition=true)
sys.path.insert(0, os.path.join(os.environ["LGB_REPO"], "tests"))
from test_distributed_mp import _make_data
X, y = _make_data()
cut = 5201                  # odd split exercises device rounding
X_local = X[:cut] if rank == 0 else X[cut:]
y_local = y[:cut] if rank == 0 else y[cut:]

mappers = sync_bin_mappers(X_local, max_bin=63)
binned = BinnedData.from_mappers(X_local, mappers)
grad_l = (0.5 - y_local).astype(np.float32)
hess_l = np.full(len(y_local), 0.25, np.float32)
(arrs, mask_l, n_glob) = pad_local_rows(
    [binned.bins, grad_l, hess_l])
bins_g = global_row_sharded(mesh, arrs[0])
grad_g = global_row_sharded(mesh, arrs[1])
hess_g = global_row_sharded(mesh, arrs[2])
mask_g = global_row_sharded(mesh, mask_l)

tcfg = Config({"objective": "binary", "num_leaves": 31,
               "min_data_in_leaf": 20, "verbosity": -1})
gcfg = G.GrowerConfig(num_leaves=31, num_bins=binned.max_num_bins,
                      split=_split_config(tcfg))
grow = G.make_grower(gcfg, mesh=mesh, data_axis=DATA_AXIS)
from jax.sharding import NamedSharding, PartitionSpec as P
rep = NamedSharding(mesh, P())
meta_arrs = [jax.device_put(np.asarray(a), rep) for a in (
    binned.num_bins_per_feature, binned.nan_bins, binned.is_categorical,
    np.zeros(binned.num_features, np.int32))]
fmask = jax.device_put(np.ones(binned.num_features, bool), rep)
tree, _rl = grow(bins_g, grad_g, hess_g, mask_g, fmask, *meta_arrs)
if rank == 0:
    np.savez(os.environ["LGB_OUT"],
             split_feature=np.asarray(tree.split_feature),
             split_bin=np.asarray(tree.split_bin),
             leaf_value=np.asarray(tree.leaf_value),
             num_leaves=int(tree.num_leaves))
shutdown()
print("PP_WORKER_OK", rank)
"""


def test_pre_partitioned_two_process_matches_serial(tmp_path):
    """pre_partition distributed loading (reference
    DatasetLoader::LoadFromFile(rank, num_machines) + the distributed
    bin-mapper allgather, dataset_loader.cpp:1070): each rank holds only
    its OWN rows, mappers are feature-partitioned + synced, and the global
    sharded grower must produce EXACTLY the tree a single process grows
    from the concatenated data binned with the same synced mappers."""
    from lightgbm_tpu.binning import BinnedData, bin_dataset

    X, y = _make_data()
    cut = 5201          # odd split: exercises per-device padding (4 devs)
    script = tmp_path / "pp_worker.py"
    script.write_text(PP_WORKER)
    out_npz = str(tmp_path / "pp_tree.npz")
    _machines, outs = _spawn_two_workers(script, {"LGB_OUT": out_npz})
    for rank, out in enumerate(outs):
        assert f"PP_WORKER_OK {rank}" in out

    # expected: single process, same per-owner mapper assembly (feature f's
    # boundaries from rank f%2's local sample), padded global row order
    halves = (X[:cut], X[cut:])
    local_mappers = [bin_dataset(h, max_bin=63).mappers for h in halves]
    f = X.shape[1]
    synced = [local_mappers[j % 2][j] for j in range(f)]
    n_shard = max(cut, len(X) - cut)
    n_shard += (-n_shard) % 4            # pad_local_rows device rounding
    bins_parts, g_parts, m_parts = [], [], []
    for rk, h in enumerate(halves):
        binned = BinnedData.from_mappers(h, synced)
        yl = y[:cut] if rk == 0 else y[cut:]
        pad = n_shard - len(h)
        bins_parts.append(np.concatenate(
            [binned.bins, np.zeros((pad, f), binned.bins.dtype)]))
        g_parts.append(np.concatenate(
            [(0.5 - yl).astype(np.float32), np.zeros(pad, np.float32)]))
        m_parts.append(np.concatenate(
            [np.ones(len(h), np.float32), np.zeros(pad, np.float32)]))
    bins_full = np.concatenate(bins_parts)
    grad_full = np.concatenate(g_parts)
    mask_full = np.concatenate(m_parts)
    binned0 = BinnedData.from_prebinned(bins_full, synced)

    import jax.numpy as jnp

    import lightgbm_tpu.models.grower as G
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.models.gbdt import _split_config

    tcfg = Config({"objective": "binary", "num_leaves": 31,
                   "min_data_in_leaf": 20, "verbosity": -1})
    gcfg = G.GrowerConfig(num_leaves=31, num_bins=binned0.max_num_bins,
                          split=_split_config(tcfg))
    tree, _ = G.make_grower(gcfg)(
        jnp.asarray(bins_full), jnp.asarray(grad_full),
        jnp.full(len(bins_full), 0.25, jnp.float32), jnp.asarray(mask_full),
        jnp.ones(f, bool), jnp.asarray(binned0.num_bins_per_feature),
        jnp.asarray(binned0.nan_bins), jnp.asarray(binned0.is_categorical),
        jnp.zeros(f, jnp.int32))
    got = np.load(out_npz)
    nl = int(got["num_leaves"])
    assert nl == int(tree.num_leaves)
    np.testing.assert_array_equal(got["split_feature"][: nl - 1],
                                  np.asarray(tree.split_feature)[: nl - 1])
    np.testing.assert_array_equal(got["split_bin"][: nl - 1],
                                  np.asarray(tree.split_bin)[: nl - 1])
    np.testing.assert_allclose(got["leaf_value"][:nl],
                               np.asarray(tree.leaf_value)[:nl], rtol=1e-5,
                               atol=1e-6)
