"""Distributed training tests over the 8-virtual-device CPU mesh.

Reference pattern: tests/distributed/_test_distributed.py — train distributed,
assert parity with single-machine results.  Here "distributed" is sharding the
same jit program over a Mesh, so parity is exact-compilation-level: we assert the
models match the serial run closely.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P
from sklearn.datasets import make_classification

import lightgbm_tpu as lgb
from lightgbm_tpu.parallel.mesh import (DATA_AXIS, FEATURE_AXIS, make_mesh,
                                        mesh_for_tree_learner)

pytestmark = pytest.mark.skipif(len(jax.devices()) < 8,
                                reason="needs 8 virtual devices")


def _data(n=2000, f=16, seed=0):
    return make_classification(n_samples=n, n_features=f, n_informative=8,
                               random_state=seed)


def test_mesh_construction():
    m = make_mesh(4, 2)
    assert m.devices.shape == (4, 2)
    assert m.axis_names == (DATA_AXIS, FEATURE_AXIS)
    assert mesh_for_tree_learner("serial") is None
    assert mesh_for_tree_learner("data").devices.shape == (8, 1)
    assert mesh_for_tree_learner("feature").devices.shape == (1, 8)


@pytest.mark.parametrize("tree_learner", ["data", "feature"])
def test_sharded_training_matches_serial(tree_learner):
    X, y = _data()
    params = {"objective": "binary", "num_leaves": 15, "min_data_in_leaf": 5,
              "metric": "auc", "verbosity": -1}
    serial = lgb.train(dict(params, tree_learner="serial"),
                       lgb.Dataset(X, label=y), 10)
    sharded = lgb.train(dict(params, tree_learner=tree_learner),
                        lgb.Dataset(X, label=y), 10)
    ps = serial.predict(X, raw_score=True)
    pp = sharded.predict(X, raw_score=True)
    # Same algorithm, same data — differences only from f32 reduction order.
    assert np.corrcoef(ps, pp)[0, 1] > 0.999
    np.testing.assert_allclose(ps, pp, rtol=5e-2, atol=5e-2)


def test_histogram_psum_across_shards():
    """The histogram contraction must produce identical results when rows are
    sharded across devices (the automatic ReduceScatter path)."""
    from lightgbm_tpu.ops.histogram import build_histogram

    rng = np.random.RandomState(0)
    n, f, B = 4096, 8, 32
    bins = rng.randint(0, B, size=(n, f)).astype(np.uint8)
    g = rng.randn(n).astype(np.float32)
    h = rng.rand(n).astype(np.float32)

    ref = build_histogram(jnp.asarray(bins), jnp.asarray(g), jnp.asarray(h),
                          None, num_bins=B, impl="onehot", rows_block=512)

    mesh = make_mesh(8, 1)
    row_sh = NamedSharding(mesh, P(DATA_AXIS))
    bins_sh = jax.device_put(jnp.asarray(bins),
                             NamedSharding(mesh, P(DATA_AXIS, None)))
    g_sh = jax.device_put(jnp.asarray(g), row_sh)
    h_sh = jax.device_put(jnp.asarray(h), row_sh)
    out = build_histogram(bins_sh, g_sh, h_sh, None, num_bins=B,
                          impl="onehot", rows_block=512)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out),
                               rtol=1e-5, atol=1e-4)


def test_dryrun_multichip_entrypoint():
    import sys
    sys.path.insert(0, "/root/repo")
    import __graft_entry__ as ge
    ge.dryrun_multichip(8)
    ge.dryrun_multichip(4)


def test_entry_entrypoint():
    import sys
    sys.path.insert(0, "/root/repo")
    import __graft_entry__ as ge
    fn, args = ge.entry()
    out, num_leaves = jax.jit(fn)(*args)
    assert int(num_leaves) >= 2
    assert out.shape == args[0].shape[:1]


def test_sharded_perm_grower_matches_serial_exactly():
    """The sharded permutation layout must pick the SAME splits as the serial
    grower: all decisions derive from psum'd histograms, so tree structure is
    bitwise-identical and only leaf values see f32 reduce-order noise.

    (Reference parity pattern: tests/python_package_test/test_dual.py:37 —
    near-equal eval metrics across device types.)"""
    import lightgbm_tpu.models.grower as G
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.dataset import TrainData
    from lightgbm_tpu.models.gbdt import _split_config

    n, f = 8 * 4096, 12   # > _MIN_BUCKET rows per shard on 8 shards
    rng = np.random.RandomState(7)
    X = rng.randn(n, f)
    y = (X[:, 0] + 0.7 * X[:, 1] * X[:, 2] + 0.3 * rng.randn(n) > 0)
    cfg = Config({"objective": "binary", "num_leaves": 31,
                  "min_data_in_leaf": 20, "verbosity": -1})
    td = TrainData.build(X, y.astype(np.float64), cfg)
    meta = td.feature_meta_device()
    bins = jnp.asarray(td.binned.bins)
    p = 1.0 / (1.0 + np.exp(0.0))
    grad = jnp.asarray((p - y).astype(np.float32))
    hess = jnp.asarray(np.full(n, p * (1 - p), np.float32))
    mask = jnp.ones(n, jnp.float32)
    fmask = jnp.ones(f, bool)

    for leaf_batch in (1, 4):
        gcfg = G.GrowerConfig(num_leaves=31,
                              num_bins=td.binned.max_num_bins,
                              split=_split_config(cfg),
                              leaf_batch=leaf_batch)
        args = (bins, grad, hess, mask, fmask,
                meta["num_bins_per_feature"], meta["nan_bins"],
                meta["is_categorical"], meta["monotone"])
        tree_s, rl_s = G.make_grower(gcfg)(*args)
        mesh = make_mesh(8, 1)
        tree_m, rl_m = G.make_grower(gcfg, mesh=mesh,
                                     data_axis=DATA_AXIS)(*args)
        # Identical structure: same split features/bins/children everywhere.
        assert int(tree_s.num_leaves) == int(tree_m.num_leaves)
        np.testing.assert_array_equal(np.asarray(tree_s.split_feature),
                                      np.asarray(tree_m.split_feature))
        np.testing.assert_array_equal(np.asarray(tree_s.split_bin),
                                      np.asarray(tree_m.split_bin))
        np.testing.assert_array_equal(np.asarray(tree_s.left_child),
                                      np.asarray(tree_m.left_child))
        np.testing.assert_array_equal(np.asarray(rl_s), np.asarray(rl_m))
        np.testing.assert_allclose(np.asarray(tree_s.leaf_value),
                                   np.asarray(tree_m.leaf_value),
                                   rtol=1e-4, atol=1e-6)


def test_sharded_perm_parity_at_bench_depth():
    """Same exact-structure parity at bench-like depth: 255 leaves,
    leaf_batch=16, 100k rows — exercises the sharded-perm bucket ladder
    deep enough that every bucket branch and the full wave scheduler run
    (VERDICT r3: the 8-leaf dryrun proves lockstep, not depth)."""
    import lightgbm_tpu.models.grower as G
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.dataset import TrainData
    from lightgbm_tpu.models.gbdt import _split_config

    n, f = 8 * 12800, 12                               # 102,400 rows
    rng = np.random.RandomState(11)
    X = rng.randn(n, f)
    y = (X[:, 0] + 0.7 * X[:, 1] * X[:, 2] + np.sin(2 * X[:, 3])
         + 0.3 * rng.randn(n) > 0)
    cfg = Config({"objective": "binary", "num_leaves": 255,
                  "min_data_in_leaf": 20, "verbosity": -1})
    td = TrainData.build(X, y.astype(np.float64), cfg)
    meta = td.feature_meta_device()
    bins = jnp.asarray(td.binned.bins)
    p = 0.5
    grad = jnp.asarray((p - y).astype(np.float32))
    hess = jnp.asarray(np.full(n, p * (1 - p), np.float32))
    args = (bins, grad, hess, jnp.ones(n, jnp.float32), jnp.ones(f, bool),
            meta["num_bins_per_feature"], meta["nan_bins"],
            meta["is_categorical"], meta["monotone"])
    gcfg = G.GrowerConfig(num_leaves=255, num_bins=td.binned.max_num_bins,
                          split=_split_config(cfg), leaf_batch=16)
    tree_s, rl_s = G.make_grower(gcfg)(*args)
    tree_m, rl_m = G.make_grower(gcfg, mesh=make_mesh(8, 1),
                                 data_axis=DATA_AXIS)(*args)
    assert int(tree_s.num_leaves) == int(tree_m.num_leaves) == 255
    np.testing.assert_array_equal(np.asarray(tree_s.split_feature),
                                  np.asarray(tree_m.split_feature))
    np.testing.assert_array_equal(np.asarray(tree_s.split_bin),
                                  np.asarray(tree_m.split_bin))
    np.testing.assert_array_equal(np.asarray(tree_s.left_child),
                                  np.asarray(tree_m.left_child))
    np.testing.assert_array_equal(np.asarray(rl_s), np.asarray(rl_m))
    np.testing.assert_allclose(np.asarray(tree_s.leaf_value),
                               np.asarray(tree_m.leaf_value),
                               rtol=1e-3, atol=1e-5)


def test_feature_parallel_perm_exact_parity():
    """The feature-sharded perm layout (reference
    FeatureParallelTreeLearner: rows replicated, features sharded, local
    scans + SyncUpGlobalBestSplit) must pick the SAME tree as serial, at
    bench-like depth.  This replaces the old mask-layout fallback whose
    per-split cost was O(N * num_leaves); the perm layout's is
    O(leaf rows + N) (VERDICT r3 weak #3)."""
    import lightgbm_tpu.models.grower as G
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.dataset import TrainData
    from lightgbm_tpu.models.gbdt import _split_config

    n, f = 60000, 12
    rng = np.random.RandomState(13)
    X = rng.randn(n, f)
    X[rng.rand(n) < 0.05, 3] = np.nan           # exercise NaN routing
    y = (X[:, 0] + 0.7 * X[:, 1] * X[:, 2] + np.sin(2 * X[:, 4])
         + 0.3 * rng.randn(n) > 0)
    cfg = Config({"objective": "binary", "num_leaves": 255,
                  "min_data_in_leaf": 20, "verbosity": -1})
    td = TrainData.build(X, y.astype(np.float64), cfg)
    meta = td.feature_meta_device()
    gcfg = G.GrowerConfig(num_leaves=255, num_bins=td.binned.max_num_bins,
                          split=_split_config(cfg))
    args = (jnp.asarray(td.binned.bins),
            jnp.asarray((0.5 - y).astype(np.float32)),
            jnp.full(n, 0.25, jnp.float32), jnp.ones(n, jnp.float32),
            jnp.ones(f, bool), meta["num_bins_per_feature"],
            meta["nan_bins"], meta["is_categorical"], meta["monotone"])
    tree_s, rl_s = G.make_grower(gcfg)(*args)
    grow_f = G.make_grower(gcfg, mesh=make_mesh(1, 8), data_axis=DATA_AXIS)
    assert grow_f.fp_capable           # routed to the perm layout, not mask
    tree_f, rl_f = grow_f(*args)
    assert int(tree_s.num_leaves) == int(tree_f.num_leaves) == 255
    np.testing.assert_array_equal(np.asarray(tree_s.split_feature),
                                  np.asarray(tree_f.split_feature))
    np.testing.assert_array_equal(np.asarray(tree_s.split_bin),
                                  np.asarray(tree_f.split_bin))
    np.testing.assert_array_equal(np.asarray(tree_s.default_left),
                                  np.asarray(tree_f.default_left))
    np.testing.assert_array_equal(np.asarray(rl_s), np.asarray(rl_f))
    np.testing.assert_allclose(np.asarray(tree_s.leaf_value),
                               np.asarray(tree_f.leaf_value),
                               rtol=1e-3, atol=1e-5)


def test_feature_parallel_composition_fallback():
    """Knobs the local-scan layout cannot honor (interaction constraints,
    EFB bundling, per-node randomness, CEGB, wave batching, voting,
    intermediate monotone) fall back to the mask layout — capability flag
    off.  Basic monotone constraints DO run on the fp path (the split
    feature's constraint type is broadcast by its owner shard)."""
    import dataclasses

    import lightgbm_tpu.models.grower as G
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.models.gbdt import _split_config

    cfg = Config({"objective": "binary", "verbosity": -1})
    base = dict(num_leaves=15, num_bins=64, split=_split_config(cfg))
    mesh = make_mesh(1, 8)
    assert G.make_grower(G.GrowerConfig(**base), mesh=mesh,
                         data_axis=DATA_AXIS).fp_capable
    sp = base["split"]
    for bad in (dict(interaction_groups=((0, 1), (2, 3))),
                dict(bundled=True, hist_bins=64),
                dict(feature_fraction_bynode=0.5),
                dict(leaf_batch=4),
                dict(voting=True),
                dict(split=dataclasses.replace(sp, extra_trees=True)),
                dict(split=dataclasses.replace(sp, use_cegb=True)),
                dict(mono_intermediate=True,
                     split=dataclasses.replace(sp, has_monotone=True))):
        g = G.make_grower(G.GrowerConfig(**dict(base, **bad)), mesh=mesh,
                          data_axis=DATA_AXIS)
        assert not g.fp_capable, bad
    # basic monotone stays ON the fp path
    g = G.make_grower(G.GrowerConfig(**dict(
        base, split=dataclasses.replace(sp, has_monotone=True))),
        mesh=mesh, data_axis=DATA_AXIS)
    assert g.fp_capable


def test_sharded_training_metric_parity():
    """End-to-end data-parallel training must match serial at METRIC level
    (reference test_dual.py:37 asserts near-equal evals, not loose corr)."""
    from lightgbm_tpu.metrics import _auc

    n, f = 8 * 4096, 10
    rng = np.random.RandomState(3)
    X = rng.randn(n, f)
    logits = X[:, 0] + 0.5 * X[:, 1] * X[:, 2]
    y = (rng.rand(n) < 1 / (1 + np.exp(-logits))).astype(np.float64)
    params = {"objective": "binary", "num_leaves": 31, "min_data_in_leaf": 20,
              "verbosity": -1}
    serial = lgb.train(dict(params, tree_learner="serial"),
                       lgb.Dataset(X, label=y), 5)
    sharded = lgb.train(dict(params, tree_learner="data"),
                        lgb.Dataset(X, label=y), 5)
    ps = serial.predict(X, raw_score=True)
    pp = sharded.predict(X, raw_score=True)
    auc_s = _auc(y, ps, None, None)
    auc_p = _auc(y, pp, None, None)
    assert abs(auc_s - auc_p) < 1e-3
    np.testing.assert_allclose(ps, pp, rtol=1e-3, atol=1e-3)


def _grower_collective_wire_bytes(gcfg, n=8 * 2304, f=64):
    """Total collective WIRE bytes (ring model: all-reduce 2(K-1)/K,
    reduce-scatter (K-1)/K) in the compiled sharded grower HLO."""
    import lightgbm_tpu.models.grower as G
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.dataset import TrainData
    from lightgbm_tpu.models.gbdt import _split_config
    from tools.comm_census import collective_census

    rng = np.random.RandomState(0)
    X = rng.randn(n, f)
    y = (X[:, 0] > 0).astype(np.float64)
    cfg = Config({"objective": "binary", "verbosity": -1})
    td = TrainData.build(X, y, cfg)
    mesh = make_mesh(8, 1)
    grow = G.make_grower(gcfg, mesh=mesh, data_axis=DATA_AXIS)
    meta = td.feature_meta_device()
    args = (jnp.asarray(td.binned.bins),
            jnp.zeros(n, jnp.float32), jnp.ones(n, jnp.float32),
            jnp.ones(n, jnp.float32), jnp.ones(f, bool),
            meta["num_bins_per_feature"], meta["nan_bins"],
            meta["is_categorical"], meta["monotone"])
    txt = grow.lower(*args).compile().as_text()
    return sum(o["wire_bytes"] for o in collective_census(txt, 8))


def test_voting_reduces_collective_bytes():
    """HLO-level evidence that voting-parallel moves LESS than data-parallel
    (reference PV-Tree claim, voting_parallel_tree_learner.cpp): the
    per-wave reduce shrinks from (2W, F, B, 3) to (2W, 2k, B, 3) — and it
    must beat data-parallel even now that the latter reduce-scatters
    (halved wire volume) instead of all-reducing."""
    import lightgbm_tpu.models.grower as G
    from lightgbm_tpu.models.gbdt import _split_config
    from lightgbm_tpu.config import Config
    cfg = Config({"objective": "binary", "verbosity": -1})
    base = dict(num_leaves=15, num_bins=256, split=_split_config(cfg),
                leaf_batch=4)
    data_bytes = _grower_collective_wire_bytes(
        G.GrowerConfig(**base))
    vote_bytes = _grower_collective_wire_bytes(
        G.GrowerConfig(voting=True, vote_top_k=4, **base))
    # Voting syncs BOTH children of each split but only 2k features;
    # data-parallel reduce-scatters W smaller siblings across all F
    # features.  At F=64, k=4 the static wire volume should still drop
    # well below half of the reduce-scatter path's.
    assert vote_bytes < data_bytes * 0.6, (vote_bytes, data_bytes)


def test_voting_composes_with_node_options(capsys):
    """Voting-parallel composes with per-node randomness, interaction
    constraints and CEGB like the reference's orthogonal learners
    (tree_learner.cpp:31-44): the node key and penalties are replicated
    across shards, so every shard votes consistently.  Forced splits still
    fall back (sequential-only)."""
    n, f = 8 * 256, 12
    rng = np.random.RandomState(5)
    X = rng.randn(n, f)
    y = (X[:, 0] > 0).astype(np.float64)
    base = {"objective": "binary", "num_leaves": 7, "verbosity": 1,
            "min_data_in_leaf": 5, "tree_learner": "voting"}
    for extra in ({"extra_trees": True},
                  {"feature_fraction_bynode": 0.5},
                  {"interaction_constraints": [[0, 1], [2, 3]]},
                  {"cegb_penalty_split": 0.1}):
        bst = lgb.train(dict(base, **extra), lgb.Dataset(X, label=y), 2)
        assert bst.num_trees() == 2
        assert bst._gbdt.grower_cfg.voting, extra
        out = capsys.readouterr()
        assert "falling back" not in (out.out + out.err).lower(), extra
        acc = ((bst.predict(X) > 0.5) == (y > 0.5)).mean()
        assert acc > 0.8, (extra, acc)
    import json, tempfile, os as _os
    fd, path = tempfile.mkstemp(suffix=".json")
    with _os.fdopen(fd, "w") as fh:
        json.dump({"feature": 0, "threshold": 0.0}, fh)
    try:
        bst = lgb.train(dict(base, forcedsplits_filename=path),
                        lgb.Dataset(X, label=y), 2)
        assert bst.num_trees() == 2
        out = capsys.readouterr()
        assert "forced splits" in out.out + out.err
    finally:
        _os.unlink(path)


@pytest.mark.parametrize("quantized", [False, True])
def test_hist_comm_reduce_scatter_matches_allreduce(quantized):
    """ISSUE-3 acceptance: the feature-sliced reduce-scatter path
    (feature-block psum_scatter + slice-local scan + SplitInfo payload
    sync) must produce BITWISE-identical trees to the full-histogram
    allreduce path — identical split order, structure, row partitions and
    leaf values — on a virtual >= 4-shard mesh, num_leaves >= 31,
    leaf_batch > 1, quantized on/off.  psum_scatter sums bitwise-equal to
    psum elementwise and the payload broadcast transports exact f32, so
    any divergence is a real layout bug, not reduce-order noise."""
    import dataclasses

    import lightgbm_tpu.models.grower as G
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.dataset import TrainData
    from lightgbm_tpu.models.gbdt import _split_config

    n, f = 4 * 2560, 12                    # > _MIN_BUCKET rows per shard
    rng = np.random.RandomState(7)
    X = rng.randn(n, f)
    X[rng.rand(n) < 0.05, 3] = np.nan      # exercise NaN default-direction
    y = (X[:, 0] + 0.7 * X[:, 1] * X[:, 2] + 0.3 * rng.randn(n) > 0)
    cfg = Config({"objective": "binary", "num_leaves": 31,
                  "min_data_in_leaf": 20, "verbosity": -1})
    td = TrainData.build(X, y.astype(np.float64), cfg)
    meta = td.feature_meta_device()
    args = (jnp.asarray(td.binned.bins),
            jnp.asarray((0.5 - y).astype(np.float32)),
            jnp.full(n, 0.25, jnp.float32), jnp.ones(n, jnp.float32),
            jnp.ones(f, bool), meta["num_bins_per_feature"],
            meta["nan_bins"], meta["is_categorical"], meta["monotone"])
    base = G.GrowerConfig(num_leaves=31, num_bins=td.binned.max_num_bins,
                          split=_split_config(cfg), leaf_batch=4,
                          quantized=quantized)
    mesh = make_mesh(4, 1)
    g_ar = G.make_grower(dataclasses.replace(base, hist_comm="allreduce"),
                         mesh=mesh, data_axis=DATA_AXIS)
    g_rs = G.make_grower(
        dataclasses.replace(base, hist_comm="reduce_scatter"),
        mesh=mesh, data_axis=DATA_AXIS)
    assert g_rs.rs_active and not g_ar.rs_active
    t_ar, rl_ar = g_ar(*args)
    t_rs, rl_rs = g_rs(*args)
    assert int(t_ar.num_leaves) == int(t_rs.num_leaves) == 31
    for field in ("split_feature", "split_bin", "default_left",
                  "left_child", "right_child", "leaf_value", "leaf_count"):
        np.testing.assert_array_equal(
            np.asarray(getattr(t_ar, field)),
            np.asarray(getattr(t_rs, field)), err_msg=field)
    np.testing.assert_array_equal(np.asarray(rl_ar), np.asarray(rl_rs))


def test_hist_comm_reduce_scatter_matches_allreduce_efb():
    """Same bitwise equivalence with EFB bundling engaged end-to-end
    (histograms reduce-scatter in BUNDLE space; expansion + scan stay in
    the owned slice with ownership-masked original features)."""
    from tests.test_efb import _onehot_data

    n = 8 * 2304
    X, y = _onehot_data(n=n)
    base = {"objective": "binary", "num_leaves": 31, "min_data_in_leaf": 20,
            "verbosity": -1, "tree_learner": "data", "enable_bundle": True,
            "tpu_leaf_batch": 4}
    b_ar = lgb.train(dict(base, tpu_hist_comm="allreduce"),
                     lgb.Dataset(X, label=y), 3)
    b_rs = lgb.train(dict(base, tpu_hist_comm="reduce_scatter"),
                     lgb.Dataset(X, label=y), 3)
    assert b_ar._gbdt.bundles is not None
    assert b_rs._gbdt.grow.rs_active and not b_ar._gbdt.grow.rs_active
    # identical model files up to the serialized knob value itself
    strip = lambda s: "\n".join(ln for ln in s.splitlines()
                                if not ln.startswith("[tpu_hist_comm:"))
    assert strip(b_ar.model_to_string()) == strip(b_rs.model_to_string())
    np.testing.assert_array_equal(b_ar.predict(X, raw_score=True),
                                  b_rs.predict(X, raw_score=True))


def test_hist_comm_fallbacks_warn():
    """Compositions the slice-local scan cannot honor (voting, the
    monotone refresh modes, forced splits) keep the allreduce; an explicit
    tpu_hist_comm=reduce_scatter request then warns instead of silently
    flipping (round-2 verdict: no silent dead params)."""
    import dataclasses

    import lightgbm_tpu.models.grower as G
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.models.gbdt import _split_config

    cfg = Config({"objective": "binary", "verbosity": -1})
    sp = _split_config(cfg)
    base = dict(num_leaves=15, num_bins=64, split=sp,
                hist_comm="reduce_scatter")
    mesh = make_mesh(8, 1)
    assert G.make_grower(G.GrowerConfig(**base), mesh=mesh,
                         data_axis=DATA_AXIS).rs_active
    for bad in (dict(voting=True),
                dict(forced_splits=((0, 1, -1, -1),)),
                dict(mono_intermediate=True,
                     split=dataclasses.replace(sp, has_monotone=True)),
                # static full-F multipliers cannot follow a feature slice
                dict(split=dataclasses.replace(
                    sp, feature_contri=(0.5,) * 8))):
        g = G.make_grower(G.GrowerConfig(**dict(base, **bad)), mesh=mesh,
                          data_axis=DATA_AXIS)
        assert not g.rs_active, bad
    # ... but the EFB slice scans full-F under an ownership mask, so
    # feature_contri composes there (predicate only: building a bundled
    # grower needs bundle metadata)
    assert G.rs_active_for(
        G.GrowerConfig(**dict(base, bundled=True,
                              split=dataclasses.replace(
                                  sp, feature_contri=(0.5,) * 8))),
        mesh, DATA_AXIS)
    # feature-only meshes never reduce-scatter (rows are replicated there)
    assert not G.make_grower(G.GrowerConfig(**base), mesh=make_mesh(1, 8),
                             data_axis=DATA_AXIS).rs_active
    with pytest.raises(ValueError, match="hist_comm"):
        G.make_grower(G.GrowerConfig(**dict(base, hist_comm="bogus")),
                      mesh=mesh, data_axis=DATA_AXIS)


def test_voting_training_quality():
    """Voting-parallel training must track serial quality closely (it is an
    approximation — reference docs call the quality loss negligible)."""
    from lightgbm_tpu.metrics import _auc

    n, f = 8 * 4096, 24
    rng = np.random.RandomState(3)
    X = rng.randn(n, f)
    logits = X[:, 0] + 0.5 * X[:, 1] * X[:, 2] + 0.3 * X[:, 5]
    y = (rng.rand(n) < 1 / (1 + np.exp(-logits))).astype(np.float64)
    params = {"objective": "binary", "num_leaves": 31,
              "min_data_in_leaf": 20, "verbosity": -1, "top_k": 5}
    serial = lgb.train(dict(params, tree_learner="serial"),
                       lgb.Dataset(X, label=y), 5)
    voting = lgb.train(dict(params, tree_learner="voting"),
                       lgb.Dataset(X, label=y), 5)
    auc_s = _auc(y, serial.predict(X, raw_score=True), None, None)
    auc_v = _auc(y, voting.predict(X, raw_score=True), None, None)
    assert auc_v > auc_s - 2e-3, (auc_s, auc_v)
