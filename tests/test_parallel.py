"""Distributed training tests over the 8-virtual-device CPU mesh.

Reference pattern: tests/distributed/_test_distributed.py — train distributed,
assert parity with single-machine results.  Here "distributed" is sharding the
same jit program over a Mesh, so parity is exact-compilation-level: we assert the
models match the serial run closely.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P
from sklearn.datasets import make_classification

import lightgbm_tpu as lgb
from lightgbm_tpu.parallel.mesh import (DATA_AXIS, FEATURE_AXIS, make_mesh,
                                        mesh_for_tree_learner)

pytestmark = pytest.mark.skipif(len(jax.devices()) < 8,
                                reason="needs 8 virtual devices")


def _data(n=2000, f=16, seed=0):
    return make_classification(n_samples=n, n_features=f, n_informative=8,
                               random_state=seed)


def test_mesh_construction():
    m = make_mesh(4, 2)
    assert m.devices.shape == (4, 2)
    assert m.axis_names == (DATA_AXIS, FEATURE_AXIS)
    assert mesh_for_tree_learner("serial") is None
    assert mesh_for_tree_learner("data").devices.shape == (8, 1)
    assert mesh_for_tree_learner("feature").devices.shape == (1, 8)


@pytest.mark.parametrize("tree_learner", ["data", "feature"])
def test_sharded_training_matches_serial(tree_learner):
    X, y = _data()
    params = {"objective": "binary", "num_leaves": 15, "min_data_in_leaf": 5,
              "metric": "auc", "verbosity": -1}
    serial = lgb.train(dict(params, tree_learner="serial"),
                       lgb.Dataset(X, label=y), 10)
    sharded = lgb.train(dict(params, tree_learner=tree_learner),
                        lgb.Dataset(X, label=y), 10)
    ps = serial.predict(X, raw_score=True)
    pp = sharded.predict(X, raw_score=True)
    # Same algorithm, same data — differences only from f32 reduction order.
    assert np.corrcoef(ps, pp)[0, 1] > 0.999
    np.testing.assert_allclose(ps, pp, rtol=5e-2, atol=5e-2)


def test_histogram_psum_across_shards():
    """The histogram contraction must produce identical results when rows are
    sharded across devices (the automatic ReduceScatter path)."""
    from lightgbm_tpu.ops.histogram import build_histogram

    rng = np.random.RandomState(0)
    n, f, B = 4096, 8, 32
    bins = rng.randint(0, B, size=(n, f)).astype(np.uint8)
    g = rng.randn(n).astype(np.float32)
    h = rng.rand(n).astype(np.float32)

    ref = build_histogram(jnp.asarray(bins), jnp.asarray(g), jnp.asarray(h),
                          None, num_bins=B, impl="onehot", rows_block=512)

    mesh = make_mesh(8, 1)
    row_sh = NamedSharding(mesh, P(DATA_AXIS))
    bins_sh = jax.device_put(jnp.asarray(bins),
                             NamedSharding(mesh, P(DATA_AXIS, None)))
    g_sh = jax.device_put(jnp.asarray(g), row_sh)
    h_sh = jax.device_put(jnp.asarray(h), row_sh)
    out = build_histogram(bins_sh, g_sh, h_sh, None, num_bins=B,
                          impl="onehot", rows_block=512)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out),
                               rtol=1e-5, atol=1e-4)


def test_dryrun_multichip_entrypoint():
    import sys
    sys.path.insert(0, "/root/repo")
    import __graft_entry__ as ge
    ge.dryrun_multichip(8)
    ge.dryrun_multichip(4)


def test_entry_entrypoint():
    import sys
    sys.path.insert(0, "/root/repo")
    import __graft_entry__ as ge
    fn, args = ge.entry()
    out, num_leaves = jax.jit(fn)(*args)
    assert int(num_leaves) >= 2
    assert out.shape == args[0].shape[:1]
