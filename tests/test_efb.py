"""EFB (exclusive feature bundling) tests.

Reference: ``DatasetLoader::FindGroups`` + ``FeatureGroup``
(``src/io/dataset_loader.cpp``, ``feature_group.h:26``) — sparse exclusive
features share one histogram column; split semantics stay per-original-
feature.
"""

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.binning import bin_dataset, build_bundles
from lightgbm_tpu.metrics import _auc


def _onehot_data(n=6000, blocks=4, card=12, dense=6, seed=0):
    rng = np.random.RandomState(seed)
    parts = []
    for _ in range(blocks):
        cat = rng.randint(0, card, n)
        oh = np.zeros((n, card))
        oh[np.arange(n), cat] = rng.rand(n) + 0.5
        parts.append(oh)
    parts.append(rng.randn(n, dense))
    X = np.concatenate(parts, axis=1)
    logits = X[:, 0] * 2 - X[:, 5] + X[:, blocks * card] \
        + 0.5 * X[:, blocks * card + 1]
    y = (rng.rand(n) < 1 / (1 + np.exp(-logits))).astype(np.float64)
    return X, y


def test_bundles_merge_exclusive_columns():
    X, y = _onehot_data()
    b = bin_dataset(X)
    fb = build_bundles(b)
    assert fb is not None
    # 4 exclusive blocks + 6 dense singletons
    assert fb.num_groups == 10
    # re-bundling an original-bin matrix reproduces the stored matrix
    np.testing.assert_array_equal(fb.bundle_row_matrix(b.bins), fb.bins)
    # bundle bins partition correctly: decode every feature's range back
    for f in range(X.shape[1]):
        g, off = int(fb.feat_group[f]), int(fb.feat_offset[f])
        if off < 0:
            continue
        nb = int(b.num_bins_per_feature[f])
        col = b.bins[:, f].astype(np.int64)
        raw = fb.bins[:, g].astype(np.int64)
        dec = np.where((raw >= off) & (raw < off + nb - 1), raw - off + 1, 0)
        nz = col > 0
        np.testing.assert_array_equal(dec[nz], col[nz])


def test_bundles_none_for_dense_data():
    rng = np.random.RandomState(0)
    X = rng.randn(3000, 20)
    assert build_bundles(bin_dataset(X)) is None


def test_efb_training_parity_and_engagement():
    """Bundled training must reproduce unbundled results (exclusive columns
    -> exact same histograms up to f32 reduce order)."""
    X, y = _onehot_data()
    params = {"objective": "binary", "num_leaves": 31, "min_data_in_leaf": 20,
              "verbosity": -1}
    b_off = lgb.train(dict(params, enable_bundle=False),
                      lgb.Dataset(X, label=y), 8)
    b_on = lgb.train(dict(params, enable_bundle=True),
                     lgb.Dataset(X, label=y), 8)
    assert b_on._gbdt.bundles is not None
    assert b_on._gbdt.bundles.num_groups == 10
    auc_off = _auc(y, b_off.predict(X, raw_score=True), None, None)
    auc_on = _auc(y, b_on.predict(X, raw_score=True), None, None)
    assert abs(auc_off - auc_on) < 1e-3
    # save/load round trip stays in original feature space
    s = b_on.model_to_string()
    reloaded = lgb.Booster(model_str=s)
    np.testing.assert_allclose(reloaded.predict(X[:100]),
                               b_on.predict(X[:100]), rtol=1e-6, atol=1e-6)


def test_efb_conflict_budget():
    """max_conflict_rate > 0 merges near-exclusive features (EFB paper's
    gamma)."""
    rng = np.random.RandomState(1)
    n, f = 5000, 24
    X = np.zeros((n, f))
    for j in range(f):
        rows = rng.choice(n, size=n // 30, replace=False)
        X[rows, j] = rng.rand(len(rows)) + 0.1
    b = bin_dataset(X)
    assert build_bundles(b, max_conflict_rate=0.0) is None
    fb = build_bundles(b, max_conflict_rate=0.05)
    assert fb is not None and fb.num_groups < f


def test_efb_composes_with_sharded_and_voting_learners():
    """EFB + data/voting-parallel on the 8-device CPU mesh (the review-caught
    interaction: votes must live in ORIGINAL feature space after bundle
    expansion)."""
    import jax
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    from lightgbm_tpu.models.grower import _MIN_BUCKET

    n = 8 * (_MIN_BUCKET + 256)
    X, y = _onehot_data(n=n, blocks=3, card=8, dense=4, seed=2)
    params = {"objective": "binary", "num_leaves": 15, "min_data_in_leaf": 20,
              "verbosity": -1, "top_k": 4, "enable_bundle": True}
    for learner in ("data", "voting"):
        bst = lgb.train(dict(params, tree_learner=learner),
                        lgb.Dataset(X, label=y), 3)
        assert bst._gbdt.bundles is not None
        auc = _auc(y, bst.predict(X, raw_score=True), None, None)
        assert auc > 0.6, (learner, auc)


def test_enable_bundle_not_sticky_across_trainings():
    """Re-training on the same Dataset with a different enable_bundle must
    re-decide bundling (review regression: one-shot cache)."""
    X, y = _onehot_data(n=3000)
    ds = lgb.Dataset(X, label=y)
    params = {"objective": "binary", "num_leaves": 15, "verbosity": -1}
    b_on = lgb.train(dict(params, enable_bundle=True), ds, 2)
    assert b_on._gbdt.bundles is not None
    b_off = lgb.train(dict(params, enable_bundle=False), ds, 2)
    assert b_off._gbdt.bundles is None
    b_on2 = lgb.train(dict(params, enable_bundle=True), ds, 2)
    assert b_on2._gbdt.bundles is not None
