"""Bounded histogram pool (ISSUE-4 tentpole; reference ``HistogramPool``,
``serial_tree_learner.h``: LRU slots + recompute-on-miss driven by
``histogram_pool_size`` MB).

Bitwise discipline mirrors docs/PERF.md: pool slots hold exactly the values
the unpooled (L, G, B, 3) carry held, sibling subtraction lands in the
parent's slot, and a miss recomputes the leaf's histogram from its
contiguous perm segment in creation-time row order — exact under quantized
training (integer histograms are order-independent) and under fp32 whenever
the gradient sums are exactly representable (these tests use the
first-iteration binary gradients +-0.5 / hess 0.25, like the parallel
parity suite) — so pooled trees pin BITWISE-identical to the unpooled path
across serial/wave/sharded layouts x fp32/quantized x EFB x packed4 x
``tpu_hist_comm=reduce_scatter``.
"""

import dataclasses
import io
import json
import os
import tempfile

import numpy as np
import pytest

import jax.numpy as jnp

import lightgbm_tpu as lgb
import lightgbm_tpu.models.grower as G
from lightgbm_tpu.config import Config
from lightgbm_tpu.dataset import TrainData
from lightgbm_tpu.models.gbdt import _split_config
from lightgbm_tpu.parallel.mesh import DATA_AXIS, make_mesh

_TREE_FIELDS = ("split_feature", "split_bin", "default_left", "left_child",
                "right_child", "split_gain", "leaf_value", "leaf_count")


def _assert_same_tree(t0, t1, rl0=None, rl1=None):
    for field in _TREE_FIELDS:
        np.testing.assert_array_equal(
            np.asarray(getattr(t0, field)), np.asarray(getattr(t1, field)),
            err_msg=field)
    assert int(t0.num_leaves) == int(t1.num_leaves)
    if rl0 is not None:
        np.testing.assert_array_equal(np.asarray(rl0), np.asarray(rl1))


@pytest.fixture(scope="module")
def grow_args():
    """Exact-sum fp32 inputs (grads +-0.5, hess 0.25) at > _MIN_BUCKET rows
    per 4-way shard, with NaNs for default-direction coverage."""
    n, f = 4 * 2560, 12
    rng = np.random.RandomState(7)
    X = rng.randn(n, f)
    X[rng.rand(n) < 0.05, 3] = np.nan
    y = (X[:, 0] + 0.7 * X[:, 1] * X[:, 2] + 0.3 * rng.randn(n) > 0)
    cfg = Config({"objective": "binary", "num_leaves": 31, "verbosity": -1})
    td = TrainData.build(X, y.astype(np.float64), cfg)
    meta = td.feature_meta_device()
    args = (jnp.asarray(td.binned.bins),
            jnp.asarray((0.5 - y).astype(np.float32)),
            jnp.full(n, 0.25, jnp.float32), jnp.ones(n, jnp.float32),
            jnp.ones(f, bool), meta["num_bins_per_feature"],
            meta["nan_bins"], meta["is_categorical"], meta["monotone"])
    base = G.GrowerConfig(num_leaves=31, num_bins=td.binned.max_num_bins,
                          split=_split_config(cfg))
    slot_mb = f * td.binned.max_num_bins * 3 * 4 / (1 << 20)
    return args, base, slot_mb


@pytest.mark.parametrize("leaf_batch,slots", [(1, 5), (4, 9)])
def test_pool_bitwise_serial_and_wave(grow_args, leaf_batch, slots):
    """Perm (W=1) and wave (W=4) layouts under a pool far smaller than the
    leaf count (heavy LRU eviction + recompute-on-miss) grow BITWISE the
    same trees and row partitions as the unpooled carry."""
    args, base, slot_mb = grow_args
    base = dataclasses.replace(base, leaf_batch=leaf_batch)
    g0 = G.make_grower(base)
    g1 = G.make_grower(dataclasses.replace(
        base, histogram_pool_size=slots * slot_mb))
    assert not g0.pool_capable and g1.pool_capable
    assert g1.pool_slots(12) < base.num_leaves
    t0, rl0 = g0(*args)
    t1, rl1 = g1(*args)
    assert int(t1.num_leaves) == base.num_leaves
    _assert_same_tree(t0, t1, rl0, rl1)


@pytest.mark.parametrize("quantized", [False, True])
def test_pool_bitwise_sharded_reduce_scatter(grow_args, quantized):
    """Data-parallel sharded-perm wave growth with the feature-sliced
    reduce-scatter: pool slots then hold only the owned ceil(G/K) feature
    block (the wins multiply), misses re-reduce through the identical
    scatter, and trees stay bitwise-identical to the unpooled rs path —
    fp32 and quantized (int16 wire + int32 fallback intact)."""
    args, base, slot_mb = grow_args
    base = dataclasses.replace(base, leaf_batch=4, quantized=quantized,
                               hist_comm="reduce_scatter")
    mesh = make_mesh(4, 1)
    g0 = G.make_grower(base, mesh=mesh, data_axis=DATA_AXIS)
    g1 = G.make_grower(
        dataclasses.replace(base, histogram_pool_size=10 * slot_mb),
        mesh=mesh, data_axis=DATA_AXIS)
    assert g0.rs_active and g1.rs_active and g1.pool_capable
    t0, rl0 = g0(*args)
    t1, rl1 = g1(*args)
    _assert_same_tree(t0, t1, rl0, rl1)


def _xy(n=6000, f=10, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, f)
    y = (X[:, 0] + X[:, 1] * X[:, 2] > 0).astype(float)
    return X, y


def test_pool_bitwise_booster_packed4_and_efb_quantized():
    """Full Booster path over several boosting rounds with a TINY pool
    (guaranteed evictions + misses) under quantized training — integer
    histograms make the recompute unconditionally exact — composed with
    4-bit packed bins and with EFB bundling."""
    X, y = _xy()
    base = {"objective": "binary", "num_leaves": 31, "verbosity": -1,
            "use_quantized_grad": True}
    # packed4 (max_bin <= 15 auto-packs)
    p4 = dict(base, max_bin=15)
    b0 = lgb.train(p4, lgb.Dataset(X, label=y), 3)
    b1 = lgb.train(dict(p4, histogram_pool_size=0.005),
                   lgb.Dataset(X, label=y), 3)
    assert b0._gbdt.grower_cfg.packed4
    assert b1._gbdt.grow.pool_capable
    np.testing.assert_array_equal(b0.predict(X, raw_score=True),
                                  b1.predict(X, raw_score=True))
    # EFB
    from tests.test_efb import _onehot_data
    Xe, ye = _onehot_data(n=6000)
    e0 = lgb.train(dict(base, enable_bundle=True),
                   lgb.Dataset(Xe, label=ye), 3)
    e1 = lgb.train(dict(base, enable_bundle=True, histogram_pool_size=0.02),
                   lgb.Dataset(Xe, label=ye), 3)
    assert e0._gbdt.bundles is not None and e1._gbdt.grow.pool_capable
    np.testing.assert_array_equal(e0.predict(Xe, raw_score=True),
                                  e1.predict(Xe, raw_score=True))


def test_pool_forced_splits_recompute_on_miss():
    """Forced splits read an arbitrary (possibly long-evicted) leaf's
    histogram at split time — the reference's recompute-on-miss case.  A
    3-node forced tree under a near-minimal pool must reproduce the
    unpooled model exactly (quantized => integer-exact recompute)."""
    X, y = _xy()
    spec = {"feature": 0, "threshold": 0.0,
            "left": {"feature": 1, "threshold": 0.0},
            "right": {"feature": 2, "threshold": 0.0}}
    fd, path = tempfile.mkstemp(suffix=".json")
    with os.fdopen(fd, "w") as fh:
        json.dump(spec, fh)
    try:
        p = {"objective": "binary", "num_leaves": 31, "verbosity": -1,
             "use_quantized_grad": True, "forcedsplits_filename": path}
        f0 = lgb.train(p, lgb.Dataset(X, label=y), 3)
        f1 = lgb.train(dict(p, histogram_pool_size=0.004),
                       lgb.Dataset(X, label=y), 3)
        assert f1._gbdt.grow.pool_capable
        np.testing.assert_array_equal(f0.predict(X, raw_score=True),
                                      f1.predict(X, raw_score=True))
    finally:
        os.unlink(path)


def test_pool_slots_clamp_and_predicate():
    """MB -> slot arithmetic and the composition predicate: the frontier
    floor (2W+1) and the L cap clamp the user knob; -1 and the excluded
    compositions (mask layout, voting, monotone refresh) keep the full
    carry; pool_active_for is the ONE shared gate."""
    split = G.SplitConfig()
    base = G.GrowerConfig(num_leaves=255, num_bins=256, split=split,
                          leaf_batch=16, histogram_pool_size=1.0)
    g = G.make_grower(base)
    # 1 MB / (28*256*3*4 B/slot) = 12 slots, below the 2*16+1 frontier floor
    assert g.pool_slots(28) == 2 * 16 + 1
    big = G.make_grower(dataclasses.replace(base,
                                            histogram_pool_size=1e6))
    assert big.pool_slots(28) == 255          # cap at L == unpooled carry
    off = G.make_grower(dataclasses.replace(base,
                                            histogram_pool_size=-1.0))
    assert not off.pool_capable
    # excluded compositions keep full residency
    assert not G.pool_active_for(dataclasses.replace(
        base, gather_rows=False))
    assert not G.pool_active_for(dataclasses.replace(base, voting=True))
    assert not G.pool_active_for(dataclasses.replace(
        base, mono_intermediate=True,
        split=dataclasses.replace(split, has_monotone=True)))
    assert G.pool_active_for(base)


def test_pool_knob_warns_only_when_inert(capsys):
    """histogram_pool_size is a REAL knob now: accepting it must not emit
    the dead-param warning; requesting it on a full-residency composition
    (intermediate monotone) warns once, naming the fallback (repo rule:
    no silent dead params)."""
    X, y = _xy(n=3000)
    lgb.train({"objective": "binary", "num_leaves": 15, "verbosity": 1,
               "histogram_pool_size": 2.0}, lgb.Dataset(X, label=y), 2)
    out = capsys.readouterr()
    txt = out.out + out.err
    assert "histogram_pool_size" not in txt, txt
    lgb.train({"objective": "binary", "num_leaves": 15, "verbosity": 1,
               "histogram_pool_size": 2.0,
               "monotone_constraints": [1] + [0] * 9,
               "monotone_constraints_method": "intermediate"},
              lgb.Dataset(X, label=y), 2)
    out = capsys.readouterr()
    assert "histogram_pool_size is ignored" in out.out + out.err
