"""Explicit collective primitives vs local reductions on the 8-device CPU mesh
(reference pattern: exercising the Network layer over loopback,
tests/distributed/_test_distributed.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from lightgbm_tpu.parallel.mesh import DATA_AXIS, make_mesh
from lightgbm_tpu.parallel import collectives as C

pytestmark = pytest.mark.skipif(len(jax.devices()) < 8,
                                reason="needs 8 virtual devices")


@pytest.fixture
def mesh():
    return make_mesh(8, 1)


def _sharded(mesh, arr, spec):
    return jax.device_put(jnp.asarray(arr), NamedSharding(mesh, spec))


def test_histogram_reduce_scatter_matches_sum(mesh):
    rng = np.random.RandomState(0)
    K, F, B = 8, 16, 32
    partials = rng.randn(K, F, B, 3).astype(np.float32)
    # global layout: per-shard partial hists stacked on the leading axis
    stacked = _sharded(mesh, partials.reshape(K * F, B, 3), P(DATA_AXIS))
    out = C.histogram_reduce_scatter(stacked, mesh)
    expect = partials.sum(axis=0)                    # (F, B, 3)
    np.testing.assert_allclose(np.asarray(out), expect, rtol=1e-5, atol=1e-5)


def test_reduce_scatter_then_allgather_roundtrip(mesh):
    rng = np.random.RandomState(1)
    K, F, B = 8, 8, 16
    partials = rng.randn(K, F, B, 3).astype(np.float32)
    stacked = _sharded(mesh, partials.reshape(K * F, B, 3), P(DATA_AXIS))
    owned = C.histogram_reduce_scatter(stacked, mesh)
    full = C.allgather_histogram(owned, mesh)
    np.testing.assert_allclose(np.asarray(full), partials.sum(axis=0),
                               rtol=1e-5, atol=1e-5)


def test_sync_global_best_split(mesh):
    gains = np.array([0.1, 3.0, 0.5, 2.0, 0.0, 1.0, 0.2, 0.9], np.float32)
    payload = np.arange(8 * 4, dtype=np.float32).reshape(8, 4)
    g, p = C.sync_global_best_split(
        _sharded(mesh, gains, P(DATA_AXIS)),
        _sharded(mesh, payload, P(DATA_AXIS, None)), mesh)
    assert float(g) == 3.0
    np.testing.assert_array_equal(np.asarray(p), payload[1])


def test_scalar_syncs(mesh):
    v = np.arange(8, dtype=np.float32)
    sh = _sharded(mesh, v, P(DATA_AXIS))
    assert float(C.global_sum(sh, mesh)[0]) == v.sum()
    assert float(C.global_min(sh, mesh)[0]) == 0.0
    assert float(C.global_max(sh, mesh)[0]) == 7.0


def test_global_mean_weighted(mesh):
    v = np.arange(8, dtype=np.float32)
    w = np.array([1, 1, 1, 1, 2, 2, 2, 2], np.float32)
    out = C.global_mean(_sharded(mesh, v, P(DATA_AXIS)),
                        _sharded(mesh, w, P(DATA_AXIS)), mesh)
    np.testing.assert_allclose(float(out[0]), (v * w).sum() / w.sum(),
                               rtol=1e-6)


def test_global_feature_vote(mesh):
    F = 10
    rng = np.random.RandomState(2)
    gains = rng.rand(8, F).astype(np.float32) * 0.1
    # every shard agrees features 3 and 7 are the best
    gains[:, 3] += 10.0
    gains[:, 7] += 5.0
    mask = C.global_feature_vote(
        _sharded(mesh, gains, P(DATA_AXIS, None)), top_k=2, mesh=mesh)
    mask = np.asarray(mask)
    assert mask[3] and mask[7]
    assert mask.sum() <= 4  # top-2k winners


def test_parse_machine_list_and_rank(tmp_path):
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.parallel import distributed as D

    cfg = Config({"machines": "127.0.0.1:12400,10.0.0.2:12400",
                  "num_machines": 2})
    machines = D.parse_machine_list(cfg)
    assert machines == ["127.0.0.1:12400", "10.0.0.2:12400"]
    assert D.derive_rank(machines, 12400) == 0

    mlist = tmp_path / "mlist.txt"
    mlist.write_text("127.0.0.1:12401\n10.0.0.9:12401\n")
    cfg2 = Config({"machine_list_filename": str(mlist), "num_machines": 2})
    assert D.parse_machine_list(cfg2) == ["127.0.0.1:12401", "10.0.0.9:12401"]


def test_init_distributed_single_process_noop():
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.parallel import distributed as D

    rank, world = D.init_distributed(Config({"num_machines": 1}))
    assert (rank, world) == (0, 1)


def test_comm_backend_reaches_grower_reduce_scatter(mesh):
    """The reduce-scatter facade is now LIVE in the grower hot loop: a
    backend registered through register_comm_backend with a traceable
    ``histogram_reduce_scatter_local`` hook must be what the compiled
    sharded grower calls for its per-wave histogram reduce — and, when the
    hook is semantically a reduce-scatter, training results must be
    unchanged (round-trip)."""
    import lightgbm_tpu.models.grower as G
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.dataset import TrainData
    from lightgbm_tpu.models.gbdt import _split_config

    n, f = 8 * 2304, 8
    rng = np.random.RandomState(3)
    X = rng.randn(n, f)
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.float64)
    cfg = Config({"objective": "binary", "num_leaves": 15,
                  "min_data_in_leaf": 5, "verbosity": -1})
    td = TrainData.build(X, y, cfg)
    meta = td.feature_meta_device()
    args = (jnp.asarray(td.binned.bins),
            jnp.asarray((0.5 - y).astype(np.float32)),
            jnp.full(n, 0.25, jnp.float32), jnp.ones(n, jnp.float32),
            jnp.ones(f, bool), meta["num_bins_per_feature"],
            meta["nan_bins"], meta["is_categorical"], meta["monotone"])
    gcfg = G.GrowerConfig(num_leaves=15, num_bins=td.binned.max_num_bins,
                          split=_split_config(cfg), leaf_batch=2,
                          hist_comm="reduce_scatter")
    grow = G.make_grower(gcfg, mesh=mesh, data_axis=DATA_AXIS)
    assert grow.rs_active
    tree_ref, rl_ref = grow(*args)

    calls = []

    class TraceableBackend:
        def histogram_reduce_scatter_local(self, h, axis, dim):
            calls.append((str(h.dtype), dim))        # trace-time record
            return jax.lax.psum_scatter(h, axis, scatter_dimension=dim,
                                        tiled=True)

    try:
        C.register_comm_backend(TraceableBackend())
        grow2 = G.make_grower(gcfg, mesh=mesh, data_axis=DATA_AXIS)
        tree_inj, rl_inj = grow2(*args)
    finally:
        C.register_comm_backend(None)
    # the hook intercepted the wave + root reduces, scattering the feature
    # axis of (G, B, 3) / (W, G, B, 3) histograms
    assert calls and {d for _, d in calls} == {0, 1}, calls
    np.testing.assert_array_equal(np.asarray(tree_ref.split_feature),
                                  np.asarray(tree_inj.split_feature))
    np.testing.assert_array_equal(np.asarray(tree_ref.leaf_value),
                                  np.asarray(tree_inj.leaf_value))
    np.testing.assert_array_equal(np.asarray(rl_ref), np.asarray(rl_inj))


def test_comm_backend_injection(mesh):
    """External comm injection seam (reference
    LGBM_NetworkInitWithFunctions, c_api.cpp:2773): a registered backend
    replaces the built-in XLA collectives in the facade."""
    import lightgbm_tpu.parallel.collectives as C

    calls = []

    class FakeBackend:
        def global_sum(self, value, mesh, axis):
            calls.append("sum")
            return jnp.asarray(42.0)

    v = jnp.ones(8)
    builtin = float(np.asarray(C.global_sum(v, mesh)))
    try:
        C.register_comm_backend(FakeBackend())
        injected = float(np.asarray(C.global_sum(v, mesh)))
        # unhooked functions keep the XLA path
        mx = float(np.asarray(C.global_max(jnp.arange(8.0), mesh)))
    finally:
        C.register_comm_backend(None)
    assert injected == 42.0 and calls == ["sum"]
    assert builtin == 8.0 and mx == 7.0
    assert float(np.asarray(C.global_sum(v, mesh))) == 8.0
