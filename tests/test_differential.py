"""Differential harness vs the GENUINE LightGBM binary.

Trains the same data/params through our framework and the reference CLI
(built from ``/root/reference`` via ``tools/refbuild/build_reference.sh``)
and compares holdout quality. Opt-in like the live interop test: set
``LGBM_REFERENCE_BIN`` to the binary's path; skipped otherwise so CI does
not depend on a from-source C++ build.

These are QUALITY-parity checks (same data, same params, tolerance on the
holdout metric), not tree-identity checks — tree identity at depth is
covered by ``test_interop.py`` (first-tree splits) and the bench-config
AUC pin (``tests/fixtures/bench_auc.json``).
"""

import os
import shutil
import subprocess
import tempfile

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.metrics import _auc

BIN = os.environ.get("LGBM_REFERENCE_BIN")

pytestmark = pytest.mark.skipif(
    not BIN, reason="set LGBM_REFERENCE_BIN to a reference CLI binary")

N_TRAIN, N_VALID, SEED = 16_000, 4_000, 0


def _data(objective, n_features=12, n_classes=3):
    rng = np.random.RandomState(SEED)
    n = N_TRAIN + N_VALID
    X = rng.randn(n, n_features)
    logits = X[:, 0] - 0.7 * X[:, 1] + 0.4 * X[:, 2] * X[:, 3]
    if objective.startswith("multiclass"):
        y = np.clip((logits - logits.mean()) / logits.std() + 1.5, 0,
                    n_classes - 1).round()
    elif objective == "binary":
        y = (logits + 0.3 * rng.randn(n) > 0).astype(float)
    else:
        y = logits + 0.1 * rng.randn(n)
    return X, y


def _cli(conf_path):
    """Run the reference CLI surfacing its own stderr on failure."""
    proc = subprocess.run([BIN, f"config={conf_path}"], capture_output=True,
                          text=True)
    assert proc.returncode == 0, (
        f"reference CLI failed ({proc.returncode}): {proc.stderr[-2000:]}")


def _run_reference(X, y, params, pred_X):
    d = tempfile.mkdtemp()
    try:
        def save(path, X_, y_):
            np.savetxt(path, np.column_stack([y_, X_]), delimiter=",",
                       fmt="%.7g")

        save(f"{d}/tr.csv", X[:N_TRAIN], y[:N_TRAIN])
        save(f"{d}/va.csv", pred_X, np.zeros(len(pred_X)))
        conf = "".join(f"{k} = {v}\n" for k, v in params.items())
        with open(f"{d}/train.conf", "w") as fh:
            fh.write(conf + f"data = {d}/tr.csv\noutput_model = {d}/m.txt\n")
        _cli(f"{d}/train.conf")
        with open(f"{d}/pred.conf", "w") as fh:
            fh.write(f"task = predict\ndata = {d}/va.csv\n"
                     f"input_model = {d}/m.txt\noutput_result = {d}/p.txt\n"
                     "predict_raw_score = true\n")
        _cli(f"{d}/pred.conf")
        return np.loadtxt(f"{d}/p.txt")
    finally:
        shutil.rmtree(d, ignore_errors=True)


def _run_ours(X, y, params):
    ds = lgb.Dataset(X[:N_TRAIN], label=y[:N_TRAIN])
    return lgb.train(dict(params), ds, params["num_iterations"])


BASE = {"num_leaves": 31, "learning_rate": 0.1, "num_iterations": 30,
        "min_data_in_leaf": 20, "verbosity": -1, "seed": 1}


@pytest.mark.parametrize("case, params, tol", [
    ("binary", {"objective": "binary"}, 3e-3),
    ("binary_options", {"objective": "binary", "bagging_fraction": 0.7,
                        "bagging_freq": 1, "feature_fraction": 0.8,
                        "lambda_l1": 0.5, "lambda_l2": 2.0}, 8e-3),
    ("binary_monotone", {"objective": "binary",
                         "monotone_constraints": "1,-1,0,0,0,0,0,0,0,0,0,0"},
     5e-3),
], ids=lambda v: v if isinstance(v, str) else "")
def test_binary_auc_parity(case, params, tol):
    """Holdout AUC must track the genuine binary within tolerance on the
    same data/params (bagging RNG differs by design, hence wider tol)."""
    full = dict(BASE, **params)
    X, y = _data("binary")
    yva = y[N_TRAIN:]
    ref_raw = _run_reference(X, y, full, X[N_TRAIN:])
    ref_auc = _auc(yva, ref_raw, None, None)
    ours = _run_ours(X, y, full)
    our_auc = _auc(yva, ours.predict(X[N_TRAIN:], raw_score=True),
                   None, None)
    assert abs(our_auc - ref_auc) < tol, (case, our_auc, ref_auc)


@pytest.mark.parametrize("objective, tol", [
    ("regression", 0.03), ("regression_l1", 0.05), ("huber", 0.05)])
def test_regression_rmse_parity(objective, tol):
    """Holdout RMSE ratio vs the genuine binary within tolerance."""
    full = dict(BASE, objective=objective)
    X, y = _data(objective)
    yva = y[N_TRAIN:]
    ref_pred = _run_reference(X, y, full, X[N_TRAIN:])
    ref_rmse = float(np.sqrt(np.mean((yva - ref_pred) ** 2)))
    ours = _run_ours(X, y, full)
    our_rmse = float(np.sqrt(np.mean(
        (yva - ours.predict(X[N_TRAIN:], raw_score=True)) ** 2)))
    assert our_rmse < ref_rmse * (1 + tol), (our_rmse, ref_rmse)


def test_multiclass_accuracy_parity():
    full = dict(BASE, objective="multiclass", num_class=3)
    X, y = _data("multiclass")
    yva = y[N_TRAIN:]
    ref_raw = _run_reference(X, y, full, X[N_TRAIN:])  # (n, 3) raw scores
    ref_acc = (ref_raw.reshape(len(yva), 3).argmax(1) == yva).mean()
    ours = _run_ours(X, y, full)
    our_acc = (ours.predict(X[N_TRAIN:]).argmax(1) == yva).mean()
    assert abs(our_acc - ref_acc) < 5e-3, (our_acc, ref_acc)
