"""Differential harness vs the GENUINE LightGBM binary.

Trains the same data/params through our framework and the reference CLI
(built from ``/root/reference`` via ``tools/refbuild/build_reference.sh``)
and compares holdout quality. Opt-in like the live interop test: set
``LGBM_REFERENCE_BIN`` to the binary's path; skipped otherwise so CI does
not depend on a from-source C++ build.

These are QUALITY-parity checks (same data, same params, tolerance on the
holdout metric), not tree-identity checks — tree identity at depth is
covered by ``test_interop.py`` (first-tree splits) and the bench-config
AUC pin (``tests/fixtures/bench_auc.json``).
"""

import os
import shutil
import subprocess
import tempfile

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.metrics import _auc

BIN = os.environ.get("LGBM_REFERENCE_BIN")

pytestmark = pytest.mark.skipif(
    not BIN, reason="set LGBM_REFERENCE_BIN to a reference CLI binary")

N_TRAIN, N_VALID, SEED = 16_000, 4_000, 0


def _data(objective, n_features=12, n_classes=3):
    rng = np.random.RandomState(SEED)
    n = N_TRAIN + N_VALID
    X = rng.randn(n, n_features)
    logits = X[:, 0] - 0.7 * X[:, 1] + 0.4 * X[:, 2] * X[:, 3]
    if objective.startswith("multiclass"):
        y = np.clip((logits - logits.mean()) / logits.std() + 1.5, 0,
                    n_classes - 1).round()
    elif objective == "binary":
        y = (logits + 0.3 * rng.randn(n) > 0).astype(float)
    else:
        y = logits + 0.1 * rng.randn(n)
    return X, y


def _cli(conf_path):
    """Run the reference CLI surfacing its own stderr on failure."""
    proc = subprocess.run([BIN, f"config={conf_path}"], capture_output=True,
                          text=True)
    assert proc.returncode == 0, (
        f"reference CLI failed ({proc.returncode}): {proc.stderr[-2000:]}")


def _run_reference(X, y, params, pred_X, n_train=None, query=None,
                   weight=None):
    """Train + raw-predict through the reference CLI.  ``query`` is an
    optional (train_groups, pred_groups) pair written as .query sidecars
    (ranking objectives); ``weight`` an optional train-weight sidecar."""
    n_train = N_TRAIN if n_train is None else n_train
    d = tempfile.mkdtemp()
    try:
        def save(path, X_, y_):
            np.savetxt(path, np.column_stack([y_, X_]), delimiter=",",
                       fmt="%.17g")

        save(f"{d}/tr.csv", X[:n_train], y[:n_train])
        save(f"{d}/va.csv", pred_X, np.zeros(len(pred_X)))
        if query is not None:
            np.savetxt(f"{d}/tr.csv.query", query[0], fmt="%d")
            np.savetxt(f"{d}/va.csv.query", query[1], fmt="%d")
        if weight is not None:
            np.savetxt(f"{d}/tr.csv.weight", weight[:n_train], fmt="%.17g")
        conf = "".join(f"{k} = {v}\n" for k, v in params.items())
        with open(f"{d}/train.conf", "w") as fh:
            fh.write(conf + f"data = {d}/tr.csv\noutput_model = {d}/m.txt\n")
        _cli(f"{d}/train.conf")
        with open(f"{d}/pred.conf", "w") as fh:
            fh.write(f"task = predict\ndata = {d}/va.csv\n"
                     f"input_model = {d}/m.txt\noutput_result = {d}/p.txt\n"
                     "predict_raw_score = true\n")
        _cli(f"{d}/pred.conf")
        return np.loadtxt(f"{d}/p.txt")
    finally:
        shutil.rmtree(d, ignore_errors=True)


def _run_ours(X, y, params):
    ds = lgb.Dataset(X[:N_TRAIN], label=y[:N_TRAIN])
    return lgb.train(dict(params), ds, params["num_iterations"])


BASE = {"num_leaves": 31, "learning_rate": 0.1, "num_iterations": 30,
        "min_data_in_leaf": 20, "verbosity": -1, "seed": 1}


@pytest.mark.parametrize("case, params, tol", [
    ("binary", {"objective": "binary"}, 3e-3),
    ("binary_options", {"objective": "binary", "bagging_fraction": 0.7,
                        "bagging_freq": 1, "feature_fraction": 0.8,
                        "lambda_l1": 0.5, "lambda_l2": 2.0}, 8e-3),
    ("binary_monotone", {"objective": "binary",
                         "monotone_constraints": "1,-1,0,0,0,0,0,0,0,0,0,0"},
     5e-3),
    # groups keep the generator's X2*X3 interaction within one set, so
    # both implementations can express the signal and the comparison is
    # not dominated by how each routes around a forbidden interaction
    ("interaction", {"objective": "binary",
                     "interaction_constraints":
                         "[0,1],[2,3,4,5,6,7,8,9,10,11]"}, 5e-3),
    ("cegb", {"objective": "binary", "cegb_penalty_split": 0.05,
              "cegb_tradeoff": 0.8}, 8e-3),
    ("maxbin63", {"objective": "binary", "max_bin": 63,
                  "min_gain_to_split": 0.01}, 5e-3),
    # balanced bagging resamples with class-dependent rates (RNG differs
    # across implementations by design)
    ("posneg_bagging", {"objective": "binary", "pos_bagging_fraction": 0.5,
                        "neg_bagging_fraction": 0.9, "bagging_freq": 1},
     1.2e-2),
], ids=lambda v: v if isinstance(v, str) else "")
def test_binary_auc_parity(case, params, tol):
    """Holdout AUC must track the genuine binary within tolerance on the
    same data/params (bagging RNG differs by design, hence wider tol)."""
    full = dict(BASE, **params)
    X, y = _data("binary")
    yva = y[N_TRAIN:]
    ref_raw = _run_reference(X, y, full, X[N_TRAIN:])
    ref_auc = _auc(yva, ref_raw, None, None)
    ours = _run_ours(X, y, full)
    our_auc = _auc(yva, ours.predict(X[N_TRAIN:], raw_score=True),
                   None, None)
    assert abs(our_auc - ref_auc) < tol, (case, our_auc, ref_auc)


@pytest.mark.parametrize("objective, tol", [
    ("regression", 0.03), ("regression_l1", 0.05), ("huber", 0.05)])
def test_regression_rmse_parity(objective, tol):
    """Holdout RMSE ratio vs the genuine binary within tolerance."""
    full = dict(BASE, objective=objective)
    X, y = _data(objective)
    yva = y[N_TRAIN:]
    ref_pred = _run_reference(X, y, full, X[N_TRAIN:])
    ref_rmse = float(np.sqrt(np.mean((yva - ref_pred) ** 2)))
    ours = _run_ours(X, y, full)
    our_rmse = float(np.sqrt(np.mean(
        (yva - ours.predict(X[N_TRAIN:], raw_score=True)) ** 2)))
    assert our_rmse < ref_rmse * (1 + tol), (our_rmse, ref_rmse)


def test_multiclass_accuracy_parity():
    full = dict(BASE, objective="multiclass", num_class=3)
    X, y = _data("multiclass")
    yva = y[N_TRAIN:]
    ref_raw = _run_reference(X, y, full, X[N_TRAIN:])  # (n, 3) raw scores
    ref_acc = (ref_raw.reshape(len(yva), 3).argmax(1) == yva).mean()
    ours = _run_ours(X, y, full)
    our_acc = (ours.predict(X[N_TRAIN:]).argmax(1) == yva).mean()
    assert abs(our_acc - ref_acc) < 5e-3, (our_acc, ref_acc)


def test_quantile_pinball_parity():
    alpha = 0.7
    full = dict(BASE, objective="quantile", alpha=alpha)
    X, y = _data("quantile")
    yva = y[N_TRAIN:]

    def pinball(pred):
        d = yva - pred
        return float(np.mean(np.where(d >= 0, alpha * d, (alpha - 1) * d)))

    ref = pinball(_run_reference(X, y, full, X[N_TRAIN:]))
    ours = _run_ours(X, y, full)
    got = pinball(ours.predict(X[N_TRAIN:], raw_score=True))
    assert got < ref * 1.05, (got, ref)


@pytest.mark.parametrize("objective", ["poisson", "tweedie"])
def test_positive_regression_parity(objective):
    full = dict(BASE, objective=objective)
    rng = np.random.RandomState(SEED)
    n = N_TRAIN + N_VALID
    X = rng.randn(n, 10)
    rate = np.exp(0.5 * X[:, 0] - 0.4 * X[:, 1])
    y = rng.poisson(rate).astype(np.float64)
    yva = y[N_TRAIN:]
    # both emit raw log-rate scores; compare Poisson deviance
    ref_raw = _run_reference(X, y, full, X[N_TRAIN:])
    ours = _run_ours(X, y, full)
    our_raw = ours.predict(X[N_TRAIN:], raw_score=True)

    def dev(raw):
        mu = np.exp(raw)
        return float(np.mean(mu - yva * raw))

    assert dev(our_raw) < dev(ref_raw) * 1.03, (dev(our_raw), dev(ref_raw))


def test_xentropy_parity():
    full = dict(BASE, objective="xentropy")
    X, y = _data("binary")
    y = np.clip(y * 0.8 + 0.1, 0, 1)   # soft labels in [0,1]
    yva = y[N_TRAIN:]

    def ll(raw):
        p = 1 / (1 + np.exp(-raw))
        p = np.clip(p, 1e-15, 1 - 1e-15)
        return float(-np.mean(yva * np.log(p) + (1 - yva) * np.log(1 - p)))

    ref = ll(_run_reference(X, y, full, X[N_TRAIN:]))
    ours = _run_ours(X, y, full)
    got = ll(ours.predict(X[N_TRAIN:], raw_score=True))
    assert got < ref * 1.03, (got, ref)


def test_categorical_feature_parity():
    """Integer categorical columns declared via categorical_feature must
    track the reference's categorical split quality."""
    rng = np.random.RandomState(SEED)
    n = N_TRAIN + N_VALID
    Xnum = rng.randn(n, 6)
    cat1 = rng.randint(0, 12, n)
    cat2 = rng.randint(0, 5, n)
    effect = np.where(np.isin(cat1, [2, 5, 7]), 1.5, -0.5)
    y = (Xnum[:, 0] + effect + 0.4 * (cat2 == 3)
         + 0.3 * rng.randn(n) > 0).astype(float)
    X = np.column_stack([cat1, cat2, Xnum]).astype(np.float64)
    full = dict(BASE, objective="binary", categorical_feature="0,1")
    yva = y[N_TRAIN:]
    ref_auc = _auc(yva, _run_reference(X, y, full, X[N_TRAIN:]), None, None)
    ds = lgb.Dataset(X[:N_TRAIN], label=y[:N_TRAIN],
                     categorical_feature=[0, 1])
    ours = lgb.train({k: v for k, v in full.items()
                      if k != "categorical_feature"}, ds,
                     full["num_iterations"])
    our_auc = _auc(yva, ours.predict(X[N_TRAIN:], raw_score=True),
                   None, None)
    assert abs(our_auc - ref_auc) < 5e-3, (our_auc, ref_auc)


def test_quantized_training_parity():
    """int8-gradient training (use_quantized_grad) quality must track the
    reference's quantized mode."""
    full = dict(BASE, objective="binary", use_quantized_grad="true",
                num_grad_quant_bins=4)
    X, y = _data("binary")
    yva = y[N_TRAIN:]
    ref_auc = _auc(yva, _run_reference(X, y, full, X[N_TRAIN:]), None, None)
    ours = _run_ours(X, y, full)
    our_auc = _auc(yva, ours.predict(X[N_TRAIN:], raw_score=True),
                   None, None)
    assert abs(our_auc - ref_auc) < 8e-3, (our_auc, ref_auc)


@pytest.mark.parametrize("objective, tol", [
    ("lambdarank", 0.02),
    ("rank_xendcg", 0.03),   # stochastic gradients by design — wider band
])
def test_ranking_ndcg_parity(objective, tol):
    """Ranking NDCG@5 vs the genuine binary (query sidecar files)."""
    from lightgbm_tpu.metrics import _ndcg_multi
    rng = np.random.RandomState(SEED)
    n_q, per_q = 1200, 10
    n = n_q * per_q
    X = rng.randn(n, 8)
    rel = X[:, 0] + 0.6 * X[:, 1] + 0.4 * rng.randn(n)
    y = np.zeros(n, np.int64)
    for q in range(n_q):
        sl = slice(q * per_q, (q + 1) * per_q)
        y[sl] = np.minimum(4, np.argsort(np.argsort(rel[sl])) * 5 // per_q)
    n_tr_q = 1000
    ntr = n_tr_q * per_q
    full = dict(BASE, objective=objective, num_iterations=40)
    ref_scores = _run_reference(
        X, y, full, X[ntr:], n_train=ntr,
        query=(np.full(n_tr_q, per_q), np.full(n_q - n_tr_q, per_q)))
    ds = lgb.Dataset(X[:ntr], label=y[:ntr], group=np.full(n_tr_q, per_q))
    ours = lgb.train(full, ds, full["num_iterations"])
    gains = np.array([(1 << i) - 1 for i in range(32)], np.float64)
    va_group = np.full(n_q - n_tr_q, per_q)

    def ndcg5(scores):
        return _ndcg_multi(y[ntr:], scores, va_group, (5,), gains)[0]

    assert abs(ndcg5(ours.predict(X[ntr:], raw_score=True))
               - ndcg5(ref_scores)) < tol


def test_linear_tree_parity():
    """linear_tree leaves fit per-leaf linear models (Eigen in the
    reference, normal equations here); holdout RMSE must track."""
    full = dict(BASE, objective="regression", linear_tree="true",
                linear_lambda=0.01)
    X, y = _data("regression")
    yva = y[N_TRAIN:]
    ref_pred = _run_reference(X, y, full, X[N_TRAIN:])
    ref_rmse = float(np.sqrt(np.mean((yva - ref_pred) ** 2)))
    ours = _run_ours(X, y, full)
    our_rmse = float(np.sqrt(np.mean(
        (yva - ours.predict(X[N_TRAIN:], raw_score=True)) ** 2)))
    assert our_rmse < ref_rmse * 1.05, (our_rmse, ref_rmse)


@pytest.mark.parametrize("case, extra, tol", [
    ("goss", {"data_sample_strategy": "goss"}, 1e-2),
    ("dart", {"boosting": "dart", "drop_rate": 0.1}, 1.5e-2),
    ("extra_path_smooth", {"extra_trees": "true", "path_smooth": 1.0,
                           "max_depth": 8}, 1.5e-2),
])
def test_stochastic_mode_auc_parity(case, extra, tol):
    """Sampling/drop RNG differs across implementations by design; the
    holdout AUC of each mode must still land in the same band."""
    full = dict(BASE, objective="binary", **extra)
    X, y = _data("binary")
    yva = y[N_TRAIN:]
    ref_auc = _auc(yva, _run_reference(X, y, full, X[N_TRAIN:]), None, None)
    ours = _run_ours(X, y, full)
    our_auc = _auc(yva, ours.predict(X[N_TRAIN:], raw_score=True),
                   None, None)
    assert abs(our_auc - ref_auc) < tol, (case, our_auc, ref_auc)


def test_weighted_binary_parity():
    """Sample weights flow through gradients, hessians, min_sum_hessian
    and boost-from-average; weighted AUC must track the reference."""
    full = dict(BASE, objective="binary")
    X, y = _data("binary")
    rng = np.random.RandomState(3)
    w = np.exp(rng.randn(len(y)) * 0.5)
    yva, wva = y[N_TRAIN:], w[N_TRAIN:]
    ref_raw = _run_reference(X, y, full, X[N_TRAIN:], weight=w)
    ref_auc = _auc(yva, ref_raw, wva, None)
    ds = lgb.Dataset(X[:N_TRAIN], label=y[:N_TRAIN], weight=w[:N_TRAIN])
    ours = lgb.train(dict(full), ds, full["num_iterations"])
    our_auc = _auc(yva, ours.predict(X[N_TRAIN:], raw_score=True), wva, None)
    assert abs(our_auc - ref_auc) < 5e-3, (our_auc, ref_auc)


def test_leaf_and_contrib_prediction_parity():
    """Load OUR model file into the genuine binary and compare leaf-index
    and SHAP-contribution predictions element-wise — same model, so
    traversal and TreeSHAP must agree exactly (not just in quality)."""
    full = dict(BASE, objective="binary", num_iterations=12)
    X, y = _data("binary")
    Xva = X[N_TRAIN:N_TRAIN + 500]
    ours = _run_ours(X, y, full)

    d = tempfile.mkdtemp()
    try:
        ours.save_model(f"{d}/m.txt")
        np.savetxt(f"{d}/va.csv",
                   np.column_stack([np.zeros(len(Xva)), Xva]),
                   delimiter=",", fmt="%.17g")
        for mode, flag in [("leaf", "predict_leaf_index"),
                           ("contrib", "predict_contrib")]:
            with open(f"{d}/{mode}.conf", "w") as fh:
                fh.write(f"task = predict\ndata = {d}/va.csv\n"
                         f"input_model = {d}/m.txt\n"
                         f"output_result = {d}/{mode}.txt\n"
                         f"{flag} = true\n")
            _cli(f"{d}/{mode}.conf")
        ref_leaf = np.loadtxt(f"{d}/leaf.txt")
        ref_contrib = np.loadtxt(f"{d}/contrib.txt")
    finally:
        shutil.rmtree(d, ignore_errors=True)

    our_leaf = ours.predict(Xva, pred_leaf=True)
    np.testing.assert_array_equal(our_leaf, ref_leaf)
    our_contrib = ours.predict(Xva, pred_contrib=True)
    np.testing.assert_allclose(our_contrib, ref_contrib,
                               rtol=1e-5, atol=1e-6)


def test_forced_splits_parity(tmp_path):
    """forcedsplits_filename pins the tree's top splits on both sides; the
    forced structure plus learned remainder must match in quality."""
    import json as _json
    spec = {"feature": 0, "threshold": 0.0,
            "left": {"feature": 1, "threshold": -0.5}}
    fs = tmp_path / "forced.json"
    fs.write_text(_json.dumps(spec))
    full = dict(BASE, objective="binary", forcedsplits_filename=str(fs))
    X, y = _data("binary")
    yva = y[N_TRAIN:]
    ref_auc = _auc(yva, _run_reference(X, y, full, X[N_TRAIN:]), None, None)
    ours = _run_ours(X, y, full)
    our_auc = _auc(yva, ours.predict(X[N_TRAIN:], raw_score=True),
                   None, None)
    assert abs(our_auc - ref_auc) < 5e-3, (our_auc, ref_auc)


def test_weight_column_cli_parity(tmp_path):
    """weight_column=<idx> in-data weights through BOTH CLIs: ours and the
    genuine binary must produce matching weighted-AUC on the holdout."""
    import subprocess as sp
    X, y = _data("binary")
    rng = np.random.RandomState(5)
    w = np.exp(rng.randn(len(y)) * 0.5)
    yva, wva = y[N_TRAIN:], w[N_TRAIN:]
    full = dict(BASE, objective="binary", weight_column="0")

    def run_cli(cmd_prefix, out_model):
        tr = tmp_path / f"{out_model}_tr.csv"
        va = tmp_path / f"{out_model}_va.csv"
        # file columns: label, weight, features  (weight_column=0 in
        # X-space = first post-label column)
        np.savetxt(tr, np.column_stack([y[:N_TRAIN], w[:N_TRAIN],
                                        X[:N_TRAIN]]),
                   delimiter=",", fmt="%.17g")
        np.savetxt(va, np.column_stack([np.zeros(N_VALID), w[N_TRAIN:],
                                        X[N_TRAIN:]]),
                   delimiter=",", fmt="%.17g")
        conf = tmp_path / f"{out_model}.conf"
        conf.write_text("".join(f"{k} = {v}\n" for k, v in full.items())
                        + f"data = {tr}\noutput_model = "
                        f"{tmp_path}/{out_model}.txt\n")
        env = dict(os.environ, LIGHTGBM_TPU_PLATFORM="cpu")
        r = sp.run([*cmd_prefix, f"config={conf}"], capture_output=True,
                   text=True, env=env)
        assert r.returncode == 0, r.stderr[-1500:]
        pconf = tmp_path / f"{out_model}_p.conf"
        pconf.write_text(
            f"task = predict\ndata = {va}\ninput_model = "
            f"{tmp_path}/{out_model}.txt\noutput_result = "
            f"{tmp_path}/{out_model}_p.txt\npredict_raw_score = true\n"
            f"weight_column = 0\nlabel_column = 0\n")
        r = sp.run([*cmd_prefix, f"config={pconf}"], capture_output=True,
                   text=True, env=env)
        assert r.returncode == 0, r.stderr[-1500:]
        return np.loadtxt(f"{tmp_path}/{out_model}_p.txt")

    ref_raw = run_cli([BIN], "ref")
    import sys
    ours_raw = run_cli([sys.executable, "-m", "lightgbm_tpu"], "ours")
    ref_auc = _auc(yva, ref_raw, wva, None)
    our_auc = _auc(yva, ours_raw, wva, None)
    assert abs(our_auc - ref_auc) < 5e-3, (our_auc, ref_auc)
