"""Feature-tiled split scan (ISSUE-4 tentpole, ``tpu_split_tile``): the
(F, B) cumsum/gain buffers evaluate per G-block through a sequential
``lax.map`` so peak scan scratch stops scaling with full F — and the
cross-block winner reduction replays the untiled argmax's exact tie-break
order (lowest flat index in a block, lowest block across blocks,
sorted-categorical only on strictly greater gain), so tiling NEVER changes
the chosen split."""

import dataclasses

import numpy as np
import pytest

import jax.numpy as jnp

import lightgbm_tpu.models.grower as G
from lightgbm_tpu.config import Config
from lightgbm_tpu.dataset import TrainData
from lightgbm_tpu.models.gbdt import _split_config
from lightgbm_tpu.ops.split import SplitConfig, _resolve_tile, best_split


def test_resolve_tile_semantics():
    """0 = auto (engages 128 past 256 columns), 1 = untiled, >= 2 explicit;
    a block width >= F degenerates to the untiled scan."""
    assert _resolve_tile(0, 28) == 0
    assert _resolve_tile(0, 256) == 0
    assert _resolve_tile(0, 700) == 128
    assert _resolve_tile(1, 700) == 0
    assert _resolve_tile(64, 700) == 64
    assert _resolve_tile(4096, 700) == 0


@pytest.mark.parametrize("cfg_kw", [
    {},
    {"use_cegb": True},
    {"lambda_l1": 0.5, "path_smooth": 2.0},
    {"monotone_penalty": 1.0},
])
def test_tiled_best_split_matches_untiled(cfg_kw):
    """Synthetic histograms with categorical columns, NaN bins, monotone
    directions, CEGB penalties and feature_contri: every BestSplit field
    (and the voting per-feature gain vector) is identical tiled vs untiled
    at a block width that does not divide F (exercises the padded tail)."""
    rng = np.random.RandomState(0)
    f, b = 300, 32
    hist = (rng.rand(f, b, 3) * 10).astype(np.float32)
    hist[..., 2] = np.round(hist[..., 2] * 20)
    nbpf = rng.randint(5, b, f).astype(np.int32)
    nanb = np.where(rng.rand(f) < 0.3, nbpf - 1, b).astype(np.int32)
    common = dict(
        num_bins_per_feature=jnp.asarray(nbpf),
        nan_bins=jnp.asarray(nanb),
        is_categorical=jnp.asarray(rng.rand(f) < 0.2),
        monotone=jnp.asarray(rng.randint(-1, 2, f).astype(np.int32)),
        feature_mask=jnp.asarray(rng.rand(f) < 0.9),
        gain_penalty=jnp.asarray((rng.rand(f) * 0.01).astype(np.float32)),
        parent_output=jnp.float32(0.1), leaf_depth=jnp.int32(2))
    pg = np.float32(hist[..., 0].sum())
    ph = np.float32(hist[..., 1].sum())
    pc = np.float32(hist[..., 2].sum())
    if not cfg_kw:
        cfg_kw = {"feature_contri":
                  tuple(np.round(rng.rand(f), 2).tolist())}
    c_off = SplitConfig(scan_tile=1, **cfg_kw)
    c_on = SplitConfig(scan_tile=64, **cfg_kw)      # 300 = 4*64 + 44 tail
    h = jnp.asarray(hist)
    b0, fg0 = best_split(h, pg, ph, pc, cfg=c_off,
                         with_feature_gains=True, **common)
    b1, fg1 = best_split(h, pg, ph, pc, cfg=c_on,
                         with_feature_gains=True, **common)
    for field in b0._fields:
        np.testing.assert_array_equal(np.asarray(getattr(b0, field)),
                                      np.asarray(getattr(b1, field)),
                                      err_msg=field)
    np.testing.assert_array_equal(np.asarray(fg0), np.asarray(fg1))


def test_tiled_grower_trees_bitwise_identical():
    """End-to-end: a grower forced onto 4-wide scan blocks (explicit
    tpu_split_tile smaller than F) grows BITWISE the same tree as the
    untiled scan — fp32, wave growth, NaN routing included."""
    n, f = 6000, 12
    rng = np.random.RandomState(7)
    X = rng.randn(n, f)
    X[rng.rand(n) < 0.05, 3] = np.nan
    y = (X[:, 0] + 0.7 * X[:, 1] * X[:, 2] + 0.3 * rng.randn(n) > 0)
    cfg = Config({"objective": "binary", "num_leaves": 31,
                  "verbosity": -1})
    td = TrainData.build(X, y.astype(np.float64), cfg)
    meta = td.feature_meta_device()
    args = (jnp.asarray(td.binned.bins),
            jnp.asarray((0.5 - y).astype(np.float32)),
            jnp.full(n, 0.25, jnp.float32), jnp.ones(n, jnp.float32),
            jnp.ones(f, bool), meta["num_bins_per_feature"],
            meta["nan_bins"], meta["is_categorical"], meta["monotone"])
    split = _split_config(cfg)
    base = G.GrowerConfig(num_leaves=31, num_bins=td.binned.max_num_bins,
                          split=split, leaf_batch=4)
    t0, rl0 = G.make_grower(dataclasses.replace(
        base, split=dataclasses.replace(split, scan_tile=1)))(*args)
    t1, rl1 = G.make_grower(dataclasses.replace(
        base, split=dataclasses.replace(split, scan_tile=4)))(*args)
    for field in ("split_feature", "split_bin", "default_left",
                  "left_child", "right_child", "leaf_value", "leaf_count"):
        np.testing.assert_array_equal(np.asarray(getattr(t0, field)),
                                      np.asarray(getattr(t1, field)),
                                      err_msg=field)
    np.testing.assert_array_equal(np.asarray(rl0), np.asarray(rl1))
