"""Input-format coverage: pandas DataFrames (incl. categorical dtype) and
scipy sparse matrices (reference python-package basic.py _data_from_pandas
and CSR ingestion paths)."""

import numpy as np
import pytest

import lightgbm_tpu as lgb

pd = pytest.importorskip("pandas")


def test_pandas_dataframe_train_predict():
    rng = np.random.RandomState(0)
    n = 800
    df = pd.DataFrame({
        "a": rng.randn(n),
        "b": rng.randn(n),
        "c": pd.Categorical(rng.choice(["x", "y", "z"], n)),
    })
    y = (df["a"].to_numpy() + (df["c"] == "x").to_numpy() > 0).astype(float)
    bst = lgb.train({"objective": "binary", "num_leaves": 15,
                     "verbosity": -1}, lgb.Dataset(df, label=y), 10)
    # auto feature names from columns
    assert bst.feature_name() == ["a", "b", "c"]
    p_df = bst.predict(df)
    assert ((p_df > 0.5) == (y > 0.5)).mean() > 0.9
    # categorical column handled as categorical (codes round-trip)
    ds = lgb.Dataset(df, label=y)
    td = ds.construct({"objective": "binary", "verbosity": -1})
    assert bool(td.binned.is_categorical[2])


def test_pandas_object_column_rejected():
    df = pd.DataFrame({"a": [1.0, 2.0], "b": ["p", "q"]})
    with pytest.raises(ValueError, match="object dtype"):
        lgb.Dataset(df, label=[0, 1]).construct({"objective": "binary"})


def test_scipy_sparse_input():
    sp = pytest.importorskip("scipy.sparse")
    rng = np.random.RandomState(1)
    n, f = 600, 30
    dense = np.zeros((n, f))
    for j in range(f):
        rows = rng.choice(n, size=20, replace=False)
        dense[rows, j] = rng.rand(20) + 0.5
    y = (dense[:, 0] > 0).astype(float)
    X = sp.csr_matrix(dense)
    bst = lgb.train({"objective": "binary", "num_leaves": 7,
                     "min_data_in_leaf": 5, "verbosity": -1},
                    lgb.Dataset(X, label=y), 5)
    p_sparse = bst.predict(sp.csr_matrix(dense[:50]))
    p_dense = bst.predict(dense[:50])
    np.testing.assert_allclose(p_sparse, p_dense, rtol=1e-9)


def test_sparse_bins_match_dense_bins():
    """CSR-direct binning (binning._bin_sparse_matrix — the TPU answer to
    sparse_bin.hpp:73) must produce bit-identical bins to the dense path,
    including NaN entries and training equivalence."""
    sp = pytest.importorskip("scipy.sparse")
    from lightgbm_tpu.binning import bin_dataset

    rng = np.random.RandomState(4)
    n, f = 3000, 40
    dense = np.zeros((n, f))
    for j in range(f):
        rows = rng.choice(n, size=n // 20, replace=False)
        dense[rows, j] = rng.randn(len(rows))
    nanr = rng.choice(n, size=30, replace=False)
    dense[nanr, 3] = np.nan
    X = sp.csr_matrix(dense)
    b_dense = bin_dataset(dense, max_bin=63)
    b_sparse = bin_dataset(X, max_bin=63)
    np.testing.assert_array_equal(b_dense.bins, b_sparse.bins)
    np.testing.assert_array_equal(b_dense.nan_bins, b_sparse.nan_bins)
    # training end-to-end equality
    y = (np.nansum(dense[:, :3], axis=1) > 0).astype(float)
    p = {"objective": "binary", "num_leaves": 15, "min_data_in_leaf": 5,
         "verbosity": -1, "deterministic": True, "seed": 1}
    bd = lgb.train(p, lgb.Dataset(dense, label=y), 8)
    bs = lgb.train(p, lgb.Dataset(X, label=y), 8)
    np.testing.assert_allclose(bd.predict(dense), bs.predict(X), rtol=1e-9)


def test_sparse_ingestion_memory_bounded():
    """Constructing a Dataset from a 100k x 2000 / ~1% CSR must stay O(nnz)
    + the uint8 bin matrix — never the ~1.6 GB dense f64 copy (VERDICT r3
    missing #4).  Measured as the child process's peak-RSS DELTA across the
    construct call against a same-process baseline taken right before it —
    an absolute bound flaked under concurrent test processes (allocator /
    import-baseline noise moved the ambient floor); the delta is invariant
    to whatever the baseline happens to be (ISSUE-5 satellite).  The
    watermark plumbing is MemoryTracker's (telemetry/memory.py, ISSUE-10)
    — this test asserts on the tracker's host-RSS watermark instead of
    re-implementing the clear_refs bookkeeping it used to duplicate."""
    pytest.importorskip("scipy.sparse")
    import os
    import subprocess
    import sys

    code = r"""
import sys
import numpy as np
import scipy.sparse as sp
import lightgbm_tpu as lgb
from lightgbm_tpu.telemetry.memory import MemoryTracker

n, f, nnz_per_col = 100_000, 2000, 1000
rng = np.random.RandomState(0)
# .copy() matters: choice(replace=False) returns a slice view that pins
# the full n-permutation buffer, which alone would look like ~1.6 GB
rows = np.concatenate([rng.choice(n, nnz_per_col, replace=False).copy()
                       for _ in range(f)])
cols = np.repeat(np.arange(f), nnz_per_col)
vals = rng.randn(f * nnz_per_col)
X = sp.csr_matrix((vals, (rows, cols)), shape=(n, f))
y = (np.asarray(X[:, 0].todense()).ravel() > 0).astype(float)
ds = lgb.Dataset(X, label=y)

# Same-process baseline: imports done, data built, nothing constructed.
# reset_host_peak resets the kernel VmHWM watermark (clear_refs "5") so
# the post-construct read covers only the construct; where /proc is
# unavailable the ru_maxrss fallback's pre/post difference still catches
# any allocation pushing past the prior lifetime peak (the 1.6 GB dense
# copy always does).
_hwm_ok = MemoryTracker.reset_host_peak()
base_mb = MemoryTracker.host_peak_rss_mb(use_hwm=_hwm_ok)

ds.construct({"objective": "binary", "verbosity": -1,
              "enable_bundle": False})
delta_mb = MemoryTracker.host_peak_rss_mb(use_hwm=_hwm_ok) - base_mb
print("BASE_MB", base_mb, "DELTA_MB", delta_mb,
      "(VmHWM)" if _hwm_ok else "(ru_maxrss)")
# Legit construct cost: bins (100k x 2000 uint8) = 200 MB plus per-column
# working buffers; 900 MB of headroom still sits far below the ~1.6 GB
# the dense-f64 copy would add on top.
sys.exit(0 if delta_mb < 900 else 1)
"""
    r = subprocess.run([sys.executable, "-u", "-c", code],
                       capture_output=True, text=True, timeout=600,
                       env={**os.environ, "LIGHTGBM_TPU_PLATFORM": "cpu"})
    assert r.returncode == 0, r.stdout + r.stderr


def test_pandas_series_label_and_weight():
    rng = np.random.RandomState(2)
    X = rng.randn(300, 4)
    y = pd.Series((X[:, 0] > 0).astype(float))
    w = pd.Series(np.ones(300))
    bst = lgb.train({"objective": "binary", "num_leaves": 7,
                     "verbosity": -1},
                    lgb.Dataset(X, label=y, weight=w), 3)
    assert bst.num_trees() == 3


def test_pyarrow_table_input():
    pa = pytest.importorskip("pyarrow")
    rng = np.random.RandomState(3)
    n = 500
    codes = rng.randint(0, 4, n)
    tbl = pa.table({
        "f0": rng.randn(n),
        "f1": rng.randn(n),
        "cat": pa.array(np.array(["a", "b", "c", "d"])[codes]).dictionary_encode(),
    })
    y = (tbl.column("f0").to_numpy() + (codes == 1) > 0.3).astype(float)
    bst = lgb.train({"objective": "binary", "num_leaves": 15,
                     "verbosity": -1}, lgb.Dataset(tbl, label=y), 8)
    assert bst.feature_name() == ["f0", "f1", "cat"]
    td = lgb.Dataset(tbl, label=y).construct({"objective": "binary",
                                              "verbosity": -1})
    assert bool(td.binned.is_categorical[2])
    acc = ((bst.predict(tbl) > 0.5) == (y > 0.5)).mean()
    assert acc > 0.85


def test_chunked_and_sequence_input():
    rng = np.random.RandomState(4)
    chunks = [rng.randn(200, 4) for _ in range(5)]
    X = np.concatenate(chunks, axis=0)
    y = (X[:, 0] > 0).astype(float)

    class _Seq(lgb.Sequence):
        def __init__(self, arr):
            self.arr = arr

        def __len__(self):
            return len(self.arr)

        def __getitem__(self, idx):
            return self.arr[idx]

    for data in (chunks, _Seq(X), [_Seq(chunks[0]), _Seq(chunks[1]),
                                   np.concatenate(chunks[2:], axis=0)]):
        bst = lgb.train({"objective": "binary", "num_leaves": 7,
                         "verbosity": -1}, lgb.Dataset(data, label=y), 3)
        p_chunks = bst.predict(X[:50])
        assert p_chunks.shape == (50,)


def test_dataset_subset_and_add_features():
    rng = np.random.RandomState(5)
    X = rng.randn(400, 4)
    y = (X[:, 0] > 0).astype(float)
    ds = lgb.Dataset(X, label=y, weight=np.ones(400))
    sub = ds.subset(np.arange(0, 400, 2))
    assert sub.num_data() == 200
    bst = lgb.train({"objective": "binary", "num_leaves": 7,
                     "verbosity": -1}, sub, 3)
    assert bst.num_trees() == 3

    extra = lgb.Dataset(rng.randn(400, 2))
    ds2 = lgb.Dataset(X.copy(), label=y, feature_name=[f"f{i}" for i in range(4)])
    ds2.add_features_from(extra)
    assert ds2.num_feature() == 6
    td = ds2.construct({"objective": "binary", "verbosity": -1})
    assert td.num_features == 6


def test_in_data_column_specs(tmp_path):
    """weight_column / group_column / ignore_column reference semantics:
    int indices don't count the label column; name: uses the header; the
    query column holds per-row ids; ignored columns leave the matrix."""
    import numpy as np
    from lightgbm_tpu.io.parser import load_data_file

    rng = np.random.RandomState(0)
    n = 40
    y = rng.randint(0, 2, n).astype(float)
    f0 = rng.randn(n)
    w = rng.rand(n) + 0.5
    qid = np.repeat(np.arange(8), 5).astype(float)
    junk = np.full(n, 9.9)
    f1 = rng.randn(n)
    # file columns: label, f0, weight, qid, junk, f1
    mat = np.column_stack([y, f0, w, qid, junk, f1])
    path = tmp_path / "d.csv"
    np.savetxt(path, mat, delimiter=",", fmt="%.10g",
               header="lab,f0,wt,q,junk,f1", comments="")

    X, yy, ww, gg = load_data_file(
        str(path), label_column="0", header=True,
        weight_column="1",     # X-space: w is file col 2 -> X col 1
        group_column="2",      # X-space: qid is file col 3 -> X col 2
        ignore_column="3")     # X-space: junk is file col 4 -> X col 3
    np.testing.assert_allclose(yy, y)
    np.testing.assert_allclose(ww, w, rtol=1e-9)
    np.testing.assert_array_equal(gg, np.full(8, 5))
    assert X.shape == (n, 2)
    np.testing.assert_allclose(X[:, 0], f0, rtol=1e-9)
    np.testing.assert_allclose(X[:, 1], f1, rtol=1e-9)

    # name: form resolves through the header identically
    X2, _, ww2, gg2 = load_data_file(
        str(path), label_column="name:lab", header=True,
        weight_column="name:wt", group_column="name:q",
        ignore_column="name:junk")
    np.testing.assert_allclose(ww2, w, rtol=1e-9)
    np.testing.assert_array_equal(gg2, np.full(8, 5))
    np.testing.assert_allclose(X2, X, rtol=1e-9)


def test_column_specs_tsv_and_sidefile_independence(tmp_path):
    """name: specs must work on TSV headers, and a .query side file loads
    even when weight comes from an in-data column (independent fields,
    reference metadata.cpp)."""
    import numpy as np
    from lightgbm_tpu.io.parser import load_data_file

    rng = np.random.RandomState(1)
    n = 20
    y = rng.randint(0, 2, n).astype(float)
    f0 = rng.randn(n)
    w = rng.rand(n) + 0.5
    mat = np.column_stack([y, f0, w])
    path = tmp_path / "d.tsv"
    np.savetxt(path, mat, delimiter="\t", fmt="%.10g",
               header="lab\tf0\twt", comments="")
    np.savetxt(str(path) + ".query", np.array([5, 5, 10]), fmt="%d")

    X, yy, ww, gg = load_data_file(str(path), label_column="name:lab",
                                   header=True, weight_column="name:wt")
    np.testing.assert_allclose(ww, w, rtol=1e-9)
    np.testing.assert_array_equal(gg, [5, 5, 10])   # side file still loads
    assert X.shape == (n, 1)
    np.testing.assert_allclose(X[:, 0], f0, rtol=1e-9)


def test_header_names_propagate_to_model(tmp_path):
    """CSV header names must survive into the saved model's feature_names
    (reference DatasetLoader reads them from the header), accounting for
    extracted weight columns."""
    import subprocess, sys, os
    import numpy as np
    rng = np.random.RandomState(0)
    n = 200
    mat = np.column_stack([rng.randint(0, 2, n), rng.rand(n) + 0.5,
                           rng.randn(n), rng.randn(n)])
    path = tmp_path / "d.csv"
    np.savetxt(path, mat, delimiter=",", fmt="%.8g",
               header="lab,wt,alpha,beta", comments="")
    out = tmp_path / "m.txt"
    env = dict(os.environ, LIGHTGBM_TPU_PLATFORM="cpu")
    r = subprocess.run(
        [sys.executable, "-m", "lightgbm_tpu", "task=train",
         "objective=binary", "header=true", f"data={path}",
         "weight_column=0", "num_iterations=2", "num_leaves=4",
         f"output_model={out}"], capture_output=True, text=True, env=env)
    assert r.returncode == 0, r.stderr[-1500:]
    model = out.read_text()
    assert "feature_names=alpha beta" in model


def test_dataset_accepts_text_file_path(tmp_path):
    """lgb.Dataset('train.csv') must load text files like the reference
    python package (binary caches remain the fast path), honoring header
    names and params column specs."""
    import numpy as np
    import lightgbm_tpu as lgb
    rng = np.random.RandomState(0)
    n = 500
    Xf = rng.randn(n, 3)
    y = (Xf[:, 0] > 0).astype(float)
    path = tmp_path / "tr.csv"
    np.savetxt(path, np.column_stack([y, Xf]), delimiter=",", fmt="%.8g",
               header="lab,a,b,c", comments="")
    ds = lgb.Dataset(str(path), params={"header": True})
    bst = lgb.train({"objective": "binary", "verbosity": -1,
                     "num_leaves": 7, "header": True}, ds, 5)
    assert bst.feature_name() == ["a", "b", "c"]
    acc = ((bst.predict(Xf) > 0.5) == (y > 0.5)).mean()
    assert acc > 0.95


def test_categorical_feature_name_prefix(tmp_path):
    """categorical_feature='name:c1,c2' (reference form: one prefix for
    the whole list) resolves through feature names."""
    import numpy as np
    import lightgbm_tpu as lgb
    rng = np.random.RandomState(0)
    n = 600
    cat = rng.randint(0, 6, n).astype(float)
    num = rng.randn(n)
    y = (np.isin(cat, [1, 4]) ^ (num > 0)).astype(float)
    X = np.column_stack([cat, num])
    ds = lgb.Dataset(X, label=y, feature_name=["kind", "score"],
                     categorical_feature="name:kind")
    bst = lgb.train({"objective": "binary", "verbosity": -1,
                     "num_leaves": 7}, ds, 15)
    model = bst.model_to_string()
    # trees record categorical split counts in num_cat (reference
    # gbdt_model_text format)
    assert any(line.startswith("num_cat=") and set(line[8:].split()) != {"0"}
               for line in model.splitlines())
    acc = ((bst.predict(X) > 0.5) == (y > 0.5)).mean()
    assert acc > 0.9
