"""Iteration-packed training (docs/ITER_PACK.md): ``tpu_iter_pack=K`` scans
K boosting rounds into ONE jitted dispatch.  Pack size is a scheduling
knob, never a modeling knob — these tests pin bitwise-identical models
between K=1 and K=4 across the supported mask configurations, identical
early-stopping behavior, the exact pack-boundary degenerate stop, and the
auto-degrade contract."""

import numpy as np
import pytest

import lightgbm_tpu as lgb


def _data(n=600, f=8, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, f)
    y = (X[:, 0] + 0.5 * rng.randn(n) > 0).astype(np.float64)
    return X, y


BASE = {"objective": "binary", "num_leaves": 7, "min_data_in_leaf": 5,
        "verbosity": -1}


def _train(extra, pack, num_round=8, label=None, X=None):
    Xd, y = _data()
    if X is not None:
        Xd = X
    if label is not None:
        y = label
    params = dict(BASE, tpu_iter_pack=pack)
    params.update(extra)
    return lgb.train(params, lgb.Dataset(Xd, label=y), num_round)


def _assert_identical(b1, b4, scores_exact=True):
    """Bitwise model identity: tree structure, leaf values, final scores.
    ``scores_exact=False`` allows float dust in the resident train scores
    (mid-pack early stop recovers them by predict-and-subtract); the MODEL
    stays bitwise identical either way."""
    assert b1.num_trees() == b4.num_trees()
    for c1, c4 in zip(b1._gbdt.models, b4._gbdt.models):
        for t1, t4 in zip(c1, c4):
            assert t1.num_leaves == t4.num_leaves
            k = max(t1.num_leaves - 1, 0)
            assert np.array_equal(t1.split_feature[:k], t4.split_feature[:k])
            assert np.array_equal(t1.split_bin[:k], t4.split_bin[:k])
            assert np.array_equal(t1.leaf_value, t4.leaf_value)
    s1 = np.asarray(b1._gbdt.scores)
    s4 = np.asarray(b4._gbdt.scores)
    if scores_exact:
        assert np.array_equal(s1, s4)
    else:
        np.testing.assert_allclose(s1, s4, rtol=1e-6, atol=1e-6)


@pytest.mark.parametrize("extra", [
    {},                                                    # binary, static
    {"bagging_fraction": 0.7, "bagging_freq": 2},          # device bagging
    {"feature_fraction": 0.6},                             # device col mask
    {"bagging_fraction": 0.8, "bagging_freq": 1,
     "feature_fraction": 0.7},                             # both dynamic
    {"data_sample_strategy": "goss"},                      # in-trace GOSS
    {"cegb_tradeoff": 0.5, "cegb_penalty_split": 0.02,
     "cegb_penalty_feature_coupled": [2.0] * 8},           # in-trace CEGB
], ids=["binary", "bagging", "feature_fraction", "bagging+ff", "goss",
        "cegb"])
def test_pack_bitwise_identical_binary(extra):
    _assert_identical(_train(extra, 1), _train(extra, 4))


def test_pack_bitwise_identical_multiclass():
    rng = np.random.RandomState(1)
    y = rng.randint(0, 3, 600).astype(np.float64)
    extra = {"objective": "multiclass", "num_class": 3}
    _assert_identical(_train(extra, 1, label=y), _train(extra, 4, label=y))


def test_pack_bitwise_identical_quantized():
    extra = {"use_quantized_grad": True}
    _assert_identical(_train(extra, 1), _train(extra, 4))


def test_pack_remainder_rounds():
    """num_boost_round not divisible by K: the trailing smaller pack trains
    the exact remaining rounds."""
    b = _train({}, 4, num_round=10)
    assert b.num_trees() == 10
    _assert_identical(_train({}, 1, num_round=10), b)


def test_auto_pack_matches_explicit_on_static_masks():
    """tpu_iter_pack=0 (auto) packs static-mask configs and must produce
    the same model as the explicit pack path AND the per-round semantics."""
    auto = _train({"tpu_iter_pack": 0}, 0)
    _assert_identical(auto, _train({}, 1))


def test_early_stopping_fires_same_iteration():
    """Early stopping must fire at the SAME iteration for K=1 and K=4: the
    engine commits pack rounds one by one and replays callbacks per round
    (valid scores update per committed tree), then discards the mid-pack
    tail — per-iteration semantics survive packing exactly."""
    X, y = _data()
    Xv, yv = _data(n=300, seed=7)
    results = []
    for pack in (1, 4):
        params = dict(BASE, tpu_iter_pack=pack, metric="binary_logloss",
                      early_stopping_round=3)
        bst = lgb.train(params, lgb.Dataset(X, label=y), 60,
                        valid_sets=[lgb.Dataset(Xv, label=yv)],
                        valid_names=["v"])
        results.append(bst)
    b1, b4 = results
    assert b1.best_iteration == b4.best_iteration
    assert b1.num_trees() == b4.num_trees()
    _assert_identical(b1, b4, scores_exact=False)


def test_pack_boundary_degenerate_stop_is_exact():
    """A constant target grows no tree; the pack path trims the degenerate
    rounds at the pack boundary, storing NO stump trees (the per-round
    deferred check stores up to two — see
    test_degenerate_stop_deferred_exactly_one_extra)."""
    X, _ = _data()
    y = np.zeros(X.shape[0])
    bst = lgb.train({"objective": "regression", "verbosity": -1,
                     "num_leaves": 7, "tpu_iter_pack": 4},
                    lgb.Dataset(X, label=y), 10)
    assert bst.num_trees() == 0
    # predictions are still exact: init score only
    np.testing.assert_allclose(bst.predict(X[:16]), np.zeros(16), atol=1e-7)


def test_pack_degrades_for_host_paths():
    """Configs that need the host every round must degrade to the per-round
    path (with a warning), not crash or silently change semantics."""
    X, y = _data()
    # GOSS packs by default (the tpu_device_goss auto/on in-trace mask);
    # only the host-RNG sampler (off) pins the per-round loop.
    gdev = lgb.train(dict(BASE, tpu_iter_pack=4,
                          data_sample_strategy="goss"),
                     lgb.Dataset(X, label=y), 5)._gbdt
    assert gdev.iter_pack_degrade_reason() is None
    # CEGB packs too: the first-use used vector is device state carried
    # through the scan
    gcegb = lgb.train(dict(BASE, tpu_iter_pack=4, cegb_tradeoff=0.5,
                           cegb_penalty_split=0.02,
                           cegb_penalty_feature_coupled=[2.0] * 8),
                      lgb.Dataset(X, label=y), 5)._gbdt
    assert gcegb.iter_pack_degrade_reason() is None
    assert gcegb.iter_pack_plan(4) == (4, True)
    gbdt = lgb.train(dict(BASE, tpu_iter_pack=4,
                          data_sample_strategy="goss",
                          tpu_device_goss="off"),
                     lgb.Dataset(X, label=y), 5)._gbdt
    assert gbdt.iter_pack_degrade_reason() is not None
    assert gbdt.iter_pack_plan(5) == (1, False)
    # linear trees: host leaf solves
    greg = lgb.train({"objective": "regression", "verbosity": -1,
                      "num_leaves": 7, "linear_tree": True,
                      "tpu_iter_pack": 4},
                     lgb.Dataset(X, label=X[:, 0] * 2.0), 3)._gbdt
    assert greg.iter_pack_degrade_reason() is not None
    # l1 regression renews leaf outputs on the host
    gl1 = lgb.train({"objective": "regression_l1", "verbosity": -1,
                     "num_leaves": 7, "tpu_iter_pack": 4},
                    lgb.Dataset(X, label=X[:, 0]), 3)._gbdt
    assert gl1.iter_pack_degrade_reason() is not None


def test_auto_pack_preserves_host_rng_sampling():
    """Auto mode must not silently swap the host bagging RNG for device
    sampling: with bagging active, auto resolves to the per-round path and
    the model matches the seed's host-RNG behavior."""
    extra = {"bagging_fraction": 0.7, "bagging_freq": 2}
    auto = _train(dict(extra, tpu_iter_pack=0), 0)
    assert auto._gbdt.iter_pack_plan(8) == (1, False)
    # explicit pack (device sampling) is allowed to differ from auto here;
    # it must still be self-consistent (covered by the bitwise test above)


def test_update_pack_booster_api():
    """Booster.update_pack trains K rounds in one dispatch and reports the
    rounds actually kept."""
    X, y = _data()
    bst = lgb.Booster(params=dict(BASE, tpu_iter_pack=6),
                      train_set=lgb.Dataset(X, label=y))
    done, finished = bst.update_pack(6)
    assert (done, finished) == (6, False)
    assert bst.num_trees() == 6
    ref = _train({}, 1, num_round=6)
    _assert_identical(ref, bst)
