"""tools/profile_iter.py dispatch census (ISSUE-4 satellite, re-pinned by
ISSUE-5): GOSS (device, the ``tpu_device_goss`` auto default) and CEGB now
ride the fused ONE-dispatch iteration — the census must report exactly 1.0
compiled-program dispatches per boosting round for them, same as the plain
fused hot path.  The remaining ``used_fused=False`` paths are the host
GOSS sampler (``tpu_device_goss=off``, kept for host-RNG replay) and
linear trees — whose leaf models solve in ONE batched device dispatch, so
their host-sync count is a constant independent of the leaf count (zero
per-leaf syncs in the solve)."""

from tools.profile_iter import nonfused_dispatch_census


def test_census_fused_paths_one_dispatch():
    blobs = {b["path"]: b for b in
             nonfused_dispatch_census(rows=4096, iters=3, num_leaves=15)}
    assert set(blobs) == {"fused", "goss", "goss_host", "cegb",
                          "linear_tree"}
    for path in ("fused", "goss", "cegb"):
        assert blobs[path]["used_fused"] is True, blobs[path]
        assert blobs[path]["dispatches_per_iter"] == 1.0, blobs[path]
    # tpu_device_goss=off replays the reference's host sampler: extra
    # dispatches (gradients + grower) plus the gradient pull to the host.
    assert blobs["goss_host"]["used_fused"] is False
    assert blobs["goss_host"]["dispatches_per_iter"] > 1.0
    assert (blobs["goss_host"]["host_syncs_per_iter"]
            > blobs["goss"]["host_syncs_per_iter"])


def test_fused_wave_census_one_dispatch_per_wave():
    """ISSUE-7: the fused wave grower issues ONE histogram-kernel dispatch
    per wave (leaf batches pipelined through the pallas grid); unfused
    issues one per leaf (a W-trip fori_loop).  Either way the boosting
    round stays ONE compiled program launch."""
    from tools.profile_iter import fused_wave_census

    blobs = {b["wave_kernel"]: b for b in fused_wave_census(
        rows=4096, features=10, num_leaves=15, leaf_batch=4)}
    fused, unfused = blobs["fused"], blobs["unfused"]
    assert fused["fused_active"] is True
    assert unfused["fused_active"] is False
    assert fused["hist_dispatches_per_wave"] == 1
    assert unfused["hist_dispatches_per_wave"] == 4 == unfused["leaf_batch"]
    assert fused["dispatches_per_iter"] == 1.0
    assert unfused["dispatches_per_iter"] == 1.0


def test_predict_dispatch_census_one_dispatch_per_call():
    """ISSUE-12: the serve plan costs exactly ONE compiled dispatch and
    ONE host sync per raw predict call — on BOTH traversal paths (the
    fused Pallas kernel rides inside the same jitted program, so fusion
    cannot add launches).  The output transform adds one eager dispatch's
    sync (the documented convert-output cost, docs/SERVING.md)."""
    from tools.profile_iter import predict_dispatch_census

    blobs = {b["path"]: b for b in predict_dispatch_census(
        rows=1024, features=6, iters=4, calls=3)}
    assert set(blobs) == {"fused", "unfused"}
    assert blobs["fused"]["traverse_active"] == "fused"
    assert blobs["unfused"]["traverse_active"] == "unfused"
    for blob in blobs.values():
        assert blob["dispatches_per_predict_raw"] == 1.0, blob
        assert blob["host_syncs_per_predict_raw"] == 1.0, blob
        assert blob["dispatches_per_predict_transform"] == 1.0, blob
        assert blob["host_syncs_per_predict_transform"] == 2.0, blob


def test_census_linear_solve_no_per_leaf_syncs():
    """The batched linear-leaf solve: host syncs per iteration must NOT
    scale with num_leaves (the per-leaf Python solve loop pulled 6 arrays
    per leaf batch; the batched op does one constant-size readback)."""
    lo, hi = (nonfused_dispatch_census(rows=4096, iters=3, num_leaves=nl,
                                       paths=("linear_tree",))[0]
              for nl in (7, 31))
    assert lo["used_fused"] is False and hi["used_fused"] is False
    assert hi["host_syncs_per_iter"] == lo["host_syncs_per_iter"], (lo, hi)
    # one grower + one gradient + one batched-solve program per round —
    # nothing per-leaf
    assert hi["dispatches_per_iter"] <= 4.0, hi
