"""tools/profile_iter.py non-fused dispatch census (ISSUE-4 satellite):
the GOSS / CEGB / linear_tree fallbacks (``gbdt.train_one_iter``
``used_fused=False``) must report MORE compiled-program dispatches per
boosting iteration than the fused hot path (1.0) — the measured fused-path
coverage gap, visible in profiles instead of silent."""

from tools.profile_iter import nonfused_dispatch_census


def test_nonfused_census_shapes_and_gap():
    blobs = {b["path"]: b for b in
             nonfused_dispatch_census(rows=4096, iters=3, num_leaves=15)}
    assert set(blobs) == {"fused", "goss", "cegb", "linear_tree"}
    assert blobs["fused"]["used_fused"] is True
    assert blobs["fused"]["dispatches_per_iter"] == 1.0
    for path in ("goss", "cegb", "linear_tree"):
        assert blobs[path]["used_fused"] is False
        assert blobs[path]["dispatches_per_iter"] > 1.0, blobs[path]
    # linear_tree does host leaf solves: its per-iteration host syncs are
    # the worst of the family — the census must expose that, not hide it
    assert (blobs["linear_tree"]["host_syncs_per_iter"]
            > blobs["fused"]["host_syncs_per_iter"])
