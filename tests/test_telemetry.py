"""Unified telemetry invariants (ISSUE-9, docs/OBSERVABILITY.md):

- registry thread-safety under hammering threads (serve threads + the
  training loop publish concurrently);
- JSONL schema round-trip: every event a train run emits re-parses and
  carries the schema/ts/kind envelope, ``train.iter`` events split wall
  time into dispatch wait vs host bookkeeping, and the report/census/
  health tools all read the same artifact;
- the inertness contract: ``tpu_telemetry=off`` compiles bitwise-identical
  training programs (equal lowered-HLO text) and the fused dispatch
  census stays 1.0 dispatches/iter WITH telemetry armed;
- the Prometheus exposition renders every ServeMetrics gauge, including
  the degradation and nan_scores counters, with a stable plan-less schema;
- tools/telemetry_report.py CLI smoke (subprocess);
- utils/timer.py thread-safety and nested same-name re-entrancy.
"""

import json
import os
import subprocess
import sys
import threading

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu import telemetry
from lightgbm_tpu.serve.metrics import ServeMetrics
from lightgbm_tpu.utils.timer import Timer

pytestmark = pytest.mark.telemetry

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _data(n=1200, f=6, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, f)
    y = (X[:, 0] + X[:, 1] * X[:, 2] > 0).astype(np.float64)
    return X, y


@pytest.fixture(autouse=True)
def _rearm():
    """Every test starts armed (the process default) and leaves no sink."""
    telemetry.set_enabled(True)
    yield
    telemetry.close_log()
    telemetry.set_enabled(True)


# ------------------------------------------------------------------ registry
def test_registry_thread_safety_under_hammering():
    reg = telemetry.MetricsRegistry()
    threads, per_thread = 8, 2000

    def hammer(i):
        c = reg.counter("hammer.count")
        h = reg.histogram("hammer.lat")
        g = reg.gauge("hammer.depth")
        for j in range(per_thread):
            c.inc()
            h.observe(0.001 * (j % 7))
            g.set(j)

    ts = [threading.Thread(target=hammer, args=(i,)) for i in range(threads)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    snap = reg.snapshot()
    assert snap["counters"]["hammer.count"] == threads * per_thread
    hist = snap["histograms"]["hammer.lat"]
    assert hist["count"] == threads * per_thread
    assert hist["p50"] is not None and hist["max"] is not None
    assert snap["gauges"]["hammer.depth"] == per_thread - 1


def test_registry_instruments_are_shared_per_name():
    reg = telemetry.MetricsRegistry()
    assert reg.counter("x") is reg.counter("x")
    assert reg.histogram("h") is reg.histogram("h")
    reg.counter("x").inc(3)
    assert reg.counter("x").value == 3


# ------------------------------------------------------------------- timer
def test_timer_nested_same_name_reentrant():
    t = Timer()
    t.start("a")
    t.start("a")      # nested same-name span must not lose the outer start
    t.stop("a")
    t.stop("a")
    t.stop("a")       # unmatched stop is a no-op, not corruption
    assert t.counts["a"] == 2
    assert t.durations["a"] >= 0.0


def test_timer_thread_safety():
    t = Timer()

    def work():
        for _ in range(500):
            t.start("w")
            t.stop("w")

    ts = [threading.Thread(target=work) for _ in range(8)]
    for th in ts:
        th.start()
    for th in ts:
        th.join()
    assert t.counts["w"] == 8 * 500
    assert not t._starts     # no stranded in-flight starts


# -------------------------------------------------------------------- spans
def test_span_hierarchy_and_disable():
    telemetry.reset_spans()
    with telemetry.span("outer"):
        with telemetry.span("inner"):
            pass
    totals = telemetry.span_totals()
    assert totals["outer"]["count"] == 1
    assert totals["outer/inner"]["count"] == 1
    telemetry.set_enabled(False)
    with telemetry.span("outer"):
        pass
    assert telemetry.span_totals()["outer"]["count"] == 1   # unchanged


# -------------------------------------------------------- JSONL round-trip
def test_jsonl_schema_roundtrip_and_tools(tmp_path):
    log = str(tmp_path / "run.jsonl")
    X, y = _data()
    Xv, yv = _data(400, seed=1)
    ds = lgb.Dataset(X, label=y)
    dv = lgb.Dataset(Xv, label=yv, reference=ds)
    history = {}
    bst = lgb.train(
        {"objective": "binary", "num_leaves": 15, "verbosity": -1,
         "metric": "binary_logloss", "tpu_telemetry_log": log,
         "checkpoint_interval": 2,
         "checkpoint_dir": str(tmp_path / "ckpt")},
        ds, 5, valid_sets=[dv], valid_names=["valid"],
        callbacks=[lgb.record_evaluation(history)])
    assert bst.num_trees() == 5
    # the sink the engine opened is closed again (leak contract)
    assert telemetry.active_sink() is None

    events = [json.loads(line) for line in open(log)]
    kinds = [e["kind"] for e in events]
    assert kinds[0] == "train.start" and kinds[-1] == "train.end"
    assert kinds.count("train.iter") == 5
    assert "train.checkpoint" in kinds
    ts = [e["ts"] for e in events]
    assert ts == sorted(ts)      # monotonic clock, in-order writes
    for e in events:             # envelope on every single line
        assert e["schema"] == telemetry.SCHEMA_VERSION
        assert isinstance(e["ts"], float) and isinstance(e["kind"], str)
        assert "wall" in e and "pid" in e
    iters = [e for e in events if e["kind"] == "train.iter"]
    for e in iters:
        assert e["wall_s"] >= e["dispatch_wait_s"] >= 0.0
        assert e["host_s"] >= 0.0 and e["pack_size"] >= 1
    # the record_evaluation callback pins the per-round path; checkpoint
    # write durations land on their rounds
    assert any(e["checkpoint_s"] is not None for e in iters)
    end = events[-1]
    assert end["iterations"] == 5 and end["spans"], end

    # one artifact, three readers (ISSUE-9 satellite)
    from tools.profile_iter import census_from_log
    census = census_from_log(log)
    assert census["iters"] == 5 and census["mean_wall_s"] > 0
    from tools.health_report import bench_health_rows, is_telemetry_log
    assert is_telemetry_log(log)
    rows = bench_health_rows([log])
    assert rows and rows[0][1] == "log" and rows[0][3] == 5
    from tools.telemetry_report import load_events
    loaded, problems = load_events(log)
    assert len(loaded) == len(events) and not problems


def test_telemetry_report_cli_smoke(tmp_path):
    log = str(tmp_path / "run.jsonl")
    X, y = _data(800)
    ds = lgb.Dataset(X, label=y)
    lgb.train({"objective": "binary", "num_leaves": 7, "verbosity": -1,
               "metric": "none", "tpu_telemetry_log": log}, ds, 3)
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "telemetry_report.py"),
         log], capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stderr
    assert "iterations" in proc.stdout and "phases" in proc.stdout
    assert "train.iter" in proc.stdout


def test_report_tolerates_torn_and_unknown_lines(tmp_path):
    log = tmp_path / "torn.jsonl"
    log.write_text(
        json.dumps({"schema": 1, "kind": "train.iter", "ts": 1.0,
                    "wall": 0.0, "pid": 1, "iteration": 1, "wall_s": 0.5,
                    "dispatch_wait_s": 0.4, "host_s": 0.1,
                    "pack_size": 1}) + "\n"
        + json.dumps({"schema": 99, "kind": "future.kind", "ts": 2.0}) + "\n"
        + '{"torn": \n')
    from tools.telemetry_report import load_events
    events, problems = load_events(str(log))
    assert len(events) == 1 and len(problems) == 2


# ------------------------------------------------------- inertness contract
def _fused_lowered_text(tpu_telemetry):
    X, y = _data(600)
    ds = lgb.Dataset(X, label=y)
    bst = lgb.Booster(params={"objective": "binary", "num_leaves": 7,
                              "verbosity": -1, "metric": "none",
                              "tpu_telemetry": tpu_telemetry},
                      train_set=ds)
    g = bst._gbdt
    assert g._fused_iter is not None
    lowered = g._fused_iter.lower(g.bins_dev, g.scores, g._full_mask,
                                  g._fmask_static, 0.1, None, None, None,
                                  None, None)
    return lowered.as_text()


def test_off_mode_bitwise_program_identity():
    """tpu_telemetry=off vs on: the lowered fused-iteration HLO is equal
    TEXT — telemetry never enters a traced program."""
    on = _fused_lowered_text("on")
    off = _fused_lowered_text("off")
    assert on == off
    telemetry.set_enabled(True)


def test_census_one_dispatch_with_telemetry_armed(tmp_path):
    """The fused census stays 1.0 dispatches/iter WITH telemetry armed
    (spans + a live JSONL sink): instrumentation adds zero launches."""
    from tools.profile_iter import nonfused_dispatch_census
    telemetry.configure_log(str(tmp_path / "census.jsonl"))
    try:
        blobs = nonfused_dispatch_census(rows=2048, iters=2, num_leaves=7,
                                         paths=("fused",))
    finally:
        telemetry.close_log()
    assert blobs[0]["used_fused"] is True
    assert blobs[0]["dispatches_per_iter"] == 1.0, blobs[0]


def test_telemetry_knob_validated():
    X, y = _data(300)
    ds = lgb.Dataset(X, label=y)
    with pytest.raises(ValueError, match="tpu_telemetry"):
        lgb.Booster(params={"objective": "binary", "verbosity": -1,
                            "tpu_telemetry": "maybe"}, train_set=ds)


# ------------------------------------------------------------- prometheus
def test_prometheus_renders_every_serve_gauge():
    m = ServeMetrics()
    m.observe_request(8, 0.002)
    m.observe_batch(8, 16)
    m.observe_queue_depth(3)
    m.observe_shed()
    m.observe_deadline_miss()
    m.observe_device_fault()
    m.observe_host_fallback()
    m.observe_nan_scores()
    snap = m.snapshot()
    text = m.render_prometheus()
    for key, val in snap.items():
        if isinstance(val, dict) or val is None:
            continue
        assert f"lgbm_tpu_serve_{key} " in text, key
    # the degradation + nan_scores counters, with their values
    assert "lgbm_tpu_serve_shed 1.0" in text
    assert "lgbm_tpu_serve_deadline_misses 1.0" in text
    assert "lgbm_tpu_serve_nan_scores 1.0" in text
    assert "# TYPE lgbm_tpu_serve_requests counter" in text
    assert "# TYPE lgbm_tpu_serve_queue_depth gauge" in text


def test_snapshot_stable_schema_without_plan():
    """plan=None keeps the plan-derived keys (as None) so scrapers see one
    schema; the exposition renders them as NaN instead of dropping them."""
    m = ServeMetrics()
    snap = m.snapshot()
    assert "compiles" in snap and snap["compiles"] is None
    assert "plan_cache" in snap and snap["plan_cache"] is None
    text = m.render_prometheus()
    assert "lgbm_tpu_serve_compiles NaN" in text
    assert "lgbm_tpu_serve_plan_cache_hits NaN" in text


def test_prometheus_registry_snapshot_typing():
    """The whole-registry exposition types by SECTION: everything under
    `counters` is a counter, gauges/histograms are gauges — regardless of
    leaf-name collisions with the serve key list."""
    reg = telemetry.MetricsRegistry()
    reg.counter("health.trips").inc(2)
    reg.counter("custom.rows").inc(5)          # leaf collides with a gauge-y name
    reg.gauge("watchdog.probe_latency_s").set(1.5)
    reg.histogram("checkpoint.save_s").observe(0.01)
    text = telemetry.render_prometheus(reg.snapshot(), prefix="lgbm_tpu")
    assert "# TYPE lgbm_tpu_counters_health_trips counter" in text
    assert "# TYPE lgbm_tpu_counters_custom_rows counter" in text
    assert "# TYPE lgbm_tpu_gauges_watchdog_probe_latency_s gauge" in text
    assert "# TYPE lgbm_tpu_histograms_checkpoint_save_s_count gauge" in text


def test_pack_path_checkpoints_counted_from_log(tmp_path):
    """Packed runs snapshot at pack boundaries (no train.iter carries the
    duration), so the census counts checkpoint writes from the
    train.checkpoint events both paths emit."""
    log = str(tmp_path / "pack.jsonl")
    X, y = _data(1500)
    ds = lgb.Dataset(X, label=y)
    lgb.train({"objective": "binary", "num_leaves": 7, "verbosity": -1,
               "metric": "none", "tpu_telemetry_log": log,
               "tpu_iter_pack": 3, "checkpoint_interval": 3,
               "checkpoint_dir": str(tmp_path / "ckpt")}, ds, 6)
    events = [json.loads(line) for line in open(log)]
    kinds = [e["kind"] for e in events]
    assert kinds.count("train.iter") == 6
    assert all(e["pack_size"] == 3 for e in events
               if e["kind"] == "train.iter")
    n_ckpt = kinds.count("train.checkpoint")
    assert n_ckpt >= 1
    from tools.profile_iter import census_from_log
    assert census_from_log(log)["checkpoint_writes"] == n_ckpt


def test_serve_metrics_mirror_into_process_registry():
    before = telemetry.registry().counter("serve.nan_scores").value
    m = ServeMetrics()
    m.observe_nan_scores()
    assert telemetry.registry().counter("serve.nan_scores").value \
        == before + 1


# ------------------------------------------------------------ bench block
def test_bench_telemetry_block_schema():
    import bench
    telemetry.reset_spans()
    X, y = _data(600)
    ds = lgb.Dataset(X, label=y)
    bst = lgb.Booster(params={"objective": "binary", "num_leaves": 7,
                              "verbosity": -1, "metric": "none"},
                      train_set=ds)
    bst.update()
    blk = bench._telemetry_block()
    assert blk["schema"] == telemetry.SCHEMA_VERSION
    assert blk["enabled"] is True
    assert isinstance(blk["events"], dict)
    spans = blk["spans"]
    assert any(name.startswith("train/") for name in spans), spans
    for d in spans.values():
        assert d["seconds"] >= 0.0 and d["count"] >= 1
    assert "counters" in blk["registry"]
    json.dumps(blk)     # JSON-safe end to end
