"""Wave growth (tpu_leaf_batch > 1): multi-leaf splitting per step.

The wave grower keeps the best-first SPLIT SET (each wave takes the current
top-gain leaves, truncated to the leaf budget by gain) but batches up to W
splits per compiled step with a single multi-sibling histogram kernel.
Quality must match strict leaf-wise growth; the exact tree may differ only
through wave interleaving.
"""

import numpy as np
import pytest

import lightgbm_tpu as lgb


def _data(n=6000, f=10, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, f)
    logits = X[:, 0] * 2 - X[:, 1] + np.sin(X[:, 2] * 2) + 0.3 * rng.randn(n)
    y = (logits > 0).astype(np.float64)
    return X, y


def _auc(bst, X, y):
    from lightgbm_tpu.metrics import _auc as auc
    return auc(y, bst.predict(X, raw_score=True), None, None)


BASE = {"objective": "binary", "num_leaves": 31, "learning_rate": 0.1,
        "min_data_in_leaf": 10, "verbosity": -1, "metric": "none",
        "deterministic": True}


def test_wave_matches_strict_quality():
    X, y = _data()
    strict = lgb.train(BASE, lgb.Dataset(X, label=y), 15)
    wave = lgb.train(dict(BASE, tpu_leaf_batch=8),
                     lgb.Dataset(X, label=y), 15)
    a_strict = _auc(strict, X, y)
    a_wave = _auc(wave, X, y)
    assert abs(a_strict - a_wave) < 0.01, (a_strict, a_wave)
    # same number of trees; every tree uses the full leaf budget when
    # splits are available
    assert wave.num_trees() == strict.num_trees()
    nl_wave = [t["num_leaves"] for t in wave.dump_model()["tree_info"]]
    nl_strict = [t["num_leaves"] for t in strict.dump_model()["tree_info"]]
    assert nl_wave == nl_strict


def test_wave_respects_budget_and_quality_small_tree():
    """Wave growth may interleave differently from strict best-first (a wave
    splits the whole current frontier; strict lets children of split i
    compete for split i+1), but the leaf budget is never exceeded and
    quality stays equivalent."""
    X, y = _data(n=3000, f=5, seed=3)
    p = dict(BASE, num_leaves=4)
    strict = lgb.train(p, lgb.Dataset(X, label=y), 5)
    wave = lgb.train(dict(p, tpu_leaf_batch=8), lgb.Dataset(X, label=y), 5)
    for t in wave.dump_model()["tree_info"]:
        assert t["num_leaves"] <= 4
    a_s, a_w = _auc(strict, X, y), _auc(wave, X, y)
    assert abs(a_s - a_w) < 0.01, (a_s, a_w)


def test_wave_with_bagging_goss_quantized():
    X, y = _data(n=5000)
    for extra in ({"bagging_fraction": 0.7, "bagging_freq": 1},
                  {"data_sample_strategy": "goss"},
                  {"use_quantized_grad": True}):
        p = dict(BASE, tpu_leaf_batch=4, **extra)
        bst = lgb.train(p, lgb.Dataset(X, label=y), 8)
        assert _auc(bst, X, y) > 0.8, extra


def test_wave_categorical_and_nan():
    rng = np.random.RandomState(1)
    n = 4000
    cat = rng.randint(0, 12, n).astype(np.float64)
    x1 = rng.randn(n)
    x1[rng.rand(n) < 0.2] = np.nan
    lift = np.where(cat % 3 == 0, 1.5, -1.0)
    y = (lift + np.nan_to_num(x1) + 0.3 * rng.randn(n) > 0).astype(float)
    X = np.column_stack([cat, x1, rng.randn(n)])
    p = dict(BASE, tpu_leaf_batch=4, num_leaves=15, max_cat_to_onehot=1,
             min_data_per_group=5, cat_smooth=2.0)
    bst = lgb.train(p, lgb.Dataset(X, label=y, categorical_feature=[0]), 10)
    assert _auc(bst, X, y) > 0.85
    # round trip
    s = bst.model_to_string()
    re = lgb.Booster(model_str=s)
    np.testing.assert_allclose(re.predict(X), bst.predict(X),
                               rtol=1e-5, atol=1e-6)


def test_wave_row_leaf_consistency():
    """row_leaf from the wave grower must agree with tree traversal."""
    X, y = _data(n=4000, f=6, seed=9)
    p = dict(BASE, tpu_leaf_batch=8, num_leaves=15, learning_rate=0.3)
    bst = lgb.train(p, lgb.Dataset(X, label=y), 3)
    # predictions on training data equal the incremental scores
    import jax
    sc = np.asarray(jax.device_get(bst._gbdt.scores))
    pred = bst.predict(X, raw_score=True)
    np.testing.assert_allclose(sc, pred, rtol=2e-3, atol=2e-3)


@pytest.mark.parametrize("quantized", [False, True])
def test_bench_config_auc_parity(quantized):
    """Pin bench-config quality against GENUINE LightGBM (VERDICT r3 weak #2:
    the 0.01 wave-vs-strict gate was the only guard; this pins the wave
    scheduler + quantized paths at the bench config against the reference
    binary's own holdout AUC, committed in tests/fixtures/bench_auc.json by
    tools/gen_bench_auc_fixture.py — reference parity bar:
    docs/GPU-Performance.rst:133-160 device AUC table)."""
    import json
    import os
    import sys

    fix_path = os.path.join(os.path.dirname(__file__), "fixtures",
                            "bench_auc.json")
    with open(fix_path) as fh:
        fix = json.load(fh)
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from bench import make_higgs_like

    d = fix["data"]
    X, y = make_higgs_like(d["n_train"] + d["n_valid"], d["n_features"],
                           seed=d["seed"])
    nt = d["n_train"]
    params = dict(fix["params"])
    iters = params.pop("num_iterations")
    params["tpu_leaf_batch"] = 16
    if quantized:
        params["use_quantized_grad"] = True
    bst = lgb.train(params, lgb.Dataset(X[:nt], label=y[:nt]), iters)
    from lightgbm_tpu.metrics import _auc as auc
    ours = auc(y[nt:], bst.predict(X[nt:], raw_score=True), None, None)
    # fp32 compares to the reference's fp32 AUC, quantized to the
    # reference's own quantized-training AUC — both at the fixture's full
    # 100-iteration depth so hist-precision/leaf-renewal divergence has
    # room to compound (VERDICT r4 weak #6).  Quantized keeps a wider bar:
    # stochastic int8 rounding differs by construction.
    ref = fix["ref_auc_quantized"] if quantized else fix["ref_auc"]
    tol = 3e-3 if quantized else 1e-3
    assert abs(ours - ref) < tol, (ours, ref)
