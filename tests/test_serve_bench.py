"""Smoke test for tools/serve_bench.py: the BENCH_serve blob must be
emittable hermetically (JAX_PLATFORMS=cpu) carrying every field the
``bench_compare.py`` serve gate watches (ISSUE-12: warm QPS, p50/p99,
compile count, plan bytes + shrink ratio, post-restart compile count,
platform honesty)."""

import json
import os
import subprocess
import sys

import pytest

pytestmark = pytest.mark.serve


def test_serve_bench_smoke():
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(
        os.environ,
        LIGHTGBM_TPU_PLATFORM="cpu",
        JAX_PLATFORMS="cpu",
        SERVE_BENCH_ROWS="1500",
        SERVE_BENCH_ITERS="3",
        SERVE_BENCH_CALLS="12",
        SERVE_BENCH_MAX_BATCH="128",
        PYTHONPATH=os.pathsep.join(
            [root] + os.environ.get("PYTHONPATH", "").split(os.pathsep)),
    )
    r = subprocess.run(
        [sys.executable, os.path.join(root, "tools", "serve_bench.py")],
        capture_output=True, text=True, timeout=600, env=env)
    assert r.returncode == 0, r.stdout + r.stderr
    blob = None
    for line in r.stdout.splitlines():
        line = line.strip()
        if line.startswith("{"):
            obj = json.loads(line)
            if obj.get("metric") == "BENCH_serve":
                blob = obj
    assert blob is not None, r.stdout
    assert blob["warm_qps"] > 0
    assert blob["p50_ms"] is not None and blob["p50_ms"] >= 0
    assert blob["p99_ms"] >= blob["p50_ms"]
    # ladder: 128-row cap with base 32 / ratio 2 -> at most 3 rungs
    assert blob["compiles"] <= 3
    assert blob["detail"]["served_rows"] > 0
    # the serve-gate fields (tools/bench_compare.py WATCHED serve_*)
    assert blob["quantize"] == "int8"           # SERVE_BENCH_QUANTIZE default
    assert 0 < blob["plan_bytes"] < blob["plan_bytes_fp32"]
    # the tree pack itself shrinks >= 3x even at this tiny 3-tree
    # geometry; the whole-plan ratio needs the bench-default ensemble
    # (tables are exactness-bound f64 keys, same bytes every mode)
    assert blob["detail"]["pack_shrink"] >= 3.0
    assert blob["detail"]["plan_shrink"] > 1.0
    # zero cold-start: the simulated restart paid no XLA compiles
    assert blob["restart_compiles"] == 0
    assert blob["restart_aot_hits"] >= 1
    assert blob["detail"]["restart"]["cold_compiles"] >= 1
    # platform honesty rides the blob (probe machinery input)
    assert blob["detail"]["platform"] == "cpu"
    assert blob["detail"]["cpu_fallback"] is True
    assert blob["detail"]["quantize_error_bound"] > 0


def test_bench_compare_gates_serve_blobs(tmp_path):
    """The serve gate end-to-end: a QPS collapse or a restart-compile
    appearance FAILS pair mode; an identical pair passes."""
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from tools.bench_compare import main as bc_main

    good = {"metric": "BENCH_serve", "warm_qps": 100.0, "p50_ms": 1.0,
            "p99_ms": 5.0, "compiles": 3, "plan_bytes": 50000,
            "restart_compiles": 0,
            "detail": {"platform": "cpu", "cpu_fallback": True}}
    bad = dict(good, warm_qps=40.0, restart_compiles=3)
    pa, pb, pc = (str(tmp_path / f"{n}.json") for n in "abc")
    for path, blob in ((pa, good), (pb, bad), (pc, dict(good))):
        with open(path, "w") as fh:
            json.dump(blob, fh)
    assert bc_main([pa, pb]) == 1            # regressed: qps + restart
    assert bc_main([pa, pc]) == 0            # identical: ok
    # probe honesty: serve blobs refuse CPU-vs-accelerator comparisons
    tpu = dict(good, detail={"platform": "tpu", "cpu_fallback": False})
    pt = str(tmp_path / "t.json")
    with open(pt, "w") as fh:
        json.dump(tpu, fh)
    assert bc_main([pa, pt]) == 3
