"""Smoke test for tools/serve_bench.py: the BENCH_serve blob must be
emittable hermetically (JAX_PLATFORMS=cpu) with sane fields."""

import json
import os
import subprocess
import sys


def test_serve_bench_smoke():
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(
        os.environ,
        LIGHTGBM_TPU_PLATFORM="cpu",
        JAX_PLATFORMS="cpu",
        SERVE_BENCH_ROWS="1500",
        SERVE_BENCH_ITERS="3",
        SERVE_BENCH_CALLS="12",
        SERVE_BENCH_MAX_BATCH="128",
        PYTHONPATH=os.pathsep.join(
            [root] + os.environ.get("PYTHONPATH", "").split(os.pathsep)),
    )
    r = subprocess.run(
        [sys.executable, os.path.join(root, "tools", "serve_bench.py")],
        capture_output=True, text=True, timeout=600, env=env)
    assert r.returncode == 0, r.stdout + r.stderr
    blob = None
    for line in r.stdout.splitlines():
        line = line.strip()
        if line.startswith("{"):
            obj = json.loads(line)
            if obj.get("metric") == "BENCH_serve":
                blob = obj
    assert blob is not None, r.stdout
    assert blob["warm_qps"] > 0
    assert blob["p50_ms"] is not None and blob["p50_ms"] >= 0
    assert blob["p99_ms"] >= blob["p50_ms"]
    # ladder: 128-row cap with base 32 / ratio 2 -> at most 3 rungs
    assert blob["compiles"] <= 3
    assert blob["detail"]["served_rows"] > 0
