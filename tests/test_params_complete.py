"""Every formerly-dead parameter now has behavior (or an explicit rejection).

VERDICT round-2 ask #7: reg_sqrt, monotone_penalty + method rejection,
pred_early_stop*, interaction_constraints per-branch semantics, dataset
binary save/load (save_binary), inert-layout-param warnings.
"""

import io
import os

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.config import Config


def _reg_data(n=2000, f=6, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, f)
    y = X[:, 0] * 2 + np.abs(X[:, 1]) + 0.1 * rng.randn(n)
    return X, y


P = {"objective": "regression", "num_leaves": 15, "min_data_in_leaf": 5,
     "verbosity": -1, "deterministic": True}


def test_reg_sqrt():
    rng = np.random.RandomState(1)
    X = rng.randn(1500, 4)
    y = (X[:, 0] + 0.05 * rng.randn(1500)) ** 2 * 10  # heavy right tail
    plain = lgb.train(P, lgb.Dataset(X, label=y), 30)
    sq = lgb.train(dict(P, reg_sqrt=True), lgb.Dataset(X, label=y), 30)
    p_plain, p_sq = plain.predict(X), sq.predict(X)
    assert not np.allclose(p_plain, p_sq)
    # sqrt transform fits the transformed scale; predictions square back to
    # the label scale and remain non-negative-ish for this target
    assert np.mean((p_sq - y) ** 2) < np.var(y)
    # raw scores live on the sqrt scale: predictions = sign(s)*s^2
    raw = sq.predict(X, raw_score=True)
    np.testing.assert_allclose(np.sign(raw) * raw * raw, p_sq, rtol=1e-6)
    # the back-transform survives save/load ("objective=regression sqrt")
    re = lgb.Booster(model_str=sq.model_to_string())
    np.testing.assert_allclose(re.predict(X), p_sq, rtol=1e-5, atol=1e-6)


def test_monotone_penalty_changes_trees():
    X, y = _reg_data()
    mono = [1, 0, 0, 0, 0, 0]
    base = lgb.train(dict(P, monotone_constraints=mono),
                     lgb.Dataset(X, label=y), 10)
    pen = lgb.train(dict(P, monotone_constraints=mono, monotone_penalty=2.0),
                    lgb.Dataset(X, label=y), 10)

    def root_feats(bst):
        return [r["split_feature"] for r in bst.trees_to_dataframe()
                if r["node_depth"] == 0 and r["split_feature"] is not None]
    # monotone_penalty=2 multiplies depth-0/1 monotone gains by ~0
    # (reference: penalization >= depth+1 -> kEpsilon), so the constrained
    # feature cannot win the root split anymore
    assert "Column_0" in root_feats(base)
    assert "Column_0" not in root_feats(pen)
    assert not np.allclose(base.predict(X), pen.predict(X))


def test_monotone_bounds_enforced():
    """Basic-mode bounds: model predictions must be monotone in the
    constrained feature (reference BasicLeafConstraints midpoint caps)."""
    rng = np.random.RandomState(3)
    n = 4000
    x0 = rng.uniform(-2, 2, n)
    y = 1.5 * x0 + np.sin(x0 * 4) + 0.2 * rng.randn(n)  # locally non-monotone
    X = np.column_stack([x0, rng.randn(n)])
    bst = lgb.train(dict(P, monotone_constraints=[1, 0], num_leaves=31),
                    lgb.Dataset(X, label=y), 30)
    grid = np.linspace(-2, 2, 200)
    pred = bst.predict(np.column_stack([grid, np.zeros(200)]))
    assert np.all(np.diff(pred) >= -1e-6), "violation of monotone increase"


def test_monotone_method_unknown_rejected():
    X, y = _reg_data(n=300)
    with pytest.raises(ValueError, match="monotone_constraints_method"):
        lgb.train(dict(P, monotone_constraints=[1, 0, 0, 0, 0, 0],
                       monotone_constraints_method="bogus"),
                  lgb.Dataset(X, label=y), 2)


def test_pred_early_stop_binary():
    rng = np.random.RandomState(5)
    X = rng.randn(2000, 5)
    y = (X[:, 0] * 3 > 0).astype(float)  # strong signal, huge margins
    p = dict(P, objective="binary")
    bst = lgb.train(p, lgb.Dataset(X, label=y), 40)
    full = bst.predict(X, raw_score=True)
    es = bst.predict(X, raw_score=True, pred_early_stop=True,
                     pred_early_stop_freq=5, pred_early_stop_margin=2.0)
    # early-stopped scores stop accumulating once |score| > margin: same
    # sign everywhere, smaller magnitude where stopped, identical where not
    assert np.all(np.sign(es) == np.sign(full))
    assert np.any(np.abs(es) < np.abs(full) - 1e-9)
    assert np.all(np.abs(es) <= np.abs(full) + 1e-9)
    # a loose margin never triggers -> exact equality
    noop = bst.predict(X, raw_score=True, pred_early_stop=True,
                       pred_early_stop_margin=1e9)
    np.testing.assert_allclose(noop, full, rtol=1e-6, atol=1e-7)


def test_interaction_constraints_per_branch():
    """Trees may not mix features from different groups on one path
    (reference ColSampler::GetByNode)."""
    rng = np.random.RandomState(7)
    n = 4000
    X = rng.randn(n, 4)
    # joint signal across the group boundary: unconstrained trees would mix
    y = (X[:, 0] * X[:, 2] + 0.5 * X[:, 1] + 0.5 * X[:, 3]
         + 0.1 * rng.randn(n))
    p = dict(P, num_leaves=15,
             interaction_constraints=[[0, 1], [2, 3]])
    bst = lgb.train(p, lgb.Dataset(X, label=y), 10)
    groups = [{0, 1}, {2, 3}]

    def walk_paths(node, path):
        if "leaf_index" in node:
            return [path]
        f = node["split_feature"]
        return (walk_paths(node["left_child"], path | {f})
                + walk_paths(node["right_child"], path | {f}))

    mixed = 0
    for t in bst.dump_model()["tree_info"]:
        for path in walk_paths(t["tree_structure"], set()):
            ok = any(path <= g for g in groups)
            mixed += 0 if ok else 1
    assert mixed == 0, f"{mixed} branch(es) mix interaction groups"
    # unconstrained comparison: mixing must actually happen on this data
    un = lgb.train(dict(P, num_leaves=15), lgb.Dataset(X, label=y), 10)
    un_mixed = 0
    for t in un.dump_model()["tree_info"]:
        for path in walk_paths(t["tree_structure"], set()):
            if not any(path <= g for g in groups):
                un_mixed += 1
    assert un_mixed > 0


def test_binary_dataset_round_trip(tmp_path):
    X, y = _reg_data(n=1500)
    w = np.random.RandomState(0).rand(1500)
    ds = lgb.Dataset(X, label=y, weight=w)
    bst1 = lgb.train(P, ds, 10)
    path = str(tmp_path / "train.bin")
    ds.save_binary(path)
    ds2 = lgb.Dataset(path)
    bst2 = lgb.train(P, ds2, 10)
    np.testing.assert_allclose(bst1.predict(X), bst2.predict(X),
                               rtol=1e-5, atol=1e-6)


def test_cli_save_binary_and_train_from_bin(tmp_path):
    X, y = _reg_data(n=400, f=3)
    data_path = str(tmp_path / "t.csv")
    np.savetxt(data_path, np.column_stack([y, X]), delimiter=",", fmt="%.8g")
    from lightgbm_tpu.cli import run
    rc = run(["task=save_binary", f"data={data_path}", "verbosity=-1"])
    assert rc == 0 and os.path.exists(data_path + ".bin")
    out = str(tmp_path / "m.txt")
    rc = run(["task=train", f"data={data_path}.bin", "num_iterations=5",
              "objective=regression", f"output_model={out}", "verbosity=-1"])
    assert rc == 0 and os.path.exists(out)


def test_inert_layout_params_warn(capsys):
    X, y = _reg_data(n=300)
    lgb.train(dict(P, is_enable_sparse=False), lgb.Dataset(X, label=y), 1)
    err = capsys.readouterr()
    text = err.out + err.err
    assert "is_enable_sparse" in text


def test_max_bin_by_feature_caps_per_feature():
    rng = np.random.RandomState(0)
    X = rng.randn(2000, 3)
    y = (X[:, 0] > 0).astype(float)
    ds = lgb.Dataset(X, label=y)
    td = ds.construct({"objective": "binary", "max_bin": 100,
                       "max_bin_by_feature": [5, 100, 100],
                       "verbosity": -1})
    assert td.binned.num_bins_per_feature[0] <= 6   # 5 value bins (+nan)
    assert td.binned.num_bins_per_feature[1] > 20


def test_feature_contri_scales_gains():
    rng = np.random.RandomState(1)
    X = rng.randn(3000, 3)
    # feature 0 and 1 both informative; crushing 0's contribution must
    # steer the root split to feature 1
    y = (X[:, 0] + 0.95 * X[:, 1] > 0).astype(float)
    base = {"objective": "binary", "num_leaves": 7, "verbosity": -1}
    b0 = lgb.train(base, lgb.Dataset(X, label=y), 1)
    assert b0._gbdt.models[0][0].split_feature[0] == 0
    b1 = lgb.train(dict(base, feature_contri=[0.01, 1.0, 1.0]),
                   lgb.Dataset(X, label=y), 1)
    assert b1._gbdt.models[0][0].split_feature[0] == 1


def test_early_stopping_min_delta():
    rng = np.random.RandomState(2)
    X = rng.randn(1500, 5)
    y = (X[:, 0] > 0).astype(float)
    ds = lgb.Dataset(X[:1000], label=y[:1000])
    vs = lgb.Dataset(X[1000:], label=y[1000:], reference=ds)
    params = {"objective": "binary", "num_leaves": 7, "metric": "auc",
              "verbosity": -1, "early_stopping_round": 3}
    full = lgb.train(params, ds, 60, valid_sets=[vs])
    strict = lgb.train(dict(params, early_stopping_min_delta=0.05), ds, 60,
                       valid_sets=[vs])
    # demanding 0.05 AUC improvement per round stops much earlier
    assert strict.best_iteration <= full.best_iteration
    assert strict.num_trees() < 60


def test_xgboost_dart_mode_changes_scaling():
    rng = np.random.RandomState(3)
    X = rng.randn(1200, 4)
    y = (X[:, 0] > 0).astype(float)
    base = {"objective": "binary", "boosting": "dart", "num_leaves": 7,
            "verbosity": -1, "drop_rate": 0.5, "skip_drop": 0.0,
            "drop_seed": 7}
    b_norm = lgb.train(base, lgb.Dataset(X, label=y), 8)
    b_xgb = lgb.train(dict(base, xgboost_dart_mode=True),
                      lgb.Dataset(X, label=y), 8)
    p_norm = b_norm.predict(X[:50], raw_score=True)
    p_xgb = b_xgb.predict(X[:50], raw_score=True)
    assert not np.allclose(p_norm, p_xgb)


def test_predict_shape_check_and_start_iteration_predict():
    rng = np.random.RandomState(4)
    X = rng.randn(800, 4)
    y = (X[:, 0] > 0).astype(float)
    bst = lgb.train({"objective": "binary", "num_leaves": 7,
                     "verbosity": -1}, lgb.Dataset(X, label=y), 6)
    with pytest.raises(ValueError, match="features"):
        bst.predict(X[:5, :2])
    p = bst.predict(X[:5, :2], predict_disable_shape_check=True)
    assert p.shape == (5,)
    # start_iteration_predict kwarg == start_iteration argument
    a = bst.predict(X[:20], raw_score=True, start_iteration=3)
    b = bst.predict(X[:20], raw_score=True, start_iteration_predict=3)
    np.testing.assert_allclose(a, b)


def test_two_round_loading_matches_direct(tmp_path):
    """two_round=true streams the text file in chunks (pass 1: sample +
    labels; pass 2: bin chunk-by-chunk) and must produce the same model as
    the direct in-memory load (reference dataset_loader.cpp:203,1022)."""
    import subprocess
    import sys

    rng = np.random.RandomState(6)
    n, f = 9000, 8
    X = rng.randn(n, f)
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.float64)
    data = str(tmp_path / "tr.csv")
    np.savetxt(data, np.column_stack([y, X]), delimiter=",", fmt="%.7g")

    # loader-level equality: bins identical to the one-shot path
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.dataset import TrainData, load_train_data_two_round
    from lightgbm_tpu.io.parser import load_data_file

    cfg = Config({"objective": "binary", "verbosity": -1, "max_bin": 63})
    td2 = load_train_data_two_round(data, cfg, block_lines=1000)
    Xd, yd, _w, _g = load_data_file(data)
    td1 = TrainData.build(Xd, yd, cfg)
    np.testing.assert_array_equal(td1.binned.bins, td2.binned.bins)
    np.testing.assert_allclose(td1.label, td2.label)

    # CLI end-to-end with two_round=true
    model = str(tmp_path / "m2r.txt")
    r = subprocess.run(
        [sys.executable, "-u", "-m", "lightgbm_tpu", "task=train",
         f"data={data}", "objective=binary", "num_leaves=15",
         "num_iterations=5", "two_round=true", "verbosity=-1",
         "max_bin=63", f"output_model={model}"],
        capture_output=True, text=True, timeout=600,
        env={**os.environ, "LIGHTGBM_TPU_PLATFORM": "cpu",
             "PYTHONPATH": os.pathsep.join(
                 [os.path.dirname(os.path.dirname(os.path.abspath(
                     __file__)))] + os.environ.get(
                     "PYTHONPATH", "").split(os.pathsep))})
    assert r.returncode == 0, r.stdout + r.stderr
    loaded = lgb.Booster(model_file=model)
    direct = lgb.train({"objective": "binary", "num_leaves": 15,
                        "verbosity": -1, "max_bin": 63},
                       lgb.Dataset(Xd, label=yd), 5)
    np.testing.assert_allclose(loaded.predict(Xd), direct.predict(Xd),
                               rtol=1e-5, atol=1e-6)
