"""Training continuation, snapshots, refit, Booster.eval, convert_model.

Reference behaviors: ``boosting.cpp:34-59`` (input_model), ``gbdt.cpp:250-254``
(snapshot_freq), ``gbdt.cpp:258`` (RefitTree), ``gbdt_model_text.cpp:286``
(SaveModelToIfElse), Python ``engine.train(init_model=...)``.
"""

import os
import shutil
import subprocess
import sys

import numpy as np
import pytest

import lightgbm_tpu as lgb


def _make(n=600, f=8, seed=3, binary=False):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, f)
    y = X[:, 0] * 2 - X[:, 1] + 0.3 * rng.randn(n)
    if binary:
        y = (y > 0).astype(np.float64)
    return X, y


PARAMS = {"objective": "regression", "num_leaves": 15, "learning_rate": 0.1,
          "min_data_in_leaf": 5, "verbosity": -1, "metric": "l2",
          "deterministic": True, "seed": 7}


def test_continue_matches_single_run():
    """train 50 + resume 50 == train 100 (same seeds, no sampling)."""
    X, y = _make()
    full = lgb.train(PARAMS, lgb.Dataset(X, label=y), num_boost_round=60)
    first = lgb.train(PARAMS, lgb.Dataset(X, label=y), num_boost_round=30)
    resumed = lgb.train(PARAMS, lgb.Dataset(X, label=y), num_boost_round=30,
                        init_model=first)
    assert resumed.num_trees() == full.num_trees() == 60
    assert resumed.current_iteration == 60
    p_full = full.predict(X)
    p_res = resumed.predict(X)
    # The resumed run replays base predictions through the f64 host path, so
    # scores differ at f32 rounding level; trees may tie-break differently on
    # a handful of splits.  Metric-level parity is the reference's own bar
    # (test_engine.py continuation tests assert eval improvement/closeness).
    mse_full = float(np.mean((p_full - y) ** 2))
    mse_res = float(np.mean((p_res - y) ** 2))
    assert abs(mse_full - mse_res) < 0.02 * max(mse_full, 1e-6)
    np.testing.assert_allclose(p_full, p_res, atol=0.05 * np.std(y))


def test_continue_from_file_and_string(tmp_path):
    X, y = _make()
    first = lgb.train(PARAMS, lgb.Dataset(X, label=y), num_boost_round=20)
    path = str(tmp_path / "m.txt")
    first.save_model(path)
    resumed = lgb.train(PARAMS, lgb.Dataset(X, label=y), num_boost_round=10,
                        init_model=path)
    assert resumed.num_trees() == 30
    # combined model round-trips through save/load with base trees included
    p = resumed.predict(X)
    s = resumed.model_to_string()
    assert s.count("Tree=") == 30
    reloaded = lgb.Booster(model_str=s)
    np.testing.assert_allclose(reloaded.predict(X), p, rtol=1e-5, atol=1e-5)


def test_continuation_prediction_slicing():
    X, y = _make()
    first = lgb.train(PARAMS, lgb.Dataset(X, label=y), num_boost_round=15)
    resumed = lgb.train(PARAMS, lgb.Dataset(X, label=y), num_boost_round=10,
                        init_model=first)
    p_base_only = resumed.predict(X, num_iteration=15)
    np.testing.assert_allclose(p_base_only, first.predict(X),
                               rtol=1e-5, atol=1e-5)
    p_all = resumed.predict(X)
    p_tail = resumed.predict(X, start_iteration=15, num_iteration=10)
    p_init = resumed.predict(X, num_iteration=0)  # init scores only
    base_init_and_trees = first.predict(X)
    np.testing.assert_allclose((p_tail - p_init) + base_init_and_trees, p_all,
                               rtol=1e-4, atol=1e-5)


def test_snapshot_freq(tmp_path):
    X, y = _make(n=300)
    out = str(tmp_path / "model.txt")
    params = dict(PARAMS, snapshot_freq=4, output_model=out)
    lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=10)
    snaps = sorted(os.listdir(tmp_path))
    assert f"model.txt.snapshot_iter_4" in snaps
    assert f"model.txt.snapshot_iter_8" in snaps
    snap = lgb.Booster(model_file=out + ".snapshot_iter_4")
    assert snap.num_trees() == 4


def test_booster_eval():
    X, y = _make(binary=True)
    params = dict(PARAMS, objective="binary", metric=["auc", "binary_logloss"])
    bst = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=20)
    Xv, yv = _make(seed=11, binary=True)
    res = bst.eval(lgb.Dataset(Xv, label=yv), "holdout")
    names = {r[1] for r in res}
    assert "auc" in names and "binary_logloss" in names
    auc = [r[2] for r in res if r[1] == "auc"][0]
    assert 0.6 < auc <= 1.0
    assert all(r[0] == "holdout" for r in res)


def test_refit_trained_booster():
    X, y = _make()
    bst = lgb.train(PARAMS, lgb.Dataset(X, label=y), num_boost_round=15)
    X2, y2 = _make(seed=21)
    ref = bst.refit(X2, y2, decay_rate=0.0)
    assert ref.num_trees() == bst.num_trees()
    # structure identical, leaf values refit towards the new data
    p_old = bst.predict(X2)
    p_new = ref.predict(X2)
    assert np.mean((p_new - y2) ** 2) < np.mean((p_old - y2) ** 2) + 1e-9
    assert not np.allclose(p_old, p_new)
    # decay_rate=1 keeps the model unchanged
    same = bst.refit(X2, y2, decay_rate=1.0)
    np.testing.assert_allclose(same.predict(X2), p_old, rtol=1e-5, atol=1e-6)
    # original booster untouched
    np.testing.assert_allclose(bst.predict(X2), p_old)


def test_refit_continuation_booster():
    """Refit walks the COMBINED ensemble (base trees first), mirroring
    RefitTree over all loaded models (gbdt.cpp:258)."""
    X, y = _make()
    first = lgb.train(PARAMS, lgb.Dataset(X, label=y), num_boost_round=10)
    resumed = lgb.train(PARAMS, lgb.Dataset(X, label=y), num_boost_round=10,
                        init_model=first)
    X2, y2 = _make(seed=41)
    ref = resumed.refit(X2, y2, decay_rate=0.0)
    assert ref.num_trees() == resumed.num_trees() == 20
    p_old = resumed.predict(X2)
    p_new = ref.predict(X2)
    assert np.mean((p_new - y2) ** 2) < np.mean((p_old - y2) ** 2) + 1e-9
    assert not np.allclose(p_old, p_new)
    # base-model trees were refit too, not just the continuation's own
    base_old = resumed._gbdt.base_model.trees[0].leaf_value
    base_new = ref._gbdt.base_model.trees[0].leaf_value
    assert not np.allclose(np.asarray(base_old), np.asarray(base_new))
    # decay_rate=1 keeps the combined model unchanged
    same = resumed.refit(X2, y2, decay_rate=1.0)
    np.testing.assert_allclose(same.predict(X2), p_old, rtol=1e-5, atol=1e-6)


def test_refit_linear_tree_booster(tmp_path):
    """Linear-tree refit re-solves each leaf's model on the new data with
    the leaf's EXISTING feature set, decay-blended (reference
    ``LinearTreeLearner::CalculateLinear`` with ``is_refit=true``,
    ``linear_tree_learner.cpp:326-383``)."""
    X, y = _make()
    params = dict(PARAMS, linear_tree=True, linear_lambda=0.01)
    bst = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=8)
    X2, y2 = _make(seed=51)
    ref = bst.refit(X2, y2, decay_rate=0.0)
    p_old = bst.predict(X2)
    p_new = ref.predict(X2)
    assert np.mean((p_new - y2) ** 2) < np.mean((p_old - y2) ** 2) + 1e-9
    assert not np.allclose(p_old, p_new)
    # coefficients actually moved, structure did not
    t0_old, t0_new = bst._gbdt.models[0][0], ref._gbdt.models[0][0]
    moved = any(len(a) and not np.allclose(a, b)
                for a, b in zip(t0_old.leaf_coeff, t0_new.leaf_coeff))
    assert moved
    np.testing.assert_array_equal(t0_old.split_feature, t0_new.split_feature)
    # decay_rate=1 keeps the model unchanged
    same = bst.refit(X2, y2, decay_rate=1.0)
    np.testing.assert_allclose(same.predict(X2), p_old, rtol=1e-5, atol=1e-6)
    # refit survives save/load round-trip
    ref.save_model(str(tmp_path / "lin.txt"))
    loaded = lgb.Booster(model_file=str(tmp_path / "lin.txt"))
    np.testing.assert_allclose(loaded.predict(X2), p_new, rtol=1e-5,
                               atol=1e-6)
    # and a LOADED linear model can itself be refit
    ref2 = loaded.refit(X2, y2, decay_rate=0.5)
    assert not np.allclose(ref2.predict(X2), p_new)


def test_refit_loaded_booster(tmp_path):
    X, y = _make()
    bst = lgb.train(PARAMS, lgb.Dataset(X, label=y), num_boost_round=10)
    path = str(tmp_path / "m.txt")
    bst.save_model(path)
    X2, y2 = _make(seed=31)
    loaded = lgb.Booster(model_file=path)
    ref = loaded.refit(X2, y2, decay_rate=0.2)
    p_old = loaded.predict(X2)
    p_new = ref.predict(X2)
    assert np.mean((p_new - y2) ** 2) < np.mean((p_old - y2) ** 2) + 1e-9
    # refit keeps structure: saving emits the same split set
    s_old = loaded.model_to_string()
    s_new = ref.model_to_string()
    pick = lambda s: [ln for ln in s.splitlines()
                      if ln.startswith("split_feature=")]
    assert pick(s_old) == pick(s_new)


def test_cli_refit_and_convert_model(tmp_path):
    X, y = _make(n=200, f=4)
    data = np.column_stack([y, X])
    data_path = str(tmp_path / "train.csv")
    np.savetxt(data_path, data, delimiter=",", fmt="%.8g")
    model_path = str(tmp_path / "model.txt")
    bst = lgb.train(dict(PARAMS, min_data_in_leaf=3, num_leaves=7),
                    lgb.Dataset(X, label=y), num_boost_round=5)
    bst.save_model(model_path)

    from lightgbm_tpu.cli import run
    out_path = str(tmp_path / "refitted.txt")
    rc = run([f"task=refit", f"data={data_path}", f"input_model={model_path}",
              f"output_model={out_path}", "verbosity=-1"])
    assert rc == 0 and os.path.exists(out_path)

    cpp_path = str(tmp_path / "model.cpp")
    rc = run(["task=convert_model", f"input_model={model_path}",
              f"convert_model={cpp_path}"])
    assert rc == 0
    src = open(cpp_path).read()
    assert "PredictTree0" in src and "PredictRaw" in src


@pytest.mark.skipif(shutil.which("g++") is None, reason="no C++ toolchain")
def test_convert_model_compiles_and_matches(tmp_path):
    """The generated C++ compiles and reproduces raw predictions."""
    X, y = _make(n=300, f=5)
    bst = lgb.train(dict(PARAMS, num_leaves=7), lgb.Dataset(X, label=y),
                    num_boost_round=8)
    model_path = str(tmp_path / "m.txt")
    bst.save_model(model_path)
    from lightgbm_tpu.convert_model import convert_model_file
    cpp = str(tmp_path / "m.cpp")
    convert_model_file(model_path, cpp)
    main_cpp = str(tmp_path / "main.cpp")
    with open(main_cpp, "w") as fh:
        fh.write("""
#include <cstdio>
#include \"m.cpp\"
int main() {
  double arr[5]; double out[1];
  while (scanf(\"%lf %lf %lf %lf %lf\", arr, arr+1, arr+2, arr+3, arr+4) == 5) {
    PredictRaw(arr, out);
    printf(\"%.10f\\n\", out[0]);
  }
  return 0;
}
""")
    exe = str(tmp_path / "pred")
    subprocess.run(["g++", "-O1", "-o", exe, main_cpp], check=True,
                   cwd=tmp_path)
    rows = X[:20]
    inp = "\n".join(" ".join(f"{v:.10g}" for v in r) for r in rows)
    res = subprocess.run([exe], input=inp, capture_output=True, text=True,
                         check=True)
    got = np.array([float(v) for v in res.stdout.split()])
    want = bst.predict(rows, raw_score=True)
    np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-6)


def test_convert_model_cpp_compiles_and_matches(tmp_path):
    """Compile the generated if-else C++ (reference Tree::ToIfElse) and
    check its raw scores against Booster.predict."""
    import shutil
    import subprocess

    if shutil.which("g++") is None:
        pytest.skip("no C++ compiler")
    rng = np.random.RandomState(3)
    X = rng.randn(500, 5)
    X[rng.rand(500) < 0.1, 1] = np.nan
    y = (X[:, 0] + np.nan_to_num(X[:, 1]) > 0).astype(np.float64)
    bst = lgb.train({"objective": "binary", "num_leaves": 7,
                     "verbosity": -1}, lgb.Dataset(X, label=y), 5)
    from lightgbm_tpu.convert_model import convert_model_string
    from lightgbm_tpu.serialization import load_model_string
    src = convert_model_string(load_model_string(bst.model_to_string()))
    main = r"""
#include <cstdio>
int main() {
  double row[5];
  double out[1];
  while (scanf("%lf %lf %lf %lf %lf", row, row+1, row+2, row+3, row+4) == 5) {
    PredictRaw(row, out);
    printf("%.10f\n", out[0]);
  }
  return 0;
}
"""
    cpp = tmp_path / "model.cpp"
    cpp.write_text(src + main)
    exe = tmp_path / "model"
    subprocess.run(["g++", "-O1", str(cpp), "-o", str(exe)], check=True,
                   capture_output=True)
    feed = "\n".join(" ".join("nan" if np.isnan(v) else f"{v:.17g}"
                              for v in row) for row in X[:100])
    res = subprocess.run([str(exe)], input=feed, capture_output=True,
                         text=True, check=True, timeout=120)
    got = np.array([float(t) for t in res.stdout.split()])
    want = bst.predict(X[:100], raw_score=True)
    np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-6)
