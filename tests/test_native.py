"""Native C++ runtime vs pure-Python parity.

The native library (lightgbm_tpu/native/csrc/native.cpp) re-implements the
reference's host-side C++ components (parser.cpp, bin.cpp, tree.cpp traversal);
these tests pin it to the Python implementations bit-for-bit.
"""

import os

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu import native
from lightgbm_tpu.binning import _greedy_find_boundaries, bin_dataset, find_bin

pytestmark = pytest.mark.skipif(not native.available(),
                                reason="native library unavailable")


def test_parse_csv(tmp_path):
    p = tmp_path / "d.csv"
    p.write_text("1,2.5,3\n0,na,4.5\n1,7,8\n")
    X, y = native.parse_file(str(p))
    np.testing.assert_array_equal(y, [1, 0, 1])
    assert np.isnan(X[1, 0]) and X[2, 1] == 8.0


def test_parse_csv_header_name_label(tmp_path):
    p = tmp_path / "d.csv"
    p.write_text("a,target,b\n1.5,1,3\n2.5,0,4\n")
    X, y = native.parse_file(str(p), header=True, label_column="name:target")
    np.testing.assert_array_equal(y, [1, 0])
    np.testing.assert_array_equal(X, [[1.5, 3], [2.5, 4]])


def test_parse_tsv_label_index(tmp_path):
    p = tmp_path / "d.tsv"
    p.write_text("1\t2\t0\n3\t4\t1\n")
    X, y = native.parse_file(str(p), label_column="2")
    np.testing.assert_array_equal(y, [0, 1])
    np.testing.assert_array_equal(X, [[1, 2], [3, 4]])


def test_parse_libsvm(tmp_path):
    p = tmp_path / "d.svm"
    p.write_text("1 0:1.5 3:2\n0 1:4\n")
    X, y = native.parse_file(str(p))
    np.testing.assert_array_equal(y, [1, 0])
    assert X.shape == (2, 4)
    assert X[0, 0] == 1.5 and X[0, 3] == 2 and X[1, 1] == 4 and X[1, 0] == 0


def test_parse_error(tmp_path):
    with pytest.raises(ValueError):
        native.parse_file(str(tmp_path / "missing.csv"))
    p = tmp_path / "bad.csv"
    p.write_text("1,2,3\n1,2\n")
    with pytest.raises(ValueError, match="inconsistent"):
        native.parse_file(str(p))


@pytest.mark.parametrize("max_bins", [4, 63, 255])
def test_find_boundaries_parity(rng, max_bins):
    v = np.round(rng.randn(20000), 2)
    d, c = np.unique(v, return_counts=True)
    py = _greedy_find_boundaries(d, c, max_bins, len(v), 3)
    nat = native.find_boundaries(d, c.astype(np.int64), max_bins, len(v), 3)
    np.testing.assert_allclose(py, nat)


def test_unique_counts_parity(rng):
    v = np.round(rng.randn(5000), 1)
    v[::31] = np.nan
    d, c = np.unique(v[~np.isnan(v)], return_counts=True)
    nd, nc = native.unique_counts(v)
    np.testing.assert_array_equal(d, nd)
    np.testing.assert_array_equal(c, nc)


def test_value_to_bin_parity(rng):
    v = rng.randn(5000)
    v[::13] = np.nan
    v[::7] = 0.0
    m = find_bin(v, 63)
    os.environ["LIGHTGBM_TPU_NO_NATIVE"] = "1"
    try:
        # force the numpy branch by calling internals directly
        vv = np.where(np.isnan(v), np.nan, v)
        n_value_bins = m.num_bins - (1 if m.has_nan_bin else 0)
        ref = np.searchsorted(m.upper_bounds[: n_value_bins - 1], vv,
                              side="left").astype(np.int32)
        ref = np.where(np.isnan(vv), m.nan_bin if m.has_nan_bin else 0, ref)
    finally:
        del os.environ["LIGHTGBM_TPU_NO_NATIVE"]
    nat = native.value_to_bin(v, m.upper_bounds, n_value_bins, m.nan_bin,
                              False)
    np.testing.assert_array_equal(ref, nat)


def test_predict_bins_parity(rng):
    from sklearn.datasets import make_classification

    X, y = make_classification(n_samples=800, n_features=12, random_state=3)
    X[::11, 2] = np.nan
    X[:, 11] = np.abs(X[:, 11] * 4).astype(int) % 9
    ds = lgb.Dataset(X, label=y, categorical_feature=[11])
    bst = lgb.train({"objective": "binary", "num_leaves": 31,
                     "verbosity": -1}, ds, 12)
    gbdt = bst._gbdt
    bins = gbdt.train_data.binned.apply(X)
    nan_bins = gbdt.train_data.binned.nan_bins
    trees = gbdt.models[0]
    ref = np.zeros(len(X))
    for t in trees:
        ref += t.predict_bins(bins, nan_bins)
    nat = native.predict_bins(bins, nan_bins, trees)
    np.testing.assert_allclose(ref, nat, rtol=1e-12, atol=1e-12)


def test_predict_leaf_index_parity(rng):
    from sklearn.datasets import make_regression

    X, y = make_regression(n_samples=500, n_features=8, random_state=0)
    bst = lgb.train({"objective": "regression", "num_leaves": 15,
                     "verbosity": -1}, lgb.Dataset(X, label=y), 5)
    gbdt = bst._gbdt
    bins = gbdt.train_data.binned.apply(X)
    nan_bins = gbdt.train_data.binned.nan_bins
    for t in gbdt.models[0]:
        nat = native.predict_leaf_index(bins, nan_bins, t)
        # leaves partition rows; leaf values looked up via native indices must
        # reproduce the tree's predictions exactly
        np.testing.assert_allclose(t.leaf_value[nat],
                                   t.predict_bins(bins, nan_bins))


def test_dataset_from_file_uses_native(tmp_path):
    rng = np.random.RandomState(0)
    X = rng.randn(200, 5)
    y = (X[:, 0] > 0).astype(int)
    rows = "\n".join(",".join([str(y[i])] + ["%.6f" % v for v in X[i]])
                     for i in range(200))
    p = tmp_path / "train.csv"
    p.write_text(rows + "\n")
    from lightgbm_tpu.io.parser import load_data_file
    Xf, yf, w, g = load_data_file(str(p))
    np.testing.assert_array_equal(yf, y)
    np.testing.assert_allclose(Xf, X, atol=1e-6)


def test_native_predict_multiclass():
    from sklearn.datasets import make_classification

    X, y = make_classification(n_samples=600, n_features=8, n_classes=3,
                               n_informative=6, random_state=1)
    bst = lgb.train({"objective": "multiclass", "num_class": 3,
                     "num_leaves": 7, "verbosity": -1},
                    lgb.Dataset(X, label=y), 5)
    p = bst.predict(X)
    assert p.shape == (600, 3)
    np.testing.assert_allclose(p.sum(axis=1), 1.0, rtol=1e-5)
    assert (p.argmax(axis=1) == y).mean() > 0.7
