"""Fused wave kernel (ISSUE-7 tentpole, ``ops/pallas_wave.py``,
``tpu_wave_kernel``): one pallas_call per wave builds the smaller-sibling
histograms, derives the larger siblings by parent subtraction and runs the
split scan in VMEM.

Bitwise discipline mirrors tests/test_hist_pool.py: with exact-sum inputs
(first-iteration binary gradients +-0.5 / hess 0.25) every histogram value
is exact regardless of accumulation order, the kernel's scan is the SAME
refactored arithmetic (``ops/split.scan_tables``) the unfused path runs,
and the Mosaic-safe one-hot selection replays the unfused argmax's
tie-break exactly — so fused trees pin BITWISE-identical to unfused across
fp32 x quantized x packed4 x pooled (and EFB, where the capability gate
degrades fused to the unfused path).  Quantized histograms are integer,
so those pins are unconditionally exact.  All of this runs the kernel
body in interpret mode on CPU — the tier-1 coverage the gate's
``fused``-forces-interpret semantics exist for."""

import dataclasses

import numpy as np
import pytest

import jax.numpy as jnp

import lightgbm_tpu as lgb
import lightgbm_tpu.models.grower as G
from lightgbm_tpu.config import Config
from lightgbm_tpu.dataset import TrainData
from lightgbm_tpu.models.gbdt import _split_config

_TREE_FIELDS = ("split_feature", "split_bin", "default_left", "is_cat",
                "left_child", "right_child", "split_gain", "leaf_value",
                "leaf_count")


def _assert_same_tree(t0, t1, rl0=None, rl1=None):
    for field in _TREE_FIELDS:
        np.testing.assert_array_equal(
            np.asarray(getattr(t0, field)), np.asarray(getattr(t1, field)),
            err_msg=field)
    assert int(t0.num_leaves) == int(t1.num_leaves)
    if rl0 is not None:
        np.testing.assert_array_equal(np.asarray(rl0), np.asarray(rl1))


def _exact_grow_args(td, n, f):
    """Exact-sum fp32 inputs (grads +-0.5, hess 0.25) — histogram sums are
    exactly representable, so accumulation order cannot perturb them."""
    rng = np.random.RandomState(3)
    sign = (rng.rand(n) > 0.5).astype(np.float32)
    meta = td.feature_meta_device()
    return (jnp.asarray(td.binned.bins),
            jnp.asarray(sign - 0.5), jnp.full(n, 0.25, jnp.float32),
            jnp.ones(n, jnp.float32), jnp.ones(f, bool),
            meta["num_bins_per_feature"], meta["nan_bins"],
            meta["is_categorical"], meta["monotone"])


@pytest.fixture(scope="module")
def grown():
    """Shared dataset: > _MIN_BUCKET rows, NaNs for default-direction
    coverage, one low-cardinality int column kept NUMERICAL."""
    n, f = 3 * 2560, 12
    rng = np.random.RandomState(7)
    X = rng.randn(n, f)
    X[rng.rand(n) < 0.05, 3] = np.nan
    X[:, 5] = rng.randint(0, 6, n)
    y = (X[:, 0] + 0.7 * X[:, 1] * X[:, 2] + 0.3 * rng.randn(n) > 0)
    cfg = Config({"objective": "binary", "num_leaves": 31, "verbosity": -1})
    td = TrainData.build(X, y.astype(np.float64), cfg)
    base = G.GrowerConfig(num_leaves=31, num_bins=td.binned.max_num_bins,
                          split=_split_config(cfg, td))
    return _exact_grow_args(td, n, f), base


def _pair(base, args, **kw):
    gu = G.make_grower(dataclasses.replace(base, wave_kernel="unfused",
                                           **kw))
    gf = G.make_grower(dataclasses.replace(base, wave_kernel="fused", **kw))
    assert not gu.wave_fused and gf.wave_fused
    return gu(*args), gf(*args)


@pytest.mark.parametrize("leaf_batch", [1, 4])
def test_fused_bitwise_fp32(grown, leaf_batch):
    """Fused trees == unfused trees bitwise, W=1 (a wave of one — the
    fused grower routes through _grow_wave even at leaf_batch=1) and
    W=4."""
    args, base = grown
    (t0, rl0), (t1, rl1) = _pair(base, args, leaf_batch=leaf_batch)
    _assert_same_tree(t0, t1, rl0, rl1)
    assert int(t0.num_leaves) > 8      # the pin actually grew a tree


def test_fused_bitwise_quantized(grown):
    """int8 wire / int32 accumulation: integer histograms are exact
    unconditionally, and the in-kernel scale-to-f32 mirrors _scale_hist
    elementwise — bitwise without any exact-sum caveat."""
    args, base = grown
    (t0, rl0), (t1, rl1) = _pair(base, args, leaf_batch=4, quantized=True)
    _assert_same_tree(t0, t1, rl0, rl1)


@pytest.mark.parametrize("quantized", [False, True])
def test_fused_bitwise_pooled(grown, quantized):
    """Bounded histogram pool x fused kernel: the kernel writes into
    claimed slots, parents recompute-on-miss through the UNFUSED branch
    and feed the kernel — trees stay bitwise across heavy eviction."""
    args, base = grown
    f = args[0].shape[1]
    slot_mb = f * base.num_bins * 3 * 4 / (1 << 20)
    (t0, rl0), (t1, rl1) = _pair(
        base, args, leaf_batch=4, quantized=quantized,
        histogram_pool_size=10.5 * slot_mb)   # ~10 slots for 31 leaves
    gf = G.make_grower(dataclasses.replace(
        base, wave_kernel="fused", leaf_batch=4,
        histogram_pool_size=10.5 * slot_mb))
    assert gf.pool_capable and gf.pool_slots(f) < base.num_leaves
    _assert_same_tree(t0, t1, rl0, rl1)


def test_fused_bitwise_packed4():
    """4-bit nibble packing: the kernel unpacks planes in VMEM and scans
    in plane order with ORIGINAL-feature-order tie-break keys — bitwise
    vs the unfused packed4 path (odd F exercises the phantom column)."""
    n, f = 3 * 2560, 9
    rng = np.random.RandomState(11)
    X = np.round(rng.randn(n, f) * 2)      # few distinct values -> <=16 bins
    y = (X[:, 0] + X[:, 1] > 0)
    cfg = Config({"objective": "binary", "num_leaves": 31, "max_bin": 15,
                  "verbosity": -1})
    td = TrainData.build(X, y.astype(np.float64), cfg)
    assert td.binned.max_num_bins <= 16
    from lightgbm_tpu.ops.histogram import pack_bins4
    args = list(_exact_grow_args(td, n, f))
    args[0] = pack_bins4(args[0])
    base = G.GrowerConfig(num_leaves=31, num_bins=td.binned.max_num_bins,
                          split=_split_config(cfg, td), packed4=True)
    (t0, rl0), (t1, rl1) = _pair(base, tuple(args), leaf_batch=4)
    _assert_same_tree(t0, t1, rl0, rl1)
    assert int(t0.num_leaves) > 8


def test_fused_bitwise_onehot_categorical():
    """One-hot categorical splits INSIDE the kernel (cat_stats gather,
    bis_cat selection, the cat_mask payload lanes): a low-cardinality
    categorical feature engineered to win splits must produce bitwise
    trees — including the (L, B) cat_mask routing — on the fused path.
    max_cat_to_onehot is raised so no feature is sorted-eligible (the
    sorted scan is the one categorical path the kernel excludes)."""
    n, f = 3 * 2560, 4
    rng = np.random.RandomState(13)
    cat = rng.randint(0, 6, n).astype(np.float64)
    X = np.column_stack([cat, rng.randn(n, f - 1)])
    y = ((cat == 2.0) | (cat == 5.0)) ^ (X[:, 1] > 1.0)
    cfg = Config({"objective": "binary", "num_leaves": 31,
                  "max_cat_to_onehot": 16, "verbosity": -1})
    td = TrainData.build(X, y.astype(np.float64), cfg,
                         categorical_features=[0])
    scfg = _split_config(cfg, td)
    assert scfg.has_categorical and not scfg.use_sorted_categorical
    base = G.GrowerConfig(num_leaves=31, num_bins=td.binned.max_num_bins,
                          split=scfg)
    (t0, rl0), (t1, rl1) = _pair(base, _exact_grow_args(td, n, f),
                                 leaf_batch=4)
    _assert_same_tree(t0, t1, rl0, rl1)
    np.testing.assert_array_equal(np.asarray(t0.cat_mask),
                                  np.asarray(t1.cat_mask))
    assert bool(np.any(np.asarray(t0.is_cat)[
        :int(t0.num_leaves) - 1])), "no categorical split won — dead pin"


def test_small_n_reports_fused_inactive():
    """n <= _MIN_BUCKET routes to the mask layout (no wave at all):
    wave_fused_active — and everything the census/bench derive from it —
    must say so instead of reporting a kernel that never runs."""
    rng = np.random.RandomState(1)
    X = rng.randn(1500, 4)
    y = (X[:, 0] > 0).astype(np.float64)
    bst = lgb.train({"objective": "binary", "num_leaves": 15,
                     "tpu_wave_kernel": "fused", "tpu_leaf_batch": 4,
                     "verbosity": -1, "metric": "none"},
                    lgb.Dataset(X, label=y), 2)
    assert bst._gbdt.wave_fused_active is False


def test_fused_degrades_under_efb_and_stays_identical():
    """EFB bundling keeps the unfused wave (bundle-offset expansion is not
    Mosaic-expressible): tpu_wave_kernel=fused must DEGRADE — and then
    trivially match the unfused run byte for byte."""
    n = 4000
    rng = np.random.RandomState(2)
    # mutually exclusive one-hot blocks bundle under EFB
    base_col = rng.randint(0, 4, n)
    X = np.zeros((n, 8))
    for j in range(4):
        X[:, j] = (base_col == j) * rng.rand(n)
    X[:, 4:] = rng.randn(n, 4)
    y = (X[:, 4] + base_col > 1.5).astype(np.float64)
    params = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
              "metric": "none", "deterministic": True, "tpu_leaf_batch": 4}
    b_f = lgb.train(dict(params, tpu_wave_kernel="fused"),
                    lgb.Dataset(X, label=y), 3)
    b_u = lgb.train(dict(params, tpu_wave_kernel="unfused"),
                    lgb.Dataset(X, label=y), 3)
    assert b_f._gbdt.bundles is not None          # EFB actually engaged
    assert b_f._gbdt.wave_fused_active is False   # ... and fused degraded
    # byte-identical trees; only the echoed parameter block may differ
    tree_f = b_f.model_to_string().split("end of parameters")[1]
    tree_u = b_u.model_to_string().split("end of parameters")[1]
    assert tree_f == tree_u


def test_fused_iter_pack_k1_eq_k4():
    """tpu_wave_kernel=fused composes with iteration packing: K=4 packed
    rounds (the pallas kernel traced inside the lax.scan body) produce the
    byte-identical model of 4 per-round updates."""
    n = 3 * 2560
    rng = np.random.RandomState(5)
    X = rng.randn(n, 8)
    y = (X[:, 0] + X[:, 1] * X[:, 2] > 0).astype(np.float64)
    params = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
              "metric": "none", "deterministic": True, "tpu_leaf_batch": 4,
              "tpu_wave_kernel": "fused"}
    b1 = lgb.Booster(params=params, train_set=lgb.Dataset(X, label=y))
    for _ in range(4):
        b1.update()
    b4 = lgb.Booster(params=params, train_set=lgb.Dataset(X, label=y))
    assert b4._gbdt.iter_pack_plan(4)[1], "config must be pack-capable"
    b4.update_pack(4)
    assert b1.model_to_string() == b4.model_to_string()


def test_selection_parity_onehot_vs_argmax(rng):
    """ops/split.select_payload (the Mosaic-safe one-hot selection the
    kernel uses) must pick the SAME winner as _select_from_tables' argmax
    — including on exact gain ties and the all--inf no-split case."""
    from lightgbm_tpu.ops.split import (SplitConfig, _select_from_tables,
                                        scan_tables, select_payload)

    F, B = 5, 16
    cfg = SplitConfig(min_data_in_leaf=1, has_nan=True,
                      has_categorical=False, use_sorted_categorical=False,
                      has_monotone=False)
    hist = np.zeros((F, B, 3), np.float32)
    hist[:, :, 0] = rng.randn(F, B)
    hist[:, :, 1] = rng.rand(F, B) + 0.1
    hist[:, :, 2] = rng.randint(1, 20, (F, B))
    hist[2] = hist[1]                      # exact duplicate -> gain ties
    tot = hist[0].sum(axis=0)
    for variant in ("normal", "no_split"):
        cfgv = cfg if variant == "normal" else dataclasses.replace(
            cfg, min_data_in_leaf=10**6)
        t = scan_tables(
            jnp.asarray(hist[..., 0]), jnp.asarray(hist[..., 1]),
            jnp.asarray(hist[..., 2]), *(jnp.asarray(v) for v in tot),
            num_bins_per_feature=jnp.full(F, B, jnp.int32),
            nan_bins=jnp.full(F, B, jnp.int32),
            is_categorical=jnp.zeros(F, bool),
            feature_mask=jnp.ones(F, bool), cfg=cfgv)
        ref = _select_from_tables(t, jnp.zeros(F, bool), cfgv)
        got = select_payload(t, jnp.zeros(F, bool), cfgv)
        gain, bf, bb, dl, ic, GL, HL, CL, GR, HR, CR = got
        assert float(gain) == float(ref.gain)
        assert int(bf) == int(ref.feature) and int(bb) == int(ref.bin)
        assert bool(dl) == bool(ref.default_left)
        for a, b in ((GL, ref.sum_grad_left), (HL, ref.sum_hess_left),
                     (CL, ref.count_left), (GR, ref.sum_grad_right),
                     (HR, ref.sum_hess_right), (CR, ref.count_right)):
            assert float(a) == float(b)


def test_wave_layout_legal_and_budgeted():
    """Hermetic kernel_layout-style pin for the fused kernel's VMEM plan:
    every BlockSpec-relevant dimension Mosaic-legal (128-multiple lane
    dims, nibble-pair-even feature tiles), histogram block + scan scratch
    under budget wherever the layout claims to fit, and the shapes that
    must (bench Higgs) / must not (Epsilon-wide) fuse."""
    from lightgbm_tpu.ops.pallas_wave import (WAVE_VMEM_BUDGET,
                                              wave_layout)

    for dtype in ("f32", "bf16", "int8"):
        for nb in (16, 64, 255, 256):
            for f in (1, 28, 137):
                lay = wave_layout(f, nb, dtype)
                assert lay["b_pad"] % 128 == 0 and lay["b_pad"] >= nb
                assert (lay["ftile"] * lay["b_pad"]) % 128 == 0
                assert lay["rows_block"] % 128 == 0
                if lay["fits"]:
                    assert lay["single_chunk"]
                    assert lay["total_bytes"] <= WAVE_VMEM_BUDGET
                    assert (lay["hist_block_bytes"]
                            + lay["scan_scratch_bytes"]) <= WAVE_VMEM_BUDGET
        lay4 = wave_layout(13, 16, dtype, packed4=True)
        assert lay4["ftile"] % 2 == 0
    # the bench Higgs shape fuses (fp32 AND the quantized int8 wire) ...
    assert wave_layout(28, 256, "f32")["fits"]
    assert wave_layout(28, 256, "int8")["fits"]
    # ... Epsilon-wide does not (keeps the unfused + pool + tiled scan)
    assert not wave_layout(2000, 256, "f32")["fits"]


def test_capability_predicate_and_knob():
    """wave_fused_for: the composition gate (shared with GBDT and the
    census) — excluded axes degrade, explicit fused forces on CPU, auto
    engages only where the flat pallas impl is live."""
    from lightgbm_tpu.ops.split import SplitConfig

    plain = SplitConfig(has_nan=True, has_categorical=False,
                        use_sorted_categorical=False, has_monotone=False)
    base = G.GrowerConfig(num_leaves=15, num_bins=64, split=plain,
                          leaf_batch=4)
    rep = dataclasses.replace
    assert G.wave_fused_for(rep(base, wave_kernel="fused"))
    # auto on a CPU backend (resolve_impl -> segment): stays unfused
    assert not G.wave_fused_for(rep(base, wave_kernel="auto"))
    # ... but auto with the flat pallas impl engages
    assert G.wave_fused_for(rep(base, wave_kernel="auto",
                                histogram_impl="flat"))
    assert not G.wave_fused_for(rep(base, wave_kernel="unfused"))
    for bad in (
        rep(base, wave_kernel="fused", voting=True),
        rep(base, wave_kernel="fused", bundled=True),
        rep(base, wave_kernel="fused", gather_rows=False),
        rep(base, wave_kernel="fused",
            forced_splits=((0, 1, -1, -1),)),
        rep(base, wave_kernel="fused",
            split=rep(plain, has_monotone=True)),
        rep(base, wave_kernel="fused", split=rep(plain, use_cegb=True)),
        rep(base, wave_kernel="fused",
            split=rep(plain, extra_trees=True)),
        rep(base, wave_kernel="fused", feature_fraction_bynode=0.5),
        rep(base, wave_kernel="fused", interaction_groups=((0, 1),)),
        rep(base, wave_kernel="fused",
            split=rep(plain, feature_contri=(0.5, 1.0))),
        rep(base, wave_kernel="fused",
            split=rep(plain, has_categorical=True,
                      use_sorted_categorical=True)),
    ):
        assert not G.wave_fused_for(bad), bad
    with pytest.raises(ValueError, match="wave_kernel"):
        G.wave_fused_for(rep(base, wave_kernel="bogus"))
    with pytest.raises(ValueError, match="tpu_wave_kernel"):
        lgb.train({"objective": "binary", "tpu_wave_kernel": "bogus",
                   "verbosity": -1},
                  lgb.Dataset(np.random.rand(100, 3),
                              label=np.zeros(100)), 1)


def test_explicit_fused_downgrades_through_matrix(capsys):
    """The capability matrix owns the composition downgrades: an explicit
    fused request against monotone constraints warns and keeps the
    unfused wave (same message discipline as every other rule)."""
    rng = np.random.RandomState(0)
    X = rng.rand(1500, 4)
    y = 2 * X[:, 0] + 0.1 * rng.randn(1500)
    bst = lgb.train({"objective": "regression", "num_leaves": 15,
                     "monotone_constraints": [1, 0, 0, 0],
                     "tpu_wave_kernel": "fused", "tpu_leaf_batch": 4,
                     "verbosity": 1},
                    lgb.Dataset(X, label=y), 2)
    out = capsys.readouterr()
    assert "tpu_wave_kernel=fused" in out.out + out.err
    assert bst._gbdt.wave_fused_active is False
    assert bst._gbdt.grower_cfg.wave_kernel == "unfused"
