"""Plotting + model-dump tests (reference tests/python_package_test/
test_plotting.py — matplotlib Agg backend, no display)."""

import matplotlib

matplotlib.use("Agg")

import matplotlib.pyplot as plt  # noqa: E402
import numpy as np  # noqa: E402
import pytest  # noqa: E402

import lightgbm_tpu as lgb  # noqa: E402


@pytest.fixture
def model(rng):
    X = rng.randn(500, 6)
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(float)
    ds = lgb.Dataset(X, label=y,
                     feature_name=[f"f{i}" for i in range(6)])
    bst = lgb.train({"objective": "binary", "num_leaves": 7,
                     "verbosity": -1, "min_data_in_leaf": 5}, ds, 12)
    return bst


def test_dump_model_structure(model):
    dump = model.dump_model()
    assert dump["name"] == "tree"
    assert dump["num_class"] == 1
    assert len(dump["tree_info"]) == 12
    root = dump["tree_info"][0]["tree_structure"]
    assert "split_feature" in root
    assert "left_child" in root and "right_child" in root
    # leaves carry values
    node = root
    while "left_child" in node:
        node = node["left_child"]
    assert "leaf_value" in node
    assert dump["feature_names"] == [f"f{i}" for i in range(6)]


def test_dump_model_num_iteration(model):
    dump = model.dump_model(num_iteration=3)
    assert len(dump["tree_info"]) == 3


def test_trees_to_dataframe(model):
    rows = model.trees_to_dataframe()
    assert len(rows) > 12
    split_rows = [r for r in rows if r["split_feature"] is not None]
    leaf_rows = [r for r in rows if r["split_feature"] is None]
    assert split_rows and leaf_rows
    assert all(r["node_index"].startswith("0-") for r in rows
               if r["tree_index"] == 0)


def test_plot_importance(model):
    ax = lgb.plot_importance(model)
    assert len(ax.patches) > 0
    assert ax.get_title() == "Feature importance"
    plt.close("all")


def test_plot_importance_gain(model):
    ax = lgb.plot_importance(model, importance_type="gain",
                             max_num_features=3)
    assert len(ax.patches) <= 3
    plt.close("all")


def test_plot_split_value_histogram(model):
    imp = model.feature_importance()
    feat = int(np.argmax(imp))
    ax = lgb.plot_split_value_histogram(model, feat)
    assert len(ax.patches) > 0
    plt.close("all")


def test_plot_metric(rng):
    X = rng.randn(400, 5)
    y = (X[:, 0] > 0).astype(float)
    ds = lgb.Dataset(X[:300], label=y[:300])
    vs = lgb.Dataset(X[300:], label=y[300:], reference=ds)
    evals = {}
    lgb.train({"objective": "binary", "metric": "binary_logloss",
               "num_leaves": 7, "verbosity": -1}, ds, 10,
              valid_sets=[vs], callbacks=[lgb.record_evaluation(evals)])
    ax = lgb.plot_metric(evals)
    assert ax.get_title() == "Metric during training"
    plt.close("all")


def test_plot_metric_sklearn(rng):
    X = rng.randn(400, 5)
    y = (X[:, 0] > 0).astype(float)
    clf = lgb.LGBMClassifier(n_estimators=8, num_leaves=7, verbosity=-1)
    clf.fit(X[:300], y[:300], eval_set=[(X[300:], y[300:])])
    assert clf.evals_result_
    ax = lgb.plot_metric(clf)
    plt.close("all")


def test_plot_tree(model):
    ax = lgb.plot_tree(model, tree_index=2)
    assert ax is not None
    plt.close("all")


def test_plot_tree_bad_index(model):
    with pytest.raises(IndexError):
        lgb.plot_tree(model, tree_index=999)


def test_create_tree_digraph_requires_graphviz(model):
    try:
        import graphviz  # noqa: F401
        g = lgb.create_tree_digraph(model, 0)
        assert g is not None
    except ImportError:
        with pytest.raises(ImportError):
            lgb.create_tree_digraph(model, 0)
