"""Monotone constraint modes (reference monotone_constraints.hpp:
BasicLeafConstraints:487, IntermediateLeafConstraints:516,
AdvancedLeafConstraints:583; reference tests:
tests/python_package_test/test_engine.py test_monotone_constraints).

Intermediate here = per-step fresh bound derivation from leaf-rectangle
adjacency + full best-split refresh (see grower.py _inter_refresh).
"""

import numpy as np
import pytest

import lightgbm_tpu as lgb


def _mono_data(n=4000, seed=0):
    """x0 increasing, x1 decreasing, x2/x3 free; interactions so basic's
    frozen midpoint caps actually cost accuracy."""
    rng = np.random.RandomState(seed)
    X = rng.rand(n, 4)
    y = (3 * X[:, 0] + np.sin(4 * X[:, 0]) - 2.5 * X[:, 1]
         + 1.5 * X[:, 2] * X[:, 0] + 0.5 * np.cos(3 * X[:, 3])
         + 0.1 * rng.randn(n))
    return X, y


P = {"objective": "regression", "num_leaves": 31, "learning_rate": 0.1,
     "min_data_in_leaf": 10, "verbosity": -1, "metric": "l2",
     "monotone_constraints": [1, -1, 0, 0]}


def _is_monotone(bst, n_probe=40, n_grid=25, seed=3):
    """Predictions must be non-decreasing in x0 and non-increasing in x1
    when all other features are held fixed."""
    rng = np.random.RandomState(seed)
    base = rng.rand(n_probe, 4)
    grid = np.linspace(0, 1, n_grid)
    for feat, sign in ((0, 1), (1, -1)):
        Xg = np.repeat(base, n_grid, axis=0)
        Xg[:, feat] = np.tile(grid, n_probe)
        pred = bst.predict(Xg).reshape(n_probe, n_grid)
        diffs = sign * np.diff(pred, axis=1)
        if diffs.min() < -1e-10:
            return False
    return True


@pytest.mark.parametrize("method", ["basic", "intermediate", "advanced"])
def test_all_methods_train_and_are_monotone(method):
    X, y = _mono_data()
    bst = lgb.train(dict(P, monotone_constraints_method=method),
                    lgb.Dataset(X, label=y), 30)
    assert bst.num_trees() == 30
    assert _is_monotone(bst), method


def test_intermediate_beats_basic_holdout():
    """Basic's frozen midpoint caps over-constrain; intermediate's fresh
    per-leaf bounds must win on held-out loss (the reference docs motivate
    intermediate exactly this way)."""
    X, y = _mono_data(n=6000, seed=1)
    Xv, yv = _mono_data(n=3000, seed=2)
    ds = lambda: lgb.Dataset(X, label=y)
    basic = lgb.train(dict(P, monotone_constraints_method="basic"),
                      ds(), 60)
    inter = lgb.train(dict(P, monotone_constraints_method="intermediate"),
                      ds(), 60)
    mse_b = float(np.mean((basic.predict(Xv) - yv) ** 2))
    mse_i = float(np.mean((inter.predict(Xv) - yv) ** 2))
    assert mse_i < mse_b, (mse_i, mse_b)


def test_intermediate_sharded_matches_serial():
    """The per-step refresh runs on replicated state under shard_map, so
    data-parallel intermediate training must match serial exactly in
    structure."""
    import jax

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    X, y = _mono_data(n=8 * 2500, seed=4)
    params = dict(P, monotone_constraints_method="intermediate",
                  min_data_in_leaf=20)
    serial = lgb.train(dict(params, tree_learner="serial"),
                       lgb.Dataset(X, label=y), 5)
    sharded = lgb.train(dict(params, tree_learner="data"),
                        lgb.Dataset(X, label=y), 5)
    np.testing.assert_allclose(serial.predict(X), sharded.predict(X),
                               rtol=1e-3, atol=1e-4)
    assert _is_monotone(sharded)


def test_intermediate_wave_composes_and_rejects_randomness():
    """Wave growth now composes with the monotone refresh (conflict-free
    wave selection + per-wave refresh); per-node randomness still cannot."""
    X, y = _mono_data(n=1500)
    bst = lgb.train(dict(P, monotone_constraints_method="intermediate",
                         tpu_leaf_batch=8),
                    lgb.Dataset(X, label=y), 3)
    assert bst._gbdt.grower_cfg.leaf_batch == 8
    assert _is_monotone(bst)
    with pytest.raises(ValueError, match="extra_trees"):
        lgb.train(dict(P, monotone_constraints_method="intermediate",
                       extra_trees=True), lgb.Dataset(X, label=y), 2)


@pytest.mark.parametrize("method", ["intermediate", "advanced"])
def test_wave_matches_sequential_with_bounded_divergence(method):
    """Conflict-free wave selection executes monotone-ordered splits in
    separate waves, so wave trees may interleave differently from
    sequential but the quality gap must stay small and monotonicity must
    hold exactly (VERDICT r4 weak #4)."""
    rng = np.random.RandomState(0)
    n = 6000
    X = rng.rand(n, 4).astype(np.float32)
    y = (3 * X[:, 0] + np.sin(5 * X[:, 1]) - 2 * X[:, 2]
         + 0.2 * rng.randn(n))
    p = {"objective": "regression", "num_leaves": 63,
         "monotone_constraints": [1, 0, -1, 0], "min_data_in_leaf": 10,
         "verbosity": -1, "monotone_constraints_method": method}
    seq = lgb.train(dict(p), lgb.Dataset(X, label=y), 8)
    wav = lgb.train(dict(p, tpu_leaf_batch=16), lgb.Dataset(X, label=y), 8)
    assert wav._gbdt.grower_cfg.leaf_batch == 16
    mse_s = float(np.mean((seq.predict(X) - y) ** 2))
    mse_w = float(np.mean((wav.predict(X) - y) ** 2))
    assert mse_w < mse_s * 1.05, (mse_w, mse_s)
    base = rng.rand(30, 4)
    grid = np.linspace(0, 1, 40)
    for feat, sign in ((0, 1), (2, -1)):
        Xg = np.repeat(base, 40, axis=0)
        Xg[:, feat] = np.tile(grid, 30)
        pred = wav.predict(Xg).reshape(30, 40)
        assert (sign * np.diff(pred, axis=1)).min() >= -1e-10


def test_monotone_with_missing_values():
    """NaN rows route by the learned default direction and are exempt from
    the value-axis monotone ordering (reference: missing handled outside
    the constrained range), but non-NaN predictions stay monotone."""
    X, y = _mono_data(n=4000, seed=5)
    X = X.copy()
    X[np.random.RandomState(0).rand(len(X)) < 0.15, 0] = np.nan
    bst = lgb.train(dict(P, monotone_constraints_method="intermediate"),
                    lgb.Dataset(X, label=y), 20)
    assert _is_monotone(bst)


def test_advanced_runs_native_not_downgraded(capsys):
    """`advanced` must run its own per-threshold machinery (no downgrade
    warning) and produce monotone predictions."""
    X, y = _mono_data(n=2500)
    bst = lgb.train(dict(P, monotone_constraints_method="advanced",
                         verbosity=1), lgb.Dataset(X, label=y), 5)
    out = capsys.readouterr()
    assert "falling back" not in (out.out + out.err).lower()
    assert "not implemented" not in (out.out + out.err).lower()
    assert bst._gbdt.grower_cfg.mono_advanced
    assert _is_monotone(bst)


def test_advanced_tightens_intermediate():
    """Advanced's per-threshold constraint slices only apply a neighbour's
    output to the part of a leaf's range actually adjacent to it, so its
    effective constraints are a strict subset of intermediate's whole-leaf
    bounds — training loss must improve strictly on a constructed case
    (reference AdvancedLeafConstraints, monotone_constraints.hpp:583:
    'monotone precise mode')."""
    rng = np.random.RandomState(0)
    n = 3000
    X = rng.rand(n, 3).astype(np.float32)
    y = 3 * X[:, 0] + np.sin(6 * X[:, 1]) + 0.3 * rng.randn(n)
    p = {"objective": "regression", "num_leaves": 31,
         "monotone_constraints": [1, 0, 0], "min_data_in_leaf": 5,
         "verbosity": -1}
    mse = {}
    for method in ("intermediate", "advanced"):
        bst = lgb.train(dict(p, monotone_constraints_method=method),
                        lgb.Dataset(X, label=y), 10)
        assert _is_monotone_1feat(bst)
        mse[method] = float(np.mean((bst.predict(X) - y) ** 2))
    assert mse["advanced"] < mse["intermediate"], mse


def _is_monotone_1feat(bst, n_probe=30, n_grid=40, seed=7):
    rng = np.random.RandomState(seed)
    base = rng.rand(n_probe, 3)
    grid = np.linspace(0, 1, n_grid)
    Xg = np.repeat(base, n_grid, axis=0)
    Xg[:, 0] = np.tile(grid, n_probe)
    pred = bst.predict(Xg).reshape(n_probe, n_grid)
    return np.diff(pred, axis=1).min() >= -1e-10


def test_advanced_rejects_forced_splits(tmp_path):
    import json
    X, y = _mono_data(n=1500)
    path = tmp_path / "forced.json"
    path.write_text(json.dumps({"feature": 2, "threshold": 0.5}))
    with pytest.raises(ValueError, match="forced"):
        lgb.train(dict(P, monotone_constraints_method="advanced",
                       forcedsplits_filename=str(path)),
                  lgb.Dataset(X, label=y), 2)


def test_intermediate_sharded_wave_composes():
    """Data-parallel + wave growth + monotone refresh: the conflict-free
    wave selection runs on replicated state under shard_map, so the
    sharded wave grower must train, stay monotone, and track the serial
    wave grower closely."""
    import jax

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    X, y = _mono_data(n=8 * 2000, seed=6)
    params = dict(P, monotone_constraints_method="intermediate",
                  tpu_leaf_batch=8, min_data_in_leaf=20)
    serial = lgb.train(dict(params, tree_learner="serial"),
                       lgb.Dataset(X, label=y), 5)
    sharded = lgb.train(dict(params, tree_learner="data"),
                        lgb.Dataset(X, label=y), 5)
    assert sharded._gbdt.grower_cfg.leaf_batch == 8
    assert _is_monotone(sharded)
    mse_s = float(np.mean((serial.predict(X) - y) ** 2))
    mse_d = float(np.mean((sharded.predict(X) - y) ** 2))
    assert mse_d < mse_s * 1.05, (mse_d, mse_s)
