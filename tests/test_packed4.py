"""4-bit bin packing (reference ``DenseBin`` IS_4BIT arm,
``src/io/dense_bin.hpp``): with max_bin <= 15 every feature fits a nibble,
so the (N, F) bin matrix is stored as (N, ceil(F/2)) byte pairs and the
histogram kernels unpack in-register.  Resident memory and per-leaf row
gathers halve; trees must be EXACTLY the ones the byte-per-bin path grows.
"""

import numpy as np
import pytest

import jax.numpy as jnp

import lightgbm_tpu as lgb
from lightgbm_tpu.ops.histogram import (histogram_onehot, histogram_segment,
                                        pack_bins4, unpack_bins4)

P15 = {"objective": "binary", "num_leaves": 31, "max_bin": 15,
       "min_data_in_leaf": 5, "verbosity": -1, "deterministic": True,
       "seed": 3}


def _data(n=20000, f=7, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, f)
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(float)
    return X, y


def _assert_same_trees(a, b):
    for k in range(len(a._gbdt.models)):
        for t1, t2 in zip(a._gbdt.models[k], b._gbdt.models[k]):
            np.testing.assert_array_equal(t1.split_feature, t2.split_feature)
            np.testing.assert_array_equal(t1.split_bin, t2.split_bin)
            np.testing.assert_allclose(t1.leaf_value, t2.leaf_value,
                                       rtol=1e-6, atol=1e-7)


def test_pack_unpack_roundtrip(rng):
    for f in (6, 7):
        bins = rng.randint(0, 16, (500, f)).astype(np.uint8)
        packed = pack_bins4(jnp.asarray(bins))
        assert packed.shape == (500, (f + 1) // 2)
        np.testing.assert_array_equal(np.asarray(unpack_bins4(packed, f)),
                                      bins)


def test_kernel_parity_all_impls(rng):
    n, f, B = 5000, 7, 16
    bins = rng.randint(0, 16, (n, f)).astype(np.uint8)
    vals = rng.randn(n, 3).astype(np.float32)
    packed = pack_bins4(jnp.asarray(bins))
    # same impl, packed vs unpacked: bit-identical
    h = histogram_segment(jnp.asarray(bins), jnp.asarray(vals), num_bins=B)
    hp = histogram_segment(packed, jnp.asarray(vals), num_bins=B,
                           packed4=True, features=f)
    np.testing.assert_array_equal(np.asarray(h), np.asarray(hp))
    ho = histogram_onehot(jnp.asarray(bins), jnp.asarray(vals), num_bins=B)
    hop = histogram_onehot(packed, jnp.asarray(vals), num_bins=B,
                           packed4=True, features=f)
    np.testing.assert_array_equal(np.asarray(ho), np.asarray(hop))


def test_kernel_parity_pallas_interpret(rng):
    from lightgbm_tpu.ops.pallas_histogram import histogram_flat
    n, f, B = 3000, 8, 16
    bins = rng.randint(0, 16, (n, f)).astype(np.uint8)
    vals = rng.randn(n, 3).astype(np.float32)
    packed = pack_bins4(jnp.asarray(bins))
    h = histogram_flat(jnp.asarray(bins), jnp.asarray(vals), num_bins=B,
                       interpret=True)
    hp = histogram_flat(packed, jnp.asarray(vals), num_bins=B, packed4=True,
                        features=f, interpret=True)
    np.testing.assert_allclose(np.asarray(h), np.asarray(hp),
                               rtol=1e-5, atol=1e-5)


def test_auto_enable_and_memory_halves():
    X, y = _data()
    on = lgb.train(dict(P15), lgb.Dataset(X, label=y), 3)
    assert on._gbdt.grower_cfg.packed4
    assert on._gbdt.bins_dev.shape == (len(X), 4)
    off = lgb.train(dict(P15, tpu_4bit_bins=False),
                    lgb.Dataset(X, label=y), 3)
    assert not off._gbdt.grower_cfg.packed4
    # ceil(7/2)/7; an even F halves exactly
    assert on._gbdt.bins_dev.nbytes * 7 == off._gbdt.bins_dev.nbytes * 4
    coarse = lgb.train(dict(P15, max_bin=255), lgb.Dataset(X, label=y), 2)
    assert not coarse._gbdt.grower_cfg.packed4


@pytest.mark.parametrize("extra", [
    {},                                           # serial perm
    {"tpu_leaf_batch": 8},                        # wave growth
    {"use_quantized_grad": True},                 # int8 grads, i32 hists
    {"monotone_constraints": [1, 0, 0, 0, 0, 0, 0]},
])
def test_exact_tree_parity(extra):
    X, y = _data()
    on = lgb.train(dict(P15, **extra), lgb.Dataset(X, label=y), 6)
    off = lgb.train(dict(P15, tpu_4bit_bins=False, **extra),
                    lgb.Dataset(X, label=y), 6)
    assert on._gbdt.grower_cfg.packed4
    _assert_same_trees(on, off)
    np.testing.assert_allclose(on.predict(X[:500]), off.predict(X[:500]),
                               rtol=1e-7)


def test_sharded_perm_parity():
    import jax

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    X, y = _data(n=8 * 4000, f=6, seed=1)
    on = lgb.train(dict(P15, tree_learner="data"),
                   lgb.Dataset(X, label=y), 5)
    off = lgb.train(dict(P15, tree_learner="data", tpu_4bit_bins=False),
                    lgb.Dataset(X, label=y), 5)
    assert on._gbdt.grower_cfg.packed4
    _assert_same_trees(on, off)


def test_efb_bundling_disables_packing():
    rng = np.random.RandomState(2)
    n, f = 4000, 12
    X = np.zeros((n, f))
    # mutually-exclusive sparse columns bundle under EFB
    owner = rng.randint(0, f, n)
    X[np.arange(n), owner] = rng.rand(n) + 0.5
    y = (owner % 2).astype(float)
    bst = lgb.train(dict(P15, enable_bundle=True),
                    lgb.Dataset(X, label=y), 2)
    # the data is constructed to bundle; a vacuous pass would hide the
    # EFB/packed4 exclusion this test exists for
    assert bst._gbdt.bundles is not None
    assert not bst._gbdt.grower_cfg.packed4


def test_dart_and_rollback_parity():
    """score_bins_dev consumers (DART drop/renorm, rollback) index ORIGINAL
    feature columns — they must see unpacked bins (review finding r5)."""
    X, y = _data(n=6000)
    p = dict(P15, boosting="dart", drop_rate=0.5, num_leaves=15)
    on = lgb.train(dict(p), lgb.Dataset(X, label=y), 8)
    off = lgb.train(dict(p, tpu_4bit_bins=False), lgb.Dataset(X, label=y), 8)
    assert on._gbdt.grower_cfg.packed4
    np.testing.assert_allclose(on.predict(X[:500]), off.predict(X[:500]),
                               rtol=1e-6, atol=1e-7)
    # rollback path
    ds = lgb.Dataset(X, label=y)
    bst = lgb.Booster(params=dict(P15), train_set=ds)
    bst.update()
    bst.update()
    assert bst._gbdt.grower_cfg.packed4
    bst.rollback_one_iter()
    assert bst.num_trees() == 1


def test_kernel_parity_pallas_odd_features_and_chunks(rng):
    """packed4 kernel emits per-chunk nibble planes into contiguous halves
    and un-permutes outside; odd F (phantom high nibble) and the
    multi-chunk feature path must both reproduce the unpacked histogram."""
    from lightgbm_tpu.ops.pallas_histogram import histogram_flat

    for n, f, B in [(777, 7, 16), (256, 260, 15)]:
        bins = rng.randint(0, B, (n, f)).astype(np.uint8)
        vals = rng.randn(n, 3).astype(np.float32)
        packed = pack_bins4(jnp.asarray(bins))
        h = histogram_onehot(jnp.asarray(bins), jnp.asarray(vals), num_bins=B)
        hp = histogram_flat(packed, jnp.asarray(vals), num_bins=B,
                            packed4=True, features=f, interpret=True)
        np.testing.assert_allclose(np.asarray(h), np.asarray(hp),
                                   rtol=1e-5, atol=1e-5)
