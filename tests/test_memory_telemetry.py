"""Memory & compile observability invariants (ISSUE-10,
docs/OBSERVABILITY.md memory section):

- ``tpu_telemetry_memory=off`` is bitwise-inert — the lowered fused-
  iteration HLO is equal TEXT with accounting off vs census (the PR-9
  inertness pin extended to the new knob) and the fused dispatch census
  stays 1.0 dispatches/iter WITH memory tracking armed;
- live-buffer census math on a synthetic array set (grouping, byte
  totals, largest-first ordering);
- the CPU graceful-None path of ``device_memory_stats``;
- tracked spans: ``memory.watermark`` events with a positive live-buffer
  delta when a span allocates, silence when the mode is off;
- compile telemetry: a first-time jit launch bumps ``compile.count`` and
  emits ``compile.end``;
- the bench ``detail.memory`` block schema (the per-rung assertions live
  in tests/test_bench_rungs.py);
- serve plan-pack byte gauges (``plan_bytes``, plan-cache ``bytes``) and
  their Prometheus exposition.
"""

import json
import os
import tempfile

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu import telemetry
from lightgbm_tpu.telemetry import memory

pytestmark = pytest.mark.telemetry

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _data(n=800, f=5, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, f)
    y = (X[:, 0] + X[:, 1] * X[:, 2] > 0).astype(np.float64)
    return X, y


@pytest.fixture(autouse=True)
def _rearm():
    """Every test starts armed with accounting OFF (the process default)
    and leaves no sink or armed mode behind."""
    telemetry.set_enabled(True)
    telemetry.set_memory_mode("off")
    yield
    telemetry.close_log()
    telemetry.set_enabled(True)
    telemetry.set_memory_mode("off")


# ----------------------------------------------------------------- knob
def test_memory_knob_validated():
    X, y = _data(300)
    with pytest.raises(ValueError, match="tpu_telemetry_memory"):
        lgb.Booster(params={"objective": "binary", "verbosity": -1,
                            "tpu_telemetry_memory": "sometimes"},
                    train_set=lgb.Dataset(X, label=y))
    with pytest.raises(ValueError, match="tpu_telemetry_memory"):
        memory.set_memory_mode("maybe")


def test_memory_mode_armed_only_when_explicit():
    """A default-params booster must not flip the mode under an armed
    session (the tpu_telemetry explicit-params rule, extended)."""
    X, y = _data(300)
    telemetry.set_memory_mode("census")
    lgb.Booster(params={"objective": "binary", "verbosity": -1,
                        "metric": "none"},
                train_set=lgb.Dataset(X, label=y))
    assert memory.memory_mode() == "census"
    lgb.Booster(params={"objective": "binary", "verbosity": -1,
                        "metric": "none", "tpu_telemetry_memory": "off"},
                train_set=lgb.Dataset(X, label=y))
    assert memory.memory_mode() == "off"


# ------------------------------------------------------ inertness contract
def _fused_lowered_text(memory_mode):
    X, y = _data(600)
    ds = lgb.Dataset(X, label=y)
    bst = lgb.Booster(params={"objective": "binary", "num_leaves": 7,
                              "verbosity": -1, "metric": "none",
                              "tpu_telemetry_memory": memory_mode},
                      train_set=ds)
    g = bst._gbdt
    assert g._fused_iter is not None
    lowered = g._fused_iter.lower(g.bins_dev, g.scores, g._full_mask,
                                  g._fmask_static, 0.1, None, None, None,
                                  None, None)
    return lowered.as_text()


def test_off_mode_bitwise_program_identity():
    """tpu_telemetry_memory=off vs census: equal lowered-HLO text — memory
    accounting is host-side observation at span boundaries, never part of
    a traced program (the PR-9 pin, extended to the new knob)."""
    off = _fused_lowered_text("off")
    census = _fused_lowered_text("census")
    assert off == census


def test_census_one_dispatch_with_memory_armed(tmp_path):
    """The fused census stays 1.0 dispatches/iter WITH memory tracking
    armed (census mode + a live JSONL sink): watermark reads are never
    dispatches (acceptance criterion)."""
    from tools.profile_iter import nonfused_dispatch_census
    telemetry.set_memory_mode("census")
    telemetry.configure_log(str(tmp_path / "census.jsonl"))
    try:
        blobs = nonfused_dispatch_census(rows=2048, iters=2, num_leaves=7,
                                         paths=("fused",))
    finally:
        telemetry.close_log()
    assert blobs[0]["used_fused"] is True
    assert blobs[0]["dispatches_per_iter"] == 1.0, blobs[0]


# ------------------------------------------------------------------ census
def test_census_math_on_synthetic_arrays():
    arrays = [jnp.zeros((4, 4), jnp.float32) for _ in range(3)]
    arrays.append(jnp.zeros((256,), jnp.int8))
    c = memory.live_buffer_census(arrays=arrays)
    assert c["total_arrays"] == 4
    assert c["total_bytes"] == 3 * 64 + 256
    assert c["distinct_shapes"] == 2
    g0, g1 = c["groups"]
    # largest group first
    assert g0 == {"shape": [256], "dtype": "int8", "count": 1,
                  "bytes": 256}
    assert g1 == {"shape": [4, 4], "dtype": "float32", "count": 3,
                  "bytes": 192}
    assert c["truncated"] == 0
    json.dumps(c)


def test_census_top_truncation():
    arrays = [jnp.zeros((i + 1,), jnp.float32) for i in range(6)]
    c = memory.live_buffer_census(arrays=arrays, top=2)
    assert len(c["groups"]) == 2 and c["truncated"] == 4
    assert c["distinct_shapes"] == 6
    # totals cover EVERYTHING, not just the kept groups
    assert c["total_bytes"] == 4 * sum(range(1, 7))


def test_process_census_sees_live_arrays():
    # collect first: cyclic garbage from earlier tests (e.g. serve plans,
    # whose jitted closures capture the plan) still shows in
    # jax.live_arrays() until a gen-2 GC and can crowd the truncated
    # top-groups list — the pin is about arrays actually HELD live.
    import gc
    gc.collect()
    keep = jnp.zeros((128, 128), jnp.float32)      # 64 KiB, held live
    c = memory.live_buffer_census()
    assert c["total_bytes"] >= keep.nbytes
    assert any(g["shape"] == [128, 128] and g["dtype"] == "float32"
               for g in c["groups"]), c["groups"][:4]


# ------------------------------------------------------- device stats path
def test_device_stats_graceful_none_on_cpu():
    """CPU jax reports no allocator stats — the snapshot must be None,
    never an exception (the graceful-None contract; on a real TPU the
    same call returns bytes_in_use/peak_bytes_in_use)."""
    stats = memory.device_memory_stats()
    if jax.default_backend() == "cpu":
        assert stats is None
    else:   # live accelerator: the dict contract
        assert stats is not None and stats["bytes_in_use"] >= 0


def test_host_rss_watermark_positive_and_resettable():
    ok = memory.MemoryTracker.reset_host_peak()
    v = memory.MemoryTracker.host_peak_rss_mb(use_hwm=ok)
    assert v > 0
    # module-level helper publishes the gauge
    assert telemetry.host_peak_rss_mb() > 0
    assert telemetry.registry().gauge(
        "memory.host_peak_rss_mb").value > 0


# -------------------------------------------------------------- span hook
def test_tracked_span_emits_watermark_with_positive_delta(tmp_path):
    log = str(tmp_path / "mem.jsonl")
    telemetry.set_memory_mode("census")
    telemetry.configure_log(log)
    big = None
    try:
        with telemetry.span("memtest/alloc", track_memory=True):
            big = jnp.zeros((512, 512), jnp.float32)   # 1 MiB, kept live
            big.block_until_ready()
    finally:
        telemetry.close_log()
    events = [json.loads(line) for line in open(log)]
    wm = [e for e in events if e["kind"] == "memory.watermark"]
    assert len(wm) == 1
    e = wm[0]
    assert e["span"] == "memtest/alloc"
    # census mode: live-buffer accounting works even where device stats
    # are None (CPU) — the allocation's bytes must show in the delta
    assert e["live_delta_bytes"] >= big.nbytes
    assert e["live_bytes"] >= big.nbytes
    assert e["host_peak_rss_mb"] > 0
    assert isinstance(e["census"], list) and e["census"]
    if jax.default_backend() == "cpu":
        assert e["bytes_in_use"] is None and e["peak_bytes"] is None
    # gauges landed too
    assert telemetry.registry().gauge("memory.live_bytes").value \
        >= big.nbytes


def test_off_mode_tracked_span_emits_nothing(tmp_path):
    log = str(tmp_path / "off.jsonl")
    telemetry.configure_log(log)      # mode stays "off" (fixture default)
    try:
        with telemetry.span("memtest/off", track_memory=True):
            jnp.zeros((64,), jnp.float32).block_until_ready()
    finally:
        telemetry.close_log()
    kinds = [json.loads(line)["kind"] for line in open(log)]
    assert "memory.watermark" not in kinds


def test_train_sites_tracked_and_train_end_rss(tmp_path):
    """An armed training run brackets its span sites (pack dispatch /
    fused iter / checkpoint capture) with watermark events, dataset
    construction is tracked, and train.end carries host_peak_rss_mb."""
    log = str(tmp_path / "run.jsonl")
    X, y = _data(1200)
    lgb.train({"objective": "binary", "num_leaves": 7, "verbosity": -1,
               "metric": "none", "tpu_telemetry_log": log,
               "tpu_telemetry_memory": "watermark",
               "checkpoint_interval": 2,
               "checkpoint_dir": str(tmp_path / "ckpt")},
              lgb.Dataset(X, label=y), 4)
    events = [json.loads(line) for line in open(log)]
    spans = {e["span"] for e in events if e["kind"] == "memory.watermark"}
    assert "checkpoint/capture" in spans, spans
    assert any(s.startswith("train/") for s in spans), spans
    end = [e for e in events if e["kind"] == "train.end"][-1]
    assert end["host_peak_rss_mb"] > 0


def test_construct_arms_from_its_own_params(tmp_path):
    """Dataset construction runs BEFORE the GBDT constructor or the
    engine session ever sees the config, so construct() arms the mode
    from its own merged params (explicit-params rule) — no caller-side
    set_memory_mode needed for the run's own training set to be
    tracked."""
    log = str(tmp_path / "construct.jsonl")
    X, y = _data(900)
    telemetry.configure_log(log)
    try:
        lgb.Dataset(X, label=y).construct(
            {"objective": "binary", "verbosity": -1,
             "tpu_telemetry_memory": "census"})
    finally:
        telemetry.close_log()
    assert memory.memory_mode() == "census"   # armed by construct itself
    events = [json.loads(line) for line in open(log)]
    spans = {e["span"] for e in events if e["kind"] == "memory.watermark"}
    assert "data/construct" in spans, spans


# ------------------------------------------------------- compile telemetry
def test_compile_emits_event_and_counters(tmp_path):
    log = str(tmp_path / "compile.jsonl")
    reg = telemetry.registry()
    before = reg.counter("compile.count").value
    telemetry.configure_log(log)
    try:
        fn = telemetry.watch_compiles(jax.jit(lambda a: a * 2 + 1),
                                      "test/prog")
        fn(jnp.ones((16,), jnp.float32))            # compiles
        fn(jnp.ones((16,), jnp.float32))            # cache hit
        fn(jnp.ones((32,), jnp.float32))            # new shape: compiles
    finally:
        telemetry.close_log()
    assert reg.counter("compile.count").value == before + 2
    events = [json.loads(line) for line in open(log)]
    ce = [e for e in events if e["kind"] == "compile.end"]
    assert len(ce) == 2
    assert all(e["label"] == "test/prog" and e["seconds"] > 0
               for e in ce)
    # the report tool aggregates them
    from tools.telemetry_report import compile_rows
    rows = compile_rows(events)
    assert rows and rows[0][0] == "test/prog" and rows[0][1] == 2


def test_memory_analysis_summary_from_compiled():
    compiled = jax.jit(lambda a: a @ a).lower(
        jnp.ones((8, 8), jnp.float32)).compile()
    summary = memory.memory_analysis_summary(compiled)
    assert summary is not None
    assert summary.get("argument_size_in_bytes", 0) > 0
    assert all(isinstance(v, int) for v in summary.values())


def test_aot_compile_event_carries_memory_analysis(tmp_path):
    """The profile/train_step AOT path holds the compiled object, so its
    compile.end event is the one that carries the memory_analysis byte
    summary the jit seam cannot produce."""
    from tools.profile_iter import train_step_memory_analysis
    log = str(tmp_path / "aot.jsonl")
    X, y = _data(600)
    bst = lgb.Booster(params={"objective": "binary", "num_leaves": 7,
                              "verbosity": -1, "metric": "none"},
                      train_set=lgb.Dataset(X, label=y))
    bst.update()
    telemetry.configure_log(log)
    try:
        ma = train_step_memory_analysis(bst)
    finally:
        telemetry.close_log()
    assert "error" not in ma and "unavailable" not in ma, ma
    events = [json.loads(line) for line in open(log)]
    ce = [e for e in events if e["kind"] == "compile.end"
          and e["label"] == "profile/train_step"]
    assert len(ce) == 1
    assert ce[0]["memory_analysis"] == ma


# ------------------------------------------------------------ bench block
def test_bench_memory_block_schema():
    import bench
    X, y = _data(600)
    bst = lgb.Booster(params={"objective": "binary", "num_leaves": 7,
                              "verbosity": -1, "metric": "none"},
                      train_set=lgb.Dataset(X, label=y))
    bst.update()
    blk = bench._memory_block(bst)
    assert "error" not in blk, blk
    assert set(blk) >= {"mode", "device", "live_buffers", "compile",
                        "host_peak_rss_mb", "memory_analysis"}
    if jax.default_backend() == "cpu":
        assert blk["device"] is None
    lb = blk["live_buffers"]
    assert lb["total_bytes"] > 0 and lb["groups"]
    assert blk["compile"]["count"] >= 0
    assert blk["compile"]["seconds"] >= 0.0
    assert blk["host_peak_rss_mb"] > 0
    ma = blk["memory_analysis"]
    assert "error" not in ma, ma
    json.dumps(blk)


def test_memory_report_tool_section(tmp_path):
    """CLI smoke: --memory renders the watermark and compile tables from
    a real training artifact (subprocess, like the other tools)."""
    import subprocess
    import sys
    log = str(tmp_path / "run.jsonl")
    X, y = _data(900)
    lgb.train({"objective": "binary", "num_leaves": 7, "verbosity": -1,
               "metric": "none", "tpu_telemetry_log": log,
               "tpu_telemetry_memory": "census"},
              lgb.Dataset(X, label=y), 3)
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools",
                                      "telemetry_report.py"),
         "--memory", log], capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stderr
    assert "memory watermarks" in proc.stdout
    assert "compiles" in proc.stdout
    assert "memory.watermark" in proc.stdout   # event counts table


# ------------------------------------------------------- serve plan bytes
def test_serve_plan_bytes_and_cache_byte_gauges():
    from lightgbm_tpu import serve
    from lightgbm_tpu.serve.plan import cache_stats, clear_plan_cache
    X, y = _data(600)
    bst = lgb.train({"objective": "binary", "num_leaves": 15,
                     "verbosity": -1, "metric": "none"},
                    lgb.Dataset(X, label=y), 3)
    clear_plan_cache()
    pred = serve.Predictor(bst, raw_score=True)
    out = pred.predict(X[:32])
    assert out.shape[0] == 32
    plan = pred.plan
    assert plan.plan_bytes > 0
    snap = pred.metrics_snapshot()
    assert snap["plan_bytes"] == plan.plan_bytes
    stats = cache_stats()
    assert stats["bytes"] >= plan.plan_bytes and stats["size"] >= 1
    assert snap["plan_cache"]["bytes"] == stats["bytes"]
    reg = telemetry.registry()
    assert reg.gauge("serve.plan_bytes").value == plan.plan_bytes
    assert reg.gauge("serve.plan_cache_bytes").value == stats["bytes"]
    text = pred.metrics.render_prometheus(plan=plan)
    assert "lgbm_tpu_serve_plan_bytes " in text
    assert "lgbm_tpu_serve_plan_cache_bytes " in text
    clear_plan_cache()
    assert reg.gauge("serve.plan_cache_bytes").value == 0
    # the per-plan gauge tracks the MRU cached plan — an evicted/cleared
    # pack's bytes never linger
    assert reg.gauge("serve.plan_bytes").value == 0


def test_serve_planless_snapshot_keeps_bytes_keys():
    from lightgbm_tpu.serve.metrics import ServeMetrics
    m = ServeMetrics()
    snap = m.snapshot()
    assert snap["plan_bytes"] is None
    text = m.render_prometheus()
    assert "lgbm_tpu_serve_plan_bytes NaN" in text
    assert "lgbm_tpu_serve_plan_cache_bytes NaN" in text
