"""sklearn wrapper tests (reference: tests/python_package_test/test_sklearn.py)."""

import numpy as np
from sklearn.datasets import make_classification, make_regression
from sklearn.model_selection import train_test_split

import lightgbm_tpu as lgb


def test_regressor():
    X, y = make_regression(n_samples=1000, n_features=8, noise=0.1,
                           random_state=0)
    model = lgb.LGBMRegressor(n_estimators=30, min_child_samples=5)
    model.fit(X, y)
    pred = model.predict(X)
    assert np.mean((y - pred) ** 2) < 0.1 * y.var()
    assert model.n_features_ == 8
    assert len(model.feature_importances_) == 8


def test_classifier_binary():
    X, y = make_classification(n_samples=1200, n_features=10, random_state=1)
    model = lgb.LGBMClassifier(n_estimators=30)
    model.fit(X, y)
    proba = model.predict_proba(X)
    assert proba.shape == (1200, 2)
    np.testing.assert_allclose(proba.sum(axis=1), 1.0, rtol=1e-6)
    pred = model.predict(X)
    assert (pred == y).mean() > 0.9
    assert set(model.classes_) == {0, 1}


def test_classifier_multiclass_string_labels():
    X, y_int = make_classification(n_samples=1200, n_features=10,
                                   n_informative=8, n_classes=3,
                                   random_state=2)
    labels = np.array(["cat", "dog", "fish"])[y_int]
    model = lgb.LGBMClassifier(n_estimators=20)
    model.fit(X, labels)
    pred = model.predict(X)
    assert set(pred) <= {"cat", "dog", "fish"}
    assert (pred == labels).mean() > 0.8
    assert model.n_classes_ == 3


def test_classifier_eval_set_early_stopping():
    X, y = make_classification(n_samples=2000, n_features=10, random_state=3)
    Xtr, Xva, ytr, yva = train_test_split(X, y, random_state=0)
    model = lgb.LGBMClassifier(n_estimators=200, learning_rate=0.3)
    model.fit(Xtr, ytr, eval_set=[(Xva, yva)],
              callbacks=[lgb.early_stopping(5, verbose=False)])
    assert model.best_iteration_ > 0


def test_ranker():
    rng = np.random.RandomState(4)
    n_q, per_q = 40, 15
    X = rng.randn(n_q * per_q, 8)
    y = np.zeros(n_q * per_q, np.int64)
    for q in range(n_q):
        sl = slice(q * per_q, (q + 1) * per_q)
        ranks = np.argsort(np.argsort(X[sl, 0]))
        y[sl] = np.minimum(4, ranks * 5 // per_q)
    model = lgb.LGBMRanker(n_estimators=20, min_child_samples=5)
    model.fit(X, y, group=np.full(n_q, per_q))
    pred = model.predict(X)
    corr = np.corrcoef(pred, X[:, 0])[0, 1]
    assert corr > 0.5


def test_get_set_params():
    model = lgb.LGBMRegressor(num_leaves=63, custom_param=7)
    params = model.get_params()
    assert params["num_leaves"] == 63
    assert params["custom_param"] == 7
    model.set_params(num_leaves=15)
    assert model.num_leaves == 15


def test_class_weight_balanced():
    X, y = make_classification(n_samples=1500, n_features=10, weights=[0.9],
                               random_state=5)
    model = lgb.LGBMClassifier(n_estimators=20, class_weight="balanced")
    model.fit(X, y)
    pred = model.predict(X)
    # balanced weighting should recover a reasonable recall on the minority
    minority_recall = (pred[y == 1] == 1).mean()
    assert minority_recall > 0.6


def test_ranker_eval_at_and_init_model():
    rng = np.random.RandomState(0)
    n_q, per_q = 40, 10
    n = n_q * per_q
    X = rng.randn(n, 5)
    y = (X[:, 0] > 0.3).astype(int) + (X[:, 1] > 0.5).astype(int)
    group = np.full(n_q, per_q)
    rk = lgb.LGBMRanker(n_estimators=5, num_leaves=7, min_child_samples=5)
    rk.fit(X, y, group=group, eval_at=(3,),
           eval_set=[(X, y)], eval_group=[group])
    assert any("ndcg@3" in m for m in rk.evals_result_["valid_0"])

    # continuation through the sklearn surface
    clf = lgb.LGBMClassifier(n_estimators=3, num_leaves=7)
    Xc, yc = X, (y > 0).astype(int)
    clf.fit(Xc, yc)
    clf2 = lgb.LGBMClassifier(n_estimators=2, num_leaves=7)
    clf2.fit(Xc, yc, init_model=clf.booster_)
    assert clf2.booster_.num_trees() >= 2


def test_fitted_attribute_surface():
    """Reference LGBMModel exposes best_score_/objective_/n_estimators_/
    n_iter_/feature_name_/feature_names_in_ on fitted estimators."""
    X, y = make_classification(n_samples=800, n_features=8, random_state=0)
    est = lgb.LGBMClassifier(n_estimators=12, num_leaves=7)
    est.fit(X, y, eval_set=[(X, y)], eval_metric="binary_logloss")
    assert est.objective_ == "binary"
    assert est.n_estimators_ == 12 and est.n_iter_ == 12
    # objective supplied through an alias kwarg must be reported (not the
    # class default)
    X2, y2 = make_regression(n_samples=300, n_features=4, random_state=2)
    reg = lgb.LGBMRegressor(n_estimators=3, application="poisson")
    reg.fit(X2, np.abs(y2) + 1.0)
    assert reg.objective_ == "poisson"
    assert len(est.feature_name_) == 8
    assert est.feature_names_in_.shape == (8,)
    bs = est.best_score_
    assert "valid_0" in bs and "binary_logloss" in bs["valid_0"]
    assert bs["valid_0"]["binary_logloss"] == \
        est.evals_result_["valid_0"]["binary_logloss"][-1]


def test_best_score_tracks_early_stopping():
    X, y = make_classification(n_samples=2000, n_features=10, random_state=1)
    est = lgb.LGBMClassifier(n_estimators=300, learning_rate=0.3)
    est.fit(X[:1500], y[:1500], eval_set=[(X[1500:], y[1500:])],
            eval_metric="binary_logloss",
            callbacks=[lgb.early_stopping(5, verbose=False)])
    assert est.n_estimators_ == est.best_iteration_ > 0
    curve = est.evals_result_["valid_0"]["binary_logloss"]
    assert est.best_score_["valid_0"]["binary_logloss"] == \
        curve[est.best_iteration_ - 1]


def test_classifier_alias_objective_multiclass():
    """application='multiclassova' on 3-class data must train OVA, not be
    silently replaced by the multiclass default (alias suppression must
    apply to the classifier path too); a None-valued alias must be inert."""
    X, y = make_classification(n_samples=900, n_features=8, n_informative=6,
                               n_classes=3, random_state=5)
    est = lgb.LGBMClassifier(n_estimators=5, application="multiclassova")
    est.fit(X, y)
    assert est.objective_ == "multiclassova"
    est2 = lgb.LGBMClassifier(n_estimators=5, application=None)
    est2.fit(X, y)
    assert est2.objective_ == "multiclass"
