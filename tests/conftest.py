"""Test harness config: hermetic CPU backend with 8 virtual devices so sharding
tests run without TPU hardware (mirrors the reference's localhost mock-cluster
pattern, tests/distributed/_test_distributed.py)."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import _hermetic  # noqa: E402

_hermetic.force_cpu(8)

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.RandomState(42)
