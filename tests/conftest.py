"""Test harness config: hermetic CPU backend with 8 virtual devices so sharding
tests run without TPU hardware (mirrors the reference's localhost mock-cluster
pattern, tests/distributed/_test_distributed.py)."""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

# The environment's PJRT plugin boot (sitecustomize) may force
# jax_platforms to the accelerator; tests are hermetic on CPU.
jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.RandomState(42)
