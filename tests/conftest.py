"""Test harness config: hermetic CPU backend with 8 virtual devices so sharding
tests run without TPU hardware (mirrors the reference's localhost mock-cluster
pattern, tests/distributed/_test_distributed.py)."""

import faulthandler
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import _hermetic  # noqa: E402

_hermetic.force_cpu(8)

# A wedged dispatch must leave a traceback, not a silent timeout kill:
# enable faulthandler here for any non-pytest import of this harness, and
# pytest.ini's faulthandler_timeout arms the per-test dump (the builtin
# faulthandler plugin re-registers per test).  SIGTERM also dumps — the
# tier-1 runner's `timeout` sends SIGTERM before SIGKILL, so even a
# whole-run overrun names the test it died in.
faulthandler.enable()
try:
    import signal

    faulthandler.register(signal.SIGTERM, chain=True)
except (AttributeError, ValueError, OSError):
    pass  # platforms without signal support keep the plain enable

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.RandomState(42)


@pytest.fixture(autouse=True)
def _telemetry_sink_leak_guard(request):
    """Leak guard (ISSUE-9 satellite): a test that configures a telemetry
    JSONL sink and forgets to close it would stream every LATER test's
    events into its file.  Warn with the offender's nodeid and close the
    sink so the leak never crosses test boundaries.  Zero-cost when the
    telemetry module was never imported."""
    yield
    tel = sys.modules.get("lightgbm_tpu.telemetry")
    if tel is None:
        return
    sink = tel.active_sink()
    if sink is not None:
        sys.stderr.write(
            f"[telemetry leak] {request.node.nodeid} left JSONL sink "
            f"{sink.path!r} registered; closing it\n")
        tel.close_log()


def pytest_terminal_summary(terminalreporter, exitstatus, config):
    """One end-of-run line making the differential-coverage gap visible
    (VERDICT weak #3): without ``LGBM_REFERENCE_BIN`` every
    test_differential.py case skips silently, so a run can look green
    while the genuine-binary parity suite never executed."""
    if os.environ.get("LGBM_REFERENCE_BIN"):
        return
    stats = terminalreporter.stats

    def _count(key):
        return sum(1 for rep in stats.get(key, ())
                   if "test_differential.py" in getattr(rep, "nodeid", ""))

    skipped = _count("skipped")
    ran = _count("passed") + _count("failed") + _count("error")
    if skipped or ran:
        terminalreporter.write_line(
            f"differential vs genuine LightGBM: {ran} ran, {skipped} "
            "skipped — set LGBM_REFERENCE_BIN (build via "
            "tools/refbuild/build_reference.sh) to run them")
