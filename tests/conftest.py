"""Test harness config: hermetic CPU backend with 8 virtual devices so sharding
tests run without TPU hardware (mirrors the reference's localhost mock-cluster
pattern, tests/distributed/_test_distributed.py)."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import _hermetic  # noqa: E402

_hermetic.force_cpu(8)

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.RandomState(42)


def pytest_terminal_summary(terminalreporter, exitstatus, config):
    """One end-of-run line making the differential-coverage gap visible
    (VERDICT weak #3): without ``LGBM_REFERENCE_BIN`` every
    test_differential.py case skips silently, so a run can look green
    while the genuine-binary parity suite never executed."""
    if os.environ.get("LGBM_REFERENCE_BIN"):
        return
    stats = terminalreporter.stats

    def _count(key):
        return sum(1 for rep in stats.get(key, ())
                   if "test_differential.py" in getattr(rep, "nodeid", ""))

    skipped = _count("skipped")
    ran = _count("passed") + _count("failed") + _count("error")
    if skipped or ran:
        terminalreporter.write_line(
            f"differential vs genuine LightGBM: {ran} ran, {skipped} "
            "skipped — set LGBM_REFERENCE_BIN (build via "
            "tools/refbuild/build_reference.sh) to run them")
