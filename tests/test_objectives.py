"""Objective gradient checks against finite differences of the loss."""

import jax.numpy as jnp
import numpy as np
import pytest

from lightgbm_tpu.config import Config
from lightgbm_tpu.objectives import create_objective


def _finite_diff_grad(loss_fn, score, eps=1e-4):
    g = np.zeros_like(score)
    for i in range(len(score)):
        s1, s2 = score.copy(), score.copy()
        s1[i] += eps
        s2[i] -= eps
        g[i] = (loss_fn(s1) - loss_fn(s2)) / (2 * eps)
    return g


def _check(objective_name, label, loss_fn, extra_params=None, n=20, rtol=1e-2):
    params = {"objective": objective_name}
    params.update(extra_params or {})
    cfg = Config(params)
    obj = create_objective(cfg)
    obj.init(label, None, None, cfg)
    rng = np.random.RandomState(0)
    score = rng.randn(n).astype(np.float64) * 0.5
    grad, _ = obj.get_gradients(jnp.asarray(score, jnp.float32))
    fd = _finite_diff_grad(loss_fn, score)
    np.testing.assert_allclose(np.asarray(grad), fd, rtol=rtol, atol=1e-3)


def test_l2_gradient():
    rng = np.random.RandomState(1)
    y = rng.randn(20)
    # reference convention: grad = score - label (0.5*(s-y)^2 loss)
    _check("regression", y, lambda s: 0.5 * np.sum((s - y) ** 2))


def test_binary_gradient():
    rng = np.random.RandomState(2)
    y = (rng.rand(20) > 0.5).astype(np.float64)

    def loss(s):
        p = 1 / (1 + np.exp(-s))
        return -np.sum(y * np.log(p) + (1 - y) * np.log(1 - p))

    _check("binary", y, loss)


def test_poisson_gradient():
    rng = np.random.RandomState(3)
    y = rng.poisson(2.0, 20).astype(np.float64)
    _check("poisson", y, lambda s: np.sum(np.exp(s) - y * s),
           extra_params={"poisson_max_delta_step": 0.0})


def test_gamma_gradient():
    rng = np.random.RandomState(4)
    y = rng.gamma(2.0, 1.0, 20) + 0.1
    _check("gamma", y, lambda s: np.sum(y * np.exp(-s) + s))


def test_tweedie_gradient():
    rng = np.random.RandomState(5)
    y = rng.gamma(2.0, 1.0, 20)
    rho = 1.5
    _check("tweedie", y, lambda s: np.sum(
        -y * np.exp((1 - rho) * s) / (1 - rho) + np.exp((2 - rho) * s) / (2 - rho)))


def test_fair_gradient():
    rng = np.random.RandomState(6)
    y = rng.randn(20)
    c = 1.0
    _check("fair", y, lambda s: np.sum(
        c ** 2 * (np.abs(s - y) / c - np.log1p(np.abs(s - y) / c))))


def test_quantile_gradient_direction():
    cfg = Config({"objective": "quantile", "alpha": 0.9})
    obj = create_objective(cfg)
    y = np.zeros(4)
    obj.init(y, None, None, cfg)
    g, _ = obj.get_gradients(jnp.asarray([1.0, -1.0, 2.0, -2.0]))
    g = np.asarray(g)
    assert (g[[0, 2]] > 0).all() and (g[[1, 3]] < 0).all()
    assert abs(g[0]) == pytest.approx(0.1, rel=1e-5)
    assert abs(g[1]) == pytest.approx(0.9, rel=1e-5)


def test_multiclass_softmax_gradient():
    rng = np.random.RandomState(7)
    n, k = 10, 3
    y = rng.randint(0, k, n)
    cfg = Config({"objective": "multiclass", "num_class": k})
    obj = create_objective(cfg)
    obj.init(y, None, None, cfg)
    score = rng.randn(n, k)
    grad, hess = obj.get_gradients(jnp.asarray(score, jnp.float32))
    # oracle: softmax - onehot
    e = np.exp(score - score.max(1, keepdims=True))
    p = e / e.sum(1, keepdims=True)
    onehot = np.eye(k)[y]
    np.testing.assert_allclose(np.asarray(grad), p - onehot, rtol=1e-4,
                               atol=1e-5)
    assert (np.asarray(hess) > 0).all()


def test_boost_from_score():
    cfg = Config({"objective": "binary"})
    obj = create_objective(cfg)
    y = np.array([1, 1, 1, 0])
    obj.init(y, None, None, cfg)
    assert obj.boost_from_score() == pytest.approx(np.log(3.0), rel=1e-6)

    cfg = Config({"objective": "regression"})
    obj = create_objective(cfg)
    obj.init(np.array([1.0, 2.0, 3.0]), None, None, cfg)
    assert obj.boost_from_score() == pytest.approx(2.0)


def test_weights_scale_gradients():
    cfg = Config({"objective": "regression"})
    obj = create_objective(cfg)
    y = np.array([0.0, 0.0])
    w = np.array([1.0, 5.0])
    obj.init(y, w, None, cfg)
    g, h = obj.get_gradients(jnp.asarray([1.0, 1.0]))
    assert np.asarray(g)[1] == pytest.approx(5 * np.asarray(g)[0])
    assert np.asarray(h)[1] == pytest.approx(5 * np.asarray(h)[0])
