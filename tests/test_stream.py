"""Out-of-core streaming training (ISSUE-13, lightgbm_tpu/stream/,
docs/STREAMING.md).

Bitwise discipline: the streamed grower is the mask-layout body driven
chunk-by-chunk, with chunked histogram accumulation SEEDED
(``histogram_from_vals(init=...)``) so the cross-chunk fold replays the
in-core add order — streamed trees pin BITWISE-identical to in-core
training with MESSY multi-iteration fp32 gradients (no exact-sum crutch)
on the CPU backend's scatter impl, and quantized int32 histograms are
unconditionally exact.  The pins run the full engine round loop on both
sides (masks, key folds, shrink epilogue, degenerate stops included).
"""

import os
import signal
import subprocess
import sys

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu import engine
from lightgbm_tpu.basic import Booster, Dataset
from lightgbm_tpu.serialization import FrameCorruptError
from lightgbm_tpu.stream import (ChunkPlan, ContinualSession,
                                 ResidencyManager, ShardedDataset,
                                 StreamDataset, StreamTrainer, append_rows,
                                 dataset_to_shards, refit_streamed,
                                 train_streamed)

pytestmark = pytest.mark.stream

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

N, F = 4096, 12
BASE_PARAMS = {
    "objective": "binary", "num_leaves": 15, "learning_rate": 0.1,
    "verbosity": -1, "min_data_in_leaf": 5, "seed": 7,
}
# tiny budget => 8 shards of 512 rows stream as multiple chunks
TINY_BUDGET_MB = 0.02


def _data(seed=11, n=N, f=F):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, f)
    X[rng.rand(n) < 0.03, 4] = np.nan
    y = (X[:, 0] + 0.6 * X[:, 1] * X[:, 2] + 0.2 * rng.randn(n) > 0
         ).astype(np.float64)
    return X, y


@pytest.fixture(scope="module")
def data():
    return _data()


@pytest.fixture(scope="module")
def store(data, tmp_path_factory):
    X, y = data
    path = str(tmp_path_factory.mktemp("stream") / "store")
    # the public surface: Dataset.to_shards (ISSUE-13 tentpole API)
    return Dataset(X, label=y, params=BASE_PARAMS).to_shards(
        path, rows_per_shard=512, params=BASE_PARAMS)


def _trees_only(bst) -> str:
    """Model string minus importances/params (streamed runs record the
    tpu_stream_* knobs; everything above that line must be bitwise)."""
    return bst.model_to_string().split("\nfeature_importances")[0]


def _stream_params(extra=None, budget=TINY_BUDGET_MB):
    p = dict(BASE_PARAMS, tpu_stream_budget_mb=budget)
    p.update(extra or {})
    return p


# ------------------------------------------------------------------- store
def test_store_roundtrip(data, store):
    X, y = data
    td = Dataset(X, label=y, params=BASE_PARAMS).construct(BASE_PARAMS)
    assert store.num_data == N and store.num_features == F
    assert store.num_shards == 8
    whole = np.concatenate([np.asarray(b) for _lo, _hi, b
                            in store.iter_shards()])
    np.testing.assert_array_equal(whole, td.binned.bins)
    np.testing.assert_array_equal(store.label, td.label)
    # mmap and checksum-validated reads agree
    np.testing.assert_array_equal(np.asarray(store.shard_bins(3, mmap=True)),
                                  store.shard_bins(3, mmap=False))
    from lightgbm_tpu.stream import bin_identity
    assert store.bin_identity == bin_identity(td.binned.mappers,
                                              td.binned.max_num_bins)
    assert store.verify() == []


def test_store_corrupt_frame_detected_and_rebuilt(data, tmp_path):
    """Corrupt-frame fallback: damage is DETECTED at read (sha256 frame),
    reported by verify(), and ``to_shards(resume=True)`` rebuilds exactly
    the damaged shard while keeping valid ones."""
    X, y = data
    ds = Dataset(X, label=y, params=BASE_PARAMS)
    st = dataset_to_shards(ds, str(tmp_path / "s"), rows_per_shard=512,
                           params=BASE_PARAMS)
    victim = os.path.join(st.path, st.manifest.shards[2])
    blob = bytearray(open(victim, "rb").read())
    blob[100] ^= 0xFF
    open(victim, "wb").write(bytes(blob))
    with pytest.raises(FrameCorruptError):
        st.shard_bins(2, mmap=False)
    assert st.verify() == [2]
    # truncation is caught even on the mmap fast path (length check)
    with open(victim, "r+b") as fh:
        fh.truncate(64)
    with pytest.raises(FrameCorruptError):
        st.shard_bins(2, mmap=True)
    st2 = dataset_to_shards(ds, str(tmp_path / "s"), rows_per_shard=512,
                            params=BASE_PARAMS, resume=True)
    assert st2.verify() == []
    np.testing.assert_array_equal(np.asarray(st2.shard_bins(2)),
                                  st.shard_bins(2, mmap=False))


def test_store_open_refuses_torn_build(tmp_path):
    with pytest.raises(Exception, match="not a shard store"):
        ShardedDataset.open(str(tmp_path / "nothing"))


def test_store_identity_mismatch_refused(data, store, tmp_path):
    X, y = data
    other = dataset_to_shards(
        Dataset(X, label=y, params=dict(BASE_PARAMS, max_bin=63)),
        str(tmp_path / "o"), rows_per_shard=1024,
        params=dict(BASE_PARAMS, max_bin=63))
    with pytest.raises(Exception, match="identity mismatch"):
        store.assert_compatible(other.bin_identity)


def test_append_rows_rebins_through_frozen_mappers(data, tmp_path):
    X, y = data
    ds = Dataset(X, label=y, params=BASE_PARAMS)
    st = dataset_to_shards(ds, str(tmp_path / "a"), rows_per_shard=512,
                           params=BASE_PARAMS)
    X2, y2 = _data(seed=99, n=700)
    st2 = append_rows(st, X2, y2)
    assert st2.num_data == N + 700
    assert st2.bin_identity == st.bin_identity
    td = Dataset(X, label=y, params=BASE_PARAMS).construct(BASE_PARAMS)
    expect = td.binned.apply(X2)
    got = np.concatenate([np.asarray(b) for _l, _h, b
                          in st2.iter_shards()])[N:]
    np.testing.assert_array_equal(got, expect)
    np.testing.assert_array_equal(st2.label[N:], y2)


# -------------------------------------------------------------- residency
def test_chunk_plan_budget_validation(store):
    with pytest.raises(ValueError, match="budget"):
        ChunkPlan(store, budget_bytes=1024)   # one 512-row shard > half
    plan = ChunkPlan(store, budget_bytes=int(TINY_BUDGET_MB * 2 ** 20))
    assert plan.num_chunks > 1
    assert plan.chunk_rows * F * 1 == plan.chunk_bytes


def test_residency_sweep_budget_and_prefetch(store):
    budget = int(TINY_BUDGET_MB * 2 ** 20)
    with ResidencyManager(store, budget) as rm:
        seen_rows = 0
        for _ci, lo, hi, arr in rm.sweep():
            assert rm.live_bytes() <= budget
            seen_rows += hi - lo
        assert seen_rows == N
        for _ in rm.sweep():
            pass
    s = rm.stats()
    assert s["peak_bytes"] <= budget
    assert s["uploads"] == 2 * rm.plan.num_chunks
    assert s["prefetch_hits"] + s["prefetch_stalls"] == s["uploads"]
    assert s["live_bytes"] == 0          # every chunk evicted


def test_residency_gather_rows(store, data):
    X, y = data
    td = Dataset(X, label=y, params=BASE_PARAMS).construct(BASE_PARAMS)
    rm = ResidencyManager(store, 1 << 20, prefetch=False)
    idx = np.asarray([0, 511, 512, 1025, N - 1, 7])
    np.testing.assert_array_equal(rm.gather_rows(idx),
                                  td.binned.bins[idx])


# ------------------------------------------------- bitwise streamed pins
def _incore(params, X, y, rounds):
    return engine.train(dict(params), Dataset(X, label=y, params=params),
                        num_boost_round=rounds)


def test_streamed_bitwise_fp32_multichunk(data, store):
    """THE acceptance pin: streamed training at a budget ~40x smaller
    than the dataset's device footprint produces bitwise-identical trees
    to in-core training — messy multi-iteration fp32 gradients, engine
    round loop on both sides."""
    X, y = data
    rounds = 6
    ref = _incore(BASE_PARAMS, X, y, rounds)
    st = train_streamed(_stream_params(), store, num_boost_round=rounds)
    assert st._stream_stats["chunks"] > 1
    assert _trees_only(st) == _trees_only(ref)


@pytest.mark.parametrize("extra,label", [
    ({"use_quantized_grad": True}, "quantized"),
    ({"max_bin": 15}, "packed4"),
    ({"tpu_iter_pack": 4}, "iter_pack_k4"),
    ({"data_sample_strategy": "goss", "use_quantized_grad": True},
     "goss_quantized"),
    ({"use_quantized_grad": True, "max_bin": 15, "tpu_iter_pack": 4},
     "quantized_packed4_pack"),
])
def test_streamed_bitwise_matrix(data, tmp_path, extra, label):
    """Streamed == in-core across the composition matrix: quantized int8
    wire, 4-bit bin packing, iter-pack K=4 (streamed degrades to
    per-round — pack size is scheduling-only since PR 1, so the trees
    must STILL match bitwise), and device GOSS on the quantized wire
    (integer histograms make GOSS's amplified gradients exact; the fp32
    GOSS cell is pinned to 1 ULP in test_streamed_goss_fp32_ulp)."""
    X, y = data
    params = dict(BASE_PARAMS, num_leaves=7, **extra)
    store = dataset_to_shards(Dataset(X, label=y, params=params),
                              str(tmp_path / "m"), rows_per_shard=512,
                              params=params)
    rounds = 4
    ref = _incore(params, X, y, rounds)
    sp = _stream_params(extra={"num_leaves": 7, **extra})
    st = train_streamed(sp, store, num_boost_round=rounds)
    assert st._stream_stats["chunks"] > 1
    assert _trees_only(st) == _trees_only(ref), label


def _assert_structure_ulp(bst, ref, atol=0.0, rtol=3e-7):
    """Tree STRUCTURE (features/bins/children/routing) bitwise, leaf
    values within ~1 f32 ULP — the fp32-GOSS contract: amplified
    (inexact-product) gradients expose XLA's fusion-context-dependent
    rounding inside the split scan's stat reductions, which no
    re-implementation can replay across differently-shaped programs
    (quantized GOSS is bitwise; docs/STREAMING.md)."""
    a, b = bst._gbdt, ref._gbdt
    for k in range(a.num_class):
        for ta, tb in zip(a.dev_models[k], b.dev_models[k]):
            for fld in ("split_feature", "split_bin", "default_left",
                        "is_cat", "left_child", "right_child",
                        "num_leaves", "leaf_count"):
                np.testing.assert_array_equal(
                    np.asarray(getattr(ta, fld)),
                    np.asarray(getattr(tb, fld)), err_msg=fld)
            np.testing.assert_allclose(
                np.asarray(ta.leaf_value), np.asarray(tb.leaf_value),
                rtol=rtol, atol=atol)


def test_streamed_goss_fp32_ulp(data, tmp_path):
    """fp32 GOSS: identical structure/routing, leaf values within 1 ULP
    (see _assert_structure_ulp — the quantized GOSS cell in the matrix
    above is the bitwise pin)."""
    X, y = data
    params = dict(BASE_PARAMS, num_leaves=7, data_sample_strategy="goss")
    store = dataset_to_shards(Dataset(X, label=y, params=params),
                              str(tmp_path / "gf"), rows_per_shard=512,
                              params=params)
    rounds = 4
    ref = _incore(params, X, y, rounds)
    st = train_streamed(_stream_params(extra={"num_leaves": 7,
                                              "data_sample_strategy":
                                              "goss"}),
                        store, num_boost_round=rounds)
    _assert_structure_ulp(st, ref)


def test_streamed_goss_residency_mode(data, tmp_path):
    """Gradient-based residency: only the device-GOSS sampled slice is
    resident per iteration (compact gather + routing sweep); trees match
    in-core GOSS training bitwise on the (non-stochastic) quantized wire
    and to 1 ULP on fp32."""
    X, y = data
    params = dict(BASE_PARAMS, num_leaves=7,
                  data_sample_strategy="goss",
                  use_quantized_grad=True, stochastic_rounding=False)
    store = dataset_to_shards(Dataset(X, label=y, params=params),
                              str(tmp_path / "g"), rows_per_shard=512,
                              params=params)
    rounds = 4
    ref = _incore(params, X, y, rounds)
    sp = _stream_params(extra={"num_leaves": 7,
                               "data_sample_strategy": "goss",
                               "use_quantized_grad": True,
                               "stochastic_rounding": False,
                               "tpu_stream_residency": "goss"},
                        budget=0.1)
    sds = StreamDataset(store, params=sp)
    bst = Booster(params=sp, train_set=sds)
    tr = StreamTrainer(bst, store)
    assert tr.residency == "goss"
    for _ in range(rounds):
        tr.train_round()
    tr.close()
    _assert_structure_ulp(bst, ref)
    # the sampled slice really is the resident set: compact bytes cover
    # top_rate+other_rate of the rows, far under the full matrix
    assert 0 < tr.goss_resident_bytes < N * F


def test_streamed_degrade_reasons(data, store):
    """Unsupported compositions refuse with a clear reason instead of
    silently diverging."""
    X, y = data
    sp = _stream_params(extra={"linear_tree": True})
    with pytest.raises(ValueError, match="linear trees"):
        train_streamed(sp, store, num_boost_round=2)


# ----------------------------------------------------- budget via census
def test_budget_respected_live_buffer_census(data, store):
    """The residency invariant against the PR-10 live-buffer census: while
    a sweep holds a chunk, the census sees streaming buffers totalling at
    most the budget, and the FULL (N, F) matrix appears nowhere."""
    import gc

    from lightgbm_tpu.telemetry import live_buffer_census

    def _shape_bytes(census, shape):
        return sum(g["bytes"] for g in census["groups"]
                   if g["shape"] == shape)

    budget = int(TINY_BUDGET_MB * 2 ** 20)
    gc.collect()   # drop earlier tests' dead boosters from the live set
    with ResidencyManager(store, budget) as rm:
        chunk_shape = [rm.plan.chunk_rows, rm.plan.cols]
        base = live_buffer_census(top=200)
        base_chunk = _shape_bytes(base, chunk_shape)
        base_full = _shape_bytes(base, [N, F])
        for _ci, _lo, _hi, _arr in rm.sweep():
            census = live_buffer_census(top=200)
            stream_bytes = _shape_bytes(census, chunk_shape) - base_chunk
            assert 0 < stream_bytes <= budget
            # the full (N, F) matrix never lands on the device
            assert _shape_bytes(census, [N, F]) == base_full
    # and end-to-end training never exceeded it either (manager accounting)
    st = train_streamed(_stream_params(), store, num_boost_round=2)
    assert st._stream_stats["peak_bytes"] <= budget


# --------------------------------------------------- SIGKILL resume pin
_KILL_CHILD = r"""
import os, sys
sys.path.insert(0, os.environ["LGB_REPO"])
import _hermetic
_hermetic.force_cpu(1)
import numpy as np
import lightgbm_tpu as lgb
from lightgbm_tpu.stream import dataset_to_shards, train_streamed

rng = np.random.RandomState(0)
X = rng.rand(3072, 8)
y = (X[:, 0] + X[:, 1] > 1.0).astype(np.float64)
params = dict(objective="binary", num_leaves=7, seed=3, verbosity=-1,
              min_data_in_leaf=5, checkpoint_interval=4,
              checkpoint_keep=3, checkpoint_dir=sys.argv[1],
              tpu_stream_budget_mb=0.02)
store_dir = "store"
if not os.path.exists(os.path.join(store_dir, "manifest.json")):
    dataset_to_shards(lgb.Dataset(X, label=y, params=params), store_dir,
                      rows_per_shard=512, params=params)
resume = sys.argv[3] if len(sys.argv) > 3 else None
bst = train_streamed(params, store_dir, num_boost_round=12,
                     resume_from=resume)
bst.save_model(sys.argv[2])
"""


def _run_child(cwd, args, fault=None, timeout=420):
    from lightgbm_tpu.resilience import faults
    env = {k: v for k, v in os.environ.items()
           if k not in (faults.ENV_VAR, "JAX_PLATFORMS", "XLA_FLAGS")}
    env["LGB_REPO"] = REPO
    if fault:
        env[faults.ENV_VAR] = fault
    os.makedirs(cwd, exist_ok=True)
    return subprocess.run([sys.executable, "-c", _KILL_CHILD, *args],
                          env=env, cwd=cwd, capture_output=True, text=True,
                          timeout=timeout)


def test_sigkill_mid_stream_resume_byte_identical(tmp_path):
    """A continual trainer SIGKILLed mid-stream (fault seam, right after
    round 10 commits) resumes from the last checkpoint and the final
    model FILE is byte-identical to the uninterrupted run's."""
    from lightgbm_tpu.resilience import checkpoint
    golden = str(tmp_path / "golden.txt")
    resumed = str(tmp_path / "resumed.txt")
    cwd_full, cwd_kill = str(tmp_path / "full"), str(tmp_path / "kill")

    p = _run_child(cwd_full, ["ck", golden])
    assert p.returncode == 0, p.stderr[-2000:]
    p = _run_child(cwd_kill, ["ck", str(tmp_path / "never.txt")],
                   fault="kill_after_iter:10")
    assert p.returncode == -signal.SIGKILL, (p.returncode, p.stderr[-2000:])
    assert not os.path.exists(str(tmp_path / "never.txt"))
    assert [it for it, _p in checkpoint.list_snapshots(
        os.path.join(cwd_kill, "ck"))] == [8, 4]
    p = _run_child(cwd_kill, ["ck", resumed, "ck"])
    assert p.returncode == 0, p.stderr[-2000:]
    with open(golden, "rb") as a, open(resumed, "rb") as b:
        assert a.read() == b.read()


# ----------------------------------------------- continuation / continual
def test_streamed_continuation_matches_engine(data, store, tmp_path):
    """init_model continuation parity: the streamed continuation's init
    fold (bin-space f64 routing) reproduces engine.train's raw-space fold
    bitwise, so the continued trees match too."""
    X, y = data
    r1, r2 = 4, 3
    ref1 = _incore(BASE_PARAMS, X, y, r1)
    ref2 = engine.train(dict(BASE_PARAMS),
                        Dataset(X, label=y, params=BASE_PARAMS),
                        num_boost_round=r2, init_model=ref1)
    st1 = train_streamed(_stream_params(), store, num_boost_round=r1)
    st2 = train_streamed(_stream_params(), store, num_boost_round=r2,
                         init_model=st1)
    assert _trees_only(st2) == _trees_only(ref2)


def test_continual_session_ingest_train_refit(data, tmp_path):
    X, y = data
    params = dict(BASE_PARAMS, num_leaves=7)
    st = dataset_to_shards(Dataset(X, label=y, params=params),
                           str(tmp_path / "c"), rows_per_shard=512,
                           params=params)
    sess = ContinualSession(st, _stream_params(extra={"num_leaves": 7}))
    m1 = sess.train(3)
    assert m1._gbdt.iter_ == 3
    X2, y2 = _data(seed=5, n=600)
    sess.ingest(X2, y2)
    assert sess.store.num_data == N + 600
    m2 = sess.train(2, continue_training=True)
    # the chained model predicts with base + own trees
    pred = m2.predict(X[:64], raw_score=True)
    assert np.isfinite(pred).all()
    assert m2._gbdt.base_model is not None
    m3 = sess.train(3, continue_training=False)
    r = refit_streamed(m3, sess.store, decay_rate=0.5)
    assert r._gbdt._pred_version == m3._gbdt._pred_version + 1
    # structures identical, leaf values moved
    assert (np.asarray(r._gbdt.dev_models[0][0].split_feature)
            == np.asarray(m3._gbdt.dev_models[0][0].split_feature)).all()


def test_refit_streamed_matches_host_refit(data, tmp_path):
    """Streamed (per-shard) refit == the host refit path over the same
    rows: same leaf sums, same decay blend."""
    X, y = data
    params = dict(BASE_PARAMS, num_leaves=7)
    st = dataset_to_shards(Dataset(X, label=y, params=params),
                           str(tmp_path / "r"), rows_per_shard=512,
                           params=params)
    bst = _incore(params, X, y, 3)
    from lightgbm_tpu.refit import refit_booster
    want = refit_booster(bst, X, y, 0.7, params)
    got = refit_streamed(bst, st, decay_rate=0.7)
    for t_w, t_g in zip(want._gbdt.models[0], got._gbdt.models[0]):
        np.testing.assert_allclose(t_g.leaf_value, t_w.leaf_value,
                                   rtol=0, atol=0)


# ------------------------------------------------------- serve handoff
def test_continual_train_to_serve_swap_parity(data, tmp_path, monkeypatch):
    """The closing loop: retrain -> publish -> a RUNNING predictor serves
    the new model (zero restart), bitwise-parity with Booster.predict's
    device path (the serve parity contract — the native host traversal
    accumulates f64 and differs in ULPs by design), swaps counted, and
    (same architecture) zero fresh AOT compiles."""
    from lightgbm_tpu import serve
    monkeypatch.setenv("LIGHTGBM_TPU_NATIVE_PREDICT_MAX_ROWS", "0")
    X, y = data
    params = dict(BASE_PARAMS, num_leaves=7)
    st = dataset_to_shards(Dataset(X, label=y, params=params),
                           str(tmp_path / "p"), rows_per_shard=512,
                           params=params)
    cache_dir = str(tmp_path / "aot")
    sp = _stream_params(extra={"num_leaves": 7})
    sess = ContinualSession(st, sp)
    m1 = sess.train(3)
    predictor = serve.Predictor(m1, raw_score=True,
                                compile_cache=cache_dir)
    Xq = X[:256]
    out1 = predictor.predict(Xq)
    np.testing.assert_array_equal(out1, m1.predict(Xq, raw_score=True))
    # fresh retrain over the grown store lands without a restart
    sess.ingest(*_data(seed=21, n=512)[:2])
    m2 = sess.train(3, continue_training=False)
    sess.publish(predictor)
    out2 = predictor.predict(Xq)
    assert predictor.metrics.model_swaps == 1
    np.testing.assert_array_equal(out2, m2.predict(Xq, raw_score=True))
    assert not np.array_equal(out1, out2)
    # zero cold-start: the swapped plan's executables came from the AOT
    # cache (structural identity — same architecture, new values)
    aot = predictor.plan.aot_stats()
    assert aot["compiles"] == 0 and aot["hits"] >= 1


# ------------------------------------------------- satellites: RSS, telemetry
def test_to_shards_free_raw_data_bounds_host_rss(tmp_path):
    """Satellite: ``free_raw_data`` on the streaming path — the raw f64
    matrix is RELEASED once the binned representation exists, so the
    store build adds far less than another raw-matrix copy to host peak
    RSS (pinned as a same-process delta via MemoryTracker, the
    test_inputs idiom)."""
    from lightgbm_tpu.telemetry import MemoryTracker
    n, f = 200_000, 28
    rng = np.random.RandomState(0)
    X = rng.randn(n, f)                      # 44.8 MB raw f64
    y = (X[:, 0] > 0).astype(np.float64)
    ds = Dataset(X, label=y, params=BASE_PARAMS, free_raw_data=True)
    ds.construct(BASE_PARAMS)                # binning paid OUTSIDE the delta
    hwm_ok = MemoryTracker.reset_host_peak()
    base_mb = MemoryTracker.host_peak_rss_mb(use_hwm=hwm_ok)
    store = dataset_to_shards(ds, str(tmp_path / "rss"),
                              rows_per_shard=25_000, params=BASE_PARAMS)
    delta_mb = MemoryTracker.host_peak_rss_mb(use_hwm=hwm_ok) - base_mb
    assert ds.data.size == 0                 # raw matrix released
    assert store.num_data == n
    raw_mb = X.nbytes / 2 ** 20
    # bound: one shard's frame copy + the meta payload + slack — well
    # under another raw-matrix copy (the leak this satellite closes)
    assert delta_mb < raw_mb * 0.75, (delta_mb, raw_mb)


def test_stream_telemetry_events_and_inertness(data, store, tmp_path):
    """Satellite: stream.* telemetry — prefetch hit/stall counters in the
    registry, per-chunk stream.chunk events through the JSONL sink
    (rendered by tools/telemetry_report.py), and tpu_telemetry=off stays
    bitwise-inert (identical trees)."""
    import json as _json
    import subprocess

    from lightgbm_tpu.telemetry import registry
    log = str(tmp_path / "t.jsonl")
    sp = _stream_params(extra={"tpu_telemetry_log": log})
    bst_on = train_streamed(sp, store, num_boost_round=2)
    reg = registry().snapshot()
    hits = reg["counters"].get("stream.prefetch_hits", 0)
    stalls = reg["counters"].get("stream.prefetch_stalls", 0)
    assert hits + stalls > 0
    assert reg["counters"].get("stream.upload_bytes", 0) > 0
    kinds = [(_json.loads(line)).get("kind")
             for line in open(log) if line.strip()]
    assert kinds.count("stream.chunk") > 0
    assert "train.start" in kinds and "train.end" in kinds
    r = subprocess.run([sys.executable,
                        os.path.join(REPO, "tools", "telemetry_report.py"),
                        log], capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "stream chunks" in r.stdout
    # off-mode: same trees (telemetry is host-side observation only)
    bst_off = train_streamed(_stream_params(extra={"tpu_telemetry": "off"}),
                             store, num_boost_round=2)
    assert _trees_only(bst_on) == _trees_only(bst_off)


def test_torn_append_leaves_previous_consistent_store(data, tmp_path):
    """Crash-contract regression: a crash between append_rows' metadata
    write and its manifest write must leave the PREVIOUS consistent
    store (orphaned metadata tail dropped at open), never a brick."""
    X, y = data
    ds = Dataset(X, label=y, params=BASE_PARAMS)
    st = dataset_to_shards(ds, str(tmp_path / "t"), rows_per_shard=512,
                           params=BASE_PARAMS)
    manifest_path = os.path.join(st.path, "manifest.json")
    old_manifest = open(manifest_path, "rb").read()
    X2, y2 = _data(seed=3, n=300)
    append_rows(st, X2, y2)
    # simulate the crash point: meta.npz (and shards) written, manifest
    # rollback to the pre-append generation
    open(manifest_path, "wb").write(old_manifest)
    st2 = ShardedDataset.open(st.path)
    assert st2.num_data == N
    assert len(st2.label) == N
    np.testing.assert_array_equal(st2.label, y)
    # and the store still trains
    bst = train_streamed(_stream_params(), st2, num_boost_round=1)
    assert bst._gbdt.iter_ == 1


def test_residency_sweep_releases_prefetch_on_consumer_raise(store):
    """A consumer that raises mid-sweep must not leak the in-flight
    prefetched chunk's bytes (the live_bytes() <= budget invariant the
    bench witnesses)."""
    budget = int(TINY_BUDGET_MB * 2 ** 20)
    rm = ResidencyManager(store, budget)
    try:
        with pytest.raises(RuntimeError, match="boom"):
            for _ci, _lo, _hi, _arr in rm.sweep():
                raise RuntimeError("boom")
        assert rm.live_bytes() == 0
    finally:
        rm.close()
    assert rm.stats()["live_bytes"] == 0
