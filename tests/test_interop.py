"""Model-file interoperability with the GENUINE LightGBM implementation.

The fixtures were produced by the actual reference binary (built from
/root/reference with g++ during round 3) trained on the reference's own
``examples/binary_classification`` data:

- ``fixtures/ref_model.txt``   — model saved by the reference binary
  (objective=binary, 20 trees, 15 leaves)
- ``fixtures/ref_rows.tsv``    — first 50 rows of the reference's
  ``binary.test`` example data (label in column 0)
- ``fixtures/ref_preds_50.txt``— the reference binary's own predictions for
  those rows

Both directions were verified live against the binary during the round:
reference-model -> our predict matched to 6.6e-8, and our-model ->
reference-binary predict matched to 6.4e-8 (after folding boost-from-average
into the first tree and emitting ObjectiveFunction::ToString suffixes).
This file pins the loader direction permanently; the reverse direction runs
when a reference binary is supplied via $LGBM_REFERENCE_BIN.
"""

import os
import subprocess

import numpy as np
import pytest

import lightgbm_tpu as lgb

FIX = os.path.join(os.path.dirname(__file__), "fixtures")


def _rows():
    data = np.loadtxt(os.path.join(FIX, "ref_rows.tsv"), delimiter="\t")
    return data[:, 1:], data[:, 0]


def test_load_genuine_lightgbm_model_and_predict():
    """Our loader must reproduce the reference binary's predictions on a
    model file the reference itself trained and saved."""
    bst = lgb.Booster(model_file=os.path.join(FIX, "ref_model.txt"))
    assert bst.num_trees() == 20
    X, _y = _rows()
    ours = bst.predict(X)
    ref = np.loadtxt(os.path.join(FIX, "ref_preds_50.txt"))
    np.testing.assert_allclose(ours, ref, atol=1e-6)


def test_genuine_model_raw_score_and_importance():
    bst = lgb.Booster(model_file=os.path.join(FIX, "ref_model.txt"))
    X, _y = _rows()
    raw = bst.predict(X, raw_score=True)
    prob = bst.predict(X)
    np.testing.assert_allclose(prob, 1.0 / (1.0 + np.exp(-raw)), atol=1e-9)
    assert bst.feature_importance("split").sum() > 0


@pytest.mark.skipif(not os.environ.get("LGBM_REFERENCE_BIN"),
                    reason="set LGBM_REFERENCE_BIN to a reference "
                           "lightgbm binary to run the reverse direction")
def test_reference_binary_predicts_our_model(tmp_path):
    """Train with OUR framework, save, and have the genuine LightGBM binary
    predict — outputs must match our own predictions."""
    binary = os.environ["LGBM_REFERENCE_BIN"]
    X, y = _rows()
    rng = np.random.RandomState(0)
    Xb = np.tile(X, (20, 1)) + 0.01 * rng.randn(50 * 20, X.shape[1])
    yb = np.tile(y, 20)
    bst = lgb.train({"objective": "binary", "num_leaves": 15,
                     "verbosity": -1}, lgb.Dataset(Xb, label=yb), 10)
    model_path = tmp_path / "our_model.txt"
    bst.save_model(str(model_path))
    data_path = tmp_path / "rows.tsv"
    np.savetxt(data_path, np.column_stack([y, X]), delimiter="\t",
               fmt="%.9g")
    out_path = tmp_path / "preds.txt"
    subprocess.run([binary, "task=predict", f"data={data_path}",
                    f"input_model={model_path}",
                    f"output_result={out_path}"], check=True,
                   capture_output=True, timeout=300)
    ref_preds = np.loadtxt(out_path)
    np.testing.assert_allclose(ref_preds, bst.predict(X), atol=1e-6)


@pytest.mark.skipif(
    not os.path.exists(
        "/root/reference/examples/binary_classification/binary.train"),
    reason="reference example data not mounted")
def test_training_fidelity_first_tree_matches_genuine():
    """Train on the reference's example data with the fixture's params: the
    first tree's split features must match the genuine binary's model
    (fixtures/ref_model.txt tree 0) — pins binning + gain computation +
    split selection against the real implementation."""
    import re

    from lightgbm_tpu.io.parser import load_data_file

    X, y, _w, _g = load_data_file(
        "/root/reference/examples/binary_classification/binary.train")
    bst = lgb.train({"objective": "binary", "num_leaves": 15,
                     "learning_rate": 0.1, "verbosity": -1},
                    lgb.Dataset(X, label=y), 1)
    ours = list(map(int, bst._gbdt.models[0][0].split_feature[:8]))
    ref_txt = open(os.path.join(FIX, "ref_model.txt")).read()
    m = re.search(r"Tree=0\n.*?split_feature=([^\n]*)\n", ref_txt, re.S)
    ref = list(map(int, m.group(1).split()))[:8]
    # the first 8 best-gain splits match the genuine implementation exactly;
    # beyond that near-ties reorder (as they do between LightGBM builds)
    assert ours == ref
