"""bench.py shape-matrix rungs (ISSUE-4 satellite / VERDICT weak #2,
GOSS rung ISSUE-5): the lambdarank (MS-LTR-like), wide (Epsilon-like) and
GOSS (Higgs-shape sampled) rungs must emit their detail blobs on ANY
platform — the hermetic CPU fallback included — the wide rung must
actually engage the bounded histogram pool it exists to exercise, and the
GOSS rung must witness the device-resident sampler's ONE compiled dispatch
per boosting round.  Scaled-down geometries here; bench.py's env knobs
carry the full sizes."""

import jax

from bench import run_goss_rung, run_ltr_rung, run_wide_rung


def test_ltr_rung_blob():
    blob = run_ltr_rung(4200, 2, "cpu", jax, features=24, group=60,
                        num_leaves=15)
    assert blob["rows"] == 4200 and blob["features"] == 24
    assert blob["queries"] == 70
    assert blob["row_iters_per_sec"] > 0
    assert 0.0 <= blob["ndcg5_train_sample"] <= 1.0


def test_wide_rung_blob_pool_engaged():
    # features > 256 also auto-engages the tiled split scan; rows must
    # exceed _MIN_BUCKET so the pooled perm layout (not the mask
    # fallback) runs.
    blob = run_wide_rung(2600, 2, "cpu", jax, features=320, num_leaves=31,
                         max_bin=31, pool_mb=1.0)
    assert blob["rows"] == 2600 and blob["features"] == 320
    assert blob["row_iters_per_sec"] > 0
    assert blob["pool_engaged"] is True
    assert blob["pool_slots"] < 31
    assert blob["leaf_hist_mb_pooled"] < blob["leaf_hist_mb_unpooled"]


def test_goss_rung_blob_one_dispatch():
    blob = run_goss_rung(4096, 2, "cpu", jax, features=12, num_leaves=15)
    assert blob["rows"] == 4096 and blob["features"] == 12
    assert blob["data_sample_strategy"] == "goss"
    assert blob["row_iters_per_sec"] > 0
    # device GOSS (tpu_device_goss auto) keeps the round fused: the mask
    # is derived in-trace, so the census sees exactly one program launch
    assert blob["used_fused"] is True
    assert blob["dispatches_per_iter"] == 1.0
    assert blob["host_syncs_per_iter"] <= 2.0
