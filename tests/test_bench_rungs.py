"""bench.py shape-matrix rungs (ISSUE-4 satellite / VERDICT weak #2,
GOSS rung ISSUE-5): the lambdarank (MS-LTR-like), wide (Epsilon-like) and
GOSS (Higgs-shape sampled) rungs must emit their detail blobs on ANY
platform — the hermetic CPU fallback included — the wide rung must
actually engage the bounded histogram pool it exists to exercise, and the
GOSS rung must witness the device-resident sampler's ONE compiled dispatch
per boosting round.  Scaled-down geometries here; bench.py's env knobs
carry the full sizes."""

import json

import jax
import pytest

from bench import (_load_watchdog, _probe_backend, _probe_block,
                   run_fused_rung, run_goss_rung, run_ltr_rung,
                   run_serve_fused_rung, run_stream_rung, run_wide_rung)


def _assert_hlo_cost(blob):
    """Every rung blob carries the XLA cost-model block (ISSUE-7
    satellite: detail.hlo_cost — the compile-time number kernel PRs land
    with even when no chip answers)."""
    cost = blob["hlo_cost"]
    assert cost.get("flops", 0) > 0, cost
    assert cost.get("bytes_accessed", 0) > 0, cost
    # ISSUE-8 satellite: every rung blob also carries the post-hoc health
    # audit — a rung that trained on NaN can't publish a clean rate.
    h = blob["health"]
    assert h["verdict"] == "healthy", h
    assert h["rounds_checked"] == blob["iters"]
    assert h["last_health"]["grad_nonfinite"] == 0.0
    # ISSUE-9 satellite: and the schema-valid unified-telemetry block —
    # span totals at dispatch boundaries, per-kind event counts, the
    # process registry snapshot (docs/OBSERVABILITY.md BENCH section).
    t = blob["telemetry"]
    assert t.get("schema") == 1 and t["enabled"] is True, t
    assert isinstance(t["events"], dict)
    assert isinstance(t["registry"], dict) and "counters" in t["registry"]
    assert t["spans"], t
    assert all(d["seconds"] >= 0.0 and d["count"] >= 1
               for d in t["spans"].values()), t["spans"]
    json.dumps(t)   # JSON-serializable end to end (it rides the blob)
    # ISSUE-10: every rung blob also carries the schema-valid
    # detail.memory block — device watermark (None on CPU), live-buffer
    # census, compile count/seconds, host RSS, and the grower program's
    # compiled memory plan beside hlo_cost.
    m = blob["memory"]
    assert "error" not in m, m
    assert set(m) >= {"mode", "device", "live_buffers", "compile",
                      "host_peak_rss_mb", "memory_analysis"}, sorted(m)
    lb = m["live_buffers"]
    assert lb["total_bytes"] > 0 and lb["total_arrays"] > 0, lb
    assert lb["groups"] and lb["groups"][0]["bytes"] >= lb["groups"][-1]["bytes"]
    assert m["compile"]["count"] >= 0 and m["compile"]["seconds"] >= 0.0
    assert m["host_peak_rss_mb"] > 0
    ma = m["memory_analysis"]
    assert "error" not in ma, ma
    json.dumps(m)   # rides the blob too


def test_ltr_rung_blob():
    blob = run_ltr_rung(4200, 2, "cpu", jax, features=24, group=60,
                        num_leaves=15)
    assert blob["rows"] == 4200 and blob["features"] == 24
    assert blob["queries"] == 70
    assert blob["row_iters_per_sec"] > 0
    assert 0.0 <= blob["ndcg5_train_sample"] <= 1.0
    _assert_hlo_cost(blob)


def test_fused_rung_blob_one_dispatch_per_wave():
    """The quantized-fused rung (ISSUE-7): tpu_wave_kernel=fused engages
    (interpret mode on CPU — the kernel body actually runs), the census
    fact says one histogram dispatch per wave, and the blob carries the
    compile-time cost block."""
    blob = run_fused_rung(4096, 2, "cpu", jax, features=10, num_leaves=15)
    assert blob["rows"] == 4096 and blob["quantized"] is True
    assert blob["wave_kernel"] == "fused"
    assert blob["wave_fused_active"] is True
    assert blob["hist_dispatches_per_wave"] == 1
    assert blob["interpret_mode"] is True
    assert blob["row_iters_per_sec"] > 0
    _assert_hlo_cost(blob)


def test_wide_rung_blob_pool_engaged():
    # features > 256 also auto-engages the tiled split scan; rows must
    # exceed _MIN_BUCKET so the pooled perm layout (not the mask
    # fallback) runs.
    blob = run_wide_rung(2600, 2, "cpu", jax, features=320, num_leaves=31,
                         max_bin=31, pool_mb=1.0)
    assert blob["rows"] == 2600 and blob["features"] == 320
    assert blob["row_iters_per_sec"] > 0
    assert blob["pool_engaged"] is True
    assert blob["pool_slots"] < 31
    assert blob["leaf_hist_mb_pooled"] < blob["leaf_hist_mb_unpooled"]
    _assert_hlo_cost(blob)


def test_goss_rung_blob_one_dispatch():
    blob = run_goss_rung(4096, 2, "cpu", jax, features=12, num_leaves=15)
    assert blob["rows"] == 4096 and blob["features"] == 12
    assert blob["data_sample_strategy"] == "goss"
    assert blob["row_iters_per_sec"] > 0
    # device GOSS (tpu_device_goss auto) keeps the round fused: the mask
    # is derived in-trace, so the census sees exactly one program launch
    assert blob["used_fused"] is True
    assert blob["dispatches_per_iter"] == 1.0
    assert blob["host_syncs_per_iter"] <= 2.0
    _assert_hlo_cost(blob)


def test_serve_fused_rung_blob():
    """The quantized-traversal serving rung (ISSUE-12): int8 pack + fused
    Pallas traversal (interpret mode on CPU — the kernel body runs), the
    fused-vs-unfused integer identity asserted in-rung, >= 3x pack
    shrink, fp32 parity inside the analytic bound, and the zero-cold-
    start restart paying no compiles."""
    blob = run_serve_fused_rung(2600, 2, "cpu", jax, features=10,
                                num_leaves=15, calls=4, max_batch=64)
    assert blob["rows"] == 2600 and blob["quantize"] == "int8"
    assert blob["traverse"] == "fused"
    assert blob["interpret_mode"] is True
    assert blob["fused_bitwise_unfused"] is True
    assert blob["warm_qps"] > 0
    assert blob["p99_ms"] >= blob["p50_ms"] >= 0
    assert blob["pack_shrink"] >= 3.0
    assert 0 < blob["plan_bytes"] < blob["plan_bytes_fp32"]
    assert blob["parity_ok"] is True
    assert blob["parity_err"] <= blob["parity_bound"] + 1e-12
    r = blob["restart"]
    assert r["cold_compiles"] >= 1
    assert r["restart_compiles"] == 0
    assert r["restart_aot_hits"] >= 1


# --------------------------- watchdog probe block (ISSUE-6 satellite) ----
PROBE_KEYS = {"verdict", "backend", "devices", "latency_s", "budget_s",
              "error"}


def test_stream_rung_blob_budget_witnessed():
    """The out-of-core streaming rung (ISSUE-13): trains through the
    budget-bounded residency pipeline, WITNESSES peak streaming bytes <=
    the budget (asserted in-rung too — a violating blob never publishes),
    reports the prefetch ledger, and on CPU asserts the streamed trees
    bitwise-equal the in-core run's."""
    blob = run_stream_rung(4096, 2, "cpu", jax, features=10, num_leaves=7,
                           budget_mb=0.25)
    assert blob["rows"] == 4096 and blob["budget_ok"] is True
    assert blob["bitwise_identical"] is True
    assert 0 < blob["peak_stream_bytes"] <= blob["budget_bytes"]
    assert blob["peak_stream_bytes"] < blob["full_bins_bytes"] \
        or blob["chunks"] == 1
    assert blob["prefetch_hits"] + blob["prefetch_stalls"] >= blob["chunks"]
    assert blob["s_per_iter"] > 0 and blob["incore_s_per_iter"] > 0
    assert blob["shards"] >= 1 and blob["train_time_s"] > 0


def test_probe_block_carries_outer_watchdog_verdict(monkeypatch):
    """The outer bench process's subprocess probe verdict rides into the
    inner run's JSON via _BENCH_PROBE, verbatim."""
    blk = {"verdict": "wedged", "backend": None, "devices": 0,
           "latency_s": 240.0, "budget_s": 240, "error": "budget exceeded"}
    monkeypatch.setenv("_BENCH_PROBE", json.dumps(blk))
    assert _probe_block("cpu", 1, 0.5) == blk


def test_probe_block_synthesized_when_direct(monkeypatch):
    """A directly-invoked inner run (no outer watchdog) still emits a
    complete probe block from its own backend init."""
    monkeypatch.delenv("_BENCH_PROBE", raising=False)
    blk = _probe_block("cpu", 8, 1.2345)
    assert PROBE_KEYS <= set(blk)
    assert blk["verdict"] == "live" and blk["backend"] == "cpu"
    assert blk["devices"] == 8 and blk["latency_s"] == 1.234


def test_watchdog_loads_by_file_path_and_budgets():
    """bench.main() loads the watchdog WITHOUT importing lightgbm_tpu (a
    wedged plugin can hang even at package import); the loaded module's
    probe must return a wedged verdict AT its budget, not hang."""
    wd = _load_watchdog()
    res = wd.probe_backend(
        timeout=2.0,
        extra_env={"LIGHTGBM_TPU_FAULTS": "wedge_dispatch:600"})
    assert res.verdict == "wedged"
    assert PROBE_KEYS <= set(res.as_dict())


def test_forced_cpu_rung_refuses_accelerator_label(monkeypatch):
    """The honesty guard (ROADMAP 3b): a forced-CPU fallback rung that
    somehow resolves an accelerator backend must die, not publish a
    mislabeled number."""
    import _hermetic

    class _FakeJax:
        @staticmethod
        def devices():
            return [object()]

        @staticmethod
        def default_backend():
            return "tpu"

    monkeypatch.setenv("_BENCH_FORCE_CPU", "1")
    monkeypatch.setattr(_hermetic, "force_cpu", lambda n: _FakeJax)
    with pytest.raises(RuntimeError, match="forced-CPU"):
        _probe_backend()
