"""C API shim tests — the reference's ctypes-driven pattern
(``tests/c_api_test/test_.py``): load the C-ABI library, run the full
Dataset -> Booster -> train -> eval -> predict -> save/load workflow through
the C surface, and check parity with the Python API.
"""

import ctypes

import numpy as np
import pytest
from sklearn.datasets import make_classification

import lightgbm_tpu as lgb
from lightgbm_tpu import capi

_LIB = capi.lib_path()
pytestmark = pytest.mark.skipif(_LIB is None,
                                reason="C API shim failed to build")


def _load():
    lib = ctypes.CDLL(_LIB)
    lib.LGBM_GetLastError.restype = ctypes.c_char_p
    return lib


def _check(lib, rc):
    assert rc == 0, lib.LGBM_GetLastError().decode()


def _dataset_from_mat(lib, X, y=None, params=b"", reference=None):
    X32 = np.ascontiguousarray(X, np.float32)
    handle = ctypes.c_void_p()
    _check(lib, lib.LGBM_DatasetCreateFromMat(
        X32.ctypes.data_as(ctypes.c_void_p), 0,  # C_API_DTYPE_FLOAT32
        ctypes.c_int32(X32.shape[0]), ctypes.c_int32(X32.shape[1]),
        ctypes.c_int(1), params, reference or ctypes.c_void_p(),
        ctypes.byref(handle)))
    if y is not None:
        y32 = np.ascontiguousarray(y, np.float32)
        _check(lib, lib.LGBM_DatasetSetField(
            handle, b"label", y32.ctypes.data_as(ctypes.c_void_p),
            ctypes.c_int(len(y32)), 0))
    return handle


def test_capi_full_workflow(tmp_path):
    lib = _load()
    assert lib.LGBM_CAPIVersion() == 1

    X, y = make_classification(n_samples=800, n_features=6, n_informative=4,
                               random_state=0)
    train = _dataset_from_mat(lib, X[:600], y[:600])
    valid = _dataset_from_mat(lib, X[600:], y[600:])

    nd, nf = ctypes.c_int32(), ctypes.c_int32()
    _check(lib, lib.LGBM_DatasetGetNumData(train, ctypes.byref(nd)))
    _check(lib, lib.LGBM_DatasetGetNumFeature(train, ctypes.byref(nf)))
    assert (nd.value, nf.value) == (600, 6)

    params = b"objective=binary metric=auc num_leaves=15 verbosity=-1"
    bst = ctypes.c_void_p()
    _check(lib, lib.LGBM_BoosterCreate(train, params, ctypes.byref(bst)))
    _check(lib, lib.LGBM_BoosterAddValidData(bst, valid))

    finished = ctypes.c_int()
    for _ in range(10):
        _check(lib, lib.LGBM_BoosterUpdateOneIter(bst, ctypes.byref(finished)))

    it = ctypes.c_int()
    _check(lib, lib.LGBM_BoosterGetCurrentIteration(bst, ctypes.byref(it)))
    assert it.value == 10
    nc = ctypes.c_int()
    _check(lib, lib.LGBM_BoosterGetNumClasses(bst, ctypes.byref(nc)))
    assert nc.value == 1

    # eval on the valid set: AUC should be sane
    n_eval = ctypes.c_int()
    _check(lib, lib.LGBM_BoosterGetEvalCounts(bst, ctypes.byref(n_eval)))
    assert n_eval.value >= 1
    res = (ctypes.c_double * 8)()
    out_len = ctypes.c_int()
    _check(lib, lib.LGBM_BoosterGetEval(bst, 1, ctypes.byref(out_len), res))
    assert out_len.value >= 1
    assert 0.7 < res[0] <= 1.0

    # predict through the C API and compare with the Python API
    Xp = np.ascontiguousarray(X[600:], np.float64)
    out = (ctypes.c_double * Xp.shape[0])()
    out_n = ctypes.c_int64()
    _check(lib, lib.LGBM_BoosterPredictForMat(
        bst, Xp.ctypes.data_as(ctypes.c_void_p), 1,
        ctypes.c_int32(Xp.shape[0]), ctypes.c_int32(Xp.shape[1]),
        ctypes.c_int(1), ctypes.c_int(1),  # RAW_SCORE
        ctypes.c_int(0), ctypes.c_int(-1), b"", ctypes.byref(out_n), out))
    assert out_n.value == Xp.shape[0]
    c_pred = np.array(out[:])

    # "pred_early_stop=false" must be parsed as bool false (reference
    # Config::GetBool), not as a truthy non-empty string — predictions with
    # the flag explicitly disabled must match the default exactly
    out_es = (ctypes.c_double * Xp.shape[0])()
    _check(lib, lib.LGBM_BoosterPredictForMat(
        bst, Xp.ctypes.data_as(ctypes.c_void_p), 1,
        ctypes.c_int32(Xp.shape[0]), ctypes.c_int32(Xp.shape[1]),
        ctypes.c_int(1), ctypes.c_int(1), ctypes.c_int(0), ctypes.c_int(-1),
        b"pred_early_stop=false", ctypes.byref(out_n), out_es))
    np.testing.assert_array_equal(np.array(out_es[:]), c_pred)

    # save -> reload via string round trip
    buf_len = ctypes.c_int64(1 << 22)
    buf = ctypes.create_string_buffer(buf_len.value)
    str_len = ctypes.c_int64()
    _check(lib, lib.LGBM_BoosterSaveModelToString(
        bst, ctypes.c_int(0), ctypes.c_int(-1), ctypes.c_int(0), buf_len,
        ctypes.byref(str_len), buf))
    model_str = buf.value.decode()
    assert "tree" in model_str

    bst2 = ctypes.c_void_p()
    out_it = ctypes.c_int()
    _check(lib, lib.LGBM_BoosterLoadModelFromString(
        buf.value, ctypes.byref(out_it), ctypes.byref(bst2)))
    assert out_it.value == 10
    out2 = (ctypes.c_double * Xp.shape[0])()
    _check(lib, lib.LGBM_BoosterPredictForMat(
        bst2, Xp.ctypes.data_as(ctypes.c_void_p), 1,
        ctypes.c_int32(Xp.shape[0]), ctypes.c_int32(Xp.shape[1]),
        ctypes.c_int(1), ctypes.c_int(1), ctypes.c_int(0), ctypes.c_int(-1),
        b"", ctypes.byref(out_n), out2))
    np.testing.assert_allclose(np.array(out2[:]), c_pred, rtol=1e-6,
                               atol=1e-6)

    # parity with the Python surface (same params, same data)
    py = lgb.train({"objective": "binary", "metric": "auc", "num_leaves": 15,
                    "verbosity": -1},
                   lgb.Dataset(X[:600], label=y[:600]), 10)
    py_pred = py.predict(X[600:], raw_score=True)
    np.testing.assert_allclose(c_pred, py_pred, rtol=1e-4, atol=1e-4)

    # model file save + load
    path = str(tmp_path / "capi_model.txt")
    _check(lib, lib.LGBM_BoosterSaveModel(
        bst, ctypes.c_int(0), ctypes.c_int(-1), ctypes.c_int(0),
        path.encode()))
    bst3 = ctypes.c_void_p()
    _check(lib, lib.LGBM_BoosterCreateFromModelfile(
        path.encode(), ctypes.byref(out_it), ctypes.byref(bst3)))
    assert out_it.value == 10

    # feature importance
    imp = (ctypes.c_double * 6)()
    _check(lib, lib.LGBM_BoosterFeatureImportance(
        bst, ctypes.c_int(-1), ctypes.c_int(0), imp))
    assert sum(imp[:]) > 0

    for h in (bst, bst2, bst3):
        _check(lib, lib.LGBM_BoosterFree(h))
    for h in (train, valid):
        _check(lib, lib.LGBM_DatasetFree(h))


def test_capi_error_reporting():
    lib = _load()
    bad = ctypes.c_void_p()
    rc = lib.LGBM_DatasetCreateFromFile(b"/nonexistent/file.csv", b"",
                                        ctypes.c_void_p(), ctypes.byref(bad))
    assert rc == -1
    msg = lib.LGBM_GetLastError().decode()
    assert "nonexistent" in msg or "No such file" in msg


def test_capi_rollback_and_dump():
    lib = _load()
    X, y = make_classification(n_samples=400, n_features=5, random_state=1)
    train = _dataset_from_mat(lib, X, y)
    bst = ctypes.c_void_p()
    _check(lib, lib.LGBM_BoosterCreate(
        train, b"objective=binary num_leaves=7 verbosity=-1",
        ctypes.byref(bst)))
    fin = ctypes.c_int()
    for _ in range(3):
        _check(lib, lib.LGBM_BoosterUpdateOneIter(bst, ctypes.byref(fin)))
    _check(lib, lib.LGBM_BoosterRollbackOneIter(bst))
    it = ctypes.c_int()
    _check(lib, lib.LGBM_BoosterGetCurrentIteration(bst, ctypes.byref(it)))
    assert it.value == 2

    buf_len = ctypes.c_int64(1 << 22)
    buf = ctypes.create_string_buffer(buf_len.value)
    out_len = ctypes.c_int64()
    _check(lib, lib.LGBM_BoosterDumpModel(
        bst, ctypes.c_int(0), ctypes.c_int(-1), ctypes.c_int(0), buf_len,
        ctypes.byref(out_len), buf))
    import json
    model = json.loads(buf.value.decode())
    assert model["num_tree_per_iteration"] >= 1
    _check(lib, lib.LGBM_BoosterFree(bst))
    _check(lib, lib.LGBM_DatasetFree(train))


def test_capi_standalone_c_program(tmp_path):
    """Compile a plain C program against the shim and run it OUTSIDE any
    Python process — proves the embedded-interpreter mode (the reference's
    c_api is likewise consumable from bare C)."""
    import os
    import shutil
    import subprocess
    import sys

    if shutil.which("gcc") is None:
        pytest.skip("no C compiler")
    import lightgbm_tpu
    pkg_root = os.path.dirname(os.path.dirname(
        os.path.abspath(lightgbm_tpu.__file__)))
    src = tmp_path / "demo.c"
    src.write_text(r'''
#include <stdio.h>
#include "lightgbm_tpu_c_api.h"
int main(void) {
  float X[200 * 3]; float y[200];
  for (int i = 0; i < 200; ++i) {
    for (int j = 0; j < 3; ++j) X[i*3+j] = (float)((i*37+j*11) % 100) / 100.0f - 0.5f;
    y[i] = X[i*3] > 0 ? 1.0f : 0.0f;
  }
  DatasetHandle ds; BoosterHandle bst; int fin;
  if (LGBM_DatasetCreateFromMat(X, C_API_DTYPE_FLOAT32, 200, 3, 1, "", NULL, &ds)) { fprintf(stderr, "%s\n", LGBM_GetLastError()); return 1; }
  if (LGBM_DatasetSetField(ds, "label", y, 200, C_API_DTYPE_FLOAT32)) return 1;
  if (LGBM_BoosterCreate(ds, "objective=binary num_leaves=7 min_data_in_leaf=5 verbosity=-1", &bst)) { fprintf(stderr, "%s\n", LGBM_GetLastError()); return 1; }
  for (int i = 0; i < 3; ++i) if (LGBM_BoosterUpdateOneIter(bst, &fin)) { fprintf(stderr, "%s\n", LGBM_GetLastError()); return 1; }
  int it; LGBM_BoosterGetCurrentIteration(bst, &it);
  printf("iters=%d\n", it);
  return it == 3 ? 0 : 1;
}
''')
    exe = tmp_path / "demo"
    subprocess.run(
        ["gcc", "-O1", str(src),
         f"-I{os.path.join(pkg_root, 'lightgbm_tpu', 'capi', 'include')}",
         _LIB, "-o", str(exe),
         f"-Wl,-rpath,{os.path.dirname(_LIB)}"],
        check=True, capture_output=True)
    env = dict(os.environ,
               LIGHTGBM_TPU_PLATFORM="cpu",
               LIGHTGBM_TPU_PKG_DIR=pkg_root,
               PYTHONPATH=pkg_root + os.pathsep
               + os.environ.get("PYTHONPATH", ""))
    res = subprocess.run([str(exe)], env=env, capture_output=True,
                         text=True, timeout=240)
    assert res.returncode == 0, (res.stdout, res.stderr)
    assert "iters=3" in res.stdout


def test_capi_csr_and_feature_names():
    sp = pytest.importorskip("scipy.sparse")
    lib = _load()
    rng = np.random.RandomState(2)
    dense = np.zeros((500, 12))
    for j in range(12):
        rows = rng.choice(500, size=40, replace=False)
        dense[rows, j] = rng.rand(40) + 0.2
    y = (dense[:, 0] > 0).astype(np.float32)
    csr = sp.csr_matrix(dense)
    indptr = np.ascontiguousarray(csr.indptr, np.int32)
    indices = np.ascontiguousarray(csr.indices, np.int32)
    data = np.ascontiguousarray(csr.data, np.float64)
    ds = ctypes.c_void_p()
    _check(lib, lib.LGBM_DatasetCreateFromCSR(
        indptr.ctypes.data_as(ctypes.c_void_p), ctypes.c_int(2),  # INT32
        indices.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        data.ctypes.data_as(ctypes.c_void_p), ctypes.c_int(1),  # FLOAT64
        ctypes.c_int64(len(indptr)), ctypes.c_int64(len(data)),
        ctypes.c_int64(12), b"", ctypes.c_void_p(), ctypes.byref(ds)))
    _check(lib, lib.LGBM_DatasetSetField(
        ds, b"label", y.ctypes.data_as(ctypes.c_void_p),
        ctypes.c_int(len(y)), 0))

    names = [f"feat_{i}".encode() for i in range(12)]
    arr = (ctypes.c_char_p * 12)(*names)
    _check(lib, lib.LGBM_DatasetSetFeatureNames(
        ds, ctypes.cast(arr, ctypes.POINTER(ctypes.c_char_p)),
        ctypes.c_int(12)))
    bufs = [ctypes.create_string_buffer(64) for _ in range(12)]
    ptrs = (ctypes.c_char_p * 12)(*[ctypes.addressof(b) for b in bufs])
    nn, blen = ctypes.c_int(), ctypes.c_size_t()
    _check(lib, lib.LGBM_DatasetGetFeatureNames(
        ds, ctypes.c_int(12), ctypes.byref(nn), ctypes.c_size_t(64),
        ctypes.byref(blen), ctypes.cast(ptrs,
                                        ctypes.POINTER(ctypes.c_char_p))))
    assert nn.value == 12 and bufs[3].value == b"feat_3"

    bst = ctypes.c_void_p()
    _check(lib, lib.LGBM_BoosterCreate(
        ds, b"objective=binary num_leaves=7 min_data_in_leaf=5 verbosity=-1",
        ctypes.byref(bst)))
    fin = ctypes.c_int()
    for _ in range(5):
        _check(lib, lib.LGBM_BoosterUpdateOneIter(bst, ctypes.byref(fin)))
    _check(lib, lib.LGBM_BoosterResetParameter(bst, b"learning_rate=0.05"))

    out = (ctypes.c_double * 500)()
    out_n = ctypes.c_int64()
    _check(lib, lib.LGBM_BoosterPredictForCSR(
        bst, indptr.ctypes.data_as(ctypes.c_void_p), ctypes.c_int(2),
        indices.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        data.ctypes.data_as(ctypes.c_void_p), ctypes.c_int(1),
        ctypes.c_int64(len(indptr)), ctypes.c_int64(len(data)),
        ctypes.c_int64(12), ctypes.c_int(0), ctypes.c_int(0),
        ctypes.c_int(-1), b"", ctypes.byref(out_n), out))
    assert out_n.value == 500
    preds = np.array(out[:])
    from lightgbm_tpu.metrics import _auc
    auc = _auc(y.astype(np.float64), preds, None, None)
    assert auc > 0.9, auc
    assert preds.std() > 1e-6  # actually discriminates
    _check(lib, lib.LGBM_BoosterFree(bst))
    _check(lib, lib.LGBM_DatasetFree(ds))


def test_capi_streaming_push():
    """CreateByReference + PushRows chunks + WithMetadata (reference
    streaming protocol, c_api.h:162-323): a dataset streamed in 4 chunks
    must train identically to the one-shot matrix dataset."""
    lib = _load()
    rng = np.random.RandomState(8)
    n, f = 800, 6
    X = rng.randn(n, f)
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.float64)

    ref = _dataset_from_mat(lib, X, y, params=b"max_bin=63")
    stream = ctypes.c_void_p()
    _check(lib, lib.LGBM_DatasetCreateByReference(
        ref, ctypes.c_int64(n), ctypes.byref(stream)))
    _check(lib, lib.LGBM_DatasetSetWaitForManualFinish(stream, 1))
    chunk = n // 4
    for i in range(4):
        blk = np.ascontiguousarray(X[i * chunk:(i + 1) * chunk], np.float64)
        lab = np.ascontiguousarray(y[i * chunk:(i + 1) * chunk], np.float32)
        _check(lib, lib.LGBM_DatasetPushRowsWithMetadata(
            stream, blk.ctypes.data_as(ctypes.c_void_p), 1,
            ctypes.c_int32(chunk), ctypes.c_int32(f),
            ctypes.c_int32(i * chunk),
            lab.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            None, None, None, ctypes.c_int32(0)))
    _check(lib, lib.LGBM_DatasetMarkFinished(stream))
    nd = ctypes.c_int32()
    _check(lib, lib.LGBM_DatasetGetNumData(stream, ctypes.byref(nd)))
    assert nd.value == n

    def _train(ds):
        bst = ctypes.c_void_p()
        _check(lib, lib.LGBM_BoosterCreate(
            ds, b"objective=binary num_leaves=15 min_data_in_leaf=5 "
                b"verbosity=-1 max_bin=63 deterministic=true seed=3",
            ctypes.byref(bst)))
        fin = ctypes.c_int()
        for _ in range(8):
            _check(lib, lib.LGBM_BoosterUpdateOneIter(bst, ctypes.byref(fin)))
        return bst

    b_stream = _train(stream)
    b_mat = _train(_dataset_from_mat(lib, X, y, params=b"max_bin=63"))
    Xp = np.ascontiguousarray(X[:100], np.float64)
    outs = []
    for bst in (b_stream, b_mat):
        out = (ctypes.c_double * 100)()
        out_n = ctypes.c_int64()
        _check(lib, lib.LGBM_BoosterPredictForMat(
            bst, Xp.ctypes.data_as(ctypes.c_void_p), 1,
            ctypes.c_int32(100), ctypes.c_int32(f), ctypes.c_int(1),
            ctypes.c_int(0), ctypes.c_int(0), ctypes.c_int(-1), b"",
            ctypes.byref(out_n), out))
        outs.append(np.array(out[:]))
    np.testing.assert_allclose(outs[0], outs[1], rtol=1e-9)


def test_capi_csr_push_and_csc_create():
    sp = pytest.importorskip("scipy.sparse")
    lib = _load()
    rng = np.random.RandomState(9)
    n, f = 600, 8
    dense = np.where(rng.rand(n, f) < 0.3, rng.randn(n, f), 0.0)
    y = (dense[:, 0] > 0).astype(np.float64)

    # CSC create routes through the sparse-direct binning path
    csc = sp.csc_matrix(dense)
    indptr = np.ascontiguousarray(csc.indptr, np.int32)
    indices = np.ascontiguousarray(csc.indices, np.int32)
    vals = np.ascontiguousarray(csc.data, np.float64)
    h_csc = ctypes.c_void_p()
    _check(lib, lib.LGBM_DatasetCreateFromCSC(
        indptr.ctypes.data_as(ctypes.c_void_p), 2,
        indices.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        vals.ctypes.data_as(ctypes.c_void_p), 1,
        ctypes.c_int64(len(indptr)), ctypes.c_int64(csc.nnz),
        ctypes.c_int64(n), b"max_bin=63", ctypes.c_void_p(),
        ctypes.byref(h_csc)))
    nd = ctypes.c_int32()
    nf = ctypes.c_int32()
    _check(lib, lib.LGBM_DatasetGetNumData(h_csc, ctypes.byref(nd)))
    _check(lib, lib.LGBM_DatasetGetNumFeature(h_csc, ctypes.byref(nf)))
    assert (nd.value, nf.value) == (n, f)

    # CSR streaming push against it
    stream = ctypes.c_void_p()
    _check(lib, lib.LGBM_DatasetCreateByReference(
        h_csc, ctypes.c_int64(n), ctypes.byref(stream)))
    csr = sp.csr_matrix(dense)
    half = n // 2
    for i, (lo, hi) in enumerate(((0, half), (half, n))):
        blk = csr[lo:hi]
        bi = np.ascontiguousarray(blk.indptr, np.int32)
        bj = np.ascontiguousarray(blk.indices, np.int32)
        bv = np.ascontiguousarray(blk.data, np.float64)
        lab = np.ascontiguousarray(y[lo:hi], np.float32)
        _check(lib, lib.LGBM_DatasetPushRowsByCSRWithMetadata(
            stream, bi.ctypes.data_as(ctypes.c_void_p), 2,
            bj.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            bv.ctypes.data_as(ctypes.c_void_p), 1,
            ctypes.c_int64(len(bi)), ctypes.c_int64(blk.nnz),
            ctypes.c_int64(lo),
            lab.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            None, None, None, ctypes.c_int32(0)))
    _check(lib, lib.LGBM_DatasetMarkFinished(stream))
    bst = ctypes.c_void_p()
    _check(lib, lib.LGBM_BoosterCreate(
        stream, b"objective=binary num_leaves=7 verbosity=-1 max_bin=63",
        ctypes.byref(bst)))
    fin = ctypes.c_int()
    for _ in range(3):
        _check(lib, lib.LGBM_BoosterUpdateOneIter(bst, ctypes.byref(fin)))
    it = ctypes.c_int()
    _check(lib, lib.LGBM_BoosterGetCurrentIteration(bst, ctypes.byref(it)))
    assert it.value == 3


def test_capi_single_row_fast_predict():
    """FastConfig single-row serving (reference c_api.h:1332): parity with
    the batch path and a sub-millisecond per-call budget."""
    import time

    lib = _load()
    rng = np.random.RandomState(10)
    n, f = 1200, 10
    X = rng.randn(n, f)
    y = (X[:, 0] + 0.3 * X[:, 1] > 0).astype(np.float64)
    ds = _dataset_from_mat(lib, X, y)
    bst = ctypes.c_void_p()
    _check(lib, lib.LGBM_BoosterCreate(
        ds, b"objective=binary num_leaves=31 verbosity=-1",
        ctypes.byref(bst)))
    fin = ctypes.c_int()
    for _ in range(20):
        _check(lib, lib.LGBM_BoosterUpdateOneIter(bst, ctypes.byref(fin)))

    fast = ctypes.c_void_p()
    _check(lib, lib.LGBM_BoosterPredictForMatSingleRowFastInit(
        bst, ctypes.c_int(0), ctypes.c_int(0), ctypes.c_int(-1),
        ctypes.c_int(1), ctypes.c_int32(f), b"", ctypes.byref(fast)))

    # parity vs batch predict
    rows = np.ascontiguousarray(X[:50], np.float64)
    batch = (ctypes.c_double * 50)()
    out_n = ctypes.c_int64()
    _check(lib, lib.LGBM_BoosterPredictForMat(
        bst, rows.ctypes.data_as(ctypes.c_void_p), 1, ctypes.c_int32(50),
        ctypes.c_int32(f), ctypes.c_int(1), ctypes.c_int(0),
        ctypes.c_int(0), ctypes.c_int(-1), b"", ctypes.byref(out_n), batch))
    one = ctypes.c_double()
    for i in range(50):
        row = np.ascontiguousarray(rows[i], np.float64)
        _check(lib, lib.LGBM_BoosterPredictForMatSingleRowFast(
            fast, row.ctypes.data_as(ctypes.c_void_p),
            ctypes.byref(out_n), ctypes.byref(one)))
        assert out_n.value == 1
        # batch path converts outputs through jax f32; the fast path's
        # host-numpy sigmoid is f64 — identical rounding is not expected
        np.testing.assert_allclose(one.value, batch[i], rtol=1e-6,
                                   atol=1e-7)

    # latency budget: <= 1 ms/call averaged over 200 calls (after warmup)
    row = np.ascontiguousarray(rows[0], np.float64)
    t0 = time.perf_counter()
    for _ in range(200):
        lib.LGBM_BoosterPredictForMatSingleRowFast(
            fast, row.ctypes.data_as(ctypes.c_void_p),
            ctypes.byref(out_n), ctypes.byref(one))
    per_call_ms = (time.perf_counter() - t0) / 200 * 1e3
    assert per_call_ms < 1.0, f"{per_call_ms:.3f} ms/call"
    _check(lib, lib.LGBM_FastConfigFree(fast))


def test_capi_extended_surface(tmp_path):
    """Round-4 parity batch: metadata getters, leaf get/set, bounds, merge,
    shuffle, refit, custom objective, subset, param aliases, sampling,
    log callback (reference c_api.h declarations of the same names)."""
    lib = _load()
    rng = np.random.RandomState(11)
    n, f = 900, 6
    X = rng.randn(n, f)
    y = (X[:, 0] + 0.4 * X[:, 1] > 0).astype(np.float64)
    ds = _dataset_from_mat(lib, X, y)
    bst = ctypes.c_void_p()
    _check(lib, lib.LGBM_BoosterCreate(
        ds, b"objective=binary num_leaves=15 verbosity=-1",
        ctypes.byref(bst)))
    fin = ctypes.c_int()
    for _ in range(6):
        _check(lib, lib.LGBM_BoosterUpdateOneIter(bst, ctypes.byref(fin)))

    # CalcNumPredict / NumberOfTotalModel / GetLinear
    n_pred = ctypes.c_int64()
    _check(lib, lib.LGBM_BoosterCalcNumPredict(
        bst, ctypes.c_int(50), ctypes.c_int(0), ctypes.c_int(0),
        ctypes.c_int(-1), ctypes.byref(n_pred)))
    assert n_pred.value == 50
    _check(lib, lib.LGBM_BoosterCalcNumPredict(
        bst, ctypes.c_int(50), ctypes.c_int(3), ctypes.c_int(0),
        ctypes.c_int(-1), ctypes.byref(n_pred)))
    assert n_pred.value == 50 * (f + 1)
    total = ctypes.c_int()
    _check(lib, lib.LGBM_BoosterNumberOfTotalModel(bst, ctypes.byref(total)))
    assert total.value == 6
    lin = ctypes.c_int()
    _check(lib, lib.LGBM_BoosterGetLinear(bst, ctypes.byref(lin)))
    assert lin.value == 0

    # bounds bracket every prediction
    lo, hi = ctypes.c_double(), ctypes.c_double()
    _check(lib, lib.LGBM_BoosterGetLowerBoundValue(bst, ctypes.byref(lo)))
    _check(lib, lib.LGBM_BoosterGetUpperBoundValue(bst, ctypes.byref(hi)))
    Xp = np.ascontiguousarray(X[:100], np.float64)
    out = (ctypes.c_double * 100)()
    out_n = ctypes.c_int64()
    _check(lib, lib.LGBM_BoosterPredictForMat(
        bst, Xp.ctypes.data_as(ctypes.c_void_p), 1, ctypes.c_int32(100),
        ctypes.c_int32(f), ctypes.c_int(1), ctypes.c_int(1), ctypes.c_int(0),
        ctypes.c_int(-1), b"", ctypes.byref(out_n), out))
    preds = np.array(out[:])
    assert lo.value <= preds.min() + 1e-9
    assert hi.value >= preds.max() - 1e-9

    # leaf get/set round trip changes predictions
    v = ctypes.c_double()
    _check(lib, lib.LGBM_BoosterGetLeafValue(
        bst, ctypes.c_int(0), ctypes.c_int(0), ctypes.byref(v)))
    _check(lib, lib.LGBM_BoosterSetLeafValue(
        bst, ctypes.c_int(0), ctypes.c_int(0),
        ctypes.c_double(v.value + 1.0)))
    v2 = ctypes.c_double()
    _check(lib, lib.LGBM_BoosterGetLeafValue(
        bst, ctypes.c_int(0), ctypes.c_int(0), ctypes.byref(v2)))
    assert abs(v2.value - v.value - 1.0) < 1e-12
    _check(lib, lib.LGBM_BoosterSetLeafValue(
        bst, ctypes.c_int(0), ctypes.c_int(0), v))

    # GetPredict over the training data matches batch predict
    npred = ctypes.c_int64()
    _check(lib, lib.LGBM_BoosterGetNumPredict(bst, ctypes.c_int(0),
                                              ctypes.byref(npred)))
    assert npred.value == n
    trainp = (ctypes.c_double * n)()
    _check(lib, lib.LGBM_BoosterGetPredict(bst, ctypes.c_int(0),
                                           ctypes.byref(npred), trainp))
    full = (ctypes.c_double * n)()
    Xa = np.ascontiguousarray(X, np.float64)
    _check(lib, lib.LGBM_BoosterPredictForMat(
        bst, Xa.ctypes.data_as(ctypes.c_void_p), 1, ctypes.c_int32(n),
        ctypes.c_int32(f), ctypes.c_int(1), ctypes.c_int(0), ctypes.c_int(0),
        ctypes.c_int(-1), b"", ctypes.byref(out_n), full))
    np.testing.assert_allclose(np.array(trainp[:]), np.array(full[:]),
                               rtol=2e-3, atol=2e-3)

    # refit with the model's own leaf assignments at decay 1 is a no-op
    nleaf = (ctypes.c_double * (n * 6))()
    _check(lib, lib.LGBM_BoosterPredictForMat(
        bst, Xa.ctypes.data_as(ctypes.c_void_p), 1, ctypes.c_int32(n),
        ctypes.c_int32(f), ctypes.c_int(1), ctypes.c_int(2), ctypes.c_int(0),
        ctypes.c_int(-1), b"", ctypes.byref(out_n), nleaf))
    leaf_preds = np.ascontiguousarray(
        np.array(nleaf[: n * 6]).reshape(n, 6), np.int32)
    _check(lib, lib.LGBM_BoosterRefit(
        bst, leaf_preds.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        ctypes.c_int32(n), ctypes.c_int32(6)))

    # shuffle + merge keep model count consistent
    _check(lib, lib.LGBM_BoosterShuffleModels(bst, ctypes.c_int(0),
                                              ctypes.c_int(-1)))
    bst2 = ctypes.c_void_p()
    _check(lib, lib.LGBM_BoosterCreate(
        ds, b"objective=binary num_leaves=7 verbosity=-1",
        ctypes.byref(bst2)))
    for _ in range(2):
        _check(lib, lib.LGBM_BoosterUpdateOneIter(bst2, ctypes.byref(fin)))
    _check(lib, lib.LGBM_BoosterMerge(bst, bst2))
    _check(lib, lib.LGBM_BoosterNumberOfTotalModel(bst, ctypes.byref(total)))
    assert total.value == 8

    # custom objective iteration
    bst3 = ctypes.c_void_p()
    _check(lib, lib.LGBM_BoosterCreate(
        ds, b"objective=custom num_leaves=7 verbosity=-1",
        ctypes.byref(bst3)))
    grad = np.ascontiguousarray(rng.randn(n), np.float32)
    hess = np.ascontiguousarray(np.ones(n), np.float32)
    _check(lib, lib.LGBM_BoosterUpdateOneIterCustom(
        bst3, grad.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        hess.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        ctypes.byref(fin)))
    it = ctypes.c_int()
    _check(lib, lib.LGBM_BoosterGetCurrentIteration(bst3, ctypes.byref(it)))
    assert it.value == 1

    # dataset helpers
    nb = ctypes.c_int()
    _check(lib, lib.LGBM_DatasetGetFeatureNumBin(ds, ctypes.c_int(0),
                                                 ctypes.byref(nb)))
    assert nb.value > 1
    fl = ctypes.c_int()
    ptr = ctypes.c_void_p()
    ftype = ctypes.c_int()
    _check(lib, lib.LGBM_DatasetGetField(
        ds, b"label", ctypes.byref(fl), ctypes.byref(ptr),
        ctypes.byref(ftype)))
    assert fl.value == n and ftype.value == 0
    # a second GetField must not invalidate the first pointer
    w32 = np.ascontiguousarray(np.ones(n), np.float32)
    _check(lib, lib.LGBM_DatasetSetField(
        ds, b"weight", w32.ctypes.data_as(ctypes.c_void_p),
        ctypes.c_int(n), 0))
    wl = ctypes.c_int()
    wptr = ctypes.c_void_p()
    _check(lib, lib.LGBM_DatasetGetField(
        ds, b"weight", ctypes.byref(wl), ctypes.byref(wptr),
        ctypes.byref(ftype)))
    lab = np.ctypeslib.as_array(
        ctypes.cast(ptr, ctypes.POINTER(ctypes.c_float)), shape=(n,))
    np.testing.assert_allclose(lab, y.astype(np.float32))
    idx = np.ascontiguousarray(np.arange(0, n, 2), np.int32)
    sub = ctypes.c_void_p()
    _check(lib, lib.LGBM_DatasetGetSubset(
        ds, idx.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        ctypes.c_int32(len(idx)), b"", ctypes.byref(sub)))
    nd = ctypes.c_int32()
    _check(lib, lib.LGBM_DatasetGetNumData(sub, ctypes.byref(nd)))
    assert nd.value == len(idx)
    rc = lib.LGBM_DatasetUpdateParamChecking(b"max_bin=255", b"max_bin=63")
    assert rc == -1
    _check(lib, lib.LGBM_DatasetUpdateParamChecking(
        b"max_bin=255", b"learning_rate=0.2"))
    txt = str(tmp_path / "dump.tsv")
    _check(lib, lib.LGBM_DatasetDumpText(ds, txt.encode()))
    assert len(open(txt).readlines()) == n

    # param aliases / threads / sampling
    buf = ctypes.create_string_buffer(1 << 20)
    blen = ctypes.c_int64()
    _check(lib, lib.LGBM_DumpParamAliases(
        ctypes.c_int64(1 << 20), ctypes.byref(blen), buf))
    import json
    aliases = json.loads(buf.value.decode())
    assert "num_leaves" in aliases
    _check(lib, lib.LGBM_SetMaxThreads(4))
    mt = ctypes.c_int()
    _check(lib, lib.LGBM_GetMaxThreads(ctypes.byref(mt)))
    assert mt.value == 4
    sc = ctypes.c_int()
    _check(lib, lib.LGBM_GetSampleCount(
        ctypes.c_int32(10 ** 7), b"bin_construct_sample_cnt=5000",
        ctypes.byref(sc)))
    assert sc.value == 5000
    sidx = (ctypes.c_int32 * 5000)()
    slen = ctypes.c_int32()
    _check(lib, lib.LGBM_SampleIndices(
        ctypes.c_int32(10 ** 7), b"bin_construct_sample_cnt=5000",
        sidx, ctypes.byref(slen)))
    assert slen.value == 5000
    arr = np.array(sidx[:])
    assert (np.diff(arr) > 0).all() and arr.max() < 10 ** 7

    # feature names + validation + loaded params
    name_bufs = [ctypes.create_string_buffer(64) for _ in range(f)]
    names = (ctypes.c_char_p * f)(*[
        ctypes.cast(b, ctypes.c_char_p) for b in name_bufs])
    nn = ctypes.c_int()
    bl = ctypes.c_size_t()
    _check(lib, lib.LGBM_BoosterGetFeatureNames(
        bst, ctypes.c_int(f), ctypes.byref(nn), ctypes.c_size_t(64),
        ctypes.byref(bl), names))
    assert nn.value == f
    _check(lib, lib.LGBM_BoosterValidateFeatureNames(bst, names,
                                                     ctypes.c_int(f)))
    rc = lib.LGBM_BoosterValidateFeatureNames(
        bst, (ctypes.c_char_p * 1)(b"bogus"), ctypes.c_int(1))
    assert rc == -1
    pbuf = ctypes.create_string_buffer(1 << 16)
    plen = ctypes.c_int64()
    _check(lib, lib.LGBM_BoosterGetLoadedParam(
        bst, ctypes.c_int64(1 << 16), ctypes.byref(plen), pbuf))
    assert "num_leaves" in pbuf.value.decode()

    # error report helpers + log callback
    _check(lib, lib.LGBM_SetLastError(b"custom error"))
    assert lib.LGBM_GetLastError().decode() == "custom error"
    seen = []
    CB = ctypes.CFUNCTYPE(None, ctypes.c_char_p)
    cb = CB(lambda m: seen.append(m))
    _check(lib, lib.LGBM_RegisterLogCallback(cb))
    bst4 = ctypes.c_void_p()
    # num_threads triggers a deterministic warning through Log
    _check(lib, lib.LGBM_BoosterCreate(
        ds, b"objective=binary num_leaves=7 verbosity=2 num_threads=4",
        ctypes.byref(bst4)))
    _check(lib, lib.LGBM_BoosterUpdateOneIter(bst4, ctypes.byref(fin)))
    assert seen, "log callback never fired"
    CB0 = ctypes.CFUNCTYPE(None, ctypes.c_char_p)
    lib.LGBM_RegisterLogCallback(ctypes.cast(None, CB0))

    # network facade: single-machine init is a no-op success
    _check(lib, lib.LGBM_NetworkInit(b"", ctypes.c_int(0), ctypes.c_int(0),
                                     ctypes.c_int(1)))
    _check(lib, lib.LGBM_NetworkFree())


def test_capi_predict_csc_and_single_row():
    sp = pytest.importorskip("scipy.sparse")
    lib = _load()
    rng = np.random.RandomState(12)
    n, f = 700, 7
    X = rng.randn(n, f)
    y = (X[:, 0] > 0).astype(np.float64)
    ds = _dataset_from_mat(lib, X, y)
    bst = ctypes.c_void_p()
    _check(lib, lib.LGBM_BoosterCreate(
        ds, b"objective=binary num_leaves=15 verbosity=-1",
        ctypes.byref(bst)))
    fin = ctypes.c_int()
    for _ in range(5):
        _check(lib, lib.LGBM_BoosterUpdateOneIter(bst, ctypes.byref(fin)))
    Xp = np.ascontiguousarray(X[:40], np.float64)
    ref = (ctypes.c_double * 40)()
    out_n = ctypes.c_int64()
    _check(lib, lib.LGBM_BoosterPredictForMat(
        bst, Xp.ctypes.data_as(ctypes.c_void_p), 1, ctypes.c_int32(40),
        ctypes.c_int32(f), ctypes.c_int(1), ctypes.c_int(0), ctypes.c_int(0),
        ctypes.c_int(-1), b"", ctypes.byref(out_n), ref))

    # CSC batch predict
    csc = sp.csc_matrix(Xp)
    ip = np.ascontiguousarray(csc.indptr, np.int32)
    ind = np.ascontiguousarray(csc.indices, np.int32)
    vals = np.ascontiguousarray(csc.data, np.float64)
    out = (ctypes.c_double * 40)()
    _check(lib, lib.LGBM_BoosterPredictForCSC(
        bst, ip.ctypes.data_as(ctypes.c_void_p), 2,
        ind.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        vals.ctypes.data_as(ctypes.c_void_p), 1,
        ctypes.c_int64(len(ip)), ctypes.c_int64(csc.nnz),
        ctypes.c_int64(40), ctypes.c_int(0), ctypes.c_int(0),
        ctypes.c_int(-1), b"", ctypes.byref(out_n), out))
    np.testing.assert_allclose(np.array(out[:]), np.array(ref[:]),
                               rtol=1e-9)

    # single-row variants
    one = ctypes.c_double()
    row = np.ascontiguousarray(Xp[3], np.float64)
    _check(lib, lib.LGBM_BoosterPredictForMatSingleRow(
        bst, row.ctypes.data_as(ctypes.c_void_p), 1, ctypes.c_int(f),
        ctypes.c_int(1), ctypes.c_int(0), ctypes.c_int(0), ctypes.c_int(-1),
        b"", ctypes.byref(out_n), ctypes.byref(one)))
    np.testing.assert_allclose(one.value, ref[3], rtol=1e-9)
    csr = sp.csr_matrix(Xp[3:4])
    rip = np.ascontiguousarray(csr.indptr, np.int32)
    rind = np.ascontiguousarray(csr.indices, np.int32)
    rval = np.ascontiguousarray(csr.data, np.float64)
    _check(lib, lib.LGBM_BoosterPredictForCSRSingleRow(
        bst, rip.ctypes.data_as(ctypes.c_void_p), 2,
        rind.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        rval.ctypes.data_as(ctypes.c_void_p), 1,
        ctypes.c_int64(len(rip)), ctypes.c_int64(csr.nnz),
        ctypes.c_int64(f), ctypes.c_int(0), ctypes.c_int(0),
        ctypes.c_int(-1), b"", ctypes.byref(out_n), ctypes.byref(one)))
    np.testing.assert_allclose(one.value, ref[3], rtol=1e-9)


def test_capi_multiclass_tree_index_convention():
    """tree_idx is iteration-major (it*num_class + k, reference c_api):
    a get/set round trip must address the SAME tree."""
    lib = _load()
    rng = np.random.RandomState(13)
    n, f = 600, 5
    X = rng.randn(n, f)
    y = rng.randint(0, 3, n).astype(np.float64)
    ds = _dataset_from_mat(lib, X, y)
    bst = ctypes.c_void_p()
    _check(lib, lib.LGBM_BoosterCreate(
        ds, b"objective=multiclass num_class=3 num_leaves=7 verbosity=-1",
        ctypes.byref(bst)))
    fin = ctypes.c_int()
    for _ in range(2):
        _check(lib, lib.LGBM_BoosterUpdateOneIter(bst, ctypes.byref(fin)))
    for tree_idx in range(6):
        v = ctypes.c_double()
        _check(lib, lib.LGBM_BoosterGetLeafValue(
            bst, ctypes.c_int(tree_idx), ctypes.c_int(0), ctypes.byref(v)))
        _check(lib, lib.LGBM_BoosterSetLeafValue(
            bst, ctypes.c_int(tree_idx), ctypes.c_int(0),
            ctypes.c_double(v.value + 0.125)))
        v2 = ctypes.c_double()
        _check(lib, lib.LGBM_BoosterGetLeafValue(
            bst, ctypes.c_int(tree_idx), ctypes.c_int(0), ctypes.byref(v2)))
        assert abs(v2.value - v.value - 0.125) < 1e-12, tree_idx


def test_capi_arrow_interface():
    """Arrow C data interface (reference arrow.h + the three LGBM_*Arrow
    entry points): export pyarrow batches to C structs, create a dataset,
    set a field, train, and predict — all through raw Arrow pointers.
    Caller keeps struct ownership (shallow copies with no-op release)."""
    pa = pytest.importorskip("pyarrow")
    lib = _load()
    rng = np.random.RandomState(14)
    n, f = 800, 5
    X = rng.randn(n, f)
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.float64)
    table = pa.table({f"f{j}": X[:, j] for j in range(f)})
    batches = table.to_batches(max_chunksize=300)

    class ArrowArray(ctypes.Structure):
        _fields_ = [("length", ctypes.c_int64),
                    ("null_count", ctypes.c_int64),
                    ("offset", ctypes.c_int64),
                    ("n_buffers", ctypes.c_int64),
                    ("n_children", ctypes.c_int64),
                    ("buffers", ctypes.c_void_p),
                    ("children", ctypes.c_void_p),
                    ("dictionary", ctypes.c_void_p),
                    ("release", ctypes.c_void_p),
                    ("private_data", ctypes.c_void_p)]

    class ArrowSchema(ctypes.Structure):
        _fields_ = [("format", ctypes.c_char_p),
                    ("name", ctypes.c_char_p),
                    ("metadata", ctypes.c_char_p),
                    ("flags", ctypes.c_int64),
                    ("n_children", ctypes.c_int64),
                    ("children", ctypes.c_void_p),
                    ("dictionary", ctypes.c_void_p),
                    ("release", ctypes.c_void_p),
                    ("private_data", ctypes.c_void_p)]

    n_chunks = len(batches)
    chunk_arr = (ArrowArray * n_chunks)()
    schema = ArrowSchema()
    # export schema once and every batch
    batches[0]._export_to_c(ctypes.addressof(chunk_arr[0]),
                            ctypes.addressof(schema))
    for i in range(1, n_chunks):
        batches[i]._export_to_c(ctypes.addressof(chunk_arr[i]))

    ds = ctypes.c_void_p()
    _check(lib, lib.LGBM_DatasetCreateFromArrow(
        ctypes.c_int64(n_chunks), chunk_arr, ctypes.byref(schema),
        b"max_bin=63", ctypes.c_void_p(), ctypes.byref(ds)))
    nd = ctypes.c_int32()
    _check(lib, lib.LGBM_DatasetGetNumData(ds, ctypes.byref(nd)))
    assert nd.value == n

    # label via Arrow
    lab = pa.array(y.astype(np.float32))
    lab_arr = ArrowArray()
    lab_schema = ArrowSchema()
    lab._export_to_c(ctypes.addressof(lab_arr),
                     ctypes.addressof(lab_schema))
    _check(lib, lib.LGBM_DatasetSetFieldFromArrow(
        ds, b"label", ctypes.c_int64(1), ctypes.byref(lab_arr),
        ctypes.byref(lab_schema)))

    bst = ctypes.c_void_p()
    _check(lib, lib.LGBM_BoosterCreate(
        ds, b"objective=binary num_leaves=15 verbosity=-1 max_bin=63",
        ctypes.byref(bst)))
    fin = ctypes.c_int()
    for _ in range(5):
        _check(lib, lib.LGBM_BoosterUpdateOneIter(bst, ctypes.byref(fin)))

    # predict through Arrow, compare against the Mat path
    p_arrow = (ctypes.c_double * n)()
    out_n = ctypes.c_int64()
    chunk_arr2 = (ArrowArray * n_chunks)()
    schema2 = ArrowSchema()
    batches[0]._export_to_c(ctypes.addressof(chunk_arr2[0]),
                            ctypes.addressof(schema2))
    for i in range(1, n_chunks):
        batches[i]._export_to_c(ctypes.addressof(chunk_arr2[i]))
    _check(lib, lib.LGBM_BoosterPredictForArrow(
        bst, ctypes.c_int64(n_chunks), chunk_arr2, ctypes.byref(schema2),
        ctypes.c_int(0), ctypes.c_int(0), ctypes.c_int(-1), b"",
        ctypes.byref(out_n), p_arrow))
    assert out_n.value == n
    Xa = np.ascontiguousarray(X, np.float64)
    p_mat = (ctypes.c_double * n)()
    _check(lib, lib.LGBM_BoosterPredictForMat(
        bst, Xa.ctypes.data_as(ctypes.c_void_p), 1, ctypes.c_int32(n),
        ctypes.c_int32(f), ctypes.c_int(1), ctypes.c_int(0), ctypes.c_int(0),
        ctypes.c_int(-1), b"", ctypes.byref(out_n), p_mat))
    np.testing.assert_allclose(np.array(p_arrow[:]), np.array(p_mat[:]),
                               rtol=1e-9)


def test_capi_serialized_reference_and_mats():
    """ByteBuffer reference serialization (c_api.h:162-215): serialize a
    dataset's bin mappers, rebuild an aligned streaming dataset from the
    buffer in a 'fresh worker', push rows, train — bins align with the
    original.  Plus CreateFromMats and PredictForMats."""
    lib = _load()
    rng = np.random.RandomState(15)
    n, f = 700, 6
    X = rng.randn(n, f)
    y = (X[:, 0] > 0).astype(np.float64)
    ds = _dataset_from_mat(lib, X, y, params=b"max_bin=31")

    buf = ctypes.c_void_p()
    blen = ctypes.c_int32()
    _check(lib, lib.LGBM_DatasetSerializeReferenceToBinary(
        ds, ctypes.byref(buf), ctypes.byref(blen)))
    assert blen.value > 64
    # spot-check GetAt, then read the full buffer byte-by-byte (the
    # reference's consumption pattern for shipping the buffer elsewhere)
    one = ctypes.c_uint8()
    full = bytearray(blen.value)
    for i in range(blen.value):
        _check(lib, lib.LGBM_ByteBufferGetAt(buf, ctypes.c_int32(i),
                                             ctypes.byref(one)))
        full[i] = one.value
    full = bytes(full)
    rc = lib.LGBM_ByteBufferGetAt(buf, ctypes.c_int32(blen.value),
                                  ctypes.byref(one))
    assert rc == -1                      # out-of-range errors, not crashes

    stream = ctypes.c_void_p()
    cbuf = (ctypes.c_char * len(full)).from_buffer_copy(full)
    _check(lib, lib.LGBM_DatasetCreateFromSerializedReference(
        cbuf, ctypes.c_int32(len(full)), ctypes.c_int64(n),
        ctypes.c_int32(1), b"max_bin=31", ctypes.byref(stream)))
    _check(lib, lib.LGBM_DatasetInitStreaming(
        stream, 0, 0, 0, ctypes.c_int32(1), ctypes.c_int32(1),
        ctypes.c_int32(1)))
    Xa = np.ascontiguousarray(X, np.float64)
    lab = np.ascontiguousarray(y, np.float32)
    _check(lib, lib.LGBM_DatasetPushRowsWithMetadata(
        stream, Xa.ctypes.data_as(ctypes.c_void_p), 1, ctypes.c_int32(n),
        ctypes.c_int32(f), ctypes.c_int32(0),
        lab.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), None, None,
        None, ctypes.c_int32(0)))
    _check(lib, lib.LGBM_DatasetMarkFinished(stream))
    nb1 = ctypes.c_int()
    nb2 = ctypes.c_int()
    _check(lib, lib.LGBM_DatasetGetFeatureNumBin(ds, 0, ctypes.byref(nb1)))
    _check(lib, lib.LGBM_DatasetGetFeatureNumBin(stream, 0,
                                                 ctypes.byref(nb2)))
    assert nb1.value == nb2.value
    _check(lib, lib.LGBM_ByteBufferFree(buf))

    # CreateFromMats: two blocks == one matrix
    half = n // 2
    b1 = np.ascontiguousarray(X[:half], np.float64)
    b2 = np.ascontiguousarray(X[half:], np.float64)
    ptrs = (ctypes.c_void_p * 2)(b1.ctypes.data_as(ctypes.c_void_p),
                                 b2.ctypes.data_as(ctypes.c_void_p))
    nrows = (ctypes.c_int32 * 2)(half, n - half)
    majors = (ctypes.c_int * 2)(1, 1)
    dmats = ctypes.c_void_p()
    _check(lib, lib.LGBM_DatasetCreateFromMats(
        ctypes.c_int32(2), ptrs, 1, nrows, ctypes.c_int32(f), majors,
        b"max_bin=31", ctypes.c_void_p(), ctypes.byref(dmats)))
    ndm = ctypes.c_int32()
    _check(lib, lib.LGBM_DatasetGetNumData(dmats, ctypes.byref(ndm)))
    assert ndm.value == n

    # PredictForMats row-pointer batch == contiguous batch
    bst = ctypes.c_void_p()
    _check(lib, lib.LGBM_BoosterCreate(
        ds, b"objective=binary num_leaves=7 verbosity=-1 max_bin=31",
        ctypes.byref(bst)))
    fin = ctypes.c_int()
    for _ in range(3):
        _check(lib, lib.LGBM_BoosterUpdateOneIter(bst, ctypes.byref(fin)))
    rows = np.ascontiguousarray(X[:20], np.float64)
    rptrs = (ctypes.c_void_p * 20)(*[
        rows[i:i + 1].ctypes.data_as(ctypes.c_void_p) for i in range(20)])
    outm = (ctypes.c_double * 20)()
    out_n = ctypes.c_int64()
    _check(lib, lib.LGBM_BoosterPredictForMats(
        bst, rptrs, 1, ctypes.c_int32(20), ctypes.c_int32(f),
        ctypes.c_int(0), ctypes.c_int(0), ctypes.c_int(-1), b"",
        ctypes.byref(out_n), outm))
    ref = (ctypes.c_double * 20)()
    _check(lib, lib.LGBM_BoosterPredictForMat(
        bst, rows.ctypes.data_as(ctypes.c_void_p), 1, ctypes.c_int32(20),
        ctypes.c_int32(f), ctypes.c_int(1), ctypes.c_int(0), ctypes.c_int(0),
        ctypes.c_int(-1), b"", ctypes.byref(out_n), ref))
    np.testing.assert_allclose(np.array(outm[:]), np.array(ref[:]),
                               rtol=1e-9)


def test_capi_multiclass_custom_objective_layout():
    """LGBM_BoosterUpdateOneIterCustom and LGBM_BoosterGetPredict use the
    reference's CLASS-MAJOR buffers (grad[class*num_data+row], c_api.h;
    GBDT::GetPredictAt gbdt.cpp:665).  Feeding class-major softmax
    gradients through the C API must reproduce the built-in multiclass
    objective — a row-major mixup scrambles classes and diverges wildly
    (ADVICE r4 medium #1)."""
    lib = _load()
    rng = np.random.RandomState(7)
    n, f, k = 600, 5, 3
    X = rng.randn(n, f)
    y = (X[:, 0] + 0.7 * X[:, 1] > 0).astype(int) + (X[:, 2] > 0.5)

    params = (b"objective=multiclass num_class=3 num_leaves=7 "
             b"verbosity=-1 boost_from_average=false")
    ds_a = _dataset_from_mat(lib, X, y)
    bst_a = ctypes.c_void_p()
    _check(lib, lib.LGBM_BoosterCreate(ds_a, params, ctypes.byref(bst_a)))
    fin = ctypes.c_int()
    iters = 4
    for _ in range(iters):
        _check(lib, lib.LGBM_BoosterUpdateOneIter(bst_a, ctypes.byref(fin)))

    ds_b = _dataset_from_mat(lib, X, y)
    bst_b = ctypes.c_void_p()
    _check(lib, lib.LGBM_BoosterCreate(
        ds_b, b"objective=custom num_class=3 num_leaves=7 verbosity=-1 "
        b"boost_from_average=false",
        ctypes.byref(bst_b)))
    onehot = np.eye(k, dtype=np.float64)[y]
    out_len = ctypes.c_int64()
    scores = (ctypes.c_double * (n * k))()
    for _ in range(iters):
        # class-major raw scores of the CURRENT model state
        _check(lib, lib.LGBM_BoosterGetPredict(
            bst_b, ctypes.c_int(0), ctypes.byref(out_len), scores))
        assert out_len.value == n * k
        s = np.array(scores[:]).reshape(k, n).T          # back to (n, k)
        e = np.exp(s - s.max(axis=1, keepdims=True))
        p = e / e.sum(axis=1, keepdims=True)
        grad = np.ascontiguousarray((p - onehot).T, np.float32)  # (k, n)
        # reference softmax hessian factor k/(k-1) (multiclass_objective.hpp:31)
        hess = np.ascontiguousarray(
            (k / (k - 1.0) * p * (1.0 - p)).T, np.float32)
        _check(lib, lib.LGBM_BoosterUpdateOneIterCustom(
            bst_b,
            grad.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            hess.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            ctypes.byref(fin)))

    def _raw_predict(bst):
        out = (ctypes.c_double * (n * k))()
        m = ctypes.c_int64()
        _check(lib, lib.LGBM_BoosterPredictForMat(
            bst, np.ascontiguousarray(X, np.float32).ctypes.data_as(
                ctypes.c_void_p), 0, ctypes.c_int32(n), ctypes.c_int32(f),
            ctypes.c_int(1), ctypes.c_int(1), ctypes.c_int(0),
            ctypes.c_int(-1), b"", ctypes.byref(m), out))
        return np.array(out[: n * k]).reshape(n, k)

    a, b = _raw_predict(bst_a), _raw_predict(bst_b)
    np.testing.assert_allclose(b, a, rtol=2e-3, atol=2e-3)


def test_capi_sparse_predict_output():
    """LGBM_BoosterPredictSparseOutput returns num_class stacked CSR
    matrices of non-zero SHAP contributions with one shared data buffer
    (reference Booster::PredictSparseCSR, c_api.cpp); parity against the
    dense contrib path, then LGBM_BoosterFreePredictSparse releases it."""
    import scipy.sparse as sp

    lib = _load()
    rng = np.random.RandomState(3)
    n, f = 300, 6
    X = rng.randn(n, f)
    X[rng.rand(n, f) < 0.3] = 0.0
    y = (X[:, 0] + X[:, 1] > 0).astype(float)
    ds = _dataset_from_mat(lib, X, y)
    bst = ctypes.c_void_p()
    _check(lib, lib.LGBM_BoosterCreate(
        ds, b"objective=binary num_leaves=15 verbosity=-1",
        ctypes.byref(bst)))
    fin = ctypes.c_int()
    for _ in range(5):
        _check(lib, lib.LGBM_BoosterUpdateOneIter(bst, ctypes.byref(fin)))

    Xcsr = sp.csr_matrix(X)
    indptr = np.ascontiguousarray(Xcsr.indptr, np.int32)
    indices = np.ascontiguousarray(Xcsr.indices, np.int32)
    data = np.ascontiguousarray(Xcsr.data, np.float64)

    out_len = (ctypes.c_int64 * 2)()
    out_indptr = ctypes.c_void_p()
    out_indices = ctypes.POINTER(ctypes.c_int32)()
    out_data = ctypes.c_void_p()
    _check(lib, lib.LGBM_BoosterPredictSparseOutput(
        bst, indptr.ctypes.data_as(ctypes.c_void_p), ctypes.c_int(2),
        indices.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        data.ctypes.data_as(ctypes.c_void_p), ctypes.c_int(1),
        ctypes.c_int64(len(indptr)), ctypes.c_int64(len(data)),
        ctypes.c_int64(f), ctypes.c_int(3),  # C_API_PREDICT_CONTRIB
        ctypes.c_int(0), ctypes.c_int(-1), b"", ctypes.c_int(0),  # CSR
        out_len, ctypes.byref(out_indptr), ctypes.byref(out_indices),
        ctypes.byref(out_data)))
    nnz, ip_len = out_len[0], out_len[1]
    assert ip_len == n + 1          # one class -> one stacked matrix
    got_ip = np.ctypeslib.as_array(
        ctypes.cast(out_indptr, ctypes.POINTER(ctypes.c_int32)),
        shape=(ip_len,)).copy()
    got_ix = np.ctypeslib.as_array(out_indices, shape=(max(nnz, 1),))[
        :nnz].copy()
    got_dt = np.ctypeslib.as_array(
        ctypes.cast(out_data, ctypes.POINTER(ctypes.c_double)),
        shape=(max(nnz, 1),))[:nnz].copy()
    sparse_contrib = sp.csr_matrix((got_dt, got_ix, got_ip),
                                   shape=(n, f + 1)).toarray()

    dense = (ctypes.c_double * (n * (f + 1)))()
    m = ctypes.c_int64()
    _check(lib, lib.LGBM_BoosterPredictForCSR(
        bst, indptr.ctypes.data_as(ctypes.c_void_p), ctypes.c_int(2),
        indices.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        data.ctypes.data_as(ctypes.c_void_p), ctypes.c_int(1),
        ctypes.c_int64(len(indptr)), ctypes.c_int64(len(data)),
        ctypes.c_int64(f), ctypes.c_int(3), ctypes.c_int(0),
        ctypes.c_int(-1), b"", ctypes.byref(m), dense))
    np.testing.assert_allclose(
        sparse_contrib, np.array(dense[:]).reshape(n, f + 1), rtol=1e-9)
    _check(lib, lib.LGBM_BoosterFreePredictSparse(
        out_indptr, out_indices, out_data, ctypes.c_int(2),
        ctypes.c_int(1)))


def test_capi_csr_single_row_fast():
    """FastConfig pair for CSR rows (reference c_api.h:1162-1202): per-row
    predictions must match the batch CSR path."""
    import scipy.sparse as sp

    lib = _load()
    rng = np.random.RandomState(5)
    n, f = 400, 5
    X = rng.randn(n, f)
    X[rng.rand(n, f) < 0.4] = 0.0
    y = (X[:, 0] - X[:, 2] > 0).astype(float)
    ds = _dataset_from_mat(lib, X, y)
    bst = ctypes.c_void_p()
    _check(lib, lib.LGBM_BoosterCreate(
        ds, b"objective=binary num_leaves=15 verbosity=-1",
        ctypes.byref(bst)))
    fin = ctypes.c_int()
    for _ in range(5):
        _check(lib, lib.LGBM_BoosterUpdateOneIter(bst, ctypes.byref(fin)))

    fast = ctypes.c_void_p()
    _check(lib, lib.LGBM_BoosterPredictForCSRSingleRowFastInit(
        bst, ctypes.c_int(0), ctypes.c_int(0), ctypes.c_int(-1),
        ctypes.c_int(1), ctypes.c_int64(f), b"", ctypes.byref(fast)))

    batch = np.zeros(n)
    outv = ctypes.c_double()
    out_n = ctypes.c_int64()
    full = (ctypes.c_double * n)()
    Xcsr = sp.csr_matrix(X)
    _check(lib, lib.LGBM_BoosterPredictForCSR(
        bst,
        np.ascontiguousarray(Xcsr.indptr, np.int32).ctypes.data_as(
            ctypes.c_void_p), ctypes.c_int(2),
        np.ascontiguousarray(Xcsr.indices, np.int32).ctypes.data_as(
            ctypes.POINTER(ctypes.c_int32)),
        np.ascontiguousarray(Xcsr.data, np.float64).ctypes.data_as(
            ctypes.c_void_p), ctypes.c_int(1),
        ctypes.c_int64(n + 1), ctypes.c_int64(Xcsr.nnz), ctypes.c_int64(f),
        ctypes.c_int(0), ctypes.c_int(0), ctypes.c_int(-1), b"",
        ctypes.byref(out_n), full))
    for i in range(0, n, 37):
        row = sp.csr_matrix(X[i:i + 1])
        rp = np.ascontiguousarray(row.indptr, np.int32)
        ri = np.ascontiguousarray(row.indices, np.int32)
        rd = np.ascontiguousarray(row.data, np.float64)
        if row.nnz == 0:
            ri = np.zeros(1, np.int32)
            rd = np.zeros(1, np.float64)
        _check(lib, lib.LGBM_BoosterPredictForCSRSingleRowFast(
            fast, rp.ctypes.data_as(ctypes.c_void_p), ctypes.c_int(2),
            ri.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            rd.ctypes.data_as(ctypes.c_void_p), ctypes.c_int64(2),
            ctypes.c_int64(row.nnz), ctypes.byref(out_n),
            ctypes.byref(outv)))
        batch[i] = outv.value
        # fast path bins through baked f32 LUTs; 1e-6 covers the rounding
        np.testing.assert_allclose(outv.value, full[i], rtol=1e-6)
    _check(lib, lib.LGBM_FastConfigFree(fast))


def test_capi_dataset_create_from_csr_func(tmp_path):
    """LGBM_DatasetCreateFromCSRFunc consumes a C++ row callback
    (std::function pointer, the SynapseML seam — reference c_api.h:363);
    driven here through a small compiled helper."""
    import subprocess
    import sys
    import sysconfig

    helper_src = tmp_path / "rowfn.cpp"
    helper_src.write_text(r"""
    #include <functional>
    #include <utility>
    #include <vector>
    #include <cmath>
    using RowFn = std::function<void(int, std::vector<std::pair<int, double>>&)>;
    static RowFn g_fn = [](int i, std::vector<std::pair<int, double>>& ret) {
      ret.clear();
      ret.emplace_back(i % 4, std::sin(i * 0.7) + 1.5);
      if (i % 3 == 0) ret.emplace_back(4, 1.0);
    };
    extern "C" void* make_row_fn() { return &g_fn; }
    """)
    so = tmp_path / "rowfn.so"
    subprocess.run(["g++", "-O1", "-shared", "-fPIC", str(helper_src),
                    "-o", str(so)], check=True)
    helper = ctypes.CDLL(str(so))
    helper.make_row_fn.restype = ctypes.c_void_p

    lib = _load()
    n, f = 600, 5
    ds = ctypes.c_void_p()
    _check(lib, lib.LGBM_DatasetCreateFromCSRFunc(
        ctypes.c_void_p(helper.make_row_fn()), ctypes.c_int(n),
        ctypes.c_int64(f), b"min_data_in_bin=1", ctypes.c_void_p(),
        ctypes.byref(ds)))
    nd, nf = ctypes.c_int32(), ctypes.c_int32()
    _check(lib, lib.LGBM_DatasetGetNumData(ds, ctypes.byref(nd)))
    _check(lib, lib.LGBM_DatasetGetNumFeature(ds, ctypes.byref(nf)))
    assert (nd.value, nf.value) == (n, f)
    # label + one boosting iteration proves the dataset is usable
    y = np.ascontiguousarray((np.arange(n) % 4 < 2).astype(np.float32))
    _check(lib, lib.LGBM_DatasetSetField(
        ds, b"label", y.ctypes.data_as(ctypes.c_void_p), ctypes.c_int(n),
        0))
    bst = ctypes.c_void_p()
    _check(lib, lib.LGBM_BoosterCreate(
        ds, b"objective=binary num_leaves=7 verbosity=-1",
        ctypes.byref(bst)))
    fin = ctypes.c_int()
    _check(lib, lib.LGBM_BoosterUpdateOneIter(bst, ctypes.byref(fin)))


def test_capi_network_init_with_functions():
    """LGBM_NetworkInitWithFunctions (reference c_api.cpp:2773, the
    SynapseML injection seam) installs external reduce-scatter/allgather
    C functions as the collectives-facade transport; a training run with
    the backend installed keeps working, and the facade routes through
    the injected functions until LGBM_NetworkFree."""
    import jax.numpy as jnp

    import lightgbm_tpu as lgb
    import lightgbm_tpu.parallel.collectives as C
    from lightgbm_tpu.parallel.mesh import make_mesh

    lib = _load()
    calls = []
    world = 2

    AG_T = ctypes.CFUNCTYPE(
        None, ctypes.c_void_p, ctypes.c_int32,
        ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int32),
        ctypes.c_int, ctypes.c_void_p, ctypes.c_int32)
    RS_T = ctypes.CFUNCTYPE(
        None, ctypes.c_void_p, ctypes.c_int32, ctypes.c_int,
        ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int32),
        ctypes.c_int, ctypes.c_void_p, ctypes.c_int32, ctypes.c_void_p)

    def fake_allgather(inp, in_size, starts, lens, nblock, out, out_size):
        # single-process fake: every "rank" contributes the same block
        calls.append("allgather")
        blk = ctypes.string_at(inp, in_size)
        buf = (ctypes.c_char * out_size).from_address(out)
        for b in range(nblock):
            buf[starts[b]:starts[b] + lens[b]] = blk[:lens[b]]

    def fake_reduce_scatter(inp, in_size, type_size, starts, lens, nblock,
                            out, out_size, reducer):
        # world identical contributions -> own block times world
        calls.append("reduce_scatter")
        own = np.frombuffer(ctypes.string_at(inp, lens[0]), np.float32)
        res = (own * world).astype(np.float32).tobytes()
        ctypes.memmove(out, res, min(out_size, len(res)))

    ag = AG_T(fake_allgather)
    rs = RS_T(fake_reduce_scatter)
    _check(lib, lib.LGBM_NetworkInitWithFunctions(
        ctypes.c_int(world), ctypes.c_int(0),
        ctypes.cast(rs, ctypes.c_void_p), ctypes.cast(ag, ctypes.c_void_p)))
    try:
        mesh = make_mesh()
        v = jnp.ones(4)
        s = np.asarray(C.global_sum(v, mesh))
        # fake allgather replicates this rank's contribution world times,
        # so the backend's sum over ranks doubles each element
        np.testing.assert_allclose(s, world * np.ones(4))
        hist = jnp.arange(8 * 4 * 3, dtype=jnp.float32).reshape(8, 4, 3)
        red = np.asarray(C.histogram_reduce_scatter(hist, mesh))
        # the single-process fakes: reduce_scatter returns own block * world,
        # allgather replicates this rank's block into every slot
        expect = np.tile(np.asarray(hist[:4]) * world, (world, 1, 1))
        np.testing.assert_allclose(red, expect)
        assert "allgather" in calls and "reduce_scatter" in calls
        # training still works with the backend installed (the in-jit
        # grower collectives are XLA's and unaffected by design)
        rng = np.random.RandomState(0)
        X = rng.randn(500, 4)
        y = (X[:, 0] > 0).astype(float)
        bst = lgb.train({"objective": "binary", "num_leaves": 7,
                         "verbosity": -1}, lgb.Dataset(X, label=y), 3)
        assert bst.num_trees() == 3
    finally:
        _check(lib, lib.LGBM_NetworkFree())
    assert C._comm_backend is None


def test_capi_sparse_predict_output_csc():
    """CSC matrix_type: input is column-compressed and the output is a CSC
    matrix over the (num_data, num_feature+1) contribution block — col_ptr
    of length ncols_out+1 per class (reference Booster::PredictSparseCSC)."""
    import scipy.sparse as sp

    lib = _load()
    rng = np.random.RandomState(11)
    n, f = 250, 5
    X = rng.randn(n, f)
    X[rng.rand(n, f) < 0.35] = 0.0
    y = (X[:, 0] - X[:, 1] > 0).astype(float)
    ds = _dataset_from_mat(lib, X, y)
    bst = ctypes.c_void_p()
    _check(lib, lib.LGBM_BoosterCreate(
        ds, b"objective=binary num_leaves=7 verbosity=-1", ctypes.byref(bst)))
    fin = ctypes.c_int()
    for _ in range(4):
        _check(lib, lib.LGBM_BoosterUpdateOneIter(bst, ctypes.byref(fin)))

    Xcsc = sp.csc_matrix(X)
    col_ptr = np.ascontiguousarray(Xcsc.indptr, np.int32)
    indices = np.ascontiguousarray(Xcsc.indices, np.int32)
    data = np.ascontiguousarray(Xcsc.data, np.float64)
    out_len = (ctypes.c_int64 * 2)()
    oip = ctypes.c_void_p()
    oix = ctypes.POINTER(ctypes.c_int32)()
    odt = ctypes.c_void_p()
    _check(lib, lib.LGBM_BoosterPredictSparseOutput(
        bst, col_ptr.ctypes.data_as(ctypes.c_void_p), ctypes.c_int(2),
        indices.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        data.ctypes.data_as(ctypes.c_void_p), ctypes.c_int(1),
        ctypes.c_int64(len(col_ptr)), ctypes.c_int64(len(data)),
        ctypes.c_int64(n),               # CSC: num rows
        ctypes.c_int(3), ctypes.c_int(0), ctypes.c_int(-1), b"",
        ctypes.c_int(1),                 # C_API_MATRIX_TYPE_CSC
        out_len, ctypes.byref(oip), ctypes.byref(oix), ctypes.byref(odt)))
    nnz, ip_len = out_len[0], out_len[1]
    assert ip_len == f + 2               # (ncols_out + 1) per class
    got_ip = np.ctypeslib.as_array(
        ctypes.cast(oip, ctypes.POINTER(ctypes.c_int32)),
        shape=(ip_len,)).copy()
    got_ix = np.ctypeslib.as_array(oix, shape=(max(nnz, 1),))[:nnz].copy()
    got_dt = np.ctypeslib.as_array(
        ctypes.cast(odt, ctypes.POINTER(ctypes.c_double)),
        shape=(max(nnz, 1),))[:nnz].copy()
    contrib_csc = sp.csc_matrix((got_dt, got_ix, got_ip),
                                shape=(n, f + 1)).toarray()
    _check(lib, lib.LGBM_BoosterFreePredictSparse(
        oip, oix, odt, ctypes.c_int(2), ctypes.c_int(1)))

    # parity vs the dense contrib path on the same rows
    dense = (ctypes.c_double * (n * (f + 1)))()
    m = ctypes.c_int64()
    X32 = np.ascontiguousarray(X, np.float32)
    _check(lib, lib.LGBM_BoosterPredictForMat(
        bst, X32.ctypes.data_as(ctypes.c_void_p), 0, ctypes.c_int32(n),
        ctypes.c_int32(f), ctypes.c_int(1), ctypes.c_int(3), ctypes.c_int(0),
        ctypes.c_int(-1), b"", ctypes.byref(m), dense))
    np.testing.assert_allclose(
        contrib_csc, np.array(dense[:]).reshape(n, f + 1), rtol=1e-9)
