"""Linear trees (reference ``LinearTreeLearner``) and CEGB (reference
``cost_effective_gradient_boosting.hpp``) behavior tests."""

import numpy as np
import pytest

import lightgbm_tpu as lgb


def _linear_data(rng, n=3000, f=5):
    X = rng.randn(n, f)
    y = 3.0 * X[:, 0] + 2.0 * X[:, 1] + 0.1 * rng.randn(n)
    return X, y


def test_linear_tree_beats_constant_leaves_on_linear_data(rng):
    X, y = _linear_data(rng)
    rmses = {}
    for lin in (False, True):
        ds = lgb.Dataset(X[:2400], label=y[:2400])
        bst = lgb.train({"objective": "regression", "num_leaves": 15,
                         "verbosity": -1, "linear_tree": lin,
                         "linear_lambda": 0.01}, ds, 30)
        p = bst.predict(X[2400:])
        rmses[lin] = np.sqrt(((p - y[2400:]) ** 2).mean())
    assert rmses[True] < rmses[False] * 0.8


def test_linear_tree_save_load_roundtrip(rng, tmp_path):
    X, y = _linear_data(rng, n=2000)
    X[::31, 2] = np.nan
    ds = lgb.Dataset(X, label=y)
    bst = lgb.train({"objective": "regression", "num_leaves": 15,
                     "verbosity": -1, "linear_tree": True}, ds, 15)
    p1 = bst.predict(X)
    path = str(tmp_path / "lin.txt")
    bst.save_model(path)
    b2 = lgb.Booster(model_file=path)
    p2 = b2.predict(X)
    np.testing.assert_allclose(p1, p2, rtol=1e-6, atol=1e-6)


def test_linear_tree_nan_rows_fall_back(rng):
    X, y = _linear_data(rng, n=2000)
    ds = lgb.Dataset(X, label=y)
    bst = lgb.train({"objective": "regression", "num_leaves": 7,
                     "verbosity": -1, "linear_tree": True}, ds, 5)
    Xq = X[:10].copy()
    Xq[:, :] = np.nan
    p = bst.predict(Xq)
    assert np.isfinite(p).all()


def test_linear_tree_with_valid_set(rng):
    X, y = _linear_data(rng)
    ds = lgb.Dataset(X[:2400], label=y[:2400])
    vs = lgb.Dataset(X[2400:], label=y[2400:], reference=ds)
    evals = {}
    bst = lgb.train({"objective": "regression", "num_leaves": 15,
                     "verbosity": -1, "linear_tree": True, "metric": "l2"},
                    ds, 20, valid_sets=[vs],
                    callbacks=[lgb.record_evaluation(evals)])
    curve = evals["valid_0"]["l2"]
    assert curve[-1] < curve[0]
    # recorded valid metric must match fresh prediction
    p = bst.predict(X[2400:])
    assert abs(((p - y[2400:]) ** 2).mean() - curve[-1]) < 1e-3


def test_cegb_coupled_penalty_reduces_features(rng):
    X = rng.randn(4000, 10)
    y = (X[:, 0] + 0.5 * X[:, 1] + 0.25 * X[:, 2] > 0).astype(float)
    used = {}
    for name, params in (
        ("base", {}),
        ("cegb", {"cegb_tradeoff": 1.0,
                  "cegb_penalty_feature_coupled": [5.0] * 10}),
    ):
        ds = lgb.Dataset(X, label=y)
        bst = lgb.train({"objective": "binary", "num_leaves": 31,
                         "verbosity": -1, **params}, ds, 10)
        used[name] = int((bst.feature_importance() > 0).sum())
    assert used["cegb"] <= used["base"]


def test_cegb_split_penalty_shrinks_trees(rng):
    X = rng.randn(3000, 6)
    y = (X[:, 0] > 0).astype(float) + 0.05 * rng.randn(3000)
    leaves = {}
    for name, pen in (("base", 0.0), ("cegb", 10.0)):
        ds = lgb.Dataset(X, label=y)
        bst = lgb.train({"objective": "regression", "num_leaves": 63,
                         "verbosity": -1, "cegb_penalty_split": pen,
                         "cegb_tradeoff": 0.001}, ds, 3)
        leaves[name] = bst.dump_model()["tree_info"][0]["num_leaves"]
    assert leaves["cegb"] <= leaves["base"]


def test_cegb_model_still_accurate(rng):
    X = rng.randn(4000, 10)
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(float)
    ds = lgb.Dataset(X, label=y)
    bst = lgb.train({"objective": "binary", "num_leaves": 31, "verbosity": -1,
                     "cegb_tradeoff": 1.0,
                     "cegb_penalty_feature_coupled": [2.0] * 10}, ds, 20)
    acc = ((bst.predict(X) > 0.5) == y).mean()
    assert acc > 0.9
