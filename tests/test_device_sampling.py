"""ISSUE-5 device-resident sampling/penalty paths.

Device GOSS (``tpu_device_goss``): the in-trace mask's top set must match
the host sampler's bit-for-bit under distinct scores and carry the exact
``(1-top_rate)/other_rate`` amplification; the random rest-sample is a
different (seed-keyed device) stream than the host ``np.random`` one, so
end-to-end quality is pinned by AUC parity, not bitwise equality.

Fused CEGB: deterministic, so routing it through the one-dispatch fused
iteration must leave trees BITWISE identical to the per-tree
``_grow_apply`` fallback (fp32 x quantized x EFB).

Linear trees: the batched device solve must match the reference-style
host f64 solve (``LIGHTGBM_TPU_HOST_LINEAR=1`` facade) to float tolerance.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.config import Config
from lightgbm_tpu.sampling import SampleStrategy, goss_mask_device


def _data(n=3000, f=8, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, f)
    y = (X[:, 0] + 0.6 * X[:, 1] * X[:, 2] + 0.3 * rng.randn(n) > 0)
    return X, y.astype(np.float64)


def _auc(y, s):
    order = np.argsort(s)
    ranks = np.empty(len(s))
    ranks[order] = np.arange(1, len(s) + 1)
    pos = y == 1
    npos, nneg = pos.sum(), (~pos).sum()
    return (ranks[pos].sum() - npos * (npos + 1) / 2) / (npos * nneg)


def _unfuse(bst):
    """Force the per-round non-fused branch (the pre-ISSUE-5 path shape):
    gradients in their own dispatch, per-tree _grow_apply."""
    bst._gbdt._fused_iter = None
    return bst


class TestDeviceGoss:
    def test_top_set_matches_host_under_distinct_scores(self):
        rng = np.random.RandomState(3)
        n = 5000
        grad = rng.randn(n).astype(np.float32)
        hess = (0.1 + rng.rand(n)).astype(np.float32)
        cfg = Config({"data_sample_strategy": "goss",
                      "top_rate": 0.2, "other_rate": 0.1,
                      "verbosity": -1})
        strat = SampleStrategy(cfg, n)
        top_k, other_k, amp = strat.goss_constants()
        host = strat.mask(0, grad, hess)
        dev = np.asarray(goss_mask_device(
            jnp.asarray(grad), jnp.asarray(hess), jax.random.PRNGKey(9),
            top_k, other_k, amp))
        # the deterministic top set (mask == 1.0) is identical
        np.testing.assert_array_equal(host == 1.0, dev == 1.0)
        assert int((dev == 1.0).sum()) == top_k
        # rest-sample: exact count, exact amplification weight, disjoint
        # from the top set
        amp32 = np.float32(amp)
        assert int((dev == amp32).sum()) == other_k
        assert not np.any((dev == amp32) & (host == 1.0))
        assert set(np.unique(dev)) <= {np.float32(0.0), np.float32(1.0),
                                       amp32}
        # host path carries the same amplification value
        assert int((host == amp32).sum()) == other_k

    def test_fused_goss_identical_to_standalone_device_mask(self):
        """auto (in-trace mask inside the fused dispatch) and the
        non-fused standalone-mask branch (tpu_device_goss=on with the
        fused program disabled) share one key stream and must produce
        bitwise-identical trees."""
        X, y = _data()
        params = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
                  "data_sample_strategy": "goss", "metric": "none"}
        fused = lgb.Booster(params=params, train_set=lgb.Dataset(X, label=y))
        standalone = _unfuse(lgb.Booster(
            params=dict(params, tpu_device_goss="on"),
            train_set=lgb.Dataset(X, label=y)))
        for _ in range(6):
            fused.update()
            standalone.update()
        assert fused._gbdt.fused_path_active is True
        assert standalone._gbdt.fused_path_active is False
        for tf, ts in zip(fused._gbdt.models[0], standalone._gbdt.models[0]):
            assert tf.num_leaves == ts.num_leaves
            k = max(tf.num_leaves - 1, 0)
            np.testing.assert_array_equal(tf.split_feature[:k],
                                          ts.split_feature[:k])
            np.testing.assert_array_equal(tf.leaf_value, ts.leaf_value)

    def test_device_vs_host_goss_auc_parity(self):
        """The device rest-sample is a different RNG stream than the host
        np.random one — statistically equivalent: both land the same
        quality on a held-out split."""
        X, y = _data(n=6000, seed=1)
        nt = 4500
        aucs = {}
        for name, dg in (("device", "auto"), ("host", "off")):
            bst = lgb.train({"objective": "binary", "num_leaves": 31,
                             "verbosity": -1, "metric": "none",
                             "data_sample_strategy": "goss",
                             "tpu_device_goss": dg},
                            lgb.Dataset(X[:nt], label=y[:nt]), 30)
            aucs[name] = _auc(y[nt:], bst.predict(X[nt:], raw_score=True))
        assert aucs["device"] > 0.85 and aucs["host"] > 0.85, aucs
        assert abs(aucs["device"] - aucs["host"]) < 0.02, aucs

    def test_bad_knob_value_rejected(self):
        X, y = _data(n=400)
        with pytest.raises(ValueError, match="tpu_device_goss"):
            lgb.train({"objective": "binary", "verbosity": -1,
                       "data_sample_strategy": "goss",
                       "tpu_device_goss": "maybe"},
                      lgb.Dataset(X, label=y), 1)


CEGB = {"cegb_tradeoff": 0.5, "cegb_penalty_split": 0.02,
        "cegb_penalty_feature_coupled": [2.0] * 8,
        "cegb_penalty_feature_lazy": [0.5] * 8}


class TestFusedCegb:
    @pytest.mark.parametrize("extra", [
        {},
        {"use_quantized_grad": True},
        {"enable_bundle": True},
    ], ids=["fp32", "quantized", "efb"])
    def test_fused_bitwise_identical_to_nonfused(self, extra):
        """CEGB is deterministic: carrying the first-use ``used`` vector
        in-trace (fused one-dispatch path) must not move a single split
        vs the per-tree fallback."""
        X, y = _data()
        if extra.get("enable_bundle"):
            # sparsify some columns so EFB actually bundles
            X = X.copy()
            X[:, 5][X[:, 5] < 1.0] = 0.0
            X[:, 6][X[:, 6] > -1.0] = 0.0
        params = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
                  "metric": "none", **CEGB, **extra}
        fused = lgb.Booster(params=params, train_set=lgb.Dataset(X, label=y))
        plain = _unfuse(lgb.Booster(params=params,
                                    train_set=lgb.Dataset(X, label=y)))
        for _ in range(8):
            fused.update()
            plain.update()
        assert fused._gbdt.fused_path_active is True
        for tf, tp in zip(fused._gbdt.models[0], plain._gbdt.models[0]):
            assert tf.num_leaves == tp.num_leaves
            k = max(tf.num_leaves - 1, 0)
            np.testing.assert_array_equal(tf.split_feature[:k],
                                          tp.split_feature[:k])
            np.testing.assert_array_equal(tf.split_bin[:k], tp.split_bin[:k])
            np.testing.assert_array_equal(tf.leaf_value, tp.leaf_value)
        # the penalty actually bit: coupled first-use marks accumulated
        assert bool(np.asarray(
            jax.device_get(fused._gbdt._cegb_used_dev)).any())

    def test_discard_rounds_rolls_back_used_vector(self):
        """A discarded pack tail must not leak first-use marks: the
        resident used vector only advances through committed rounds."""
        X, y = _data(n=1200)
        params = {"objective": "binary", "num_leaves": 7, "verbosity": -1,
                  "metric": "none", "tpu_iter_pack": 4, **CEGB}
        bst = lgb.Booster(params=params, train_set=lgb.Dataset(X, label=y))
        g = bst._gbdt
        rounds, _fin = g.train_pack(4)
        used_before = np.asarray(jax.device_get(g._cegb_used_dev))
        assert not used_before.any()        # fresh booster: nothing marked
        g.commit_round(rounds[0])
        used_commit1 = np.asarray(jax.device_get(g._cegb_used_dev))
        # the committed snapshot is EXACTLY round 0's live split features
        expect = np.zeros_like(used_before)
        for arrays in rounds[0]:
            sf, nl = jax.device_get((arrays.split_feature,
                                     arrays.num_leaves))
            expect[np.asarray(sf)[: max(int(nl) - 1, 0)]] = True
        np.testing.assert_array_equal(used_commit1, expect)
        assert expect.any()                 # the penalty actually bit
        g.discard_rounds(rounds[1:])
        used_after = np.asarray(jax.device_get(g._cegb_used_dev))
        # discarding the tail advances nothing further
        np.testing.assert_array_equal(used_commit1, used_after)


class TestDeviceLinearSolve:
    def test_device_solve_matches_host_facade(self, monkeypatch):
        rng = np.random.RandomState(5)
        X = rng.randn(2500, 6)
        X[::17, 3] = np.nan            # NaN rows fall back per leaf
        y = 2.0 * X[:, 0] - 1.5 * X[:, 1] + 0.05 * rng.randn(2500)
        params = {"objective": "regression", "num_leaves": 15,
                  "verbosity": -1, "linear_tree": True,
                  "linear_lambda": 0.1, "metric": "none"}
        preds = {}
        for name, env in (("device", "0"), ("host", "1")):
            monkeypatch.setenv("LIGHTGBM_TPU_HOST_LINEAR", env)
            bst = lgb.train(params, lgb.Dataset(X, label=y), 8)
            preds[name] = bst.predict(X)
        np.testing.assert_allclose(preds["device"], preds["host"],
                                   rtol=2e-3, atol=2e-3)
