"""The learner-composition capability matrix (models/capabilities.py):
every warn-and-fallback / rejection decision is a declarative rule, and
this test enumerates the full (option-combination) space against the
matrix so no silently-degraded config exists outside it.  Reference
contrast: tree_learner.cpp:31-44 composes learners orthogonally."""

import itertools

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.models.capabilities import RULES, Composition, resolve


def _comp(**kw):
    base = dict(voting=False, leaf_batch=1, mono_method="none",
                forced_splits=False, extra_trees=False,
                feature_fraction_bynode=False)
    base.update(kw)
    return Composition(**base)


def test_rule_names_unique_and_actions_valid():
    names = [r.name for r in RULES]
    assert len(names) == len(set(names))
    for r in RULES:
        assert r.action in ("error", "fallback")
        assert (r.fix is None) == (r.action == "error")


def test_matrix_enumeration_is_total():
    """Resolve the FULL boolean space: every outcome must be a fixed point
    (no rule still applies after resolve) or an error — i.e. the matrix
    is closed under its own fallbacks."""
    mono_methods = ("none", "basic", "intermediate", "advanced")
    flags = list(itertools.product((False, True), repeat=4))
    checked = errors = fallbacks = 0
    for mono in mono_methods:
        for voting, forced, extra, bynode in flags:
            for leaf_batch in (1, 16):
                comp = _comp(voting=voting, leaf_batch=leaf_batch,
                             mono_method=mono, forced_splits=forced,
                             extra_trees=extra,
                             feature_fraction_bynode=bynode)
                checked += 1
                try:
                    out, fired = resolve(comp)
                except ValueError:
                    errors += 1
                    continue
                fallbacks += bool(fired)
                for r in RULES:
                    if r.action == "fallback":
                        assert not r.applies(out), (r.name, comp)
    assert checked == 4 * 16 * 2
    assert errors and fallbacks        # both classes actually exercised


@pytest.mark.parametrize("kw,expect_voting,expect_batch,expect_fired", [
    # voting composes with per-node randomness/CEGB since round 5
    (dict(voting=True, extra_trees=True, leaf_batch=16), True, 16, False),
    (dict(voting=True, forced_splits=True, leaf_batch=16), False, 1, True),
    # monotone refresh composes with wave growth (conflict-free selection)
    (dict(mono_method="intermediate", leaf_batch=16), False, 16, False),
    (dict(mono_method="advanced", voting=True, leaf_batch=16), False, 16,
     True),
])
def test_fallback_outcomes(kw, expect_voting, expect_batch, expect_fired):
    out, fired = resolve(_comp(**kw))
    assert out.voting == expect_voting
    assert out.leaf_batch == expect_batch
    assert bool(fired) == expect_fired


@pytest.mark.parametrize("kw", [
    dict(mono_method="intermediate", extra_trees=True),
    dict(mono_method="advanced", feature_fraction_bynode=True),
    dict(mono_method="advanced", forced_splits=True),
])
def test_error_outcomes(kw):
    with pytest.raises(ValueError, match="does not compose"):
        resolve(_comp(**kw))


def test_gbdt_routes_through_matrix(capsys, tmp_path):
    """The driver's downgrades must be the matrix's downgrades (same
    messages, same effects)."""
    rng = np.random.RandomState(0)
    X = rng.rand(1500, 4)
    y = 2 * X[:, 0] + 0.1 * rng.randn(1500)
    import json
    forced_path = tmp_path / "forced.json"
    forced_path.write_text(json.dumps({"feature": 1, "threshold": 0.5}))
    bst = lgb.train({"objective": "regression", "num_leaves": 15,
                     "forcedsplits_filename": str(forced_path),
                     "tpu_leaf_batch": 8, "verbosity": 1},
                    lgb.Dataset(X, label=y), 2)
    out = capsys.readouterr()
    assert "tpu_leaf_batch=1" in out.out + out.err
    assert bst._gbdt.grower_cfg.leaf_batch == 1
    with pytest.raises(ValueError, match="extra_trees"):
        lgb.train({"objective": "regression", "num_leaves": 15,
                   "monotone_constraints": [1, 0, 0, 0],
                   "monotone_constraints_method": "intermediate",
                   "extra_trees": True, "verbosity": -1},
                  lgb.Dataset(X, label=y), 2)
