"""lightgbm_tpu.resilience: fault-tolerant training & serving (ISSUE 6).

Pins the subsystem's contract:
- checksummed atomic frames detect truncation/bitrot at read time,
- checkpoint/resume produces trees BITWISE-identical to the uninterrupted
  run — incl. bagging/feature_fraction, GOSS, CEGB, linear trees and
  iter-pack K>1 (the commit-boundary snapshot semantics),
- a mid-training SIGKILL (via the fault seam, in a real subprocess)
  resumes from the last committed boundary and the final model FILE is
  byte-identical to the uninterrupted run's (acceptance criterion),
- a corrupted newest generation falls back to the previous one,
- the budgeted watchdog probe returns "wedged" WITHIN its budget under
  the ``wedge_dispatch`` fault (no hang), "live" on a healthy backend,
  and the engine preflight turns a wedged verdict into a clear error,
- serve-side degradation: shed past ``serve_max_queue``, deadline misses
  past ``serve_deadline_ms``, one-shot host-predict fallback on a device
  fault — each counted in ServeMetrics.

Every injected failure goes through resilience/faults.py — the one seam —
so these tests are deterministic: no sleeps hoping for a race, no real
hardware faults required.
"""

import os
import re
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.resilience import checkpoint, faults, watchdog
from lightgbm_tpu.serialization import (FrameCorruptError, read_frame,
                                        write_atomic_frame)
from lightgbm_tpu.serve import ServeDeadlineError, ServeOverloadError

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_faults():
    """No test inherits another's armed faults (or leaks its own)."""
    faults.install(None)
    yield
    faults.install(None)


def _data(n=500, f=10, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.rand(n, f)
    y = (X[:, 0] + X[:, 1] + 0.2 * rng.rand(n) > 1.1).astype(np.float64)
    return X, y


BASE = {"objective": "binary", "num_leaves": 7, "seed": 3, "verbosity": -1,
        "min_data_in_leaf": 5}


def _train(params, X, y, rounds=12, resume_from=None):
    return lgb.train(dict(params), lgb.Dataset(X.copy(), label=y.copy()),
                     num_boost_round=rounds, resume_from=resume_from)


# ----------------------------------------------------- checksummed frames
def test_frame_roundtrip(tmp_path):
    path = str(tmp_path / "frame.bin")
    payload = os.urandom(4096)
    write_atomic_frame(path, payload)
    assert read_frame(path) == payload
    assert not [f for f in os.listdir(tmp_path) if ".tmp." in f]


@pytest.mark.parametrize("damage", ["truncate", "bitflip", "magic"])
def test_frame_damage_detected(tmp_path, damage):
    path = str(tmp_path / "frame.bin")
    write_atomic_frame(path, b"x" * 1000)
    with open(path, "r+b") as fh:
        if damage == "truncate":
            fh.truncate(os.path.getsize(path) // 2)
        elif damage == "bitflip":
            fh.seek(os.path.getsize(path) - 7)
            b = fh.read(1)
            fh.seek(-1, os.SEEK_CUR)
            fh.write(bytes([b[0] ^ 0x40]))
        else:
            fh.write(b"BOGUS")
    with pytest.raises(FrameCorruptError):
        read_frame(path)


# ------------------------------------------------------ checkpoint/resume
@pytest.fixture(scope="module")
def ckpt_run(tmp_path_factory):
    """One 12-round pack-4 run checkpointing every 4 (keep 3): the golden
    model string + its generation chain, shared by the read-only tests."""
    d = str(tmp_path_factory.mktemp("ck"))
    X, y = _data()
    params = dict(BASE, tpu_iter_pack=4, checkpoint_interval=4,
                  checkpoint_keep=3, checkpoint_dir=d)
    full = _train(params, X, y).model_to_string()
    return d, full, params, (X, y)


def test_checkpoint_generations_and_prune(ckpt_run):
    d, _full, _params, _ = ckpt_run
    assert [it for it, _p in checkpoint.list_snapshots(d)] == [12, 8, 4]


def test_checkpoint_prune_keeps_newest(tmp_path):
    d = str(tmp_path / "ck")
    X, y = _data(300, 6)
    params = dict(BASE, tpu_iter_pack=1, checkpoint_interval=2,
                  checkpoint_keep=2, checkpoint_dir=d)
    _train(params, X, y, rounds=6)
    assert [it for it, _p in checkpoint.list_snapshots(d)] == [6, 4]


@pytest.mark.parametrize("extra", [
    {},                                                   # plain, pack K=4
    {"bagging_fraction": 0.7, "bagging_freq": 2,          # device sampling
     "feature_fraction": 0.8},
    {"data_sample_strategy": "goss"},                     # device GOSS
    {"cegb_penalty_feature_coupled": 0.1},                # used-vector state
    {"linear_tree": True},                                # host leaf models
], ids=["plain", "bagging_ff", "goss", "cegb", "linear"])
def test_resume_bitwise_identical(tmp_path, extra):
    """Resume from the iteration-8 snapshot of a 12-round run; the final
    model must be BITWISE identical to the uninterrupted run's."""
    d = str(tmp_path / "ck")
    X, y = _data()
    params = dict(BASE, tpu_iter_pack=4, checkpoint_interval=4,
                  checkpoint_keep=3, checkpoint_dir=d, **extra)
    full = _train(params, X, y).model_to_string()
    snap8 = [p for it, p in checkpoint.list_snapshots(d) if it == 8]
    assert snap8, "no iteration-8 snapshot emitted"
    resumed = _train(params, X, y, resume_from=snap8[0])
    assert resumed.model_to_string() == full


def test_corrupt_latest_falls_back(tmp_path):
    """The ``corrupt_ckpt:latest`` fault tears the newest generation; the
    restore scan must detect it (checksum), warn, and land on gen 8 —
    and a resume from there still reproduces the golden model.  (The
    golden run checkpoints into the SAME directory: the serialized model
    embeds checkpoint_dir in its parameters section, so byte-equality
    needs identical config strings.)"""
    d = str(tmp_path / "ck")
    X, y = _data()
    params = dict(BASE, tpu_iter_pack=4, checkpoint_interval=4,
                  checkpoint_keep=3, checkpoint_dir=d)
    full = _train(params, X, y).model_to_string()
    faults.install("corrupt_ckpt:latest")
    blob, path = checkpoint.load_latest(d)
    assert blob["meta"]["iteration"] == 8
    assert path.endswith("ckpt-00000008.lgtck")
    # the newest generation was physically truncated, not just skipped
    with pytest.raises(FrameCorruptError):
        read_frame(checkpoint.snapshot_path(d, 12))
    faults.install(None)
    resumed = _train(params, X, y, resume_from=d)
    assert resumed.model_to_string() == full


def test_all_generations_corrupt_raises(ckpt_run, tmp_path):
    import shutil
    d0 = ckpt_run[0]
    d = str(tmp_path / "ck")
    shutil.copytree(d0, d)
    for _it, p in checkpoint.list_snapshots(d):
        with open(p, "r+b") as fh:
            fh.truncate(20)
    with pytest.raises(FrameCorruptError):
        checkpoint.load_latest(d)


def test_resume_config_mismatch_rejected(ckpt_run):
    d, _full, params, (X, y) = ckpt_run
    bad = dict(params, num_leaves=15)
    with pytest.raises(ValueError, match="num_leaves"):
        _train(bad, X, y, resume_from=d)


def test_resume_sampling_rate_mismatch_rejected(ckpt_run):
    """Sampling rates are compat keys: the restored RNG streams draw masks
    at whatever rate the resumed config says, so a silent rate change would
    silently diverge the tree stream."""
    d, _full, params, (X, y) = ckpt_run
    bad = dict(params, bagging_fraction=0.5, bagging_freq=1)
    with pytest.raises(ValueError, match="bagging_fraction"):
        _train(bad, X, y, resume_from=d)


def _trees_only(model_str):
    """Strip the serialized parameters section: the resume contract is
    about the TREES, and e.g. a restored learning_rate legitimately
    differs from the booster's configured one in that section."""
    return re.sub(r"parameters:.*?end of parameters", "", model_str,
                  flags=re.DOTALL)


def test_resume_learning_rate_restored_not_rejected(ckpt_run):
    """learning_rate is training STATE (reset_parameter mutates it
    mid-run): a resume with a different configured value restores the
    snapshot's boundary value (warn) and still reproduces the golden
    trees bitwise."""
    d, full, params, (X, y) = ckpt_run
    snap8 = [p for it, p in checkpoint.list_snapshots(d) if it == 8]
    assert snap8, "no iteration-8 snapshot in the golden chain"
    resumed = _train(dict(params, learning_rate=0.31), X, y,
                     resume_from=snap8[0])
    assert _trees_only(resumed.model_to_string()) == _trees_only(full)


def test_resume_early_stopping_bitwise(tmp_path):
    """Resume + early_stopping must reproduce the uninterrupted run: the
    snapshot carries the per-round eval history and the engine replays it
    through the after-callbacks, rebuilding the callback's best/wait
    counters.  Without the replay a resumed run re-baselines 'best' at
    its first post-resume eval and stops at a different iteration."""
    d = str(tmp_path / "ck")
    rng = np.random.RandomState(5)
    X, y = _data(300, 8)
    Xv = rng.rand(60, 8)                          # small noisy valid set:
    yv = (rng.rand(60) > 0.5).astype(np.float64)  # AUC jitters, stop fires
    params = dict(BASE, checkpoint_interval=2, checkpoint_keep=20,
                  checkpoint_dir=d, learning_rate=0.3)

    def run(resume_from=None):
        ds = lgb.Dataset(X.copy(), label=y.copy())
        return lgb.train(
            dict(params), ds, num_boost_round=20, resume_from=resume_from,
            valid_sets=[lgb.Dataset(Xv.copy(), label=yv.copy(),
                                    reference=ds)],
            callbacks=[lgb.early_stopping(3, verbose=False)])

    full = run()
    assert 0 < full.best_iteration < 20, \
        f"fixture must early-stop (best_iteration={full.best_iteration})"
    snaps = checkpoint.list_snapshots(d)
    assert snaps, "no mid-run snapshot emitted before the stop"
    resumed = run(resume_from=snaps[0][1])     # newest pre-stop snapshot
    assert resumed.best_iteration == full.best_iteration
    assert resumed.model_to_string() == full.model_to_string()


def test_resume_reset_parameter_schedule_bitwise(tmp_path):
    """Callbacks see the SAME absolute (iteration, begin, end) stream on
    resume: a full-length reset_parameter learning-rate schedule validates
    and indexes identically, and early_stopping (re)initializes on its
    first firing — the resumed model stays bitwise-identical."""
    d = str(tmp_path / "ck")
    X, y = _data()
    lr = [0.1 - 0.005 * i for i in range(12)]
    params = dict(BASE, checkpoint_interval=4, checkpoint_keep=3,
                  checkpoint_dir=d)

    def run(resume_from=None):
        return lgb.train(
            dict(params), lgb.Dataset(X.copy(), label=y.copy()),
            num_boost_round=12, resume_from=resume_from,
            callbacks=[lgb.reset_parameter(learning_rate=list(lr))])

    full = run().model_to_string()
    snap8 = [p for it, p in checkpoint.list_snapshots(d) if it == 8]
    assert snap8, "no iteration-8 snapshot emitted"
    resumed = run(resume_from=snap8[0])
    assert resumed.model_to_string() == full


def test_checkpoint_interval_warns_on_dart(tmp_path):
    """DART carries per-round host drop state outside the captured set:
    checkpoint_interval must WARN and disable, not snapshot garbage."""
    X, y = _data(300, 6)
    d = str(tmp_path / "ck")
    params = dict(BASE, boosting="dart", checkpoint_interval=1,
                  checkpoint_dir=d)
    _train(params, X, y, rounds=3)
    assert checkpoint.list_snapshots(d) == []


# ----------------------------------------- SIGKILL mid-training (subprocess)
_KILL_CHILD = r"""
import os, sys
sys.path.insert(0, os.environ["LGB_REPO"])
import _hermetic
_hermetic.force_cpu(1)
import numpy as np
import lightgbm_tpu as lgb

rng = np.random.RandomState(0)
X = rng.rand(400, 8)
y = (X[:, 0] + X[:, 1] > 1.0).astype(np.float64)
params = dict(objective="binary", num_leaves=7, seed=3, verbosity=-1,
              min_data_in_leaf=5, tpu_iter_pack=4, checkpoint_interval=4,
              checkpoint_keep=3, checkpoint_dir=sys.argv[1])
resume = sys.argv[3] if len(sys.argv) > 3 else None
bst = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=12,
                resume_from=resume)
bst.save_model(sys.argv[2])
"""


def _run_child(cwd, args, fault=None, timeout=420):
    """One training child.  ``checkpoint_dir`` is passed RELATIVE and the
    child runs in its own cwd: the serialized model embeds the param
    string, so byte-identical files need identical (relative) paths."""
    env = {k: v for k, v in os.environ.items()
           if k not in (faults.ENV_VAR, "JAX_PLATFORMS", "XLA_FLAGS")}
    env["LGB_REPO"] = REPO
    if fault:
        env[faults.ENV_VAR] = fault
    os.makedirs(cwd, exist_ok=True)
    return subprocess.run([sys.executable, "-c", _KILL_CHILD, *args],
                          env=env, cwd=cwd, capture_output=True, text=True,
                          timeout=timeout)


def test_sigkill_resume_byte_identical_model(tmp_path):
    """THE acceptance criterion: training SIGKILLed mid-run (fault seam,
    right after round 10 commits — past the iteration-8 snapshot, before
    the next boundary) resumes from the last committed checkpoint and the
    final model file is BYTE-identical to the uninterrupted run's."""
    golden = str(tmp_path / "golden.txt")
    resumed = str(tmp_path / "resumed.txt")
    cwd_full, cwd_kill = str(tmp_path / "full"), str(tmp_path / "kill")
    d_kill = os.path.join(cwd_kill, "ck")

    p = _run_child(cwd_full, ["ck", golden])
    assert p.returncode == 0, p.stderr[-2000:]

    p = _run_child(cwd_kill, ["ck", str(tmp_path / "never.txt")],
                   fault="kill_after_iter:10")
    assert p.returncode == -signal.SIGKILL, (p.returncode, p.stderr[-2000:])
    assert not os.path.exists(str(tmp_path / "never.txt"))
    # the crash landed between boundaries: snapshots stop at 8
    assert [it for it, _p in checkpoint.list_snapshots(d_kill)] == [8, 4]

    p = _run_child(cwd_kill, ["ck", resumed, "ck"])
    assert p.returncode == 0, p.stderr[-2000:]
    with open(golden, "rb") as a, open(resumed, "rb") as b:
        assert a.read() == b.read()


# ------------------------------------------------------- backend watchdog
def test_watchdog_wedged_verdict_within_budget():
    """A probe child stalled by ``wedge_dispatch`` must be classified
    wedged AT the budget — never hang past it (acceptance criterion)."""
    t0 = time.time()
    res = watchdog.probe_backend(
        timeout=2.0,
        extra_env={faults.ENV_VAR: "wedge_dispatch:600"})
    elapsed = time.time() - t0
    assert res.verdict == "wedged" and not res.live
    assert res.latency_s >= 2.0 and elapsed < 30.0
    assert "budget" in (res.error or "")


def test_watchdog_live_cpu_probe():
    res = watchdog.probe_backend(platform="cpu")
    assert res.verdict == "live" and res.live
    assert res.backend == "cpu" and res.devices >= 1
    d = res.as_dict()
    assert {"verdict", "backend", "devices", "latency_s",
            "budget_s", "error"} <= set(d)


def test_watchdog_error_verdict():
    res = watchdog.probe_backend(timeout=90.0, platform="bogus_device")
    assert res.verdict == "error" and not res.live
    assert res.error


def test_watchdog_cli_exit_codes(monkeypatch, capsys):
    monkeypatch.setenv(faults.ENV_VAR, "wedge_dispatch:600")
    rc = watchdog.main(["--timeout", "2"])
    assert rc == 2
    import json
    assert json.loads(capsys.readouterr().out)["verdict"] == "wedged"


def test_engine_preflight_wedged_raises(monkeypatch):
    """LIGHTGBM_TPU_WATCHDOG=1 turns a wedged backend into a clear crash
    BEFORE the trainer touches the device — within the probe budget."""
    monkeypatch.setenv(watchdog.WATCHDOG_ENV, "1")
    monkeypatch.setenv(faults.ENV_VAR, "wedge_dispatch:600")
    X, y = _data(100, 4)
    t0 = time.time()
    with pytest.raises(watchdog.BackendWedgedError, match="wedged"):
        lgb.train(dict(BASE, tpu_probe_timeout=1.5),
                  lgb.Dataset(X, label=y), num_boost_round=1)
    assert time.time() - t0 < 30.0


def test_unknown_fault_name_ignored():
    faults.install("no_such_seam:1,wedge_dispatch:0")
    assert set(faults.spec()) == {"wedge_dispatch"}
    assert not faults.active("kill_after_iter")


# --------------------------------------------- serve graceful degradation
@pytest.fixture(scope="module")
def served():
    X, y = _data(400, 8, seed=1)
    bst = lgb.train(dict(BASE, serve_max_queue=7, serve_deadline_ms=123.0),
                    lgb.Dataset(X, label=y), num_boost_round=5)
    return bst.serving_predictor(), X


def test_serve_host_fallback_on_device_fault(served):
    """The request that sees a device fault is answered from the host
    mirror — same scores, counted — and the NEXT request uses the device
    again (one-shot, not a permanent downgrade)."""
    pred, X = served
    base = pred.predict(X[:16])
    m0 = pred.metrics_snapshot()
    faults.install("serve_device_error:1")
    out = pred.predict(X[:16])
    after = pred.predict(X[:16])     # 2nd dispatch: fault seam already spent
    m1 = pred.metrics_snapshot()
    np.testing.assert_allclose(out, base, atol=1e-6)
    np.testing.assert_array_equal(after, base)
    assert m1["device_faults"] == m0["device_faults"] + 1
    assert m1["host_fallbacks"] == m0["host_fallbacks"] + 1


def test_serve_input_error_not_routed_to_fallback(served):
    """A caller input error (wrong feature count) is the caller's to see:
    it must raise ValueError, not be silently answered by the host mirror
    or counted as a device fault."""
    pred, X = served
    m0 = pred.metrics_snapshot()
    with pytest.raises(ValueError, match="plan expects"):
        pred.predict(X[:4, :-1])
    m1 = pred.metrics_snapshot()
    assert m1["device_faults"] == m0["device_faults"]
    assert m1["host_fallbacks"] == m0["host_fallbacks"]


def test_serve_host_fallback_multiclass_softmax():
    """The numpy output-transform mirror must match the device softmax."""
    rng = np.random.RandomState(2)
    X = rng.rand(300, 5)
    y = rng.randint(0, 3, 300).astype(np.float64)
    bst = lgb.train({"objective": "multiclass", "num_class": 3,
                     "num_leaves": 7, "verbosity": -1, "seed": 3},
                    lgb.Dataset(X, label=y), num_boost_round=3)
    pred = bst.serving_predictor()
    base = pred.predict(X[:8])
    faults.install("serve_device_error:1")
    out = pred.predict(X[:8])
    np.testing.assert_allclose(out, base, atol=1e-6)
    np.testing.assert_allclose(out.sum(axis=1), 1.0, atol=1e-6)


def test_batcher_defaults_from_config(served):
    pred, _X = served
    mb = pred.batcher()
    try:
        assert mb.max_queue == 7
        assert mb.deadline_s == pytest.approx(0.123)
    finally:
        mb.close()


def test_serve_shed_past_max_queue(served):
    """With the dispatch wedged slow and a 2-deep queue, submits past the
    bound must shed with ServeOverloadError and be counted."""
    pred, X = served
    shed0 = pred.metrics_snapshot()["shed"]
    faults.install("wedge_dispatch:0.3")
    # deadline_ms=0 explicitly: the fixture model's serve_deadline_ms=123
    # would otherwise expire the queued-behind-the-wedge requests we are
    # asserting resolve
    mb = pred.batcher(max_batch=1, max_wait_ms=1.0, max_queue=2,
                      deadline_ms=0.0)
    futs, sheds = [], 0
    try:
        for i in range(10):
            try:
                futs.append(mb.submit(X[i]))
            except ServeOverloadError:
                sheds += 1
        assert sheds >= 1
        for f in futs:          # every ADMITTED request still resolves
            assert f.result(timeout=30).shape == (1,)
    finally:
        faults.install(None)
        mb.close()
    assert pred.metrics_snapshot()["shed"] == shed0 + sheds


def test_serve_deadline_miss_failed_not_dispatched(served):
    """Requests queued past their deadline while a slow dispatch holds the
    worker are failed with ServeDeadlineError (and counted) instead of
    dispatched late; the in-flight request itself still succeeds."""
    pred, X = served
    miss0 = pred.metrics_snapshot()["deadline_misses"]
    faults.install("wedge_dispatch:0.25")
    mb = pred.batcher(max_batch=8, max_wait_ms=1.0, deadline_ms=40.0)
    try:
        first = mb.submit(X[0])
        time.sleep(0.05)         # worker has picked it up and is dispatching
        late = [mb.submit(X[i]) for i in (1, 2)]
        assert first.result(timeout=30).shape == (1,)
        for f in late:
            with pytest.raises(ServeDeadlineError):
                f.result(timeout=30)
    finally:
        faults.install(None)
        mb.close()
    assert pred.metrics_snapshot()["deadline_misses"] == miss0 + 2


def test_serve_expired_only_batch_skips_dispatch(served):
    """A flush whose EVERY request already expired must not dispatch at
    all — padding the device with dead work only delays live requests."""
    pred, X = served
    sizes = []
    orig = pred.predict
    pred.predict = lambda Xb, _record=True, **kw: (
        sizes.append(Xb.shape[0]) or orig(Xb, _record=_record, **kw))
    try:
        faults.install("wedge_dispatch:0.3")
        mb = pred.batcher(max_batch=8, max_wait_ms=1.0, deadline_ms=40.0)
        try:
            first = mb.submit(X[0])
            time.sleep(0.05)     # worker is inside the wedged dispatch
            late = [mb.submit(X[i]) for i in (1, 2)]
            assert first.result(timeout=30).shape == (1,)
            for f in late:
                with pytest.raises(ServeDeadlineError):
                    f.result(timeout=30)
        finally:
            faults.install(None)
            mb.close()
    finally:
        pred.predict = orig
    assert sizes == [1], f"expired-only batch was dispatched: {sizes}"
