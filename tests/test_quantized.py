"""Quantized training (use_quantized_grad) — reference GradientDiscretizer
(src/treelearner/gradient_discretizer.hpp:128, cuda_gradient_discretizer.cu)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.ops.histogram import histogram_onehot, histogram_segment
from lightgbm_tpu.ops.quantize import discretize_gradients, gradient_scales


def _auc(y, s):
    order = np.argsort(s)
    ranks = np.empty(len(s))
    ranks[order] = np.arange(1, len(s) + 1)
    pos = y == 1
    npos, nneg = pos.sum(), (~pos).sum()
    return (ranks[pos].sum() - npos * (npos + 1) / 2) / (npos * nneg)


def _binary_problem(n, f=8, seed=3):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, f)
    logits = X[:, 0] + 0.7 * X[:, 1] - 0.4 * X[:, 2] * X[:, 0]
    y = (rng.rand(n) < 1 / (1 + np.exp(-logits))).astype(np.float64)
    return X, y


class TestIntHistogram:
    def test_int8_matches_oracle(self, rng):
        n, f, b = 500, 6, 16
        bins = rng.randint(0, b, size=(n, f)).astype(np.uint8)
        vals = rng.randint(-5, 6, size=(n, 3)).astype(np.int8)
        oracle = np.zeros((f, b, 3), np.int64)
        for i in range(n):
            for j in range(f):
                oracle[j, bins[i, j]] += vals[i]
        h1 = np.asarray(histogram_onehot(jnp.asarray(bins), jnp.asarray(vals),
                                         num_bins=b, rows_block=128))
        h2 = np.asarray(histogram_segment(jnp.asarray(bins), jnp.asarray(vals),
                                          num_bins=b))
        assert h1.dtype == np.int32 and h2.dtype == np.int32
        np.testing.assert_array_equal(h1, oracle)
        np.testing.assert_array_equal(h2, oracle)


class TestDiscretize:
    def test_zero_stays_zero_and_unbiased(self):
        g = jnp.asarray(np.concatenate([np.zeros(1000),
                                        np.full(1000, 0.3)]), jnp.float32)
        h = jnp.asarray(np.concatenate([np.zeros(1000),
                                        np.full(1000, 0.21)]), jnp.float32)
        gs, hs = gradient_scales(g, h, 4)
        gq, hq = discretize_gradients(g, h, gs, hs, jax.random.PRNGKey(7))
        gq, hq = np.asarray(gq), np.asarray(hq)
        # masked-out rows must stay exactly zero (in-bag accounting)
        assert (gq[:1000] == 0).all() and (hq[:1000] == 0).all()
        # stochastic rounding is unbiased: mean(q)*scale ~= value
        np.testing.assert_allclose(gq[1000:].mean() * float(gs), 0.3, rtol=0.1)
        np.testing.assert_allclose(hq[1000:].mean() * float(hs), 0.21, rtol=0.1)

    def test_deterministic_rounding(self):
        # Reference scales (gradient_discretizer.cpp): delta_g =
        # max|g|/(B/2), delta_h = max h/B — at B=4, g levels span -2..2
        # and the max hessian lands on level B, not B-1.
        g = jnp.asarray([0.6, -0.6, 0.2], jnp.float32)
        h = jnp.asarray([0.5, 0.25, 1.0], jnp.float32)
        gs, hs = gradient_scales(g, h, 4)
        np.testing.assert_allclose(float(gs), 0.3, rtol=1e-6)
        np.testing.assert_allclose(float(hs), 0.25, rtol=1e-6)
        gq, hq = discretize_gradients(g, h, gs, hs, jax.random.PRNGKey(0),
                                      stochastic=False)
        np.testing.assert_array_equal(np.asarray(gq), [2, -2, 1])
        assert np.asarray(hq)[2] == 4  # max hess -> top level (B)


class TestQuantizedTraining:
    @pytest.mark.parametrize("n", [1500, 4000])  # mask path / perm path
    def test_auc_parity_with_fp32(self, n):
        X, y = _binary_problem(n)
        base = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
                "min_data_in_leaf": 5, "seed": 7, "metric": "none"}
        out = {}
        for name, extra in [("fp32", {}),
                            ("quant", {"use_quantized_grad": True,
                                       "num_grad_quant_bins": 16})]:
            bst = lgb.train({**base, **extra}, lgb.Dataset(X, label=y), 40)
            out[name] = _auc(y, bst.predict(X, raw_score=True))
        assert out["fp32"] > 0.8
        assert abs(out["fp32"] - out["quant"]) < 2e-3, out

    def test_default_bins_learns(self):
        X, y = _binary_problem(3000)
        bst = lgb.train({"objective": "binary", "num_leaves": 15,
                         "verbosity": -1, "use_quantized_grad": True,
                         "seed": 1, "metric": "none"},
                        lgb.Dataset(X, label=y), 60)
        assert _auc(y, bst.predict(X, raw_score=True)) > 0.85

    def test_deterministic_given_seed(self):
        X, y = _binary_problem(1200)
        params = {"objective": "binary", "num_leaves": 7, "verbosity": -1,
                  "use_quantized_grad": True, "seed": 11, "metric": "none"}
        p = [lgb.train(params, lgb.Dataset(X, label=y), 10).predict(X)
             for _ in range(2)]
        np.testing.assert_array_equal(p[0], p[1])

    def test_renew_leaf(self):
        X, y = _binary_problem(2500)
        params = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
                  "use_quantized_grad": True, "quant_train_renew_leaf": True,
                  "num_grad_quant_bins": 8, "seed": 5, "metric": "none"}
        bst = lgb.train(params, lgb.Dataset(X, label=y), 40)
        assert _auc(y, bst.predict(X, raw_score=True)) > 0.85

    def test_quantized_with_bagging_and_goss(self):
        X, y = _binary_problem(2500)
        for extra in [{"bagging_fraction": 0.7, "bagging_freq": 1},
                      {"data_sample_strategy": "goss"}]:
            params = {"objective": "binary", "num_leaves": 15,
                      "verbosity": -1, "use_quantized_grad": True,
                      "num_grad_quant_bins": 16, "seed": 5, "metric": "none",
                      **extra}
            bst = lgb.train(params, lgb.Dataset(X, label=y), 30)
            assert _auc(y, bst.predict(X, raw_score=True)) > 0.8


def test_quantized_composes_with_sharded_learner():
    """Quantized training under the data mesh: int32 histograms psum across
    shards (bin.h:48-81 integer reducers) and results track fp32 closely."""
    import jax
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    from lightgbm_tpu.models.grower import _MIN_BUCKET
    from lightgbm_tpu.metrics import _auc

    n = 8 * (_MIN_BUCKET + 128)
    rng = np.random.RandomState(0)
    X = rng.randn(n, 10)
    y = (X[:, 0] + 0.5 * X[:, 1] * X[:, 2] > 0).astype(np.float64)
    params = {"objective": "binary", "num_leaves": 15, "min_data_in_leaf": 20,
              "verbosity": -1, "tree_learner": "data",
              "use_quantized_grad": True}
    bst = lgb.train(params, lgb.Dataset(X, label=y), 5)
    auc = _auc(y, bst.predict(X, raw_score=True), None, None)
    fp32 = lgb.train(dict(params, use_quantized_grad=False),
                     lgb.Dataset(X, label=y), 5)
    auc_fp = _auc(y, fp32.predict(X, raw_score=True), None, None)
    assert auc > auc_fp - 5e-3, (auc, auc_fp)


def test_quantized_composes_with_efb():
    from lightgbm_tpu.metrics import _auc

    rng = np.random.RandomState(1)
    n = 6000
    blocks = []
    for _ in range(3):
        cat = rng.randint(0, 10, n)
        oh = np.zeros((n, 10))
        oh[np.arange(n), cat] = rng.rand(n) + 0.5
        blocks.append(oh)
    X = np.concatenate(blocks + [rng.randn(n, 4)], axis=1)
    y = (X[:, 0] * 2 + X[:, 30] > 0.5).astype(np.float64)
    params = {"objective": "binary", "num_leaves": 15, "min_data_in_leaf": 20,
              "verbosity": -1, "enable_bundle": True,
              "use_quantized_grad": True}
    bst = lgb.train(params, lgb.Dataset(X, label=y), 6)
    assert bst._gbdt.bundles is not None
    auc = _auc(y, bst.predict(X, raw_score=True), None, None)
    assert auc > 0.75, auc
